"""Peer discovery: signed node records + Kademlia lookups over UDP.

Reference: `@chainsafe/discv5` used by `network/peers/discover.ts` —
ENR records, k-bucket routing table keyed by XOR distance, iterative
FINDNODE lookups, and subnet-targeted peer queries (attnets bitfield in
the ENR, `discover.ts` subnet queries).

Native re-design notes: records are SSZ-style binary signed with the
node's ed25519 identity key (the same key that authenticates the
transport handshake, so a discovered record is attributable to the peer
you will dial); packets are individually signed rather than running
discv5's session handshake — the transport layer provides the
authenticated channel, discovery only needs spoofing-resistant
liveness/topology hints.
"""

from __future__ import annotations

import asyncio
import struct
import time
from collections import OrderedDict
from dataclasses import dataclass, field

from ..params import ATTESTATION_SUBNET_COUNT
from ..ssz.hashing import sha256
from ..utils.logger import get_logger
from .transport import NodeIdentity, peer_id_from_pubkey, verify_identity

log = get_logger("discovery")

K_BUCKET_SIZE = 16
ALPHA = 3  # lookup concurrency
MAX_PACKET = 1280  # discv5 MTU discipline
PING_INTERVAL = 30.0
RECORD_TTL = 600.0
# endpoint-proof challenge bookkeeping bounds (round-2 advisor: identity
# minting is free, so these maps must not grow with attacker traffic)
_CHALLENGE_TTL = 5.0
_MAX_CHALLENGES = 512
_PROVEN_MAX = 4096
_KEYS_MAX = 16384
_CHALLENGE_PINGS_PER_SEC = 64.0  # global budget for challenge PINGs
_NONCE_WINDOW_SEC = 600.0  # max accepted sender-clock age (anti-replay)


def _lru_put(d: "OrderedDict", key, value, cap: int) -> None:
    d[key] = value
    d.move_to_end(key)
    while len(d) > cap:
        d.popitem(last=False)

_PING = 1
_PONG = 2
_FINDNODE = 3
_NODES = 4
_PTYPE_NAMES = {_PING: "ping", _PONG: "pong", _FINDNODE: "findnode", _NODES: "nodes"}


@dataclass
class ENR:
    """Signed node record (role of discv5's ENR)."""

    node_id: str  # transport peer id (hex of sha256(pubkey)[:20])
    pubkey: bytes  # ed25519, 32B
    ip: str
    tcp_port: int
    udp_port: int
    seq: int = 1
    fork_digest: bytes = b"\x00\x00\x00\x00"
    attnets: int = 0  # bitfield as int, bit i = subnet i
    signature: bytes = b""

    def signing_payload(self) -> bytes:
        import socket

        try:
            ip_raw = socket.inet_pton(socket.AF_INET, self.ip)
        except OSError:
            ip_raw = socket.inet_pton(socket.AF_INET6, self.ip)
        return (
            b"enr:"
            + self.pubkey
            + struct.pack(">QHH", self.seq, self.tcp_port, self.udp_port)
            + bytes([len(ip_raw)])
            + ip_raw
            + self.fork_digest
            + self.attnets.to_bytes(ATTESTATION_SUBNET_COUNT // 8, "little")
        )

    def sign(self, identity: NodeIdentity) -> "ENR":
        self.signature = identity.sign(self.signing_payload())
        return self

    def verify(self) -> bool:
        return (
            peer_id_from_pubkey(self.pubkey) == self.node_id
            and verify_identity(self.pubkey, self.signature, self.signing_payload())
        )

    def has_attnet(self, subnet: int) -> bool:
        return bool(self.attnets >> subnet & 1)

    def encode(self) -> bytes:
        payload = self.signing_payload()
        return struct.pack(">H", len(payload)) + payload + self.signature

    @classmethod
    def decode(cls, data: bytes, offset: int = 0) -> tuple["ENR", int]:
        (plen,) = struct.unpack_from(">H", data, offset)
        payload = data[offset + 2 : offset + 2 + plen]
        sig = data[offset + 2 + plen : offset + 2 + plen + 64]
        if len(payload) != plen or len(sig) != 64 or payload[:4] != b"enr:":
            raise ValueError("bad ENR encoding")
        pubkey = payload[4:36]
        seq, tcp_port, udp_port = struct.unpack_from(">QHH", payload, 36)
        import socket

        ip_len = payload[48]
        ip_raw = payload[49 : 49 + ip_len]
        family = socket.AF_INET if ip_len == 4 else socket.AF_INET6
        ip = socket.inet_ntop(family, ip_raw)
        rest = payload[49 + ip_len :]
        fork_digest = rest[:4]
        attnets = int.from_bytes(rest[4 : 4 + ATTESTATION_SUBNET_COUNT // 8], "little")
        enr = cls(
            node_id=peer_id_from_pubkey(pubkey),
            pubkey=pubkey,
            ip=ip,
            tcp_port=tcp_port,
            udp_port=udp_port,
            seq=seq,
            fork_digest=fork_digest,
            attnets=attnets,
            signature=sig,
        )
        return enr, offset + 2 + plen + 64


def _distance(a: str, b: str) -> int:
    """XOR distance over hashed ids (discv5 log2-distance basis)."""
    ha = int.from_bytes(sha256(bytes.fromhex(a)), "big")
    hb = int.from_bytes(sha256(bytes.fromhex(b)), "big")
    return ha ^ hb


@dataclass
class _BucketEntry:
    enr: ENR
    last_seen: float = field(default_factory=time.monotonic)


class RoutingTable:
    """256 k-buckets by log2(xor distance)."""

    def __init__(self, local_id: str):
        self.local_id = local_id
        self.buckets: list[dict[str, _BucketEntry]] = [dict() for _ in range(256)]

    def _bucket_of(self, node_id: str) -> dict[str, _BucketEntry]:
        d = _distance(self.local_id, node_id)
        return self.buckets[d.bit_length() - 1 if d else 0]

    def update(self, enr: ENR) -> bool:
        """Insert/refresh; True only when the node is NEW to the table (the
        discovered-callback trigger — refreshes are not discoveries)."""
        if enr.node_id == self.local_id or not enr.verify():
            return False
        bucket = self._bucket_of(enr.node_id)
        entry = bucket.get(enr.node_id)
        if entry is not None:
            if enr.seq >= entry.enr.seq:
                bucket[enr.node_id] = _BucketEntry(enr)
            return False
        if len(bucket) >= K_BUCKET_SIZE:
            # evict stalest entry (liveness-checked eviction is the ping
            # loop's job; here we keep the table bounded)
            stalest = min(bucket.values(), key=lambda e: e.last_seen)
            if time.monotonic() - stalest.last_seen < RECORD_TTL:
                return False
            del bucket[stalest.enr.node_id]
        bucket[enr.node_id] = _BucketEntry(enr)
        return True

    def remove(self, node_id: str) -> None:
        self._bucket_of(node_id).pop(node_id, None)

    def touch(self, node_id: str) -> None:
        entry = self._bucket_of(node_id).get(node_id)
        if entry is not None:
            entry.last_seen = time.monotonic()

    def closest(self, target_id: str, count: int = K_BUCKET_SIZE) -> list[ENR]:
        all_entries = [e.enr for b in self.buckets for e in b.values()]
        all_entries.sort(key=lambda e: _distance(target_id, e.node_id))
        return all_entries[:count]

    def all(self) -> list[ENR]:
        return [e.enr for b in self.buckets for e in b.values()]

    def __len__(self) -> int:
        return sum(len(b) for b in self.buckets)


class Discovery(asyncio.DatagramProtocol):
    """UDP discovery service; every packet is `node_id(20B hex=40) +
    sig(64) + type(1) + body`, signed over (type + body)."""

    def __init__(self, identity: NodeIdentity, enr: ENR):
        self.identity = identity
        self.local_enr = enr.sign(identity)
        self.table = RoutingTable(enr.node_id)
        self.transport_udp: asyncio.DatagramTransport | None = None
        self._pending_pong: dict[str, asyncio.Future] = {}
        # endpoint proof (anti-reflection): node_id -> addr that answered
        # OUR ping with a valid PONG (discv5 WHOAREYOU-equivalent role).
        # Bounded LRU: fresh signed identities are free to mint, so any
        # per-identity map an attacker can populate must cap (round-2
        # advisor) — eviction only costs the evicted peer one extra
        # challenge round-trip.
        self._endpoint_proven: "OrderedDict[str, tuple]" = OrderedDict()
        # live challenges: node_id -> (addr, issued_at monotonic); entries
        # expire after _CHALLENGE_TTL and the maps cap at _MAX_CHALLENGES
        self._ping_addr: dict[str, tuple] = {}
        # FINDNODEs held back until the challenge round-trip completes:
        # node_id -> (addr, target_id) — answered from the PONG handler
        self._pending_findnode: dict[str, tuple] = {}
        self._pending_nodes: dict[str, asyncio.Future] = {}
        # node_id → pubkey / highest-seen-nonce: same identity-minting
        # growth concern as _endpoint_proven, same bounded-LRU treatment
        self._known_keys: "OrderedDict[str, bytes]" = OrderedDict()
        self._last_nonce: "OrderedDict[str, int]" = OrderedDict()
        self._nonce = int(time.time() * 1000) << 16  # survives restarts
        # token bucket for challenge PINGs (each unproven FINDNODE reflects
        # one ~86B PING; bound the reflected bandwidth toward spoofed addrs)
        self._challenge_tokens = _CHALLENGE_PINGS_PER_SEC
        self._challenge_refill_t = time.monotonic()
        self._liveness_task: asyncio.Task | None = None
        self.on_discovered: list = []  # callbacks(enr)
        # optional beacon metrics bundle (network wiring sets it); every
        # increment is guarded so discovery runs identically unwired
        self.metrics = None

    # -- lifecycle -----------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> tuple[str, int]:
        loop = asyncio.get_running_loop()
        self.transport_udp, _ = await loop.create_datagram_endpoint(
            lambda: self, local_addr=(host, port)
        )
        addr = self.transport_udp.get_extra_info("sockname")[:2]
        if self.local_enr.udp_port == 0:
            self.local_enr.udp_port = addr[1]
            self.local_enr.seq += 1
            self.local_enr.sign(self.identity)
        return addr

    def start_liveness_loop(self) -> None:
        """Periodically ping the stalest table entries; dead ones are
        evicted by ping()'s timeout path (discv5 liveness checks)."""
        self._liveness_task = asyncio.get_running_loop().create_task(
            self._liveness_loop()
        )

    async def _liveness_loop(self) -> None:
        while True:
            await asyncio.sleep(PING_INTERVAL)
            now = time.monotonic()
            stale = sorted(
                (
                    e
                    for b in self.table.buckets
                    for e in b.values()
                    if now - e.last_seen > PING_INTERVAL
                ),
                key=lambda e: e.last_seen,
            )[:4]
            for entry in stale:
                await self.ping(entry.enr)

    def stop(self) -> None:
        if self._liveness_task is not None:
            self._liveness_task.cancel()
        if self.transport_udp is not None:
            self.transport_udp.close()

    # -- packet plumbing -----------------------------------------------------

    def _send(self, addr, ptype: int, body: bytes) -> None:
        if self.transport_udp is None:
            return
        # monotonic per-sender nonce, covered by the signature: receivers
        # reject non-increasing nonces, so captured packets can't be
        # replayed to fake liveness or reflect NODES at victims
        # advance the clock component on every send (not just at startup):
        # receivers enforce a freshness window on the high 48 bits, so a
        # nonce pinned at process-start time would make every packet from
        # a >window-old process look stale and break discovery liveness
        self._nonce = max(self._nonce + 1, int(time.time() * 1000) << 16)
        content = struct.pack(">Q", self._nonce) + bytes([ptype]) + body
        sig = self.identity.sign(b"disc:" + content)
        packet = self.local_enr.node_id.encode() + sig + content
        if len(packet) <= MAX_PACKET:
            self.transport_udp.sendto(packet, addr)
            if self.metrics is not None:
                self.metrics.discv5_tx_total.inc(
                    type=_PTYPE_NAMES.get(ptype, str(ptype))
                )

    def datagram_received(self, data: bytes, addr) -> None:
        try:
            node_id = data[:40].decode()
            sig, content = data[40:104], data[104:]
            (nonce,) = struct.unpack_from(">Q", content, 0)
            ptype, body = content[8], content[9:]
        except Exception:
            return
        if nonce <= self._last_nonce.get(node_id, 0):
            return  # replayed or reordered-stale packet
        # freshness window: the nonce's high 48 bits are the sender's
        # epoch-ms clock. Bounding _last_nonce (LRU) alone would re-enable
        # replay of a victim's captured packets once its entry is flooded
        # out; rejecting packets older than the window closes that hole for
        # anything but a <window-old capture racing an eviction flood —
        # consensus peers keep clocks within slot tolerance, so a generous
        # window costs nothing. (round-3 review)
        if (nonce >> 16) < (time.time() - _NONCE_WINDOW_SEC) * 1000:
            return
        if self.metrics is not None:
            self.metrics.discv5_rx_total.inc(
                type=_PTYPE_NAMES.get(ptype, str(ptype))
            )
        asyncio.get_running_loop().create_task(
            self._handle(node_id, sig, nonce, ptype, body, addr, content)
        )

    async def _handle(
        self, node_id: str, sig: bytes, nonce: int, ptype: int, body: bytes, addr, content: bytes
    ):
        # Authentication: PING carries the sender's ENR (with pubkey);
        # other packets must come from a node whose key we've learned.
        try:
            if ptype == _PING:
                enr, _ = ENR.decode(body)
                if enr.node_id != node_id or not enr.verify():
                    return
                if not verify_identity(enr.pubkey, sig, b"disc:" + content):
                    return
                _lru_put(self._last_nonce, node_id, nonce, _KEYS_MAX)
                _lru_put(self._known_keys, node_id, enr.pubkey, _KEYS_MAX)
                if self.table.update(enr):
                    self._notify(enr)
                self.table.touch(node_id)
                self._send(addr, _PONG, self.local_enr.encode())
                return

            pubkey = self._pubkey_for(node_id)
            if pubkey is None or not verify_identity(
                pubkey, sig, b"disc:" + content
            ):
                return
            _lru_put(self._last_nonce, node_id, nonce, _KEYS_MAX)
            self.table.touch(node_id)

            if ptype == _PONG:
                enr, _ = ENR.decode(body)
                if enr.node_id == node_id and enr.verify():
                    if self.table.update(enr):
                        self._notify(enr)
                # endpoint proof: a valid PONG from the address we PINGed
                # demonstrates the peer actually RECEIVES at that address
                # (a spoofed source cannot complete the round trip).
                # addr[:2]: IPv6 recvfrom yields 4-tuples; compare host+port.
                entry = self._ping_addr.get(node_id)
                if entry is not None and tuple(addr)[:2] == tuple(entry[0])[:2]:
                    del self._ping_addr[node_id]  # pop ONLY on match: a
                    # concurrent ping must not destroy a live challenge
                    _lru_put(
                        self._endpoint_proven, node_id, tuple(addr)[:2], _PROVEN_MAX
                    )
                    held = self._pending_findnode.pop(node_id, None)
                    if held is not None:
                        self._answer_findnode(held[0], held[1])
                fut = self._pending_pong.pop(node_id, None)
                if fut is not None and not fut.done():
                    fut.set_result(True)
            elif ptype == _FINDNODE:
                target = body[:40].decode()
                proven = self._endpoint_proven.get(node_id)
                if proven == tuple(addr)[:2]:
                    self._endpoint_proven.move_to_end(node_id)  # keep hot
                    self._answer_findnode(tuple(addr)[:2], target)
                    return
                # unproven source address: a ~49B FINDNODE must not
                # reflect a ~1.2KB NODES at a spoofed victim (round-1
                # advisor finding). Hold the query, run the proof
                # round-trip (our PING -> their PONG), and the PONG
                # handler answers it — the querier's single in-flight
                # lookup still completes (just one RTT later).
                now = time.monotonic()
                self._gc_challenges(now)
                live = self._ping_addr.get(node_id)
                if live is not None:
                    # challenge already in flight for this identity: refresh
                    # the held query, never issue a second PING (per-identity
                    # amplification would defeat the rate limit)
                    if tuple(addr)[:2] == tuple(live[0])[:2]:
                        self._pending_findnode[node_id] = (tuple(addr)[:2], target)
                    return
                if len(self._ping_addr) >= _MAX_CHALLENGES:
                    if self.metrics is not None:
                        self.metrics.discv5_challenge_drops_total.inc()
                    return  # full table of live challenges: shed load
                self._challenge_tokens = min(
                    _CHALLENGE_PINGS_PER_SEC,
                    self._challenge_tokens
                    + (now - self._challenge_refill_t) * _CHALLENGE_PINGS_PER_SEC,
                )
                self._challenge_refill_t = now
                if self._challenge_tokens < 1.0:
                    if self.metrics is not None:
                        self.metrics.discv5_challenge_drops_total.inc()
                    return  # over the global challenge-PING budget
                self._challenge_tokens -= 1.0
                self._pending_findnode[node_id] = (tuple(addr)[:2], target)
                self._ping_addr[node_id] = (tuple(addr)[:2], now)
                self._send(addr, _PING, self.local_enr.encode())
            elif ptype == _NODES:
                count = body[0]
                offset = 1
                enrs = []
                for _ in range(min(count, K_BUCKET_SIZE)):
                    enr, offset = ENR.decode(body, offset)
                    if enr.verify():
                        enrs.append(enr)
                        # record the key: packets from relayed peers must be
                        # verifiable, or multi-hop discovery can't converge
                        _lru_put(self._known_keys, enr.node_id, enr.pubkey, _KEYS_MAX)
                        if self.table.update(enr):
                            self._notify(enr)
                fut = self._pending_nodes.pop(node_id, None)
                if fut is not None and not fut.done():
                    fut.set_result(enrs)
        except Exception as e:  # malformed packet — drop
            log.debug(f"discovery packet error from {node_id[:8]}: {e}")

    def _gc_challenges(self, now: float) -> None:
        """Expire stale challenge state; held FINDNODEs die with their
        challenge (the querier simply retries)."""
        expired = [
            nid
            for nid, (_, t) in self._ping_addr.items()
            if now - t > _CHALLENGE_TTL
        ]
        for nid in expired:
            self._ping_addr.pop(nid, None)
            self._pending_findnode.pop(nid, None)

    def _answer_findnode(self, addr, target: str) -> None:
        closest = self.table.closest(target, K_BUCKET_SIZE)
        out = bytearray()
        count = 0
        for enr in closest:
            encoded = enr.encode()
            if len(out) + len(encoded) > MAX_PACKET - 120:
                break
            out += encoded
            count += 1
        self._send(addr, _NODES, bytes([count]) + bytes(out))

    def _pubkey_for(self, node_id: str) -> bytes | None:
        """Sender key for packet auth: the learned-keys map, else the
        signature-verified table record."""
        pubkey = self._known_keys.get(node_id)
        if pubkey is not None:
            return pubkey
        for enr in self.table.all():
            if enr.node_id == node_id:
                _lru_put(self._known_keys, node_id, enr.pubkey, _KEYS_MAX)
                return enr.pubkey
        return None

    def _notify(self, enr: ENR) -> None:
        for cb in self.on_discovered:
            try:
                cb(enr)
            except Exception:
                log.warning("discovery callback failed", exc_info=True)

    # -- protocol ops --------------------------------------------------------

    async def ping(self, enr: ENR, timeout: float = 2.0) -> bool:
        fut = asyncio.get_running_loop().create_future()
        self._pending_pong[enr.node_id] = fut
        self._ping_addr[enr.node_id] = ((enr.ip, enr.udp_port), time.monotonic())
        self._send((enr.ip, enr.udp_port), _PING, self.local_enr.encode())
        try:
            await asyncio.wait_for(fut, timeout)
            return True
        except asyncio.TimeoutError:
            self.table.remove(enr.node_id)
            if self.metrics is not None:
                self.metrics.discv5_liveness_evictions_total.inc()
            return False
        finally:
            # a stale future must not swallow a later request's response
            if self._pending_pong.get(enr.node_id) is fut:
                del self._pending_pong[enr.node_id]

    async def find_node(self, enr: ENR, target_id: str, timeout: float = 2.0) -> list[ENR]:
        fut = asyncio.get_running_loop().create_future()
        self._pending_nodes[enr.node_id] = fut
        self._send((enr.ip, enr.udp_port), _FINDNODE, target_id.encode())
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            return []
        finally:
            if self._pending_nodes.get(enr.node_id) is fut:
                del self._pending_nodes[enr.node_id]

    async def bootstrap(self, bootnodes: list[ENR]) -> None:
        for enr in bootnodes:
            if not enr.verify() or enr.node_id == self.local_enr.node_id:
                continue
            _lru_put(self._known_keys, enr.node_id, enr.pubkey, _KEYS_MAX)
            if self.table.update(enr):
                self._notify(enr)
            await self.ping(enr)
        await self.lookup(self.local_enr.node_id)

    async def lookup(self, target_id: str) -> list[ENR]:
        """Iterative Kademlia lookup: query ALPHA closest, absorb NODES
        (inserted by the receive path), repeat until the closest-known
        distance stops improving."""
        if self.metrics is not None:
            self.metrics.discv5_lookups_total.inc()
        queried: set[str] = set()

        def best() -> int:
            closest = self.table.closest(target_id, 1)
            return _distance(target_id, closest[0].node_id) if closest else 1 << 256

        while True:
            candidates = [
                e for e in self.table.closest(target_id, K_BUCKET_SIZE)
                if e.node_id not in queried
            ][:ALPHA]
            if not candidates:
                break
            before = best()
            results = await asyncio.gather(
                *(self.find_node(e, target_id) for e in candidates)
            )
            queried.update(e.node_id for e in candidates)
            if not any(results) or best() >= before:
                break
        return self.table.closest(target_id, K_BUCKET_SIZE)

    # -- consumer queries ----------------------------------------------------

    def find_peers_for_subnet(self, subnet: int) -> list[ENR]:
        """Peers advertising the attnet (reference subnet-targeted query)."""
        return [e for e in self.table.all() if e.has_attnet(subnet)]

    def update_attnets(self, bits: list[bool]) -> None:
        """Refresh the local ENR's attnets bitfield (reference:
        AttnetsService updating the ENR on subscription changes)."""
        value = 0
        for i, b in enumerate(bits):
            if b:
                value |= 1 << i
        if value != self.local_enr.attnets:
            self.local_enr.attnets = value
            self.local_enr.seq += 1
            self.local_enr.sign(self.identity)


def enr_to_text(enr: ENR) -> str:
    """Shareable one-line record (role of the base64 `enr:` text form)."""
    import base64

    return "enr-tpu:" + base64.urlsafe_b64encode(enr.encode()).decode().rstrip("=")


def enr_from_text(text: str) -> ENR:
    import base64

    if not text.startswith("enr-tpu:"):
        raise ValueError("not an enr-tpu record")
    raw = text[len("enr-tpu:"):]
    raw += "=" * (-len(raw) % 4)
    enr, _ = ENR.decode(base64.urlsafe_b64decode(raw))
    if not enr.verify():
        raise ValueError("invalid record signature")
    return enr
