"""Subnet subscription services.

Reference: `network/subnets/attnetsService.ts` / `syncnetsService.ts` —
long-lived random subnet subscriptions (rotated every
EPOCHS_PER_RANDOM_SUBNET_SUBSCRIPTION epochs, seeded per node) plus
short-lived committee-duty subscriptions; exposes the ENR attnets
bitfield and the subscription set the gossip router joins.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..params import ATTESTATION_SUBNET_COUNT
from ..ssz.hashing import sha256

RANDOM_SUBNETS_PER_VALIDATOR = 1
EPOCHS_PER_RANDOM_SUBNET_SUBSCRIPTION = 256


@dataclass
class Subscription:
    subnet: int
    until_epoch: int


class AttnetsService:
    def __init__(self, node_id: bytes, slots_per_epoch: int):
        self.node_id = node_id
        self.spe = slots_per_epoch
        self.long_lived: list[Subscription] = []
        self.short_lived: list[Subscription] = []

    # -- long-lived random subscriptions -------------------------------------

    def _random_subnet(self, epoch: int, i: int) -> int:
        period = epoch // EPOCHS_PER_RANDOM_SUBNET_SUBSCRIPTION
        seed = sha256(
            self.node_id + period.to_bytes(8, "little") + i.to_bytes(4, "little")
        )
        return int.from_bytes(seed[:8], "little") % ATTESTATION_SUBNET_COUNT

    def rotate(self, epoch: int, validator_count: int) -> None:
        """Refresh long-lived subscriptions for the current period and
        drop expired short-lived ones."""
        n_subs = max(1, min(validator_count, 4)) * RANDOM_SUBNETS_PER_VALIDATOR
        period_end = (
            (epoch // EPOCHS_PER_RANDOM_SUBNET_SUBSCRIPTION + 1)
            * EPOCHS_PER_RANDOM_SUBNET_SUBSCRIPTION
        )
        self.long_lived = [
            Subscription(self._random_subnet(epoch, i), period_end)
            for i in range(n_subs)
        ]
        self.short_lived = [s for s in self.short_lived if s.until_epoch > epoch]

    # -- committee-duty subscriptions ----------------------------------------

    def subscribe_committee(self, subnet: int, until_epoch: int) -> None:
        self.short_lived.append(Subscription(subnet, until_epoch))

    # -- views ----------------------------------------------------------------

    def active_subnets(self, epoch: int) -> set[int]:
        return {
            s.subnet
            for s in self.long_lived + self.short_lived
            if s.until_epoch > epoch
        }

    def enr_attnets(self, epoch: int) -> list[bool]:
        """ENR attnets bitfield advertises only LONG-LIVED subscriptions
        (p2p spec: short-lived duties are not advertised)."""
        bits = [False] * ATTESTATION_SUBNET_COUNT
        for s in self.long_lived:
            if s.until_epoch > epoch:
                bits[s.subnet] = True
        return bits


class SyncnetsService:
    """Sync-committee subnet subscriptions (reference
    `network/subnets/syncnetsService.ts`): subscriptions follow the
    validator's sync-committee membership for whole sync-committee
    periods — no random rotation, unlike attnets."""

    SYNC_COMMITTEE_SUBNET_COUNT = 4

    def __init__(self, slots_per_epoch: int, epochs_per_period: int = 256):
        self.spe = slots_per_epoch
        self.epochs_per_period = epochs_per_period
        self.subscriptions: list[Subscription] = []

    def subscribe_committee_member(self, subnet: int, until_epoch: int) -> None:
        """Called when a local validator joins a sync subcommittee."""
        self.subscriptions.append(Subscription(subnet, until_epoch))

    def prune(self, epoch: int) -> None:
        self.subscriptions = [s for s in self.subscriptions if s.until_epoch > epoch]

    def active_subnets(self, epoch: int) -> set[int]:
        return {s.subnet for s in self.subscriptions if s.until_epoch > epoch}

    def enr_syncnets(self, epoch: int) -> list[bool]:
        bits = [False] * self.SYNC_COMMITTEE_SUBNET_COUNT
        for s in self.subscriptions:
            if s.until_epoch > epoch:
                bits[s.subnet] = True
        return bits
