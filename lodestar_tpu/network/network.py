"""Network facade: transport + gossip + reqresp + peers + subnets.

Reference: `network/network.ts:39` — the `Network` class owns
`Eth2Gossipsub`, `ReqResp`, `PeerManager`, attnets/syncnets services and
the fork-transition topic logic (`subscribeGossipCoreTopics` :225, and
subscribing both fork digests ±epochs around a scheduled fork,
network.ts:39-110).
"""

from __future__ import annotations

import asyncio

from ..utils.logger import get_logger
from .gossip.gossipsub import Gossipsub, GossipsubService
from .gossip.handlers import GossipHandlers
from .gossip.score import PeerScoreParams, ethereum_topic_params
from .gossip.topic import GossipTopic, GossipType, stringify_topic
from .peers import PeerAction, PeerManager
from .reqresp.handlers import ReqRespHandlers
from .reqresp.service import RemotePeer, ReqRespService
from .subnets import AttnetsService
from .transport import NodeIdentity, Transport

log = get_logger("network")

CORE_TOPICS = [
    GossipType.beacon_block,
    GossipType.beacon_aggregate_and_proof,
    GossipType.voluntary_exit,
    GossipType.proposer_slashing,
    GossipType.attester_slashing,
]

HEARTBEAT_SEC = 2.0
DIAL_TIMEOUT = 5.0  # TCP connect + handshake, per dial attempt


class Network:
    """One object the node wires in; start() listens, connect() dials."""

    def __init__(
        self,
        config,
        types,
        chain,
        identity: NodeIdentity | None = None,
        verify_signatures: bool = True,
        subscribe_all_subnets: bool = False,
        metrics=None,
        fleet_router=None,
    ):
        self.metrics = metrics
        self.config = config
        self.types = types
        self.chain = chain
        self.transport = Transport(identity)
        self.peer_id = self.transport.peer_id
        self.peer_manager = PeerManager()
        self.subscribe_all_subnets = subscribe_all_subnets

        # gossip: Ethereum score params for the topics we will join
        score_params = PeerScoreParams()
        self.gossip = Gossipsub(score_params)
        self.gossip.metrics = metrics
        self.gossip_service = GossipsubService(self.transport, self.gossip)
        self.gossip_handlers = GossipHandlers(
            config, types, chain, verify_signatures=verify_signatures,
            fleet_router=fleet_router,
        )
        self.gossip_handlers.register(self.gossip)
        self._score_params = score_params

        # reqresp
        self.reqresp_handlers = ReqRespHandlers(config, types, chain)
        self.reqresp = ReqRespService(
            self.transport, self.reqresp_handlers, types, self.peer_manager,
            metrics=_ReqRespMetricsAdapter(metrics) if metrics is not None else None,
        )

        # subnets
        from .subnets import SyncnetsService

        node_id = bytes.fromhex(self.peer_id)
        self.attnets = AttnetsService(node_id, config.preset.SLOTS_PER_EPOCH)
        self.syncnets = SyncnetsService(config.preset.SLOTS_PER_EPOCH)

        self.discovery = None  # enabled via start(discovery=True)
        self._dial_backoff: dict[str, float] = {}  # node_id → retry-after
        self._queue_drops_seen: dict[str, int] = {}  # per-topic drop watermark
        self._mesh_kinds_seen: set[str] = set()

        self._heartbeat_task: asyncio.Task | None = None
        self.transport.on_connection.append(self._on_connection)

    # -- lifecycle -----------------------------------------------------------

    async def start(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        discovery: bool = False,
        bootnodes: list | None = None,
        advertise_ip: str | None = None,
    ) -> tuple[str, int]:
        addr = await self.transport.listen(host, port)
        if discovery or bootnodes:
            # the ENR must carry a dialable address — a wildcard bind is not
            # one, so an explicit advertise_ip is required off-loopback
            ip = advertise_ip or addr[0]
            if ip in ("0.0.0.0", "::"):
                log.warning("wildcard bind with no advertise_ip; ENR uses loopback")
                ip = "127.0.0.1"
            await self._start_discovery((ip, addr[1]), bootnodes or [], bind_host=host)
        await self.subscribe_gossip_core_topics()
        self.gossip.start_heartbeat()
        self._heartbeat_task = asyncio.get_running_loop().create_task(
            self._heartbeat_loop()
        )
        return addr

    async def _start_discovery(
        self, advertise_addr, bootnodes: list, bind_host: str | None = None
    ) -> None:
        from .discovery import ENR, Discovery

        epoch = self.chain.clock.current_epoch
        enr = ENR(
            node_id=self.peer_id,
            pubkey=self.transport.identity.public_bytes,
            ip=advertise_addr[0],
            tcp_port=advertise_addr[1],
            udp_port=0,
            fork_digest=self._fork_digests_now()[0],
        )
        attnets = self.attnets.enr_attnets(epoch)
        self.discovery = Discovery(self.transport.identity, enr)
        self.discovery.metrics = self.metrics
        self.discovery.update_attnets(attnets)
        self.discovery.on_discovered.append(self._on_discovered)
        await self.discovery.start(bind_host or advertise_addr[0])
        self.discovery.start_liveness_loop()
        if bootnodes:
            await self.discovery.bootstrap(bootnodes)

    def _on_discovered(self, enr) -> None:
        """Dial newly-discovered peers while below the connection target
        (reference: PeerManager consuming discv5 discoveries); at target,
        the heartbeat re-dials from the discovery table when slots free."""
        if enr.node_id in self.transport.connections:
            return
        if len(self.transport.connections) >= self.peer_manager.target_peers:
            return
        asyncio.get_running_loop().create_task(self._dial_enr(enr))

    def _may_dial(self, node_id: str, now: float) -> bool:
        from .peers import ScoreState as _SS

        if self.peer_manager.scores.state(node_id) == _SS.Banned:
            return False
        return self._dial_backoff.get(node_id, 0.0) <= now

    async def _dial_enr(self, enr) -> None:
        import time as _time

        now = _time.monotonic()
        if not self._may_dial(enr.node_id, now):
            return
        # exponential per-peer backoff so dead records don't get a fresh
        # connect attempt every heartbeat
        prev = self._dial_backoff.get(enr.node_id)
        delay = DIAL_TIMEOUT if prev is None else min(
            300.0, max(DIAL_TIMEOUT, (prev - now) * 2 if prev > now else DIAL_TIMEOUT * 2)
        )
        self._dial_backoff[enr.node_id] = now + delay
        try:
            await asyncio.wait_for(self.connect(enr.ip, enr.tcp_port), DIAL_TIMEOUT)
            self._dial_backoff.pop(enr.node_id, None)
        except Exception as e:
            log.debug(f"dial {enr.node_id[:8]} failed: {e}")

    async def stop(self) -> None:
        if self._heartbeat_task is not None:
            self._heartbeat_task.cancel()
            try:
                await self._heartbeat_task
            except asyncio.CancelledError:
                pass
        await self.gossip.stop()
        if self.discovery is not None:
            self.discovery.stop()
        for q in self.gossip_handlers.queues.values():
            q.close()
        await self.transport.close()

    async def connect(self, host: str, port: int):
        conn = await self.transport.dial(host, port)
        return conn

    # -- topic management ----------------------------------------------------

    def _fork_digests_now(self) -> list[bytes]:
        """Digests to subscribe: current fork, plus the next fork's digest
        around a scheduled transition (reference network.ts fork logic)."""
        epoch = self.chain.clock.current_epoch
        return [
            self.config.fork_digest(f)
            for f in self.config.get_active_forks_around_epoch(epoch)
        ]

    async def subscribe_gossip_core_topics(self) -> None:
        for digest in self._fork_digests_now():
            for gtype in CORE_TOPICS:
                topic_str = stringify_topic(GossipTopic(gtype, digest))
                self._ensure_topic_params(topic_str)
                await self.gossip.subscribe(topic_str)
            # altair+ digests also carry the sync-committee topics
            fork = self.config.fork_name_from_digest(digest)
            if fork not in ("phase0",):
                for gtype in (
                    GossipType.sync_committee_contribution_and_proof,
                    GossipType.light_client_finality_update,
                    GossipType.light_client_optimistic_update,
                ):
                    topic_str = stringify_topic(GossipTopic(gtype, digest))
                    self._ensure_topic_params(topic_str)
                    await self.gossip.subscribe(topic_str)
                epoch = self.chain.clock.current_epoch
                for subnet in sorted(self.syncnets.active_subnets(epoch)):
                    topic_str = stringify_topic(
                        GossipTopic(GossipType.sync_committee, digest, subnet)
                    )
                    self._ensure_topic_params(topic_str)
                    await self.gossip.subscribe(topic_str)
            subnets = (
                range(64)
                if self.subscribe_all_subnets
                else sorted(self.attnets.active_subnets(self.chain.clock.current_epoch))
            )
            for subnet in subnets:
                await self.subscribe_subnet(subnet, digest)

    async def subscribe_subnet(self, subnet: int, digest: bytes | None = None) -> None:
        digests = [digest] if digest is not None else self._fork_digests_now()
        for d in digests:
            topic = GossipTopic(GossipType.beacon_attestation, d, subnet)
            await self.gossip.subscribe(stringify_topic(topic))
            self._ensure_topic_params(stringify_topic(topic))

    def _ensure_topic_params(self, topic_str: str) -> None:
        if topic_str not in self._score_params.topics:
            kind = topic_str.split("/")[3]
            base = kind.rsplit("_", 1)[0] if kind.rsplit("_", 1)[-1].isdigit() else kind
            self._score_params.topics[topic_str] = ethereum_topic_params(base)

    async def publish_block(self, signed_block) -> int:
        from .gossip.encoding import encode_message

        digest = self.config.fork_digest(
            self.config.get_fork_name_at_slot(int(signed_block.message.slot))
        )
        topic = stringify_topic(GossipTopic(GossipType.beacon_block, digest))
        return await self.gossip.publish(topic, encode_message(signed_block.serialize()))

    async def publish_attestation(self, attestation, subnet: int) -> int:
        from .gossip.encoding import encode_message

        digest = self.config.fork_digest(
            self.config.get_fork_name_at_slot(int(attestation.data.slot))
        )
        topic = stringify_topic(
            GossipTopic(GossipType.beacon_attestation, digest, subnet)
        )
        return await self.gossip.publish(topic, encode_message(attestation.serialize()))

    async def publish_aggregate(self, signed_agg) -> int:
        from .gossip.encoding import encode_message

        digest = self.config.fork_digest(
            self.config.get_fork_name_at_slot(
                int(signed_agg.message.aggregate.data.slot)
            )
        )
        topic = stringify_topic(
            GossipTopic(GossipType.beacon_aggregate_and_proof, digest)
        )
        return await self.gossip.publish(topic, encode_message(signed_agg.serialize()))

    # -- peers ---------------------------------------------------------------

    def _on_connection(self, conn) -> None:
        if not self.peer_manager.on_connect(
            conn.peer_id, "outbound" if conn.initiator else "inbound"
        ):
            asyncio.get_running_loop().create_task(conn.close())
            return
        # a replaced connection (simultaneous cross-dial) must not tear down
        # the live successor's PeerInfo — only the CURRENT conn's close counts
        def on_close(c=conn):
            if self.transport.connections.get(c.peer_id) is None:
                self.peer_manager.on_disconnect(c.peer_id)

        conn.on_close.append(on_close)
        asyncio.get_running_loop().create_task(self._status_handshake(conn.peer_id))

    async def _status_handshake(self, peer_id: str) -> None:
        try:
            status = await self.reqresp.status(peer_id)
            self.peer_manager.on_status(peer_id, status)
        except Exception as e:
            # peers that never answer status get pruned by scoring
            log.debug("status handshake with %s failed: %s", peer_id, e)

    def sync_peers(self, loop: asyncio.AbstractEventLoop) -> list[RemotePeer]:
        """RemotePeer views of all connected peers for the sync layer."""
        return [
            RemotePeer(self.reqresp, pid, loop)
            for pid in self.transport.connections
        ]

    async def _refresh_subnet_subscriptions(self) -> None:
        """Join any newly-active duty subnets (attnets short-lived +
        syncnets membership change after start) and prune expired ones —
        the dynamic half of the reference's subnet services."""
        epoch = self.chain.clock.current_epoch
        self.syncnets.prune(epoch)
        for digest in self._fork_digests_now():
            for subnet in self.attnets.active_subnets(epoch):
                topic = stringify_topic(
                    GossipTopic(GossipType.beacon_attestation, digest, subnet)
                )
                if topic not in self.gossip.subscriptions:
                    await self.subscribe_subnet(subnet, digest)
            if self.config.fork_name_from_digest(digest) != "phase0":
                for subnet in self.syncnets.active_subnets(epoch):
                    topic = stringify_topic(
                        GossipTopic(GossipType.sync_committee, digest, subnet)
                    )
                    if topic not in self.gossip.subscriptions:
                        self._ensure_topic_params(topic)
                        await self.gossip.subscribe(topic)

    def _export_metrics(self) -> None:
        m = self.metrics
        if m is None:
            return
        m.peers_connected.set(len(self.transport.connections))
        if self.discovery is not None:
            m.discovery_table_size.set(len(self.discovery.table))
            m.discv5_endpoint_proofs.set(len(self.discovery._endpoint_proven))
            m.discv5_pending_challenges.set(len(self.discovery._ping_addr))
        from .gossip.topic import parse_topic

        by_kind: dict[str, int] = {}
        for topic, mesh in self.gossip.mesh.items():
            try:
                kind = parse_topic(topic).type.value
            except ValueError:
                continue
            by_kind[kind] = by_kind.get(kind, 0) + len(mesh)
        # zero kinds that left the mesh so stale gauge series don't linger
        for kind in self._mesh_kinds_seen - set(by_kind):
            m.gossip_mesh_peers.set(0, kind=kind)
        self._mesh_kinds_seen |= set(by_kind)
        for kind, size in by_kind.items():
            m.gossip_mesh_peers.set(size, kind=kind)
        # peer-score distribution (reference gossipsub scores dashboard)
        scores = [
            self.gossip.score.score(pid) for pid in self.gossip.peers
        ]
        if scores:
            bands = {"negative": 0, "zero": 0, "positive": 0}
            for sc in scores:
                if sc < 0:
                    bands["negative"] += 1
                elif sc > 0:
                    bands["positive"] += 1
                else:
                    bands["zero"] += 1
            for band, n in bands.items():
                m.gossip_peers_by_score.set(n, band=band)
            m.gossip_score_min.set(min(scores))
            m.gossip_score_max.set(max(scores))
        # process health
        try:
            import os as _os

            with open("/proc/self/statm") as f:
                rss_pages = int(f.read().split()[1])
            m.process_rss_bytes.set(rss_pages * _os.sysconf("SC_PAGE_SIZE"))
        except (OSError, ValueError, IndexError):
            pass  # no /proc (non-Linux): RSS gauge simply stays unset
        try:
            import os as _os

            m.open_fds.set(len(_os.listdir("/proc/self/fd")))
        except OSError:
            pass  # no /proc (non-Linux): fd gauge simply stays unset
        for gtype, queue in self.gossip_handlers.queues.items():
            m.gossip_queue_length.set(len(queue), topic=gtype.value)
            seen = self._queue_drops_seen.get(gtype.value, 0)
            dropped = queue.metrics.dropped_jobs
            if dropped > seen:
                m.gossip_queue_dropped_total.inc(dropped - seen, topic=gtype.value)
                self._queue_drops_seen[gtype.value] = dropped

    async def _heartbeat_loop(self) -> None:
        while True:
            t0 = asyncio.get_running_loop().time()
            await asyncio.sleep(HEARTBEAT_SEC)
            if self.metrics is not None:
                # scheduling overshoot of the sleep = event-loop lag
                lag = asyncio.get_running_loop().time() - t0 - HEARTBEAT_SEC
                self.metrics.event_loop_lag_seconds.set(max(0.0, lag))
            try:
                self._export_metrics()
                await self._refresh_subnet_subscriptions()
                # below-target: dial peers known to discovery but not yet
                # connected (reference: PeerManager discover-on-heartbeat).
                # Dials are concurrent, time-capped tasks, at most enough to
                # reach the target — a stale ENR must not stall the beat
                if self.discovery is not None:
                    want = self.peer_manager.target_peers - len(
                        self.transport.connections
                    )
                    if want > 0:
                        import time as _time

                        now = _time.monotonic()
                        candidates = [
                            enr
                            for enr in self.discovery.table.all()
                            if enr.node_id not in self.transport.connections
                            and self._may_dial(enr.node_id, now)
                        ][:want]
                        for enr in candidates:
                            asyncio.get_running_loop().create_task(
                                self._dial_enr(enr)
                            )
                # feed gossip scores into the peer manager: deep gossip
                # negatives become actionable peer-manager penalties so the
                # prune pass below disconnects/bans them
                from .gossip.score import GRAYLIST_THRESHOLD, PUBLISH_THRESHOLD

                for pid in list(self.transport.connections):
                    gscore = self.gossip.score.score(pid)
                    if gscore <= GRAYLIST_THRESHOLD:
                        self.peer_manager.report_peer(pid, PeerAction.Fatal)
                    elif gscore <= PUBLISH_THRESHOLD:
                        self.peer_manager.report_peer(
                            pid, PeerAction.LowToleranceError
                        )
                to_drop = self.peer_manager.heartbeat()
                for pid in to_drop:
                    conn = self.transport.connections.get(pid)
                    if conn is not None:
                        await self.reqresp.goodbye(pid)
                        await conn.close()
            except Exception as e:  # noqa: BLE001
                log.debug(f"network heartbeat error: {e}")

    def report_peer(self, peer_id: str, action: PeerAction) -> None:
        self.peer_manager.report_peer(peer_id, action)


class _ReqRespMetricsAdapter:
    """Bridges ReqRespService's observe hooks onto the metric registry
    (per-protocol latency, request/byte/error counters, rate limits —
    reference metric families: lodestar.ts reqResp.*)."""

    def __init__(self, metrics):
        self._metrics = metrics

    def observe_reqresp(self, protocol: str, seconds: float) -> None:
        self._metrics.reqresp_seconds.observe(seconds, protocol=protocol)

    def incoming_request(self, protocol: str) -> None:
        self._metrics.reqresp_incoming_requests_total.inc(protocol=protocol)

    def incoming_error(self, protocol: str) -> None:
        self._metrics.reqresp_incoming_errors_total.inc(protocol=protocol)

    def outgoing_request(self, protocol: str) -> None:
        self._metrics.reqresp_outgoing_requests_total.inc(protocol=protocol)

    def outgoing_error(self, protocol: str) -> None:
        self._metrics.reqresp_outgoing_errors_total.inc(protocol=protocol)

    def bytes_sent(self, protocol: str, n: int) -> None:
        self._metrics.reqresp_bytes_sent_total.inc(n, protocol=protocol)

    def bytes_received(self, protocol: str, n: int) -> None:
        self._metrics.reqresp_bytes_received_total.inc(n, protocol=protocol)

    def rate_limited(self, limiter: str) -> None:
        self._metrics.reqresp_rate_limited_total.inc(limiter=limiter)

    def response_chunk(self, code: str, n: int = 1) -> None:
        self._metrics.reqresp_response_chunks_total.inc(n, code=code)
