"""Peer scoring + peer manager bookkeeping.

Reference: `network/peers/score.ts` (PeerRpcScoreStore — actioned score
bands, exponential decay, ban thresholds) and `peerManager.ts` (target
peer maintenance, status handshake bookkeeping). The transport-level
dial/disconnect side arrives with the live transport; scoring and the
keep/prune decision logic are transport-independent.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from enum import Enum


class PeerAction(str, Enum):
    # reference score.ts action weights
    Fatal = "Fatal"
    LowToleranceError = "LowToleranceError"
    MidToleranceError = "MidToleranceError"
    HighToleranceError = "HighToleranceError"


ACTION_SCORES = {
    PeerAction.Fatal: -(2**10),
    PeerAction.LowToleranceError: -10.0,
    PeerAction.MidToleranceError: -5.0,
    PeerAction.HighToleranceError: -1.0,
}

MIN_SCORE = -100.0
MAX_SCORE = 100.0
BAN_THRESHOLD = -50.0
DISCONNECT_THRESHOLD = -20.0
SCORE_HALFLIFE_SEC = 600.0


class ScoreState(str, Enum):
    Healthy = "Healthy"
    Disconnected = "Disconnected"
    Banned = "Banned"


@dataclass
class _PeerScore:
    score: float = 0.0
    last_update: float = field(default_factory=time.time)


class PeerRpcScoreStore:
    def __init__(self, time_fn=time.time):
        self._scores: dict[str, _PeerScore] = {}
        self._time = time_fn

    def apply_action(self, peer_id: str, action: PeerAction) -> None:
        rec = self._scores.setdefault(peer_id, _PeerScore(last_update=self._time()))
        self._decay(rec)
        rec.score = max(MIN_SCORE, min(MAX_SCORE, rec.score + ACTION_SCORES[action]))

    def _decay(self, rec: _PeerScore) -> None:
        now = self._time()
        dt = now - rec.last_update
        if dt > 0:
            rec.score *= 0.5 ** (dt / SCORE_HALFLIFE_SEC)
            rec.last_update = now

    def score(self, peer_id: str) -> float:
        rec = self._scores.get(peer_id)
        if rec is None:
            return 0.0
        self._decay(rec)
        return rec.score

    def state(self, peer_id: str) -> ScoreState:
        s = self.score(peer_id)
        if s <= BAN_THRESHOLD:
            return ScoreState.Banned
        if s <= DISCONNECT_THRESHOLD:
            return ScoreState.Disconnected
        return ScoreState.Healthy


@dataclass
class PeerInfo:
    peer_id: str
    status: object | None = None  # last Status handshake
    connected_at: float = 0.0
    direction: str = "outbound"


class PeerManager:
    """Connected-peer bookkeeping + prune decisions (reference
    peerManager.ts heartbeat: keep target_peers, prune worst-scored,
    never keep banned)."""

    def __init__(self, target_peers: int = 50, time_fn=time.time):
        self.target_peers = target_peers
        self.peers: dict[str, PeerInfo] = {}
        self.scores = PeerRpcScoreStore(time_fn)
        self._time = time_fn

    def on_connect(self, peer_id: str, direction: str = "outbound") -> bool:
        if self.scores.state(peer_id) == ScoreState.Banned:
            return False
        self.peers[peer_id] = PeerInfo(
            peer_id=peer_id, connected_at=self._time(), direction=direction
        )
        return True

    def on_disconnect(self, peer_id: str) -> None:
        self.peers.pop(peer_id, None)

    def on_status(self, peer_id: str, status) -> None:
        info = self.peers.get(peer_id)
        if info is not None:
            info.status = status

    def report_peer(self, peer_id: str, action: PeerAction) -> None:
        self.scores.apply_action(peer_id, action)

    def heartbeat(self) -> list[str]:
        """Returns peer ids to disconnect: banned/bad-scored first, then
        excess above target (worst score first)."""
        to_drop = [
            pid
            for pid in self.peers
            if self.scores.state(pid) != ScoreState.Healthy
        ]
        remaining = [p for p in self.peers if p not in to_drop]
        excess = len(remaining) - self.target_peers
        if excess > 0:
            remaining.sort(key=lambda p: self.scores.score(p))
            to_drop.extend(remaining[:excess])
        for pid in to_drop:
            self.on_disconnect(pid)
        return to_drop
