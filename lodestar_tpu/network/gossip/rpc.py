"""Gossipsub RPC wire codec.

Reference: gossipsub v1.1 RPCs (`@chainsafe/libp2p-gossipsub` message.ts /
protobuf RPC). Ethereum gossip is *anonymous* (no from/seqno/signature —
StrictNoSign, message id is content-derived: `gossip/encoding.ts`), so the
RPC here carries exactly: subscriptions, published messages (topic+data),
and control (IHAVE/IWANT/GRAFT/PRUNE). Encoding is tag-length-value with
varints — one self-contained frame per RPC on the gossip stream.
"""

from __future__ import annotations

from dataclasses import dataclass, field

_TAG_SUB = 1
_TAG_UNSUB = 2
_TAG_MSG = 3
_TAG_IHAVE = 4
_TAG_IWANT = 5
_TAG_GRAFT = 6
_TAG_PRUNE = 7

MAX_RPC_SIZE = 10 * 2**20


@dataclass
class ControlIHave:
    topic: str
    msg_ids: list[bytes] = field(default_factory=list)


@dataclass
class ControlPrune:
    topic: str
    backoff_sec: int = 60


@dataclass
class RPC:
    subscriptions: list[tuple[bool, str]] = field(default_factory=list)
    messages: list[tuple[str, bytes]] = field(default_factory=list)  # (topic, wire data)
    ihave: list[ControlIHave] = field(default_factory=list)
    iwant: list[bytes] = field(default_factory=list)  # msg ids
    graft: list[str] = field(default_factory=list)  # topics
    prune: list[ControlPrune] = field(default_factory=list)

    def is_empty(self) -> bool:
        return not (
            self.subscriptions or self.messages or self.ihave or self.iwant
            or self.graft or self.prune
        )


def _varint(n: int) -> bytes:
    out = bytearray()
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    return bytes(out)


def _read_varint(data: bytes, i: int) -> tuple[int, int]:
    value, shift = 0, 0
    while i < len(data):
        b = data[i]
        i += 1
        value |= (b & 0x7F) << shift
        if not (b & 0x80):
            if value > MAX_RPC_SIZE:
                raise ValueError("varint exceeds RPC bound")
            return value, i
        shift += 7
        if shift > 35:
            break
    raise ValueError("bad varint in gossip RPC")


def _lv(data: bytes) -> bytes:
    return _varint(len(data)) + data


def _read_lv(data: bytes, i: int) -> tuple[bytes, int]:
    n, i = _read_varint(data, i)
    if i + n > len(data):
        raise ValueError("truncated RPC field")
    return data[i : i + n], i + n


def encode_rpc(rpc: RPC) -> bytes:
    out = bytearray()
    for subscribe, topic in rpc.subscriptions:
        out.append(_TAG_SUB if subscribe else _TAG_UNSUB)
        out += _lv(topic.encode())
    for topic, data in rpc.messages:
        out.append(_TAG_MSG)
        out += _lv(_lv(topic.encode()) + data)
    for ih in rpc.ihave:
        out.append(_TAG_IHAVE)
        body = _lv(ih.topic.encode()) + _varint(len(ih.msg_ids)) + b"".join(
            _lv(m) for m in ih.msg_ids
        )
        out += _lv(body)
    if rpc.iwant:
        out.append(_TAG_IWANT)
        body = _varint(len(rpc.iwant)) + b"".join(_lv(m) for m in rpc.iwant)
        out += _lv(body)
    for topic in rpc.graft:
        out.append(_TAG_GRAFT)
        out += _lv(topic.encode())
    for pr in rpc.prune:
        out.append(_TAG_PRUNE)
        out += _lv(_lv(pr.topic.encode()) + _varint(pr.backoff_sec))
    return bytes(out)


def decode_rpc(wire: bytes) -> RPC:
    if len(wire) > MAX_RPC_SIZE:
        raise ValueError("RPC too large")
    rpc = RPC()
    i = 0
    while i < len(wire):
        tag = wire[i]
        i += 1
        if tag in (_TAG_SUB, _TAG_UNSUB):
            topic, i = _read_lv(wire, i)
            rpc.subscriptions.append((tag == _TAG_SUB, topic.decode(errors="replace")))
        elif tag == _TAG_MSG:
            body, i = _read_lv(wire, i)
            topic, j = _read_lv(body, 0)
            rpc.messages.append((topic.decode(errors="replace"), body[j:]))
        elif tag == _TAG_IHAVE:
            body, i = _read_lv(wire, i)
            topic, j = _read_lv(body, 0)
            count, j = _read_varint(body, j)
            ids = []
            for _ in range(min(count, 5000)):
                mid, j = _read_lv(body, j)
                ids.append(mid)
            rpc.ihave.append(ControlIHave(topic.decode(errors="replace"), ids))
        elif tag == _TAG_IWANT:
            body, i = _read_lv(wire, i)
            count, j = _read_varint(body, 0)
            for _ in range(min(count, 5000)):
                mid, j = _read_lv(body, j)
                rpc.iwant.append(mid)
        elif tag == _TAG_GRAFT:
            topic, i = _read_lv(wire, i)
            rpc.graft.append(topic.decode(errors="replace"))
        elif tag == _TAG_PRUNE:
            body, i = _read_lv(wire, i)
            topic, j = _read_lv(body, 0)
            backoff, j = _read_varint(body, j)
            rpc.prune.append(ControlPrune(topic.decode(errors="replace"), backoff))
        else:
            raise ValueError(f"unknown RPC tag {tag}")
    return rpc
