"""Gossipsub v1.1 router over the secure transport.

Reference: `network/gossip/gossipsub.ts:77` (`Eth2Gossipsub extends
GossipSub`) + `@chainsafe/libp2p-gossipsub`. Implements the v1.1 mesh
protocol: per-topic meshes bounded by D_LO ≤ D ≤ D_HI, heartbeat mesh
maintenance with score-aware GRAFT/PRUNE + prune backoff, fanout for
unsubscribed publishes, message-cache windows feeding IHAVE gossip,
IWANT recovery, flood-publish for own messages, and the v1.1 peer-score
gates (gossip/publish/graylist thresholds).

Ethereum profile: anonymous messages (content-derived msg-id via
`encoding.compute_msg_id`), ssz_snappy payloads, per-topic async
validators returning ACCEPT/IGNORE/REJECT wired by
`network/gossip/handlers.py`.
"""

from __future__ import annotations

import asyncio
import random
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from enum import Enum

from ...utils.logger import get_logger
from .encoding import compute_msg_id
from .rpc import RPC, ControlIHave, ControlPrune, decode_rpc, encode_rpc
from .score import (
    DECAY_INTERVAL,
    GOSSIP_THRESHOLD,
    GRAYLIST_THRESHOLD,
    OPPORTUNISTIC_GRAFT_THRESHOLD,
    PUBLISH_THRESHOLD,
    PeerScore,
    PeerScoreParams,
)

GOSSIPSUB_PROTOCOL = "/meshsub/1.1.0"

# mesh degree bounds (gossipsub spec defaults, used by the reference)
D = 8
D_LO = 6
D_HI = 12
D_SCORE = 4  # mesh peers kept by score during pruning
D_LAZY = 6  # gossip emission degree
GOSSIP_FACTOR = 0.25
HEARTBEAT_INTERVAL = 0.7  # seconds (gossipsub spec)
MCACHE_GOSSIP = 3  # windows advertised in IHAVE
MCACHE_LEN = 6  # total history windows
SEEN_TTL = 120.0
PRUNE_BACKOFF = 60.0
FANOUT_TTL = 60.0
MAX_IHAVE_PER_HEARTBEAT = 5000
# per-peer IWANT service budget, reset each heartbeat (bandwidth-sink guard)
MAX_IWANT_SERVED_PER_HEARTBEAT = 512


def _topic_kind(topic: str) -> str:
    """Topic kind for metric labels (bounded cardinality: subnet topics
    collapse onto their kind)."""
    from .topic import parse_topic

    try:
        return parse_topic(topic).type.value
    except ValueError:
        return "unknown"

log = get_logger("gossipsub")


class ValidationResult(str, Enum):
    ACCEPT = "ACCEPT"
    IGNORE = "IGNORE"
    REJECT = "REJECT"


class TimedSet:
    """Insertion-ordered set whose entries expire after a TTL."""

    def __init__(self, ttl: float, time_fn=time.monotonic):
        self.ttl = ttl
        self._time = time_fn
        self._items: OrderedDict[bytes, float] = OrderedDict()

    def put(self, key: bytes) -> bool:
        """True if newly added (not seen before)."""
        self._expire()
        if key in self._items:
            return False
        self._items[key] = self._time()
        return True

    def __contains__(self, key: bytes) -> bool:
        self._expire()
        return key in self._items

    def _expire(self) -> None:
        cutoff = self._time() - self.ttl
        while self._items:
            key, t = next(iter(self._items.items()))
            if t >= cutoff:
                break
            self._items.popitem(last=False)


class MessageCache:
    """Sliding windows of recent messages for IHAVE/IWANT (mcache)."""

    def __init__(self, gossip_windows: int = MCACHE_GOSSIP, total: int = MCACHE_LEN):
        self.gossip_windows = gossip_windows
        self.windows: list[list[tuple[bytes, str]]] = [[] for _ in range(total)]
        self.msgs: dict[bytes, tuple[str, bytes]] = {}

    def put(self, msg_id: bytes, topic: str, data: bytes) -> None:
        self.msgs[msg_id] = (topic, data)
        self.windows[0].append((msg_id, topic))

    def get(self, msg_id: bytes) -> tuple[str, bytes] | None:
        return self.msgs.get(msg_id)

    def gossip_ids(self, topic: str) -> list[bytes]:
        out = []
        for window in self.windows[: self.gossip_windows]:
            out.extend(mid for mid, t in window if t == topic)
        return out

    def shift(self) -> None:
        expired = self.windows.pop()
        for mid, _topic in expired:
            self.msgs.pop(mid, None)
        self.windows.insert(0, [])


@dataclass
class PeerState:
    peer_id: str
    send: object  # async callable(bytes) -> None
    topics: set[str] = field(default_factory=set)  # peer's subscriptions
    outbound: bool = False  # we dialed them (quota for mesh diversity)
    dont_send_until: dict[str, float] = field(default_factory=dict)  # prune backoff


class Gossipsub:
    """The router. Transport-agnostic: peers are attached with an async
    `send(bytes)`; incoming RPC bytes are fed to `on_rpc(peer_id, wire)`."""

    def __init__(
        self,
        score_params: PeerScoreParams | None = None,
        time_fn=time.monotonic,
        rng: random.Random | None = None,
    ):
        self.peers: dict[str, PeerState] = {}
        # per-peer IWANT messages served this heartbeat: lives on the
        # ROUTER (not PeerState) so connection churn cannot reset it —
        # mirroring how PeerScore retains scores across reconnects
        self._iwant_served: dict[str, int] = {}
        self.subscriptions: set[str] = set()
        self.mesh: dict[str, set[str]] = {}
        self.fanout: dict[str, set[str]] = {}
        self.fanout_last_pub: dict[str, float] = {}
        self.mcache = MessageCache()
        self.seen = TimedSet(SEEN_TTL, time_fn)
        self.score = PeerScore(score_params, time_fn)
        self.validators: dict[str, object] = {}  # topic prefix → async validator
        self._time = time_fn
        self._rng = rng or random.Random(0xE7)
        self._heartbeat_task: asyncio.Task | None = None
        self._last_decay = time_fn()
        self.on_message = None  # async (topic, ssz_wire) after ACCEPT — app tap
        self.metrics = None

    # ------------------------------------------------------------- peer admin

    def add_peer(self, peer_id: str, send, outbound: bool, ip: str | None = None) -> None:
        self.peers[peer_id] = PeerState(peer_id=peer_id, send=send, outbound=outbound)
        self.score.add_peer(peer_id, ip)

    def remove_peer(self, peer_id: str) -> None:
        self.peers.pop(peer_id, None)
        for peers in self.mesh.values():
            peers.discard(peer_id)
        for peers in self.fanout.values():
            peers.discard(peer_id)
        self.score.remove_peer(peer_id)

    # ---------------------------------------------------------- subscriptions

    async def subscribe(self, topic: str) -> None:
        if topic in self.subscriptions:
            return
        self.subscriptions.add(topic)
        self.mesh.setdefault(topic, set())
        # announce to all peers; graft happens at heartbeat (or join now)
        await self._broadcast(RPC(subscriptions=[(True, topic)]))
        await self._join(topic)

    async def unsubscribe(self, topic: str) -> None:
        if topic not in self.subscriptions:
            return
        self.subscriptions.discard(topic)
        peers = self.mesh.pop(topic, set())
        rpc = RPC(subscriptions=[(False, topic)], prune=[ControlPrune(topic)])
        for pid in peers:
            self.score.prune(pid, topic)
            await self._send(pid, rpc)
        others = RPC(subscriptions=[(False, topic)])
        for pid in self.peers:
            if pid not in peers:
                await self._send(pid, others)

    async def _join(self, topic: str) -> None:
        mesh = self.mesh.setdefault(topic, set())
        candidates = self._topic_peers(topic, exclude=mesh)
        add = self._select_peers(candidates, D - len(mesh))
        for pid in add:
            mesh.add(pid)
            self.score.graft(pid, topic)
            await self._send(pid, RPC(graft=[topic]))

    # ---------------------------------------------------------------- publish

    async def publish(self, topic: str, data: bytes) -> int:
        """Publish ssz_snappy wire data; returns receiver count.

        Flood-publish (v1.1 default): send to ALL known topic peers above
        the publish threshold, not just the mesh — hardens own messages
        against sybil meshes."""
        msg_id = compute_msg_id(topic, data)
        if not self.seen.put(msg_id):
            return 0
        self.mcache.put(msg_id, topic, data)
        targets = {
            pid
            for pid in self._topic_peers(topic)
            if self.score.score(pid) >= PUBLISH_THRESHOLD
        }
        if not targets and topic not in self.subscriptions:
            # fanout fallback when nobody known yet
            targets = self.fanout.setdefault(topic, set())
            self.fanout_last_pub[topic] = self._time()
        rpc = RPC(messages=[(topic, data)])
        for pid in targets:
            await self._send(pid, rpc)
        if self.metrics is not None:
            self.metrics.gossip_tx_total.inc()
        return len(targets)

    # ------------------------------------------------------------------ input

    async def on_rpc(self, peer_id: str, wire: bytes) -> None:
        peer = self.peers.get(peer_id)
        if peer is None:
            return
        if self.score.score(peer_id) < GRAYLIST_THRESHOLD:
            return  # graylisted: ignore everything
        try:
            rpc = decode_rpc(wire)
        except ValueError:
            self.score.add_behaviour_penalty(peer_id)
            return
        for subscribe, topic in rpc.subscriptions:
            (peer.topics.add if subscribe else peer.topics.discard)(topic)
            if not subscribe:
                self.mesh.get(topic, set()).discard(peer_id)
        for topic, data in rpc.messages:
            await self._handle_message(peer_id, topic, data)
        if rpc.graft or rpc.prune:
            await self._handle_graft_prune(peer, rpc)
        if rpc.ihave or rpc.iwant:
            await self._handle_gossip_control(peer, rpc)

    async def _handle_message(self, peer_id: str, topic: str, data: bytes) -> None:
        msg_id = compute_msg_id(topic, data)
        first = self.seen.put(msg_id)
        self.score.deliver_message(peer_id, topic, first=first)
        if not first:
            if self.metrics is not None:
                self.metrics.gossip_duplicates_total.inc()
            return
        if topic not in self.subscriptions:
            # not our topic: don't validate or forward
            return
        import time as _time

        t0 = _time.monotonic()
        result = await self._validate(topic, data)
        if self.metrics is not None:
            self.metrics.gossip_rx_total.inc(outcome=result.value)
            kind = _topic_kind(topic)
            self.metrics.gossip_validation_total.inc(
                kind=kind, outcome=result.value
            )
            self.metrics.gossip_validation_seconds.observe(
                _time.monotonic() - t0, kind=kind
            )
        if result is ValidationResult.REJECT:
            self.score.reject_message(peer_id, topic)
            return
        if result is ValidationResult.IGNORE:
            return
        self.mcache.put(msg_id, topic, data)
        await self._forward(topic, data, exclude={peer_id})
        if self.on_message is not None:
            await self.on_message(topic, data)

    async def _validate(self, topic: str, data: bytes) -> ValidationResult:
        validator = self.validators.get(topic)
        if validator is None:
            # prefix match (subnet topics share one validator)
            for prefix, v in self.validators.items():
                if topic.startswith(prefix):
                    validator = v
                    break
        if validator is None:
            return ValidationResult.ACCEPT
        try:
            return await validator(topic, data)
        except Exception as e:  # validator crash = ignore, never forward
            log.debug(f"validator error on {topic}: {e}")
            return ValidationResult.IGNORE

    async def _forward(self, topic: str, data: bytes, exclude: set[str]) -> None:
        mesh = self.mesh.get(topic, set())
        rpc = RPC(messages=[(topic, data)])
        for pid in mesh - exclude:
            await self._send(pid, rpc)

    async def _handle_graft_prune(self, peer: PeerState, rpc: RPC) -> None:
        if self.metrics is not None:
            if rpc.graft:
                self.metrics.gossip_graft_rx_total.inc(len(rpc.graft))
            if rpc.prune:
                self.metrics.gossip_prune_rx_total.inc(len(rpc.prune))
        prunes = []
        now = self._time()
        for topic in rpc.graft:
            mesh = self.mesh.get(topic)
            backoff = peer.dont_send_until.get(topic, 0.0)
            if mesh is None:
                prunes.append(ControlPrune(topic))  # not subscribed
            elif backoff > now:
                # grafting inside backoff is a protocol violation (v1.1)
                self.score.add_behaviour_penalty(peer.peer_id)
                prunes.append(ControlPrune(topic))
            elif self.score.score(peer.peer_id) < 0:
                prunes.append(ControlPrune(topic))
            else:
                mesh.add(peer.peer_id)
                self.score.graft(peer.peer_id, topic)
                if self.metrics is not None:
                    self.metrics.gossip_mesh_churn_total.inc(direction="graft")
        for pr in rpc.prune:
            mesh = self.mesh.get(pr.topic)
            if mesh is not None and peer.peer_id in mesh:
                mesh.discard(peer.peer_id)
                self.score.prune(peer.peer_id, pr.topic)
                if self.metrics is not None:
                    self.metrics.gossip_mesh_churn_total.inc(direction="prune")
            peer.dont_send_until[pr.topic] = now + pr.backoff_sec
        if prunes:
            await self._send(peer.peer_id, RPC(prune=prunes))

    async def _handle_gossip_control(self, peer: PeerState, rpc: RPC) -> None:
        peer_score = self.score.score(peer.peer_id)  # once per RPC
        if self.metrics is not None:
            if rpc.ihave:
                self.metrics.gossip_ihave_rx_total.inc(
                    sum(len(ih.msg_ids) for ih in rpc.ihave)
                )
            if rpc.iwant:
                self.metrics.gossip_iwant_rx_total.inc(len(rpc.iwant))
        # IHAVE → request unseen ids (only from peers above gossip threshold)
        if rpc.ihave and peer_score >= GOSSIP_THRESHOLD:
            want = []
            for ih in rpc.ihave:
                if ih.topic not in self.subscriptions:
                    continue
                want.extend(mid for mid in ih.msg_ids if mid not in self.seen)
            if want:
                await self._send(peer.peer_id, RPC(iwant=want[:MAX_IHAVE_PER_HEARTBEAT]))
        # IWANT → serve from mcache, gated on peer score and a per-peer
        # per-heartbeat budget (round-1 advisor: without the quota a
        # graylist-adjacent peer can re-request the whole cache every RPC
        # and use the node as a bandwidth sink; the v1.1 spec expects
        # IWANT service limits — reference gossipsub MAX_IWANT quota)
        if rpc.iwant and peer_score >= GOSSIP_THRESHOLD:
            budget = MAX_IWANT_SERVED_PER_HEARTBEAT - self._iwant_served.get(
                peer.peer_id, 0
            )
            if budget > 0:
                msgs = []
                examined = 0
                for mid in rpc.iwant:
                    if len(msgs) >= budget:
                        break  # budget counts SERVED messages, not ids
                    examined += 1
                    entry = self.mcache.get(mid)
                    if entry is not None:
                        msgs.append(entry)
                if self.metrics is not None:
                    # only ids the serve loop never reached were gated by
                    # the budget; examined-but-expired ids are not drops
                    skipped = len(rpc.iwant) - examined
                    if skipped > 0:
                        self.metrics.gossip_iwant_budget_drops_total.inc(skipped)
                if msgs:
                    self._iwant_served[peer.peer_id] = (
                        self._iwant_served.get(peer.peer_id, 0) + len(msgs)
                    )
                    if self.metrics is not None:
                        self.metrics.gossip_iwant_served_total.inc(len(msgs))
                    await self._send(peer.peer_id, RPC(messages=msgs))
            elif self.metrics is not None:
                # budget exhausted before this RPC: everything requested
                # was gated by the budget
                self.metrics.gossip_iwant_budget_drops_total.inc(len(rpc.iwant))

    # -------------------------------------------------------------- heartbeat

    def start_heartbeat(self) -> None:
        self._heartbeat_task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._heartbeat_task is not None:
            self._heartbeat_task.cancel()
            try:
                await self._heartbeat_task
            except asyncio.CancelledError:
                pass

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(HEARTBEAT_INTERVAL)
            try:
                await self.heartbeat()
            except Exception as e:  # noqa: BLE001
                log.debug(f"heartbeat error: {e}")

    async def heartbeat(self) -> None:
        now = self._time()
        self._iwant_served.clear()  # refresh the per-heartbeat IWANT budgets
        if now - self._last_decay >= DECAY_INTERVAL:
            self.score.decay()
            self._last_decay = now

        for topic in list(self.subscriptions):
            mesh = self.mesh.setdefault(topic, set())
            # drop negative-score mesh members
            for pid in [p for p in mesh if self.score.score(p) < 0]:
                mesh.discard(pid)
                self.score.prune(pid, topic)
                await self._send_prune(pid, topic)
            # grow to D
            if len(mesh) < D_LO:
                candidates = [
                    pid
                    for pid in self._topic_peers(topic, exclude=mesh)
                    if self.score.score(pid) >= 0
                    and self.peers[pid].dont_send_until.get(topic, 0.0) <= now
                ]
                for pid in self._select_peers(candidates, D - len(mesh)):
                    mesh.add(pid)
                    self.score.graft(pid, topic)
                    await self._send(pid, RPC(graft=[topic]))
            # shrink to D, keeping the best D_SCORE by score
            elif len(mesh) > D_HI:
                ranked = sorted(mesh, key=lambda p: -self.score.score(p))
                keep = set(ranked[:D_SCORE])
                pool = [p for p in ranked[D_SCORE:]]
                self._rng.shuffle(pool)
                keep.update(pool[: D - D_SCORE])
                for pid in list(mesh - keep):
                    mesh.discard(pid)
                    self.score.prune(pid, topic)
                    await self._send_prune(pid, topic)
            # opportunistic grafting: median mesh score too low → add good peers
            elif len(mesh) >= D_LO:
                scores = sorted(self.score.score(p) for p in mesh)
                median = scores[len(scores) // 2] if scores else 0.0
                if median < OPPORTUNISTIC_GRAFT_THRESHOLD:
                    candidates = [
                        pid
                        for pid in self._topic_peers(topic, exclude=mesh)
                        if self.score.score(pid) > median
                        and self.peers[pid].dont_send_until.get(topic, 0.0) <= now
                    ]
                    for pid in self._select_peers(candidates, 2):
                        mesh.add(pid)
                        self.score.graft(pid, topic)
                        await self._send(pid, RPC(graft=[topic]))

            # emit IHAVE gossip to a random slice of non-mesh topic peers
            ids = self.mcache.gossip_ids(topic)
            if ids:
                others = [
                    pid
                    for pid in self._topic_peers(topic, exclude=mesh)
                    if self.score.score(pid) >= GOSSIP_THRESHOLD
                ]
                k = max(D_LAZY, int(GOSSIP_FACTOR * len(others)))
                self._rng.shuffle(others)
                ih = RPC(ihave=[ControlIHave(topic, ids[:MAX_IHAVE_PER_HEARTBEAT])])
                for pid in others[:k]:
                    await self._send(pid, ih)

        # expire fanout
        for topic in list(self.fanout):
            if now - self.fanout_last_pub.get(topic, 0.0) > FANOUT_TTL:
                del self.fanout[topic]
                self.fanout_last_pub.pop(topic, None)

        self.mcache.shift()

    async def _send_prune(self, pid: str, topic: str) -> None:
        await self._send(pid, RPC(prune=[ControlPrune(topic, int(PRUNE_BACKOFF))]))

    # ------------------------------------------------------------------ utils

    def _topic_peers(self, topic: str, exclude: set[str] | None = None) -> list[str]:
        exclude = exclude or set()
        return [
            pid
            for pid, peer in self.peers.items()
            if topic in peer.topics and pid not in exclude
        ]

    def _select_peers(self, candidates: list[str], count: int) -> list[str]:
        if count <= 0:
            return []
        pool = list(candidates)
        self._rng.shuffle(pool)
        return pool[:count]

    async def _send(self, peer_id: str, rpc: RPC) -> None:
        peer = self.peers.get(peer_id)
        if peer is None or rpc.is_empty():
            return
        try:
            await peer.send(encode_rpc(rpc))
        except Exception:  # dead pipe → drop peer
            self.remove_peer(peer_id)

    async def _broadcast(self, rpc: RPC) -> None:
        for pid in list(self.peers):
            await self._send(pid, rpc)


class GossipsubService:
    """Binds a Gossipsub router to the secure Transport: one outbound
    gossip stream per connection for sending, inbound stream frames fed to
    the router (mirrors libp2p's per-direction streams)."""

    def __init__(self, transport, router: Gossipsub | None = None):
        self.transport = transport
        self.router = router or Gossipsub()
        transport.set_stream_handler(GOSSIPSUB_PROTOCOL, self._on_stream)
        transport.on_connection.append(self._on_connection)

    def _on_connection(self, conn) -> None:
        asyncio.get_running_loop().create_task(self._attach(conn))

    async def _attach(self, conn) -> None:
        try:
            stream = await conn.open_stream(GOSSIPSUB_PROTOCOL)
        except Exception:
            return
        lock = asyncio.Lock()

        async def send(data: bytes) -> None:
            async with lock:
                await stream.write(len(data).to_bytes(4, "big") + data)

        self.router.add_peer(conn.peer_id, send, outbound=conn.initiator)

        # only drop the router peer if this conn is still the live one —
        # an _adopt-replaced conn closing must not evict its successor
        def on_close(c=conn):
            if self.transport.connections.get(c.peer_id) is None:
                self.router.remove_peer(c.peer_id)

        conn.on_close.append(on_close)
        # announce current subscriptions to the new peer
        subs = [(True, t) for t in self.router.subscriptions]
        if subs:
            await self.router._send(conn.peer_id, RPC(subscriptions=subs))

    async def _on_stream(self, stream) -> None:
        """Inbound gossip stream: length-prefixed RPC frames."""
        buf = b""
        while True:
            chunk = await stream.read()
            if chunk is None:
                return
            buf += chunk
            while len(buf) >= 4:
                n = int.from_bytes(buf[:4], "big")
                if n > 10 * 2**20:
                    await stream.reset()
                    return
                if len(buf) < 4 + n:
                    break
                frame, buf = buf[4 : 4 + n], buf[4 + n :]
                await self.router.on_rpc(stream.conn.peer_id, frame)
