"""Gossip handlers: topic → bounded validation queue → chain.

Reference: `network/gossip/handlers/index.ts:76+` (decode, validate,
act on ACCEPT, penalize on REJECT) and the per-topic-type queues of
`network/gossip/validation/queue.ts:10-22`:

    beacon_attestation            LIFO  maxLen 24,576  concurrency 64
    beacon_aggregate_and_proof    LIFO   maxLen 5,120  concurrency 16
    beacon_block                  FIFO   maxLen 1,024  concurrency 1
    (everything else)             FIFO   maxLen 4,096  concurrency 16

Queues keep slow validation (BLS, regen) from starving the router;
LIFO prefers fresh attestations under backlog, exactly like the
reference. The decoded-object cache avoids double-decode between the
router's validator callback and the post-accept side effects.
"""

from __future__ import annotations

from ...chain.validation import (
    GossipAction,
    validate_gossip_aggregate_and_proof,
    validate_gossip_attestation,
    validate_gossip_attester_slashing,
    validate_gossip_block,
    validate_gossip_proposer_slashing,
    validate_gossip_voluntary_exit,
)
from ...observability import spans as _spans
from ...utils.logger import get_logger
from ...utils.queue import JobItemQueue, QueueType
from .encoding import decode_message
from .gossipsub import ValidationResult
from .topic import GossipType, parse_topic

log = get_logger("gossip-handlers")

QUEUE_OPTS: dict[GossipType, tuple[QueueType, int, int]] = {
    GossipType.beacon_attestation: (QueueType.LIFO, 24_576, 64),
    GossipType.beacon_aggregate_and_proof: (QueueType.LIFO, 5_120, 16),
    GossipType.beacon_block: (QueueType.FIFO, 1_024, 1),
}
DEFAULT_QUEUE = (QueueType.FIFO, 4_096, 16)

_ACTION_TO_RESULT = {
    GossipAction.ACCEPT: ValidationResult.ACCEPT,
    GossipAction.IGNORE: ValidationResult.IGNORE,
    GossipAction.REJECT: ValidationResult.REJECT,
}


class GossipHandlers:
    """Owns the validation queues and the per-type handler logic."""

    def __init__(self, config, types, chain, verify_signatures: bool = True,
                 fleet_router=None):
        self.config = config
        self.types = types
        self.chain = chain
        self.verify_signatures = verify_signatures
        # subnet → host routing for fleet ingest (parallel/fleet.py);
        # None = single-host node, validate every subnet. Node wiring may
        # also bind this post-construction (node.attach_network).
        self.fleet_router = fleet_router
        self.queues: dict[GossipType, JobItemQueue] = {}
        for gtype in GossipType:
            qt, max_len, conc = QUEUE_OPTS.get(gtype, DEFAULT_QUEUE)
            self.queues[gtype] = JobItemQueue(
                self._process,
                max_length=max_len,
                max_concurrency=conc,
                queue_type=qt,
                name=f"gossip.{gtype.value}",
            )

    def register(self, router) -> None:
        """Install one validator per topic type on the gossipsub router
        (prefix-matched, so every fork digest and subnet is covered)."""
        async def validator(topic_str: str, wire: bytes) -> ValidationResult:
            try:
                topic = parse_topic(topic_str)
            except ValueError:
                return ValidationResult.REJECT
            queue = self.queues[topic.type]
            try:
                return await queue.push((topic, wire))
            except Exception:
                return ValidationResult.IGNORE  # queue full / closed

        # the router prefix-matches on "/eth2/" — one validator for all
        router.validators["/eth2/"] = validator

    # -- queue processor -----------------------------------------------------

    async def _process(self, item) -> ValidationResult:
        import asyncio

        topic, wire = item
        # one lifecycle trace per gossip message: wire decode → validation
        # ladder → (for blocks) bls verify → fork choice → import → head
        # update, all correlated under one trace-id (observability.spans)
        with _spans.tracer.trace(
            f"gossip/{topic.type.value}", kind=topic.type.value
        ):
            with _spans.tracer.span("gossip/decode", wire_bytes=len(wire)):
                try:
                    ssz = decode_message(wire)
                except ValueError:
                    return ValidationResult.REJECT
            from ...ssz import DeserializationError

            # run_in_executor does not copy contextvars: hand the worker
            # thread the live span explicitly so its spans stay correlated
            trace_ctx = _spans.tracer.context()
            try:
                # run validation + import in an executor thread: the handler
                # does BLS verification and may wait on the chain's import
                # lock (held by range sync), neither of which may stall the
                # event loop
                return await asyncio.get_running_loop().run_in_executor(
                    None, self._handle_traced, trace_ctx, topic, ssz
                )
            except DeserializationError:
                return ValidationResult.REJECT  # undecodable object = bad peer
            except Exception as e:  # noqa: BLE001 — a handler bug must not REJECT
                log.debug(f"handler error on {topic.type.value}: {e}")
                return ValidationResult.IGNORE

    def _handle_traced(self, trace_ctx, topic, ssz: bytes) -> ValidationResult:
        with _spans.tracer.attach(trace_ctx):
            return self._handle(topic, ssz)

    def _handle(self, topic, ssz: bytes) -> ValidationResult:
        chain, types = self.chain, self.types
        t = topic.type

        if t is GossipType.beacon_block:
            signed = types.SignedBeaconBlock.deserialize(ssz)
            slot = int(signed.message.slot)
            _spans.tracer.annotate(
                slot=slot, root=signed.message.hash_tree_root().hex()
            )
            _milestone(chain, "block_received", slot)
            with _spans.tracer.span("validation/block", slot=slot):
                result = validate_gossip_block(chain, types, signed)
            if result.action is GossipAction.ACCEPT:
                _milestone(chain, "validated", slot)
                chain.seen_block_proposers.add(
                    slot, int(signed.message.proposer_index)
                )
                try:
                    chain.process_block(
                        signed, verify_signatures=self.verify_signatures
                    )
                except Exception as e:
                    log.debug(f"gossip block import failed: {e}")
                    _persist_invalid_ssz(signed, "block", e)
                    return ValidationResult.REJECT
            return _ACTION_TO_RESULT[result.action]

        if t is GossipType.beacon_attestation:
            # subnet-sharded fleet ingest (ISSUE 20): when a FleetRouter
            # is bound, this host only validates (and BLS-verifies) the
            # attestation subnets it owns — foreign-slice traffic is
            # IGNOREd before the validation ladder, so the lane
            # dispatcher sees exactly this host's share of the fleet
            # load. IGNORE (not REJECT): the attestation is not invalid,
            # it is simply another host's work.
            router = self.fleet_router
            if router is not None and topic.subnet is not None:
                try:
                    foreign = not router.owns(int(topic.subnet))
                except Exception:  # noqa: BLE001 — routing must not drop valid work
                    foreign = False
                if foreign:
                    router.record_foreign(int(topic.subnet))
                    return ValidationResult.IGNORE
            att = types.Attestation.deserialize(ssz)
            with _spans.tracer.span(
                "validation/attestation", slot=int(att.data.slot)
            ):
                result = validate_gossip_attestation(
                    chain, types, att, topic.subnet
                )
            if result.action is GossipAction.ACCEPT:
                chain.on_gossip_attestation(att, result.data_root)
            return _ACTION_TO_RESULT[result.action]

        if t is GossipType.beacon_aggregate_and_proof:
            signed_agg = types.SignedAggregateAndProof.deserialize(ssz)
            with _spans.tracer.span(
                "validation/aggregate",
                slot=int(signed_agg.message.aggregate.data.slot),
            ):
                result = validate_gossip_aggregate_and_proof(
                    chain, types, signed_agg
                )
            if result.action is GossipAction.ACCEPT:
                chain.on_aggregated_attestation(
                    signed_agg.message.aggregate, result.data_root
                )
                monitor = getattr(chain, "validator_monitor", None)
                if monitor is not None:
                    monitor.on_aggregate_published(
                        int(signed_agg.message.aggregate.data.target.epoch),
                        int(signed_agg.message.aggregator_index),
                    )
            return _ACTION_TO_RESULT[result.action]

        if t is GossipType.voluntary_exit:
            signed_exit = types.SignedVoluntaryExit.deserialize(ssz)
            result = validate_gossip_voluntary_exit(chain, types, signed_exit)
            if result.action is GossipAction.ACCEPT:
                chain.op_pool.add_voluntary_exit(signed_exit)
            return _ACTION_TO_RESULT[result.action]

        if t is GossipType.proposer_slashing:
            slashing = types.ProposerSlashing.deserialize(ssz)
            result = validate_gossip_proposer_slashing(chain, types, slashing)
            if result.action is GossipAction.ACCEPT:
                chain.op_pool.add_proposer_slashing(slashing)
            return _ACTION_TO_RESULT[result.action]

        if t is GossipType.attester_slashing:
            slashing = types.AttesterSlashing.deserialize(ssz)
            result = validate_gossip_attester_slashing(chain, types, slashing)
            if result.action is GossipAction.ACCEPT:
                chain.op_pool.add_attester_slashing(slashing)
            return _ACTION_TO_RESULT[result.action]

        if t is GossipType.sync_committee:
            if not hasattr(types, "SyncCommitteeMessage"):
                return ValidationResult.IGNORE
            msg = types.SyncCommitteeMessage.deserialize(ssz)
            from ...chain.validation import validate_gossip_sync_committee

            result = validate_gossip_sync_committee(
                chain, types, msg, topic.subnet if topic.subnet is not None else 0
            )
            if result.action is GossipAction.ACCEPT:
                pool = getattr(chain, "sync_committee_pool", None)
                if pool is not None and topic.subnet is not None:
                    # a validator can hold several positions in one
                    # subcommittee (sampling with replacement): set all
                    # of its bits from this first-seen message
                    for pos in result.positions or [result.attesting_index or 0]:
                        pool.add(msg, topic.subnet, pos)
                monitor = getattr(chain, "validator_monitor", None)
                if monitor is not None:
                    spe = chain.preset.SLOTS_PER_EPOCH
                    monitor.on_sync_committee_message(
                        int(msg.slot) // spe, int(msg.validator_index)
                    )
            return _ACTION_TO_RESULT[result.action]

        if t is GossipType.sync_committee_contribution_and_proof:
            if not hasattr(types, "SignedContributionAndProof"):
                return ValidationResult.IGNORE
            signed = types.SignedContributionAndProof.deserialize(ssz)
            from ...chain.validation import (
                validate_gossip_sync_contribution_and_proof,
            )

            result = validate_gossip_sync_contribution_and_proof(
                chain, types, signed
            )
            if result.action is GossipAction.ACCEPT:
                pool = getattr(chain, "sync_contribution_pool", None)
                if pool is not None:
                    pool.add(signed.message.contribution)
            return _ACTION_TO_RESULT[result.action]

        # light-client updates: served, not consumed, by full nodes
        return ValidationResult.IGNORE


def _milestone(chain, name: str, slot: int) -> None:
    """Record a slot milestone via the chain (which owns the clock and the
    metrics bundle); tolerant of stub chains in tests."""
    rec = getattr(chain, "_record_milestone", None)
    if rec is not None:
        try:
            rec(name, slot)
        except Exception as e:
            # milestone telemetry must never fail the handler
            log.debug("milestone %s failed: %s", name, e)


def _persist_invalid_ssz(obj, kind: str, error: Exception) -> None:
    """Debugging dump of objects that failed import (reference
    `persistInvalidSszValue`, `chain/blocks/index.ts:117-135`): enabled by
    LODESTAR_TPU_PERSIST_INVALID=<dir>; filenames carry kind + root."""
    import os

    from ...utils.env import env_str

    target = env_str("LODESTAR_TPU_PERSIST_INVALID")
    if not target:
        return
    try:
        os.makedirs(target, exist_ok=True)
        root = obj.message.hash_tree_root().hex()[:16] if hasattr(obj, "message") else "obj"
        path = os.path.join(target, f"invalid_{kind}_{root}.ssz")
        with open(path, "wb") as f:
            f.write(obj.serialize())
        with open(path + ".log", "w") as f:
            f.write(f"{type(error).__name__}: {error}\n")
        log.warning("persisted invalid %s to %s", kind, path)
    except Exception as e:
        log.debug("failed to persist invalid %s: %s", kind, e)  # diagnostics only
