"""Gossip message encoding + message-id functions.

Reference: `network/gossip/encoding.ts` — payloads are snappy-compressed
SSZ (`DataTransformSnappy`); `fastMsgIdFn` = xxhash64 of the raw wire data
(cheap de-dup key, :12); `msgIdFn` = SHA256(domain + topic-len + topic +
uncompressed)[:20] per the altair p2p spec (:21-50), with the
MESSAGE_DOMAIN_VALID/INVALID_SNAPPY split for undecodable payloads.
Codecs are the native tier (`lodestar_tpu.native`).
"""

from __future__ import annotations

from ... import native

MESSAGE_DOMAIN_INVALID_SNAPPY = b"\x00\x00\x00\x00"
MESSAGE_DOMAIN_VALID_SNAPPY = b"\x01\x00\x00\x00"
GOSSIP_MSGID_LENGTH = 20
MAX_GOSSIP_SIZE = 10 * 2**20


def encode_message(ssz_bytes: bytes) -> bytes:
    return native.snappy_compress(ssz_bytes)


def decode_message(wire: bytes) -> bytes:
    if len(wire) > MAX_GOSSIP_SIZE:
        raise ValueError("gossip message too large")
    return native.snappy_uncompress(wire)


def fast_msg_id(wire: bytes) -> int:
    """Cheap pre-filter id for the seen-cache (xxhash64 of compressed
    data)."""
    return native.xxh64(wire)


def compute_msg_id(topic: str, wire: bytes) -> bytes:
    """Canonical gossip message-id (altair p2p spec): sha256 over domain +
    uint64-le topic length + topic + (un)compressed payload, first 20B."""
    topic_bytes = topic.encode()
    prefix = len(topic_bytes).to_bytes(8, "little")
    try:
        payload = native.snappy_uncompress(wire)
        domain = MESSAGE_DOMAIN_VALID_SNAPPY
    except ValueError:
        payload = wire
        domain = MESSAGE_DOMAIN_INVALID_SNAPPY
    return native.sha256(domain + prefix + topic_bytes + payload)[:GOSSIP_MSGID_LENGTH]
