"""Gossip layer: topics, message-id encoding, validation queues."""

from .topic import GossipType, GossipTopic, stringify_topic, parse_topic  # noqa: F401
from .encoding import compute_msg_id, fast_msg_id, encode_message, decode_message  # noqa: F401
