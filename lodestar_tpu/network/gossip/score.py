"""Gossipsub v1.1 peer scoring with Ethereum-shaped parameters.

Reference: `network/gossip/scoringParameters.ts` (315 LoC) computes
per-topic score params from the chain spec; thresholds come from the
consensus p2p scoring note (gossip -4000 / publish -8000 / graylist
-16000). The score function follows the gossipsub v1.1 spec:

    score(p) = Σ_topic w_t · (P1·w1 + P2·w2 + P3·w3 + P3b·w3b + P4·w4)
               + P5·w5 + P6·w6 + P7·w7

P1 time-in-mesh, P2 first-message-deliveries, P3 mesh-delivery deficit,
P3b mesh-failure penalty, P4 invalid messages, P5 application score,
P6 IP colocation, P7 behaviour penalty. Decay is applied per
decay-interval tick by the heartbeat.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


# Thresholds (reference scoringParameters.ts gossipScoreThresholds)
GOSSIP_THRESHOLD = -4000.0
PUBLISH_THRESHOLD = -8000.0
GRAYLIST_THRESHOLD = -16000.0
ACCEPT_PX_THRESHOLD = 100.0
OPPORTUNISTIC_GRAFT_THRESHOLD = 5.0

MAX_POSITIVE_SCORE = 3600.0  # maxPositiveScore in the reference derivation
DECAY_INTERVAL = 12.0  # one slot
DECAY_TO_ZERO = 0.01


def _score_decay(decay_time_sec: float) -> float:
    """Per-interval decay factor so a counter reaches DECAY_TO_ZERO after
    `decay_time_sec` (reference scoreParameterDecay)."""
    ticks = max(decay_time_sec / DECAY_INTERVAL, 1.0)
    return DECAY_TO_ZERO ** (1.0 / ticks)


@dataclass
class TopicScoreParams:
    topic_weight: float = 0.5
    time_in_mesh_weight: float = 0.0324
    time_in_mesh_quantum: float = 12.0  # seconds per quantum (one slot)
    time_in_mesh_cap: float = 300.0
    first_message_deliveries_weight: float = 1.0
    first_message_deliveries_decay: float = 0.5
    first_message_deliveries_cap: float = 100.0
    mesh_message_deliveries_weight: float = 0.0  # ≤0; 0 disables P3
    mesh_message_deliveries_decay: float = 0.5
    mesh_message_deliveries_threshold: float = 10.0
    mesh_message_deliveries_cap: float = 100.0
    mesh_message_deliveries_activation: float = 60.0  # seconds in mesh
    mesh_failure_penalty_weight: float = 0.0  # ≤0
    mesh_failure_penalty_decay: float = 0.5
    invalid_message_deliveries_weight: float = -100.0
    invalid_message_deliveries_decay: float = _score_decay(50 * 12)


def ethereum_topic_params(topic_kind: str, slots_per_epoch: int = 32) -> TopicScoreParams:
    """Per-topic params shaped like the reference derivation: one expected
    block per slot, ~an aggregate per slot per peer, lighter subnets."""
    slot = 12.0
    epoch = slot * slots_per_epoch
    if topic_kind == "beacon_block":
        return TopicScoreParams(
            topic_weight=0.5,
            time_in_mesh_quantum=slot,
            first_message_deliveries_weight=1.14,
            first_message_deliveries_decay=_score_decay(20 * epoch),
            first_message_deliveries_cap=34.86,
            invalid_message_deliveries_weight=-214.99,
            invalid_message_deliveries_decay=_score_decay(50 * epoch),
        )
    if topic_kind == "beacon_aggregate_and_proof":
        return TopicScoreParams(
            topic_weight=0.5,
            time_in_mesh_quantum=slot,
            first_message_deliveries_weight=0.128,
            first_message_deliveries_decay=_score_decay(1 * epoch),
            first_message_deliveries_cap=179.3,
            invalid_message_deliveries_weight=-214.99,
            invalid_message_deliveries_decay=_score_decay(50 * epoch),
        )
    # attestation subnets & everything else: light weight, same invalid cost
    return TopicScoreParams(
        topic_weight=0.015,
        time_in_mesh_quantum=slot,
        first_message_deliveries_weight=0.956,
        first_message_deliveries_decay=_score_decay(1 * epoch),
        first_message_deliveries_cap=24.0,
        invalid_message_deliveries_weight=-4544.0,
        invalid_message_deliveries_decay=_score_decay(50 * epoch),
    )


@dataclass
class PeerScoreParams:
    topics: dict[str, TopicScoreParams] = field(default_factory=dict)
    topic_score_cap: float = MAX_POSITIVE_SCORE / 2
    app_specific_weight: float = 1.0
    ip_colocation_factor_weight: float = -35.11
    ip_colocation_factor_threshold: int = 3
    behaviour_penalty_weight: float = -15.92
    behaviour_penalty_threshold: float = 6.0
    behaviour_penalty_decay: float = _score_decay(10 * 12 * 32)
    retain_score_sec: float = 100 * 12 * 32


@dataclass
class _TopicStats:
    in_mesh: bool = False
    graft_time: float = 0.0
    mesh_time: float = 0.0
    first_message_deliveries: float = 0.0
    mesh_message_deliveries: float = 0.0
    mesh_message_deliveries_active: bool = False
    mesh_failure_penalty: float = 0.0
    invalid_message_deliveries: float = 0.0


@dataclass
class _PeerStats:
    topics: dict[str, _TopicStats] = field(default_factory=dict)
    app_score: float = 0.0
    behaviour_penalty: float = 0.0
    ip: str | None = None
    connected: bool = True
    disconnected_at: float = 0.0


class PeerScore:
    """Tracks and computes gossipsub scores for all known peers."""

    def __init__(self, params: PeerScoreParams | None = None, time_fn=time.monotonic):
        self.params = params or PeerScoreParams()
        self.peers: dict[str, _PeerStats] = {}
        self._time = time_fn

    # -- events reported by the router ---------------------------------------

    def _peer(self, peer_id: str) -> _PeerStats:
        return self.peers.setdefault(peer_id, _PeerStats())

    def _topic(self, peer_id: str, topic: str) -> _TopicStats:
        return self._peer(peer_id).topics.setdefault(topic, _TopicStats())

    def add_peer(self, peer_id: str, ip: str | None = None) -> None:
        stats = self._peer(peer_id)
        stats.connected = True
        stats.ip = ip

    def remove_peer(self, peer_id: str) -> None:
        stats = self.peers.get(peer_id)
        if stats is None:
            return
        # retain negative scores for retain_score_sec (spec: no whitewashing
        # by reconnecting); positive scores reset
        if self.score(peer_id) > 0:
            self.peers.pop(peer_id, None)
            return
        stats.connected = False
        stats.disconnected_at = self._time()
        for t in stats.topics.values():
            t.in_mesh = False

    def graft(self, peer_id: str, topic: str) -> None:
        t = self._topic(peer_id, topic)
        t.in_mesh = True
        t.graft_time = self._time()
        t.mesh_time = 0.0
        t.mesh_message_deliveries_active = False

    def prune(self, peer_id: str, topic: str) -> None:
        t = self._topic(peer_id, topic)
        tp = self.params.topics.get(topic)
        # mesh failure penalty: deficit square at prune time (spec P3b)
        if tp is not None and tp.mesh_failure_penalty_weight < 0 and t.mesh_message_deliveries_active:
            deficit = max(
                0.0, tp.mesh_message_deliveries_threshold - t.mesh_message_deliveries
            )
            t.mesh_failure_penalty += deficit * deficit
        t.in_mesh = False

    def deliver_message(self, peer_id: str, topic: str, first: bool) -> None:
        t = self._topic(peer_id, topic)
        tp = self.params.topics.get(topic, TopicScoreParams())
        if first:
            t.first_message_deliveries = min(
                tp.first_message_deliveries_cap, t.first_message_deliveries + 1
            )
        if t.in_mesh:
            t.mesh_message_deliveries = min(
                tp.mesh_message_deliveries_cap, t.mesh_message_deliveries + 1
            )

    def reject_message(self, peer_id: str, topic: str) -> None:
        self._topic(peer_id, topic).invalid_message_deliveries += 1

    def add_behaviour_penalty(self, peer_id: str, count: float = 1.0) -> None:
        self._peer(peer_id).behaviour_penalty += count

    def set_app_score(self, peer_id: str, score: float) -> None:
        self._peer(peer_id).app_score = score

    # -- scoring -------------------------------------------------------------

    def score(self, peer_id: str) -> float:
        stats = self.peers.get(peer_id)
        if stats is None:
            return 0.0
        now = self._time()
        p = self.params
        topic_sum = 0.0
        for topic, t in stats.topics.items():
            tp = p.topics.get(topic)
            if tp is None:
                continue
            s = 0.0
            if t.in_mesh:
                quanta = min(
                    (now - t.graft_time) / tp.time_in_mesh_quantum, tp.time_in_mesh_cap
                )
                s += quanta * tp.time_in_mesh_weight
            s += t.first_message_deliveries * tp.first_message_deliveries_weight
            if (
                tp.mesh_message_deliveries_weight < 0
                and t.in_mesh
                and now - t.graft_time > tp.mesh_message_deliveries_activation
                and t.mesh_message_deliveries < tp.mesh_message_deliveries_threshold
            ):
                deficit = tp.mesh_message_deliveries_threshold - t.mesh_message_deliveries
                s += deficit * deficit * tp.mesh_message_deliveries_weight
            s += t.mesh_failure_penalty * tp.mesh_failure_penalty_weight
            s += (
                t.invalid_message_deliveries
                * t.invalid_message_deliveries
                * tp.invalid_message_deliveries_weight
            )
            topic_sum += tp.topic_weight * s
        if topic_sum > 0:
            topic_sum = min(topic_sum, p.topic_score_cap)
        total = topic_sum
        total += stats.app_score * p.app_specific_weight
        # IP colocation: penalize peers sharing an IP beyond the threshold
        if stats.ip is not None and p.ip_colocation_factor_weight < 0:
            same_ip = sum(
                1
                for s2 in self.peers.values()
                if s2.connected and s2.ip == stats.ip
            )
            excess = same_ip - p.ip_colocation_factor_threshold
            if excess > 0:
                total += excess * excess * p.ip_colocation_factor_weight
        if stats.behaviour_penalty > p.behaviour_penalty_threshold:
            excess = stats.behaviour_penalty - p.behaviour_penalty_threshold
            total += excess * excess * p.behaviour_penalty_weight
        return total

    def decay(self) -> None:
        """One decay-interval tick (heartbeat calls this every DECAY_INTERVAL)."""
        now = self._time()
        p = self.params
        for peer_id in list(self.peers):
            stats = self.peers[peer_id]
            if (
                not stats.connected
                and now - stats.disconnected_at > p.retain_score_sec
            ):
                del self.peers[peer_id]
                continue
            for topic, t in stats.topics.items():
                tp = p.topics.get(topic, TopicScoreParams())
                t.first_message_deliveries *= tp.first_message_deliveries_decay
                t.mesh_message_deliveries *= tp.mesh_message_deliveries_decay
                t.mesh_failure_penalty *= tp.mesh_failure_penalty_decay
                t.invalid_message_deliveries *= tp.invalid_message_deliveries_decay
                if t.in_mesh:
                    t.mesh_time = now - t.graft_time
                    if t.mesh_time > tp.mesh_message_deliveries_activation:
                        t.mesh_message_deliveries_active = True
            stats.behaviour_penalty *= p.behaviour_penalty_decay
