"""Gossip topic naming: `/eth2/<forkDigest>/<name>/ssz_snappy`.

Reference: `network/gossip/topic.ts` + `interface.ts:14-27` (the 10 gossip
types). Subnet topics carry their index in the name.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum


class GossipType(str, Enum):
    beacon_block = "beacon_block"
    beacon_aggregate_and_proof = "beacon_aggregate_and_proof"
    beacon_attestation = "beacon_attestation"
    voluntary_exit = "voluntary_exit"
    proposer_slashing = "proposer_slashing"
    attester_slashing = "attester_slashing"
    sync_committee_contribution_and_proof = "sync_committee_contribution_and_proof"
    sync_committee = "sync_committee"
    light_client_finality_update = "light_client_finality_update"
    light_client_optimistic_update = "light_client_optimistic_update"


SUBNET_TYPES = {GossipType.beacon_attestation, GossipType.sync_committee}


@dataclass(frozen=True)
class GossipTopic:
    type: GossipType
    fork_digest: bytes
    subnet: int | None = None


def stringify_topic(topic: GossipTopic) -> str:
    name = topic.type.value
    if topic.type in SUBNET_TYPES:
        if topic.subnet is None:
            raise ValueError(f"{name} topic requires a subnet index")
        name = f"{name}_{topic.subnet}"
    return f"/eth2/{topic.fork_digest.hex()}/{name}/ssz_snappy"


def parse_topic(s: str) -> GossipTopic:
    parts = s.split("/")
    if len(parts) != 5 or parts[1] != "eth2" or parts[4] != "ssz_snappy":
        raise ValueError(f"malformed gossip topic: {s}")
    fork_digest = bytes.fromhex(parts[2])
    name = parts[3]
    # exact names FIRST: "sync_committee_contribution_and_proof" starts
    # with the "sync_committee_" subnet prefix and must not be parsed as
    # a subnet topic (round-2 regression found driving the wire path)
    try:
        return GossipTopic(GossipType(name), fork_digest, None)
    except ValueError:
        pass
    for t in SUBNET_TYPES:
        prefix = t.value + "_"
        if name.startswith(prefix):
            return GossipTopic(t, fork_digest, int(name[len(prefix):]))
    raise ValueError(f"unknown gossip topic name: {name}")
