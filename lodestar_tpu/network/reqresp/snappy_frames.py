"""Snappy *framing* format (streaming), used by req/resp payloads.

Reference: `reqresp/encodingStrategies/sszSnappy/` — the p2p spec requires
the framing format (not the block format gossip uses): a stream identifier
frame, then compressed/uncompressed data frames each carrying a masked
CRC32C of the uncompressed content. Inner compression reuses the native
block codec.
"""

from __future__ import annotations

from ... import native

STREAM_IDENTIFIER = b"\xff\x06\x00\x00sNaPpY"
CHUNK_COMPRESSED = 0x00
CHUNK_UNCOMPRESSED = 0x01
MAX_UNCOMPRESSED_CHUNK = 65536

# CRC32C (Castagnoli) table
_CRC_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC_TABLE.append(_c)


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def _masked_checksum(data: bytes) -> int:
    c = crc32c(data)
    return (((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def compress_frames(data: bytes) -> bytes:
    """data → stream identifier + one frame per 64 KiB chunk."""
    out = bytearray(STREAM_IDENTIFIER)
    for i in range(0, max(len(data), 1), MAX_UNCOMPRESSED_CHUNK):
        chunk = data[i : i + MAX_UNCOMPRESSED_CHUNK]
        checksum = _masked_checksum(chunk)
        compressed = native.snappy_compress(chunk)
        if len(compressed) < len(chunk):
            body = checksum.to_bytes(4, "little") + compressed
            kind = CHUNK_COMPRESSED
        else:
            body = checksum.to_bytes(4, "little") + chunk
            kind = CHUNK_UNCOMPRESSED
        out.append(kind)
        out += len(body).to_bytes(3, "little")
        out += body
        if not data:
            break
    return bytes(out)


def decompress_frames(stream: bytes) -> bytes:
    """Frames → payload, verifying checksums; raises ValueError on corrupt
    input."""
    if not stream.startswith(STREAM_IDENTIFIER):
        raise ValueError("missing snappy stream identifier")
    i = len(STREAM_IDENTIFIER)
    out = bytearray()
    while i < len(stream):
        if i + 4 > len(stream):
            raise ValueError("truncated frame header")
        kind = stream[i]
        length = int.from_bytes(stream[i + 1 : i + 4], "little")
        i += 4
        if i + length > len(stream):
            raise ValueError("truncated frame body")
        body = stream[i : i + length]
        i += length
        if kind == 0xFF:  # repeated stream identifier
            continue
        if kind in (CHUNK_COMPRESSED, CHUNK_UNCOMPRESSED):
            if length < 4:
                raise ValueError("frame too short for checksum")
            checksum = int.from_bytes(body[:4], "little")
            payload = body[4:]
            if kind == CHUNK_COMPRESSED:
                payload = native.snappy_uncompress(payload)
            if _masked_checksum(payload) != checksum:
                raise ValueError("frame checksum mismatch")
            out += payload
        elif kind >= 0x80:  # reserved skippable
            continue
        else:
            raise ValueError(f"unknown frame type {kind:#x}")
    return bytes(out)
