"""Server-side req/resp handlers against the chain/db.

Reference: `network/reqresp/handlers/` — status from chain state,
beaconBlocksByRange streaming from hot + archived blocks, byRoot lookups,
ping/metadata from the local metadata object.
"""

from __future__ import annotations

from .codec import RespCode, encode_error_chunk, encode_response_chunk

MAX_REQUEST_BLOCKS = 1024


class ReqRespHandlers:
    def __init__(self, config, types, chain, metadata=None):
        self.config = config
        self.types = types
        self.chain = chain
        self.metadata = metadata if metadata is not None else types.Metadata()
        self.seq_number = 0

    # -- payload producers (SSZ objects in, SSZ objects out) -----------------

    def local_status(self):
        chain = self.chain
        fin_epoch, fin_root = chain.finalized_checkpoint
        genesis_root = b"\x00" * 32
        return self.types.Status(
            fork_digest=self.config.fork_digest(
                self.config.get_fork_name_at_slot(chain.head_state.state.slot)
            ),
            finalized_root=fin_root if fin_epoch > 0 else genesis_root,
            finalized_epoch=fin_epoch,
            head_root=chain.head_root,
            head_slot=chain.head_state.state.slot,
        )

    def on_status(self, request) -> bytes:
        return encode_response_chunk(self.local_status().serialize())

    def on_ping(self, request) -> bytes:
        from ...ssz import uint64

        return encode_response_chunk(uint64.serialize(self.seq_number))

    def on_metadata(self, request) -> bytes:
        return encode_response_chunk(self.metadata.serialize())

    def on_goodbye(self, request) -> bytes:
        from ...ssz import uint64

        return encode_response_chunk(uint64.serialize(0))

    def on_beacon_blocks_by_range(self, start_slot: int, count: int) -> bytes:
        """Stream canonical blocks in [start_slot, start_slot+count) —
        archived (finalized) first, then hot chain blocks."""
        if count < 1 or count > MAX_REQUEST_BLOCKS:
            return encode_error_chunk(RespCode.INVALID_REQUEST, "bad count")
        chain = self.chain
        out = bytearray()
        end_slot = start_slot + count
        # archived range (slot-ordered repository scan)
        for key in chain.db.block_archive.keys_stream():
            slot = int.from_bytes(key, "big")
            if start_slot <= slot < end_slot:
                raw = chain.db.block_archive.get_binary(key)
                out += encode_response_chunk(raw)
        # hot canonical chain via fork choice ancestry from head
        hot = []
        for node in chain.fork_choice.proto.iter_ancestors(chain.head_root):
            if start_slot <= node.slot < end_slot:
                signed = chain.blocks.get(node.root)
                if signed is not None:
                    hot.append(signed)
        for signed in reversed(hot):  # ascending slot order
            out += encode_response_chunk(signed.serialize())
        return bytes(out)

    # -- light client server protocols (reference reqresp/types.ts:55-67) ---

    def _lc_server(self):
        return getattr(self.chain, "light_client_server", None)

    def on_light_client_bootstrap(self, block_root: bytes) -> bytes:
        lc = self._lc_server()
        bootstrap = lc.get_bootstrap(block_root) if lc is not None else None
        if bootstrap is None:
            return encode_error_chunk(RespCode.RESOURCE_UNAVAILABLE, "no bootstrap")
        return encode_response_chunk(bootstrap.serialize())

    def on_light_client_updates_by_range(self, start_period: int, count: int) -> bytes:
        lc = self._lc_server()
        if lc is None or count < 1 or count > 128:
            return encode_error_chunk(RespCode.INVALID_REQUEST, "bad range")
        out = bytearray()
        for update in lc.get_updates(start_period, count):
            out += encode_response_chunk(update.serialize())
        return bytes(out)

    def on_light_client_finality_update(self) -> bytes:
        lc = self._lc_server()
        update = getattr(lc, "latest_finality_update", None) if lc is not None else None
        if update is None:
            return encode_error_chunk(RespCode.RESOURCE_UNAVAILABLE, "none yet")
        return encode_response_chunk(update.serialize())

    def on_light_client_optimistic_update(self) -> bytes:
        lc = self._lc_server()
        update = getattr(lc, "latest_optimistic_update", None) if lc is not None else None
        if update is None:
            return encode_error_chunk(RespCode.RESOURCE_UNAVAILABLE, "none yet")
        return encode_response_chunk(update.serialize())

    def on_beacon_blocks_by_root(self, roots: list[bytes]) -> bytes:
        if len(roots) > MAX_REQUEST_BLOCKS:
            return encode_error_chunk(RespCode.INVALID_REQUEST, "too many roots")
        chain = self.chain
        out = bytearray()
        for root in roots:
            signed = chain.blocks.get(root) or chain.finalized_blocks.get(root)
            if signed is None:
                signed = chain.db.get_archived_block_by_root(root)
            if signed is not None:
                out += encode_response_chunk(signed.serialize())
        return bytes(out)
