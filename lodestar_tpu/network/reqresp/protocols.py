"""Req/Resp protocol registry.

Reference: `network/reqresp/types.ts:7-67` — Status, Goodbye, Ping,
Metadata, BeaconBlocksByRange/Root (V1+V2), LightClient*. Protocol ids:
/eth2/beacon_chain/req/<name>/<version>/ssz_snappy.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum


class Protocol(str, Enum):
    Status = "status"
    Goodbye = "goodbye"
    Ping = "ping"
    Metadata = "metadata"
    BeaconBlocksByRange = "beacon_blocks_by_range"
    BeaconBlocksByRoot = "beacon_blocks_by_root"
    LightClientBootstrap = "light_client_bootstrap"
    LightClientUpdatesByRange = "light_client_updates_by_range"
    LightClientFinalityUpdate = "light_client_finality_update"
    LightClientOptimisticUpdate = "light_client_optimistic_update"


@dataclass(frozen=True)
class ProtocolSpec:
    protocol: Protocol
    version: int
    has_request: bool
    multiple_responses: bool


PROTOCOLS: list[ProtocolSpec] = [
    ProtocolSpec(Protocol.Status, 1, True, False),
    ProtocolSpec(Protocol.Goodbye, 1, True, False),
    ProtocolSpec(Protocol.Ping, 1, True, False),
    ProtocolSpec(Protocol.Metadata, 2, False, False),
    ProtocolSpec(Protocol.BeaconBlocksByRange, 2, True, True),
    ProtocolSpec(Protocol.BeaconBlocksByRoot, 2, True, True),
    ProtocolSpec(Protocol.LightClientBootstrap, 1, True, False),
    ProtocolSpec(Protocol.LightClientUpdatesByRange, 1, True, True),
    ProtocolSpec(Protocol.LightClientFinalityUpdate, 1, False, False),
    ProtocolSpec(Protocol.LightClientOptimisticUpdate, 1, False, False),
]


def protocol_id(protocol: Protocol, version: int = 1) -> str:
    return f"/eth2/beacon_chain/req/{protocol.value}/{version}/ssz_snappy"


def parse_protocol_id(pid: str) -> tuple[Protocol, int]:
    parts = pid.split("/")
    if (
        len(parts) != 7
        or parts[1] != "eth2"
        or parts[2] != "beacon_chain"
        or parts[3] != "req"
        or parts[6] != "ssz_snappy"
    ):
        raise ValueError(f"malformed protocol id: {pid}")
    return Protocol(parts[4]), int(parts[5])
