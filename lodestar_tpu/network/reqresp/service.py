"""Req/Resp over live transport streams.

Reference: `network/reqresp/reqResp.ts` — per-protocol dial/respond over
libp2p streams with TTFB/RESP timeouts, response-time peer scoring and a
served-request rate tracker (`reqresp/rateTracker.ts`,
`reqresp/score.ts`). This module binds the transport (stream layer), the
wire codec (`codec.py`), and the server handlers (`handlers.py`).

The client surface is async; `RemotePeer` adapts it to the synchronous
`IPeer` protocol the sync layer consumes (via the owning loop), keeping
sync logic transport-agnostic.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field

from ...utils.logger import get_logger
from ..peers import PeerAction
from .codec import (
    RespCode,
    decode_request,
    decode_response_chunks,
    encode_error_chunk,
    encode_request,
)
from .protocols import Protocol, parse_protocol_id, protocol_id

TTFB_TIMEOUT = 5.0  # time-to-first-byte (reference constants.ts)
RESP_TIMEOUT = 10.0
REQUEST_TIMEOUT = 5.0

log = get_logger("reqresp")


class RequestError(Exception):
    def __init__(self, code: str, message: str = ""):
        super().__init__(f"{code}: {message}")
        self.code = code


@dataclass
class RateTracker:
    """Sliding-window served-objects quota (reference rateTracker.ts)."""

    limit: int = 500
    window_sec: float = 60.0
    _events: list[tuple[float, int]] = field(default_factory=list)

    def request_objects(self, count: int, now: float | None = None) -> int:
        """Returns objects granted (0 when over quota)."""
        now = time.monotonic() if now is None else now
        cutoff = now - self.window_sec
        self._events = [(t, c) for t, c in self._events if t > cutoff]
        used = sum(c for _, c in self._events)
        if used + count > self.limit:
            return 0
        self._events.append((now, count))
        return count


class ReqRespService:
    """Server dispatch + typed async client calls for every protocol."""

    def __init__(self, transport, handlers, types, peer_manager=None, metrics=None):
        self.transport = transport
        self.handlers = handlers
        self.types = types
        self.peer_manager = peer_manager
        self.metrics = metrics
        self.block_rate = RateTracker(limit=2000)
        self.request_rate = RateTracker(limit=50, window_sec=10.0)
        transport.set_prefix_handler("/eth2/beacon_chain/req/", self._on_stream)

    # ------------------------------------------------------------------ server

    async def _on_stream(self, stream) -> None:
        try:
            proto, _version = parse_protocol_id(stream.protocol)
        except ValueError:
            await stream.reset()
            return
        peer_id = stream.conn.peer_id
        self._hook("incoming_request", proto.value)
        if self.request_rate.request_objects(1) == 0:
            self._hook("rate_limited", "requests")
            await stream.write(encode_error_chunk(RespCode.RESOURCE_UNAVAILABLE, "rate limit"))
            await stream.close()
            self._penalize(peer_id, PeerAction.MidToleranceError)
            return
        try:
            wire_req = await asyncio.wait_for(
                self._read_request_capped(stream), REQUEST_TIMEOUT
            )
            response = self._respond(proto, wire_req)
        except Exception as e:  # malformed request
            log.debug(f"reqresp {proto.value} from {peer_id[:8]} failed: {e}")
            response = encode_error_chunk(RespCode.INVALID_REQUEST, str(e)[:64])
            self._penalize(peer_id, PeerAction.LowToleranceError)
            self._hook("incoming_error", proto.value)
        self._hook("bytes_sent", proto.value, len(response))
        try:
            await stream.write(response)
            await stream.close()
        except Exception as e:
            # peer hung up mid-response; scoring already recorded the event
            log.debug("response write to %s failed: %s", peer_id, e)

    # requests are tiny (Status=84B SSZ, ByRoot ≤ 32KiB of roots); anything
    # bigger is hostile — cap buffering so a frame-pumping peer can't balloon
    # server memory inside the request timeout
    MAX_REQUEST_WIRE = 256 * 1024

    async def _read_request_capped(self, stream) -> bytes:
        chunks: list[bytes] = []
        total = 0
        while True:
            chunk = await stream.read()
            if chunk is None:
                return b"".join(chunks)
            total += len(chunk)
            if total > self.MAX_REQUEST_WIRE:
                raise ValueError("request exceeds size cap")
            chunks.append(chunk)

    def _respond(self, proto: Protocol, wire_req: bytes) -> bytes:
        h = self.handlers
        if proto is Protocol.Status:
            return h.on_status(self.types.Status.deserialize(decode_request(wire_req)))
        if proto is Protocol.Goodbye:
            return h.on_goodbye(int.from_bytes(decode_request(wire_req)[:8], "little"))
        if proto is Protocol.Ping:
            return h.on_ping(int.from_bytes(decode_request(wire_req)[:8], "little"))
        if proto is Protocol.Metadata:
            return h.on_metadata(None)
        if proto is Protocol.BeaconBlocksByRange:
            raw = decode_request(wire_req)
            start_slot = int.from_bytes(raw[0:8], "little")
            count = int.from_bytes(raw[8:16], "little")
            granted = self.block_rate.request_objects(min(count, 1024))
            if granted == 0:
                self._hook("rate_limited", "blocks")
                return encode_error_chunk(RespCode.RESOURCE_UNAVAILABLE, "rate limit")
            return h.on_beacon_blocks_by_range(start_slot, count)
        if proto is Protocol.BeaconBlocksByRoot:
            raw = decode_request(wire_req)
            roots = [raw[i : i + 32] for i in range(0, len(raw), 32)]
            granted = self.block_rate.request_objects(max(1, len(roots)))
            if granted == 0:
                self._hook("rate_limited", "blocks")
                return encode_error_chunk(RespCode.RESOURCE_UNAVAILABLE, "rate limit")
            return h.on_beacon_blocks_by_root(roots)
        if proto is Protocol.LightClientBootstrap:
            return h.on_light_client_bootstrap(decode_request(wire_req))
        if proto is Protocol.LightClientUpdatesByRange:
            raw = decode_request(wire_req)
            start = int.from_bytes(raw[0:8], "little")
            count = int.from_bytes(raw[8:16], "little")
            return h.on_light_client_updates_by_range(start, count)
        if proto is Protocol.LightClientFinalityUpdate:
            return h.on_light_client_finality_update()
        if proto is Protocol.LightClientOptimisticUpdate:
            return h.on_light_client_optimistic_update()
        return encode_error_chunk(RespCode.SERVER_ERROR, "unhandled protocol")

    def _penalize(self, peer_id: str, action: PeerAction) -> None:
        if self.peer_manager is not None:
            self.peer_manager.report_peer(peer_id, action)

    def _hook(self, name: str, *args) -> None:
        fn = getattr(self.metrics, name, None)
        if fn is not None:
            fn(*args)

    # ------------------------------------------------------------------ client

    async def _request_raw(
        self, peer_id: str, proto: Protocol, version: int, req_ssz: bytes | None
    ) -> list[tuple[RespCode, bytes]]:
        conn = self.transport.connections.get(peer_id)
        if conn is None:
            raise RequestError("DIAL_ERROR", f"no connection to {peer_id[:8]}")
        self._hook("outgoing_request", proto.value)
        t0 = time.monotonic()
        stream = await conn.open_stream(protocol_id(proto, version))
        try:
            if req_ssz is not None:
                await stream.write(encode_request(req_ssz))
            await stream.close()
            first = await stream.read(timeout=TTFB_TIMEOUT)
            if first is None:
                raise RequestError("EMPTY_RESPONSE")
            rest = await asyncio.wait_for(stream.read_all(), RESP_TIMEOUT)
        except (TimeoutError, asyncio.TimeoutError):
            # asyncio.TimeoutError is a distinct class until 3.11
            self._penalize(peer_id, PeerAction.HighToleranceError)
            self._hook("outgoing_error", proto.value)
            raise RequestError("RESP_TIMEOUT", proto.value) from None
        finally:
            await stream.reset()
        observe = getattr(self.metrics, "observe_reqresp", None)
        if observe is not None:
            observe(proto.value, time.monotonic() - t0)
        self._hook("bytes_received", proto.value, len(first) + len(rest))
        chunks = decode_response_chunks(first + rest)
        for code, payload in chunks:
            self._hook("response_chunk", code.name)
            if code != RespCode.SUCCESS:
                self._hook("outgoing_error", proto.value)
                raise RequestError(code.name, payload[:64].decode(errors="replace"))
        return chunks

    async def status(self, peer_id: str, local_status=None):
        local = local_status or self.handlers.local_status()
        chunks = await self._request_raw(peer_id, Protocol.Status, 1, local.serialize())
        return self.types.Status.deserialize(chunks[0][1])

    async def goodbye(self, peer_id: str, reason: int = 0) -> None:
        try:
            await self._request_raw(
                peer_id, Protocol.Goodbye, 1, reason.to_bytes(8, "little")
            )
        except RequestError:
            pass  # goodbye is best-effort

    async def ping(self, peer_id: str, seq: int = 0) -> int:
        chunks = await self._request_raw(peer_id, Protocol.Ping, 1, seq.to_bytes(8, "little"))
        return int.from_bytes(chunks[0][1][:8], "little")

    async def metadata(self, peer_id: str):
        chunks = await self._request_raw(peer_id, Protocol.Metadata, 2, None)
        return self.types.Metadata.deserialize(chunks[0][1])

    async def beacon_blocks_by_range(self, peer_id: str, start_slot: int, count: int, step: int = 1):
        req = (
            start_slot.to_bytes(8, "little")
            + count.to_bytes(8, "little")
            + step.to_bytes(8, "little")
        )
        chunks = await self._request_raw(peer_id, Protocol.BeaconBlocksByRange, 2, req)
        return [self.types.SignedBeaconBlock.deserialize(p) for _, p in chunks]

    async def beacon_blocks_by_root(self, peer_id: str, roots: list[bytes]):
        chunks = await self._request_raw(
            peer_id, Protocol.BeaconBlocksByRoot, 2, b"".join(roots)
        )
        return [self.types.SignedBeaconBlock.deserialize(p) for _, p in chunks]


class RemotePeer:
    """Synchronous `IPeer` view of a remote peer for the sync layer.

    Sync's download loop is synchronous rounds; each call submits the
    coroutine to the network's event loop and blocks the calling (worker)
    thread on the result — mirroring how the reference sync awaits
    reqresp promises."""

    def __init__(self, service: ReqRespService, peer_id: str, loop: asyncio.AbstractEventLoop):
        self.service = service
        self.peer_id = peer_id
        self.loop = loop

    def _run(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self.loop).result(timeout=30.0)

    def status(self):
        return self._run(self.service.status(self.peer_id))

    def beacon_blocks_by_range(self, start_slot: int, count: int) -> list:
        return self._run(
            self.service.beacon_blocks_by_range(self.peer_id, start_slot, count)
        )

    def beacon_blocks_by_root(self, roots: list[bytes]) -> list:
        return self._run(self.service.beacon_blocks_by_root(self.peer_id, roots))
