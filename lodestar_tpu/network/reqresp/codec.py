"""Req/Resp wire codec: varint length prefix + snappy-framed SSZ.

Reference: `reqresp/encodingStrategies/sszSnappy/{encode,decode}.ts` and
response chunking (`response/` — <result byte><varint len><frames>).
"""

from __future__ import annotations

from enum import IntEnum

from .snappy_frames import compress_frames, decompress_frames

MAX_VARINT_BYTES = 10
MAX_PAYLOAD = 10 * 2**20


class RespCode(IntEnum):
    SUCCESS = 0
    INVALID_REQUEST = 1
    SERVER_ERROR = 2
    RESOURCE_UNAVAILABLE = 3


def _write_varint(n: int) -> bytes:
    out = bytearray()
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    return bytes(out)


def _read_varint(data: bytes, offset: int) -> tuple[int, int]:
    value = 0
    shift = 0
    i = offset
    while i < len(data) and i - offset < MAX_VARINT_BYTES:
        b = data[i]
        i += 1
        value |= (b & 0x7F) << shift
        if not (b & 0x80):
            return value, i
        shift += 7
    raise ValueError("truncated/oversized varint")


def encode_request(ssz_bytes: bytes) -> bytes:
    return _write_varint(len(ssz_bytes)) + compress_frames(ssz_bytes)


def decode_request(wire: bytes) -> bytes:
    declared, offset = _read_varint(wire, 0)
    if declared > MAX_PAYLOAD:
        raise ValueError("request too large")
    payload = decompress_frames(wire[offset:])
    if len(payload) != declared:
        raise ValueError("request length mismatch")
    return payload


def encode_response_chunk(ssz_bytes: bytes, code: RespCode = RespCode.SUCCESS) -> bytes:
    return bytes([code]) + _write_varint(len(ssz_bytes)) + compress_frames(ssz_bytes)


def encode_error_chunk(code: RespCode, message: str) -> bytes:
    msg = message.encode()[:256]
    return bytes([code]) + _write_varint(len(msg)) + compress_frames(msg)


def decode_response_chunks(wire: bytes) -> list[tuple[RespCode, bytes]]:
    """Split a response stream into (code, payload) chunks.

    The framing self-delimits: each chunk is result byte + varint + frames,
    and frames carry explicit lengths, so chunks can be walked without an
    outer transport framing."""
    out: list[tuple[RespCode, bytes]] = []
    i = 0
    while i < len(wire):
        code = RespCode(wire[i])
        declared, i = _read_varint(wire, i + 1)
        if declared > MAX_PAYLOAD:
            raise ValueError("chunk too large")
        payload, consumed = _decompress_frames_prefix(wire, i, declared)
        i = consumed
        if len(payload) != declared:
            raise ValueError("chunk length mismatch")
        out.append((code, payload))
    return out


def _decompress_frames_prefix(wire: bytes, offset: int, want: int) -> tuple[bytes, int]:
    """Decompress frames starting at `offset` until `want` bytes are
    produced; returns (payload, next offset)."""
    from .snappy_frames import (
        CHUNK_COMPRESSED,
        CHUNK_UNCOMPRESSED,
        STREAM_IDENTIFIER,
        _masked_checksum,
    )
    from ... import native

    if wire[offset : offset + len(STREAM_IDENTIFIER)] != STREAM_IDENTIFIER:
        raise ValueError("missing stream identifier")
    i = offset + len(STREAM_IDENTIFIER)
    out = bytearray()
    while len(out) < want or (want == 0 and len(out) == 0):
        if i + 4 > len(wire):
            raise ValueError("truncated frames")
        kind = wire[i]
        length = int.from_bytes(wire[i + 1 : i + 4], "little")
        i += 4
        body = wire[i : i + length]
        if len(body) < length:
            raise ValueError("truncated frame body")
        i += length
        if kind == 0xFF:
            continue
        if kind in (CHUNK_COMPRESSED, CHUNK_UNCOMPRESSED):
            checksum = int.from_bytes(body[:4], "little")
            payload = body[4:]
            if kind == CHUNK_COMPRESSED:
                payload = native.snappy_uncompress(payload)
            if _masked_checksum(payload) != checksum:
                raise ValueError("frame checksum mismatch")
            out += payload
            if want == 0:
                break
        elif kind >= 0x80:
            continue
        else:
            raise ValueError(f"unknown frame type {kind:#x}")
    return bytes(out), i
