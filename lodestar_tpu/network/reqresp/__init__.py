"""Req/Resp domain: framed request/response protocols.

Reference: `network/reqresp/` — protocol ids, varint + SSZ-snappy (framing
format) encoding strategies (`encodingStrategies/sszSnappy/`), per-protocol
handlers, response codes.
"""

from .protocols import Protocol, PROTOCOLS, protocol_id  # noqa: F401
from .codec import (  # noqa: F401
    RespCode,
    decode_request,
    decode_response_chunks,
    encode_request,
    encode_response_chunk,
    encode_error_chunk,
)
from .handlers import ReqRespHandlers  # noqa: F401
