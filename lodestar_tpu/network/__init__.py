"""Network layer (SURVEY.md §2.2 `beacon-node/src/network/`).

Built bottom-up: gossip topic/encoding (native snappy + xxhash msg-ids),
req/resp SSZ-snappy framing, validation queues. The libp2p transport
equivalent arrives as an asyncio TCP service; gossip/reqresp logic is
transport-independent and tested over in-memory pipes.
"""
