"""SSZ type system: serialization, deserialization, merkleization.

Equivalent role of `@chainsafe/ssz` for the reference (SURVEY.md §2.1 `types`):
implements the SimpleSerialize spec — basic uints/boolean, byte vectors/lists,
bit vectors/lists, vectors, lists, containers, unions — with offset-based
variable-size serialization and `hash_tree_root` merkleization (pack,
merkleize with limit, length mix-in).

Values are plain Python objects (int, bool, bytes, list, Container instances)
rather than tree-backed views; the state-transition layer keeps its own flat
numpy caches for the hot paths (reference keeps ViewDU trees + flat caches,
state-transition/src/cache/*).
"""

from __future__ import annotations

from typing import Any, Iterable, Sequence

from .hashing import (
    ZERO_HASHES,
    merkleize_chunks,
    mix_in_length,
    mix_in_selector,
)

BYTES_PER_CHUNK = 32
OFFSET_SIZE = 4


class DeserializationError(ValueError):
    pass


def _pack_bytes_to_chunks(data: bytes) -> bytes:
    if len(data) % BYTES_PER_CHUNK:
        data = data + b"\x00" * (BYTES_PER_CHUNK - len(data) % BYTES_PER_CHUNK)
    return data


class SSZType:
    """Base type descriptor. Instances describe a type; values are plain."""

    def is_fixed_size(self) -> bool:
        raise NotImplementedError

    def fixed_size(self) -> int:
        raise NotImplementedError

    def serialize(self, value: Any) -> bytes:
        raise NotImplementedError

    def deserialize(self, data: bytes) -> Any:
        raise NotImplementedError

    def hash_tree_root(self, value: Any) -> bytes:
        raise NotImplementedError

    def default(self) -> Any:
        raise NotImplementedError

    # JSON-ish representation for the REST API layer
    def to_obj(self, value: Any) -> Any:
        raise NotImplementedError

    def from_obj(self, obj: Any) -> Any:
        raise NotImplementedError

    def min_size(self) -> int:
        return self.fixed_size() if self.is_fixed_size() else 0

    def equals(self, a: Any, b: Any) -> bool:
        return self.serialize(a) == self.serialize(b)


class UintType(SSZType):
    def __init__(self, byte_length: int):
        assert byte_length in (1, 2, 4, 8, 16, 32)
        self.byte_length = byte_length
        self._max = (1 << (8 * byte_length)) - 1

    def __repr__(self) -> str:
        return f"uint{self.byte_length * 8}"

    def is_fixed_size(self) -> bool:
        return True

    def fixed_size(self) -> int:
        return self.byte_length

    def serialize(self, value: int) -> bytes:
        v = int(value)
        if v < 0 or v > self._max:
            raise ValueError(f"uint{self.byte_length*8} out of range: {value}")
        return v.to_bytes(self.byte_length, "little")

    def deserialize(self, data: bytes) -> int:
        if len(data) != self.byte_length:
            raise DeserializationError(f"uint{self.byte_length*8}: bad length {len(data)}")
        return int.from_bytes(data, "little")

    def hash_tree_root(self, value: int) -> bytes:
        return int(value).to_bytes(self.byte_length, "little") + b"\x00" * (32 - self.byte_length)

    def default(self) -> int:
        return 0

    def to_obj(self, value: int) -> str:
        return str(int(value))

    def from_obj(self, obj: Any) -> int:
        return int(obj)


class BooleanType(SSZType):
    def __repr__(self) -> str:
        return "boolean"

    def is_fixed_size(self) -> bool:
        return True

    def fixed_size(self) -> int:
        return 1

    def serialize(self, value: bool) -> bytes:
        return b"\x01" if value else b"\x00"

    def deserialize(self, data: bytes) -> bool:
        if data == b"\x00":
            return False
        if data == b"\x01":
            return True
        raise DeserializationError(f"boolean: invalid byte {data!r}")

    def hash_tree_root(self, value: bool) -> bytes:
        return (b"\x01" if value else b"\x00") + b"\x00" * 31

    def default(self) -> bool:
        return False

    def to_obj(self, value: bool) -> bool:
        return bool(value)

    def from_obj(self, obj: Any) -> bool:
        return bool(obj)


class ByteVectorType(SSZType):
    def __init__(self, length: int):
        self.length = length

    def __repr__(self) -> str:
        return f"ByteVector[{self.length}]"

    def is_fixed_size(self) -> bool:
        return True

    def fixed_size(self) -> int:
        return self.length

    def serialize(self, value: bytes) -> bytes:
        value = bytes(value)
        if len(value) != self.length:
            raise ValueError(f"ByteVector[{self.length}]: bad length {len(value)}")
        return value

    def deserialize(self, data: bytes) -> bytes:
        if len(data) != self.length:
            raise DeserializationError(f"ByteVector[{self.length}]: bad length {len(data)}")
        return bytes(data)

    def hash_tree_root(self, value: bytes) -> bytes:
        return merkleize_chunks(_pack_bytes_to_chunks(self.serialize(value)))

    def default(self) -> bytes:
        return b"\x00" * self.length

    def to_obj(self, value: bytes) -> str:
        return "0x" + bytes(value).hex()

    def from_obj(self, obj: str) -> bytes:
        return bytes.fromhex(obj[2:] if obj.startswith("0x") else obj)


class ByteListType(SSZType):
    def __init__(self, limit: int):
        self.limit = limit

    def __repr__(self) -> str:
        return f"ByteList[{self.limit}]"

    def is_fixed_size(self) -> bool:
        return False

    def serialize(self, value: bytes) -> bytes:
        value = bytes(value)
        if len(value) > self.limit:
            raise ValueError(f"ByteList[{self.limit}]: too long {len(value)}")
        return value

    def deserialize(self, data: bytes) -> bytes:
        if len(data) > self.limit:
            raise DeserializationError(f"ByteList[{self.limit}]: too long {len(data)}")
        return bytes(data)

    def hash_tree_root(self, value: bytes) -> bytes:
        value = bytes(value)
        if len(value) > self.limit:
            raise ValueError(f"ByteList[{self.limit}]: too long {len(value)}")
        limit_chunks = (self.limit + 31) // 32
        root = merkleize_chunks(_pack_bytes_to_chunks(value), limit=limit_chunks)
        return mix_in_length(root, len(value))

    def default(self) -> bytes:
        return b""

    def to_obj(self, value: bytes) -> str:
        return "0x" + bytes(value).hex()

    def from_obj(self, obj: str) -> bytes:
        return bytes.fromhex(obj[2:] if obj.startswith("0x") else obj)


class BitVectorType(SSZType):
    def __init__(self, length: int):
        assert length > 0
        self.length = length

    def __repr__(self) -> str:
        return f"BitVector[{self.length}]"

    def is_fixed_size(self) -> bool:
        return True

    def fixed_size(self) -> int:
        return (self.length + 7) // 8

    def serialize(self, value: Sequence[bool]) -> bytes:
        if len(value) != self.length:
            raise ValueError(f"BitVector[{self.length}]: bad length {len(value)}")
        return _bits_to_bytes(value)

    def deserialize(self, data: bytes) -> list[bool]:
        if len(data) != self.fixed_size():
            raise DeserializationError(f"BitVector[{self.length}]: bad byte length {len(data)}")
        bits = _bytes_to_bits(data)
        # Check padding bits beyond `length` are zero
        if any(bits[self.length :]):
            raise DeserializationError(f"BitVector[{self.length}]: nonzero padding")
        return bits[: self.length]

    def hash_tree_root(self, value: Sequence[bool]) -> bytes:
        return merkleize_chunks(
            _pack_bytes_to_chunks(self.serialize(value)), limit=(self.length + 255) // 256
        )

    def default(self) -> list[bool]:
        return [False] * self.length

    def to_obj(self, value: Sequence[bool]) -> str:
        return "0x" + self.serialize(value).hex()

    def from_obj(self, obj: str) -> list[bool]:
        return self.deserialize(bytes.fromhex(obj[2:] if obj.startswith("0x") else obj))


class BitListType(SSZType):
    def __init__(self, limit: int):
        self.limit = limit

    def __repr__(self) -> str:
        return f"BitList[{self.limit}]"

    def is_fixed_size(self) -> bool:
        return False

    def min_size(self) -> int:
        return 1

    def serialize(self, value: Sequence[bool]) -> bytes:
        if len(value) > self.limit:
            raise ValueError(f"BitList[{self.limit}]: too long {len(value)}")
        # Append the delimiter bit at position len(value)
        bits = list(value) + [True]
        return _bits_to_bytes(bits)

    def deserialize(self, data: bytes) -> list[bool]:
        if len(data) == 0:
            raise DeserializationError("BitList: empty")
        if data[-1] == 0:
            raise DeserializationError("BitList: missing delimiter bit")
        bits = _bytes_to_bits(data)
        # Find the delimiter: highest set bit
        last = len(bits) - 1
        while not bits[last]:
            last -= 1
        bit_len = last
        if bit_len > self.limit:
            raise DeserializationError(f"BitList[{self.limit}]: too long {bit_len}")
        # Delimiter must be within the final byte
        if len(data) != (bit_len // 8) + 1:
            raise DeserializationError("BitList: excess bytes")
        return bits[:bit_len]

    def hash_tree_root(self, value: Sequence[bool]) -> bytes:
        if len(value) > self.limit:
            raise ValueError(f"BitList[{self.limit}]: too long {len(value)}")
        data = _bits_to_bytes(list(value))  # no delimiter in merkleization
        root = merkleize_chunks(_pack_bytes_to_chunks(data), limit=(self.limit + 255) // 256)
        return mix_in_length(root, len(value))

    def default(self) -> list[bool]:
        return []

    def to_obj(self, value: Sequence[bool]) -> str:
        return "0x" + self.serialize(value).hex()

    def from_obj(self, obj: str) -> list[bool]:
        return self.deserialize(bytes.fromhex(obj[2:] if obj.startswith("0x") else obj))


def _bits_to_bytes(bits: Sequence[bool]) -> bytes:
    out = bytearray((len(bits) + 7) // 8)
    for i, bit in enumerate(bits):
        if bit:
            out[i // 8] |= 1 << (i % 8)
    return bytes(out)


def _bytes_to_bits(data: bytes) -> list[bool]:
    return [bool((byte >> j) & 1) for byte in data for j in range(8)]


class _HomogeneousType(SSZType):
    """Shared machinery for Vector/List of arbitrary element types."""

    elem: SSZType

    def _serialize_elems(self, values: Iterable[Any]) -> bytes:
        elem = self.elem
        if elem.is_fixed_size():
            return b"".join(elem.serialize(v) for v in values)
        parts = [elem.serialize(v) for v in values]
        offset = OFFSET_SIZE * len(parts)
        out = bytearray()
        for p in parts:
            out += offset.to_bytes(OFFSET_SIZE, "little")
            offset += len(p)
        for p in parts:
            out += p
        return bytes(out)

    def _deserialize_elems(self, data: bytes) -> list[Any]:
        elem = self.elem
        if elem.is_fixed_size():
            size = elem.fixed_size()
            if len(data) % size:
                raise DeserializationError(f"{self}: byte length {len(data)} not multiple of {size}")
            return [elem.deserialize(data[i : i + size]) for i in range(0, len(data), size)]
        if len(data) == 0:
            return []
        if len(data) < OFFSET_SIZE:
            raise DeserializationError(f"{self}: truncated offsets")
        first_offset = int.from_bytes(data[:OFFSET_SIZE], "little")
        if first_offset == 0 or first_offset % OFFSET_SIZE or first_offset > len(data):
            raise DeserializationError(f"{self}: bad first offset {first_offset}")
        count = first_offset // OFFSET_SIZE
        offsets = [
            int.from_bytes(data[i * OFFSET_SIZE : (i + 1) * OFFSET_SIZE], "little")
            for i in range(count)
        ]
        offsets.append(len(data))
        values = []
        for i in range(count):
            if offsets[i] > offsets[i + 1]:
                raise DeserializationError(f"{self}: decreasing offsets")
            values.append(elem.deserialize(data[offsets[i] : offsets[i + 1]]))
        return values

    def _chunks(self, values: Sequence[Any]) -> bytes:
        elem = self.elem
        if isinstance(elem, (UintType, BooleanType)):
            return _pack_bytes_to_chunks(b"".join(elem.serialize(v) for v in values))
        return b"".join(elem.hash_tree_root(v) for v in values)

    def _chunk_limit(self, length: int) -> int:
        elem = self.elem
        if isinstance(elem, (UintType, BooleanType)):
            return (length * elem.fixed_size() + 31) // 32
        return length


class VectorType(_HomogeneousType):
    def __init__(self, elem: SSZType, length: int):
        assert length > 0
        self.elem = elem
        self.length = length

    def __repr__(self) -> str:
        return f"Vector[{self.elem!r}, {self.length}]"

    def is_fixed_size(self) -> bool:
        return self.elem.is_fixed_size()

    def fixed_size(self) -> int:
        return self.elem.fixed_size() * self.length

    def serialize(self, value: Sequence[Any]) -> bytes:
        if len(value) != self.length:
            raise ValueError(f"{self}: bad length {len(value)}")
        return self._serialize_elems(value)

    def deserialize(self, data: bytes) -> list[Any]:
        values = self._deserialize_elems(data)
        if len(values) != self.length:
            raise DeserializationError(f"{self}: bad element count {len(values)}")
        return values

    def hash_tree_root(self, value: Sequence[Any]) -> bytes:
        if len(value) != self.length:
            raise ValueError(f"{self}: bad length {len(value)}")
        return merkleize_chunks(self._chunks(value), limit=self._chunk_limit(self.length))

    def default(self) -> list[Any]:
        return [self.elem.default() for _ in range(self.length)]

    def to_obj(self, value: Sequence[Any]) -> list[Any]:
        return [self.elem.to_obj(v) for v in value]

    def from_obj(self, obj: Sequence[Any]) -> list[Any]:
        return [self.elem.from_obj(v) for v in obj]


class ListType(_HomogeneousType):
    def __init__(self, elem: SSZType, limit: int):
        self.elem = elem
        self.limit = limit

    def __repr__(self) -> str:
        return f"List[{self.elem!r}, {self.limit}]"

    def is_fixed_size(self) -> bool:
        return False

    def serialize(self, value: Sequence[Any]) -> bytes:
        if len(value) > self.limit:
            raise ValueError(f"{self}: too long {len(value)}")
        return self._serialize_elems(value)

    def deserialize(self, data: bytes) -> list[Any]:
        values = self._deserialize_elems(data)
        if len(values) > self.limit:
            raise DeserializationError(f"{self}: too long {len(values)}")
        return values

    def hash_tree_root(self, value: Sequence[Any]) -> bytes:
        if len(value) > self.limit:
            raise ValueError(f"{self}: too long {len(value)}")
        root = merkleize_chunks(self._chunks(value), limit=self._chunk_limit(self.limit))
        return mix_in_length(root, len(value))

    def default(self) -> list[Any]:
        return []

    def to_obj(self, value: Sequence[Any]) -> list[Any]:
        return [self.elem.to_obj(v) for v in value]

    def from_obj(self, obj: Sequence[Any]) -> list[Any]:
        return [self.elem.from_obj(v) for v in obj]


class Container:
    """Base class for container *values*. Subclasses set ``fields`` as a list
    of (name, SSZType) pairs; a matching ContainerType is auto-attached as
    ``cls.ssz_type`` (reference: per-fork ContainerTypes in
    packages/types/src/*/sszTypes.ts)."""

    fields: list[tuple[str, SSZType]] = []
    ssz_type: "ContainerType"

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        if cls.__dict__.get("fields"):
            cls.ssz_type = ContainerType(cls.fields, value_class=cls)

    def __init__(self, **kwargs: Any):
        field_names = {name for name, _ in self.fields}
        for name, typ in self.fields:
            if name in kwargs:
                setattr(self, name, kwargs[name])
            else:
                setattr(self, name, typ.default())
        unknown = set(kwargs) - field_names
        if unknown:
            raise TypeError(f"{type(self).__name__}: unknown fields {sorted(unknown)}")

    @classmethod
    def default(cls) -> "Container":
        return cls()

    def serialize(self) -> bytes:
        return self.ssz_type.serialize(self)

    def hash_tree_root(self) -> bytes:
        return self.ssz_type.hash_tree_root(self)

    @classmethod
    def deserialize(cls, data: bytes) -> "Container":
        return cls.ssz_type.deserialize(data)

    def copy(self) -> "Container":
        """Deep copy through non-destructive structural copying."""
        out = type(self).__new__(type(self))
        for name, typ in self.fields:
            out.__dict__[name] = _copy_value(typ, getattr(self, name))
        return out

    def to_obj(self) -> dict:
        return self.ssz_type.to_obj(self)

    @classmethod
    def from_obj(cls, obj: dict) -> "Container":
        return cls.ssz_type.from_obj(obj)

    def __eq__(self, other: object) -> bool:
        if type(other) is not type(self):
            return NotImplemented
        return all(getattr(self, n) == getattr(other, n) for n, _ in self.fields)

    def __repr__(self) -> str:
        inner = ", ".join(f"{n}={getattr(self, n)!r}" for n, _ in self.fields[:4])
        more = "..." if len(self.fields) > 4 else ""
        return f"{type(self).__name__}({inner}{more})"


def _copy_value(typ: SSZType, value: Any) -> Any:
    if isinstance(value, Container):
        return value.copy()
    if isinstance(value, list):
        elem = getattr(typ, "elem", None)
        if elem is not None:
            return [_copy_value(elem, v) for v in value]
        return list(value)
    return value  # int/bytes/bool are immutable


class ContainerType(SSZType):
    def __init__(self, fields: list[tuple[str, SSZType]], value_class: type | None = None):
        self.fields = fields
        self.value_class = value_class
        self._fixed = all(t.is_fixed_size() for _, t in fields)
        self._fixed_part_size = sum(
            t.fixed_size() if t.is_fixed_size() else OFFSET_SIZE for _, t in fields
        )

    def __repr__(self) -> str:
        name = self.value_class.__name__ if self.value_class else "Container"
        return f"ContainerType[{name}]"

    def is_fixed_size(self) -> bool:
        return self._fixed

    def fixed_size(self) -> int:
        if not self._fixed:
            raise TypeError(f"{self} is variable-size")
        return self._fixed_part_size

    def min_size(self) -> int:
        return self._fixed_part_size

    def _get(self, value: Any, name: str) -> Any:
        return getattr(value, name) if not isinstance(value, dict) else value[name]

    def serialize(self, value: Any) -> bytes:
        fixed_parts: list[bytes | None] = []
        variable_parts: list[bytes] = []
        for name, typ in self.fields:
            v = self._get(value, name)
            if typ.is_fixed_size():
                fixed_parts.append(typ.serialize(v))
            else:
                fixed_parts.append(None)
                variable_parts.append(typ.serialize(v))
        offset = self._fixed_part_size
        out = bytearray()
        var_i = 0
        for part in fixed_parts:
            if part is None:
                out += offset.to_bytes(OFFSET_SIZE, "little")
                offset += len(variable_parts[var_i])
                var_i += 1
            else:
                out += part
        for part in variable_parts:
            out += part
        return bytes(out)

    def deserialize(self, data: bytes) -> Any:
        if len(data) < self._fixed_part_size:
            raise DeserializationError(f"{self}: truncated ({len(data)} bytes)")
        values: dict[str, Any] = {}
        pos = 0
        offsets: list[tuple[str, SSZType, int]] = []
        for name, typ in self.fields:
            if typ.is_fixed_size():
                size = typ.fixed_size()
                values[name] = typ.deserialize(data[pos : pos + size])
                pos += size
            else:
                offset = int.from_bytes(data[pos : pos + OFFSET_SIZE], "little")
                offsets.append((name, typ, offset))
                pos += OFFSET_SIZE
        if offsets:
            if offsets[0][2] != self._fixed_part_size:
                raise DeserializationError(f"{self}: first offset {offsets[0][2]} != fixed size")
            ends = [o for _, _, o in offsets[1:]] + [len(data)]
            for (name, typ, start), end in zip(offsets, ends):
                if start > end or end > len(data):
                    raise DeserializationError(f"{self}: invalid offsets")
                values[name] = typ.deserialize(data[start:end])
        elif pos != len(data):
            raise DeserializationError(f"{self}: {len(data) - pos} excess bytes")
        if self.value_class is not None:
            return self.value_class(**values)
        return values

    def hash_tree_root(self, value: Any) -> bytes:
        chunks = b"".join(typ.hash_tree_root(self._get(value, name)) for name, typ in self.fields)
        return merkleize_chunks(chunks)

    def field_index(self, field_name: str) -> int:
        for i, (name, _) in enumerate(self.fields):
            if name == field_name:
                return i
        raise KeyError(field_name)

    def get_field_branch(self, value: Any, field_name: str) -> list[bytes]:
        """Merkle sibling path proving `field_name`'s root against this
        container's hash_tree_root (bottom-up). Compose paths for nested
        fields by concatenation: inner branch first, then outer."""
        _, branches = self.get_field_branches(value, [field_name])
        return branches[field_name]

    def get_field_branches(
        self, value: Any, field_names: list[str]
    ) -> tuple[bytes, dict[str, list[bytes]]]:
        """(container root, {field: branch}) computed from ONE pass over the
        field roots — callers proving several fields (light-client server)
        must not re-merkleize the container per field."""
        from .hashing import merkle_branch

        chunks = [
            typ.hash_tree_root(self._get(value, name)) for name, typ in self.fields
        ]
        root = merkleize_chunks(b"".join(chunks))
        branches = {
            name: merkle_branch(chunks, self.field_index(name))
            for name in field_names
        }
        return root, branches

    def default(self) -> Any:
        if self.value_class is not None:
            return self.value_class()
        return {name: typ.default() for name, typ in self.fields}

    def to_obj(self, value: Any) -> dict:
        return {name: typ.to_obj(self._get(value, name)) for name, typ in self.fields}

    def from_obj(self, obj: dict) -> Any:
        values = {name: typ.from_obj(obj[name]) for name, typ in self.fields}
        if self.value_class is not None:
            return self.value_class(**values)
        return values


class UnionType(SSZType):
    """SSZ Union (selector byte + value). Option 0 may be None."""

    def __init__(self, options: list[SSZType | None]):
        assert len(options) >= 1
        # Spec rule: None is only permitted as option 0 (and then there must
        # be at least one more option).
        if any(t is None for t in options[1:]) or (options[0] is None and len(options) < 2):
            raise TypeError("Union: None only allowed as first of >=2 options")
        self.options = options

    def is_fixed_size(self) -> bool:
        return False

    def min_size(self) -> int:
        return 1

    def serialize(self, value: tuple[int, Any]) -> bytes:
        selector, v = value
        typ = self.options[selector]
        if typ is None:
            if v is not None:
                raise ValueError("Union None option with value")
            return bytes([selector])
        return bytes([selector]) + typ.serialize(v)

    def deserialize(self, data: bytes) -> tuple[int, Any]:
        if not data:
            raise DeserializationError("Union: empty")
        selector = data[0]
        if selector >= len(self.options):
            raise DeserializationError(f"Union: bad selector {selector}")
        typ = self.options[selector]
        if typ is None:
            if len(data) != 1:
                raise DeserializationError("Union: excess bytes for None option")
            return (selector, None)
        return (selector, typ.deserialize(data[1:]))

    def hash_tree_root(self, value: tuple[int, Any]) -> bytes:
        selector, v = value
        typ = self.options[selector]
        root = ZERO_HASHES[0] if typ is None else typ.hash_tree_root(v)
        return mix_in_selector(root, selector)

    def default(self) -> tuple[int, Any]:
        typ = self.options[0]
        return (0, None if typ is None else typ.default())

    def to_obj(self, value: tuple[int, Any]) -> dict:
        selector, v = value
        typ = self.options[selector]
        return {"selector": selector, "value": None if typ is None else typ.to_obj(v)}

    def from_obj(self, obj: dict) -> tuple[int, Any]:
        selector = int(obj["selector"])
        typ = self.options[selector]
        return (selector, None if typ is None else typ.from_obj(obj["value"]))
