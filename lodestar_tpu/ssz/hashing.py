"""SSZ hashing backend.

Equivalent role of `@chainsafe/as-sha256` (WASM) + `persistent-merkle-tree`
zero-hash machinery in the reference (SURVEY.md §2.3): SHA-256 pair hashing
with precomputed zero-subtree roots. The backend is pluggable so a native
C++ (and later batched-XLA) implementation can replace hashlib without
touching merkleization logic.
"""

from __future__ import annotations

from hashlib import sha256 as _sha256
from typing import Callable, List

HashFn = Callable[[bytes], bytes]


def sha256(data: bytes) -> bytes:
    return _sha256(data).digest()


def hash_pair(a: bytes, b: bytes) -> bytes:
    return _sha256(a + b).digest()


def hash_level(data: bytes) -> bytes:
    """Hash a concatenated level of 64-byte sibling pairs -> concatenated
    32-byte parents. `len(data)` must be a multiple of 64.

    This is the batch seam: a native backend can process all pairs at once.
    """
    n = len(data) // 64
    out = bytearray(32 * n)
    for i in range(n):
        out[32 * i : 32 * i + 32] = _sha256(data[64 * i : 64 * i + 64]).digest()
    return bytes(out)


# Backend slot — native/C++ implementations override these at import time.
_backend_hash_level = hash_level


def set_hash_backend(level_fn: Callable[[bytes], bytes]) -> None:
    global _backend_hash_level
    _backend_hash_level = level_fn


MAX_DEPTH = 64

# ZERO_HASHES[i] = root of a depth-i subtree of zero chunks
ZERO_HASHES: List[bytes] = [b"\x00" * 32]
for _ in range(MAX_DEPTH):
    ZERO_HASHES.append(hash_pair(ZERO_HASHES[-1], ZERO_HASHES[-1]))


def next_power_of_two(n: int) -> int:
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


def merkleize_chunks(chunks: list[bytes] | bytes, limit: int | None = None) -> bytes:
    """Merkleize 32-byte chunks into a single root, virtually padding with
    zero chunks up to ``limit`` (or to the next power of two of the count).

    Matches the spec's `merkleize(chunks, limit)`. ``chunks`` may be a list of
    32-byte values or a single bytes blob whose length is a multiple of 32.
    """
    if isinstance(chunks, (bytes, bytearray)):
        data = bytes(chunks)
        count = len(data) // 32
    else:
        data = b"".join(chunks)
        count = len(chunks)

    size = limit if limit is not None else count
    if size < count:
        raise ValueError(f"chunk count {count} exceeds limit {limit}")
    if size == 0:
        return ZERO_HASHES[0]

    depth = (next_power_of_two(size) - 1).bit_length()
    if count == 0:
        return ZERO_HASHES[depth]

    level = data
    for d in range(depth):
        n = len(level) // 32
        if n % 2 == 1:
            level += ZERO_HASHES[d]
            n += 1
        level = _backend_hash_level(level)
    return level


def merkle_branch(chunks: list[bytes], index: int, limit: int | None = None) -> list[bytes]:
    """Sibling path for chunk `index` under the same padding rules as
    `merkleize_chunks` — bottom-up, `depth` elements. Verifiable with the
    standard is_valid_merkle_branch walk (the single-proof seam the
    light-client protocol needs; reference: persistent-merkle-tree proofs)."""
    count = len(chunks)
    size = limit if limit is not None else count
    depth = (next_power_of_two(max(size, 1)) - 1).bit_length()
    branch: list[bytes] = []
    level = list(chunks)
    idx = index
    for d in range(depth):
        if len(level) % 2 == 1:
            level.append(ZERO_HASHES[d])
        sibling = idx ^ 1
        branch.append(level[sibling] if sibling < len(level) else ZERO_HASHES[d])
        level = [
            hash_pair(level[i], level[i + 1]) for i in range(0, len(level), 2)
        ]
        idx //= 2
    return branch


def mix_in_length(root: bytes, length: int) -> bytes:
    return hash_pair(root, length.to_bytes(32, "little"))


def mix_in_selector(root: bytes, selector: int) -> bytes:
    return hash_pair(root, selector.to_bytes(32, "little"))
