"""SSZ (SimpleSerialize) engine — equivalent of @chainsafe/ssz + as-sha256.

Common type aliases mirror the reference's primitive sszTypes
(packages/types/src/primitive/sszTypes.ts).
"""

from .core import (  # noqa: F401
    BitListType,
    BitVectorType,
    BooleanType,
    ByteListType,
    ByteVectorType,
    Container,
    ContainerType,
    DeserializationError,
    ListType,
    SSZType,
    UintType,
    UnionType,
    VectorType,
)
from .hashing import (  # noqa: F401
    ZERO_HASHES,
    hash_pair,
    merkleize_chunks,
    mix_in_length,
    set_hash_backend,
    sha256,
)

# Basic type singletons
boolean = BooleanType()
byte = UintType(1)
uint8 = UintType(1)
uint16 = UintType(2)
uint32 = UintType(4)
uint64 = UintType(8)
uint128 = UintType(16)
uint256 = UintType(32)

# Primitive aliases (reference: types/src/primitive/sszTypes.ts)
Bytes4 = ByteVectorType(4)
Bytes8 = ByteVectorType(8)
Bytes20 = ByteVectorType(20)
Bytes32 = ByteVectorType(32)
Bytes48 = ByteVectorType(48)
Bytes96 = ByteVectorType(96)

Slot = uint64
Epoch = uint64
CommitteeIndex = uint64
SubcommitteeIndex = uint64
ValidatorIndex = uint64
Gwei = uint64
Root = Bytes32
Version = Bytes4
DomainType = Bytes4
ForkDigest = Bytes4
Domain = Bytes32
BLSPubkey = Bytes48
BLSSignature = Bytes96
ExecutionAddress = Bytes20
ParticipationFlags = uint8
