"""Incremental merkleization: cached chunk trees with dirty-path rehashing.

The reference backs every beacon state with a persistent merkle tree
(`@chainsafe/ssz` ViewDU; `stateTransition.ts:69-74` ends in
commit+hashTreeRoot per block) precisely because a full-tree recompute at
mainnet size is minutes. Here the same role is played columnar-style: the
hot state fields already live in flat numpy arrays
(`state_transition/cache.FlatValidators`), so instead of object-graph
dirty tracking the tree DIFFS its leaf array against the previous call —
one vectorized compare (O(n) bytes, no hashing) finds the dirty chunks,
and only their root paths re-hash (O(dirty · log n) SHA-256 pairs through
the native batched `sha256_level`).

`ChunkTree` is the building block: a merkle tree over a growable array of
32-byte chunks with a fixed virtual limit (spec `merkleize(chunks, limit)`
semantics, zero-subtree padding). `hash_tree_root` output is
bit-identical to `hashing.merkleize_chunks` — differential-tested.
"""

from __future__ import annotations

import numpy as np

from .hashing import ZERO_HASHES, next_power_of_two
from . import hashing as _hashing

_ZERO_ROWS = [np.frombuffer(z, np.uint8) for z in ZERO_HASHES]


def _hash_rows(pairs: np.ndarray) -> np.ndarray:
    """(k, 64) uint8 sibling pairs → (k, 32) uint8 parents via the
    pluggable (native-batched) level hasher."""
    out = _hashing._backend_hash_level(pairs.tobytes())
    return np.frombuffer(out, np.uint8).reshape(-1, 32)


def rows_ne(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(n, 32) uint8 row-wise inequality as (n,) bool — compared through a
    uint64 view (4 words/row) instead of 32 byte lanes: at mainnet sizes
    the naive `(a != b).any(1)` byte compare is the dominant per-call cost
    of the whole incremental hasher (measured 80 ms per million rows)."""
    n = len(a)
    if n == 0:
        return np.zeros(0, bool)
    av = np.ascontiguousarray(a).view(np.uint64).reshape(n, 4)
    bv = np.ascontiguousarray(b).view(np.uint64).reshape(n, 4)
    return np.any(av != bv, axis=1)


class ChunkTree:
    """Merkle tree over ≤ `limit` 32-byte chunks with cached levels.

    `update(leaves)` adopts a new (n, 32) uint8 leaf array: unchanged
    chunks (vs the previous call) cost one vectorized compare; changed and
    appended chunks re-hash only their root paths. Shrinking rebuilds (the
    big consensus lists are append-only; small ones are cheap anyway).
    """

    __slots__ = ("limit", "depth", "levels", "_top")

    def __init__(self, limit_chunks: int):
        self.limit = limit_chunks
        self.depth = (next_power_of_two(max(limit_chunks, 1)) - 1).bit_length()
        self.levels: list[np.ndarray] | None = None
        self._top: bytes | None = None

    # -- internals ----------------------------------------------------------

    def _level_sizes(self, n: int) -> list[int]:
        """Real node count per level, leaves upward, until one node."""
        sizes = [n]
        while sizes[-1] > 1:
            sizes.append((sizes[-1] + 1) // 2)
        return sizes

    def _hash_parents(self, lvl: np.ndarray, idx: np.ndarray, d: int) -> np.ndarray:
        """Hash the `idx` parents of level-d array `lvl` → (k, 32)."""
        n = len(lvl)
        left = lvl[2 * idx]
        right_idx = 2 * idx + 1
        right = np.where(
            (right_idx < n)[:, None],
            lvl[np.minimum(right_idx, n - 1)],
            _ZERO_ROWS[d][None, :],
        )
        return _hash_rows(np.concatenate([left, right], axis=1))

    def _rebuild(self, leaves: np.ndarray) -> None:
        sizes = self._level_sizes(len(leaves))
        levels = [leaves]
        for d in range(len(sizes) - 1):
            idx = np.arange(sizes[d + 1])
            levels.append(self._hash_parents(levels[d], idx, d))
        self.levels = levels
        self._top = None

    # -- public -------------------------------------------------------------

    def update(self, leaves: np.ndarray) -> None:
        """Adopt a new leaf array ((n, 32) uint8, n ≤ limit)."""
        if leaves.ndim != 2 or leaves.shape[1] != 32:
            raise ValueError("leaves must be (n, 32)")
        if len(leaves) > self.limit:
            raise ValueError(f"chunk count {len(leaves)} exceeds limit {self.limit}")
        leaves = np.ascontiguousarray(leaves, dtype=np.uint8)

        # NOTE on aliasing: callers may hand a view of a buffer they mutate
        # in place between calls (the validators hasher does) — the stored
        # level-0 array is the diff baseline and must not alias it, so a
        # private copy is taken at every adoption point below. The clean
        # path (no dirty chunks) adopts nothing and stays copy-free.
        if self.levels is None or len(leaves) < len(self.levels[0]):
            self._rebuild(leaves.copy())
            return
        old = self.levels[0]
        n_old, n_new = len(old), len(leaves)
        if n_new == 0:
            self._rebuild(leaves.copy())
            return
        dirty = np.nonzero(rows_ne(old, leaves[:n_old]))[0]
        if n_new > n_old:
            dirty = np.concatenate([dirty, np.arange(n_old, n_new)])
        if len(dirty) == 0:
            return
        leaves = leaves.copy()
        sizes = self._level_sizes(n_new)
        levels = [leaves]
        for d in range(len(sizes) - 1):
            dirty = np.unique(dirty // 2)
            nxt = np.empty((sizes[d + 1], 32), np.uint8)
            prev = self.levels[d + 1] if d + 1 < len(self.levels) else None
            if prev is not None:
                keep = min(len(prev), sizes[d + 1])
                nxt[:keep] = prev[:keep]
            nxt[dirty] = self._hash_parents(levels[d], dirty, d)
            levels.append(nxt)
        self.levels = levels
        self._top = None

    def root(self) -> bytes:
        """Spec merkleize(chunks, limit) root (no length mix-in)."""
        if self._top is not None:
            return self._top
        if self.levels is None or len(self.levels[0]) == 0:
            return ZERO_HASHES[self.depth]
        top = self.levels[-1][0].tobytes()
        # fold the real subtree up through the virtual zero padding
        for d in range(len(self.levels) - 1, self.depth):
            top = _hashing.hash_pair(top, ZERO_HASHES[d])
        self._top = top
        return top
