"""Execution layer client (SURVEY.md §2.2 `execution/`).

Reference: `execution/engine/` — `IExecutionEngine` (interface.ts),
JSON-RPC HTTP client with JWT auth (http.ts: engine_newPayloadV1,
engine_forkchoiceUpdatedV1, engine_getPayloadV1), and the complete
in-memory mock EL (mock.ts:31) used by tests/sim.
"""

from .engine import (  # noqa: F401
    ExecutePayloadStatus,
    ExecutionEngineHttp,
    ExecutionEngineMock,
    IExecutionEngine,
    PayloadAttributes,
)
