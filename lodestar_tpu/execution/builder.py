"""MEV builder API client + in-process mock relay.

Reference: `beacon-node/src/execution/builder/http.ts` + `api/src/builder`
routes — the builder flow: registerValidator → getHeader (bid with payload
header) → submitBlindedBlock (reveal full payload). The mock relay plays
the role the reference's builder test doubles play.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class BuilderApiError(Exception):
    pass


class BuilderApiClient:
    """Blocking client to a builder-spec relay endpoint."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.host = host
        self.port = port
        self.timeout = timeout

    def _request(self, method: str, path: str, body=None):
        from ..utils.http import json_http_request

        return json_http_request(
            self.host, self.port, method, path, body,
            timeout=self.timeout, error_cls=BuilderApiError,
        )

    def check_status(self) -> bool:
        try:
            self._request("GET", "/eth/v1/builder/status")
            return True
        except Exception:
            return False

    def register_validators(self, registrations: list[dict]) -> None:
        self._request("POST", "/eth/v1/builder/validators", registrations)

    def get_header(self, slot: int, parent_hash: bytes, pubkey: bytes) -> dict | None:
        """The builder's bid: {header, value, pubkey} or None when it has
        nothing for this slot."""
        try:
            out = self._request(
                "GET",
                f"/eth/v1/builder/header/{slot}/0x{parent_hash.hex()}/0x{pubkey.hex()}",
            )
        except BuilderApiError:
            return None
        return (out or {}).get("data")

    def submit_blinded_block(self, signed_blinded_block: dict) -> dict:
        out = self._request(
            "POST", "/eth/v1/builder/blinded_blocks", signed_blinded_block
        )
        return (out or {}).get("data")


class MockBuilderRelay:
    """In-process relay: bids a header for any parent it has a payload for;
    reveals the payload on blinded-block submission."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.registrations: list[dict] = []
        # parent_hash hex → payload json offered for the next slot
        self.payloads: dict[str, dict] = {}
        relay = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _send(self, status: int, obj) -> None:
                raw = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            def do_GET(self):
                if self.path == "/eth/v1/builder/status":
                    return self._send(200, {})
                if self.path.startswith("/eth/v1/builder/header/"):
                    parts = self.path.split("/")
                    parent_hash = parts[-2].removeprefix("0x")
                    payload = relay.payloads.get(parent_hash)
                    if payload is None:
                        return self._send(204, {})
                    return self._send(
                        200,
                        {
                            "data": {
                                "header": payload["header"],
                                "value": payload.get("value", "1"),
                            }
                        },
                    )
                self._send(404, {"message": "not found"})

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length)) if length else None
                if self.path == "/eth/v1/builder/validators":
                    relay.registrations.extend(body or [])
                    return self._send(200, {})
                if self.path == "/eth/v1/builder/blinded_blocks":
                    # reveal: match by parent hash in the blinded header
                    parent = (
                        body["message"]["body"]["execution_payload_header"][
                            "parent_hash"
                        ].removeprefix("0x")
                        if body
                        else ""
                    )
                    payload = relay.payloads.get(parent)
                    if payload is None:
                        return self._send(400, {"message": "unknown payload"})
                    return self._send(200, {"data": payload["payload"]})
                self._send(404, {"message": "not found"})

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def offer_payload(self, parent_hash: bytes, header: dict, payload: dict, value: str = "1"):
        self.payloads[parent_hash.hex()] = {
            "header": header,
            "payload": payload,
            "value": value,
        }

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
