"""Engine API: interface, in-memory mock EL, JSON-RPC client.

Reference: `execution/engine/interface.ts` (IExecutionEngine),
`engine/mock.ts:31` (ExecutionEngineMock — a full fake EL maintaining a
block tree with TTD logic), `engine/http.ts` (JSON-RPC with
jwt-simple HS256 auth).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Protocol

from ..ssz.hashing import sha256


class ExecutePayloadStatus(str, Enum):
    VALID = "VALID"
    INVALID = "INVALID"
    SYNCING = "SYNCING"
    ACCEPTED = "ACCEPTED"
    INVALID_BLOCK_HASH = "INVALID_BLOCK_HASH"
    ELERROR = "ELERROR"
    UNAVAILABLE = "UNAVAILABLE"


@dataclass
class PayloadAttributes:
    timestamp: int
    prev_randao: bytes
    suggested_fee_recipient: bytes
    # capella (engine API v2): expected withdrawals for the built payload
    withdrawals: list = field(default_factory=list)


class IExecutionEngine(Protocol):
    def notify_new_payload(self, payload) -> ExecutePayloadStatus: ...

    def notify_forkchoice_update(
        self,
        head_block_hash: bytes,
        safe_block_hash: bytes,
        finalized_block_hash: bytes,
        attributes: PayloadAttributes | None = None,
    ) -> str | None: ...

    def get_payload(self, payload_id: str, fork: str = "bellatrix"): ...


@dataclass
class _MockPayload:
    block_hash: bytes
    parent_hash: bytes
    block_number: int
    timestamp: int
    prev_randao: bytes
    fee_recipient: bytes
    transactions: list = field(default_factory=list)
    withdrawals: list = field(default_factory=list)


class ExecutionEngineMock:
    """In-memory EL: payload tree + building sessions (reference mock.ts).

    Used by the dev chain and sim tests exactly like the reference uses
    ExecutionEngineMock — valid unless told otherwise."""

    def __init__(self, genesis_block_hash: bytes = b"\x00" * 32):
        self.head: bytes = genesis_block_hash
        self.finalized: bytes = genesis_block_hash
        self.payloads: dict[bytes, _MockPayload] = {
            genesis_block_hash: _MockPayload(
                block_hash=genesis_block_hash,
                parent_hash=b"\x00" * 32,
                block_number=0,
                timestamp=0,
                prev_randao=b"\x00" * 32,
                fee_recipient=b"\x00" * 20,
            )
        }
        self._building: dict[str, _MockPayload] = {}
        self._payload_id = 0
        # test hook: mark hashes invalid (reference mock supports error
        # injection for invalid-payload paths)
        self.invalid_hashes: set[bytes] = set()

    def notify_new_payload(self, payload) -> ExecutePayloadStatus:
        if payload.block_hash in self.invalid_hashes:
            return ExecutePayloadStatus.INVALID
        if payload.parent_hash not in self.payloads:
            return ExecutePayloadStatus.SYNCING
        parent = self.payloads[payload.parent_hash]
        if payload.block_number != parent.block_number + 1:
            return ExecutePayloadStatus.INVALID
        self.payloads[payload.block_hash] = payload
        return ExecutePayloadStatus.VALID

    def notify_forkchoice_update(
        self,
        head_block_hash: bytes,
        safe_block_hash: bytes,
        finalized_block_hash: bytes,
        attributes: PayloadAttributes | None = None,
    ) -> str | None:
        if head_block_hash not in self.payloads:
            return None
        self.head = head_block_hash
        self.finalized = finalized_block_hash
        if attributes is None:
            return None
        parent = self.payloads[head_block_hash]
        self._payload_id += 1
        payload_id = f"0x{self._payload_id:016x}"
        block_hash = sha256(
            head_block_hash + attributes.timestamp.to_bytes(8, "little")
        )
        self._building[payload_id] = _MockPayload(
            block_hash=block_hash,
            parent_hash=head_block_hash,
            block_number=parent.block_number + 1,
            timestamp=attributes.timestamp,
            prev_randao=attributes.prev_randao,
            fee_recipient=attributes.suggested_fee_recipient,
            withdrawals=list(attributes.withdrawals),
        )
        return payload_id

    def get_payload(self, payload_id: str, fork: str = "bellatrix") -> _MockPayload:
        payload = self._building.pop(payload_id, None)
        if payload is None:
            raise ValueError(f"unknown payload id {payload_id}")
        return payload


def payload_to_engine_json(payload) -> dict:
    """SSZ ExecutionPayload container → engine-API JSON (camelCase, 0x-hex,
    hex-quantity numbers) — reference serializeExecutionPayload."""
    out = {
        "parentHash": "0x" + bytes(payload.parent_hash).hex(),
        "feeRecipient": "0x" + bytes(payload.fee_recipient).hex(),
        "stateRoot": "0x" + bytes(payload.state_root).hex(),
        "receiptsRoot": "0x" + bytes(payload.receipts_root).hex(),
        "logsBloom": "0x" + bytes(payload.logs_bloom).hex(),
        "prevRandao": "0x" + bytes(payload.prev_randao).hex(),
        "blockNumber": hex(payload.block_number),
        "gasLimit": hex(payload.gas_limit),
        "gasUsed": hex(payload.gas_used),
        "timestamp": hex(payload.timestamp),
        "extraData": "0x" + bytes(payload.extra_data).hex(),
        "baseFeePerGas": hex(payload.base_fee_per_gas),
        "blockHash": "0x" + bytes(payload.block_hash).hex(),
        "transactions": ["0x" + bytes(tx).hex() for tx in payload.transactions],
    }
    if hasattr(payload, "withdrawals"):
        out["withdrawals"] = [
            {
                "index": hex(w.index),
                "validatorIndex": hex(w.validator_index),
                "address": "0x" + bytes(w.address).hex(),
                "amount": hex(w.amount),
            }
            for w in payload.withdrawals
        ]
    return out


_ENGINE_KEY_MAP = {
    "parent_hash": "parentHash",
    "fee_recipient": "feeRecipient",
    "state_root": "stateRoot",
    "receipts_root": "receiptsRoot",
    "logs_bloom": "logsBloom",
    "prev_randao": "prevRandao",
    "block_number": "blockNumber",
    "gas_limit": "gasLimit",
    "gas_used": "gasUsed",
    "timestamp": "timestamp",
    "extra_data": "extraData",
    "base_fee_per_gas": "baseFeePerGas",
    "block_hash": "blockHash",
    "transactions": "transactions",
    "withdrawals": "withdrawals",
}


def engine_json_field(built, snake_name: str, default=None):
    """Field from an engine get_payload result: mock payload objects use
    snake_case attributes, engine JSON uses camelCase keys."""
    if isinstance(built, dict):
        camel = _ENGINE_KEY_MAP.get(snake_name, snake_name)
        if camel in built:
            return built[camel]
        return built.get(snake_name, default)
    return getattr(built, snake_name, default)


def _jwt_hs256(secret: bytes) -> str:
    """Engine-API JWT: HS256, iat claim (reference uses jwt-simple)."""
    b64 = lambda b: base64.urlsafe_b64encode(b).rstrip(b"=")
    header = b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    claims = b64(json.dumps({"iat": int(time.time())}).encode())
    signing_input = header + b"." + claims
    sig = b64(hmac.new(secret, signing_input, hashlib.sha256).digest())
    return (signing_input + b"." + sig).decode()


class ExecutionEngineHttp:
    """JSON-RPC engine client (engine_newPayloadV1 / forkchoiceUpdatedV1 /
    getPayloadV1) with fresh JWT per request (reference http.ts)."""

    def __init__(
        self, host: str, port: int, jwt_secret: bytes, timeout: float = 8.0,
        metrics=None, retries: int = 2,
    ):
        from ..utils.retry import RetryPolicy, transient_http

        self.host = host
        self.port = port
        self.jwt_secret = jwt_secret
        self.timeout = timeout
        self.metrics = metrics
        self._id = 0
        # transport-level retry (shared utils/retry helper): a dropped
        # connection to the EL must not surface as SYNCING/ELERROR on a
        # proposal path. JSON-RPC error REPLIES are never retried — the
        # EL answered; engine semantics decide what an error means.
        self._retry_policy = RetryPolicy(
            max_attempts=1 + max(0, retries),
            base_delay_s=0.25,
            max_delay_s=2.0,
            retryable=transient_http,
        )

    def _call(self, method: str, params: list):
        import http.client
        import time as _time

        from ..utils.retry import retry_call

        t0 = _time.monotonic()
        self._id += 1
        body = json.dumps(
            {"jsonrpc": "2.0", "id": self._id, "method": method, "params": params}
        ).encode()

        def _transport():
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout
            )
            try:
                conn.request(
                    "POST",
                    "/",
                    body=body,
                    headers={
                        "Content-Type": "application/json",
                        "Authorization": f"Bearer {_jwt_hs256(self.jwt_secret)}",
                    },
                )
                return json.loads(conn.getresponse().read())
            finally:
                conn.close()

        resp = retry_call(_transport, policy=self._retry_policy)
        if self.metrics is not None:
            self.metrics.engine_request_seconds.observe(
                _time.monotonic() - t0, method=method
            )
            self.metrics.engine_requests_total.inc(
                method=method,
                outcome="error" if "error" in resp else "ok",
            )
        if "error" in resp:
            raise RuntimeError(f"{method}: {resp['error']}")
        return resp["result"]

    def notify_new_payload(self, payload) -> ExecutePayloadStatus:
        """Accepts an SSZ ExecutionPayload container or a pre-built engine
        JSON dict. The engine's latestValidHash (when present and nonzero)
        is kept on `last_latest_valid_hash` for the caller's
        optimistic-sync invalidation — the return shape stays a bare
        status so every IExecutionEngine implementation agrees."""
        payload_json = (
            payload if isinstance(payload, dict) else payload_to_engine_json(payload)
        )
        version = "V2" if "withdrawals" in payload_json else "V1"
        result = self._call(f"engine_newPayload{version}", [payload_json])
        if self.metrics is not None:
            self.metrics.engine_payload_status_total.inc(
                status=str(result.get("status"))
            )
        lvh_hex = result.get("latestValidHash")
        lvh = (
            bytes.fromhex(lvh_hex.removeprefix("0x"))
            if isinstance(lvh_hex, str)
            else None
        )
        # the zero hash means "no valid ancestor known" (engine API): no LVH
        self.last_latest_valid_hash = (
            lvh if lvh and lvh != b"\x00" * 32 else None
        )
        return ExecutePayloadStatus(result["status"])

    def notify_forkchoice_update(
        self, head: bytes, safe: bytes, finalized: bytes, attributes=None
    ):
        fc_state = {
            "headBlockHash": "0x" + head.hex(),
            "safeBlockHash": "0x" + safe.hex(),
            "finalizedBlockHash": "0x" + finalized.hex(),
        }
        attrs = None
        version = "V1"
        if attributes is not None:
            attrs = {
                "timestamp": hex(attributes.timestamp),
                "prevRandao": "0x" + attributes.prev_randao.hex(),
                "suggestedFeeRecipient": "0x" + attributes.suggested_fee_recipient.hex(),
            }
            if attributes.withdrawals:
                # capella: engine API V2 carries the expected withdrawals
                version = "V2"
                attrs["withdrawals"] = [
                    {
                        "index": hex(w.index),
                        "validatorIndex": hex(w.validator_index),
                        "address": "0x" + bytes(w.address).hex(),
                        "amount": hex(w.amount),
                    }
                    for w in attributes.withdrawals
                ]
        result = self._call(f"engine_forkchoiceUpdated{version}", [fc_state, attrs])
        payload_id = result.get("payloadId")
        return payload_id

    def get_payload(self, payload_id: str, fork: str = "bellatrix") -> dict:
        """engine_getPayloadV1 pre-capella; V2 (which wraps the payload as
        {executionPayload, blockValue} and carries withdrawals) after."""
        if fork in ("phase0", "altair", "bellatrix"):
            return self._call("engine_getPayloadV1", [payload_id])
        result = self._call("engine_getPayloadV2", [payload_id])
        if isinstance(result, dict) and "executionPayload" in result:
            return result["executionPayload"]
        return result

    def exchange_transition_configuration(self, ttd: int, terminal_block_hash: bytes) -> bool:
        """engine_exchangeTransitionConfigurationV1 (`engine/http.ts:308`):
        CL and EL cross-check their merge configuration; mismatch means a
        mis-configured pair that would fork at the transition."""
        result = self._call(
            "engine_exchangeTransitionConfigurationV1",
            [
                {
                    "terminalTotalDifficulty": hex(ttd),
                    "terminalBlockHash": "0x" + terminal_block_hash.hex(),
                    "terminalBlockNumber": "0x0",
                }
            ],
        )
        if not isinstance(result, dict):
            return False
        got_ttd = int(str(result.get("terminalTotalDifficulty", "0x0")), 16)
        got_hash = str(result.get("terminalBlockHash", "0x")).removeprefix("0x")
        return got_ttd == ttd and bytes.fromhex(got_hash or "00" * 32) == terminal_block_hash
