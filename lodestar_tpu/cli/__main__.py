"""`python -m lodestar_tpu.cli` — command dispatcher (reference:
cli/src/cli.ts yargs tree)."""

from __future__ import annotations

import argparse
import sys

from .beacon import add_beacon_parser
from .dev import add_dev_parser
from .flare import add_flare_parser
from .lightclient import add_lightclient_parser
from .validator import add_validator_parser


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="lodestar-tpu", description="TPU-native beacon chain framework"
    )
    sub = parser.add_subparsers(dest="cmd", required=True)
    add_dev_parser(sub)
    add_beacon_parser(sub)
    add_validator_parser(sub)
    add_lightclient_parser(sub)
    add_flare_parser(sub)
    args = parser.parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
