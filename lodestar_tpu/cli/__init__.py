"""CLI (SURVEY.md §2.1 `cli`): `python -m lodestar_tpu.cli <cmd>`.

Reference: `packages/cli` yargs commands — `dev` (single-process local
testnet: `cli/src/cmds/dev`), `beacon`, `validator`. The `dev` command is
the minimum end-to-end slice (SURVEY.md §7): interop genesis, in-process
validators, block production + import with batched signature verification,
REST API + metrics servers, finality tracking.
"""
