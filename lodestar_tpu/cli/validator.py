"""`validator` command: run a validator client against a beacon node.

Reference: `cli/src/cmds/validator` — keys from interop range, keystore
directory, or an external signer; duty loop over the Beacon API; EIP-3076
slashing-protection db in the datadir.
"""

from __future__ import annotations

import signal
import time
from urllib.parse import urlparse

from ..api.client import BeaconApiClient
from ..bls import api as bls
from ..config.beacon_config import BeaconConfig
from ..config.chain_config import MAINNET_CHAIN_CONFIG, MINIMAL_CHAIN_CONFIG
from ..db.controller import FileDb, MemoryDb
from ..params.presets import MAINNET, MINIMAL
from ..types import get_types
from ..utils.logger import get_logger
from ..validator import SlashingProtection, ValidatorStore
from ..validator.doppelganger import DoppelgangerService
from ..validator.rest_service import RestValidatorService


def _client_for(url: str) -> BeaconApiClient:
    parsed = urlparse(url if "//" in url else f"http://{url}")
    return BeaconApiClient(parsed.hostname, parsed.port or 5052)


def run_validator(args) -> int:
    log = get_logger("validator-cli")
    preset, chain_config = (
        (MINIMAL, MINIMAL_CHAIN_CONFIG)
        if args.network == "minimal-dev"
        else (MAINNET, MAINNET_CHAIN_CONFIG)
    )
    client = _client_for(args.beacon_url)
    genesis = client.getGenesis()
    config = BeaconConfig(
        chain_config,
        bytes.fromhex(genesis["genesis_validators_root"].removeprefix("0x")),
        preset,
    )
    types = get_types(preset).phase0

    controller = FileDb(args.datadir) if args.datadir else MemoryDb()
    store = ValidatorStore(config, SlashingProtection(controller))

    if args.interop_keys:
        lo, _, hi = args.interop_keys.partition(":")
        for i in range(int(lo), int(hi or int(lo) + 1)):
            store.add_secret_key(bls.interop_secret_key(i))
    if args.keystores_dir:
        from ..validator.keystore import load_keystores_dir

        password = ""
        if args.keystores_password_file:
            with open(args.keystores_password_file) as f:
                password = f.read().strip()
        for sk in load_keystores_dir(args.keystores_dir, password):
            store.add_secret_key(sk)
    if args.external_signer_url:
        from ..validator.external_signer import ExternalSignerClient

        parsed = urlparse(
            args.external_signer_url
            if "//" in args.external_signer_url
            else f"http://{args.external_signer_url}"
        )
        signer = ExternalSignerClient(parsed.hostname, parsed.port or 9000)
        for pk in signer.list_pubkeys():
            store.add_remote_key(pk, signer)
    if not store.pubkeys:
        log.error("no keys: pass --interop-keys, --keystores-dir, or --external-signer-url")
        return 1
    log.info("%d validator keys loaded", len(store.pubkeys))

    keymanager_server = None
    if args.keymanager:
        from ..api.keymanager import create_keymanager_server

        # args.datadir is the FileDb log FILE path, not a directory —
        # the token lives beside it as <datadir>.api-token.txt
        token_file = args.datadir + ".api-token.txt" if args.datadir else None
        keymanager_server = create_keymanager_server(
            store, port=args.keymanager_port, token_file=token_file
        )
        keymanager_server.start()
        log.info(
            "keymanager API on port %d (token file: %s)",
            keymanager_server.port,
            keymanager_server.token_file,
        )

    try:
        doppelganger = DoppelgangerService() if args.doppelganger else None
        service = RestValidatorService(config, types, client, store, doppelganger)
        genesis_time = int(genesis["genesis_time"])
        if doppelganger is not None:
            current_epoch = max(
                0,
                int(time.time() - genesis_time)
                // (config.SECONDS_PER_SLOT * preset.SLOTS_PER_EPOCH),
            )
            service.resolve_indices()
            for idx in service._indices.values():
                doppelganger.register(idx, current_epoch)

        stop = {"flag": False}
        signal.signal(signal.SIGINT, lambda s, f: stop.update(flag=True))
        spt = config.SECONDS_PER_SLOT
        last_slot = -1
        deadline = time.time() + args.run_seconds if args.run_seconds else None
        while not stop["flag"]:
            now = time.time()
            if deadline and now >= deadline:
                break
            slot = max(0, int(now - genesis_time) // spt)
            if slot != last_slot:
                try:
                    service.on_slot(slot)
                except Exception as e:
                    log.error("slot %d: %s", slot, e)
                last_slot = slot
            time.sleep(min(0.2, spt / 10))
        return 0
    finally:
        if keymanager_server is not None:
            keymanager_server.close()


def add_validator_parser(sub) -> None:
    p = sub.add_parser("validator", help="run a validator client")
    p.add_argument("--network", default="minimal-dev", choices=["minimal-dev", "mainnet"])
    p.add_argument("--beacon-url", default="http://127.0.0.1:5052")
    p.add_argument("--datadir", default=None, help="slashing-protection db path")
    p.add_argument("--interop-keys", default=None, help="interop key range lo:hi")
    p.add_argument("--keystores-dir", default=None, help="EIP-2335 keystore directory")
    p.add_argument("--keystores-password-file", default=None)
    p.add_argument("--external-signer-url", default=None, help="web3signer-compatible endpoint")
    p.add_argument("--doppelganger", action="store_true", help="enable doppelganger protection")
    p.add_argument("--keymanager", action="store_true", help="serve the keymanager API")
    p.add_argument("--keymanager-port", type=int, default=5062)
    p.add_argument("--run-seconds", type=float, default=0)
    p.set_defaults(func=run_validator)
