"""`dev` command: single-process local testnet.

Reference behavior: `lodestar dev` (cli/src/cmds/dev) — start a beacon
node from an interop genesis with all validators in-process, produce and
import blocks every (accelerated) slot, expose the REST API and metrics.
"""

from __future__ import annotations

import time

from ..api import BeaconApiServer
from ..api.impl import BeaconApiImpl
from ..bls import api as bls
from ..chain import BeaconChain, CpuBlsVerifier
from ..chain.bls_verifier import DeviceBlsVerifier
from ..config.beacon_config import BeaconConfig, ChainForkConfig
from ..config.chain_config import MINIMAL_CHAIN_CONFIG
from ..db import MemoryDb
from ..metrics import MetricsServer, create_beacon_metrics
from ..params.presets import MINIMAL
from ..state_transition import interop_genesis_state
from ..types import get_types
from ..utils.logger import get_logger
from ..validator import SlashingProtection, ValidatorService, ValidatorStore


def run_dev(args) -> int:
    log = get_logger("dev")
    preset = MINIMAL
    types = get_types(preset).phase0
    spe = preset.SLOTS_PER_EPOCH

    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, preset)
    genesis_time = int(time.time())
    state = interop_genesis_state(
        fork_config, types, args.validators, genesis_time=genesis_time
    )
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), preset
    )
    log.info(
        "interop genesis: %d validators, root %s",
        args.validators,
        state.genesis_validators_root.hex()[:16],
    )

    if args.tpu_verifier:
        # same supervised stack as BeaconNode.init: device tier behind
        # the deadline/retry/fallback/breaker policy (docs/robustness.md)
        from ..chain import SupervisedBlsVerifier

        verifier = SupervisedBlsVerifier(DeviceBlsVerifier(), CpuBlsVerifier())
    else:
        verifier = CpuBlsVerifier()
    chain = BeaconChain(config, types, state, verifier=verifier)
    store = ValidatorStore(config, SlashingProtection(MemoryDb()))
    for i in range(args.validators):
        store.add_secret_key(bls.interop_secret_key(i))
    service = ValidatorService(config, types, chain, store)

    metrics = create_beacon_metrics()
    chain.metrics = metrics
    # per-validator duty monitor over the local keys (reference
    # validatorMonitor: epoch-end duty summaries + metrics)
    from ..metrics.validator_monitor import ValidatorMonitor

    monitor = ValidatorMonitor(metrics.registry)
    for i in range(args.validators):
        monitor.register_validator(i)
    chain.validator_monitor = monitor
    api_server = None
    metrics_server = None
    if args.rest:
        impl = BeaconApiImpl(config, types, chain, validator_service=service)
        api_server = BeaconApiServer(impl, port=args.rest_port)
        api_server.start()
        log.info("REST API on :%d", api_server.port)
    if args.metrics:
        metrics_server = MetricsServer(metrics.registry, port=args.metrics_port)
        metrics_server.start()
        log.info("metrics on :%d", metrics_server.port)

    try:
        for slot in range(1, args.slots + 1):
            chain.clock.set_slot(slot)
            t0 = time.perf_counter()
            signed = service.propose_block_if_due(slot)
            dt = time.perf_counter() - t0  # produce+import only
            service.attest_if_due(slot)
            if slot % preset.SLOTS_PER_EPOCH == 0:
                epoch_now = slot // preset.SLOTS_PER_EPOCH
                # summarize an epoch only after its inclusion window fully
                # closed (attestations from epoch e can land early in e+1);
                # stamp current balances onto the epoch being closed
                if epoch_now >= 2:
                    monitor.on_balances(
                        epoch_now - 2, chain.head_state.state.balances
                    )
                    monitor.log_epoch(epoch_now - 2, log)
            metrics.head_slot.set(chain.head_state.state.slot)
            metrics.current_justified_epoch.set(chain.justified_checkpoint[0])
            metrics.finalized_epoch.set(chain.finalized_checkpoint[0])
            if signed is not None:
                metrics.proposed_blocks_total.inc()
                metrics.processed_blocks_total.inc()
                metrics.block_import_seconds.observe(dt)
            log.info(
                "slot %d/%d  epoch %d  head %s  justified %d  finalized %d  (%.0f ms)",
                slot,
                args.slots,
                slot // spe,
                chain.head_root.hex()[:8],
                chain.justified_checkpoint[0],
                chain.finalized_checkpoint[0],
                dt * 1e3,
            )
            if args.slot_time > 0:
                time.sleep(args.slot_time)
        log.info(
            "done: head slot %d, justified epoch %d, finalized epoch %d",
            chain.head_state.state.slot,
            chain.justified_checkpoint[0],
            chain.finalized_checkpoint[0],
        )
        if args.slots >= 3 * spe and chain.justified_checkpoint[0] == 0:
            log.error("chain failed to justify after %d slots", args.slots)
            return 1
        return 0
    finally:
        stopper = getattr(verifier, "stop_profiling", None)
        if callable(stopper):
            stopper()  # flush the XLA trace (LODESTAR_TPU_PROFILE)
        if api_server:
            api_server.close()
        if metrics_server:
            metrics_server.close()


def add_dev_parser(sub) -> None:
    p = sub.add_parser("dev", help="single-process local testnet")
    p.add_argument("--validators", type=int, default=16)
    p.add_argument("--slots", type=int, default=24, help="slots to run")
    p.add_argument("--slot-time", type=float, default=0.0, help="seconds per slot (0 = as fast as possible)")
    p.add_argument("--rest", action="store_true", help="serve the REST API")
    p.add_argument("--rest-port", type=int, default=0)
    p.add_argument("--metrics", action="store_true", help="serve /metrics")
    p.add_argument("--metrics-port", type=int, default=0)
    p.add_argument(
        "--tpu-verifier",
        action="store_true",
        help="verify signatures on the device batch kernels instead of the CPU oracle",
    )
    p.set_defaults(func=run_dev)
