"""`flare` — beacon chain multi-purpose and debugging tool.

Reference: `packages/flare` (`flare/package.json:4`) with its two
commands `self-slash-proposer` / `self-slash-attester`
(`flare/src/cmds/selfSlashProposer.ts`, `selfSlashAttester.ts`): craft
valid slashing objects for validators whose keys you control (interop /
dev keys here) and submit them to a beacon node — the standard way to
exercise slashing processing on a testnet.
"""

from __future__ import annotations

from ..bls import api as bls
from ..config.beacon_config import compute_signing_root
from ..params import DOMAIN_BEACON_ATTESTER, DOMAIN_BEACON_PROPOSER
from ..utils.logger import get_logger

log = get_logger("flare")


def _client(server: str):
    from urllib.parse import urlparse

    from ..api.client import BeaconApiClient

    parsed = urlparse(server if "//" in server else f"http://{server}")
    return BeaconApiClient(parsed.hostname, parsed.port or 5052)


def _setup(server: str, network: str):
    from ..config.beacon_config import BeaconConfig
    from ..config.chain_config import MAINNET_CHAIN_CONFIG, MINIMAL_CHAIN_CONFIG
    from ..params.presets import MAINNET, MINIMAL
    from ..types import get_types

    client = _client(server)
    genesis = client.getGenesis()
    root = bytes.fromhex(genesis["genesis_validators_root"].removeprefix("0x"))
    if network == "minimal-dev":
        config = BeaconConfig(MINIMAL_CHAIN_CONFIG, root, MINIMAL)
        types = get_types(MINIMAL).phase0
    else:
        config = BeaconConfig(MAINNET_CHAIN_CONFIG, root, MAINNET)
        types = get_types(MAINNET).phase0
    return client, config, types


def _parse_indices(spec: str) -> list[int]:
    """'0..4' or '1,3,7' → validator indices (interop keys)."""
    if ".." in spec:
        lo, hi = spec.split("..")
        return list(range(int(lo), int(hi)))
    return [int(x) for x in spec.split(",") if x]


def run_self_slash_proposer(args) -> int:
    """Sign two conflicting block headers per validator and submit
    ProposerSlashing objects (selfSlashProposer.ts)."""
    client, config, types = _setup(args.server, args.network)
    slot = int(args.slot)
    domain = config.get_domain(DOMAIN_BEACON_PROPOSER, slot)
    submitted = 0
    for index in _parse_indices(args.validators):
        sk = bls.interop_secret_key(index)
        headers = []
        for variant in (b"\x01", b"\x02"):
            header = types.BeaconBlockHeader(
                slot=slot,
                proposer_index=index,
                parent_root=b"\x00" * 32,
                state_root=b"\x00" * 32,
                body_root=variant * 32,
            )
            sig = sk.sign(compute_signing_root(header.hash_tree_root(), domain))
            headers.append(
                types.SignedBeaconBlockHeader(message=header, signature=sig.to_bytes())
            )
        slashing = types.ProposerSlashing(
            signed_header_1=headers[0], signed_header_2=headers[1]
        )
        client.submitPoolProposerSlashings(body=slashing.to_obj())
        submitted += 1
        log.info("self-slashed proposer %d at slot %d", index, slot)
    print(f"submitted {submitted} proposer slashings")
    return 0


def run_self_slash_attester(args) -> int:
    """Sign two attestations with the same target (double vote) per batch
    of validators and submit AttesterSlashing objects
    (selfSlashAttester.ts — batched across MAX_VALIDATORS_PER_COMMITTEE)."""
    client, config, types = _setup(args.server, args.network)
    slot = int(args.slot)
    epoch = slot // config.preset.SLOTS_PER_EPOCH
    domain = config.get_domain(
        DOMAIN_BEACON_ATTESTER,
        epoch * config.preset.SLOTS_PER_EPOCH,
        epoch,
    )
    indices = _parse_indices(args.validators)
    batch = max(1, int(args.batch_size))
    submitted = 0
    for off in range(0, len(indices), batch):
        group = sorted(indices[off : off + batch])
        atts = []
        for variant in (b"\x01", b"\x02"):
            data = types.AttestationData(
                slot=slot,
                index=0,
                beacon_block_root=variant * 32,
                source=types.Checkpoint(epoch=max(0, epoch - 1), root=b"\x00" * 32),
                target=types.Checkpoint(epoch=epoch, root=variant * 32),
            )
            root = compute_signing_root(data.hash_tree_root(), domain)
            sigs = [bls.interop_secret_key(i).sign(root) for i in group]
            atts.append(
                types.IndexedAttestation(
                    attesting_indices=group,
                    data=data,
                    signature=bls.aggregate_signatures(sigs).to_bytes(),
                )
            )
        slashing = types.AttesterSlashing(attestation_1=atts[0], attestation_2=atts[1])
        client.submitPoolAttesterSlashings(body=slashing.to_obj())
        submitted += 1
        log.info("self-slashed attesters %s at slot %d", group, slot)
    print(f"submitted {submitted} attester slashings")
    return 0


def add_flare_parser(sub) -> None:
    p = sub.add_parser(
        "flare", help="beacon chain multi-purpose and debugging tool"
    )
    flare_sub = p.add_subparsers(dest="flare_cmd", required=True)

    common = dict(
        server="beacon node REST endpoint (host[:port])",
        validators="interop validator indices: '0..4' or '1,3'",
    )
    sp = flare_sub.add_parser(
        "self-slash-proposer", help="submit double-proposal slashings for own keys"
    )
    sp.add_argument("--server", default="127.0.0.1:5052", help=common["server"])
    sp.add_argument("--network", default="minimal-dev")
    sp.add_argument("--validators", required=True, help=common["validators"])
    sp.add_argument("--slot", default="1")
    sp.set_defaults(func=run_self_slash_proposer)

    sa = flare_sub.add_parser(
        "self-slash-attester", help="submit double-vote slashings for own keys"
    )
    sa.add_argument("--server", default="127.0.0.1:5052", help=common["server"])
    sa.add_argument("--network", default="minimal-dev")
    sa.add_argument("--validators", required=True, help=common["validators"])
    sa.add_argument("--slot", default="1")
    sa.add_argument("--batch-size", default="32", dest="batch_size")
    sa.set_defaults(func=run_self_slash_attester)
