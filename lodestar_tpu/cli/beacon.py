"""`beacon` command: run a beacon node.

Reference: `cli/src/cmds/beacon/handler.ts:25` — config from flags, db at
the datadir, anchor state via the checkpoint-sync / db-resume / genesis
decision tree (`initBeaconState.ts`), then `BeaconNode.init` and a clock
loop until interrupted.
"""

from __future__ import annotations

import signal
import time

from ..config.beacon_config import BeaconConfig, ChainForkConfig
from ..config.chain_config import MAINNET_CHAIN_CONFIG, MINIMAL_CHAIN_CONFIG
from ..db import BeaconDb
from ..db.controller import FileDb, MemoryDb
from ..node import BeaconNode, NodeOptions, init_beacon_state
from ..params.presets import MAINNET, MINIMAL
from ..state_transition import interop_genesis_state
from ..types import get_types
from ..utils.logger import get_logger



def _read_token_file(path: str | None) -> str | None:
    """Bearer token from a file (reference: api/rest bearer-auth token file);
    whitespace-stripped, None when unset. A missing or empty file is a
    configuration error — refusing loudly beats serving with a
    zero-entropy token or rejecting every client."""
    if not path:
        return None
    try:
        with open(path) as f:
            token = f.read().strip()
    except OSError as e:
        raise SystemExit(f"--rest-auth-token-file: cannot read {path}: {e}")
    if not token:
        raise SystemExit(f"--rest-auth-token-file: {path} is empty")
    return token

def _fetch_checkpoint_state(url: str) -> tuple[str, bytes]:
    """(fork_name, ssz_bytes) of a finalized state over the debug SSZ route
    (reference: fetchWeakSubjectivityState from --checkpointSyncUrl)."""
    from urllib.parse import urlparse

    from ..api.client import BeaconApiClient

    parsed = urlparse(url if "//" in url else f"http://{url}")
    client = BeaconApiClient(parsed.hostname, parsed.port or 5052)
    data = client.getStateV2("finalized")
    return data["version"], bytes.fromhex(data["ssz"].removeprefix("0x"))


def run_beacon(args) -> int:
    log = get_logger("beacon")
    if args.network == "minimal-dev":
        preset, chain_config = MINIMAL, MINIMAL_CHAIN_CONFIG
    else:
        preset, chain_config = MAINNET, MAINNET_CHAIN_CONFIG
    types_all = get_types(preset)
    fork_config = ChainForkConfig(chain_config, preset)

    # anchor decision tree
    checkpoint_bytes = None
    checkpoint_fork = "phase0"
    genesis_state = None
    if args.checkpoint_sync_url:
        log.info("checkpoint sync from %s", args.checkpoint_sync_url)
        checkpoint_fork, checkpoint_bytes = _fetch_checkpoint_state(
            args.checkpoint_sync_url
        )
    if args.datadir:
        import os

        if os.path.isfile(args.datadir):
            # legacy layout: --datadir pointed straight at the db log file
            log.info("using legacy single-file datadir layout")
            db_controller = FileDb(args.datadir)
        elif os.path.isfile(os.path.join(args.datadir, "chain.db")):
            # round-1 datadir (python log format): keep reading it
            log.info("using round-1 FileDb datadir layout")
            db_controller = FileDb(os.path.join(args.datadir, "chain.db"))
        else:
            os.makedirs(args.datadir, exist_ok=True)
            try:
                from ..db.controller import NativeKvDb

                db_controller = NativeKvDb(os.path.join(args.datadir, "kv"))
                log.info("native KV engine at %s/kv", args.datadir)
            except (RuntimeError, OSError) as e:
                log.warning("native KV unavailable (%s); FileDb fallback", e)
                db_controller = FileDb(os.path.join(args.datadir, "chain.db"))
    else:
        db_controller = MemoryDb()
    probe_db = BeaconDb(types_all.phase0, db_controller)
    if checkpoint_bytes is None and args.genesis_validators:
        genesis_state = interop_genesis_state(
            fork_config,
            types_all.phase0,
            args.genesis_validators,
            genesis_time=args.genesis_time or int(time.time()),
        )
    state, origin = init_beacon_state(
        fork_config,
        types_all,
        probe_db,
        checkpoint_state_bytes=checkpoint_bytes,
        checkpoint_fork=checkpoint_fork,
        genesis_state=genesis_state,
    )
    from lodestar_tpu.node.init_state import _fork_of_state

    types = types_all.by_fork[_fork_of_state(state)]
    config = BeaconConfig(chain_config, bytes(state.genesis_validators_root), preset)
    log.info("anchor: %s (slot %d)", origin, state.slot)

    engine = None
    if args.execution == "mock":
        from ..execution.engine import ExecutionEngineMock

        engine = ExecutionEngineMock()
    elif args.execution:
        from ..execution.engine import ExecutionEngineHttp

        host, _, port = args.execution.rpartition(":")
        secret = bytes.fromhex(args.jwt_secret) if args.jwt_secret else b"\x00" * 32
        engine = ExecutionEngineHttp(host or "127.0.0.1", int(port), secret)

    eth1_provider = None
    if args.eth1_endpoint:
        from ..eth1.provider import Eth1ProviderHttp

        e1_host, _, e1_port = args.eth1_endpoint.rpartition(":")
        eth1_provider = Eth1ProviderHttp(
            config,
            types,
            e1_host or "127.0.0.1",
            int(e1_port),
            deploy_block=args.eth1_deploy_block,
        )
        log.info("eth1 deposit follower: %s", args.eth1_endpoint)

    node = BeaconNode.init(
        config,
        types,
        state,
        NodeOptions(
            db_controller=db_controller,  # datadir-backed, persists restarts
            rest=args.rest,
            rest_port=args.rest_port,
            rest_bearer_token=_read_token_file(args.rest_auth_token_file),
            rest_cors_origin=args.rest_cors,
            metrics=args.metrics,
            metrics_port=args.metrics_port,
            tpu_verifier=args.tpu_verifier,
            execution_engine=engine,
            eth1_provider=eth1_provider,
        ),
    )

    stop = {"flag": False}

    def _sigint(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGINT, _sigint)

    # irrecoverable fork-choice faults force an orderly exit (reference
    # ProcessShutdownCallback wired in cmds/beacon/handler.ts:43-46)
    def _process_shutdown(reason: str) -> None:
        log.critical("process shutdown requested: %s", reason)
        stop["flag"] = True

    node.chain.process_shutdown_callback = _process_shutdown

    if args.port:
        return _run_networked(args, node, config, types, stop, log)

    clock = _SlotClock(node, state.genesis_time, config.SECONDS_PER_SLOT, args.run_seconds)
    try:
        while not stop["flag"] and not clock.expired():
            clock.tick()
            time.sleep(clock.nap())
        return 0
    finally:
        node.close()
        log.info("node stopped; state persisted")


class _SlotClock:
    """Wall-clock slot follower shared by the plain and networked loops."""

    def __init__(self, node, genesis_time: int, seconds_per_slot: int, run_seconds: float):
        self.node = node
        self.genesis_time = genesis_time
        self.spt = seconds_per_slot
        self.deadline = time.time() + run_seconds if run_seconds else None
        self.last_slot = -1

    def expired(self) -> bool:
        return self.deadline is not None and time.time() >= self.deadline

    def current_slot(self) -> int:
        return max(0, int(time.time() - self.genesis_time) // self.spt)

    def tick(self) -> int | None:
        """Advance the node if a new slot started; returns it (else None)."""
        slot = self.current_slot()
        if slot == self.last_slot:
            return None
        self.node.on_clock_slot(slot)
        self.last_slot = slot
        return slot

    def nap(self) -> float:
        return min(0.2, self.spt / 10)


def _run_networked(args, node, config, types, stop, log) -> int:
    """Live-networked node: gossip + discovery + reqresp + range sync
    (reference beacon handler with network.start, §3.1)."""
    import asyncio
    import os

    from ..network.discovery import enr_from_text, enr_to_text
    from ..network.network import Network

    async def main() -> int:
        bootnodes = []
        for text in (args.bootnodes or "").split(","):
            text = text.strip()
            if text:
                bootnodes.append(enr_from_text(text))
        network = Network(
            config, types, node.chain,
            identity=_load_identity(args.datadir),
            metrics=node.metrics,
        )
        await network.start(
            host=args.listen_address,
            port=args.port if args.port > 0 else 0,
            discovery=True,
            bootnodes=bootnodes,
            advertise_ip=args.advertise_ip,
        )
        node.attach_network(network)
        # merge persisted peers from the last run into the table (reference:
        # libp2p datastore persistence, network/peers/datastore.ts)
        _load_peerstore(args.datadir, network)
        enr_text = enr_to_text(network.discovery.local_enr)
        log.info("p2p listening on %s, peer id %s", network.transport.listen_addr, network.peer_id[:16])
        log.info("ENR: %s", enr_text)
        if args.datadir and os.path.isdir(args.datadir):
            with open(os.path.join(args.datadir, "enr.txt"), "w") as f:
                f.write(enr_text + "\n")

        clock = _SlotClock(
            node, node.chain.head_state.state.genesis_time,
            config.SECONDS_PER_SLOT, args.run_seconds,
        )
        loop = asyncio.get_running_loop()
        sync_state = {"task": None}
        try:
            while not stop["flag"] and not clock.expired():
                slot = clock.tick()
                if slot is not None and (
                    sync_state["task"] is None or sync_state["task"].done()
                ):
                    # background task: the clock must keep ticking and SIGINT
                    # must stay responsive while a long catch-up sync runs
                    sync_state["task"] = loop.create_task(
                        _maybe_range_sync(node, network, slot, loop, log)
                    )
                await asyncio.sleep(clock.nap())
            return 0
        finally:
            _save_peerstore(args.datadir, network)
            await network.stop()
            node.close()
            log.info("node stopped; state persisted")

    return asyncio.run(main())


def _peerstore_path(datadir):
    import os

    if not datadir or not os.path.isdir(datadir):
        return None
    return os.path.join(datadir, "peerstore.txt")


def _save_peerstore(datadir, network) -> None:
    from ..network.discovery import enr_to_text

    path = _peerstore_path(datadir)
    if path is None or network.discovery is None:
        return
    try:
        with open(path, "w") as f:
            for enr in network.discovery.table.all():
                f.write(enr_to_text(enr) + "\n")
    except OSError:
        pass


def _load_peerstore(datadir, network) -> None:
    import os

    from ..network.discovery import enr_from_text

    path = _peerstore_path(datadir)
    if path is None or network.discovery is None or not os.path.exists(path):
        return
    loaded = 0
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    enr = enr_from_text(line)
                except ValueError:
                    continue
                network.discovery._known_keys[enr.node_id] = enr.pubkey
                if network.discovery.table.update(enr):
                    loaded += 1
    except OSError:
        return
    if loaded:
        get_logger("beacon").info("restored %d peers from peerstore", loaded)


def _load_identity(datadir):
    """Persist the p2p identity key under the datadir so the node's peer id
    and ENR survive restarts (reference: ENR + peer-id persistence)."""
    from ..network.transport import NodeIdentity

    if not datadir:
        return None
    import os

    if os.path.isfile(datadir):
        return None  # legacy single-file layout has nowhere to keep it
    path = os.path.join(datadir, "network_key")
    if os.path.exists(path):
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PrivateKey,
        )

        with open(path, "rb") as f:
            return NodeIdentity(Ed25519PrivateKey.from_private_bytes(f.read()))
    identity = NodeIdentity()
    raw = identity.private_key.private_bytes_raw()
    with open(path, "wb") as f:
        f.write(raw)
    os.chmod(path, 0o600)
    return identity


async def _maybe_range_sync(node, network, clock_slot: int, loop, log) -> None:
    """If the head trails the clock by more than an epoch, range-sync from
    the best-status peer (reference RangeSync trigger)."""
    from ..sync.range_sync import RangeSync

    head_slot = node.chain.head_state.state.slot
    if clock_slot <= head_slot + node.config.preset.SLOTS_PER_EPOCH:
        return
    peers = network.sync_peers(loop)
    if not peers:
        return

    def run_sync() -> int:
        rs = RangeSync(
            node.chain, node.types, node.config.preset.SLOTS_PER_EPOCH,
            metrics=getattr(node, "metrics", None),
        )
        for peer in peers:
            rs.add_peer(peer)
        return rs.sync_to(clock_slot)

    try:
        synced = await loop.run_in_executor(None, run_sync)
        log.info("range sync reached slot %d", synced)
    except Exception as e:
        log.warning("range sync failed: %s", e)


def add_beacon_parser(sub) -> None:
    p = sub.add_parser("beacon", help="run a beacon node")
    p.add_argument("--network", default="minimal-dev", choices=["minimal-dev", "mainnet"])
    p.add_argument("--datadir", default=None, help="persistent db path (default: memory)")
    p.add_argument("--checkpoint-sync-url", default=None, help="trusted Beacon API for weak-subjectivity anchor")
    p.add_argument("--genesis-validators", type=int, default=0, help="interop genesis with N validators")
    p.add_argument("--genesis-time", type=int, default=0)
    p.add_argument("--rest", action="store_true")
    p.add_argument("--rest-port", type=int, default=5052)
    p.add_argument(
        "--rest-auth-token-file",
        help="file holding the bearer token required on every REST request",
    )
    p.add_argument(
        "--rest-cors",
        help='CORS allowed origin for the REST API (e.g. "*")',
    )
    p.add_argument("--metrics", action="store_true")
    p.add_argument("--metrics-port", type=int, default=8008)
    p.add_argument("--execution", default=None, help='"mock" or host:port of an EL engine API')
    p.add_argument("--eth1-endpoint", default=None, help="host:port of an eth1 JSON-RPC node (deposit follower)")
    p.add_argument("--eth1-deploy-block", type=int, default=0, help="deposit contract deployment block")
    p.add_argument("--jwt-secret", default=None, help="hex engine-API JWT secret")
    p.add_argument("--tpu-verifier", action="store_true")
    p.add_argument("--run-seconds", type=float, default=0, help="exit after N seconds (0 = forever)")
    p.add_argument("--port", type=int, default=0, help="p2p TCP/UDP listen port (enables live networking; -1 = ephemeral)")
    p.add_argument("--bootnodes", default=None, help="comma-separated enr-tpu: records to bootstrap from")
    p.add_argument("--advertise-ip", default=None, help="external address advertised in the ENR")
    p.add_argument("--listen-address", default="127.0.0.1", help="p2p bind address (use 0.0.0.0 with --advertise-ip for WAN)")
    p.set_defaults(func=run_beacon)
