"""`beacon` command: run a beacon node.

Reference: `cli/src/cmds/beacon/handler.ts:25` — config from flags, db at
the datadir, anchor state via the checkpoint-sync / db-resume / genesis
decision tree (`initBeaconState.ts`), then `BeaconNode.init` and a clock
loop until interrupted.
"""

from __future__ import annotations

import signal
import time

from ..config.beacon_config import BeaconConfig, ChainForkConfig
from ..config.chain_config import MAINNET_CHAIN_CONFIG, MINIMAL_CHAIN_CONFIG
from ..db import BeaconDb
from ..db.controller import FileDb, MemoryDb
from ..node import BeaconNode, NodeOptions, init_beacon_state
from ..params.presets import MAINNET, MINIMAL
from ..state_transition import interop_genesis_state
from ..types import get_types
from ..utils.logger import get_logger


def _fetch_checkpoint_state(url: str) -> tuple[str, bytes]:
    """(fork_name, ssz_bytes) of a finalized state over the debug SSZ route
    (reference: fetchWeakSubjectivityState from --checkpointSyncUrl)."""
    from urllib.parse import urlparse

    from ..api.client import BeaconApiClient

    parsed = urlparse(url if "//" in url else f"http://{url}")
    client = BeaconApiClient(parsed.hostname, parsed.port or 5052)
    data = client.getStateV2("finalized")
    return data["version"], bytes.fromhex(data["ssz"].removeprefix("0x"))


def run_beacon(args) -> int:
    log = get_logger("beacon")
    if args.network == "minimal-dev":
        preset, chain_config = MINIMAL, MINIMAL_CHAIN_CONFIG
    else:
        preset, chain_config = MAINNET, MAINNET_CHAIN_CONFIG
    types_all = get_types(preset)
    fork_config = ChainForkConfig(chain_config, preset)

    # anchor decision tree
    checkpoint_bytes = None
    checkpoint_fork = "phase0"
    genesis_state = None
    if args.checkpoint_sync_url:
        log.info("checkpoint sync from %s", args.checkpoint_sync_url)
        checkpoint_fork, checkpoint_bytes = _fetch_checkpoint_state(
            args.checkpoint_sync_url
        )
    db_controller = FileDb(args.datadir) if args.datadir else MemoryDb()
    probe_db = BeaconDb(types_all.phase0, db_controller)
    if checkpoint_bytes is None and args.genesis_validators:
        genesis_state = interop_genesis_state(
            fork_config,
            types_all.phase0,
            args.genesis_validators,
            genesis_time=args.genesis_time or int(time.time()),
        )
    state, origin = init_beacon_state(
        fork_config,
        types_all,
        probe_db,
        checkpoint_state_bytes=checkpoint_bytes,
        checkpoint_fork=checkpoint_fork,
        genesis_state=genesis_state,
    )
    from lodestar_tpu.node.init_state import _fork_of_state

    types = types_all.by_fork[_fork_of_state(state)]
    config = BeaconConfig(chain_config, bytes(state.genesis_validators_root), preset)
    log.info("anchor: %s (slot %d)", origin, state.slot)

    engine = None
    if args.execution == "mock":
        from ..execution.engine import ExecutionEngineMock

        engine = ExecutionEngineMock()
    elif args.execution:
        from ..execution.engine import ExecutionEngineHttp

        host, _, port = args.execution.rpartition(":")
        secret = bytes.fromhex(args.jwt_secret) if args.jwt_secret else b"\x00" * 32
        engine = ExecutionEngineHttp(host or "127.0.0.1", int(port), secret)

    node = BeaconNode.init(
        config,
        types,
        state,
        NodeOptions(
            db_controller=db_controller,  # datadir-backed, persists restarts
            rest=args.rest,
            rest_port=args.rest_port,
            metrics=args.metrics,
            metrics_port=args.metrics_port,
            tpu_verifier=args.tpu_verifier,
            execution_engine=engine,
        ),
    )

    stop = {"flag": False}

    def _sigint(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGINT, _sigint)

    genesis_time = state.genesis_time
    spt = config.SECONDS_PER_SLOT
    try:
        last_slot = -1
        deadline = time.time() + args.run_seconds if args.run_seconds else None
        while not stop["flag"]:
            now = time.time()
            if deadline and now >= deadline:
                break
            slot = max(0, int(now - genesis_time) // spt)
            if slot != last_slot:
                node.on_clock_slot(slot)
                last_slot = slot
            time.sleep(min(0.2, spt / 10))
        return 0
    finally:
        node.close()
        log.info("node stopped; state persisted")


def add_beacon_parser(sub) -> None:
    p = sub.add_parser("beacon", help="run a beacon node")
    p.add_argument("--network", default="minimal-dev", choices=["minimal-dev", "mainnet"])
    p.add_argument("--datadir", default=None, help="persistent db path (default: memory)")
    p.add_argument("--checkpoint-sync-url", default=None, help="trusted Beacon API for weak-subjectivity anchor")
    p.add_argument("--genesis-validators", type=int, default=0, help="interop genesis with N validators")
    p.add_argument("--genesis-time", type=int, default=0)
    p.add_argument("--rest", action="store_true")
    p.add_argument("--rest-port", type=int, default=5052)
    p.add_argument("--metrics", action="store_true")
    p.add_argument("--metrics-port", type=int, default=8008)
    p.add_argument("--execution", default=None, help='"mock" or host:port of an EL engine API')
    p.add_argument("--jwt-secret", default=None, help="hex engine-API JWT secret")
    p.add_argument("--tpu-verifier", action="store_true")
    p.add_argument("--run-seconds", type=float, default=0, help="exit after N seconds (0 = forever)")
    p.set_defaults(func=run_beacon)
