"""`lightclient` command: follow the chain with merkle-proof verification
only (no state transition).

Reference: `cli/src/cmds/lightclient` — bootstrap from a trusted block
root via the Beacon API, then poll updates per sync-committee period and
optimistic/finality updates per slot.
"""

from __future__ import annotations

import signal
import time
from urllib.parse import urlparse

from ..api.client import BeaconApiClient
from ..config.beacon_config import BeaconConfig
from ..config.chain_config import MAINNET_CHAIN_CONFIG, MINIMAL_CHAIN_CONFIG
from ..light_client import Lightclient
from ..params.presets import MAINNET, MINIMAL
from ..types import get_types
from ..utils.logger import get_logger


def run_lightclient(args) -> int:
    log = get_logger("lightclient-cli")
    preset, chain_config = (
        (MINIMAL, MINIMAL_CHAIN_CONFIG)
        if args.network == "minimal-dev"
        else (MAINNET, MAINNET_CHAIN_CONFIG)
    )
    parsed = urlparse(
        args.beacon_url if "//" in args.beacon_url else f"http://{args.beacon_url}"
    )
    client = BeaconApiClient(parsed.hostname, parsed.port or 5052)
    genesis = client.getGenesis()
    config = BeaconConfig(
        chain_config,
        bytes.fromhex(genesis["genesis_validators_root"].removeprefix("0x")),
        preset,
    )
    t = get_types(preset).altair
    lc = Lightclient(config, t, preset)

    trusted_root = bytes.fromhex(args.trusted_block_root.removeprefix("0x"))
    boot_obj = client.getLightClientBootstrap("0x" + trusted_root.hex())
    lc.bootstrap(trusted_root, t.LightClientBootstrap.from_obj(boot_obj))
    log.info("bootstrapped at slot %d", lc.optimistic_header.slot)

    stop = {"flag": False}
    signal.signal(signal.SIGINT, lambda s, f: stop.update(flag=True))
    deadline = time.time() + args.run_seconds if args.run_seconds else None
    period_len = preset.SLOTS_PER_EPOCH * preset.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
    while not stop["flag"]:
        if deadline and time.time() >= deadline:
            break
        try:
            period = lc.optimistic_header.slot // period_len
            for obj in client.getLightClientUpdatesByRange(
                query={"start_period": period, "count": 4}
            ) or []:
                lc.process_update(t.LightClientUpdate.from_obj(obj))
        except Exception as e:
            log.debug("update poll: %s", e)
        log.info(
            "optimistic slot %d  finalized slot %d  root %s",
            lc.optimistic_header.slot,
            lc.finalized_header.slot,
            lc.optimistic_header.hash_tree_root().hex()[:12],
        )
        time.sleep(args.poll_seconds)
    return 0


def add_lightclient_parser(sub) -> None:
    p = sub.add_parser("lightclient", help="run a light client")
    p.add_argument("--network", default="minimal-dev", choices=["minimal-dev", "mainnet"])
    p.add_argument("--beacon-url", default="http://127.0.0.1:5052")
    p.add_argument("--trusted-block-root", required=True)
    p.add_argument("--poll-seconds", type=float, default=2.0)
    p.add_argument("--run-seconds", type=float, default=0)
    p.set_defaults(func=run_lightclient)
