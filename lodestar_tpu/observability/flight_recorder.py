"""Black-box flight recorder: a bounded ring of recent pipeline events.

Both red driver rounds to date (BENCH_r05 `rc: 124, parsed: null` and the
round-4 MULTICHIP cold-cache kill) died without naming WHAT they were
doing when the clock ran out. This module is the crash-survivable answer:
every interesting transition — kernel dispatch, compile start/end,
breaker flip, mesh eviction, bench-phase boundary, warmup rung — drops a
tiny dict into a process-wide `collections.deque(maxlen=N)`. The bench
emitter reads the ring at EMIT time (including the watchdog and SIGTERM
paths), so an rc=124 round's final JSON carries a post-mortem naming the
exact kernel/shape/phase it wedged on instead of a bare `timed_out`
marker.

Design constraints (mirrors `bench_emit`): stdlib-only, import-light,
never raises into the hot path. A `record()` is one lock + one deque
append — cheap enough for per-batch dispatch events. The ring size is
LODESTAR_TPU_FLIGHT_RECORDER_SIZE (default 256 events); `dump()` reports
how many older events were dropped so a truncated history is visible,
never silent.

Event shape: {"seq", "t_s", "kind", ...kind-specific fields}. `t_s` is
seconds since the recorder singleton was created (≈ process start for
the bench/warmup/node entrypoints, which all touch observability early).
"""

from __future__ import annotations

import threading
import time
from collections import deque

__all__ = ["FlightRecorder", "recorder", "record"]

DEFAULT_CAPACITY = 256


def _configured_capacity() -> int:
    from ..utils.env import env_int

    size = env_int("LODESTAR_TPU_FLIGHT_RECORDER_SIZE")
    return size if size and size > 0 else DEFAULT_CAPACITY


class FlightRecorder:
    """Bounded ring of recent events; thread-safe; drop-oldest."""

    def __init__(self, capacity: int | None = None):
        if capacity is None:
            capacity = _configured_capacity()
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=max(1, int(capacity)))  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        self._t0 = time.monotonic()

    @property
    def capacity(self) -> int:
        return self._ring.maxlen

    def record(self, kind: str, **fields) -> dict:
        """Append one event; returns it (tests assert on the shape)."""
        t_s = round(time.monotonic() - self._t0, 3)
        with self._lock:
            self._seq += 1
            event = {"seq": self._seq, "t_s": t_s, "kind": kind, **fields}
            self._ring.append(event)
        return event

    def dump(self, limit: int | None = None) -> dict:
        """Snapshot for the bench doc / `/debug/compiles`: newest-last
        events plus enough bookkeeping to see what the ring dropped."""
        with self._lock:
            events = list(self._ring)
            total = self._seq
        if limit is not None and limit >= 0:
            events = events[-limit:]
        return {
            "capacity": self._ring.maxlen,
            "recorded_total": total,
            "dropped": total - len(events),
            "events": events,
        }


_recorder: FlightRecorder | None = None
_recorder_lock = threading.Lock()


def recorder() -> FlightRecorder:
    """The process-wide ring every subsystem records into."""
    global _recorder
    with _recorder_lock:
        if _recorder is None:
            _recorder = FlightRecorder()
        return _recorder


def record(kind: str, **fields) -> dict:
    """Module-level convenience: `flight_recorder.record("breaker", ...)`."""
    return recorder().record(kind, **fields)


def _reset_for_tests() -> None:
    """Drop the singleton so a test gets a fresh, empty ring."""
    global _recorder
    with _recorder_lock:
        _recorder = None
