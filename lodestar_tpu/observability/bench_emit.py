"""Structured bench emitter: a benchmark run ALWAYS ends in one JSON doc.

BENCH_r05 recorded `rc: 124, parsed: null`: the harness hit the driver's
global timeout mid-phase and emitted nothing. This module kills that
failure mode three ways:

- **per-phase deadlines** (`phase(name, deadline_s=...)`): SIGALRM raises
  `PhaseTimeout` inside the phase, which is recorded as `status: timeout`
  and skipped gracefully — later phases still run. (A deadline can only
  interrupt Python bytecode; a single long C/XLA call returns first. The
  layers below keep per-call work bounded so this is the common case.)
- **SIGTERM flush**: the driver's `timeout` sends SIGTERM; the handler
  emits the document with whatever phases completed before exiting.
- **atexit flush**: any other exit path (exception, sys.exit) emits too.
- **watchdog thread** (`global_deadline_s`): signal handlers only run on
  the main thread between bytecodes — a main thread stuck inside a long
  XLA compile (a C call) would ride SIGTERM straight into `timeout -k`'s
  SIGKILL with nothing printed. The watchdog is an ordinary daemon
  thread, immune to that: at the budget it emits the partial document
  and `os._exit(124)`s before the external killer fires.

The document's final stdout line is a single JSON object carrying the
headline metric plus per-phase throughput, the stage-time breakdown, and
planner-decision counts (sections are registered as callables and read at
emit time, so a mid-run kill still reports everything observed so far).
Every emission also carries a `flight_recorder` section — the black-box
ring of recent dispatch/compile/breaker/mesh/phase events — so a
watchdog or SIGTERM flush names WHAT the run was doing when it died, and
`on_emit` hooks run inside emit() (even on the watchdog path, which
skips atexit) for per-run artifacts like compile_ledger.json.

Deliberately import-light (stdlib only): the emitter must work even when
jax fails to initialize — that failure is itself a reportable result.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import sys
import threading
import time


class PhaseTimeout(Exception):
    """Raised inside a phase body when its deadline expires."""


def _flight(kind: str, **fields) -> None:
    """Drop one event into the black-box flight recorder; a stripped-down
    standalone copy of this module (no package siblings) stays usable."""
    try:
        from .flight_recorder import record
    except ImportError:
        return
    record(kind, **fields)


class _Phase:
    __slots__ = ("rec",)

    def __init__(self, rec: dict):
        self.rec = rec

    def record(self, key: str, value) -> None:
        self.rec["rows"][key] = value

    def update(self, rows: dict) -> None:
        self.rec["rows"].update(rows)


class _PhaseContext:
    def __init__(self, emitter: "BenchEmitter", name: str, deadline_s):
        self._em = emitter
        self._name = name
        self._deadline = deadline_s
        self._prev_handler = None
        self._armed = False

    def __enter__(self) -> _Phase:
        rec = {"status": "running", "seconds": None, "rows": {}}
        if self._deadline is not None:
            rec["deadline_s"] = self._deadline
        self._em.phases[self._name] = rec
        self._rec = rec
        self._t0 = time.monotonic()
        _flight("bench_phase", phase=self._name, status="start")
        if self._deadline is not None and self._deadline > 0:
            try:  # SIGALRM only works on the main thread
                def _expire(signum, frame):
                    raise PhaseTimeout(self._name)

                self._prev_handler = signal.signal(signal.SIGALRM, _expire)
                signal.setitimer(signal.ITIMER_REAL, self._deadline)
                self._armed = True
            except (ValueError, AttributeError, OSError):
                pass
        return _Phase(rec)

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._armed:
            signal.setitimer(signal.ITIMER_REAL, 0.0)
            signal.signal(signal.SIGALRM, self._prev_handler)
        self._rec["seconds"] = round(time.monotonic() - self._t0, 3)
        try:
            if exc_type is None:
                self._rec["status"] = "ok"
                return False
            if issubclass(exc_type, PhaseTimeout):
                self._rec["status"] = "timeout"
                return True  # graceful skip: later phases still run
            if issubclass(exc_type, (KeyboardInterrupt, SystemExit)):
                self._rec["status"] = "interrupted"
                return False  # propagate; atexit/SIGTERM emit the partial doc
            self._rec["status"] = "error"
            self._rec["error"] = f"{exc_type.__name__}: {exc}"
            return True  # graceful skip
        finally:
            _flight("bench_phase", phase=self._name,
                    status=self._rec["status"], seconds=self._rec["seconds"])


class BenchEmitter:
    """Collects phases/sections and guarantees exactly one JSON emission.

    Usage:
        em = BenchEmitter("sets_per_sec", "sets/s", baseline=50_000.0)
        em.add_section("planner", lambda: pipeline.planner_snapshot())
        with em.phase("grouped", deadline_s=120) as ph:
            ph.record("sets_per_sec", rate)
        em.set_headline(rate)
        em.emit()
    """

    def __init__(
        self,
        metric: str,
        unit: str,
        baseline: float | None = None,
        details_path: str | None = None,
        stream=None,
        global_deadline_s: float | None = None,
    ):
        self.metric = metric
        self.unit = unit
        self.baseline = baseline
        self.details_path = details_path
        self.stream = stream if stream is not None else sys.stdout
        self.phases: dict[str, dict] = {}
        self.extra: dict = {}
        # zero-arg-or-doc callables run inside emit() after the details
        # file is written — the hook for per-run artifacts (e.g. the
        # compile ledger's compile_ledger.json) that must ALSO land on
        # the watchdog path, where os._exit(124) skips atexit
        self.on_emit: list = []
        self._sections: dict[str, object] = {}
        self._headline: float | None = None
        self._emitted = False
        self._lock = threading.Lock()
        # the black-box post-mortem rides every emission (including the
        # watchdog/SIGTERM partial flush): the last N flight-recorder
        # events name the exact kernel/phase a killed run wedged on
        try:
            from .flight_recorder import recorder as _recorder

            self._sections.setdefault(
                "flight_recorder", lambda: _recorder().dump(limit=64)
            )
        except ImportError:
            pass  # standalone copy without package siblings
        atexit.register(self._emit_atexit)
        self._install_sigterm()
        if global_deadline_s is not None and global_deadline_s > 0:
            t = threading.Thread(
                target=self._watchdog, args=(global_deadline_s,),
                name="bench-watchdog", daemon=True,
            )
            t.start()

    # -- recording ----------------------------------------------------------

    def phase(self, name: str, deadline_s: float | None = None) -> _PhaseContext:
        return _PhaseContext(self, name, deadline_s)

    def add_section(self, name: str, provider) -> None:
        """Register a section rendered at EMIT time — `provider` is a dict
        or a zero-arg callable returning one (callables see everything
        observed up to the kill, not just up to registration)."""
        self._sections[name] = provider

    def set_headline(self, value: float) -> None:
        self._headline = value

    # -- emission -----------------------------------------------------------

    def document(self) -> dict:
        phases_done = [p for p in self.phases.values() if p["status"] == "ok"]
        partial = len(phases_done) != len(self.phases) or not self.phases
        value = self._headline
        if value is None:
            # best observed per-phase throughput, else 0.0 — the document
            # must always carry a numeric headline (never `parsed: null`)
            rates = [
                v
                for p in self.phases.values()
                for k, v in p["rows"].items()
                if k.endswith("sets_per_sec") and isinstance(v, (int, float)) and v
            ]
            value = max(rates) if rates else 0.0
            partial = True
        doc = {
            "metric": self.metric,
            "value": round(float(value), 2),
            "unit": self.unit,
            "partial": partial,
            "phases": self.phases,
        }
        if self.baseline:
            doc["vs_baseline"] = round(float(value) / self.baseline, 4)
        for name, provider in self._sections.items():
            try:
                doc[name] = provider() if callable(provider) else provider
            except Exception as e:  # a broken section must not block emission
                doc[name] = {"error": str(e)}
        doc.update(self.extra)
        return doc

    def emit(self) -> dict | None:
        """Write the details file and print the one-line JSON document.
        Idempotent: only the first call (from any path — normal return,
        atexit, SIGTERM) emits."""
        with self._lock:
            if self._emitted:
                return None
            self._emitted = True
        doc = self.document()
        if self.details_path:
            try:
                with open(self.details_path, "w") as f:
                    json.dump(doc, f, indent=2)
            except OSError as e:
                print(f"bench: details write failed: {e}", file=sys.stderr)
        for hook in list(self.on_emit):
            try:
                hook(doc)
            except Exception as e:  # an artifact hook must not block emission
                print(f"bench: emit hook failed: {e}", file=sys.stderr)
        print(json.dumps(doc), file=self.stream, flush=True)
        return doc

    def _emit_atexit(self) -> None:
        self.emit()

    def _watchdog(self, budget_s: float) -> None:
        deadline = time.monotonic() + budget_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._emitted:
                    return
            time.sleep(min(1.0, max(0.01, deadline - time.monotonic())))
        with self._lock:
            done = self._emitted
        if done:
            return
        for rec in self.phases.values():
            if rec["status"] == "running":
                rec["status"] = "killed"
        # self-labelling marker: tools/bench_compare.py logs-and-skips a
        # timed-out round instead of treating its partial rates as a trend
        self.extra["timed_out"] = True
        self.extra["watchdog_fired_after_s"] = budget_s
        _flight("watchdog_fired", budget_s=budget_s)
        self.emit()
        os._exit(124)

    def _install_sigterm(self) -> None:
        try:
            prev = signal.getsignal(signal.SIGTERM)

            def _on_term(signum, frame):
                # mark the in-flight phase so the doc shows where the kill hit
                for rec in self.phases.values():
                    if rec["status"] == "running":
                        rec["status"] = "killed"
                self.extra["timed_out"] = True
                _flight("sigterm")
                self.emit()
                if callable(prev):
                    prev(signum, frame)
                else:
                    os._exit(143)

            signal.signal(signal.SIGTERM, _on_term)
        except (ValueError, OSError):
            pass  # not the main thread / unsupported platform
