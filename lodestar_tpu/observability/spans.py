"""Node-wide lifecycle tracing: slot-milestone spans from gossip wire to
head update.

PR 1 made the BLS verifier pipeline legible; this layer correlates
everything *around* it. Each gossip message (and each direct block /
segment import) becomes one **trace**: a root span plus nested child
spans for decode, the validation ladder, signature verification, fork
choice, and import/head-update. Traces carry a trace-id, spans carry a
parent-id, and the active span propagates through `contextvars` — so
spans opened in asyncio tasks (context is copied at task creation) and
in executor threads (explicit `context()` / `attach()` handoff, because
`run_in_executor` does NOT copy context) land in the same trace.

Finished traces go to a bounded ring buffer; the metrics server's
`/debug/traces` endpoint serves them as JSON, filterable by slot/root.
The structured logger injects the current trace-id into every record
(`utils/logger._TraceContextFilter`), and when the process-wide XLA
profiler switch (`observability.trace`) is active, each span also opens
a `jax.profiler.TraceAnnotation` — lifecycle spans then appear on the
same timeline as PR 1's device stage scopes.

Zero-cost when disabled: `span()`/`trace()` return one shared no-op
singleton (no allocation, no clock reads, no ring writes). Disable with
`LODESTAR_TPU_TRACE_LIFECYCLE=0` or `tracer.enabled = False`.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import os
import threading
import time
from collections import deque

from . import trace as _xla_trace

_log = logging.getLogger(__name__)

# milestones recorded against the start of a block's slot (reference:
# validator-monitor timeliness + the "delay from slot start" dashboards)
MILESTONES = (
    "block_received",   # gossip wire bytes decoded
    "validated",        # gossip validation ladder ACCEPTed
    "sigs_verified",    # block signature batch verdict resolved
    "imported",         # fork choice + caches + db updated
    "head_updated",     # the block became (part of) the canonical head
)

_current: "contextvars.ContextVar[Span | None]" = contextvars.ContextVar(
    "lodestar_tpu_lifecycle_span", default=None
)


class _NullSpan:
    """Shared do-nothing span: the disabled-mode fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate(self, **attrs):
        return self

    def event(self, name, **attrs):
        return self


_NULL = _NullSpan()


class Span:
    """One timed section of a trace. Context-manager only; entering sets
    the contextvar so nested `tracer.span()` calls become children."""

    __slots__ = (
        "tracer", "name", "trace_id", "span_id", "parent_id", "attrs",
        "events", "t0", "t0_wall", "duration_s", "status", "_root",
        "_token", "_annotation", "_records", "_rec_lock",
    )

    def __init__(self, tracer: "Tracer", name: str, root: "Span | None",
                 parent: "Span | None", attrs: dict):
        self.tracer = tracer
        self.name = name
        self.span_id = os.urandom(4).hex()
        self.attrs = dict(attrs)
        self.events: list[dict] = []
        self.duration_s = None
        self.status = "ok"
        self._token = None
        self._annotation = None
        if root is None:  # this span is a trace root
            self._root = self
            self.trace_id = os.urandom(8).hex()
            self.parent_id = None
            self._records: list[dict] = []
            self._rec_lock = threading.Lock()
        else:
            self._root = root
            self.trace_id = root.trace_id
            self.parent_id = parent.span_id if parent is not None else root.span_id
            self._records = root._records
            self._rec_lock = root._rec_lock
            # creation-time attrs promote like annotate() so child spans
            # make the whole trace filterable (slot learned at decode)
            for key in ("slot", "root", "kind"):
                if key in self.attrs and key not in root.attrs:
                    root.attrs[key] = self.attrs[key]

    # -- recording helpers ----------------------------------------------------

    def annotate(self, **attrs) -> "Span":
        """Attach attributes; `slot` / `root` / `kind` also promote to the
        trace root so the whole trace is filterable by them."""
        self.attrs.update(attrs)
        root = self._root
        if root is not self:
            for key in ("slot", "root", "kind"):
                if key in attrs and key not in root.attrs:
                    root.attrs[key] = attrs[key]
        return self

    def event(self, name: str, **attrs) -> "Span":
        ev = {"name": name, "t_s": round(time.monotonic() - self._root.t0, 6)}
        if attrs:
            ev.update(attrs)
        self.events.append(ev)
        return self

    # -- lifecycle ------------------------------------------------------------

    def __enter__(self) -> "Span":
        self.t0 = time.monotonic()
        if self._root is self:
            self.t0_wall = time.time()
        self._token = _current.set(self)
        if _xla_trace.profiling_active():
            # link onto the XLA timeline next to PR 1's device stage scopes
            self._annotation = _xla_trace.annotation(f"lifecycle/{self.name}")
            self._annotation.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._annotation is not None:
            self._annotation.__exit__(exc_type, exc, tb)
            self._annotation = None
        if self._token is not None:
            try:
                _current.reset(self._token)
            except ValueError:
                # exited in a different context than entered (cross-thread
                # misuse) — clear rather than corrupt the other context
                _current.set(None)
            self._token = None
        self.duration_s = time.monotonic() - self.t0
        if exc_type is not None:
            self.status = "error"
            self.attrs["error"] = f"{exc_type.__name__}: {exc}"
        rec = {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_s": round(self.t0 - self._root.t0, 6),
            "duration_s": round(self.duration_s, 6),
        }
        if self.attrs:
            rec["attrs"] = dict(self.attrs)
        if self.events:
            rec["events"] = list(self.events)
        if self.status != "ok":
            rec["status"] = self.status
        with self._rec_lock:
            self._records.append(rec)
        if self._root is self:
            self.tracer._finish(self)
        return False


class Tracer:
    """Trace factory + bounded retention ring.

    `trace(name)` opens a new root; `span(name)` nests under the current
    span (opening a fresh root when none is active, so direct imports —
    range sync, REST publish — still produce one trace per block).
    """

    def __init__(self, capacity: int = 256, enabled: bool | None = None):
        if enabled is None:
            from ..utils.env import env_bool

            enabled = env_bool("LODESTAR_TPU_TRACE_LIFECYCLE")
        self.enabled = enabled
        self.capacity = capacity
        self._ring: deque[dict] = deque(maxlen=capacity)  # guarded-by: _lock
        self._lock = threading.Lock()
        self.completed_total = 0  # guarded-by: _lock
        # callbacks(trace_doc) — node wiring increments the prometheus
        # lifecycle-trace counter here
        self.on_finish: list = []

    # -- span creation --------------------------------------------------------

    def trace(self, name: str, **attrs):
        if not self.enabled:
            return _NULL
        return Span(self, name, root=None, parent=None, attrs=attrs)

    def span(self, name: str, **attrs):
        if not self.enabled:
            return _NULL
        cur = _current.get()
        if cur is None or isinstance(cur, _NullSpan):
            return Span(self, name, root=None, parent=None, attrs=attrs)
        return Span(self, name, root=cur._root, parent=cur, attrs=attrs)

    # -- cross-thread propagation ---------------------------------------------

    def context(self) -> "Span | None":
        """The live span to hand to another thread (run_in_executor and
        ThreadPoolExecutor do NOT copy contextvars)."""
        if not self.enabled:
            return None
        return _current.get()

    @contextlib.contextmanager
    def attach(self, span: "Span | None"):
        """Re-establish `span` as current inside a worker thread."""
        if span is None or isinstance(span, _NullSpan) or not self.enabled:
            yield None
            return
        token = _current.set(span)
        try:
            yield span
        finally:
            try:
                _current.reset(token)
            except ValueError:
                _current.set(None)

    # -- in-flight annotation -------------------------------------------------

    def annotate(self, **attrs) -> None:
        cur = _current.get()
        if cur is not None:
            cur.annotate(**attrs)

    def event(self, name: str, **attrs) -> None:
        cur = _current.get()
        if cur is not None:
            cur.event(name, **attrs)

    def current_trace_id(self) -> str | None:
        cur = _current.get()
        return None if cur is None else cur.trace_id

    # -- retention / query ----------------------------------------------------

    def _finish(self, root: Span) -> None:
        with root._rec_lock:
            spans = sorted(root._records, key=lambda r: r["start_s"])
        doc = {
            "trace_id": root.trace_id,
            "name": root.name,
            "ts": round(root.t0_wall, 3),
            "duration_s": round(root.duration_s, 6),
            "slot": root.attrs.get("slot"),
            "root": root.attrs.get("root"),
            "spans": spans,
        }
        if root.attrs:
            doc["attrs"] = dict(root.attrs)
        with self._lock:
            self._ring.append(doc)
            self.completed_total += 1
        for cb in self.on_finish:
            try:
                cb(doc)
            except Exception:
                # observers must never break the traced path
                _log.debug("on_finish observer failed", exc_info=True)

    def traces(self, slot=None, root=None, limit: int = 64) -> list[dict]:
        """Recent traces, newest first, optionally filtered by slot or
        block root (hex, with or without 0x)."""
        if root is not None:
            root = root.lower().removeprefix("0x")
        with self._lock:
            docs = list(self._ring)
        out = []
        for doc in reversed(docs):
            if slot is not None and doc.get("slot") != slot:
                continue
            if root is not None:
                have = doc.get("root")
                if not have or have.lower().removeprefix("0x") != root:
                    continue
            out.append(doc)
            if len(out) >= limit:
                break
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


# the process-wide default: node services import this instance so every
# layer lands in one ring (tests build their own Tracer for isolation)
tracer = Tracer()


def span(name: str, **attrs):
    return tracer.span(name, **attrs)


def current_trace_id() -> str | None:
    return tracer.current_trace_id()


def record_slot_milestone(chain, milestone: str, slot: int) -> float:
    """Observe `milestone` for `slot` as a delay from the slot's start:
    the histogram + last-value gauge on the chain's metrics bundle (when
    wired), plus an event on the current trace. Returns the delay."""
    delay = chain.clock.time_fn() - chain.clock.time_at_slot(int(slot))
    m = getattr(chain, "metrics", None)
    if m is not None and hasattr(m, "slot_milestone_seconds"):
        m.slot_milestone_seconds.observe(delay, milestone=milestone)
        m.slot_milestone_last.set(delay, milestone=milestone)
    tracer.event(milestone, slot=int(slot), delay_s=round(delay, 4))
    return delay
