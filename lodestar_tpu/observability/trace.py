"""JAX trace integration: named scopes + profiler lifecycle.

Two distinct scope kinds (both no-ops when jax is unavailable, so host
code can annotate unconditionally):

- `annotation(label)` — host-side `jax.profiler.TraceAnnotation`: marks a
  wall-clock span on the profiler timeline (dispatch, marshal, resolve).
- `named_scope(label)` — trace-time `jax.named_scope`: tags the HLO ops
  emitted under it, so device stages (MSM planes, Miller loop, final
  exponentiation) are attributable inside ONE fused XLA dispatch where
  host timers cannot see.

`start_profiling`/`stop_profiling` are the single process-wide switch —
shared by `DeviceBlsVerifier` (LODESTAR_TPU_PROFILE auto-start) and the
metrics server's `/profiler/start|stop` endpoints so neither can
double-start the XLA trace.
"""

from __future__ import annotations

import contextlib
import logging
import threading

_lock = threading.Lock()
_active_dir: str | None = None


def annotation(label: str):
    """Host-side profiler span; nullcontext when jax is unavailable."""
    try:
        import jax

        return jax.profiler.TraceAnnotation(label)
    except Exception:
        return contextlib.nullcontext()


def named_scope(label: str):
    """Trace-time HLO name scope; nullcontext when jax is unavailable."""
    try:
        import jax

        return jax.named_scope(label)
    except Exception:
        return contextlib.nullcontext()


def profiling_active() -> bool:
    return _active_dir is not None


def start_profiling(trace_dir: str | None = None) -> str | None:
    """Start an XLA profiler trace into `trace_dir`; returns the directory
    actually used, or None if a trace is already running or jax/profiler
    is unavailable. Idempotent under races (one trace at a time)."""
    global _active_dir
    from ..utils.env import env_str

    trace_dir = (
        trace_dir or env_str("LODESTAR_TPU_PROFILE") or "/tmp/lodestar_tpu_profile"
    )
    with _lock:
        if _active_dir is not None:
            return None
        try:
            import jax

            jax.profiler.start_trace(trace_dir)
        except Exception:
            return None
        _active_dir = trace_dir
        return trace_dir


def stop_profiling() -> str | None:
    """Stop the running trace; returns its directory, or None if no trace
    was running."""
    global _active_dir
    with _lock:
        if _active_dir is None:
            return None
        stopped, _active_dir = _active_dir, None
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:
            # the switch still resets: a profiler that died mid-trace must
            # not wedge the process-wide start/stop toggle
            logging.getLogger(__name__).debug("stop_trace failed: %s", e)
        return stopped
