"""Pipeline telemetry for the TPU BLS verifier (SURVEY §5 observability).

The reference ships prom-client metrics + the `lodestar_bls_thread_pool`
Grafana dashboard; this package is the device-pipeline equivalent,
threaded through the verifier stack:

- `stages` — stage timers (monotonic, `block_until_ready`-bounded),
  planner-decision counters, cache hit counters, flush/queue gauges,
  and the device-busy-fraction sampler, all backed by
  `metrics.registry` families so they render on `/metrics`.
- `trace` — JAX profiler integration: `TraceAnnotation` host scopes,
  `named_scope` device-graph scopes (no-ops without jax), and the
  start/stop profiling switch shared by the verifier and the
  `/profiler/*` endpoints on the metrics server.
- `stage_profile` — per-stage sub-kernel timing (the tools/
  kernel_profile methodology as a library) feeding the same stage
  histogram; used by bench for the stage-time breakdown.
- `bench_emit` — structured bench emitter: per-phase deadlines with
  graceful skip, atexit/SIGTERM JSON flush, so a benchmark run ALWAYS
  ends in one parseable JSON document (kills the `parsed: null`
  failure mode of BENCH_r05).
- `spans` — node-wide lifecycle tracing (PR 2): trace-id/parent-id
  spans with contextvar propagation threaded from gossip decode through
  validation, BLS verify, fork choice and head update; ring-buffer
  retention served by the metrics server's `/debug/traces`; slot-
  milestone delay metrics.
- `compile_ledger` — process-wide XLA compile accounting: every compile
  at the jit/shard_map seams is a measured event (kernel, signature,
  duration, persistent-cache hit/miss) feeding the
  `lodestar_tpu_compile_*` families, `/debug/compiles`, and the
  per-run `compile_ledger.json` artifact; plus the startup timeline
  whose `serving_ready_seconds` gauge is the cold-start SLO.
- `flight_recorder` — bounded black-box ring of dispatch/compile/
  breaker/mesh/phase events, dumped into every bench emission (watchdog
  and SIGTERM paths included) so an rc=124 round leaves a post-mortem.
- `slo` — declarative SLO engine (PR 16): objectives from the committed
  `dashboards/slo_rules.json` evaluated in-process over PipelineMetrics
  with Google-SRE error budgets and multi-window (5 m/1 h) burn-rate
  states; exports `lodestar_slo_*`, serves `/debug/slo`, embeds in
  bench emissions and gates `tools/bench_compare.py`.
- `device_ledger` — device-time & memory ledger (PR 16): busy/idle/
  overlap device-seconds attributed by lane x kernel x chip from the
  lane dispatcher's flush worker and the mesh dispatch hooks, plus a
  low-rate jax memory sampler with per-chip high watermarks; serves
  `/debug/device` and lands in the rc=124 post-mortem.
"""

from .stages import (  # noqa: F401
    PLANNER_PATHS,
    STAGES,
    PipelineMetrics,
    create_pipeline_metrics,
    default_pipeline,
)
from .trace import (  # noqa: F401
    annotation,
    named_scope,
    profiling_active,
    start_profiling,
    stop_profiling,
)
from .bench_emit import BenchEmitter, PhaseTimeout  # noqa: F401
from .compile_ledger import (  # noqa: F401
    CompileLedger,
    StartupTimeline,
    ledger,
    timeline,
)
from .flight_recorder import FlightRecorder, recorder  # noqa: F401
from .slo import SloEngine  # noqa: F401
from .device_ledger import DeviceLedger  # noqa: F401
from .spans import (  # noqa: F401
    MILESTONES,
    Tracer,
    current_trace_id,
    record_slot_milestone,
    tracer,
)
