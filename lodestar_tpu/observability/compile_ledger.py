"""Process-wide XLA compile accounting + the cold-start timeline.

Compilation is the tax that killed both red driver rounds (ROADMAP items
1 and 5): the deep pairing kernels take minutes each on the CPU backend,
and until now that time was invisible — it only surfaced as a watchdog
rc=124. This module makes every compile a first-class, measured event:

- `CompileLedger.wrap(fn, kernel)` wraps a jitted callable at the
  construction seam (`BatchVerifier.__init__`, the mesh dispatcher's
  sharded-verifier cache, `stage_profile`). The FIRST call per
  (kernel, signature) is timed wall-clock — jax compiles synchronously
  on the first dispatch of a new shape, and execution is async, so the
  first-call wall time is dominated by trace+lower+compile. Every later
  call goes straight through with zero overhead beyond one set lookup.
- Each event records the kernel name, the shape/dtype signature key
  (or an explicit `static_key` like the mesh's `shape@chips` string),
  the device-set fingerprint, the duration, and the persistent-cache
  outcome: `miss` (a new entry appeared in the cache dir), `hit` (cache
  enabled, no new entry), `off` (no cache dir configured). Caveat: jax
  only persists compiles above `jax_persistent_cache_min_compile_time_
  secs` (default 1 s), so sub-second kernels read as `hit` — those cost
  ~nothing either way, and the minutes-long production kernels this
  ledger exists for are always persisted.
- Events tick the `lodestar_tpu_compile_*` families on every live
  `PipelineMetrics` (instances attach themselves via weakref at
  construction — node registry and the bench/tools default pipeline
  both see the same ledger), feed the flight recorder (a `compile_start`
  event lands BEFORE the call, so a wedged compile is identifiable in a
  watchdog post-mortem as started-but-unfinished), serve
  `/debug/compiles`, and persist as `compile_ledger.json` per
  bench/warmup run.

`StartupTimeline` is the getting-to-serving half: `mark(phase)` records
seconds since PROCESS start (anchored via /proc/self/stat field 22 so
python import time is included; falls back to module-import time) into
the `lodestar_tpu_startup_phase_seconds` gauge, and
`mark_serving_ready()` sets the `lodestar_tpu_serving_ready_seconds`
SLO gauge — the ROADMAP item-5 number, measured cold vs warm
`.jax_cache` (the ledger's cache section labels which one a run was).
"""

from __future__ import annotations

import functools
import json
import os
import sys
import threading
import time
import weakref

from . import flight_recorder

__all__ = [
    "CompileLedger",
    "StartupTimeline",
    "ledger",
    "timeline",
]

MAX_LEDGER_EVENTS = 512

_IMPORT_MONOTONIC = time.monotonic()


def _shape_key(args, kwargs) -> str:
    """Positional/keyword argument signature: dtype[shape] per array arg
    (anything without `.shape` contributes its type name). Matches what
    jax re-traces on, so one key ≈ one compiled executable."""
    parts = []
    for a in list(args) + [v for _, v in sorted(kwargs.items())]:
        shape = getattr(a, "shape", None)
        if shape is not None:
            dims = "x".join(str(d) for d in shape)
            parts.append(f"{getattr(a, 'dtype', '?')}[{dims}]")
        else:
            parts.append(type(a).__name__)
    return ",".join(parts)


_device_key_cache: str | None = None


def _device_key() -> str:
    """`<platform>x<count>` fingerprint of the visible device set, cached
    after the first (backend-initializing) lookup."""
    global _device_key_cache
    if _device_key_cache is None:
        try:
            import jax

            devices = jax.devices()
            _device_key_cache = f"{devices[0].platform}x{len(devices)}"
        except (ImportError, RuntimeError):
            _device_key_cache = "nodevice"
    return _device_key_cache


def _cache_dir() -> str | None:
    """The live persistent-cache directory, or None when disabled/unset."""
    try:
        import jax

        return getattr(jax.config, "jax_compilation_cache_dir", None) or None
    except ImportError:
        return None


def _cache_listing(cache_dir: str | None) -> frozenset:
    if not cache_dir:
        return frozenset()
    try:
        return frozenset(os.listdir(cache_dir))
    except OSError:
        return frozenset()


class CompileLedger:
    """Append-only (bounded) record of compile events + the wrap seam."""

    def __init__(self, max_events: int = MAX_LEDGER_EVENTS):
        self._lock = threading.Lock()
        self._max_events = max_events
        self._events: list[dict] = []  # guarded-by: _lock
        self._seen: set = set()  # guarded-by: _lock
        self._cumulative_s = 0.0  # guarded-by: _lock
        self._counts = {"hit": 0, "miss": 0, "off": 0}  # guarded-by: _lock
        self._pipelines: list = []  # guarded-by: _lock
        self._last_prune: dict | None = None  # guarded-by: _lock
        self._entries_at_start: int | None = None  # guarded-by: _lock

    # -- pipeline fan-out ---------------------------------------------------

    def attach(self, pipeline) -> None:
        """Weakref-register a PipelineMetrics so ledger events tick its
        `lodestar_tpu_compile_*` families (PipelineMetrics.__init__ calls
        this; dead refs are compacted on every attach)."""
        with self._lock:
            self._pipelines = [r for r in self._pipelines if r() is not None]
            self._pipelines.append(weakref.ref(pipeline))

    def pipelines(self) -> list:
        """Every still-live attached PipelineMetrics."""
        with self._lock:
            refs = list(self._pipelines)
        return [p for p in (r() for r in refs) if p is not None]

    # -- the wrap seam ------------------------------------------------------

    def wrap(self, fn, kernel: str, static_key: str | None = None):
        """Wrap a jitted callable: the first call per (kernel, signature)
        is timed and recorded as one compile event; later calls pass
        straight through. `static_key` replaces the per-call shape key
        when the caller already knows the one signature the callable will
        ever see (the mesh's per-(shape, chips) verifiers)."""

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            key = static_key if static_key is not None else _shape_key(args, kwargs)
            with self._lock:
                fresh = (kernel, key) not in self._seen
                if fresh:
                    # marked BEFORE the call: a concurrent second caller
                    # must not double-record, and a wedged compile must
                    # not re-record after a watchdog restart of the phase
                    self._seen.add((kernel, key))
            if not fresh:
                return fn(*args, **kwargs)
            return self._timed_first_call(fn, kernel, key, args, kwargs)

        wrapped.__compile_ledger_kernel__ = kernel
        return wrapped

    def _timed_first_call(self, fn, kernel, key, args, kwargs):
        cache_dir = _cache_dir()
        self._ensure_cache_baseline(cache_dir)
        before = _cache_listing(cache_dir)
        # compile_start lands in the flight recorder BEFORE the call: a
        # compile that wedges past the watchdog is identifiable in the
        # post-mortem as started-but-unfinished
        flight_recorder.record("compile_start", kernel=kernel, key=key)
        t0 = time.monotonic()
        out = fn(*args, **kwargs)
        duration_s = time.monotonic() - t0
        if cache_dir is None:
            cache = "off"
        elif _cache_listing(cache_dir) - before:
            cache = "miss"
        else:
            cache = "hit"
        self.record(kernel, key, duration_s, cache)
        return out

    # -- recording ----------------------------------------------------------

    def record(self, kernel: str, key: str, duration_s: float,
               cache: str = "off") -> dict:
        """Append one compile event and fan it out (metrics + flight
        recorder). Public so seams that time compiles themselves (tests,
        AOT loaders) can feed the same ledger."""
        event = {
            "kernel": kernel,
            "key": key,
            "device_set": _device_key(),
            "seconds": round(duration_s, 4),
            "cache": cache,
        }
        with self._lock:
            self._events.append(event)
            if len(self._events) > self._max_events:
                del self._events[0]
            self._cumulative_s += duration_s
            self._counts[cache] = self._counts.get(cache, 0) + 1
            cumulative = self._cumulative_s
        flight_recorder.record(
            "compile_end", kernel=kernel, key=key,
            seconds=event["seconds"], cache=cache,
        )
        for p in self.pipelines():
            p.compile_event(kernel, cache, duration_s, cumulative)
        return event

    def note_prune(self, result: dict) -> None:
        """Record the last compile-cache prune (tools/prune_compile_cache)
        so the ledger artifact carries it; ticks the cache gauges on every
        live pipeline."""
        remaining = result.get(
            "entries_remaining",
            result.get("entries", 0) - len(result.get("removed", ())),
        )
        rec = {
            "entries": result.get("entries", 0),
            "entries_remaining": remaining,
            "removed": len(result.get("removed", ())),
            "removed_bytes": result.get("removed_bytes", 0),
            "total_bytes": result.get("total_bytes", 0),
            "unix_time": round(time.time(), 1),
        }
        with self._lock:
            self._last_prune = rec
        flight_recorder.record(
            "cache_prune",
            removed=rec["removed"], removed_bytes=rec["removed_bytes"],
        )
        for p in self.pipelines():
            p.cache_pruned(rec["removed_bytes"], remaining)

    # -- export -------------------------------------------------------------

    def _ensure_cache_baseline(self, cache_dir: str | None) -> None:
        """Record the cache-dir entry count once, before the first compile
        touches it — the cold/warm classifier for the serving-ready SLO."""
        if cache_dir is None:
            return
        with self._lock:
            known = self._entries_at_start is not None
        if known:
            return
        n = len(_cache_listing(cache_dir))
        with self._lock:
            if self._entries_at_start is None:
                self._entries_at_start = n

    def snapshot(self) -> dict:
        """The `/debug/compiles` + bench-section document."""
        cache_dir = _cache_dir()
        self._ensure_cache_baseline(cache_dir)
        device = _device_key()
        entries_now = len(_cache_listing(cache_dir)) if cache_dir else None
        with self._lock:
            events = list(self._events)
            doc = {
                "device_set": device,
                "event_count": len(events),
                "cumulative_seconds": round(self._cumulative_s, 4),
                "cache": {
                    "dir": cache_dir,
                    "entries_at_start": self._entries_at_start,
                    "entries_now": entries_now,
                    "hits": self._counts.get("hit", 0),
                    "misses": self._counts.get("miss", 0),
                    "uncached": self._counts.get("off", 0),
                },
                "events": events,
            }
            last_prune = self._last_prune
        if cache_dir is None:
            state = "off"
        elif not doc["cache"]["entries_at_start"]:
            state = "cold"
        else:
            state = "warm"
        doc["cache"]["state"] = state
        if last_prune is not None:
            doc["last_prune"] = dict(last_prune)
        return doc

    def write_artifact(self, path: str) -> str | None:
        """Persist the snapshot as `compile_ledger.json`; never raises —
        the artifact write must not block a bench emission."""
        try:
            with open(path, "w") as f:
                json.dump(self.snapshot(), f, indent=2)
            return path
        except OSError as e:
            print(f"compile_ledger: artifact write failed: {e}",
                  file=sys.stderr)
            return None


_ledger: CompileLedger | None = None
_ledger_lock = threading.Lock()


def ledger() -> CompileLedger:
    """The process-wide ledger every compile seam records into."""
    global _ledger
    with _ledger_lock:
        if _ledger is None:
            _ledger = CompileLedger()
        return _ledger


# -- startup timeline -------------------------------------------------------


def _process_start_monotonic() -> float:
    """The monotonic timestamp of PROCESS start (so interpreter + import
    time count toward the serving-ready SLO): /proc/self/stat field 22
    (starttime, clock ticks since boot) against /proc/uptime. Falls back
    to this module's import time off Linux."""
    try:
        with open("/proc/self/stat", "rb") as f:
            stat = f.read().decode("ascii", "replace")
        # fields after the parenthesized comm (which may contain spaces);
        # starttime is overall field 22 == index 19 of the tail
        tail = stat.rsplit(")", 1)[1].split()
        start_ticks = float(tail[19])
        hz = os.sysconf("SC_CLK_TCK")
        with open("/proc/uptime") as f:
            uptime_s = float(f.read().split()[0])
        age_s = uptime_s - start_ticks / hz
        if age_s < 0:
            return _IMPORT_MONOTONIC
        return time.monotonic() - age_s
    except (OSError, ValueError, IndexError):
        return _IMPORT_MONOTONIC


class StartupTimeline:
    """Phase marks measured from process start; feeds the startup-phase
    and serving-ready gauges on every live pipeline."""

    def __init__(self):
        self._lock = threading.Lock()
        self._start = _process_start_monotonic()
        self._marks: list[dict] = []  # guarded-by: _lock
        self._serving_ready_s: float | None = None  # guarded-by: _lock

    def mark(self, phase: str) -> float:
        """Record `phase` at now-since-process-start seconds."""
        t_s = time.monotonic() - self._start
        with self._lock:
            self._marks.append({"phase": phase, "t_s": round(t_s, 3)})
        flight_recorder.record("startup", phase=phase,
                               since_start_s=round(t_s, 3))
        for p in ledger().pipelines():
            p.startup_phase(phase, t_s)
        return t_s

    def mark_serving_ready(self) -> float:
        """The SLO mark: the process can serve its production dispatch
        ladder from here on (node init returned / headline kernel warm /
        warmup ladder complete)."""
        t_s = self.mark("serving_ready")
        with self._lock:
            self._serving_ready_s = t_s
        for p in ledger().pipelines():
            p.serving_ready(t_s)
        return t_s

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "marks": list(self._marks),
                "serving_ready_s": (
                    round(self._serving_ready_s, 3)
                    if self._serving_ready_s is not None
                    else None
                ),
            }


_timeline: StartupTimeline | None = None
_timeline_lock = threading.Lock()


def timeline() -> StartupTimeline:
    """The process-wide startup timeline."""
    global _timeline
    with _timeline_lock:
        if _timeline is None:
            _timeline = StartupTimeline()
        return _timeline
