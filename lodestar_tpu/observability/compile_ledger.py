"""Process-wide XLA compile accounting + the cold-start timeline.

Compilation is the tax that killed both red driver rounds (ROADMAP items
1 and 5): the deep pairing kernels take minutes each on the CPU backend,
and until now that time was invisible — it only surfaced as a watchdog
rc=124. This module makes every compile a first-class, measured event:

- `CompileLedger.wrap(fn, kernel)` wraps a jitted callable at the
  construction seam (`BatchVerifier.__init__`, the mesh dispatcher's
  sharded-verifier cache, `stage_profile`). The FIRST call per
  (kernel, signature) is timed wall-clock — jax compiles synchronously
  on the first dispatch of a new shape, and execution is async, so the
  first-call wall time is dominated by trace+lower+compile. Every later
  call goes straight through with zero overhead beyond one set lookup.
- Each event records the kernel name, the shape/dtype signature key
  (or an explicit `static_key` like the mesh's `shape@chips` string),
  the device-set fingerprint, the duration, and the persistent-cache
  outcome: `miss` (a new entry appeared in the cache dir), `hit` (cache
  enabled, no new entry), `off` (no cache dir configured). Caveat: jax
  only persists compiles above `jax_persistent_cache_min_compile_time_
  secs` (default 1 s), so sub-second kernels read as `hit` — those cost
  ~nothing either way, and the minutes-long production kernels this
  ledger exists for are always persisted.
- Events tick the `lodestar_tpu_compile_*` families on every live
  `PipelineMetrics` (instances attach themselves via weakref at
  construction — node registry and the bench/tools default pipeline
  both see the same ledger), feed the flight recorder (a `compile_start`
  event lands BEFORE the call, so a wedged compile is identifiable in a
  watchdog post-mortem as started-but-unfinished), serve
  `/debug/compiles`, and persist as `compile_ledger.json` per
  bench/warmup run.

`StartupTimeline` is the getting-to-serving half: `mark(phase)` records
seconds since PROCESS start (anchored via /proc/self/stat field 22 so
python import time is included; falls back to module-import time) into
the `lodestar_tpu_startup_phase_seconds` gauge, and
`mark_serving_ready()` sets the `lodestar_tpu_serving_ready_seconds`
SLO gauge — the ROADMAP item-5 number, measured cold vs warm
`.jax_cache` (the ledger's cache section labels which one a run was).
"""

from __future__ import annotations

import functools
import json
import os
import sys
import threading
import time
import weakref

from . import flight_recorder

__all__ = [
    "CompileLedger",
    "StartupTimeline",
    "ledger",
    "timeline",
]

MAX_LEDGER_EVENTS = 512

_IMPORT_MONOTONIC = time.monotonic()


def _shape_key(args, kwargs) -> str:
    """Positional/keyword argument signature: dtype[shape] per array arg
    (anything without `.shape` contributes its type name). Matches what
    jax re-traces on, so one key ≈ one compiled executable."""
    parts = []
    for a in list(args) + [v for _, v in sorted(kwargs.items())]:
        shape = getattr(a, "shape", None)
        if shape is not None:
            dims = "x".join(str(d) for d in shape)
            parts.append(f"{getattr(a, 'dtype', '?')}[{dims}]")
        else:
            parts.append(type(a).__name__)
    return ",".join(parts)


_device_key_cache: str | None = None


def _device_key() -> str:
    """`<platform>x<count>` fingerprint of the visible device set, cached
    after the first (backend-initializing) lookup."""
    global _device_key_cache
    if _device_key_cache is None:
        try:
            import jax

            devices = jax.devices()
            _device_key_cache = f"{devices[0].platform}x{len(devices)}"
        except (ImportError, RuntimeError):
            _device_key_cache = "nodevice"
    return _device_key_cache


def _cache_dir() -> str | None:
    """The live persistent-cache directory, or None when disabled/unset."""
    try:
        import jax

        return getattr(jax.config, "jax_compilation_cache_dir", None) or None
    except ImportError:
        return None


def _cache_listing(cache_dir: str | None) -> frozenset:
    if not cache_dir:
        return frozenset()
    try:
        return frozenset(os.listdir(cache_dir))
    except OSError:
        return frozenset()


class CompileLedger:
    """Append-only (bounded) record of compile events + the wrap seam."""

    def __init__(self, max_events: int = MAX_LEDGER_EVENTS):
        self._lock = threading.Lock()
        self._max_events = max_events
        self._events: list[dict] = []  # guarded-by: _lock
        self._seen: set = set()  # guarded-by: _lock
        self._cumulative_s = 0.0  # guarded-by: _lock
        self._counts = {"hit": 0, "miss": 0, "off": 0}  # guarded-by: _lock
        self._pipelines: list = []  # guarded-by: _lock
        self._last_prune: dict | None = None  # guarded-by: _lock
        self._entries_at_start: int | None = None  # guarded-by: _lock
        # AOT executable overrides (ISSUE 19): (kernel, key) -> loaded
        # `jax.stages.Compiled` deserialized from ops/aot_store — once an
        # entry is here, dispatches bypass the jitted fn (and therefore
        # XLA trace/compile) entirely
        self._aot_execs: dict = {}  # guarded-by: _lock
        self._aot_counts: dict = {}  # guarded-by: _lock
        self._aot_events: list[dict] = []  # guarded-by: _lock
        self._aot_marked = False  # guarded-by: _lock (aot_load phase once)

    # -- pipeline fan-out ---------------------------------------------------

    def attach(self, pipeline) -> None:
        """Weakref-register a PipelineMetrics so ledger events tick its
        `lodestar_tpu_compile_*` families (PipelineMetrics.__init__ calls
        this; dead refs are compacted on every attach)."""
        with self._lock:
            self._pipelines = [r for r in self._pipelines if r() is not None]
            self._pipelines.append(weakref.ref(pipeline))

    def pipelines(self) -> list:
        """Every still-live attached PipelineMetrics."""
        with self._lock:
            refs = list(self._pipelines)
        return [p for p in (r() for r in refs) if p is not None]

    # -- the wrap seam ------------------------------------------------------

    def wrap(self, fn, kernel: str, static_key: str | None = None):
        """Wrap a jitted callable: the first call per (kernel, signature)
        is timed and recorded as one compile event; later calls pass
        straight through. `static_key` replaces the per-call shape key
        when the caller already knows the one signature the callable will
        ever see (the mesh's per-(shape, chips) verifiers)."""

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            key = static_key if static_key is not None else _shape_key(args, kwargs)
            with self._lock:
                # AOT override first: a loaded executable serves every
                # call for its signature without touching the jitted fn
                exec_ = self._aot_execs.get((kernel, key))
                fresh = exec_ is None and (kernel, key) not in self._seen
                if fresh:
                    # marked BEFORE the call: a concurrent second caller
                    # must not double-record, and a wedged compile must
                    # not re-record after a watchdog restart of the phase
                    self._seen.add((kernel, key))
            if exec_ is not None:
                return exec_(*args, **kwargs)
            if not fresh:
                return fn(*args, **kwargs)
            return self._timed_first_call(fn, kernel, key, args, kwargs)

        wrapped.__compile_ledger_kernel__ = kernel
        return wrapped

    def _timed_first_call(self, fn, kernel, key, args, kwargs):
        # load-before-compile (ISSUE 19): a persisted AOT executable for
        # this exact signature + build fingerprint replaces the compile;
        # every store failure mode degrades to the normal JIT path below
        exec_ = self._aot_attempt(kernel, key)
        if exec_ is not None:
            return exec_(*args, **kwargs)
        cache_dir = _cache_dir()
        self._ensure_cache_baseline(cache_dir)
        before = _cache_listing(cache_dir)
        # compile_start lands in the flight recorder BEFORE the call: a
        # compile that wedges past the watchdog is identifiable in the
        # post-mortem as started-but-unfinished
        flight_recorder.record("compile_start", kernel=kernel, key=key)
        t0 = time.monotonic()
        out = self._compile_maybe_export(fn, kernel, key, args, kwargs)
        duration_s = time.monotonic() - t0
        if cache_dir is None:
            cache = "off"
        elif _cache_listing(cache_dir) - before:
            cache = "miss"
        else:
            cache = "hit"
        self.record(kernel, key, duration_s, cache)
        return out

    def _compile_maybe_export(self, fn, kernel, key, args, kwargs):
        """The first call itself. In producer mode (LODESTAR_TPU_AOT_EXPORT)
        a lowerable fn compiles via `lower().compile()` — one compile, the
        same one the plain call would do — and the executable is
        serialized into the store before dispatching. Any export failure
        degrades to the plain call: export must never fail a dispatch."""
        from ..ops import aot_store

        st = aot_store.store() if aot_store.export_enabled() else None
        if st is None or not hasattr(fn, "lower"):
            return fn(*args, **kwargs)
        try:
            compiled = fn.lower(*args, **kwargs).compile()
        except Exception as e:
            # e.g. a ledger-wrapped callable that isn't a jit entry after
            # all; the plain call still compiles + serves
            flight_recorder.record(
                "aot_export_failed", kernel=kernel, key=key,
                stage="lower", error=repr(e)[:200],
            )
            print(f"aot_store: lower/compile for export failed "
                  f"({kernel}:{key}): {e!r}", file=sys.stderr)
            return fn(*args, **kwargs)
        t0 = time.monotonic()
        try:
            st.save(kernel, key, compiled)
        except aot_store.AotError as e:
            flight_recorder.record(
                "aot_export_failed", kernel=kernel, key=key,
                stage="save", error=str(e)[:200],
            )
            print(f"aot_store: export failed ({kernel}:{key}): {e}",
                  file=sys.stderr)
        else:
            self.note_aot(kernel, key, "export",
                          seconds=time.monotonic() - t0)
        with self._lock:
            # later calls dispatch the compiled executable directly —
            # identical semantics, and it keeps the exported artifact an
            # exact record of what this process served
            self._aot_execs[(kernel, key)] = compiled
        return compiled(*args, **kwargs)

    # -- AOT store (ISSUE 19) ----------------------------------------------

    def _aot_attempt(self, kernel: str, key: str):
        """Try to serve (kernel, key) from the AOT store. Returns the
        loaded executable (memoized into the override map) or None; every
        failure mode is counted + flight-recorded, never raised."""
        from ..ops import aot_store

        st = aot_store.store() if aot_store.load_enabled() else None
        if st is None:
            return None
        t0 = time.monotonic()
        try:
            exec_ = st.load(kernel, key)
        except aot_store.AotMiss:
            self.note_aot(kernel, key, "miss")
            return None
        except aot_store.AotVersionMismatch as e:
            self.note_aot(kernel, key, "version_mismatch", detail=str(e))
            return None
        except aot_store.AotError as e:
            self.note_aot(kernel, key, "corrupt", detail=str(e))
            return None
        duration_s = time.monotonic() - t0
        with self._lock:
            self._aot_execs[(kernel, key)] = exec_
            self._seen.add((kernel, key))
            first = not self._aot_marked
            self._aot_marked = True
        if first:
            timeline().mark("aot_load")
        self.note_aot(kernel, key, "hit", seconds=duration_s)
        # aot_hit rides the compile-event stream alongside hit/miss/off:
        # the cold-start story stays in ONE place (/debug/compiles,
        # compile_ledger.json, the compile_events metric family)
        self.record(kernel, key, duration_s, cache="aot_hit")
        return exec_

    def preload_aot(self, kernels=None) -> dict:
        """Eagerly load every store artifact for the CURRENT build
        fingerprint into the override map (node restart, the cold-restart
        test): serving-ready then means every persisted signature
        dispatches without entering XLA. `kernels` optionally restricts
        to a set of kernel names. Returns a summary dict; never raises."""
        from ..ops import aot_store

        st = aot_store.store() if aot_store.load_enabled() else None
        summary: dict = {"loaded": [], "skipped": 0}
        t_start = time.monotonic()
        if st is None:
            summary["seconds"] = 0.0
            return summary
        for entry in st.entries():
            kernel, key = entry.get("kernel"), entry.get("key")
            if not kernel or key is None:
                summary["skipped"] += 1  # unreadable header: lazy path
                continue  # will classify it if the signature is dispatched
            if kernels is not None and kernel not in kernels:
                summary["skipped"] += 1
                continue
            if entry.get("fingerprint") != st.current_fingerprint():
                self.note_aot(kernel, key, "version_mismatch",
                              detail="preload: foreign build")
                summary["skipped"] += 1
                continue
            with self._lock:
                already = (kernel, key) in self._aot_execs
            if already:
                summary["skipped"] += 1
            elif self._aot_attempt(kernel, key) is None:
                summary["skipped"] += 1  # outcome already counted
            else:
                summary["loaded"].append(f"{kernel}:{key}")
        summary["seconds"] = round(time.monotonic() - t_start, 3)
        return summary

    def note_aot(self, kernel: str, key: str, outcome: str,
                 seconds: float = 0.0, detail: str | None = None) -> dict:
        """One AOT store event (hit/miss/corrupt/version_mismatch/export):
        bounded event list, flight recorder, and the
        `lodestar_tpu_aot_events_total` family on every live pipeline."""
        event = {
            "kernel": kernel,
            "key": key,
            "outcome": outcome,
            "seconds": round(seconds, 4),
        }
        if detail:
            event["detail"] = str(detail)[:200]
        with self._lock:
            self._aot_events.append(event)
            if len(self._aot_events) > self._max_events:
                del self._aot_events[0]
            self._aot_counts[outcome] = self._aot_counts.get(outcome, 0) + 1
        flight_recorder.record(
            "aot", kernel=kernel, key=key, outcome=outcome,
            seconds=event["seconds"],
        )
        for p in self.pipelines():
            p.aot_event(kernel, outcome)
        return event

    # -- recording ----------------------------------------------------------

    def record(self, kernel: str, key: str, duration_s: float,
               cache: str = "off") -> dict:
        """Append one compile event and fan it out (metrics + flight
        recorder). Public so seams that time compiles themselves (tests,
        AOT loaders) can feed the same ledger."""
        event = {
            "kernel": kernel,
            "key": key,
            "device_set": _device_key(),
            "seconds": round(duration_s, 4),
            "cache": cache,
        }
        with self._lock:
            self._events.append(event)
            if len(self._events) > self._max_events:
                del self._events[0]
            self._cumulative_s += duration_s
            self._counts[cache] = self._counts.get(cache, 0) + 1
            cumulative = self._cumulative_s
        flight_recorder.record(
            "compile_end", kernel=kernel, key=key,
            seconds=event["seconds"], cache=cache,
        )
        for p in self.pipelines():
            p.compile_event(kernel, cache, duration_s, cumulative)
        return event

    def note_prune(self, result: dict) -> None:
        """Record the last compile-cache prune (tools/prune_compile_cache)
        so the ledger artifact carries it; ticks the cache gauges on every
        live pipeline."""
        remaining = result.get(
            "entries_remaining",
            result.get("entries", 0) - len(result.get("removed", ())),
        )
        rec = {
            "entries": result.get("entries", 0),
            "entries_remaining": remaining,
            "removed": len(result.get("removed", ())),
            "removed_bytes": result.get("removed_bytes", 0),
            "total_bytes": result.get("total_bytes", 0),
            "unix_time": round(time.time(), 1),
        }
        with self._lock:
            self._last_prune = rec
        flight_recorder.record(
            "cache_prune",
            removed=rec["removed"], removed_bytes=rec["removed_bytes"],
        )
        for p in self.pipelines():
            p.cache_pruned(rec["removed_bytes"], remaining)

    # -- export -------------------------------------------------------------

    def _ensure_cache_baseline(self, cache_dir: str | None) -> None:
        """Record the cache-dir entry count once, before the first compile
        touches it — the cold/warm classifier for the serving-ready SLO."""
        if cache_dir is None:
            return
        with self._lock:
            known = self._entries_at_start is not None
        if known:
            return
        n = len(_cache_listing(cache_dir))
        with self._lock:
            if self._entries_at_start is None:
                self._entries_at_start = n

    def snapshot(self) -> dict:
        """The `/debug/compiles` + bench-section document."""
        from ..ops import aot_store

        cache_dir = _cache_dir()
        self._ensure_cache_baseline(cache_dir)
        device = _device_key()
        entries_now = len(_cache_listing(cache_dir)) if cache_dir else None
        aot_dir = aot_store.store_dir()
        with self._lock:
            events = list(self._events)
            doc = {
                "device_set": device,
                "event_count": len(events),
                "cumulative_seconds": round(self._cumulative_s, 4),
                "cache": {
                    "dir": cache_dir,
                    "entries_at_start": self._entries_at_start,
                    "entries_now": entries_now,
                    "hits": self._counts.get("hit", 0),
                    "misses": self._counts.get("miss", 0),
                    "uncached": self._counts.get("off", 0),
                    "aot_hits": self._counts.get("aot_hit", 0),
                },
                "aot": {
                    "store": aot_dir,
                    "load": aot_store.load_enabled(),
                    "export": aot_store.export_enabled(),
                    "loaded_executables": len(self._aot_execs),
                    "counts": dict(self._aot_counts),
                    "events": list(self._aot_events),
                },
                "events": events,
            }
            last_prune = self._last_prune
        if cache_dir is None:
            state = "off"
        elif not doc["cache"]["entries_at_start"]:
            state = "cold"
        else:
            state = "warm"
        doc["cache"]["state"] = state
        if last_prune is not None:
            doc["last_prune"] = dict(last_prune)
        return doc

    def write_artifact(self, path: str) -> str | None:
        """Persist the snapshot as `compile_ledger.json`; never raises —
        the artifact write must not block a bench emission."""
        try:
            with open(path, "w") as f:
                json.dump(self.snapshot(), f, indent=2)
            return path
        except OSError as e:
            print(f"compile_ledger: artifact write failed: {e}",
                  file=sys.stderr)
            return None


_ledger: CompileLedger | None = None
_ledger_lock = threading.Lock()


def ledger() -> CompileLedger:
    """The process-wide ledger every compile seam records into."""
    global _ledger
    with _ledger_lock:
        if _ledger is None:
            _ledger = CompileLedger()
        return _ledger


# -- startup timeline -------------------------------------------------------


def _process_start_monotonic() -> float:
    """The monotonic timestamp of PROCESS start (so interpreter + import
    time count toward the serving-ready SLO): /proc/self/stat field 22
    (starttime, clock ticks since boot) against /proc/uptime. Falls back
    to this module's import time off Linux."""
    try:
        with open("/proc/self/stat", "rb") as f:
            stat = f.read().decode("ascii", "replace")
        # fields after the parenthesized comm (which may contain spaces);
        # starttime is overall field 22 == index 19 of the tail
        tail = stat.rsplit(")", 1)[1].split()
        start_ticks = float(tail[19])
        hz = os.sysconf("SC_CLK_TCK")
        with open("/proc/uptime") as f:
            uptime_s = float(f.read().split()[0])
        age_s = uptime_s - start_ticks / hz
        if age_s < 0:
            return _IMPORT_MONOTONIC
        return time.monotonic() - age_s
    except (OSError, ValueError, IndexError):
        return _IMPORT_MONOTONIC


class StartupTimeline:
    """Phase marks measured from process start; feeds the startup-phase
    and serving-ready gauges on every live pipeline."""

    def __init__(self):
        self._lock = threading.Lock()
        self._start = _process_start_monotonic()
        self._marks: list[dict] = []  # guarded-by: _lock
        self._serving_ready_s: float | None = None  # guarded-by: _lock

    def mark(self, phase: str) -> float:
        """Record `phase` at now-since-process-start seconds."""
        t_s = time.monotonic() - self._start
        with self._lock:
            self._marks.append({"phase": phase, "t_s": round(t_s, 3)})
        flight_recorder.record("startup", phase=phase,
                               since_start_s=round(t_s, 3))
        for p in ledger().pipelines():
            p.startup_phase(phase, t_s)
        return t_s

    def mark_serving_ready(self) -> float:
        """The SLO mark: the process can serve its production dispatch
        ladder from here on (node init returned / headline kernel warm /
        warmup ladder complete)."""
        t_s = self.mark("serving_ready")
        with self._lock:
            self._serving_ready_s = t_s
        for p in ledger().pipelines():
            p.serving_ready(t_s)
        return t_s

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "marks": list(self._marks),
                "serving_ready_s": (
                    round(self._serving_ready_s, 3)
                    if self._serving_ready_s is not None
                    else None
                ),
            }


_timeline: StartupTimeline | None = None
_timeline_lock = threading.Lock()


def timeline() -> StartupTimeline:
    """The process-wide startup timeline."""
    global _timeline
    with _timeline_lock:
        if _timeline is None:
            _timeline = StartupTimeline()
        return _timeline
