"""Device-time attribution + memory ledger: where device-seconds go.

The bench reports sets/s but not where device time or HBM bytes actually
went — an rc=124 round died as a bare `timed_out` with no per-chip
evidence. This module is the accounting layer:

- **Time attribution.** Two nesting context managers feed one busy
  account keyed by (lane, kernel, chip):

      `lane_flush(lane, overlapped)`  the lane dispatcher's double-buffer
          worker wraps each merged verify; sets the thread's lane so
          inner dispatches inherit it. When NO inner mesh dispatch runs
          (mock/CPU verifiers), the flush itself is attributed under
          kernel `lane_flush` so stub rounds still account ~100 % of
          their device wall time.
      `dispatch(kernel, chips)`  BlsMeshDispatcher wraps every sharded
          submit; each participating chip accrues the full dispatch
          seconds (all chips are busy simultaneously).

  A global in-flight counter turns the same intervals into busy-wall /
  idle-wall seconds (union of dispatch intervals vs ledger uptime), and
  a dispatch that begins while unrelated work is in flight (or whose
  lane_flush carried the dispatcher's overlap hint) also accrues
  `overlap` seconds — the double-buffering win, measured on-device.

- **Memory sampling.** A low-rate sampler (min interval
  LODESTAR_TPU_DEVICE_LEDGER_MEM_SAMPLE_S, piggybacked on snapshot
  calls — no polling thread) reads `jax` per-device memory stats
  (bytes in use / peak / limit) and live-buffer bytes, tracks a
  monotonic per-chip high watermark, and drops a flight-recorder event
  on every watermark rise so a post-mortem shows the allocation that
  preceded the kill.

Everything exports as `lodestar_tpu_device_*` families on every attached
PipelineMetrics (same weakref fan-out as the compile ledger), serves
`/debug/device`, and embeds in every bench emission — the emitter reads
sections at emit time, so the watchdog's rc=124 document carries the
final snapshot. Stdlib-only on the hot path; `jax` is only imported by
the default memory-stats reader, inside try/except.
"""

from __future__ import annotations

import contextlib
import threading
import time
import weakref

from . import flight_recorder
from ..utils.env import env_float

__all__ = ["DeviceLedger", "ledger"]

# snapshot keeps the top-N busiest (lane, kernel, chip) rows; the full
# account stays in the counters on /metrics
SNAPSHOT_TOP_N = 24

DEFAULT_MEM_SAMPLE_S = 10.0

# jax memory_stats() keys -> exported `kind` label values
_MEM_STAT_KEYS = (
    ("bytes_in_use", "in_use"),
    ("peak_bytes_in_use", "peak"),
    ("bytes_limit", "limit"),
)


def _default_memory_stats() -> dict:
    """{chip: {kind: bytes}} from the live jax backend; empty when jax is
    absent or the backend exposes no allocator stats (CPU often doesn't)."""
    out: dict = {}
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return out  # no jax / no backend: the sampler just reports nothing
    for d in devices:
        entry: dict = {}
        try:
            stats = d.memory_stats() or {}
        except Exception:
            stats = {}  # backend without allocator stats
        for src, kind in _MEM_STAT_KEYS:
            if src in stats:
                entry[kind] = int(stats[src])
        try:
            import warnings

            with warnings.catch_warnings():
                # per-device live_buffers() is deprecated but is the only
                # per-chip byte count; the aggregate jax.live_arrays()
                # replacement loses the chip dimension
                warnings.simplefilter("ignore", DeprecationWarning)
                bufs = d.live_buffers()
            entry["live_buffers"] = int(
                sum(getattr(b, "nbytes", 0) or 0 for b in bufs)
            )
        except Exception:  # graftlint: disable=exception-hygiene — live-buffer enumeration is best-effort per backend; the other stat keys still export
            pass
        if entry:
            out[str(d.id)] = entry
    return out


class DeviceLedger:
    """Busy/idle/overlap device-seconds by lane x kernel x chip + the
    memory watermark sampler."""

    def __init__(self, clock=time.monotonic, memory_stats_fn=None):
        self._clock = clock
        self._memory_stats_fn = memory_stats_fn
        self._lock = threading.Lock()
        self._t0 = clock()
        self._busy: dict[tuple, float] = {}  # guarded-by: _lock
        self._overlap: dict[tuple, float] = {}  # guarded-by: _lock
        self._dispatches = 0  # guarded-by: _lock
        self._inflight = 0  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock  (bumped per inner dispatch)
        self._busy_wall_accum = 0.0  # guarded-by: _lock
        self._busy_wall_t0: float | None = None  # guarded-by: _lock
        self._mem: dict[str, dict] = {}  # guarded-by: _lock
        self._watermark: dict[str, int] = {}  # guarded-by: _lock
        self._mem_last_t: float | None = None  # guarded-by: _lock
        self._mem_samples = 0  # guarded-by: _lock
        self._pipelines: list = []  # guarded-by: _lock
        self._tls = threading.local()

    # -- pipeline fan-out ---------------------------------------------------

    def attach(self, pipeline) -> None:
        """Weakref-register a PipelineMetrics (PipelineMetrics.__init__
        calls this; dead refs are compacted on every attach)."""
        with self._lock:
            self._pipelines = [r for r in self._pipelines if r() is not None]
            self._pipelines.append(weakref.ref(pipeline))

    def pipelines(self) -> list:
        with self._lock:
            refs = list(self._pipelines)
        return [p for p in (r() for r in refs) if p is not None]

    # -- interval bookkeeping -----------------------------------------------

    def _begin_locked(self) -> tuple[float, int, int]:
        now = self._clock()
        foreign = self._inflight - getattr(self._tls, "depth", 0)
        if self._inflight == 0:
            self._busy_wall_t0 = now
        self._inflight += 1
        self._tls.depth = getattr(self._tls, "depth", 0) + 1
        return now, self._seq, foreign

    def _end_locked(self) -> float:
        now = self._clock()
        self._inflight -= 1
        self._tls.depth = getattr(self._tls, "depth", 1) - 1
        if self._inflight == 0 and self._busy_wall_t0 is not None:
            self._busy_wall_accum += now - self._busy_wall_t0
            self._busy_wall_t0 = None
        return now

    def _attribute(self, lane: str, kernel: str, chips, elapsed: float,
                   overlapped: bool) -> None:
        chips = tuple(str(c) for c in chips) or ("0",)
        with self._lock:
            self._dispatches += 1
            for chip in chips:
                key = (lane, kernel, chip)
                self._busy[key] = self._busy.get(key, 0.0) + elapsed
                if overlapped:
                    self._overlap[key] = self._overlap.get(key, 0.0) + elapsed
        for p in self.pipelines():
            for chip in chips:
                p.device_dispatch_time(
                    lane, kernel, chip, elapsed,
                    elapsed if overlapped else 0.0,
                )

    # -- the two instrumentation seams --------------------------------------

    @contextlib.contextmanager
    def lane_flush(self, lane: str, overlapped: bool = False):
        """Wrap one lane-dispatcher merged verify: sets the thread's lane
        so nested mesh dispatches attribute under it. Attributes the
        flush itself (kernel `lane_flush`, chip `0`) only when no inner
        dispatch ran — stub/CPU verifiers never double-count."""
        tls = self._tls
        prev_lane = getattr(tls, "lane", None)
        prev_hint = getattr(tls, "overlap_hint", False)
        tls.lane = lane
        tls.overlap_hint = bool(overlapped)
        with self._lock:
            t0, seq0, foreign = self._begin_locked()
        try:
            yield
        finally:
            with self._lock:
                now = self._end_locked()
                inner = self._seq > seq0
            tls.lane = prev_lane
            tls.overlap_hint = prev_hint
            if not inner:
                self._attribute(
                    lane, "lane_flush", ("0",), now - t0,
                    bool(overlapped) or foreign > 0,
                )

    @contextlib.contextmanager
    def dispatch(self, kernel: str, chips):
        """Wrap one sharded device submit (BlsMeshDispatcher): each
        participating chip accrues the full elapsed seconds under the
        calling thread's lane (`unlabeled` outside a lane flush)."""
        tls = self._tls
        lane = getattr(tls, "lane", None) or "unlabeled"
        hint = getattr(tls, "overlap_hint", False)
        with self._lock:
            self._seq += 1
            t0, seq0, foreign = self._begin_locked()
        try:
            yield
        finally:
            with self._lock:
                now = self._end_locked()
                raced = self._seq > seq0
            self._attribute(
                lane, kernel, chips, now - t0,
                bool(hint) or foreign > 0 or raced,
            )

    # -- memory sampler -----------------------------------------------------

    def sample_memory(self, force: bool = False) -> None:
        """Low-rate jax memory sample (piggybacked on snapshot calls):
        rate-limited by LODESTAR_TPU_DEVICE_LEDGER_MEM_SAMPLE_S; 0
        disables; `force` bypasses the limiter (tests, post-mortems)."""
        interval = env_float("LODESTAR_TPU_DEVICE_LEDGER_MEM_SAMPLE_S")
        if interval is None:
            interval = DEFAULT_MEM_SAMPLE_S
        if interval <= 0 and not force:
            return
        now = self._clock()
        with self._lock:
            if (not force and self._mem_last_t is not None
                    and now - self._mem_last_t < interval):
                return
            self._mem_last_t = now
        fn = self._memory_stats_fn or _default_memory_stats
        try:
            stats = fn() or {}
        except Exception as e:
            flight_recorder.record("device_mem_sample_error", error=str(e))
            return
        rises: list[tuple[str, int]] = []
        with self._lock:
            self._mem_samples += 1
            self._mem = {chip: dict(entry) for chip, entry in stats.items()}
            for chip, entry in stats.items():
                in_use = int(entry.get("in_use", entry.get("live_buffers", 0)))
                prev = self._watermark.get(chip, 0)
                if in_use > prev:
                    self._watermark[chip] = in_use
                    rises.append((chip, in_use))
            watermarks = dict(self._watermark)
        for chip, value in rises:
            flight_recorder.record(
                "device_mem_watermark", chip=chip, bytes=value
            )
        for p in self.pipelines():
            for chip, entry in stats.items():
                for kind, value in entry.items():
                    p.device_memory_sample(chip, kind, value)
            for chip, value in watermarks.items():
                p.device_memory_watermark_set(chip, value)

    # -- export -------------------------------------------------------------

    def snapshot(self) -> dict:
        """The `/debug/device` + bench-section document."""
        self.sample_memory()
        now = self._clock()
        with self._lock:
            busy_wall = self._busy_wall_accum
            if self._busy_wall_t0 is not None:
                busy_wall += now - self._busy_wall_t0
            uptime = max(0.0, now - self._t0)
            idle = max(0.0, uptime - busy_wall)
            rows = sorted(self._busy.items(), key=lambda kv: -kv[1])
            attributed = [
                {
                    "lane": key[0], "kernel": key[1], "chip": key[2],
                    "busy_s": round(busy, 6),
                    "overlap_s": round(self._overlap.get(key, 0.0), 6),
                }
                for key, busy in rows[:SNAPSHOT_TOP_N]
            ]
            snap = {
                "uptime_s": round(uptime, 3),
                "busy_wall_s": round(busy_wall, 6),
                "idle_wall_s": round(idle, 6),
                "utilization": round(busy_wall / uptime, 4) if uptime else 0.0,
                "dispatches": self._dispatches,
                "inflight": self._inflight,
                "attributed_busy_s": round(sum(self._busy.values()), 6),
                "attributed": attributed,
                "attributed_rows_dropped": max(0, len(rows) - SNAPSHOT_TOP_N),
                "memory": {
                    chip: {
                        **self._mem.get(chip, {}),
                        "watermark_bytes": self._watermark.get(chip, 0),
                    }
                    for chip in sorted(set(self._mem) | set(self._watermark))
                },
                "memory_samples": self._mem_samples,
            }
        for p in self.pipelines():
            p.device_idle(idle)
        return snap


# -- process-wide singleton ---------------------------------------------------

_ledger: DeviceLedger | None = None
_ledger_lock = threading.Lock()


def ledger() -> DeviceLedger:
    """The process-wide device ledger every dispatch seam records into."""
    global _ledger
    with _ledger_lock:
        if _ledger is None:
            _ledger = DeviceLedger()
        return _ledger


def _reset_for_tests() -> None:
    global _ledger
    with _ledger_lock:
        _ledger = None
