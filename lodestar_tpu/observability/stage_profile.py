"""Per-stage device timing for the verifier pipeline.

The production kernels fuse every stage into ONE XLA dispatch, so host
timers can only see the whole; this module times each stage as its own
jitted sub-kernel (the tools/kernel_profile.py methodology as a library)
and records the steady-state numbers into a `PipelineMetrics` stage
histogram — the bench's stage-time breakdown and the operator's answer
to "where does the dispatch time go".

Inputs are deterministic random limb/bit arrays: every kernel is
branchless with fixed-trip control flow, so stage TIMING is
value-independent — no host-side signing/hashing setup cost. (The
verdicts are meaningless; nothing here checks them.)

The sum of stages exceeds the fused kernel's time (XLA overlaps stages);
the RATIOS say where the next optimization dollar goes (BASELINE.md
round-5 stage profile).
"""

from __future__ import annotations

import time

import numpy as np

from .stages import PipelineMetrics, default_pipeline
from .trace import annotation

N_LIMBS = 32
R_BITS = 64


def _rand_inputs(batch: int, seed: int = 7):
    rng = np.random.default_rng(seed)
    limb = lambda *shape: rng.integers(
        0, 1 << 12, size=shape + (N_LIMBS,), dtype=np.int32
    )
    bits = rng.integers(0, 2, size=(batch, R_BITS), dtype=np.int32)
    raw = rng.integers(0, 256, size=(batch, 96), dtype=np.uint8)
    return limb, bits, raw


def profile_stages(
    pipeline: PipelineMetrics | None = None,
    batch: int = 256,
    reps: int = 2,
) -> dict:
    """Time each pipeline stage at `batch` lanes; returns
    {stage: steady-state seconds} and observes each into the stage
    histogram. `batch` must be a multiple of 4 (MSM subset-4 tables)."""
    import jax

    from ..ops import fp, fp2, fp12, msm, pallas_tower
    from ..ops.g2_decompress import decompress
    from ..ops.pairing import (
        final_exponentiation,
        final_exponentiation_batch,
        miller_loop_proj_pq,
    )
    from ..ops.points import g1, g2

    if batch % 4 != 0:
        raise ValueError("batch must be a multiple of 4")
    obs = pipeline if pipeline is not None else default_pipeline()
    limb, bits, raw = _rand_inputs(batch)

    def timed(stage: str, fn, *args):
        from .compile_ledger import ledger

        jitted = ledger().wrap(jax.jit(fn), f"stage_{stage}")
        with annotation(f"stage_profile/{stage}/compile"):
            out = jitted(*args)
            jax.block_until_ready(out)
        t0 = time.monotonic()
        with annotation(f"stage_profile/{stage}"):
            for _ in range(reps):
                out = jitted(*args)
            jax.block_until_ready(out)
        dt = (time.monotonic() - t0) / reps
        obs.observe_stage(stage, dt)
        return out, dt

    results: dict[str, float] = {}

    pk_x, pk_y = limb(batch), limb(batch)
    rpk, results["scalar_mul"] = timed(
        "scalar_mul", lambda b, x, y: g1.scalar_mul_bits(b, (x, y)),
        bits, pk_x, pk_y,
    )

    sig_x, sig_y = limb(batch, 2), limb(batch, 2)
    _, results["msm_planes"] = timed(
        "msm_planes",
        lambda x, y, b: msm.masked_plane_sums(g2, (x, y, fp2.one((batch,))), b),
        sig_x, sig_y, bits,
    )

    _, results["g2_decompress"] = timed("g2_decompress", decompress, raw)

    msg_x, msg_y = limb(batch, 2), limb(batch, 2)
    fs, results["miller_loop"] = timed(
        "miller_loop",
        lambda px, py, qx, qy: miller_loop_proj_pq(
            (px, py, fp.one((batch,))), (qx, qy, fp2.one((batch,)))
        ),
        rpk[0], rpk[1], msg_x, msg_y,
    )

    if pallas_tower.enabled():
        # device tag `bls/miller_pallas`: the VMEM-resident tower kernel
        # on the affine shape it serves (interpret mode off-TPU is far
        # slower than XLA, so this stage only runs when the knob is on)
        _, results["miller_pallas"] = timed(
            "miller_pallas",
            lambda px, py, qx, qy: pallas_tower.miller_loop_pallas(
                (px, py), (qx, qy)
            ),
            rpk[0], rpk[1], msg_x, msg_y,
        )

    prod, results["product_tree"] = timed("product_tree", fp12.product_tree, fs)

    _, results["final_exp"] = timed(
        "final_exp", lambda f: fp12.is_one(final_exponentiation(f[None]))[0], prod
    )

    # device tag `bls/final_exp_batch`: the N-wide shared-inversion final
    # exp of the per-set verdict path (ONE easy-part inversion chain for
    # the whole batch — the latency-floor win of ISSUE 14)
    _, results["final_exp_batch"] = timed(
        "final_exp_batch",
        lambda f: fp12.is_one(final_exponentiation_batch(f)), fs,
    )

    return {k: round(v, 6) for k, v in results.items()}
