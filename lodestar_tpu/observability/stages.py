"""Stage timers + planner counters + queue gauges for the BLS pipeline.

The stage taxonomy follows the verification dataflow (docs/observability.md):

host stages (timed inline, monotonic clock):
    marshal        wire bytes -> limb arrays (C tier decompress/subgroup)
    hash_to_curve  H(m) for cache-missed signing roots (C tier)
    rand           random-coefficient bit planes
    dispatch       host->XLA submit time (async; excludes device compute)
    device_wait    resolver block time (`block_until_ready`-bounded)
    bisect         bisection probe dispatches on a failed verdict tree
                   (batched shared-easy-part final exps; device tag
                   `bls/bisect` inside the probe kernel)

device stages (attributable two ways: `trace.named_scope` tags inside the
fused kernel for XLA profiles, and `stage_profile.profile_stages` timing
per-stage sub-kernels into the SAME histogram for the bench breakdown):
    g2_decompress, scalar_mul, msm_planes, miller_loop, product_tree,
    final_exp, final_exp_batch (batched shared-inversion final exp, device
    tag `bls/final_exp_batch`), miller_pallas (VMEM-resident Pallas Miller
    tower when LODESTAR_TPU_PALLAS_MILLER resolves on, device tag
    `bls/miller_pallas`)

All families live in a `metrics.registry.MetricsRegistry` so they render
on `/metrics` next to the rest of the node's families. `default_pipeline()`
backs unwired verifiers (bench, tools) with a process-local registry;
`create_beacon_metrics` attaches a node-wired instance as `m.pipeline`.
"""

from __future__ import annotations

import threading
import time

from ..metrics.registry import MetricsRegistry
from . import flight_recorder

# label set of the build-info gauge (one constant-1 series whose labels
# carry the runtime identity — utils/jax_env.runtime_info produces it)
BUILD_INFO_LABELS = (
    "jax", "jaxlib", "backend", "device_kind", "device_count",
    "mesh_divisor", "compile_cache",
)

STAGES = (
    "marshal",
    "hash_to_curve",
    "rand",
    "dispatch",
    "device_wait",
    "bisect",
    "g2_decompress",
    "scalar_mul",
    "msm_planes",
    "miller_loop",
    "miller_pallas",
    "product_tree",
    "final_exp",
    "final_exp_batch",
)

# planner decisions (parallel/verifier.verify_signature_sets_submit):
#   root_grouped  whole batch on the root-grouped kernel
#   pk_grouped    whole batch on the pubkey-grouped (dual) kernel
#   split         shared-root part peeled off, remainder routed separately
#                 (the parts also count under their own paths)
#   per_set       flat per-set kernel (nothing grouped)
#   individual    per-set verdict retry path
PLANNER_PATHS = ("root_grouped", "pk_grouped", "split", "per_set", "individual")

_STAGE_BUCKETS = (
    0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60,
)
_GROUP_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


def _block_until_ready(x):
    try:
        import jax

        jax.block_until_ready(x)
    except ImportError:
        pass  # no jax: the value was computed eagerly, nothing to wait on


class _StageTimer:
    """Context manager: observes monotonic elapsed seconds into the stage
    histogram. `bound(x)` registers a value to `block_until_ready` before
    the clock stops, so async dispatch results are timed to completion."""

    __slots__ = ("_pipeline", "_stage", "_bound", "t0", "elapsed")

    def __init__(self, pipeline: "PipelineMetrics", stage: str):
        self._pipeline = pipeline
        self._stage = stage
        self._bound = None
        self.elapsed = 0.0

    def bound(self, x):
        self._bound = x
        return x

    def __enter__(self):
        self.t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        if self._bound is not None:
            _block_until_ready(self._bound)
        self.elapsed = time.monotonic() - self.t0
        self._pipeline.observe_stage(self._stage, self.elapsed)
        return False


class PipelineMetrics:
    """The telemetry families + recording API for one verifier pipeline."""

    def __init__(self, registry: MetricsRegistry | None = None):
        r = registry if registry is not None else MetricsRegistry()
        self.registry = r
        self.stage_seconds = r.histogram(
            "lodestar_bls_pipeline_stage_seconds",
            "per-stage latency of the BLS verification pipeline",
            label_names=("stage",),
            buckets=_STAGE_BUCKETS,
        )
        self.planner_decisions = r.counter(
            "lodestar_bls_verifier_planner_decisions_total",
            "batch-planner routing decisions by kernel path",
            label_names=("path",),
        )
        self.planner_sets = r.counter(
            "lodestar_bls_verifier_planner_sets_total",
            "signature sets routed per kernel path",
            label_names=("path",),
        )
        self.planner_group_size = r.histogram(
            "lodestar_bls_verifier_planner_group_size",
            "sets per group row chosen by the planner",
            buckets=_GROUP_SIZE_BUCKETS,
        )
        self.cache_events = r.counter(
            "lodestar_bls_verifier_cache_events_total",
            "dedup cache hits/misses (h2c roots, pubkey limbs)",
            label_names=("cache", "outcome"),
        )
        self.flushes = r.counter(
            "lodestar_bls_verifier_flushes_total",
            "buffer flushes by trigger reason (size/timer/manual)",
            label_names=("reason",),
        )
        self.flush_seconds = r.histogram(
            "lodestar_bls_verifier_flush_seconds",
            "flush latency: merged batch verify incl. fallback",
            buckets=_STAGE_BUCKETS,
        )
        self.buffer_depth = r.gauge_func(
            "lodestar_bls_verifier_buffer_depth",
            "signature sets currently buffered (live callback, no polling)",
        )
        self.device_busy = r.gauge(
            "lodestar_bls_verifier_device_busy_fraction",
            "fraction of wall time the device spent on verify dispatches",
        )
        # bisection verdicts (round-6 tentpole): per-batch outcome plus
        # round/probe totals — an all-valid batch is one `clean` tick
        # with zero rounds (the ≤1-final-exp fast path); k invalid sets
        # tick `bisected` with O(log N) rounds and O(k·log N) probes
        self.bisect_batches = r.counter(
            "lodestar_bls_verifier_bisect_batches_total",
            "per-set verdict batches by outcome (clean = root passed)",
            label_names=("outcome",),
        )
        self.bisect_rounds_total = r.counter(
            "lodestar_bls_verifier_bisect_rounds_total",
            "bisection rounds walked on failed per-set verdict batches",
        )
        self.bisect_probes_total = r.counter(
            "lodestar_bls_verifier_bisect_probes_total",
            "product-tree nodes probed (batched final exps) during bisection",
        )
        # device-decompress downgrade visibility (round-6 satellite): the
        # default path silently falling back to host marshal would
        # otherwise be an invisible e2e regression
        self.decompress_fallbacks = r.counter(
            "lodestar_bls_verifier_decompress_fallback_total",
            "device-decompress batches downgraded to host marshal "
            "(native tier ineligible for the batch shape)",
        )
        # supervisor / failure-policy families (round 7): the device tier
        # is allowed to fail — these make every branch of the failure
        # policy (chain/supervisor.py) visible. Breaker state encodes
        # closed=0 / half_open=1 / open=2 so dashboards can alert on
        # `> 0` (any degradation) or `== 2` (hard open).
        self.supervisor_breaker_state = r.gauge(
            "lodestar_bls_supervisor_breaker_state",
            "device circuit breaker state (0=closed, 1=half_open, 2=open)",
        )
        self.supervisor_transitions = r.counter(
            "lodestar_bls_supervisor_breaker_transitions_total",
            "circuit breaker state transitions by destination state",
            label_names=("to",),
        )
        self.supervisor_retries = r.counter(
            "lodestar_bls_supervisor_retries_total",
            "device dispatches retried after a transient error",
        )
        self.supervisor_fallbacks = r.counter(
            "lodestar_bls_supervisor_fallbacks_total",
            "dispatches served by the CPU oracle tier, by reason "
            "(exception/deadline/breaker_open/negative_audit)",
            label_names=("reason",),
        )
        self.supervisor_deadline_exceeded = r.counter(
            "lodestar_bls_supervisor_deadline_exceeded_total",
            "device dispatches abandoned at the per-dispatch deadline",
        )
        self.supervisor_canary = r.counter(
            "lodestar_bls_supervisor_canary_probes_total",
            "half-open canary-batch probes by outcome (ok/fail)",
            label_names=("outcome",),
        )
        self.supervisor_both_tiers_failed = r.counter(
            "lodestar_bls_supervisor_both_tiers_failed_total",
            "batches where the device AND the CPU oracle both failed "
            "(waiters resolved False — the only blanket-False path left)",
        )
        self.supervisor_verdict_mismatches = r.counter(
            "lodestar_bls_supervisor_verdict_mismatch_total",
            "device-negative verdicts the CPU oracle overturned "
            "(flaky-device evidence; feeds the breaker)",
        )
        # defense-in-depth for blocked waiters (round-7 satellite): a
        # wedged flush thread must escalate, not silently deadlock every
        # gossip/import thread
        self.waiter_timeouts = r.counter(
            "lodestar_bls_verifier_waiter_timeouts_total",
            "verify waiters that gave up after the flush-thread timeout",
        )
        # mesh serving (round-7 tentpole): the grouped kernels dispatch
        # onto a jax.sharding.Mesh when >1 chip is visible; these families
        # let a dashboard tell a full 4-chip node from a degraded 3-chip
        # one (size + evicted gauges move together on an eviction)
        self.mesh_size = r.gauge(
            "lodestar_bls_mesh_size",
            "chips in the serving BLS dispatch mesh "
            "(0 = unsharded single-device dispatch)",
        )
        self.mesh_evicted = r.gauge(
            "lodestar_bls_mesh_evicted_devices",
            "chips currently evicted from the serving mesh",
        )
        self.mesh_evictions = r.counter(
            "lodestar_bls_mesh_evictions_total",
            "chips evicted from the serving mesh, by failure reason",
            label_names=("reason",),
        )
        self.mesh_readmissions = r.counter(
            "lodestar_bls_mesh_readmissions_total",
            "evicted chips re-admitted after a passing canary probe",
        )
        self.mesh_dispatches = r.counter(
            "lodestar_bls_mesh_chip_dispatch_total",
            "sharded kernel dispatches per participating chip",
            label_names=("chip",),
        )
        # fleet serving (ISSUE 20): the mesh abstracted over HOSTS — a
        # two-level (DCN × ICI) dispatch layout plus subnet-sharded
        # gossip routing; these families tell a full fleet from one
        # serving degraded after a host eviction
        self.fleet_hosts = r.gauge(
            "lodestar_bls_fleet_hosts",
            "hosts in the two-level serving fleet "
            "(0/1 = single-host, no DCN axis)",
        )
        self.fleet_evicted_hosts = r.gauge(
            "lodestar_bls_fleet_evicted_hosts",
            "hosts currently evicted from the serving fleet",
        )
        self.fleet_host_dispatches = r.counter(
            "lodestar_bls_fleet_host_dispatch_total",
            "two-level sharded dispatches per participating host",
            label_names=("host",),
        )
        self.fleet_dcn = r.counter(
            "lodestar_bls_fleet_dcn_collective_seconds_total",
            "wall seconds spent in DCN-spanning (multi-host) dispatches "
            "— an upper bound on cross-host collective cost",
        )
        self.fleet_host_evictions = r.counter(
            "lodestar_bls_fleet_host_evictions_total",
            "hosts evicted from the serving fleet, by failure reason",
            label_names=("reason",),
        )
        self.fleet_rebalances = r.counter(
            "lodestar_bls_fleet_rebalances_total",
            "subnet-routing rebalances after host eviction/re-admission",
        )
        self.fleet_subnets_moved = r.counter(
            "lodestar_bls_fleet_subnets_moved_total",
            "attestation subnets re-homed across hosts by rebalances",
        )
        # priority-lane dispatcher (round 15): continuous batching with
        # admission control — depth per lane, sheds per lane, coalesced
        # batch size, and the double-buffer overlap fraction (how often a
        # batch's host prep overlapped an in-flight device step)
        self.lane_depth = r.gauge(
            "lodestar_bls_lane_depth",
            "signature sets queued per priority lane of the "
            "continuous-batching dispatcher",
            label_names=("lane",),
        )
        self.lane_sheds = r.counter(
            "lodestar_bls_lane_shed_total",
            "signature sets shed by lane admission control or "
            "flood eviction (blocks are never shed)",
            label_names=("lane",),
        )
        self.lane_coalesced_sets = r.histogram(
            "lodestar_bls_lane_coalesced_sets",
            "signature sets coalesced into one lane-dispatcher batch",
            buckets=_GROUP_SIZE_BUCKETS,
        )
        self.lane_overlap_fraction = r.gauge(
            "lodestar_bls_lane_overlap_fraction",
            "fraction of dispatched batches whose host prep overlapped "
            "device compute of an in-flight batch (double-buffering)",
        )
        # epoch-resident crypto (round 18): the device pubkey table that
        # turns steady-state attestation marshalling into memcpys, plus
        # the dispatcher's H(msg) dedup at the coalescing point
        # (parallel/epoch_table.py and chain/dispatcher.py feed these)
        self.epoch_table_hits = r.counter(
            "lodestar_bls_epoch_table_hits_total",
            "pubkey rows served from the epoch-resident table "
            "(a memcpy instead of a C-tier G1 decompression)",
        )
        self.epoch_table_misses = r.counter(
            "lodestar_bls_epoch_table_misses_total",
            "pubkey lookups the epoch table could not serve "
            "(fell through to _pk_cache / C-tier decompress)",
        )
        self.epoch_table_occupancy_gauge = r.gauge(
            "lodestar_bls_epoch_table_occupancy",
            "decompressed pubkey rows resident across all retained epochs",
        )
        self.epoch_table_evictions = r.counter(
            "lodestar_bls_epoch_table_evictions_total",
            "pubkey rows dropped by LRU epoch rotation or the row cap",
        )
        self.h2c_dedup_counter = r.counter(
            "lodestar_bls_h2c_dedup_total",
            "duplicate hash-to-curve computations elided by message "
            "dedup at the lane-dispatcher coalescing point",
        )
        # compile-ledger / cold-start families (round 11): compilation is
        # the tax that killed both red driver rounds — these make every
        # compile event and the getting-to-serving path first-class
        # metrics (observability/compile_ledger.py feeds them)
        self.compile_events = r.counter(
            "lodestar_tpu_compile_events_total",
            "XLA compile events recorded by the compile ledger, by kernel "
            "and persistent-cache outcome (hit/miss/off)",
            label_names=("kernel", "cache"),
        )
        self.compile_seconds = r.counter(
            "lodestar_tpu_compile_seconds_total",
            "wall seconds spent in first-dispatch kernel compiles",
            label_names=("kernel",),
        )
        self.compile_cumulative = r.gauge(
            "lodestar_tpu_compile_cumulative_seconds",
            "cumulative compile seconds this process (ledger total)",
        )
        self.compile_cache_entries = r.gauge(
            "lodestar_tpu_compile_cache_entries",
            "entries in the persistent XLA compile cache at the last prune",
        )
        self.compile_cache_pruned = r.counter(
            "lodestar_tpu_compile_cache_pruned_bytes_total",
            "bytes the LRU pruner removed from the persistent compile cache",
        )
        self.aot_events = r.counter(
            "lodestar_tpu_aot_events_total",
            "AOT executable-store events by kernel and outcome (hit = "
            "executable loaded from disk instead of compiling, miss = no "
            "artifact, corrupt / version_mismatch = artifact rejected and "
            "degraded to JIT, export = artifact written by the producer)",
            label_names=("kernel", "outcome"),
        )
        self.serving_ready_gauge = r.gauge(
            "lodestar_tpu_serving_ready_seconds",
            "seconds from process start to serving-ready (cold-start SLO; "
            "measured cold vs warm .jax_cache — docs/architecture.md)",
        )
        self.startup_phase_seconds = r.gauge(
            "lodestar_tpu_startup_phase_seconds",
            "seconds from process start to each startup-phase mark "
            "(devices ready, warmup rungs, serving ready)",
            label_names=("phase",),
        )
        self.build_info = r.gauge(
            "lodestar_tpu_build_info",
            "constant 1; labels carry the runtime identity (jax/jaxlib "
            "version, backend, device kind/count, mesh divisor, "
            "compile-cache dir set/unset)",
            label_names=BUILD_INFO_LABELS,
        )
        # SLO engine families (round 16): the judgment layer over
        # everything above — objectives from dashboards/slo_rules.json
        # evaluated with Google-SRE error budgets and multi-window
        # (5 m / 1 h) burn rates (observability/slo.py feeds them)
        self.slo_burning = r.gauge(
            "lodestar_slo_burning",
            "1 while an SLO objective is burning its error budget on "
            "BOTH the short and long window (alert on == 1)",
            label_names=("objective",),
        )
        self.slo_budget_remaining = r.gauge(
            "lodestar_slo_budget_remaining_fraction",
            "fraction of an objective's error budget left since the "
            "engine started (1 = untouched, 0 = exhausted)",
            label_names=("objective",),
        )
        self.slo_burn_rate = r.gauge(
            "lodestar_slo_burn_rate",
            "error-budget burn rate per evaluation window (1.0 = burning "
            "exactly the sustainable rate; zero-tolerance objectives "
            "report raw bad-event counts)",
            label_names=("objective", "window"),
        )
        self.slo_evaluations = r.counter(
            "lodestar_slo_evaluations_total",
            "SLO engine evaluation passes (scrapes, bench sections, "
            "supervisor pokes)",
        )
        # device-time & memory ledger families (round 16): where
        # device-seconds and HBM bytes actually go, by lane x kernel x
        # chip (observability/device_ledger.py feeds them)
        self.device_dispatch_seconds = r.counter(
            "lodestar_tpu_device_dispatch_seconds_total",
            "busy device-seconds attributed per lane x kernel x chip "
            "(each participating chip accrues the full dispatch time)",
            label_names=("lane", "kernel", "chip"),
        )
        self.device_overlap_seconds = r.counter(
            "lodestar_tpu_device_overlap_seconds_total",
            "device-seconds spent while another dispatch was already in "
            "flight (double-buffering overlap), same key as dispatch time",
            label_names=("lane", "kernel", "chip"),
        )
        self.device_idle_wall = r.gauge(
            "lodestar_tpu_device_idle_wall_seconds",
            "wall seconds with NO dispatch in flight since the device "
            "ledger started (refreshed on snapshot)",
        )
        self.device_memory = r.gauge(
            "lodestar_tpu_device_memory_bytes",
            "sampled jax device memory by chip and kind "
            "(in_use/peak/limit/live_buffers)",
            label_names=("chip", "kind"),
        )
        self.device_memory_watermark = r.gauge(
            "lodestar_tpu_device_memory_watermark_bytes",
            "high watermark of sampled in-use device memory per chip "
            "(monotonic within a process)",
            label_names=("chip",),
        )
        # device-busy sampler state: busy seconds accumulate per resolve,
        # the fraction is re-sampled over >=1 s wall windows
        self._busy_lock = threading.Lock()
        self._busy_accum = 0.0
        self._busy_window_t0 = time.monotonic()
        # lane-dispatcher state: overlap fraction is maintained from
        # batch counters; the live per-lane depth callback is bound by
        # the dispatcher (None until one wires up — `lanes_snapshot()`
        # then reports unwired)
        self._lane_lock = threading.Lock()
        self._lane_batches = 0
        self._lane_overlapped = 0
        self._lane_depths_fn = None
        # the process-wide compile ledger fans its events out to every
        # live pipeline: the node registry and the bench/tools default
        # pipeline both see the same compile history (weakref — a
        # discarded test registry detaches itself)
        from .compile_ledger import ledger as _compile_ledger

        _compile_ledger().attach(self)
        # same fan-out contract for the device-time & memory ledger
        from .device_ledger import ledger as _device_ledger

        _device_ledger().attach(self)

    # -- stage timers -------------------------------------------------------

    def stage(self, name: str) -> _StageTimer:
        return _StageTimer(self, name)

    def observe_stage(self, name: str, seconds: float) -> None:
        self.stage_seconds.observe(seconds, stage=name)

    # -- planner ------------------------------------------------------------

    def planner(self, path: str, n_sets: int, group_sizes=None) -> None:
        self.planner_decisions.inc(path=path)
        self.planner_sets.inc(n_sets, path=path)
        if group_sizes:
            for size in group_sizes:
                self.planner_group_size.observe(size)
        flight_recorder.record("dispatch", path=path, sets=n_sets)

    def cache_event(self, cache: str, hit: bool, n: int = 1) -> None:
        if n:
            self.cache_events.inc(n, cache=cache, outcome="hit" if hit else "miss")

    def epoch_table_event(self, hit: bool, n: int = 1) -> None:
        if n:
            (self.epoch_table_hits if hit else self.epoch_table_misses).inc(n)

    def epoch_table_occupancy(self, rows: int) -> None:
        self.epoch_table_occupancy_gauge.set(rows)

    def epoch_table_eviction(self, n: int = 1) -> None:
        if n:
            self.epoch_table_evictions.inc(n)

    def h2c_dedup(self, n: int = 1) -> None:
        if n:
            self.h2c_dedup_counter.inc(n)

    def bisect(self, rounds: int, probes: int) -> None:
        """Record one per-set verdict batch's bisection outcome."""
        self.bisect_batches.inc(
            outcome="clean" if rounds == 0 else "bisected"
        )
        if rounds:
            self.bisect_rounds_total.inc(rounds)
        if probes:
            self.bisect_probes_total.inc(probes)

    def decompress_fallback(self, n: int = 1) -> None:
        self.decompress_fallbacks.inc(n)

    # -- supervisor / failure policy ----------------------------------------

    def breaker_state(self, value: int, to: str | None = None) -> None:
        """Set the breaker-state gauge; `to` also ticks the transition
        counter (passed on actual transitions, not on re-assertions)."""
        self.supervisor_breaker_state.set(value)
        if to is not None:
            self.supervisor_transitions.inc(to=to)
            flight_recorder.record("breaker", to=to, state=value)

    def supervisor_retry(self) -> None:
        self.supervisor_retries.inc()

    def supervisor_fallback(self, reason: str, n_sets: int = 0) -> None:
        self.supervisor_fallbacks.inc(reason=reason)
        flight_recorder.record("fallback", reason=reason, sets=n_sets)

    def supervisor_deadline(self) -> None:
        self.supervisor_deadline_exceeded.inc()
        flight_recorder.record("deadline_exceeded")

    def supervisor_canary_probe(self, ok: bool) -> None:
        self.supervisor_canary.inc(outcome="ok" if ok else "fail")

    def both_tiers_failed(self) -> None:
        self.supervisor_both_tiers_failed.inc()

    def verdict_mismatch(self, n: int = 1) -> None:
        self.supervisor_verdict_mismatches.inc(n)

    def waiter_timeout(self) -> None:
        self.waiter_timeouts.inc()

    # -- mesh serving -------------------------------------------------------

    def mesh_state(self, size: int, evicted: int) -> None:
        """Assert the current serving-mesh shape (size + evicted gauges)."""
        self.mesh_size.set(size)
        self.mesh_evicted.set(evicted)

    def mesh_eviction(self, chip: int, reason: str) -> None:
        self.mesh_evictions.inc(reason=reason)
        flight_recorder.record("mesh_eviction", chip=chip, reason=reason)

    def mesh_readmission(self, n: int = 1) -> None:
        self.mesh_readmissions.inc(n)
        flight_recorder.record("mesh_readmission", chips=n)

    def mesh_dispatch(self, chips) -> None:
        """Tick the per-chip dispatch counter for every participating chip
        of one sharded dispatch."""
        for chip in chips:
            self.mesh_dispatches.inc(chip=str(chip))

    # -- fleet serving ------------------------------------------------------

    def fleet_state(self, hosts: int, evicted: int) -> None:
        """Assert the current fleet shape (serving + evicted host gauges)."""
        self.fleet_hosts.set(hosts)
        self.fleet_evicted_hosts.set(evicted)

    def fleet_dispatch(self, hosts) -> None:
        """Tick the per-host dispatch counter for every participating host
        of one two-level (DCN-spanning) dispatch."""
        for host in hosts:
            self.fleet_host_dispatches.inc(host=str(host))

    def fleet_dcn_seconds(self, seconds: float) -> None:
        self.fleet_dcn.inc(max(seconds, 0.0))

    def fleet_host_eviction(self, host: int, reason: str) -> None:
        self.fleet_host_evictions.inc(reason=reason)
        flight_recorder.record("fleet_host_eviction", host=host,
                               reason=reason)

    def fleet_rebalance(self, subnets_moved: int) -> None:
        self.fleet_rebalances.inc()
        if subnets_moved:
            self.fleet_subnets_moved.inc(subnets_moved)
        flight_recorder.record("fleet_rebalance", subnets=subnets_moved)

    # -- priority-lane dispatcher -------------------------------------------

    def bind_lane_depths(self, fn) -> None:
        """Register the dispatcher's live lane-state callback (feeds
        `/debug/lanes` and `lanes_snapshot()`)."""
        self._lane_depths_fn = fn
        for lane in ("block", "sync_committee", "aggregate", "attestation"):
            self.lane_depth.set(0, lane=lane)
        # initialize the overlap gauge too: a scrape before the first
        # flood must see 0.0, not an absent series (round-16 satellite)
        self.lane_overlap_fraction.set(0.0)

    def lane_depth_set(self, lane: str, n_sets: int) -> None:
        self.lane_depth.set(n_sets, lane=lane)

    def lane_shed(self, lane: str, n_sets: int) -> None:
        self.lane_sheds.inc(n_sets, lane=lane)
        flight_recorder.record("lane_shed", lane=lane, sets=n_sets)

    def lane_coalesce(self, n_sets: int) -> None:
        self.lane_coalesced_sets.observe(n_sets)

    def lane_overlap(self, overlapped: bool) -> None:
        with self._lane_lock:
            self._lane_batches += 1
            if overlapped:
                self._lane_overlapped += 1
            self.lane_overlap_fraction.set(
                self._lane_overlapped / self._lane_batches
            )

    def lanes_snapshot(self) -> dict | None:
        """Lane-dispatcher state for the bench document and `/debug/lanes`;
        None until a dispatcher binds its depth callback."""
        if self._lane_depths_fn is None:
            return None
        sheds = {
            labels.get("lane", ""): int(v)
            for labels, v in self.lane_sheds.collect()
        }
        with self._lane_lock:
            batches = self._lane_batches
            overlapped = self._lane_overlapped
        snap = dict(self._lane_depths_fn())
        snap["sheds"] = sheds
        snap["batches"] = batches
        snap["overlapped_batches"] = overlapped
        snap["overlap_fraction"] = (
            round(overlapped / batches, 4) if batches else 0.0
        )
        return snap

    # -- compile ledger / cold start ----------------------------------------

    def compile_event(self, kernel: str, cache: str, seconds: float,
                      cumulative_s: float | None = None) -> None:
        """One first-dispatch compile observed by the ledger (the ledger
        fans this out to every live pipeline — don't call directly)."""
        self.compile_events.inc(kernel=kernel, cache=cache)
        self.compile_seconds.inc(seconds, kernel=kernel)
        if cumulative_s is not None:
            self.compile_cumulative.set(cumulative_s)

    def aot_event(self, kernel: str, outcome: str) -> None:
        """One AOT-store event observed by the compile ledger (the ledger
        fans this out to every live pipeline — don't call directly)."""
        self.aot_events.inc(kernel=kernel, outcome=outcome)

    def cache_pruned(self, removed_bytes: int, entries_remaining: int) -> None:
        """One compile-cache prune pass (tools/prune_compile_cache.py)."""
        if removed_bytes:
            self.compile_cache_pruned.inc(removed_bytes)
        self.compile_cache_entries.set(entries_remaining)

    def startup_phase(self, phase: str, seconds: float) -> None:
        self.startup_phase_seconds.set(seconds, phase=phase)

    def serving_ready(self, seconds: float) -> None:
        self.serving_ready_gauge.set(seconds)

    def set_build_info(self, info: dict) -> None:
        """Export the runtime identity as the constant-1 build-info gauge
        (missing keys render as "unknown" so a partial dict never throws
        a label mismatch at startup)."""
        labels = {
            k: str(info.get(k, "unknown")) for k in BUILD_INFO_LABELS
        }
        self.build_info.set(1, **labels)

    # -- SLO engine ---------------------------------------------------------

    def slo_report(self, objective: str, burning: bool,
                   budget_remaining: float, burn_short: float,
                   burn_long: float) -> None:
        """One objective's state after an engine evaluation (the SLO
        engine fans this out — don't call directly)."""
        self.slo_burning.set(1 if burning else 0, objective=objective)
        self.slo_budget_remaining.set(budget_remaining, objective=objective)
        self.slo_burn_rate.set(burn_short, objective=objective, window="short")
        self.slo_burn_rate.set(burn_long, objective=objective, window="long")

    def slo_evaluated(self) -> None:
        self.slo_evaluations.inc()

    # -- device-time & memory ledger ----------------------------------------

    def device_dispatch_time(self, lane: str, kernel: str, chip: str,
                             busy_s: float, overlap_s: float = 0.0) -> None:
        """One dispatch's attributed device time for one chip (the device
        ledger fans this out — don't call directly)."""
        self.device_dispatch_seconds.inc(
            busy_s, lane=lane, kernel=kernel, chip=chip
        )
        if overlap_s:
            self.device_overlap_seconds.inc(
                overlap_s, lane=lane, kernel=kernel, chip=chip
            )

    def device_idle(self, idle_s: float) -> None:
        self.device_idle_wall.set(idle_s)

    def device_memory_sample(self, chip: str, kind: str, value: float) -> None:
        self.device_memory.set(value, chip=chip, kind=kind)

    def device_memory_watermark_set(self, chip: str, value: float) -> None:
        self.device_memory_watermark.set(value, chip=chip)

    # -- queue / flush ------------------------------------------------------

    def bind_buffer_depth(self, fn) -> None:
        self.buffer_depth.set_function(fn)

    def flush(self, reason: str, latency_s: float | None = None) -> None:
        self.flushes.inc(reason=reason)
        if latency_s is not None:
            self.flush_seconds.observe(latency_s)

    def device_busy_sample(self, busy_s: float) -> None:
        """Accumulate one dispatch's device-busy seconds; refresh the
        busy-fraction gauge once per >=1 s wall window (short windows are
        all noise at ms dispatch times)."""
        now = time.monotonic()
        with self._busy_lock:
            self._busy_accum += busy_s
            elapsed = now - self._busy_window_t0
            if elapsed >= 1.0:
                self.device_busy.set(min(1.0, self._busy_accum / elapsed))
                self._busy_accum = 0.0
                self._busy_window_t0 = now

    # -- snapshots (bench emitter) -----------------------------------------

    def stage_snapshot(self) -> dict:
        """{stage: {"sum_s", "count"}} for every stage observed so far."""
        out = {}
        for labels, _ in self.stage_seconds._counts.items():
            stage = labels[0]
            out[stage] = {
                "sum_s": round(self.stage_seconds._sums[labels], 6),
                "count": self.stage_seconds._totals[labels],
            }
        return out

    def planner_snapshot(self) -> dict:
        decisions = {
            labels.get("path", ""): int(v)
            for labels, v in self.planner_decisions.collect()
        }
        sets = {
            labels.get("path", ""): int(v)
            for labels, v in self.planner_sets.collect()
        }
        caches = {
            f'{labels["cache"]}_{labels["outcome"]}': int(v)
            for labels, v in self.cache_events.collect()
        }
        return {"decisions": decisions, "sets": sets, "cache_events": caches}

    def bisect_snapshot(self) -> dict:
        """Bisection-verdict counters for the bench document: batch
        outcomes, total rounds walked, total nodes probed, and the
        decompress→host-marshal downgrade count."""
        outcomes = {
            labels.get("outcome", ""): int(v)
            for labels, v in self.bisect_batches.collect()
        }
        return {
            "batches": outcomes,
            "rounds": int(self.bisect_rounds_total.value()),
            "probes": int(self.bisect_probes_total.value()),
            "decompress_fallbacks": int(self.decompress_fallbacks.value()),
        }

    def mesh_snapshot(self) -> dict:
        """Mesh-serving counters for the bench document and `/debug/mesh`:
        current shape, eviction/re-admission history, per-chip dispatches."""
        evictions = {
            labels.get("reason", ""): int(v)
            for labels, v in self.mesh_evictions.collect()
        }
        dispatches = {
            labels.get("chip", ""): int(v)
            for labels, v in self.mesh_dispatches.collect()
        }
        return {
            "size": int(self.mesh_size.value()),
            "evicted": int(self.mesh_evicted.value()),
            "evictions": evictions,
            "readmissions": int(self.mesh_readmissions.value()),
            "chip_dispatches": dispatches,
        }

    def fleet_snapshot(self) -> dict:
        """Fleet-serving counters for the bench document and
        `/debug/fleet`: host gauges, per-host dispatches, DCN seconds and
        the eviction/rebalance history."""
        evictions = {
            labels.get("reason", ""): int(v)
            for labels, v in self.fleet_host_evictions.collect()
        }
        dispatches = {
            labels.get("host", ""): int(v)
            for labels, v in self.fleet_host_dispatches.collect()
        }
        return {
            "hosts": int(self.fleet_hosts.value()),
            "evicted_hosts": int(self.fleet_evicted_hosts.value()),
            "host_evictions": evictions,
            "host_dispatches": dispatches,
            "dcn_collective_seconds": round(self.fleet_dcn.value(), 6),
            "rebalances": int(self.fleet_rebalances.value()),
            "subnets_moved": int(self.fleet_subnets_moved.value()),
        }

    def supervisor_snapshot(self) -> dict:
        """Failure-policy counters for the bench document and
        `/debug/breaker`. `degraded` is the one-bit summary the bench
        regression gate keys on: a round that ran any CPU fallback, an
        open breaker, or an armed fault plan is not comparing the device
        path and must not gate device-perf history."""
        from ..testing import faults

        fallbacks = {
            labels.get("reason", ""): int(v)
            for labels, v in self.supervisor_fallbacks.collect()
        }
        canary = {
            labels.get("outcome", ""): int(v)
            for labels, v in self.supervisor_canary.collect()
        }
        fault_snap = faults.snapshot()
        snap = {
            "breaker_state": int(self.supervisor_breaker_state.value()),
            "fallbacks": fallbacks,
            "retries": int(self.supervisor_retries.value()),
            "deadline_exceeded": int(self.supervisor_deadline_exceeded.value()),
            "canary": canary,
            "both_tiers_failed": int(
                self.supervisor_both_tiers_failed.value()
            ),
            "verdict_mismatches": int(
                self.supervisor_verdict_mismatches.value()
            ),
            "waiter_timeouts": int(self.waiter_timeouts.value()),
            "faults": fault_snap,
        }
        # negative_audit alone is NOT degradation: auditing a genuinely
        # invalid batch on the oracle is the healthy-path design
        tier_fallbacks = sum(
            v for k, v in fallbacks.items() if k != "negative_audit"
        )
        snap["degraded"] = bool(
            snap["breaker_state"]
            or tier_fallbacks
            or snap["deadline_exceeded"]
            or snap["both_tiers_failed"]
            or snap["verdict_mismatches"]
            or fault_snap["active"]
            or fault_snap["injected"]
            # a mesh currently missing chips serves real traffic but its
            # throughput is not comparable to a full-mesh round
            or int(self.mesh_evicted.value())
        )
        return snap


def create_pipeline_metrics(registry: MetricsRegistry) -> PipelineMetrics:
    """Register the pipeline families on an existing node registry."""
    return PipelineMetrics(registry)


_default: PipelineMetrics | None = None
_default_lock = threading.Lock()


def default_pipeline() -> PipelineMetrics:
    """Process-local fallback instance for unwired verifiers (bench,
    tools, ad-hoc scripts). Node code should wire `m.pipeline` from
    `create_beacon_metrics` instead so the families reach `/metrics`."""
    global _default
    with _default_lock:
        if _default is None:
            _default = PipelineMetrics()
        return _default


def peek_default() -> PipelineMetrics | None:
    """The default pipeline IF one already exists — never creates one.
    CLI tools (prune_compile_cache) use this so ticking a counter doesn't
    spin up a registry in a process that never had one."""
    with _default_lock:
        return _default
