"""Declarative SLO engine: objectives -> error budgets -> burn alerts.

The repo can *measure* almost everything — stage timers, lifecycle
spans, the compile ledger and cold-start gauge, lane metrics — but until
this module nothing *judged* those measurements. `SloEngine` closes the
"metrics -> objectives -> alerts" ladder the reference node's operational
story is built on:

- Objectives live in a committed rules file (`dashboards/slo_rules.json`
  by default, `LODESTAR_TPU_SLO_RULES` overrides) — name, source metric
  family, SLI kind, threshold/target, runbook link. The file is linted
  by `tools/check_dashboards.py` so a typo'd source metric fails tier-1,
  not an on-call page.
- Each evaluation reads the SLI straight out of the live
  `PipelineMetrics` registry (no scrape loop, no sidecar) and appends a
  cumulative (bad, total) sample to a bounded per-objective history.
- Burn state is Google-SRE multi-window: an objective is `burning` only
  when BOTH the short (5 m) and long (1 h) windows exceed its
  `burn_threshold` — short-only spikes don't page, long-only drifts
  don't page late. Zero-tolerance objectives (target 1.0 / counter_zero)
  burn on any bad event above `allowed` in both windows.
- Results export as the `lodestar_slo_*` families on every attached
  pipeline, serve `/debug/slo`, embed in every bench emission, and gate
  `tools/bench_compare.py` — a round that burns a budget fails with a
  named objective instead of a raw-number diff.

SLI kinds (each yields cumulative `good`/`bad`/`total` event counts):

    counter_zero     bad = counter sum over an optional label subset;
                     zero-tolerance (any bad above `allowed` burns)
    histogram_under  good = observations <= `threshold` (largest bucket
                     boundary <= threshold), total = all observations
    gauge_under      one sample per evaluation: good while the gauge
                     reads <= `threshold`; an unset gauge contributes
                     no sample (a node that never reported can't burn)
    label_ratio      good/bad = counter sums over `good_label` /
                     `bad_label` subsets (e.g. compile cache hit/miss)

Like the flight recorder and compile ledger this module is stdlib-only,
import-light, and never raises into the serving path (`poke()` swallows
and records evaluation errors).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from . import flight_recorder
from ..utils.env import env_float, env_str

__all__ = [
    "SloEngine",
    "load_rules",
    "install",
    "engine",
    "poke",
    "snapshot_or_none",
    "DEFAULT_RULES_PATH",
    "VALID_KINDS",
]

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)
DEFAULT_RULES_PATH = os.path.join(REPO_ROOT, "dashboards", "slo_rules.json")

VALID_KINDS = ("counter_zero", "histogram_under", "gauge_under", "label_ratio")

# bounded per-objective history: at one sample per scrape/poke this
# covers the 1 h long window with plenty of slack
MAX_SAMPLES = 4096

_EPS = 1e-9


def load_rules(path: str | None = None) -> dict:
    """Load + validate the rules file; raises ValueError on a malformed
    document (check_dashboards lints the committed file in tier-1)."""
    if path is None:
        path = env_str("LODESTAR_TPU_SLO_RULES") or DEFAULT_RULES_PATH
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    validate_rules(doc)
    doc["_path"] = path
    return doc


def validate_rules(doc: dict) -> None:
    """Schema check shared with tools/check_dashboards.py."""
    if not isinstance(doc, dict):
        raise ValueError("rules document is not a JSON object")
    windows = doc.get("windows")
    if not isinstance(windows, dict):
        raise ValueError("rules document has no `windows` object")
    for key in ("short_s", "long_s"):
        if not isinstance(windows.get(key), (int, float)) or windows[key] <= 0:
            raise ValueError(f"windows.{key} must be a positive number")
    if windows["short_s"] >= windows["long_s"]:
        raise ValueError("windows.short_s must be < windows.long_s")
    objectives = doc.get("objectives")
    if not isinstance(objectives, list) or not objectives:
        raise ValueError("rules document has no objectives")
    seen: set[str] = set()
    for obj in objectives:
        if not isinstance(obj, dict):
            raise ValueError("objective entries must be JSON objects")
        name = obj.get("name")
        if not name or not isinstance(name, str):
            raise ValueError("objective without a name")
        if name in seen:
            raise ValueError(f"duplicate objective name {name!r}")
        seen.add(name)
        if not obj.get("source"):
            raise ValueError(f"objective {name!r} has no source metric")
        kind = obj.get("kind")
        if kind not in VALID_KINDS:
            raise ValueError(
                f"objective {name!r} has unknown kind {kind!r} "
                f"(valid: {', '.join(VALID_KINDS)})"
            )
        if kind in ("histogram_under", "gauge_under") and not isinstance(
            obj.get("threshold"), (int, float)
        ):
            raise ValueError(f"objective {name!r} ({kind}) needs a numeric "
                             "threshold")
        if kind == "label_ratio":
            for key in ("good_label", "bad_label"):
                if not isinstance(obj.get(key), dict):
                    raise ValueError(
                        f"objective {name!r} (label_ratio) needs {key}"
                    )


def _labels_match(labels: dict, subset: dict | None) -> bool:
    if not subset:
        return True
    return all(labels.get(k) == str(v) for k, v in subset.items())


def _find_metric(registry, name: str):
    for m in registry._metrics:
        if m.name == name:
            return m
    return None


class _Objective:
    """One objective's spec + bounded sample history + burn state."""

    def __init__(self, spec: dict):
        self.spec = spec
        self.name = spec["name"]
        self.kind = spec["kind"]
        self.source = spec["source"]
        self.target = float(spec.get("target", 1.0))
        self.threshold = float(spec.get("threshold", 0.0))
        self.burn_threshold = float(spec.get("burn_threshold", 1.0))
        self.allowed = float(spec.get("allowed", 0))
        self.budget = max(0.0, 1.0 - self.target)
        # cumulative (t, bad, total) samples, oldest first
        self.samples: deque[tuple] = deque(maxlen=MAX_SAMPLES)
        self.state: str | None = None
        # gauge_under keeps its own cumulative sample counters (the
        # gauge itself has no event count to delta)
        self.gauge_bad = 0
        self.gauge_total = 0

    # -- SLI readers (cumulative good/bad/total) ---------------------------

    def read(self, registry) -> tuple[float, float] | None:
        """Cumulative (bad, total) event counts, or None when the source
        metric is absent from the registry."""
        metric = _find_metric(registry, self.source)
        if metric is None:
            return None
        reader = getattr(self, f"_read_{self.kind}")
        return reader(metric)

    def _read_counter_zero(self, metric):
        bad = sum(
            v for labels, v in metric.collect()
            if _labels_match(labels, self.spec.get("labels"))
        )
        return bad, bad

    def _read_histogram_under(self, metric):
        idx = None
        for i, b in enumerate(metric.buckets):
            if b <= self.threshold + _EPS:
                idx = i
        good = 0
        total = 0
        subset = self.spec.get("labels")
        for key, counts in list(metric._counts.items()):
            labels = dict(zip(metric.label_names, key))
            if not _labels_match(labels, subset):
                continue
            if idx is not None:
                good += counts[idx]
            total += metric._totals.get(key, 0)
        return float(total - good), float(total)

    def _read_gauge_under(self, metric):
        value = None
        for labels, v in metric.collect():
            if _labels_match(labels, self.spec.get("labels")):
                value = v if value is None else max(value, v)
        if value is not None:
            self.gauge_total += 1
            if value > self.threshold + _EPS:
                self.gauge_bad += 1
        return float(self.gauge_bad), float(self.gauge_total)

    def _read_label_ratio(self, metric):
        good = sum(
            v for labels, v in metric.collect()
            if _labels_match(labels, self.spec["good_label"])
        )
        bad = sum(
            v for labels, v in metric.collect()
            if _labels_match(labels, self.spec["bad_label"])
        )
        return float(bad), float(good + bad)

    # -- burn math ---------------------------------------------------------

    def _window_delta(self, now: float, window_s: float) -> tuple[float, float]:
        """(bad, total) accrued inside the trailing window: newest sample
        minus the anchor (latest sample at least `window_s` old, falling
        back to the oldest — a young engine reports its whole history)."""
        newest = self.samples[-1]
        anchor = self.samples[0]
        for sample in self.samples:
            if now - sample[0] >= window_s - _EPS:
                anchor = sample
            else:
                break
        return newest[1] - anchor[1], newest[2] - anchor[2]

    def _burn_rate(self, bad: float, total: float) -> float:
        if self.budget > _EPS:
            if total <= 0:
                return 0.0
            return (bad / total) / self.budget
        # zero-tolerance: the "rate" is the raw bad-event count
        return float(bad)

    def _is_burning(self, rate_short: float, rate_long: float) -> bool:
        if self.budget > _EPS:
            return (rate_short >= self.burn_threshold
                    and rate_long >= self.burn_threshold)
        return rate_short > self.allowed and rate_long > self.allowed

    def budget_remaining(self) -> float:
        """Fraction of the error budget left since the engine started."""
        first, last = self.samples[0], self.samples[-1]
        bad = last[1] - first[1]
        total = last[2] - first[2]
        if self.budget > _EPS:
            if total <= 0:
                return 1.0
            return max(0.0, min(1.0, 1.0 - (bad / total) / self.budget))
        return 1.0 if bad <= self.allowed else 0.0


class SloEngine:
    """Evaluates the committed objectives over a live PipelineMetrics."""

    def __init__(self, pipeline, rules: dict | None = None,
                 rules_path: str | None = None, clock=time.monotonic):
        if rules is None:
            rules = load_rules(rules_path)
        else:
            validate_rules(rules)
        self._pipeline = pipeline
        self._clock = clock
        self._lock = threading.Lock()
        self._rules_path = rules.get("_path")
        self.short_s = float(rules["windows"]["short_s"])
        self.long_s = float(rules["windows"]["long_s"])
        self._objectives = [_Objective(o) for o in rules["objectives"]]  # guarded-by: _lock
        self._evaluations = 0  # guarded-by: _lock
        # baseline sample: budgets start full at engine install, so
        # pre-engine history (e.g. warmup compiles) doesn't page
        self.evaluate()

    def objectives(self) -> list[str]:
        return [o.name for o in self._objectives]

    def evaluate(self) -> list[dict]:
        """One evaluation pass: sample every objective, update burn
        state, export the `lodestar_slo_*` families. Returns the
        per-objective reports."""
        now = self._clock()
        reports = []
        with self._lock:
            self._evaluations += 1
            for obj in self._objectives:
                reports.append(self._evaluate_one_locked(obj, now))
        pipeline = self._pipeline
        if pipeline is not None:
            pipeline.slo_evaluated()
            for rep in reports:
                if rep["state"] == "absent":
                    continue
                pipeline.slo_report(
                    rep["name"], rep["state"] == "burning",
                    rep["budget_remaining"], rep["burn_rate_short"],
                    rep["burn_rate_long"],
                )
        return reports

    def _evaluate_one_locked(self, obj: _Objective, now: float) -> dict:
        sli = obj.read(self._pipeline.registry) if self._pipeline else None
        base = {
            "name": obj.name,
            "description": obj.spec.get("description", ""),
            "kind": obj.kind,
            "source": obj.source,
            "target": obj.target,
            "runbook": obj.spec.get("runbook", ""),
        }
        if sli is None:
            # source family missing from this registry (partial wiring):
            # report, don't crash — check_dashboards catches typos
            base.update(state="absent", burn_rate_short=0.0,
                        burn_rate_long=0.0, budget_remaining=1.0,
                        bad_events=0, total_events=0)
            return base
        bad, total = sli
        obj.samples.append((now, bad, total))
        bad_s, total_s = obj._window_delta(now, self.short_s)
        bad_l, total_l = obj._window_delta(now, self.long_s)
        rate_short = obj._burn_rate(bad_s, total_s)
        rate_long = obj._burn_rate(bad_l, total_l)
        state = "burning" if obj._is_burning(rate_short, rate_long) else "ok"
        if obj.state is not None and state != obj.state:
            flight_recorder.record(
                "slo_transition", objective=obj.name, state=state,
                burn_short=round(rate_short, 4),
                burn_long=round(rate_long, 4),
            )
        obj.state = state
        first = obj.samples[0]
        base.update(
            state=state,
            burn_rate_short=round(rate_short, 4),
            burn_rate_long=round(rate_long, 4),
            budget_remaining=round(obj.budget_remaining(), 4),
            bad_events=bad - first[1],
            total_events=total - first[2],
        )
        return base

    def snapshot(self) -> dict:
        """The `/debug/slo` + bench-section document (evaluates first, so
        every read is live)."""
        reports = self.evaluate()
        with self._lock:
            evaluations = self._evaluations
        return {
            "rules_path": self._rules_path,
            "windows": {"short_s": self.short_s, "long_s": self.long_s},
            "evaluations": evaluations,
            "burning": sorted(
                r["name"] for r in reports if r["state"] == "burning"
            ),
            "objectives": reports,
        }


# -- process-wide singleton ---------------------------------------------------

_engine: SloEngine | None = None
_engine_lock = threading.Lock()
# None (not 0.0): monotonic() starts near zero on a fresh boot, so a
# zero sentinel would rate-limit the very first poke of the process
_last_poke: float | None = None


def install(pipeline, rules: dict | None = None,
            rules_path: str | None = None, clock=time.monotonic) -> SloEngine:
    """Create the process-wide engine over `pipeline` (replaces any prior
    install — node startup, warmup and bench each install over the
    pipeline they actually serve)."""
    global _engine
    eng = SloEngine(pipeline, rules=rules, rules_path=rules_path, clock=clock)
    with _engine_lock:
        _engine = eng
    return eng


def engine() -> SloEngine | None:
    """The installed engine, or None — never creates one (an engine
    without a deliberately chosen pipeline would judge nothing)."""
    with _engine_lock:
        return _engine


def snapshot_or_none() -> dict | None:
    """`/debug/slo` provider: None while no engine is installed."""
    eng = engine()
    return eng.snapshot() if eng is not None else None


def poke() -> None:
    """Event-driven re-evaluation from hot-ish paths (the supervisor's
    device-failure ladder): rate-limited by LODESTAR_TPU_SLO_POKE_S and
    never raises into the caller."""
    global _last_poke
    eng = engine()
    if eng is None:
        return
    min_s = env_float("LODESTAR_TPU_SLO_POKE_S")
    now = time.monotonic()
    with _engine_lock:
        if min_s and _last_poke is not None and now - _last_poke < min_s:
            return
        _last_poke = now
    try:
        eng.evaluate()
    except Exception as e:
        flight_recorder.record("slo_poke_error", error=str(e))


def _reset_for_tests() -> None:
    global _engine, _last_poke
    with _engine_lock:
        _engine = None
        _last_poke = None
