"""Light client: trust-minimized chain following.

Reference: `light-client/src/index.ts` (Lightclient) + `validation.ts`
(assertValidLightClientUpdate): bootstrap from a trusted block root,
then apply sync-committee-signed updates — verifying committee merkle
proofs, finality proofs and the aggregate BLS signature — tracking
optimistic and finalized headers with only headers + proofs.
"""

from __future__ import annotations

from ..bls import api as bls
from ..config.beacon_config import compute_signing_root
from ..params import (
    DOMAIN_SYNC_COMMITTEE,
    CURRENT_SYNC_COMMITTEE_DEPTH,
    CURRENT_SYNC_COMMITTEE_GINDEX,
    FINALIZED_ROOT_DEPTH,
    FINALIZED_ROOT_GINDEX,
    NEXT_SYNC_COMMITTEE_DEPTH,
    NEXT_SYNC_COMMITTEE_GINDEX,
)
from ..state_transition import util as st_util


class LightClientError(ValueError):
    pass


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise LightClientError(msg)


def _verify_branch(leaf: bytes, branch, gindex: int, depth: int, root: bytes) -> bool:
    return st_util.is_valid_merkle_branch(
        leaf, [bytes(b) for b in branch], depth, gindex % (1 << depth), root
    )


class Lightclient:
    def __init__(self, config, types, preset):
        self.config = config
        self.types = types
        self.preset = preset
        self.finalized_header = None
        self.optimistic_header = None
        self.current_sync_committee = None
        self.next_sync_committee = None

    # -- bootstrap -----------------------------------------------------------

    def bootstrap(self, trusted_block_root: bytes, bootstrap) -> None:
        header = bootstrap.header
        _require(
            header.hash_tree_root() == trusted_block_root,
            "bootstrap header != trusted root",
        )
        _require(
            _verify_branch(
                bootstrap.current_sync_committee.hash_tree_root(),
                bootstrap.current_sync_committee_branch,
                CURRENT_SYNC_COMMITTEE_GINDEX,
                CURRENT_SYNC_COMMITTEE_DEPTH,
                bytes(header.state_root),
            ),
            "invalid current sync committee proof",
        )
        self.finalized_header = header.copy()
        self.optimistic_header = header.copy()
        self.current_sync_committee = bootstrap.current_sync_committee.copy()

    # -- update processing ---------------------------------------------------

    def _period(self, slot: int) -> int:
        return slot // (
            self.preset.SLOTS_PER_EPOCH * self.preset.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
        )

    def process_update(self, update) -> None:
        """assertValidLightClientUpdate + apply (simplified store: no
        best-valid-update/UPDATE_TIMEOUT machinery — updates are applied
        when finality-proven and supermajority-signed)."""
        _require(self.finalized_header is not None, "not bootstrapped")
        attested = update.attested_header
        _require(
            update.signature_slot > attested.slot,
            "signature slot not after attested slot",
        )
        _require(
            attested.slot >= self.finalized_header.slot,
            "update older than finalized header",
        )
        attested_period = self._period(attested.slot)
        store_period = self._period(self.finalized_header.slot)
        _require(
            attested_period in (store_period, store_period + 1),
            "update outside current/next period",
        )

        # next-sync-committee proof against the attested state
        _require(
            _verify_branch(
                update.next_sync_committee.hash_tree_root(),
                update.next_sync_committee_branch,
                NEXT_SYNC_COMMITTEE_GINDEX,
                NEXT_SYNC_COMMITTEE_DEPTH,
                bytes(attested.state_root),
            ),
            "invalid next sync committee proof",
        )
        # finality proof. Spec zero-case: before any finalization the
        # attested state's finalized root is ZERO — the update then carries
        # an empty header and the proof is verified against the zero leaf.
        has_finality = any(bytes(b) != b"\x00" * 32 for b in update.finality_branch)
        is_empty_header = (
            update.finalized_header == self.types.BeaconBlockHeader()
        )
        if has_finality:
            leaf = (
                b"\x00" * 32
                if is_empty_header
                else update.finalized_header.hash_tree_root()
            )
            _require(
                _verify_branch(
                    leaf,
                    update.finality_branch,
                    FINALIZED_ROOT_GINDEX,
                    FINALIZED_ROOT_DEPTH,
                    bytes(attested.state_root),
                ),
                "invalid finality proof",
            )
        has_finality = has_finality and not is_empty_header

        # sync-aggregate signature: signer committee is selected by the
        # SIGNATURE slot's period (spec validate_light_client_update) —
        # keying off the attested period stalls at every period boundary
        self._verify_sync_aggregate(
            attested, update.sync_aggregate, update.signature_slot
        )

        # apply (spec apply_light_client_update): committee rotation keys
        # off the FINALIZED period so store_period and the committees stay
        # consistent — rotating on the attested period desyncs the selector
        # and permanently stalls the client after the first cross-period
        # update
        update_finalized_period = (
            self._period(update.finalized_header.slot)
            if has_finality
            else store_period
        )
        if self.next_sync_committee is None:
            _require(
                update_finalized_period == store_period,
                "cannot learn next committee from a future-period update",
            )
            self.next_sync_committee = update.next_sync_committee.copy()
        elif update_finalized_period == store_period + 1:
            self.current_sync_committee = self.next_sync_committee
            self.next_sync_committee = update.next_sync_committee.copy()
        if attested.slot > self.optimistic_header.slot:
            self.optimistic_header = attested.copy()
        if has_finality and update.finalized_header.slot > self.finalized_header.slot:
            self.finalized_header = update.finalized_header.copy()

    def _committee_for_signature_slot(self, signature_slot: int):
        """Signer committee by the signature slot's period relative to the
        store (current period → current committee, next → next)."""
        _require(self.finalized_header is not None, "not bootstrapped")
        sig_period = self._period(signature_slot)
        store_period = self._period(self.finalized_header.slot)
        if sig_period == store_period:
            committee = self.current_sync_committee
        elif sig_period == store_period + 1:
            committee = self.next_sync_committee
        else:
            committee = None
        _require(committee is not None, "no committee for signature period")
        return committee

    def _verify_sync_aggregate(self, attested, aggregate, signature_slot: int):
        committee = self._committee_for_signature_slot(signature_slot)
        bits = list(aggregate.sync_committee_bits)
        participants = [bytes(pk) for pk, b in zip(committee.pubkeys, bits) if b]
        _require(
            3 * len(participants) >= 2 * len(bits), "insufficient participation"
        )
        previous_slot = max(signature_slot, 1) - 1
        domain = self.config.get_domain(
            DOMAIN_SYNC_COMMITTEE,
            previous_slot,
            st_util.compute_epoch_at_slot(previous_slot, self.preset.SLOTS_PER_EPOCH),
        )
        root = compute_signing_root(attested.hash_tree_root(), domain)
        pks = [bls.PublicKey.from_bytes(pk, validate=False) for pk in participants]
        sig = bls.Signature.from_bytes(
            bytes(aggregate.sync_committee_signature), validate=False
        )
        _require(bls.fast_aggregate_verify(pks, root, sig), "bad sync signature")

    def process_optimistic_update(self, update) -> None:
        """Header-only fast path (SSE optimistic updates)."""
        _require(self.finalized_header is not None, "not bootstrapped")
        attested = update.attested_header
        if self.optimistic_header is None or attested.slot > self.optimistic_header.slot:
            self._verify_sync_aggregate(
                attested, update.sync_aggregate, update.signature_slot
            )
            self.optimistic_header = attested.copy()

    def process_finality_update(self, update) -> None:
        """SSE finality updates: verified finality proof + aggregate
        advance the finalized header (reference processFinalizedUpdate)."""
        _require(self.finalized_header is not None, "not bootstrapped")
        finalized = update.finalized_header
        if finalized.slot <= self.finalized_header.slot:
            return  # stale
        _require(
            _verify_branch(
                finalized.hash_tree_root(),
                update.finality_branch,
                FINALIZED_ROOT_GINDEX,
                FINALIZED_ROOT_DEPTH,
                bytes(update.attested_header.state_root),
            ),
            "invalid finality proof",
        )
        self._verify_sync_aggregate(
            update.attested_header, update.sync_aggregate, update.signature_slot
        )
        self.finalized_header = finalized.copy()
        if (
            self.optimistic_header is None
            or update.attested_header.slot > self.optimistic_header.slot
        ):
            self.optimistic_header = update.attested_header.copy()
