"""Light-client server: produce bootstrap + updates at block import.

Reference: `beacon-node/src/chain/lightClient/index.ts` — on block import
the server stores the attested header's committee proofs and keeps the
best (most-participated) update per sync-committee period; bootstrap is
served for finalized checkpoints.
"""

from __future__ import annotations

from collections import OrderedDict

MAX_BOOTSTRAP_ENTRIES = 4096


def block_to_header(types, signed_block, state_root: bytes | None = None):
    msg = signed_block.message
    return types.BeaconBlockHeader(
        slot=msg.slot,
        proposer_index=msg.proposer_index,
        parent_root=bytes(msg.parent_root),
        state_root=state_root if state_root is not None else bytes(msg.state_root),
        body_root=msg.body.hash_tree_root(),
    )


class LightClientServer:
    def __init__(self, config, types, preset):
        self.config = config
        self.types = types
        self.preset = preset
        # period → best LightClientUpdate
        self.best_update_by_period: dict[int, object] = {}
        self.latest_finality_update = None
        self.latest_optimistic_update = None
        # block root → bootstrap data, LRU-bounded (the reference prunes
        # non-checkpoint data; unbounded growth would track chain length)
        self._bootstrap_by_root: "OrderedDict[bytes, object]" = OrderedDict()

    def _period(self, slot: int) -> int:
        return slot // (
            self.preset.SLOTS_PER_EPOCH * self.preset.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
        )

    # -- import hook ---------------------------------------------------------

    def on_import_block(self, signed_block, attested_block, attested_state_cached) -> None:
        """Called after importing `signed_block` (whose sync_aggregate signs
        `attested_block`). The attested (parent) state provides the
        committees and finality proof."""
        types = self.types
        body = signed_block.message.body
        if not hasattr(body, "sync_aggregate"):
            return
        aggregate = body.sync_aggregate
        participation = sum(1 for b in aggregate.sync_committee_bits if b)
        att_state = attested_state_cached.state
        # ONE pass over the state's field roots yields the root and every
        # branch we need — no per-field re-merkleization on the import path
        state_type = type(att_state).ssz_type
        state_root, branches = state_type.get_field_branches(
            att_state,
            ["current_sync_committee", "next_sync_committee", "finalized_checkpoint"],
        )
        att_header = block_to_header(types, attested_block, state_root)

        # record bootstrap data for the attested block (LRU-bounded)
        boot_root = att_header.hash_tree_root()
        self._bootstrap_by_root[boot_root] = types.LightClientBootstrap(
            header=att_header.copy(),
            current_sync_committee=att_state.current_sync_committee.copy(),
            current_sync_committee_branch=branches["current_sync_committee"],
        )
        self._bootstrap_by_root.move_to_end(boot_root)
        while len(self._bootstrap_by_root) > MAX_BOOTSTRAP_ENTRIES:
            self._bootstrap_by_root.popitem(last=False)

        # finality proof from the attested state. Zero checkpoint root
        # (pre-finality) → empty header + real branch (spec zero-leaf
        # case); nonzero root with no known header → drop the finality
        # claim entirely (zeroed branch) rather than emit an unprovable one.
        fin_cp = att_state.finalized_checkpoint
        cp_type = type(fin_cp).ssz_type
        finality_branch = (
            cp_type.get_field_branch(fin_cp, "root") + branches["finalized_checkpoint"]
        )
        finalized_header = self._header_for_finalized(fin_cp)
        if (
            bytes(fin_cp.root) != b"\x00" * 32
            and finalized_header == types.BeaconBlockHeader()
        ):
            finality_branch = [b"\x00" * 32] * len(finality_branch)

        update = types.LightClientUpdate(
            attested_header=att_header.copy(),
            next_sync_committee=att_state.next_sync_committee.copy(),
            next_sync_committee_branch=branches["next_sync_committee"],
            finalized_header=finalized_header,
            finality_branch=finality_branch,
            sync_aggregate=aggregate.copy(),
            signature_slot=signed_block.message.slot,
        )
        period = self._period(att_header.slot)
        best = self.best_update_by_period.get(period)

        def score(u):
            # participation first, then finality-carrying, then freshness
            # (reference isBetterUpdate ordering)
            return (
                sum(1 for b in u.sync_aggregate.sync_committee_bits if b),
                any(bytes(b) != b"\x00" * 32 for b in u.finality_branch),
                u.attested_header.slot,
            )

        if best is None or score(update) > score(best):
            self.best_update_by_period[period] = update

        self.latest_optimistic_update = types.LightClientOptimisticUpdate(
            attested_header=att_header.copy(),
            sync_aggregate=aggregate.copy(),
            signature_slot=signed_block.message.slot,
        )
        if finalized_header.slot > 0:
            self.latest_finality_update = types.LightClientFinalityUpdate(
                attested_header=att_header.copy(),
                finalized_header=finalized_header.copy(),
                finality_branch=finality_branch,
                sync_aggregate=aggregate.copy(),
                signature_slot=signed_block.message.slot,
            )

    def _header_for_finalized(self, checkpoint):
        """Header of the finalized checkpoint block; empty header when
        nothing is finalized yet (genesis semantics)."""
        boot = self._bootstrap_by_root.get(bytes(checkpoint.root))
        if boot is not None:
            return boot.header.copy()
        return self.types.BeaconBlockHeader()

    # -- queries (reqresp/REST surface) --------------------------------------

    def get_bootstrap(self, block_root: bytes):
        return self._bootstrap_by_root.get(block_root)

    def get_updates(self, start_period: int, count: int) -> list:
        return [
            self.best_update_by_period[p]
            for p in range(start_period, start_period + count)
            if p in self.best_update_by_period
        ]
