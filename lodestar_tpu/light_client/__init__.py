"""Light client (SURVEY.md §2.1 `light-client` + §2.2 `chain/lightClient/`).

Server side (`LightClientServer`): derives sync-committee-signed updates
at block import — bootstrap (header + current committee + proof), best
`LightClientUpdate` per sync period, finality/optimistic updates
(reference: `chain/lightClient/index.ts:153,208`, proofs.ts).

Client side (`Lightclient`): follows the chain from a trusted block root
with nothing but headers, merkle proofs and sync-aggregate signatures
(reference: `light-client/src/index.ts`, validation.ts).
"""

from .server import LightClientServer  # noqa: F401
from .client import Lightclient, LightClientError  # noqa: F401
