"""REST-driven light-client follower.

Reference: `light-client/src/index.ts` `Lightclient.start` (SURVEY §3.5):
bootstrap from a trusted block root over the Beacon API, replay
sync-committee-period updates, then follow the head via the SSE event
stream's light-client optimistic/finality updates.
"""

from __future__ import annotations

from ..utils.logger import get_logger
from .client import Lightclient, LightClientError

log = get_logger("lightclient")


class RestLightclientFollower:
    """Wires a verifying `Lightclient` to a node's REST + SSE surface."""

    def __init__(self, config, types, preset, client, host: str, port: int):
        self.lc = Lightclient(config, types, preset)
        self.client = client  # BeaconApiClient
        self.host = host
        self.port = port
        self.types = types

    def start(self, trusted_block_root: bytes) -> None:
        """Bootstrap + catch up on period updates (reference start())."""
        boot_obj = self.client.getLightClientBootstrap(
            "0x" + trusted_block_root.hex()
        )
        bootstrap = self.types.LightClientBootstrap.from_obj(boot_obj)
        self.lc.bootstrap(trusted_block_root, bootstrap)
        self._catch_up()

    def _catch_up(self) -> None:
        period = self.lc._period(int(self.lc.finalized_header.slot))
        while True:
            updates = self.client.getLightClientUpdatesByRange(
                query={"start_period": str(period), "count": "8"}
            ) or []
            if not updates:
                return
            for obj in updates:
                update = self.types.LightClientUpdate.from_obj(obj)
                try:
                    self.lc.process_update(update)
                except LightClientError as e:
                    log.warning("update rejected: %s", e)
                    return
            if len(updates) < 8:
                return
            period += 8

    def follow(self, max_events: int | None = None, timeout: float = 30.0) -> int:
        """Consume SSE light-client events, verifying each; returns the
        number of applied updates (runs until the stream closes, the
        timeout passes without frames, or max_events is reached)."""
        from ..api.client import stream_events

        applied = 0
        for name, payload in stream_events(
            self.host,
            self.port,
            topics=["light_client_optimistic_update", "light_client_finality_update"],
            timeout=timeout,
        ):
            try:
                if name == "light_client_optimistic_update":
                    update = self.types.LightClientOptimisticUpdate.from_obj(payload)
                    self.lc.process_optimistic_update(update)
                else:
                    update = self.types.LightClientFinalityUpdate.from_obj(payload)
                    self.lc.process_finality_update(update)
                applied += 1
            except LightClientError as e:
                log.warning("streamed update rejected: %s", e)
            if max_events is not None and applied >= max_events:
                break
        return applied
