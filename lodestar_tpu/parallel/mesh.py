"""Production mesh dispatch: one logical BLS verifier served by N chips.

`parallel/sharded.py` holds the shard_map kernels; this module is the
HOST-SIDE policy that makes them the serving path (round-7 tentpole):

- device census → serving mesh: the largest power-of-two prefix of the
  healthy chips that divides the 64 constant Miller lanes
  (`sharded.mesh_divisor`); 1 healthy chip means "no mesh" and the
  caller's single-device kernels keep serving,
- lazy per-(kind, shape, chip-set) compile cache of sharded verifiers —
  an eviction changes the chip set, so survivors recompile (served from
  the persistent XLA cache when warm) while the old executables stay
  keyed under the old chip set for re-admission,
- the failure policy's mesh half: `evict()` removes a sick chip and
  shrinks the serving mesh (a 4-chip node keeps serving as a 3-healthy/
  2-serving mesh), `readmit()` restores the full census when the
  supervisor's canary passes — mirroring the reference's worker-pool
  model where a crashed worker is dropped and respawned
  (`chain/bls/multithread/index.ts`) rather than taking the node down,
- every transition and dispatch is recorded in the `lodestar_bls_mesh_*`
  families (observability/stages.py) so dashboards can tell a full node
  from a degraded one, and `testing/faults.on_mesh_dispatch` gives the
  chaos drill a seam to make a chip sick on demand.

The dispatcher itself never imports jax at module scope: unit tests
drive the eviction state machine with a stub `verifier_factory` and fake
device lists, no kernel compiles involved.
"""

from __future__ import annotations

import threading

from ..observability import device_ledger, trace
from ..observability.stages import PipelineMetrics, default_pipeline
from ..testing import faults as _faults
from ..utils.logger import get_logger

logger = get_logger("parallel.mesh")

__all__ = ["NOT_SHARDED", "BlsMeshDispatcher", "auto_mesh", "mesh_divisor"]

# the grouped kernels split the constant −[2^b]g1 Miller lanes across
# chips: 2·HALF_BITS of them (parallel/verifier) — the serving mesh must
# divide this count evenly
CONSTANT_LANES = 64


def mesh_divisor(n_devices: int) -> int:
    """Largest usable mesh size ≤ `n_devices`: the grouped kernels split
    the 64 constant Miller lanes across chips, so the serving mesh must
    divide 64. 64 is a power of two, so this walks powers of two — 5
    healthy chips serve as a 4-chip mesh, 3 as 2, 1 as none."""
    d = 1
    while d * 2 <= min(n_devices, CONSTANT_LANES) and CONSTANT_LANES % (d * 2) == 0:
        d *= 2
    return d

# returned by dispatch_* when this batch cannot shard (mesh too small,
# rows not divisible) — the caller falls through to its single-device
# kernel; distinct from None so a sharded `False` verdict can't be
# confused with "not handled"
NOT_SHARDED = object()


def _default_factory(kind: str, devices, axis: str):
    """Build the real shard_map verifier for `kind` over `devices`."""
    import numpy as np
    from jax.sharding import Mesh

    from . import sharded  # deferred: keeps this module jax-free at import

    cls = {
        "grouped": sharded.ShardedGroupedVerifier,
        "grouped_raw": sharded.ShardedGroupedRawVerifier,
        "pk_grouped": sharded.ShardedPkGroupedVerifier,
        "pk_grouped_raw": sharded.ShardedPkGroupedRawVerifier,
        "bisect": sharded.ShardedBisectVerifier,
    }[kind]
    return cls(Mesh(np.array(devices), axis_names=(axis,)), axis)


def _ledger_wrap_submit(v, kind: str, shape, chips) -> None:
    """Route a freshly built sharded verifier through the compile ledger:
    each (kind, shape, chip-set) verifier is exactly one shard_map
    compile, so the static key encodes shape+chips — a post-eviction mesh
    shrink recompiling on the serving path records a NEW event (the
    ROADMAP item-5 restart-story cost, now measured).

    The seam prefers the verifier's jitted `_run` over the `submit`
    facade: `_run` is the actual jit entry (it has `.lower`), which is
    what the ledger's AOT store needs to export a serialized executable —
    and what lets an evicted-mesh re-dispatch for an already-exported
    shrunk chip set load machine code from disk instead of entering XLA
    (ISSUE 19). Factory products without a rebindable `_run`/`submit`
    (test stubs with __slots__/properties) fall back or are left
    untouched."""
    from ..observability.compile_ledger import ledger

    kernel = f"sharded_{kind}"
    static_key = f"{tuple(shape)}@chips{','.join(str(c) for c in chips)}"
    if getattr(v, "_run", None) is not None:
        try:
            v._run = ledger().wrap(v._run, kernel, static_key=static_key)
            return
        except AttributeError:
            logger.debug("mesh: %s verifier _run not rebindable; trying "
                         "submit", kind)
    try:
        v.submit = ledger().wrap(v.submit, kernel, static_key=static_key)
    except AttributeError:
        logger.debug("mesh: %s verifier submit not rebindable; compile "
                     "ledger seam skipped", kind)


class BlsMeshDispatcher:
    """Routes grouped/pk-grouped/bisect batches onto the serving mesh and
    owns the evict/re-admit state machine. Thread-safe: the supervisor's
    failure path and the flush thread may race."""

    def __init__(self, devices, axis: str = "dp",
                 observer: PipelineMetrics | None = None,
                 verifier_factory=None):
        self.axis = axis
        self.observer = observer if observer is not None else default_pipeline()
        self._factory = verifier_factory or _default_factory
        self._devices = list(devices)
        self._lock = threading.Lock()
        # chip ids are indices into the census; eviction order is recorded
        # for /debug/mesh and for "evict the most recent suspect" defaults
        self._healthy: list[int] = list(range(len(self._devices)))
        self._evicted: list[dict] = []
        self._verifiers: dict = {}
        self._dispatches = 0
        self._publish()

    # -- census -------------------------------------------------------------

    @property
    def size(self) -> int:
        """Current serving-mesh size (chips actually dispatched to)."""
        return mesh_divisor(len(self._healthy))

    @property
    def enabled(self) -> bool:
        return self.size >= 2

    def _serving_chips(self) -> list[int]:
        return self._healthy[: self.size]

    def _publish(self) -> None:
        self.observer.mesh_state(self.size, len(self._evicted))

    # -- verifier cache -----------------------------------------------------

    def _verifier(self, kind: str, shape):
        with self._lock:
            chips = tuple(self._serving_chips())
            key = (kind, shape, chips)
            v = self._verifiers.get(key)
            if v is None:
                v = self._factory(
                    kind, [self._devices[c] for c in chips], self.axis
                )
                _ledger_wrap_submit(v, kind, shape, chips)
                self._verifiers[key] = v
            return v, chips

    # -- dispatch -----------------------------------------------------------

    def _pre_dispatch(self, kind: str, chips) -> None:
        _faults.on_mesh_dispatch(len(chips))
        with self._lock:
            self._dispatches += 1
        self.observer.mesh_dispatch(chips)

    def dispatch_grouped(self, g, a_bits, b_bits):
        """Sharded root-grouped dispatch; NOT_SHARDED when ineligible."""
        n = self.size
        if n < 2 or g.pk_x.shape[0] % n:
            return NOT_SHARDED
        v, chips = self._verifier("grouped", g.pk_x.shape[:2])
        self._pre_dispatch("grouped", chips)
        with trace.annotation(f"bls/mesh/grouped[{len(chips)}]"), \
                device_ledger.ledger().dispatch("grouped", chips):
            return v.submit(g, a_bits, b_bits)

    def dispatch_grouped_raw(self, g, sig_raw, a_bits, b_bits):
        """Sharded root-grouped RAW dispatch (wire-byte signatures,
        on-mesh decompression); NOT_SHARDED when ineligible."""
        n = self.size
        if n < 2 or g.pk_x.shape[0] % n:
            return NOT_SHARDED
        v, chips = self._verifier("grouped_raw", g.pk_x.shape[:2])
        self._pre_dispatch("grouped_raw", chips)
        with trace.annotation(f"bls/mesh/grouped_raw[{len(chips)}]"), \
                device_ledger.ledger().dispatch("grouped_raw", chips):
            return v.submit(g, sig_raw, a_bits, b_bits)

    def dispatch_pk_grouped(self, g, a_bits, b_bits):
        """Sharded pk-grouped dispatch; NOT_SHARDED when ineligible."""
        n = self.size
        if n < 2 or g.msg_x.shape[0] % n:
            return NOT_SHARDED
        v, chips = self._verifier("pk_grouped", g.msg_x.shape[:2])
        self._pre_dispatch("pk_grouped", chips)
        with trace.annotation(f"bls/mesh/pk_grouped[{len(chips)}]"), \
                device_ledger.ledger().dispatch("pk_grouped", chips):
            return v.submit(g, a_bits, b_bits)

    def dispatch_pk_grouped_raw(self, g, sig_raw, a_bits, b_bits):
        """Sharded pk-grouped RAW dispatch (wire-byte signatures,
        on-mesh decompression); NOT_SHARDED when ineligible."""
        n = self.size
        if n < 2 or g.msg_x.shape[0] % n:
            return NOT_SHARDED
        v, chips = self._verifier("pk_grouped_raw", g.msg_x.shape[:2])
        self._pre_dispatch("pk_grouped_raw", chips)
        with trace.annotation(f"bls/mesh/pk_grouped_raw[{len(chips)}]"), \
                device_ledger.ledger().dispatch("pk_grouped_raw", chips):
            return v.submit(g, sig_raw, a_bits, b_bits)

    def dispatch_bisect(self, arrs, r_bits):
        """Sharded bisection-tree dispatch; NOT_SHARDED when ineligible
        (the sharded kernel needs a power-of-two batch the host already
        padded — non-pow2 buckets stay on the single-device kernel)."""
        n = self.size
        lanes = arrs.pk_x.shape[0]
        if n < 2 or lanes % n or lanes & (lanes - 1):
            return NOT_SHARDED
        v, chips = self._verifier("bisect", (lanes,))
        self._pre_dispatch("bisect", chips)
        with trace.annotation(f"bls/mesh/bisect[{len(chips)}]"), \
                device_ledger.ledger().dispatch("bisect", chips):
            return v.submit(arrs, r_bits)

    # -- failure policy -----------------------------------------------------

    def evict(self, chip: int | None = None, reason: str = "failure"):
        """Remove a sick chip from the census and shrink the serving mesh.
        Returns the NEW serving size, or None when nothing was evicted
        (no mesh / last healthy chip / unknown chip already out)."""
        with self._lock:
            if len(self._healthy) <= 1:
                return None
            if chip is None or chip not in self._healthy:
                # no attribution: drop the highest-index healthy chip (the
                # serving prefix keeps chip 0, the root-tail owner, stable)
                chip = self._healthy[-1]
            self._healthy.remove(chip)
            self._evicted.append({"chip": chip, "reason": reason})
            new_size = self.size
        self.observer.mesh_eviction(chip, reason)
        self._publish()
        logger.warning(
            "mesh: evicted chip %d (%s) — serving continues on %d chip(s)",
            chip, reason, max(new_size, 1),
        )
        return new_size

    def readmit(self) -> int:
        """Restore every evicted chip to the census (canary passed).
        Returns the number of chips re-admitted."""
        with self._lock:
            n = len(self._evicted)
            if not n:
                return 0
            self._healthy = list(range(len(self._devices)))
            self._evicted = []
        self.observer.mesh_readmission(n)
        self._publish()
        logger.info(
            "mesh: re-admitted %d chip(s) — serving mesh back to %d",
            n, self.size,
        )
        return n

    def has_evicted(self) -> bool:
        return bool(self._evicted)

    # -- introspection ------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "devices_total": len(self._devices),
                "healthy": list(self._healthy),
                "serving": self._serving_chips(),
                "size": self.size,
                "evicted": [dict(e) for e in self._evicted],
                "dispatches": self._dispatches,
                "compiled": sorted(
                    f"{k[0]}:{'x'.join(str(d) for d in k[1])}@{len(k[2])}"
                    for k in self._verifiers
                ),
            }


def auto_mesh(observer: PipelineMetrics | None = None):
    """Mesh policy at verifier construction (LODESTAR_TPU_MESH):

      auto (default)  mesh when >1 ACCELERATOR device is visible — real
                      multi-chip hardware. Virtual CPU meshes are opt-in:
                      tier-1 tests and single-chip tools run with 8
                      virtual CPU devices, and silently routing them
                      through the sharded compiles would be a massive
                      cold-cache regression for zero parallelism (the
                      "devices" share host cores).
      force / 1 / on  mesh whenever >1 device of ANY platform is visible
                      (bench's CPU-mesh phase, multi-chip drills).
      off / 0 / false never mesh.

    Returns a BlsMeshDispatcher or None. Never raises: a verifier must
    construct even when jax device enumeration is broken (the supervisor
    owns that failure)."""
    from ..utils.env import env_str

    mode = (env_str("LODESTAR_TPU_MESH") or "auto").strip().lower()
    if mode in ("0", "off", "false", "none"):
        return None
    try:
        import jax

        devices = jax.devices()
        if len(devices) < 2:
            return None
        if mode not in ("1", "on", "force") and devices[0].platform == "cpu":
            return None
        dispatcher = BlsMeshDispatcher(devices, observer=observer)
        if not dispatcher.enabled:
            return None
        logger.info(
            "mesh serving enabled: %d %s device(s), serving size %d",
            len(devices), devices[0].platform, dispatcher.size,
        )
        return dispatcher
    except Exception as e:  # pragma: no cover - env-dependent
        logger.warning("mesh auto-detect failed (%s); serving unsharded", e)
        return None
