"""Production mesh dispatch: one logical BLS verifier served by N chips
across M hosts.

`parallel/sharded.py` holds the shard_map kernels; this module is the
HOST-SIDE policy that makes them the serving path (round-7 tentpole,
generalized over hosts in ISSUE 20):

- device census → serving mesh: the largest power-of-two prefix of the
  healthy chips that divides the 64 constant Miller lanes
  (`sharded.mesh_divisor`); 1 healthy chip means "no mesh" and the
  caller's single-device kernels keep serving. With a multi-host census
  (`hosts=` rows from `fleet.FleetTopology.group_devices`) the serving
  shape becomes a TWO-LEVEL layout — a power-of-two host count × a
  uniform power-of-two chips-per-host width whose product divides 64 —
  and verifiers compile over a 2-D Mesh with a DCN axis (outer, across
  hosts) and an ICI axis (inner, within a host),
- lazy per-(kind, shape, layout) compile cache of sharded verifiers —
  an eviction changes the layout, so survivors recompile (served from
  the persistent XLA/AOT cache when warm) while the old executables stay
  keyed under the old layout for re-admission,
- the failure policy's mesh half: `evict()` removes a sick chip and
  shrinks the serving mesh (a 4-chip node keeps serving as a 3-healthy/
  2-serving mesh), `evict_host()` is the same FSM one level up — a sick
  HOST leaves the census, the fleet keeps serving on the survivors and
  the attached `FleetRouter` rebalances its gossip subnets onto them —
  and `readmit()` restores the full census (chips AND hosts) when the
  supervisor's canary passes, mirroring the reference's worker-pool
  model where a crashed worker is dropped and respawned
  (`chain/bls/multithread/index.ts`) rather than taking the node down,
- every transition and dispatch is recorded in the `lodestar_bls_mesh_*`
  and `lodestar_bls_fleet_*` families (observability/stages.py) so
  dashboards can tell a full fleet from a degraded one, and
  `testing/faults.on_mesh_dispatch`/`on_fleet_dispatch` give the chaos
  drill seams to make a chip or a whole host sick on demand.

The dispatcher itself never imports jax at module scope: unit tests
drive the eviction state machines with a stub `verifier_factory` and
fake device lists, no kernel compiles involved.
"""

from __future__ import annotations

import threading
import time as _time

from ..observability import device_ledger, trace
from ..observability.stages import PipelineMetrics, default_pipeline
from ..testing import faults as _faults
from ..utils.logger import get_logger

logger = get_logger("parallel.mesh")

__all__ = ["NOT_SHARDED", "BlsMeshDispatcher", "auto_mesh", "mesh_divisor"]

# the grouped kernels split the constant −[2^b]g1 Miller lanes across
# chips: 2·HALF_BITS of them (parallel/verifier) — the serving mesh must
# divide this count evenly
CONSTANT_LANES = 64


def mesh_divisor(n_devices: int) -> int:
    """Largest usable mesh size ≤ `n_devices`: the grouped kernels split
    the 64 constant Miller lanes across chips, so the serving mesh must
    divide 64. 64 is a power of two, so this walks powers of two — 5
    healthy chips serve as a 4-chip mesh, 3 as 2, 1 as none."""
    d = 1
    while d * 2 <= min(n_devices, CONSTANT_LANES) and CONSTANT_LANES % (d * 2) == 0:
        d *= 2
    return d

# returned by dispatch_* when this batch cannot shard (mesh too small,
# rows not divisible) — the caller falls through to its single-device
# kernel; distinct from None so a sharded `False` verdict can't be
# confused with "not handled"
NOT_SHARDED = object()


def _default_factory(kind: str, devices, axis):
    """Build the real shard_map verifier for `kind` over `devices`.

    `devices` is a flat list for a single-level mesh, or a list of
    per-host rows for a two-level fleet mesh — `np.array` then yields a
    (hosts, chips) grid and `axis` is the ``(dcn, ici)`` name pair."""
    import numpy as np
    from jax.sharding import Mesh

    from . import sharded  # deferred: keeps this module jax-free at import

    cls = {
        "grouped": sharded.ShardedGroupedVerifier,
        "grouped_raw": sharded.ShardedGroupedRawVerifier,
        "pk_grouped": sharded.ShardedPkGroupedVerifier,
        "pk_grouped_raw": sharded.ShardedPkGroupedRawVerifier,
        "bisect": sharded.ShardedBisectVerifier,
    }[kind]
    axis_names = (axis,) if isinstance(axis, str) else tuple(axis)
    return cls(Mesh(np.array(devices), axis_names=axis_names), axis)


def _ledger_wrap_submit(v, kind: str, shape, chips, hosts: int = 1) -> None:
    """Route a freshly built sharded verifier through the compile ledger:
    each (kind, shape, chip-set) verifier is exactly one shard_map
    compile, so the static key encodes shape+chips — a post-eviction mesh
    shrink recompiling on the serving path records a NEW event (the
    ROADMAP item-5 restart-story cost, now measured).

    The seam prefers the verifier's jitted `_run` over the `submit`
    facade: `_run` is the actual jit entry (it has `.lower`), which is
    what the ledger's AOT store needs to export a serialized executable —
    and what lets an evicted-mesh re-dispatch for an already-exported
    shrunk chip set load machine code from disk instead of entering XLA
    (ISSUE 19). Factory products without a rebindable `_run`/`submit`
    (test stubs with __slots__/properties) fall back or are left
    untouched.

    Two-level fleet twins record under their own kernel name
    (``fleet_<kind>``) with the host count in the static key: the same
    (kind, shape, chip-set) over 1 host vs 2 hosts is a DIFFERENT
    executable, and the AOT store must not conflate them."""
    from ..observability.compile_ledger import ledger

    kernel = f"sharded_{kind}" if hosts <= 1 else f"fleet_{kind}"
    static_key = f"{tuple(shape)}@chips{','.join(str(c) for c in chips)}"
    if hosts > 1:
        static_key += f"@hosts{hosts}"
    if getattr(v, "_run", None) is not None:
        try:
            v._run = ledger().wrap(v._run, kernel, static_key=static_key)
            return
        except AttributeError:
            logger.debug("mesh: %s verifier _run not rebindable; trying "
                         "submit", kind)
    try:
        v.submit = ledger().wrap(v.submit, kernel, static_key=static_key)
    except AttributeError:
        logger.debug("mesh: %s verifier submit not rebindable; compile "
                     "ledger seam skipped", kind)


class BlsMeshDispatcher:
    """Routes grouped/pk-grouped/bisect batches onto the serving mesh and
    owns the evict/re-admit state machine. Thread-safe: the supervisor's
    failure path and the flush thread may race."""

    def __init__(self, devices, axis: str = "dp",
                 observer: PipelineMetrics | None = None,
                 verifier_factory=None, hosts=None,
                 dcn_axis: str = "dcn", ici_axis: str = "ici",
                 router=None):
        self.axis = axis
        self.dcn_axis = dcn_axis
        self.ici_axis = ici_axis
        self.observer = observer if observer is not None else default_pipeline()
        self._factory = verifier_factory or _default_factory
        self._devices = list(devices)
        self._lock = threading.Lock()
        # chip ids are indices into the census; eviction order is recorded
        # for /debug/mesh and for "evict the most recent suspect" defaults
        self._healthy: list[int] = list(range(len(self._devices)))
        self._evicted: list[dict] = []
        # host census: rows of chip indices (fleet.FleetTopology grouping);
        # the default single row is the pre-fleet behavior bit-for-bit
        if hosts:
            claimed = [c for row in hosts for c in row]
            if sorted(claimed) != sorted(set(claimed)) or any(
                c not in self._healthy for c in claimed
            ):
                raise ValueError("hosts rows must partition distinct chips")
            self._host_map: list[list[int]] = [list(row) for row in hosts]
        else:
            self._host_map = [list(self._healthy)]
        self._evicted_hosts: list[dict] = []
        self._router = router
        self._verifiers: dict = {}
        self._dispatches = 0
        self._host_dispatches: dict[int, int] = {}
        self._publish()

    # -- census -------------------------------------------------------------

    def _serving_layout(self) -> list[list[int]]:
        """Per-host rows of the chips actually dispatched to. One row =
        single-level mesh (pre-fleet behavior). Multiple rows = a
        two-level (hosts × chips-per-host) layout: a power-of-two host
        count, a UNIFORM power-of-two per-host width (the minimum across
        surviving hosts — shard_map needs a rectangular grid), product
        capped so it divides the 64 constant lanes. Host 0 keeps the
        first row — its chip 0 owns the root tail."""
        return [row for _, row in self._serving_rows()]

    def _serving_rows(self) -> list[tuple[int, list[int]]]:
        """(host rank, serving chips) pairs — see `_serving_layout`."""
        gone = {e["host"] for e in self._evicted_hosts}
        healthy = set(self._healthy)
        rows = []
        for h, row in enumerate(self._host_map):
            if h in gone:
                continue
            hc = [c for c in row if c in healthy]
            if hc:
                rows.append((h, hc))
        if not rows:
            return []
        if len(rows) == 1:
            h, hc = rows[0]
            return [(h, hc[: mesh_divisor(len(hc))])]
        per = mesh_divisor(min(len(hc) for _, hc in rows))
        nh = 1
        while nh * 2 <= len(rows) and nh * 2 * per <= CONSTANT_LANES:
            nh *= 2
        return [(h, hc[:per]) for h, hc in rows[:nh]]

    @property
    def size(self) -> int:
        """Current serving-mesh size (total chips actually dispatched to,
        across every serving host)."""
        return sum(len(r) for r in self._serving_layout())

    @property
    def hosts_serving(self) -> int:
        return len(self._serving_layout())

    @property
    def hosts_total(self) -> int:
        return len(self._host_map)

    @property
    def enabled(self) -> bool:
        return self.size >= 2

    def _serving_chips(self) -> list[int]:
        return [c for row in self._serving_layout() for c in row]

    def _publish(self) -> None:
        self.observer.mesh_state(self.size, len(self._evicted))
        if len(self._host_map) > 1:
            self.observer.fleet_state(
                self.hosts_serving, len(self._evicted_hosts)
            )

    def attach_router(self, router) -> None:
        """Bind the FleetRouter whose subnet slices must follow host
        evictions (node wiring; tests pass router= directly)."""
        self._router = router

    # -- verifier cache -----------------------------------------------------

    def _verifier(self, kind: str, shape):
        with self._lock:
            rows = self._serving_rows()
            chips = tuple(c for _, row in rows for c in row)
            # keyed by the full (host rank, chip set) layout: the same
            # chip set regrouped under different hosts is a different
            # device assignment, hence a different executable
            key = (kind, shape, tuple((h, tuple(r)) for h, r in rows))
            v = self._verifiers.get(key)
            if v is None:
                if len(rows) > 1:
                    devs = [
                        [self._devices[c] for c in row] for _, row in rows
                    ]
                    ax = (self.dcn_axis, self.ici_axis)
                else:
                    devs = [self._devices[c] for c in chips]
                    ax = self.axis
                v = self._factory(kind, devs, ax)
                _ledger_wrap_submit(v, kind, shape, chips, hosts=len(rows))
                self._verifiers[key] = v
            return v, chips, rows

    # -- dispatch -----------------------------------------------------------

    def _pre_dispatch(self, kind: str, chips, rows) -> None:
        _faults.on_mesh_dispatch(len(chips))
        if len(rows) > 1:
            _faults.on_fleet_dispatch([h for h, _ in rows])
        with self._lock:
            self._dispatches += 1
            if len(rows) > 1:
                for h, _ in rows:
                    self._host_dispatches[h] = (
                        self._host_dispatches.get(h, 0) + 1
                    )
        self.observer.mesh_dispatch(chips)
        if len(rows) > 1:
            self.observer.fleet_dispatch([h for h, _ in rows])

    def _submit_timed(self, rows, fn):
        """Run one verifier submit; DCN-spanning dispatches (>1 host) are
        wall-timed into the fleet DCN-seconds counter — an upper bound on
        the cross-host collective cost (XLA doesn't expose the collective
        alone at this seam)."""
        if len(rows) <= 1:
            return fn()
        t0 = _time.monotonic()
        try:
            return fn()
        finally:
            self.observer.fleet_dcn_seconds(_time.monotonic() - t0)

    def dispatch_grouped(self, g, a_bits, b_bits):
        """Sharded root-grouped dispatch; NOT_SHARDED when ineligible."""
        n = self.size
        if n < 2 or g.pk_x.shape[0] % n:
            return NOT_SHARDED
        v, chips, rows = self._verifier("grouped", g.pk_x.shape[:2])
        self._pre_dispatch("grouped", chips, rows)
        with trace.annotation(f"bls/mesh/grouped[{len(chips)}]"), \
                device_ledger.ledger().dispatch("grouped", chips):
            return self._submit_timed(
                rows, lambda: v.submit(g, a_bits, b_bits)
            )

    def dispatch_grouped_raw(self, g, sig_raw, a_bits, b_bits):
        """Sharded root-grouped RAW dispatch (wire-byte signatures,
        on-mesh decompression); NOT_SHARDED when ineligible."""
        n = self.size
        if n < 2 or g.pk_x.shape[0] % n:
            return NOT_SHARDED
        v, chips, rows = self._verifier("grouped_raw", g.pk_x.shape[:2])
        self._pre_dispatch("grouped_raw", chips, rows)
        with trace.annotation(f"bls/mesh/grouped_raw[{len(chips)}]"), \
                device_ledger.ledger().dispatch("grouped_raw", chips):
            return self._submit_timed(
                rows, lambda: v.submit(g, sig_raw, a_bits, b_bits)
            )

    def dispatch_pk_grouped(self, g, a_bits, b_bits):
        """Sharded pk-grouped dispatch; NOT_SHARDED when ineligible."""
        n = self.size
        if n < 2 or g.msg_x.shape[0] % n:
            return NOT_SHARDED
        v, chips, rows = self._verifier("pk_grouped", g.msg_x.shape[:2])
        self._pre_dispatch("pk_grouped", chips, rows)
        with trace.annotation(f"bls/mesh/pk_grouped[{len(chips)}]"), \
                device_ledger.ledger().dispatch("pk_grouped", chips):
            return self._submit_timed(
                rows, lambda: v.submit(g, a_bits, b_bits)
            )

    def dispatch_pk_grouped_raw(self, g, sig_raw, a_bits, b_bits):
        """Sharded pk-grouped RAW dispatch (wire-byte signatures,
        on-mesh decompression); NOT_SHARDED when ineligible."""
        n = self.size
        if n < 2 or g.msg_x.shape[0] % n:
            return NOT_SHARDED
        v, chips, rows = self._verifier("pk_grouped_raw", g.msg_x.shape[:2])
        self._pre_dispatch("pk_grouped_raw", chips, rows)
        with trace.annotation(f"bls/mesh/pk_grouped_raw[{len(chips)}]"), \
                device_ledger.ledger().dispatch("pk_grouped_raw", chips):
            return self._submit_timed(
                rows, lambda: v.submit(g, sig_raw, a_bits, b_bits)
            )

    def dispatch_bisect(self, arrs, r_bits):
        """Sharded bisection-tree dispatch; NOT_SHARDED when ineligible
        (the sharded kernel needs a power-of-two batch the host already
        padded — non-pow2 buckets stay on the single-device kernel)."""
        n = self.size
        lanes = arrs.pk_x.shape[0]
        if n < 2 or lanes % n or lanes & (lanes - 1):
            return NOT_SHARDED
        v, chips, rows = self._verifier("bisect", (lanes,))
        self._pre_dispatch("bisect", chips, rows)
        with trace.annotation(f"bls/mesh/bisect[{len(chips)}]"), \
                device_ledger.ledger().dispatch("bisect", chips):
            return self._submit_timed(rows, lambda: v.submit(arrs, r_bits))

    # -- failure policy -----------------------------------------------------

    def evict(self, chip: int | None = None, reason: str = "failure"):
        """Remove a sick chip from the census and shrink the serving mesh.
        Returns the NEW serving size, or None when nothing was evicted
        (no mesh / last healthy chip / unknown chip already out)."""
        with self._lock:
            if len(self._healthy) <= 1:
                return None
            if chip is None or chip not in self._healthy:
                # no attribution: drop the highest-index healthy chip (the
                # serving prefix keeps chip 0, the root-tail owner, stable)
                chip = self._healthy[-1]
            self._healthy.remove(chip)
            self._evicted.append({"chip": chip, "reason": reason})
            new_size = self.size
        self.observer.mesh_eviction(chip, reason)
        self._publish()
        logger.warning(
            "mesh: evicted chip %d (%s) — serving continues on %d chip(s)",
            chip, reason, max(new_size, 1),
        )
        return new_size

    def evict_host(self, host: int | None = None, reason: str = "failure"):
        """The chip-eviction FSM one level up: remove a whole HOST from
        the serving census, rebalance its gossip subnets onto the
        survivors (via the attached FleetRouter) and keep serving on a
        smaller two-level mesh. Returns the NEW total serving size, or
        None when nothing was evicted (single-host census / last serving
        host / unknown host already out)."""
        with self._lock:
            gone = {e["host"] for e in self._evicted_hosts}
            active = [
                h for h in range(len(self._host_map)) if h not in gone
            ]
            if len(self._host_map) < 2 or len(active) <= 1:
                return None
            if host is None or host not in active:
                # no attribution: drop the highest-rank active host (host
                # 0, the root-tail owner of the two-level mesh, stays)
                host = active[-1]
            self._evicted_hosts.append({"host": host, "reason": reason})
            new_size = self.size
            new_hosts = self.hosts_serving
        self.observer.fleet_host_eviction(host, reason)
        moved = None
        if self._router is not None:
            try:
                moved = self._router.evict_host(host)
            except Exception:  # pragma: no cover — routing must not mask
                logger.exception("fleet: router rebalance failed")
        self._publish()
        logger.warning(
            "fleet: evicted host %d (%s) — serving continues on %d "
            "host(s) / %d chip(s)%s",
            host, reason, max(new_hosts, 1), max(new_size, 1),
            f", {moved} subnet(s) rebalanced" if moved is not None else "",
        )
        return new_size

    def readmit(self) -> int:
        """Restore every evicted chip AND host to the census (canary
        passed). Returns the number of census entries re-admitted."""
        with self._lock:
            n = len(self._evicted)
            nh = len(self._evicted_hosts)
            if not n and not nh:
                return 0
            self._healthy = list(range(len(self._devices)))
            self._evicted = []
            self._evicted_hosts = []
        if n:
            self.observer.mesh_readmission(n)
        if self._router is not None and nh:
            try:
                self._router.readmit_hosts()
            except Exception:  # pragma: no cover
                logger.exception("fleet: router readmit failed")
        self._publish()
        logger.info(
            "mesh: re-admitted %d chip(s) + %d host(s) — serving mesh "
            "back to %d", n, nh, self.size,
        )
        return n + nh

    def has_evicted(self) -> bool:
        return bool(self._evicted or self._evicted_hosts)

    # -- introspection ------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            snap = {
                "devices_total": len(self._devices),
                "healthy": list(self._healthy),
                "serving": self._serving_chips(),
                "size": self.size,
                "evicted": [dict(e) for e in self._evicted],
                "dispatches": self._dispatches,
                "compiled": sorted(
                    f"{k[0]}:{'x'.join(str(d) for d in k[1])}"
                    f"@{sum(len(r) for _, r in k[2])}"
                    + (f"/{len(k[2])}hosts" if len(k[2]) > 1 else "")
                    for k in self._verifiers
                ),
            }
            if len(self._host_map) > 1:
                snap["fleet"] = self._fleet_fields_locked()
            return snap

    def _fleet_fields_locked(self) -> dict:
        rows = self._serving_rows()
        return {
            "hosts_total": len(self._host_map),
            "hosts_serving": len(rows),
            "layout": {str(h): list(r) for h, r in rows},
            "evicted_hosts": [dict(e) for e in self._evicted_hosts],
            "host_dispatches": {
                str(h): n for h, n in sorted(self._host_dispatches.items())
            },
        }

    def fleet_snapshot(self) -> dict | None:
        """Host-level census for `/debug/fleet` and the bench document;
        None on a single-host census (endpoint reports wired: false)."""
        with self._lock:
            if len(self._host_map) <= 1:
                return None
            doc = self._fleet_fields_locked()
        if self._router is not None:
            try:
                doc["router"] = self._router.snapshot()
            except Exception as e:  # pragma: no cover — census must not fail
                logger.debug(f"fleet router snapshot failed: {e}")
        return doc


def auto_mesh(observer: PipelineMetrics | None = None):
    """Mesh policy at verifier construction (LODESTAR_TPU_MESH):

      auto (default)  mesh when >1 ACCELERATOR device is visible — real
                      multi-chip hardware. Virtual CPU meshes are opt-in:
                      tier-1 tests and single-chip tools run with 8
                      virtual CPU devices, and silently routing them
                      through the sharded compiles would be a massive
                      cold-cache regression for zero parallelism (the
                      "devices" share host cores).
      force / 1 / on  mesh whenever >1 device of ANY platform is visible
                      (bench's CPU-mesh phase, multi-chip drills).
      off / 0 / false never mesh.

    A fleet census rides the same policy: when ``LODESTAR_TPU_FLEET``
    is active (parallel/fleet.FleetTopology) the visible devices group
    into per-host rows — by `process_index` for a real jax.distributed
    fleet (initialized here, before device enumeration), or split into
    virtual hosts in emulation — and the dispatcher serves a two-level
    (DCN × ICI) mesh. Mesh policy gates first: a CPU fleet emulation
    still needs LODESTAR_TPU_MESH=force.

    Returns a BlsMeshDispatcher or None. Never raises: a verifier must
    construct even when jax device enumeration is broken (the supervisor
    owns that failure)."""
    from ..utils.env import env_str

    mode = (env_str("LODESTAR_TPU_MESH") or "auto").strip().lower()
    if mode in ("0", "off", "false", "none"):
        return None
    try:
        from .fleet import FleetTopology

        topo = FleetTopology.from_env()
        if topo.active:
            # must precede jax.devices(): the distributed runtime is what
            # makes remote hosts' devices visible in the global census
            topo.ensure_initialized()
        import jax

        devices = jax.devices()
        if len(devices) < 2:
            return None
        if mode not in ("1", "on", "force") and devices[0].platform == "cpu":
            return None
        hosts = topo.group_devices(devices) if topo.active else None
        dispatcher = BlsMeshDispatcher(devices, observer=observer,
                                       hosts=hosts)
        if not dispatcher.enabled:
            return None
        logger.info(
            "mesh serving enabled: %d %s device(s), serving size %d "
            "across %d host(s)",
            len(devices), devices[0].platform, dispatcher.size,
            dispatcher.hosts_serving,
        )
        return dispatcher
    except Exception as e:  # pragma: no cover - env-dependent
        logger.warning("mesh auto-detect failed (%s); serving unsharded", e)
        return None
