"""Fleet topology + subnet routing: the host-level (DCN) policy tier.

`parallel/mesh.py` abstracts the chips of ONE host; this module is the
layer above it — the policy that lets a serving fleet of hosts act as
one logical verifier (ROADMAP item 5, the 200k sets/s aggregate
target):

- `FleetTopology` reads the ``LODESTAR_TPU_FLEET*`` knobs and answers
  "how many hosts, which rank am I, and how do the visible jax devices
  group into hosts". Two modes: a real multi-process fleet (the knob
  names a `jax.distributed` coordinator, devices group by
  `process_index`) and single-process emulation (local devices split
  into N virtual hosts — the CPU-dryrun/parity mode, exactly how the
  virtual-chip mesh already stands in for real ICI).
- `FleetRouter` owns the subnet → host-rank assignment for attestation
  gossip: rendezvous (highest-random-weight) hashing over the active
  host set, so each host's `BlsLaneDispatcher` lanes only ever see its
  slice of the `ATTESTATION_SUBNET_COUNT` subnets. HRW is what makes
  host eviction cheap: when the supervisor evicts a whole host, ONLY
  the evicted host's subnets move (each re-hashes to its next-best
  survivor) — the other hosts' slices are untouched, mirroring how
  chip eviction keeps the serving prefix stable.

Both classes are jax-free and import-light on purpose: unit tests drive
eviction/rebalance/coverage with plain integers, and the mesh module
keeps its "no jax at import" contract when it imports this one.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass

from ..params.constants import ATTESTATION_SUBNET_COUNT
from ..utils.logger import get_logger

logger = get_logger("parallel.fleet")

__all__ = ["FleetTopology", "FleetRouter"]

_distributed_initialized = False


@dataclass(frozen=True)
class FleetTopology:
    """Resolved ``LODESTAR_TPU_FLEET*`` configuration.

    mode:        "off" | "emulate" | "distributed"
    coordinator: "host:port" of the jax.distributed coordinator
                 (distributed mode only)
    hosts:       fleet host count (process count / virtual-host count)
    rank:        this process's host rank in [0, hosts)
    """

    mode: str = "off"
    coordinator: str | None = None
    hosts: int = 1
    rank: int = 0

    @property
    def active(self) -> bool:
        return self.mode != "off" and self.hosts > 1

    @classmethod
    def from_env(cls) -> "FleetTopology":
        """Parse the fleet knobs. ``LODESTAR_TPU_FLEET`` selects the
        mode: unset/empty/off = no fleet; a value containing ``:`` names
        the jax.distributed coordinator (real multi-process fleet);
        anything else (``emulate``, ``1``, ``on``…) requests
        single-process emulation over the local devices. Never raises —
        a malformed knob degrades to "off" (the verifier must construct
        regardless)."""
        from ..utils.env import env_int, env_str

        spec = (env_str("LODESTAR_TPU_FLEET") or "").strip()
        if not spec or spec.lower() in ("0", "off", "false", "none"):
            return cls()
        hosts = max(int(env_int("LODESTAR_TPU_FLEET_HOSTS") or 2), 1)
        rank = int(env_int("LODESTAR_TPU_FLEET_RANK") or 0)
        if not 0 <= rank < hosts:
            logger.warning(
                "fleet: rank %d outside [0, %d); fleet disabled", rank, hosts
            )
            return cls()
        if ":" in spec:
            return cls(
                mode="distributed", coordinator=spec, hosts=hosts, rank=rank
            )
        return cls(mode="emulate", coordinator=None, hosts=hosts, rank=rank)

    def ensure_initialized(self) -> bool:
        """Bring up `jax.distributed` for a real multi-process fleet
        (idempotent; emulation needs no runtime). Returns True when the
        distributed runtime is (already) up, False on failure — callers
        degrade to single-host serving rather than raising."""
        global _distributed_initialized
        if self.mode != "distributed":
            return True
        if _distributed_initialized:
            return True
        try:
            import jax

            jax.distributed.initialize(
                coordinator_address=self.coordinator,
                num_processes=self.hosts,
                process_id=self.rank,
            )
            _distributed_initialized = True
            logger.info(
                "fleet: jax.distributed up (coordinator %s, rank %d/%d)",
                self.coordinator, self.rank, self.hosts,
            )
            return True
        except Exception as e:  # pragma: no cover - env-dependent
            logger.warning(
                "fleet: jax.distributed.initialize failed (%s); serving "
                "single-host", e,
            )
            return False

    def group_devices(self, devices) -> list[list[int]] | None:
        """Group the visible device list into per-host rows of device
        INDICES (the mesh dispatcher's census format). Distributed mode
        groups by `process_index`; emulation splits the local devices
        into `hosts` equal contiguous rows. Returns None when no usable
        multi-host grouping exists (callers serve single-level)."""
        if not self.active:
            return None
        if self.mode == "distributed":
            by_proc: dict[int, list[int]] = {}
            for i, d in enumerate(devices):
                by_proc.setdefault(int(getattr(d, "process_index", 0)), []).append(i)
            rows = [by_proc[p] for p in sorted(by_proc)]
        else:
            per = len(devices) // self.hosts
            if per < 1:
                return None
            rows = [
                list(range(h * per, (h + 1) * per)) for h in range(self.hosts)
            ]
        return rows if len(rows) > 1 else None


class FleetRouter:
    """Subnet → host-rank assignment via rendezvous (HRW) hashing.

    Every host computes the same deterministic owner for every subnet
    (sha256 of ``subnet:host``, highest weight wins over the ACTIVE host
    set), so the fleet needs no coordination traffic to agree on the
    partition: slices are disjoint and cover all subnets by
    construction. Thread-safe — the supervisor's eviction path and the
    gossip validator threads race on the active set."""

    def __init__(self, hosts: int, rank: int = 0,
                 subnet_count: int = ATTESTATION_SUBNET_COUNT,
                 observer=None):
        if hosts < 1:
            raise ValueError(f"fleet needs >= 1 host, got {hosts}")
        if not 0 <= rank < hosts:
            raise ValueError(f"rank {rank} outside [0, {hosts})")
        self.hosts = hosts
        self.rank = rank
        self.subnet_count = subnet_count
        self.observer = observer
        self._lock = threading.Lock()
        self._evicted: list[int] = []
        self._rebalances = 0
        self._subnets_moved = 0
        self._foreign_dropped = 0

    # -- assignment ---------------------------------------------------------

    @staticmethod
    def _weight(subnet: int, host: int) -> int:
        digest = hashlib.sha256(
            b"lodestar-fleet-subnet:%d:host:%d" % (subnet, host)
        ).digest()
        return int.from_bytes(digest[:8], "big")

    def active_hosts(self) -> list[int]:
        with self._lock:
            return [h for h in range(self.hosts) if h not in self._evicted]

    def owner(self, subnet: int) -> int:
        """The host rank that owns `subnet` under the current active set."""
        active = self.active_hosts()
        if not active:
            raise RuntimeError("fleet router has no active hosts")
        return max(active, key=lambda h: self._weight(subnet, h))

    def owns(self, subnet: int) -> bool:
        return self.owner(subnet) == self.rank

    def slice_for(self, rank: int | None = None) -> tuple[int, ...]:
        """Every subnet owned by `rank` (default: this host)."""
        r = self.rank if rank is None else rank
        return tuple(
            s for s in range(self.subnet_count) if self.owner(s) == r
        )

    # -- host eviction / rebalance ------------------------------------------

    def evict_host(self, rank: int) -> int | None:
        """Drop a host from the active set and rebalance its subnets
        onto the survivors (HRW: only the evicted host's subnets move).
        Returns the number of subnets that moved, or None when the
        eviction is a no-op (unknown/already-evicted rank, last host)."""
        with self._lock:
            active = [h for h in range(self.hosts) if h not in self._evicted]
            if rank not in active or len(active) <= 1:
                return None
            before = {
                s: max(active, key=lambda h: self._weight(s, h))
                for s in range(self.subnet_count)
            }
            self._evicted.append(rank)
            survivors = [h for h in active if h != rank]
            moved = sum(
                1
                for s in range(self.subnet_count)
                if before[s] != max(
                    survivors, key=lambda h: self._weight(s, h)
                )
            )
            self._rebalances += 1
            self._subnets_moved += moved
        if self.observer is not None:
            self.observer.fleet_rebalance(moved)
        logger.warning(
            "fleet: host %d evicted from subnet routing — %d subnet(s) "
            "rebalanced onto %d surviving host(s)",
            rank, moved, len(survivors),
        )
        return moved

    def readmit_hosts(self) -> int:
        """Restore every evicted host to the routing table (canary
        passed). Returns the number of hosts re-admitted."""
        with self._lock:
            n = len(self._evicted)
            if not n:
                return 0
            self._evicted = []
            self._rebalances += 1
        if self.observer is not None:
            self.observer.fleet_rebalance(0)
        logger.info("fleet: %d host(s) re-admitted to subnet routing", n)
        return n

    def record_foreign(self, subnet: int) -> None:
        """Count an attestation seen for a subnet this host does NOT own
        (gossip overlap — dropped before validation/BLS)."""
        with self._lock:
            self._foreign_dropped += 1

    # -- introspection ------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            evicted = list(self._evicted)
            rebalances = self._rebalances
            moved = self._subnets_moved
            foreign = self._foreign_dropped
        owned = self.slice_for()
        return {
            "hosts": self.hosts,
            "rank": self.rank,
            "active_hosts": self.active_hosts(),
            "evicted_hosts": evicted,
            "subnet_count": self.subnet_count,
            "owned_subnets": list(owned),
            "owned": len(owned),
            "rebalances": rebalances,
            "subnets_moved": moved,
            "foreign_dropped": foreign,
        }
