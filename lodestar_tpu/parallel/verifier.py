"""Batched BLS signature-set verification on device.

The device analog of blst's `verifyMultipleSignatures` as consumed by the
reference's `BlsMultiThreadWorkerPool` (`chain/bls/multithread/index.ts:98`,
`maybeBatch.ts:16-27` per SURVEY.md §2.2): verify N signature sets with one
random-linear-combination pairing equation

    Π_i e(r_i·pk_i, H(m_i)) · e(−g1, Σ_i r_i·sig_i) == 1

where r_i are independent nonzero 64-bit scalars. Where the reference
chunks sets across worker threads, here the whole batch is ONE XLA
dispatch: scalar muls, N+1 Miller loops, a log-depth Fp12 product and a
single shared final exponentiation, all vmapped over the batch axis.

Design notes (TPU-first):
- Fixed batch buckets (powers of two) keep shapes static — one compile per
  bucket, reused forever. Padding lanes are masked to the Fp12 identity.
- r_i·pk_i stays projective out of the scalar-mul scan; the Miller loop
  accepts projective P by scaling lines with Zp ∈ Fp (annihilated by the
  final exponentiation) — no per-lane field inversion anywhere. The only
  inversion in the kernel is ONE Fp2 inv for the aggregated signature.
- The per-set retry path of the reference (`multithread/worker.ts:55-95`:
  batch fails → verify each set alone) is `verify_individual`: one batched
  dispatch computing every per-set verdict, not N round-trips.

Host-side preprocessing (deserialization, subgroup checks, hash-to-curve)
currently runs through the CPU oracle; moving it to C++/device SSWU is the
next tier.
"""

from __future__ import annotations

import secrets
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..bls import api as bls_api
from ..bls.hash_to_curve import hash_to_g2
from ..ops import fp, fp2, fp12
from ..ops.io_host import g1_affine_to_limbs, g2_affine_to_limbs
from ..ops.pairing import final_exponentiation, miller_loop, miller_loop_projective
from ..ops.points import G1_GEN_X, G1_GEN_Y, g1, g2

N_LIMBS = 32
R_BITS = 64  # random-coefficient width (matches blst's 64-bit rand scaling)

__all__ = ["BatchVerifier", "TpuBlsVerifier", "SetArrays"]


_fp12_product_tree = fp12.product_tree


def _g2_sum_tree(ps):
    """log2-depth complete-add reduction of G2 projective points over axis 0."""
    x, y, z = ps
    n = x.shape[0]
    while n > 1:
        half = n // 2
        a = (x[:half], y[:half], z[:half])
        b = (x[half : 2 * half], y[half : 2 * half], z[half : 2 * half])
        hx, hy, hz = g2.add(a, b)
        if n % 2 != 0:
            hx = jnp.concatenate([hx, x[2 * half :]], 0)
            hy = jnp.concatenate([hy, y[2 * half :]], 0)
            hz = jnp.concatenate([hz, z[2 * half :]], 0)
        x, y, z = hx, hy, hz
        n = x.shape[0]
    return x[0], y[0], z[0]


def batch_verify_kernel(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, r_bits, valid):
    """All-or-nothing batch verification; shapes (N, …) static.

    pk_*  (N, 32)     G1 affine Montgomery limbs (pre-aggregated pubkeys)
    msg_* (N, 2, 32)  G2 affine limbs of H(m_i)
    sig_* (N, 2, 32)  G2 affine limbs of signatures
    r_bits (N, 64)    random coefficients, MSB-first bits
    valid (N,) bool   padding mask — False lanes are ignored
    Returns scalar bool.
    """
    n = pk_x.shape[0]
    # r_i·pk_i (G1, projective out of the scan — no inversion). Bit
    # ladders, NOT the windowed variant: measured on v5e (tools/win_check)
    # the 2^4-window table selects cost more than the saved adds (307 vs
    # 262 ms at 512 lanes for G2) and XLA compile time grows ~30x.
    rpk = g1.scalar_mul_bits(r_bits, (pk_x, pk_y))
    # Σ r_i·sig_i (G2): per-lane scalar mul, mask padding to infinity, tree sum
    rsig = g2.scalar_mul_bits(r_bits, (sig_x, sig_y))
    rsig = g2.select(valid, rsig, g2.infinity((n,)))
    s = _g2_sum_tree(rsig)
    s_inf = g2.is_infinity(s)
    s_aff = g2.to_affine(s)  # the kernel's single inversion (garbage if s_inf)

    # Pair lanes: N (r_i·pk_i, H(m_i)) plus one (−g1, S)
    xs = jnp.concatenate([rpk[0], G1_GEN_X[None]], 0)
    ys = jnp.concatenate([rpk[1], fp.neg(G1_GEN_Y)[None]], 0)
    zs = jnp.concatenate([rpk[2], fp.one((1,))], 0)
    qx = jnp.concatenate([msg_x, s_aff[0][None]], 0)
    qy = jnp.concatenate([msg_y, s_aff[1][None]], 0)
    lane_ok = jnp.concatenate([valid, ~s_inf[None]], 0)

    fs = miller_loop_projective((xs, ys, zs), (qx, qy))
    fs = fp12.select(lane_ok, fs, fp12.one((n + 1,)))
    return fp12.is_one(final_exponentiation(_fp12_product_tree(fs)))


def individual_verify_kernel(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, valid):
    """Per-set verdicts in one dispatch: e(pk_i, H(m_i))·e(−g1, sig_i) == 1.

    The device replacement for the reference's retry-individually fallback
    (`multithread/worker.ts:55-95`) — instead of N sequential re-verifies,
    2N Miller loops and N final exponentiations run batched. Returns
    (N,) bool; padding lanes report False.
    """
    n = pk_x.shape[0]
    neg_gy = fp.neg(G1_GEN_Y)
    xs = jnp.concatenate([pk_x, jnp.broadcast_to(G1_GEN_X, (n, N_LIMBS))], 0)
    ys = jnp.concatenate([pk_y, jnp.broadcast_to(neg_gy, (n, N_LIMBS))], 0)
    qx = jnp.concatenate([msg_x, sig_x], 0)
    qy = jnp.concatenate([msg_y, sig_y], 0)
    fs = miller_loop((xs, ys), (qx, qy))
    prod = fp12.mul(fs[:n], fs[n:])
    return fp12.is_one(final_exponentiation(prod)) & valid


class SetArrays:
    """Host-marshalled signature sets, padded to a fixed lane count."""

    __slots__ = ("pk_x", "pk_y", "msg_x", "msg_y", "sig_x", "sig_y", "valid", "n")

    def __init__(self, lanes: int):
        self.pk_x = np.zeros((lanes, N_LIMBS), np.int32)
        self.pk_y = np.zeros((lanes, N_LIMBS), np.int32)
        self.msg_x = np.zeros((lanes, 2, N_LIMBS), np.int32)
        self.msg_y = np.zeros((lanes, 2, N_LIMBS), np.int32)
        self.sig_x = np.zeros((lanes, 2, N_LIMBS), np.int32)
        self.sig_y = np.zeros((lanes, 2, N_LIMBS), np.int32)
        self.valid = np.zeros((lanes,), bool)
        self.n = 0


def _rand_bits(lanes: int, rng) -> np.ndarray:
    """(lanes, 64) nonzero random scalar bits, MSB first."""
    out = np.zeros((lanes, R_BITS), np.int32)
    for i in range(lanes):
        r = 0
        while r == 0:
            r = rng() & ((1 << R_BITS) - 1)
        out[i] = [(r >> (R_BITS - 1 - j)) & 1 for j in range(R_BITS)]
    return out


class BatchVerifier:
    """Shape-bucketed jitted kernels. One compile per bucket size, cached."""

    def __init__(self, buckets: tuple[int, ...] = (4, 16, 64, 128)):
        self.buckets = tuple(sorted(buckets))
        self._batch = jax.jit(batch_verify_kernel)
        self._individual = jax.jit(individual_verify_kernel)

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def verify_batch(self, arrs: SetArrays, r_bits: np.ndarray):
        return self._batch(
            arrs.pk_x, arrs.pk_y, arrs.msg_x, arrs.msg_y,
            arrs.sig_x, arrs.sig_y, r_bits, arrs.valid,
        )

    def verify_individual(self, arrs: SetArrays):
        return self._individual(
            arrs.pk_x, arrs.pk_y, arrs.msg_x, arrs.msg_y,
            arrs.sig_x, arrs.sig_y, arrs.valid,
        )


class TpuBlsVerifier:
    """`IBlsVerifier`-shaped host API over the device kernels
    (reference: `chain/bls/interface.ts:20-46`).

    verify_signature_sets(sets) — all-or-nothing batch verdict.
    verify_signature_sets_individual(sets) — per-set verdicts (retry path).

    Semantics match the reference/eth2: infinity pubkeys or signatures,
    malformed encodings, or failed subgroup checks → False (without
    raising), exactly like `maybeBatch.ts` catching blst errors.
    """

    def __init__(self, buckets: tuple[int, ...] = (4, 16, 64, 128), rng=None):
        self.kernels = BatchVerifier(buckets)
        self._rng = rng if rng is not None else (lambda: secrets.randbits(R_BITS))
        # hash-to-curve cache keyed by signing root: committee gossip
        # shares roots (every member of a committee signs the same data),
        # so H(m) recomputation dominates marshalling without this.
        # Insertion-ordered dict as LRU-ish FIFO, bounded; the lock covers
        # the get/evict/insert sequence — gossip threads and the block
        # import pool hit one shared verifier concurrently.
        import threading

        self._h2c_cache: dict[bytes, tuple] = {}
        self._h2c_cache_max = 8192
        self._h2c_lock = threading.Lock()

    # -- host marshalling ---------------------------------------------------

    def _marshal(self, sets) -> SetArrays | None:
        """Build padded device arrays; None if any set is invalid up front.

        Fast path: the native C tier (`native/src/bls12.c`) decompresses,
        subgroup-checks and hash-to-curves the whole batch in one call —
        the reference keeps exactly this preprocessing in blst C
        (multithread/worker.ts:33-55). Falls back to the big-int oracle
        when the extension is unavailable.
        """
        if not sets:
            return None
        lanes = self.kernels.bucket_for(len(sets))
        if len(sets) > lanes:
            return None  # caller must chunk (service layer's job)
        from .. import native as _native

        if _native.HAVE_NATIVE_BLS and all(
            len(s.message) == 32 and len(s.signature) == 96 for s in sets
        ):
            # the C tier assumes fixed 32B signing roots (every consensus
            # message is one); odd-length messages take the oracle path below
            try:
                pk_b = b"".join(s.pubkey.to_bytes() for s in sets)
            except (bls_api.BlsError, ValueError):
                return None
            msg_b = b"".join(s.message for s in sets)
            sig_b = b"".join(s.signature for s in sets)
            # decompress/check WITHOUT hashing; hash each UNIQUE root once
            # (cache hit = free — the dominant real-gossip case)
            pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, ok = _native.bls_marshal_sets(
                pk_b, msg_b, sig_b, bls_api.DST_G2, do_hash=False
            )
            if not ok.all():
                return None
            arrs = SetArrays(lanes)
            n = len(sets)
            arrs.pk_x[:n], arrs.pk_y[:n] = pk_x, pk_y
            arrs.sig_x[:n], arrs.sig_y[:n] = sig_x, sig_y
            cache = self._h2c_cache
            for i, s in enumerate(sets):
                key = s.message
                with self._h2c_lock:
                    hit = cache.get(key)
                if hit is None:
                    # hash OUTSIDE the lock (ms-scale C work, GIL released)
                    rc, limbs = _native.bls_hash_to_g2(key, bls_api.DST_G2)
                    if rc != 0:
                        return None
                    hit = (limbs[0], limbs[1])
                    with self._h2c_lock:
                        while len(cache) >= self._h2c_cache_max:
                            try:
                                cache.pop(next(iter(cache)))
                            except (StopIteration, KeyError):
                                break
                        cache[key] = hit
                arrs.msg_x[i], arrs.msg_y[i] = hit
            arrs.valid[:n] = True
            arrs.n = n
            return arrs
        arrs = SetArrays(lanes)
        for i, s in enumerate(sets):
            if s.pubkey.point.is_infinity():
                return None
            try:
                sig = bls_api.Signature.from_bytes(s.signature).point
            except (bls_api.BlsError, ValueError):
                return None
            if sig.is_infinity():
                return None
            arrs.pk_x[i], arrs.pk_y[i], _ = g1_affine_to_limbs(s.pubkey.point)
            h = hash_to_g2(s.message)
            arrs.msg_x[i], arrs.msg_y[i], _ = g2_affine_to_limbs(h)
            arrs.sig_x[i], arrs.sig_y[i], _ = g2_affine_to_limbs(sig)
            arrs.valid[i] = True
        arrs.n = len(sets)
        return arrs

    # -- public API ---------------------------------------------------------

    def verify_signature_sets(self, sets) -> bool:
        arrs = self._marshal(sets)
        if arrs is None:
            return False
        r_bits = _rand_bits(arrs.pk_x.shape[0], self._rng)
        return bool(self.kernels.verify_batch(arrs, r_bits))

    def verify_signature_sets_individual(self, sets) -> list[bool]:
        arrs = self._marshal(sets)
        if arrs is None:
            # mirror reference behavior: individually report malformed as False
            return [self._verify_one(s) for s in sets]
        out = np.asarray(self.kernels.verify_individual(arrs))
        return [bool(v) for v in out[: arrs.n]]

    def _verify_one(self, s) -> bool:
        try:
            arrs = self._marshal([s])
        except (bls_api.BlsError, ValueError):
            return False
        if arrs is None:
            return False
        return bool(np.asarray(self.kernels.verify_individual(arrs))[0])
