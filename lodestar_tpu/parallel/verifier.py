"""Batched BLS signature-set verification on device.

The device analog of blst's `verifyMultipleSignatures` as consumed by the
reference's `BlsMultiThreadWorkerPool` (`chain/bls/multithread/index.ts:98`,
`maybeBatch.ts:16-27` per SURVEY.md §2.2): verify N signature sets with one
random-linear-combination pairing equation

    Π_i e(r_i·pk_i, H(m_i)) · e(−g1, Σ_i r_i·sig_i) == 1

where r_i are independent nonzero 64-bit scalars. Where the reference
chunks sets across worker threads, here the whole batch is ONE XLA
dispatch: scalar muls, N+1 Miller loops, a log-depth Fp12 product and a
single shared final exponentiation, all vmapped over the batch axis.

Design notes (TPU-first):
- Fixed batch buckets (powers of two) keep shapes static — one compile per
  bucket, reused forever. Padding lanes are masked to the Fp12 identity.
- r_i·pk_i stays projective out of the scalar-mul scan; the Miller loop
  accepts projective P by scaling lines with Zp ∈ Fp (annihilated by the
  final exponentiation) — no per-lane field inversion anywhere. The only
  inversion in the kernel is ONE Fp2 inv for the aggregated signature.
- The per-set retry path of the reference (`multithread/worker.ts:55-95`:
  batch fails → verify each set alone) is `verify_individual`: one batched
  dispatch computing every per-set verdict, not N round-trips.

Host-side preprocessing (deserialization, subgroup checks, hash-to-curve)
currently runs through the CPU oracle; moving it to C++/device SSWU is the
next tier.
"""

from __future__ import annotations

import secrets
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..bls import api as bls_api
from ..bls.hash_to_curve import hash_to_g2
from ..observability.stages import default_pipeline
from ..observability.trace import named_scope
from ..testing import faults as _faults
from ..ops import fp, fp2, fp12, msm, pallas_tower
from ..ops.g2_decompress import decompress as _g2_decompress, planes_in_subgroup as _planes_in_subgroup
from ..ops.io_host import g1_affine_to_limbs, g2_affine_to_limbs
from ..ops.pairing import (
    final_exponentiation,
    final_exponentiation_batch,
    final_exponentiation_one,
    miller_loop,
    miller_loop_proj_pq,
)
from ..ops.points import (
    G1_GEN_X,
    G1_GEN_Y,
    NEG_G1_POW2_64_X,
    NEG_G1_POW2_64_Y,
    NEG_G1_POW2_X,
    NEG_G1_POW2_Y,
    g1,
    g2,
    g2_psi,
)

N_LIMBS = 32
R_BITS = 64  # random-coefficient width (matches blst's 64-bit rand scaling)
HALF_BITS = 32  # the a/b halves of the r = a + z·b GLS split
PROBE_LANES = 16  # bisection probe batch width: ONE compiled shape, chunked

__all__ = [
    "BatchVerifier",
    "TpuBlsVerifier",
    "SetArrays",
    "GroupedArrays",
    "PkGroupedArrays",
    "grouped_verify_kernel",
    "pk_grouped_verify_kernel",
    "bisect_tree_kernel",
    "bisect_probe_kernel",
]


_fp12_product_tree = fp12.product_tree

# host-side Fp12 identity for bisection probe padding (lazy: building it
# touches the device, which import-time code must not)
_FP12_ONE_NP = None


def _g2_sum_tree(ps):
    """log2-depth complete-add reduction of G2 projective points over axis 0."""
    x, y, z = ps
    n = x.shape[0]
    while n > 1:
        half = n // 2
        a = (x[:half], y[:half], z[:half])
        b = (x[half : 2 * half], y[half : 2 * half], z[half : 2 * half])
        hx, hy, hz = g2.add(a, b)
        if n % 2 != 0:
            hx = jnp.concatenate([hx, x[2 * half :]], 0)
            hy = jnp.concatenate([hy, y[2 * half :]], 0)
            hz = jnp.concatenate([hz, z[2 * half :]], 0)
        x, y, z = hx, hy, hz
        n = x.shape[0]
    return x[0], y[0], z[0]


def batch_verify_kernel(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, r_bits, valid):
    return _batch_verify_impl(
        pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, r_bits, valid,
        check_planes=False,
    )


def batch_verify_kernel_raw(pk_x, pk_y, msg_x, msg_y, sig_raw, r_bits, valid):
    """`batch_verify_kernel` taking RAW 96-byte compressed signatures.

    Device-side decompression + batched plane subgroup check
    (`ops/g2_decompress` — VERDICT r4 #5): the host's only signature work
    is a memcpy. Any valid lane whose signature fails decoding (bad
    flags, off-curve, infinity) makes the verdict False — matching the
    host-marshal path, where `_native_limbs` returns None and the caller
    reports False."""
    with named_scope("bls/g2_decompress"):
        sig_x, sig_y, dec_ok = _g2_decompress(sig_raw)
    decode_fail = jnp.any(valid & ~dec_ok)
    verdict = _batch_verify_impl(
        pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, r_bits,
        valid & dec_ok, check_planes=True,
    )
    return verdict & ~decode_fail


def _batch_verify_impl(
    pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, r_bits, valid, check_planes
):
    """All-or-nothing batch verification; shapes (N, …) static.

    pk_*  (N, 32)     G1 affine Montgomery limbs (pre-aggregated pubkeys)
    msg_* (N, 2, 32)  G2 affine limbs of H(m_i)
    sig_* (N, 2, 32)  G2 affine limbs of signatures
    r_bits (N, 64)    random coefficients, MSB-first bits
    valid (N,) bool   padding mask — False lanes are ignored
    Returns scalar bool.

    Round-4 restructure of the signature aggregate (the all-unique
    worst-case shape is adversary-selectable — VERDICT r3 #1): instead of
    per-lane 64-step G2 ladders + a sum tree + one affine inversion,
    Σ r_i·sig_i rides the grouped kernel's constant-lane trick —
    per-bit-plane masked sums U_b (subset-4 tables, `ops/msm.py`) paired
    against precomputed −[2^b]g1, so e(−g1, Σ 2^b U_b) = Π_b e(−[2^b]g1,
    U_b) with NO sequential recombination. G1 r_i·pk_i keeps its bit
    ladder (it feeds per-set Miller lanes; measured cheap). Projective-Q
    Miller costs only the 6 sparse add steps extra.

    Bit ladders, NOT the windowed variant, for the G1 side: measured on
    v5e (tools/win_check) the 2^4-window table selects cost more than the
    saved adds and XLA compile time grows ~30x.
    """
    n = pk_x.shape[0]
    # r_i·pk_i (G1, projective out of the scan — no inversion)
    with named_scope("bls/scalar_mul"):
        rpk = g1.scalar_mul_bits(r_bits, (pk_x, pk_y))

    # signature side: global bit-plane sums over all N lanes (LSB-first
    # planes; r_bits arrive MSB-first)
    sig = (sig_x, sig_y, fp2.one((n,)))
    sig = g2.select(valid, sig, g2.infinity((n,)))
    with named_scope("bls/msm_planes"):
        u_planes = msm.masked_plane_sums(
            g2, sig, jnp.flip(r_bits, axis=-1)
        )  # (64, …) projective

    # Pair lanes: N (r_i·pk_i, H(m_i)) plus 64 (−[2^b]g1, U_b)
    px = jnp.concatenate([rpk[0], NEG_G1_POW2_64_X], 0)
    py = jnp.concatenate([rpk[1], NEG_G1_POW2_64_Y], 0)
    pz = jnp.concatenate([rpk[2], fp.one((R_BITS,))], 0)
    qx = jnp.concatenate([msg_x, u_planes[0]], 0)
    qy = jnp.concatenate([msg_y, u_planes[1]], 0)
    qz = jnp.concatenate([fp2.one((n,)), u_planes[2]], 0)
    lane_ok = jnp.concatenate(
        [valid, ~g2.is_infinity(u_planes)], 0
    )

    with named_scope("bls/miller_loop"):
        fs = miller_loop_proj_pq((px, py, pz), (qx, qy, qz))
    fs = fp12.select(lane_ok, fs, fp12.one((n + R_BITS,)))
    with named_scope("bls/product_tree"):
        prod = _fp12_product_tree(fs)
    with named_scope("bls/final_exp_batch"):
        verdict = fp12.is_one(final_exponentiation_one(prod))
    if check_planes:
        # signature subgroup membership, batched: ψ(U_b) == [x]U_b on the
        # 64 random bit-planes (2^-63 even with the forced-nonzero bit —
        # soundness analysis in ops/g2_decompress.py)
        verdict = verdict & _planes_in_subgroup(u_planes)
    return verdict


def grouped_verify_kernel(
    pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, a_bits, b_bits, valid
):
    return _grouped_verify_impl(
        pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, a_bits, b_bits, valid,
        check_planes=False,
    )


def grouped_verify_kernel_raw(
    pk_x, pk_y, msg_x, msg_y, sig_raw, a_bits, b_bits, valid
):
    """`grouped_verify_kernel` taking RAW 96-byte compressed signatures
    (R, L, 96) — device decompression + plane subgroup checks, same
    contract as `batch_verify_kernel_raw`."""
    with named_scope("bls/g2_decompress"):
        sig_x, sig_y, dec_ok = _g2_decompress(sig_raw)
    decode_fail = jnp.any(valid & ~dec_ok)
    verdict = _grouped_verify_impl(
        pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, a_bits, b_bits,
        valid & dec_ok, check_planes=True,
    )
    return verdict & ~decode_fail


def _grouped_verify_impl(
    pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, a_bits, b_bits, valid, check_planes
):
    """Batch verification GROUPED by signing root — the gossip-shape fast
    path (round-3 perf centerpiece; VERDICT r2 Missing #1).

    Real gossip traffic shares signing roots (every member of a committee
    signs the same data — the reference pre-aggregates pubkeys per SET for
    this reason, `chain/bls/utils.ts:5-16`; here the whole BATCH equation
    is regrouped by bilinearity):

        Π_j e(Σ_{i∈j} r_i·pk_i, H_j) · e(−g1, Σ_i r_i·sig_i) == 1

    R root-rows × L lanes replace N+1 Miller loops with 2R+64 — at the
    64-root gossip shape that is ~60× fewer pairings. Three structural
    moves keep everything off the sequential-latency floor:

    - GLS split randomness: r_i = a_i + z·b_i with a_i, b_i uniform
      32-bit ((a,b) ↦ a+z·b is injective mod r, so r_i is uniform over
      2^64 residues — soundness unchanged at 2^-64) and ψ(Q) = [z]Q
      two fp2 multiplies. Halves every bit-plane depth.
    - per-root pubkey sums P_j = A_j + [z]B_j via bit-plane MSM
      (`ops/msm.py`): subset-4 tables + per-plane tree sums, then ONE
      Horner over 32 planes vectorized across (2, R) lanes; the [z]
      lands as e(B_j, ψ(H_j)) — no device scalar ladders at all.
    - the signature aggregate never gets Horner-combined: each plane
      U_b = Σ bit_b(a_i)·sig_i pairs against the CONSTANT −[2^b]g1
      (e(−g1, Σ 2^b U_b) = Π_b e(−[2^b]g1, U_b)), and the b-half rides
      the same constants through ψ(U'_b).

    Shapes (static): pk_* (R, L, 32); msg_* (R, 2, 32) — ONE H(m) per
    root-row; sig_* (R, L, 2, 32); a_bits/b_bits (R, L, 32) LSB-first;
    valid (R, L). L % 4 == 0. Rows may repeat a root (the marshaller
    splits >L-set roots across rows — bilinearity doesn't care). Padding
    lanes/rows are masked to infinity and contribute 1. Returns scalar
    bool, all-or-nothing like `batch_verify_kernel`.
    """
    R, L = pk_x.shape[0], pk_x.shape[1]
    n = R * L
    # mask invalid lanes to infinity (complete formulas absorb them)
    pk = (pk_x, pk_y, fp.one((R, L)))
    pk = g1.select(valid, pk, g1.infinity((R, L)))
    bits = jnp.concatenate([a_bits, b_bits], axis=-1)  # (R, L, 64)

    # per-root bit-plane sums: (64, R) G1 projective
    with named_scope("bls/msm_planes"):
        t_planes = msm.masked_plane_sums(g1, pk, bits)
        # A_j (a-half) and B_j (b-half) via one Horner over (2, R) lanes
        tp = tuple(c.reshape((2, HALF_BITS) + c.shape[1:]) for c in t_planes)
        tp = tuple(jnp.moveaxis(c, 1, 0) for c in tp)  # (32, 2, R, …)
        ab = msm.horner_pow2(g1, tp)  # (2, R) projective
    a_pt = tuple(c[0] for c in ab)
    b_pt = tuple(c[1] for c in ab)

    # signature side: global bit-plane sums over all N lanes
    sig = (
        sig_x.reshape((n,) + sig_x.shape[-2:]),
        sig_y.reshape((n,) + sig_y.shape[-2:]),
        fp2.one((n,)),
    )
    sig = g2.select(valid.reshape(n), sig, g2.infinity((n,)))
    with named_scope("bls/msm_planes"):
        u_planes = msm.masked_plane_sums(
            g2, sig, bits.reshape(n, 2 * HALF_BITS)
        )
    u_a = tuple(c[:HALF_BITS] for c in u_planes)
    u_b = g2_psi(tuple(c[HALF_BITS:] for c in u_planes))

    # Miller lanes: (A_j, H_j), (B_j, ψH_j), (−[2^b]g1, U_b), (−[2^b]g1, ψU'_b)
    h = (msg_x, msg_y, fp2.one((R,)))
    psi_h = g2_psi(h)
    px = jnp.concatenate(
        [a_pt[0], b_pt[0], NEG_G1_POW2_X, NEG_G1_POW2_X], 0
    )
    py = jnp.concatenate(
        [a_pt[1], b_pt[1], NEG_G1_POW2_Y, NEG_G1_POW2_Y], 0
    )
    pz = jnp.concatenate(
        [a_pt[2], b_pt[2], fp.one((2 * HALF_BITS,))], 0
    )
    qx = jnp.concatenate([h[0], psi_h[0], u_a[0], u_b[0]], 0)
    qy = jnp.concatenate([h[1], psi_h[1], u_a[1], u_b[1]], 0)
    qz = jnp.concatenate([h[2], psi_h[2], u_a[2], u_b[2]], 0)

    # e(O, ·) = e(·, O) = 1: mask infinity lanes (empty rows, zero planes)
    lane_ok = ~g1.is_infinity((px, py, pz)) & ~g2.is_infinity((qx, qy, qz))
    with named_scope("bls/miller_loop"):
        fs = miller_loop_proj_pq((px, py, pz), (qx, qy, qz))
    fs = fp12.select(lane_ok, fs, fp12.one((2 * R + 2 * HALF_BITS,)))
    with named_scope("bls/product_tree"):
        prod = fp12.product_tree(fs)
    with named_scope("bls/final_exp_batch"):
        verdict = fp12.is_one(final_exponentiation_one(prod))
    if check_planes:
        # u_planes BEFORE the ψ split: 64 iid random-bit planes of the
        # signature lanes (soundness analysis in ops/g2_decompress.py)
        verdict = verdict & _planes_in_subgroup(u_planes)
    return verdict


def pk_grouped_verify_kernel(
    pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, a_bits, b_bits, valid
):
    return _pk_grouped_verify_impl(
        pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, a_bits, b_bits, valid,
        check_planes=False,
    )


def pk_grouped_verify_kernel_raw(
    pk_x, pk_y, msg_x, msg_y, sig_raw, a_bits, b_bits, valid
):
    """`pk_grouped_verify_kernel` taking RAW 96-byte compressed signatures
    (R, L, 96) — device decompression + plane subgroup checks."""
    with named_scope("bls/g2_decompress"):
        sig_x, sig_y, dec_ok = _g2_decompress(sig_raw)
    decode_fail = jnp.any(valid & ~dec_ok)
    verdict = _pk_grouped_verify_impl(
        pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, a_bits, b_bits,
        valid & dec_ok, check_planes=True,
    )
    return verdict & ~decode_fail


def _pk_grouped_verify_impl(
    pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, a_bits, b_bits, valid, check_planes
):
    """Batch verification GROUPED BY PUBKEY — the DUAL of the root-grouped
    kernel, and the adversarial-floor defense (VERDICT r4 #2).

    An attacker can mint arbitrarily many unique `AttestationData` roots
    (defeating root-grouping), but every set still needs a VALID signature
    — and the attacker only controls boundedly many validator keys. Sets
    sharing a pubkey collapse by bilinearity on the OTHER side:

        Π_k e(pk_k, Σ_{i∈k} r_i·H_i) · e(−g1, Σ_i r_i·sig_i) == 1

    R pubkey-rows × L lanes run R+64 Miller loops instead of N+64. The
    per-row message combination Σ r_i·H_i is a G2 bit-plane MSM (same
    `ops/msm.py` machinery as the root-grouped pubkey sums, on the twist):
    GLS-split randomness halves plane depth, ψ lands the b-half, and ONE
    32-step Horner over (2, R) lanes recombines — the per-row result is a
    single G2 point added to ψ(b-half), so each row is ONE pairing lane.
    The signature aggregate rides the same constant-lane planes as every
    other kernel. The residual true worst case — distinct pubkeys AND
    distinct roots simultaneously — remains on the per-set kernel and is
    reported honestly as its own bench row.

    Shapes (static): pk_* (R, 32) — ONE pubkey per row; msg_* and sig_*
    (R, L, 2, 32); a_bits/b_bits (R, L, 32) LSB-first; valid (R, L).
    L % 4 == 0. Rows may repeat a pubkey (the planner splits >L-set
    groups across rows). Returns scalar bool, all-or-nothing.

    Reference analog: blst aggregates PUBKEYS per set for one shared
    message (`chain/bls/utils.ts:5-16`); this is the transpose — messages
    aggregated per pubkey — enabled by device-scale MSM.
    """
    R, L = msg_x.shape[0], msg_x.shape[1]
    n = R * L
    msgs = (msg_x, msg_y, fp2.one((R, L)))
    msgs = g2.select(valid, msgs, g2.infinity((R, L)))
    bits = jnp.concatenate([a_bits, b_bits], axis=-1)  # (R, L, 64)

    # per-row message bit-plane sums: (64, R) G2 projective
    with named_scope("bls/msm_planes"):
        m_planes = msm.masked_plane_sums(g2, msgs, bits)
        tp = tuple(c.reshape((2, HALF_BITS) + c.shape[1:]) for c in m_planes)
        tp = tuple(jnp.moveaxis(c, 1, 0) for c in tp)  # (32, 2, R, …)
        ab = msm.horner_pow2(g2, tp)  # (2, R) projective
    a_pt = tuple(c[0] for c in ab)
    b_pt = tuple(c[1] for c in ab)
    q_row = g2.add(a_pt, g2_psi(b_pt))  # Σ r_i·H_i per row

    # signature side: identical constant-lane planes as the other kernels
    sig = (
        sig_x.reshape((n,) + sig_x.shape[-2:]),
        sig_y.reshape((n,) + sig_y.shape[-2:]),
        fp2.one((n,)),
    )
    sig = g2.select(valid.reshape(n), sig, g2.infinity((n,)))
    with named_scope("bls/msm_planes"):
        u_planes = msm.masked_plane_sums(
            g2, sig, bits.reshape(n, 2 * HALF_BITS)
        )
    u_a = tuple(c[:HALF_BITS] for c in u_planes)
    u_b = g2_psi(tuple(c[HALF_BITS:] for c in u_planes))

    px = jnp.concatenate([pk_x, NEG_G1_POW2_X, NEG_G1_POW2_X], 0)
    py = jnp.concatenate([pk_y, NEG_G1_POW2_Y, NEG_G1_POW2_Y], 0)
    pz = jnp.concatenate([fp.one((R,)), fp.one((2 * HALF_BITS,))], 0)
    qx = jnp.concatenate([q_row[0], u_a[0], u_b[0]], 0)
    qy = jnp.concatenate([q_row[1], u_a[1], u_b[1]], 0)
    qz = jnp.concatenate([q_row[2], u_a[2], u_b[2]], 0)

    lane_ok = ~g1.is_infinity((px, py, pz)) & ~g2.is_infinity((qx, qy, qz))
    with named_scope("bls/miller_loop"):
        fs = miller_loop_proj_pq((px, py, pz), (qx, qy, qz))
    fs = fp12.select(lane_ok, fs, fp12.one((R + 2 * HALF_BITS,)))
    with named_scope("bls/product_tree"):
        prod = fp12.product_tree(fs)
    with named_scope("bls/final_exp_batch"):
        verdict = fp12.is_one(final_exponentiation_one(prod))
    if check_planes:
        verdict = verdict & _planes_in_subgroup(u_planes)
    return verdict


def _individual_pairing_terms(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y):
    """(N,) per-set pairing products e(pk_i, H(m_i))·e(−g1, sig_i) — the
    shared front half of both per-set verdict tails below."""
    n = pk_x.shape[0]
    neg_gy = fp.neg(G1_GEN_Y)
    xs = jnp.concatenate([pk_x, jnp.broadcast_to(G1_GEN_X, (n, N_LIMBS))], 0)
    ys = jnp.concatenate([pk_y, jnp.broadcast_to(neg_gy, (n, N_LIMBS))], 0)
    qx = jnp.concatenate([msg_x, sig_x], 0)
    qy = jnp.concatenate([msg_y, sig_y], 0)
    with named_scope("bls/miller_loop"):
        fs = miller_loop((xs, ys), (qx, qy))
    return fp12.mul(fs[:n], fs[n:])


def individual_verify_kernel(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, valid):
    """Per-set verdicts in one dispatch: e(pk_i, H(m_i))·e(−g1, sig_i) == 1.

    The device replacement for the reference's retry-individually fallback
    (`multithread/worker.ts:55-95`) — instead of N sequential re-verifies,
    2N Miller loops and N final exponentiations run batched. Returns
    (N,) bool; padding lanes report False.
    """
    if pallas_tower.pairing_enabled():
        # whole pairing (Miller loop + batched final exp) fused per tile in
        # VMEM — no HBM spill of the Fp12 accumulator between the two halves
        fe = pallas_tower.pairing_fused_pallas(
            (pk_x, pk_y), (msg_x, msg_y), (sig_x, sig_y))
        return fp12.is_one(fe) & valid
    prod = _individual_pairing_terms(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y)
    # the (N,)-wide batched final exp is the per-set path's latency win:
    # ONE shared easy-part inversion chain instead of N (ISSUE 14)
    with named_scope("bls/final_exp_batch"):
        return fp12.is_one(final_exponentiation_batch(prod)) & valid


def individual_verify_kernel_legacy_fe(
    pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, valid
):
    """The pre-batching per-set verdict tail: N independent per-lane
    final exponentiations (one Fermat inversion chain EACH). Kept only
    as the bench `floor_fused_pairing` comparison baseline — never
    dispatched in production; must stay verdict-identical to
    `individual_verify_kernel`."""
    prod = _individual_pairing_terms(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y)
    with named_scope("bls/final_exp"):
        return fp12.is_one(final_exponentiation(prod)) & valid


def bisect_tree_kernel(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, r_bits, valid):
    """Per-set randomized Fp12 terms + EVERY product-tree level, one
    final exponentiation — the bisection-verdict fast path.

    The per-set verdict path used to pay N final exps per batch
    (`individual_verify_kernel`). The classic batch-verification-with-
    bisection result does better: each lane contributes an independent
    randomized term

        f_i = ML(r_i·pk_i, H_i) · ML(−g1, r_i·sig_i)

    whose final exp is ε_i^{r_i} (ε_i = the set's pairing error). The
    product tree over f_i is materialized LEVEL BY LEVEL: the root passes
    exactly when every set is valid (up to the 2^-64 random-combination
    soundness — blst's own bound), which costs ONE final exp for the
    common all-valid case. On failure the host binary-searches the
    already-materialized internal nodes (`TpuBlsVerifier._bisect`): k
    invalid sets cost O(k·log N) probe final exps instead of N, and each
    leaf probe is EXACT (r_i < 2^64 < r is invertible mod r, so
    ε_i^{r_i} = 1 ⟺ ε_i = 1) — leaf verdicts match
    `individual_verify_kernel` bit-for-bit.

    Returns (root_ok, levels): levels[0] (M,) leaf terms with M = N
    padded to a power of two (identity padding), levels[j] (M >> j,)
    partial products, levels[-1] (1,) the root. Padding lanes (valid
    False) contribute the identity and must be reported False by the
    caller."""
    n = pk_x.shape[0]
    with named_scope("bls/scalar_mul"):
        rpk = g1.scalar_mul_bits(r_bits, (pk_x, pk_y))
        rsig = g2.scalar_mul_bits(r_bits, (sig_x, sig_y))
    neg_gy = fp.neg(G1_GEN_Y)
    px = jnp.concatenate([rpk[0], jnp.broadcast_to(G1_GEN_X, (n, N_LIMBS))], 0)
    py = jnp.concatenate([rpk[1], jnp.broadcast_to(neg_gy, (n, N_LIMBS))], 0)
    pz = jnp.concatenate([rpk[2], fp.one((n,))], 0)
    qx = jnp.concatenate([msg_x, rsig[0]], 0)
    qy = jnp.concatenate([msg_y, rsig[1]], 0)
    qz = jnp.concatenate([fp2.one((n,)), rsig[2]], 0)
    with named_scope("bls/miller_loop"):
        fs = miller_loop_proj_pq((px, py, pz), (qx, qy, qz))
    f = fp12.mul(fs[:n], fs[n:])
    f = fp12.select(valid, f, fp12.one((n,)))
    m = 1 << max(0, (n - 1).bit_length())
    if m > n:
        f = jnp.concatenate([f, fp12.one((m - n,))], 0)
    with named_scope("bls/product_tree"):
        levels = [f]
        while f.shape[0] > 1:
            f = fp12.mul(f[0::2], f[1::2])
            levels.append(f)
    with named_scope("bls/final_exp_batch"):
        root_ok = fp12.is_one(final_exponentiation_one(levels[-1][0]))
    return root_ok, levels


def bisect_probe_kernel(fs):
    """(PROBE_LANES,) stacked product-tree nodes → (PROBE_LANES,) bool:
    is_one(final_exp) per lane, the easy part's inversion shared across
    the whole probe batch (`final_exponentiation_batch` — Montgomery
    product trick). Identity-padded lanes pass trivially and are sliced
    off by the host."""
    with named_scope("bls/bisect"):
        return fp12.is_one(final_exponentiation_batch(fs))


def final_exp_batch_kernel(fs):
    """(N,) stacked Fp12 products → (N,) bool via ONE shared-inversion
    batched final exp. The standalone compile unit for the warmup ladder
    and the bench floor comparison — the fused verdict kernels inline
    the same `final_exponentiation_batch` code."""
    with named_scope("bls/final_exp_batch"):
        return fp12.is_one(final_exponentiation_batch(fs))


def miller_pallas_kernel(pk_x, pk_y, msg_x, msg_y):
    """Affine Miller loop forced onto the VMEM-resident Pallas tower
    kernel (ops/pallas_tower.py) regardless of the dispatch knob — the
    warmup/ledger compile unit for the LODESTAR_TPU_PALLAS_MILLER path
    (production kernels route here implicitly via `pairing.miller_loop`
    when the knob resolves on)."""
    return pallas_tower.miller_loop_pallas((pk_x, pk_y), (msg_x, msg_y))


def pairing_pallas_kernel(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, valid):
    """Per-set verdicts forced through the VMEM-resident fused
    full-pairing Pallas kernel (ops/pallas_tower.py) regardless of the
    LODESTAR_TPU_PALLAS_PAIRING knob — the warmup/ledger compile unit
    for the fused path (`individual_verify_kernel` routes here
    implicitly when the knob resolves on). Verdict-identical to the XLA
    `miller_loop` + `final_exponentiation_batch` route."""
    fe = pallas_tower.pairing_fused_pallas(
        (pk_x, pk_y), (msg_x, msg_y), (sig_x, sig_y))
    return fp12.is_one(fe) & valid


class SetArrays:
    """Host-marshalled signature sets, padded to a fixed lane count."""

    __slots__ = ("pk_x", "pk_y", "msg_x", "msg_y", "sig_x", "sig_y", "valid", "n")

    def __init__(self, lanes: int):
        self.pk_x = np.zeros((lanes, N_LIMBS), np.int32)
        self.pk_y = np.zeros((lanes, N_LIMBS), np.int32)
        self.msg_x = np.zeros((lanes, 2, N_LIMBS), np.int32)
        self.msg_y = np.zeros((lanes, 2, N_LIMBS), np.int32)
        self.sig_x = np.zeros((lanes, 2, N_LIMBS), np.int32)
        self.sig_y = np.zeros((lanes, 2, N_LIMBS), np.int32)
        self.valid = np.zeros((lanes,), bool)
        self.n = 0


# --- host marshalling pool ---------------------------------------------------
#
# The C marshal tier releases the GIL, so a thread pool sized to the host's
# cores lifts wire→device throughput linearly (reference sizes its BLS
# worker pool identically: chain/bls/multithread/poolSize.ts:1-16 —
# "blst runs on the main thread; size workers to cores").

_MARSHAL_CHUNK = 256  # sets per pool task (~0.3 s of C work per chunk)
_POOL = None
_POOL_SIZE = 0


def marshal_pool_size() -> int:
    import os

    from ..utils.env import env_int

    override = env_int("LODESTAR_TPU_MARSHAL_THREADS")
    if override is not None:
        return max(0, override)
    return os.cpu_count() or 1


def _marshal_pool():
    """Shared ThreadPoolExecutor, or None on single-core hosts (chunking
    through a pool of one just adds overhead)."""
    global _POOL, _POOL_SIZE
    size = marshal_pool_size()
    if size <= 1:
        return None
    if _POOL is None or _POOL_SIZE != size:
        from concurrent.futures import ThreadPoolExecutor

        _POOL = ThreadPoolExecutor(max_workers=size, thread_name_prefix="bls-marshal")
        _POOL_SIZE = size
    return _POOL


class GroupedArrays:
    """Signature sets grouped by signing root into (R rows × L lanes)."""

    __slots__ = ("pk_x", "pk_y", "msg_x", "msg_y", "sig_x", "sig_y", "valid", "n")

    def __init__(self, rows: int, lanes: int):
        self.pk_x = np.zeros((rows, lanes, N_LIMBS), np.int32)
        self.pk_y = np.zeros((rows, lanes, N_LIMBS), np.int32)
        self.msg_x = np.zeros((rows, 2, N_LIMBS), np.int32)
        self.msg_y = np.zeros((rows, 2, N_LIMBS), np.int32)
        self.sig_x = np.zeros((rows, lanes, 2, N_LIMBS), np.int32)
        self.sig_y = np.zeros((rows, lanes, 2, N_LIMBS), np.int32)
        self.valid = np.zeros((rows, lanes), bool)
        self.n = 0


class PkGroupedArrays:
    """Signature sets grouped by PUBKEY into (R rows × L lanes) — one
    pubkey per row, per-lane messages/signatures (the dual layout)."""

    __slots__ = ("pk_x", "pk_y", "msg_x", "msg_y", "sig_x", "sig_y", "valid", "n")

    def __init__(self, rows: int, lanes: int):
        self.pk_x = np.zeros((rows, N_LIMBS), np.int32)
        self.pk_y = np.zeros((rows, N_LIMBS), np.int32)
        self.msg_x = np.zeros((rows, lanes, 2, N_LIMBS), np.int32)
        self.msg_y = np.zeros((rows, lanes, 2, N_LIMBS), np.int32)
        self.sig_x = np.zeros((rows, lanes, 2, N_LIMBS), np.int32)
        self.sig_y = np.zeros((rows, lanes, 2, N_LIMBS), np.int32)
        self.valid = np.zeros((rows, lanes), bool)
        self.n = 0


def _rand_bits(lanes: int, rng) -> np.ndarray:
    """(lanes, 64) nonzero random scalar bits, MSB first."""
    out = np.zeros((lanes, R_BITS), np.int32)
    for i in range(lanes):
        r = 0
        while r == 0:
            r = rng() & ((1 << R_BITS) - 1)
        out[i] = [(r >> (R_BITS - 1 - j)) & 1 for j in range(R_BITS)]
    return out


def _rand_pairs(shape: tuple[int, ...], rng=None):
    """LSB-first bit planes of the GLS-split coefficients r = a + z·b.

    Returns (a_bits, b_bits), each shape + (32,) int32 in {0,1}. (a, b)
    uniform 32-bit with (0, 0) excluded — injective into 2^64 residues, so
    the batch equation keeps blst's 2^-64 soundness. `rng` (tests only)
    supplies 64-bit words split as (low, high) = (a, b)."""
    count = int(np.prod(shape, dtype=np.int64)) if shape else 1
    if rng is None:
        g = np.random.default_rng(secrets.randbits(128))
        a = g.integers(0, 1 << HALF_BITS, size=count, dtype=np.uint64)
        b = g.integers(0, 1 << HALF_BITS, size=count, dtype=np.uint64)
        a[(a == 0) & (b == 0)] = 1
    else:
        vals = [rng() for _ in range(count)]
        a = np.array([v & 0xFFFFFFFF for v in vals], np.uint64)
        b = np.array([v >> HALF_BITS for v in vals], np.uint64)
        a[(a == 0) & (b == 0)] = 1
    shifts = np.arange(HALF_BITS, dtype=np.uint64)[None, :]
    a_bits = ((a[:, None] >> shifts) & 1).astype(np.int32).reshape(shape + (HALF_BITS,))
    b_bits = ((b[:, None] >> shifts) & 1).astype(np.int32).reshape(shape + (HALF_BITS,))
    return a_bits, b_bits


class BatchVerifier:
    """Shape-bucketed jitted kernels. One compile per bucket size, cached.

    `grouped_configs` are (rows, lanes_per_row) shapes for the root-grouped
    kernel — one compile each, so the list stays short. lanes_per_row must
    be a multiple of 4 (the MSM subset-4 tables)."""

    def __init__(
        self,
        buckets: tuple[int, ...] = (4, 16, 64, 128),
        grouped_configs: tuple[tuple[int, int], ...] = ((16, 8), (64, 64)),
        pk_grouped_configs: tuple[tuple[int, int], ...] = ((128, 32),),
    ):
        self.buckets = tuple(sorted(buckets))
        self.grouped_configs = tuple(
            sorted(grouped_configs, key=lambda c: c[0] * c[1])
        )
        self.pk_grouped_configs = tuple(
            sorted(pk_grouped_configs, key=lambda c: c[0] * c[1])
        )
        for _, lanes in self.grouped_configs + self.pk_grouped_configs:
            if lanes % 4 != 0:
                raise ValueError("grouped lanes_per_row must be a multiple of 4")
        for b in self.buckets:
            if b % 4 != 0:
                # the per-set kernel's bit-plane signature sums use
                # subset-4 tables (ops/msm.py): lane counts must divide
                raise ValueError("buckets must be multiples of 4")
        # every jitted kernel goes through the compile ledger's wrap seam:
        # the first dispatch per shape signature is timed and recorded as
        # a compile event (kernel name, shape key, duration, persistent-
        # cache hit/miss) — zero overhead after the first call
        from ..observability.compile_ledger import ledger as _compile_ledger

        _wrap = _compile_ledger().wrap
        self._batch = _wrap(jax.jit(batch_verify_kernel), "batch")
        self._individual = _wrap(jax.jit(individual_verify_kernel), "individual")
        self._grouped = _wrap(jax.jit(grouped_verify_kernel), "grouped")
        self._batch_raw = _wrap(jax.jit(batch_verify_kernel_raw), "batch_raw")
        self._grouped_raw = _wrap(
            jax.jit(grouped_verify_kernel_raw), "grouped_raw"
        )
        self._pk_grouped = _wrap(jax.jit(pk_grouped_verify_kernel), "pk_grouped")
        self._pk_grouped_raw = _wrap(
            jax.jit(pk_grouped_verify_kernel_raw), "pk_grouped_raw"
        )
        self._bisect_tree = _wrap(jax.jit(bisect_tree_kernel), "bisect_tree")
        self._bisect_probe = _wrap(jax.jit(bisect_probe_kernel), "bisect_probe")
        # ISSUE 14 compile units: the standalone shared-inversion batched
        # final exp and the Pallas Miller tower — wrapped so their first
        # dispatches are timed, cache-classified and visible at
        # /debug/compiles like the 9 fused kernels above
        self._final_exp_batch = _wrap(
            jax.jit(final_exp_batch_kernel), "final_exp_batch"
        )
        self._miller_pallas = _wrap(
            jax.jit(miller_pallas_kernel), "miller_pallas"
        )
        # ISSUE 18 compile unit: the fused full-pairing Pallas kernel
        # (Miller loop + batched final exp, VMEM-resident per tile)
        self._pairing_pallas = _wrap(
            jax.jit(pairing_pallas_kernel), "pairing_pallas"
        )

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def verify_batch(self, arrs: SetArrays, r_bits: np.ndarray):
        return self._batch(
            arrs.pk_x, arrs.pk_y, arrs.msg_x, arrs.msg_y,
            arrs.sig_x, arrs.sig_y, r_bits, arrs.valid,
        )

    def verify_grouped(self, g: GroupedArrays, a_bits, b_bits):
        return self._grouped(
            g.pk_x, g.pk_y, g.msg_x, g.msg_y, g.sig_x, g.sig_y,
            a_bits, b_bits, g.valid,
        )

    def verify_batch_raw(self, arrs: SetArrays, sig_raw, r_bits):
        """Per-set kernel with on-device signature decompression;
        `sig_raw` (N, 96) uint8, `arrs.sig_*` ignored."""
        return self._batch_raw(
            arrs.pk_x, arrs.pk_y, arrs.msg_x, arrs.msg_y,
            sig_raw, r_bits, arrs.valid,
        )

    def verify_grouped_raw(self, g: GroupedArrays, sig_raw, a_bits, b_bits):
        """Grouped kernel with on-device signature decompression;
        `sig_raw` (R, L, 96) uint8, `g.sig_*` ignored."""
        return self._grouped_raw(
            g.pk_x, g.pk_y, g.msg_x, g.msg_y, sig_raw,
            a_bits, b_bits, g.valid,
        )

    def verify_pk_grouped(self, g: "PkGroupedArrays", a_bits, b_bits):
        return self._pk_grouped(
            g.pk_x, g.pk_y, g.msg_x, g.msg_y, g.sig_x, g.sig_y,
            a_bits, b_bits, g.valid,
        )

    def verify_pk_grouped_raw(self, g: "PkGroupedArrays", sig_raw, a_bits, b_bits):
        return self._pk_grouped_raw(
            g.pk_x, g.pk_y, g.msg_x, g.msg_y, sig_raw,
            a_bits, b_bits, g.valid,
        )

    def verify_individual(self, arrs: SetArrays):
        return self._individual(
            arrs.pk_x, arrs.pk_y, arrs.msg_x, arrs.msg_y,
            arrs.sig_x, arrs.sig_y, arrs.valid,
        )

    def verify_bisect_tree(self, arrs: SetArrays, r_bits: np.ndarray):
        """(root_ok, product-tree levels) for the bisection-verdict path;
        the all-valid common case is decided by root_ok alone (ONE final
        exp), levels feed `TpuBlsVerifier._bisect` on failure."""
        return self._bisect_tree(
            arrs.pk_x, arrs.pk_y, arrs.msg_x, arrs.msg_y,
            arrs.sig_x, arrs.sig_y, r_bits, arrs.valid,
        )

    def probe_nodes(self, fs: np.ndarray):
        """(PROBE_LANES,) stacked Fp12 tree nodes → (PROBE_LANES,) bool
        via one batched shared-easy-part final exp."""
        return self._bisect_probe(fs)

    def final_exp_batch(self, fs):
        """(N,) stacked Fp12 products → (N,) bool through the standalone
        shared-inversion batched final-exp compile unit."""
        return self._final_exp_batch(fs)

    def miller_pallas(self, p_aff, q_aff):
        """VMEM-resident Pallas Miller tower on affine (P, Q) — warmup
        rung and /debug/compiles entry; production dispatch reaches the
        same kernel via `ops.pairing.miller_loop` when
        LODESTAR_TPU_PALLAS_MILLER resolves on."""
        return self._miller_pallas(p_aff[0], p_aff[1], q_aff[0], q_aff[1])

    def pairing_pallas(self, arrs: SetArrays):
        """Per-set verdicts through the fused full-pairing Pallas kernel
        regardless of the LODESTAR_TPU_PALLAS_PAIRING knob — warmup rung
        and /debug/compiles entry; production dispatch reaches the same
        kernel via `individual_verify_kernel` when the knob resolves on."""
        return self._pairing_pallas(
            arrs.pk_x, arrs.pk_y, arrs.msg_x, arrs.msg_y,
            arrs.sig_x, arrs.sig_y, arrs.valid,
        )


class TpuBlsVerifier:
    """`IBlsVerifier`-shaped host API over the device kernels
    (reference: `chain/bls/interface.ts:20-46`).

    verify_signature_sets(sets) — all-or-nothing batch verdict.
    verify_signature_sets_individual(sets) — per-set verdicts (retry path).

    Semantics match the reference/eth2: infinity pubkeys or signatures,
    malformed encodings, or failed subgroup checks → False (without
    raising), exactly like `maybeBatch.ts` catching blst errors.
    """

    def __init__(
        self,
        buckets: tuple[int, ...] = (4, 16, 64, 128),
        rng=None,
        grouped_configs: tuple[tuple[int, int], ...] = ((16, 8), (64, 64)),
        device_decompress: bool | None = None,
        pk_grouped_configs: tuple[tuple[int, int], ...] = ((128, 32),),
        observer=None,
        mesh="auto",
    ):
        self.kernels = BatchVerifier(buckets, grouped_configs, pk_grouped_configs)
        # pipeline telemetry (observability.stages.PipelineMetrics): stage
        # timers, planner counters, cache hit rates. Node wiring passes the
        # /metrics-registered instance; the default keeps bench/tools lit.
        self.observer = observer if observer is not None else default_pipeline()
        self._custom_rng = rng
        self._rng = rng if rng is not None else (lambda: secrets.randbits(R_BITS))
        # hash-to-curve cache keyed by signing root: committee gossip
        # shares roots (every member of a committee signs the same data),
        # so H(m) recomputation dominates marshalling without this.
        # Insertion-ordered dict as LRU-ish FIFO, bounded; the lock covers
        # the get/evict/insert sequence — gossip threads and the block
        # import pool hit one shared verifier concurrently.
        import threading

        self._h2c_cache: dict[bytes, tuple] = {}
        self._h2c_cache_max = 8192
        self._h2c_lock = threading.Lock()
        # pubkey-limb cache: attesters repeat every epoch, so the per-set
        # G1 decompression (one Fp sqrt, ~0.2 ms C-tier) is redundant
        # steady-state work. The reference holds decompressed pubkeys in
        # its Index2PubkeyCache for exactly this reason (worker.ts
        # "deserializes affine without re-checking"). Bounded FIFO like
        # the h2c cache. Each entry is ONE packed (2·N_LIMBS,) int32
        # array (x‖y) — 256 B of limb data + one ndarray header + dict
        # slot + 48-B key ≈ 550 B/entry, so the 2^21 default costs
        # ~1.1 GB host RAM and holds every active mainnet validator with
        # headroom — a cap BELOW the active set would thrash to 0% hits
        # at exactly the target scale. Smaller hosts should set
        # LODESTAR_TPU_PK_CACHE_MAX (2^20 ≈ 0.55 GB still covers 1M).
        from ..utils.env import env_bool, env_int

        self._pk_cache: dict[bytes, "np.ndarray"] = {}  # guarded-by: _pk_lock
        self._pk_cache_max = env_int("LODESTAR_TPU_PK_CACHE_MAX")
        self._pk_lock = threading.Lock()
        # On-device signature decompression + batched plane subgroup
        # checks (ops/g2_decompress): removes the ~0.6 ms/set C-tier
        # signature marshal — the e2e floor on few-core hosts (VERDICT
        # r4 #5). DEFAULT-ON since round 6 (VERDICT r5 #4: the round's
        # biggest e2e win shipped off by default): the differential
        # coverage (tests/test_ops_decompress.py, the raw-kernel twins in
        # tests/test_parallel_verifier.py) is the same evidence the limb
        # kernels rest on. Constructor arg wins, then
        # LODESTAR_TPU_DEVICE_DECOMPRESS=0 as the off-switch (hosts with
        # cores to spare can keep the C tier); batches the native tier
        # can't marshal fall back to the host path automatically
        # (`_native_eligible` gates every raw dispatch).
        if device_decompress is None:
            device_decompress = env_bool("LODESTAR_TPU_DEVICE_DECOMPRESS")
        self._device_decompress = bool(device_decompress)
        # Mesh serving (round 7): grouped/pk-grouped/bisect batches
        # dispatch across every visible chip via parallel/mesh. The
        # default "auto" policy (env LODESTAR_TPU_MESH) enables the mesh
        # only on real multi-chip hardware — virtual CPU meshes are
        # opt-in ("force") because their chips share host cores. Pass a
        # BlsMeshDispatcher for explicit control, or mesh=None to pin
        # single-device dispatch.
        if mesh == "auto":
            from .mesh import auto_mesh

            self._mesh = auto_mesh(self.observer)
        else:
            self._mesh = mesh or None
        # Epoch-scoped pubkey table (ISSUE 18): committees are fixed per
        # epoch, so node.py pre-populates decompressed G1 limbs for the
        # whole active set at epoch transition; `_pk_rows` consults the
        # table before paying the C-tier sqrt, and the bounded `_pk_cache`
        # above stays as the fallback for keys the table never saw.
        if env_bool("LODESTAR_TPU_EPOCH_TABLE"):
            from .epoch_table import EpochPubkeyTable

            self._epoch_table = EpochPubkeyTable(observer=self.observer)
        else:
            self._epoch_table = None

    # -- mesh passthroughs (supervisor failure policy) ----------------------

    def mesh_evict(self, chip: int | None = None, reason: str = "failure"):
        """Evict a sick chip from the serving mesh; None when no mesh or
        nothing left to evict (the supervisor then falls back tiers)."""
        if self._mesh is None:
            return None
        return self._mesh.evict(chip=chip, reason=reason)

    def mesh_readmit(self) -> int:
        return 0 if self._mesh is None else self._mesh.readmit()

    def mesh_has_evicted(self) -> bool:
        return self._mesh is not None and self._mesh.has_evicted()

    def mesh_snapshot(self):
        return None if self._mesh is None else self._mesh.snapshot()

    def mesh_evict_host(self, host: int | None = None,
                        reason: str = "failure"):
        """Evict a whole host from the two-level serving fleet; None when
        no mesh / single-host census / nothing left to evict."""
        if self._mesh is None:
            return None
        return self._mesh.evict_host(host=host, reason=reason)

    def fleet_snapshot(self):
        return None if self._mesh is None else self._mesh.fleet_snapshot()

    def fleet_attach_router(self, router) -> None:
        """Bind the FleetRouter so host evictions rebalance its subnet
        slices (node wiring; no-op without a mesh)."""
        if self._mesh is not None:
            self._mesh.attach_router(router)

    # -- host marshalling ---------------------------------------------------

    def _native_eligible(self, sets) -> bool:
        from .. import native as _native

        return _native.HAVE_NATIVE_BLS and all(
            len(s.message) == 32 and len(s.signature) == 96 for s in sets
        )

    def _hash_root(self, key: bytes):
        """H(m) limbs for one 32-byte signing root via the bounded cache;
        None if the C tier rejects it."""
        from .. import native as _native

        cache = self._h2c_cache
        with self._h2c_lock:
            hit = cache.get(key)
        self.observer.cache_event("h2c", hit is not None)
        if hit is None:
            # hash OUTSIDE the lock (ms-scale C work, GIL released)
            with self.observer.stage("hash_to_curve"):
                rc, limbs = _native.bls_hash_to_g2(key, bls_api.DST_G2)
            if rc != 0:
                return None
            hit = (limbs[0], limbs[1])
            with self._h2c_lock:
                while len(cache) >= self._h2c_cache_max:
                    try:
                        cache.pop(next(iter(cache)))
                    except (StopIteration, KeyError):
                        break
                cache[key] = hit
        return hit

    def _pk_rows(self, sets):
        """(pk_x, pk_y) rows for every set via the pubkey-limb cache;
        None if any pubkey is malformed/infinity. Cache misses pay one
        C-tier G1 decompression each — once per validator, ever."""
        from .. import native as _native

        try:
            keys = [s.pubkey.to_bytes() for s in sets]
        except (bls_api.BlsError, ValueError):
            return None
        with self._pk_lock:
            rows = [self._pk_cache.get(k) for k in keys]
        misses = {k for k, r in zip(keys, rows) if r is None}
        self.observer.cache_event("pk", True, n=len(keys) - len(misses))
        self.observer.cache_event("pk", False, n=len(misses))
        if misses:
            fresh = {}
            # epoch table first: a hit is a memcpy off the host mirror
            # instead of a C-tier Fp sqrt (ISSUE 18)
            if self._epoch_table is not None:
                miss_keys = list(misses)
                for k, row in zip(
                    miss_keys, self._epoch_table.lookup_rows(miss_keys)
                ):
                    if row is not None:
                        fresh[k] = row
                        misses.discard(k)
            for k in misses:
                rc, limbs = _native.bls_g1_decompress(k, check_subgroup=False)
                if rc != 0:
                    return None  # infinity pubkey is invalid per Eth2
                fresh[k] = np.concatenate((limbs[0], limbs[1]))
            with self._pk_lock:
                cache = self._pk_cache
                for k, v in fresh.items():
                    while len(cache) >= self._pk_cache_max:
                        try:
                            cache.pop(next(iter(cache)))
                        except (StopIteration, KeyError):
                            break
                    cache[k] = v
            rows = [r if r is not None else fresh[k] for k, r in zip(keys, rows)]
        n = len(sets)
        pk_x = np.empty((n, N_LIMBS), np.int32)
        pk_y = np.empty((n, N_LIMBS), np.int32)
        for i, r in enumerate(rows):
            pk_x[i] = r[:N_LIMBS]
            pk_y[i] = r[N_LIMBS:]
        return pk_x, pk_y

    # -- epoch-scoped precomputation (ISSUE 18) -----------------------------

    def warm_h2c(self, messages) -> int:
        """Pre-warm the hash-to-curve cache for 32-byte signing roots —
        the dispatcher's H(msg) dedup seam: one hash_to_g2 per UNIQUE
        attestation data across a coalesced flush, after which the
        marshal path hits `_h2c_cache` for every duplicate. Returns the
        number of roots hashed (misses)."""
        hashed = 0
        for m in messages:
            if len(m) != 32:
                continue
            with self._h2c_lock:
                hit = m in self._h2c_cache
            if not hit:
                if self._hash_root(m) is not None:
                    hashed += 1
        return hashed

    def epoch_table_populate(self, epoch: int, pubkeys) -> int:
        """Install one epoch's device-resident pubkey table entry from an
        iterable of compressed pubkey bytes (node.py calls this at epoch
        transition with the active validator set). Decompression happens
        once per key here — off the dispatch path — reusing `_pk_cache`
        rows when present. Returns rows installed; 0 when the table is
        disabled or a key is malformed (population is best-effort: the
        dispatch path keeps its own fallbacks)."""
        from .. import native as _native

        if self._epoch_table is None:
            return 0
        items = []
        for k in pubkeys:
            k = bytes(k)
            with self._pk_lock:
                row = self._pk_cache.get(k)
            if row is None:
                rc, limbs = _native.bls_g1_decompress(k, check_subgroup=False)
                if rc != 0:
                    continue  # skip malformed/infinity, keep the rest
                row = np.concatenate((limbs[0], limbs[1]))
            items.append((k, row))
        return self._epoch_table.populate(epoch, items)

    def epoch_table_snapshot(self):
        """Epoch-table state for `/debug/epoch_table`; {"enabled": False}
        when LODESTAR_TPU_EPOCH_TABLE is off."""
        if self._epoch_table is None:
            return {"enabled": False}
        snap = self._epoch_table.snapshot()
        snap["enabled"] = True
        return snap

    def _native_limbs(self, sets):
        """Per-set (pk_x, pk_y, sig_x, sig_y) limb arrays via the C tier
        (decompress + subgroup checks, no hashing); None if any set is
        malformed, out of subgroup, or at infinity.

        Pubkeys come from the limb cache (`_pk_rows`); only signatures
        pay the per-set decompression. Large batches are chunked across
        the marshalling pool: the C tier releases the GIL, so threads
        scale with cores (the reference sizes its worker pool the same
        way — `chain/bls/multithread/poolSize.ts`)."""
        from .. import native as _native

        pk_rows = self._pk_rows(sets)
        if pk_rows is None:
            return None
        pk_x, pk_y = pk_rows
        n = len(sets)
        pk_b = b"\x00" * (48 * n)  # unused: do_pk=False
        msg_b = b"".join(s.message for s in sets)
        sig_b = b"".join(s.signature for s in sets)

        pool = _marshal_pool()
        if pool is None or n < 2 * _MARSHAL_CHUNK:
            _px, _py, _mx, _my, sig_x, sig_y, ok = _native.bls_marshal_sets(
                pk_b, msg_b, sig_b, bls_api.DST_G2, do_hash=False, do_pk=False
            )
            if not ok.all():
                return None
            return pk_x, pk_y, sig_x, sig_y

        def chunk(lo: int, hi: int):
            return _native.bls_marshal_sets(
                pk_b[48 * lo : 48 * hi],
                msg_b[32 * lo : 32 * hi],
                sig_b[96 * lo : 96 * hi],
                bls_api.DST_G2,
                do_hash=False,
                do_pk=False,
            )

        bounds = list(range(0, n, _MARSHAL_CHUNK)) + [n]
        futs = [
            pool.submit(chunk, lo, hi)
            for lo, hi in zip(bounds[:-1], bounds[1:])
        ]
        parts = [f.result() for f in futs]
        if not all(p[6].all() for p in parts):
            return None
        sig_x = np.concatenate([p[4] for p in parts])
        sig_y = np.concatenate([p[5] for p in parts])
        return pk_x, pk_y, sig_x, sig_y

    def _split_shared_unique(self, sets):
        """Partition set indices into (shared-root, singleton-root).

        The adversarial-mix defense (VERDICT r3 #1): an attacker minting
        unique `AttestationData` must not drag the whole batch onto the
        per-set kernel — honest committee traffic (shared roots) keeps
        the grouped fast path; only the attacker's singletons pay the
        per-set rate."""
        from collections import Counter

        freq = Counter(s.message for s in sets)
        shared = [i for i, s in enumerate(sets) if freq[s.message] >= 2]
        unique = [i for i, s in enumerate(sets) if freq[s.message] < 2]
        return shared, unique

    @staticmethod
    def _plan_runs(keys, configs):
        """Shared run-packing for both grouping axes: pack items into
        per-key runs of ≤ lane_cap, ≤ rows_cap runs total; None when no
        config fits or fewer than half the items share keys."""
        uniq = len(set(keys))
        if uniq * 2 > len(keys):
            return None
        for rows_cap, lane_cap in configs:
            if len(keys) > rows_cap * lane_cap:
                continue
            runs: list[list[int]] = []
            open_run: dict[bytes, list[int]] = {}
            fits = True
            for idx, key in enumerate(keys):
                run = open_run.get(key)
                if run is not None and len(run) < lane_cap:
                    run.append(idx)
                else:
                    run = [idx]
                    runs.append(run)
                    open_run[key] = run
                    if len(runs) > rows_cap:
                        fits = False
                        break
            if fits:
                return rows_cap, lane_cap, runs
        return None

    def _plan_groups(self, sets):
        """Choose a grouped-kernel config + row assignment, or None for the
        flat path. Grouping pays when roots are shared (committee gossip);
        a mostly-unique batch stays on the per-set kernel."""
        return self._plan_runs(
            [s.message for s in sets], self.kernels.grouped_configs
        )

    def _marshal_grouped(self, sets, plan, raw: bool = False):
        """Scatter sets into (rows × lanes) by signing root; None if any
        set is invalid (the caller reports False, same as `_marshal`).

        raw=False: C-tier signature decompression → GroupedArrays.
        raw=True: signatures stay BYTES for the device decode path →
        (GroupedArrays with sig_* zeroed, sig_raw (R, L, 96) uint8)."""
        rows_cap, lane_cap, runs = plan
        if raw:
            pk_rows = self._pk_rows(sets)
            if pk_rows is None:
                return None
            pk_x, pk_y = pk_rows
            sig_all = np.frombuffer(
                b"".join(s.signature for s in sets), np.uint8
            ).reshape(len(sets), 96)
            sig_raw = np.zeros((rows_cap, lane_cap, 96), np.uint8)
        else:
            limbs = self._native_limbs(sets)
            if limbs is None:
                return None
            pk_x, pk_y, sig_x, sig_y = limbs
        g = GroupedArrays(rows_cap, lane_cap)
        for row, run in enumerate(runs):
            hit = self._hash_root(sets[run[0]].message)
            if hit is None:
                return None
            g.msg_x[row], g.msg_y[row] = hit
            idx = np.asarray(run)
            k = len(run)
            g.pk_x[row, :k], g.pk_y[row, :k] = pk_x[idx], pk_y[idx]
            if raw:
                sig_raw[row, :k] = sig_all[idx]
            else:
                g.sig_x[row, :k], g.sig_y[row, :k] = sig_x[idx], sig_y[idx]
            g.valid[row, :k] = True
        g.n = len(sets)
        return (g, sig_raw) if raw else g

    def _plan_pk_groups(self, sets):
        """Choose a pk-grouped config + row assignment, or None. Pays when
        pubkeys repeat while roots do not (attacker-minted unique
        AttestationData — the adversarial shape; VERDICT r4 #2)."""
        try:
            keys = [s.pubkey.to_bytes() for s in sets]
        except (bls_api.BlsError, ValueError):
            return None  # flat path reports the malformed set as False
        return self._plan_runs(keys, self.kernels.pk_grouped_configs)

    def _marshal_pk_grouped(self, sets, plan, raw: bool = False):
        """Scatter sets into (rows × lanes) by pubkey; None if any set is
        invalid. raw=True keeps signatures as bytes for the device.

        This path's target workload is all-UNIQUE roots (the adversarial
        flood), so the h2c cache never hits — messages are hashed through
        the marshal pool in chunks (the C tier releases the GIL; hashing
        scales with host cores like the reference's worker pool)."""
        rows_cap, lane_cap, runs = plan
        if raw:
            pk_rows = self._pk_rows(sets)
            if pk_rows is None:
                return None
            pk_x, pk_y = pk_rows
            sig_all = np.frombuffer(
                b"".join(s.signature for s in sets), np.uint8
            ).reshape(len(sets), 96)
            sig_raw = np.zeros((rows_cap, lane_cap, 96), np.uint8)
        else:
            limbs = self._native_limbs(sets)
            if limbs is None:
                return None
            pk_x, pk_y, sig_x, sig_y = limbs
        # pooled hash-to-curve over the (mostly-unique) roots
        pool = _marshal_pool()
        hits: list = [None] * len(sets)
        if pool is not None and len(sets) >= 2 * _MARSHAL_CHUNK:
            def hash_chunk(lo, hi):
                return [self._hash_root(s.message) for s in sets[lo:hi]]

            bounds = list(range(0, len(sets), _MARSHAL_CHUNK)) + [len(sets)]
            futs = [
                pool.submit(hash_chunk, lo, hi)
                for lo, hi in zip(bounds[:-1], bounds[1:])
            ]
            out = []
            for f in futs:
                out.extend(f.result())
            hits = out
        else:
            hits = [self._hash_root(s.message) for s in sets]
        if any(h is None for h in hits):
            return None
        g = PkGroupedArrays(rows_cap, lane_cap)
        for row, run in enumerate(runs):
            g.pk_x[row], g.pk_y[row] = pk_x[run[0]], pk_y[run[0]]
            for j, idx in enumerate(run):
                g.msg_x[row, j], g.msg_y[row, j] = hits[idx]
            idxs = np.asarray(run)
            k = len(run)
            if raw:
                sig_raw[row, :k] = sig_all[idxs]
            else:
                g.sig_x[row, :k], g.sig_y[row, :k] = sig_x[idxs], sig_y[idxs]
            g.valid[row, :k] = True
        g.n = len(sets)
        return (g, sig_raw) if raw else g

    def _submit_pk_grouped_mesh(self, sets, plan):
        """Sharded pk-grouped dispatch (raw wire-byte signatures when
        device decompression is on — see `_submit_grouped_mesh`)."""
        from .mesh import NOT_SHARDED

        if self._device_decompress:
            with self.observer.stage("marshal"):
                marshalled = self._marshal_pk_grouped(sets, plan, raw=True)
            if marshalled is None:
                return None
            g, sig_raw = marshalled
            with self.observer.stage("rand"):
                a_bits, b_bits = _rand_pairs(g.valid.shape, self._custom_rng)
            with self.observer.stage("dispatch"):
                result = self._mesh.dispatch_pk_grouped_raw(
                    g, sig_raw, a_bits, b_bits
                )
                if result is NOT_SHARDED:
                    result = self.kernels.verify_pk_grouped_raw(
                        g, sig_raw, a_bits, b_bits
                    )
            return result
        with self.observer.stage("marshal"):
            g = self._marshal_pk_grouped(sets, plan)
        if g is None:
            return None
        with self.observer.stage("rand"):
            a_bits, b_bits = _rand_pairs(g.valid.shape, self._custom_rng)
        with self.observer.stage("dispatch"):
            result = self._mesh.dispatch_pk_grouped(g, a_bits, b_bits)
            if result is NOT_SHARDED:
                result = self.kernels.verify_pk_grouped(g, a_bits, b_bits)
        return result

    def _submit_pk_grouped(self, sets, plan):
        """Dispatch one pk-grouped batch; None marks an invalid set."""
        self.observer.planner(
            "pk_grouped", len(sets), group_sizes=[len(r) for r in plan[2]]
        )
        if self._mesh_shardable(plan[0]):
            return self._submit_pk_grouped_mesh(sets, plan)
        if self._device_decompress:
            with self.observer.stage("marshal"):
                marshalled = self._marshal_pk_grouped(sets, plan, raw=True)
            if marshalled is None:
                return None
            g, sig_raw = marshalled
            with self.observer.stage("rand"):
                a_bits, b_bits = _rand_pairs(g.valid.shape, self._custom_rng)
            with self.observer.stage("dispatch"):
                return self.kernels.verify_pk_grouped_raw(
                    g, sig_raw, a_bits, b_bits
                )
        with self.observer.stage("marshal"):
            g = self._marshal_pk_grouped(sets, plan)
        if g is None:
            return None
        with self.observer.stage("rand"):
            a_bits, b_bits = _rand_pairs(g.valid.shape, self._custom_rng)
        with self.observer.stage("dispatch"):
            return self.kernels.verify_pk_grouped(g, a_bits, b_bits)

    def _marshal(self, sets, raw: bool = False):
        """Build padded device arrays; None if any set is invalid up front.

        Fast path: the native C tier (`native/src/bls12.c`) decompresses,
        subgroup-checks and hash-to-curves the whole batch in one call —
        the reference keeps exactly this preprocessing in blst C
        (multithread/worker.ts:33-55). Falls back to the big-int oracle
        when the extension is unavailable.

        raw=True: signatures stay BYTES for the device decode path →
        (SetArrays with sig_* zeroed, sig_raw (lanes, 96) uint8).
        """
        if not sets:
            return None
        lanes = self.kernels.bucket_for(len(sets))
        if len(sets) > lanes:
            return None  # caller must chunk (service layer's job)

        if raw:
            pk_rows = self._pk_rows(sets)
            if pk_rows is None:
                return None
            pk_x, pk_y = pk_rows
            arrs = SetArrays(lanes)
            sig_raw = np.zeros((lanes, 96), np.uint8)
            n = len(sets)
            arrs.pk_x[:n], arrs.pk_y[:n] = pk_x, pk_y
            sig_raw[:n] = np.frombuffer(
                b"".join(s.signature for s in sets), np.uint8
            ).reshape(n, 96)
            for i, s in enumerate(sets):
                hit = self._hash_root(s.message)
                if hit is None:
                    return None
                arrs.msg_x[i], arrs.msg_y[i] = hit
            arrs.valid[:n] = True
            arrs.n = n
            return arrs, sig_raw

        if self._native_eligible(sets):
            limbs = self._native_limbs(sets)
            if limbs is None:
                return None
            pk_x, pk_y, sig_x, sig_y = limbs
            arrs = SetArrays(lanes)
            n = len(sets)
            arrs.pk_x[:n], arrs.pk_y[:n] = pk_x, pk_y
            arrs.sig_x[:n], arrs.sig_y[:n] = sig_x, sig_y
            for i, s in enumerate(sets):
                hit = self._hash_root(s.message)
                if hit is None:
                    return None
                arrs.msg_x[i], arrs.msg_y[i] = hit
            arrs.valid[:n] = True
            arrs.n = n
            return arrs
        arrs = SetArrays(lanes)
        for i, s in enumerate(sets):
            if s.pubkey.point.is_infinity():
                return None
            try:
                sig = bls_api.Signature.from_bytes(s.signature).point
            except (bls_api.BlsError, ValueError):
                return None
            if sig.is_infinity():
                return None
            arrs.pk_x[i], arrs.pk_y[i], _ = g1_affine_to_limbs(s.pubkey.point)
            h = hash_to_g2(s.message)
            arrs.msg_x[i], arrs.msg_y[i], _ = g2_affine_to_limbs(h)
            arrs.sig_x[i], arrs.sig_y[i], _ = g2_affine_to_limbs(sig)
            arrs.valid[i] = True
        arrs.n = len(sets)
        return arrs

    # -- public API ---------------------------------------------------------

    def verify_signature_sets(self, sets) -> bool:
        return self.verify_signature_sets_submit(sets)()

    def verify_signature_sets_submit(self, sets):
        """Marshal on the host NOW, dispatch to the device NOW, block
        LATER: returns a zero-arg resolver for the verdict.

        The device computes while the caller marshals its next batch —
        the double-buffering the reference gets from its worker pool
        (main thread aggregates the next job while workers verify,
        `chain/bls/interface.ts:30-35`). `verify_signature_sets` is
        submit-then-resolve with no batch behind it."""
        # fault-injection seam (testing.faults): no-op unless a plan is
        # armed via LODESTAR_TPU_FAULTS or /debug/faults — the supervisor
        # tier's failure policy is exercised against exactly this boundary
        _faults.on_device_dispatch(len(sets))
        if sets and self._native_eligible(sets):
            plan = self._plan_groups(sets)
            if plan is not None:
                t = time.monotonic()
                result = self._submit_grouped(sets, plan)
                if result is None:
                    return lambda: False
                return lambda: self._resolve(result, t)
            # roots don't group — try the DUAL axis: pubkeys repeat in
            # any adversarial unique-root flood (bounded attacker keys)
            pk_plan = self._plan_pk_groups(sets)
            if pk_plan is not None:
                t = time.monotonic()
                result = self._submit_pk_grouped(sets, pk_plan)
                if result is None:
                    return lambda: False
                return lambda: self._resolve(result, t)
            # mixed batch: peel the shared-root sets onto the grouped
            # kernel; the singleton remainder tries pk-grouping before
            # paying the per-set kernel
            shared, unique = self._split_shared_unique(sets)
            if shared and unique:
                shared_sets = [sets[i] for i in shared]
                sub_plan = self._plan_groups(shared_sets)
                if sub_plan is not None:
                    # the peeled parts also count under their own paths
                    self.observer.planner("split", len(sets))
                    t = time.monotonic()
                    grouped_res = self._submit_grouped(shared_sets, sub_plan)
                    if grouped_res is None:
                        return lambda: False
                    unique_sets = [sets[i] for i in unique]
                    pk_plan = self._plan_pk_groups(unique_sets)
                    if pk_plan is not None:
                        pk_res = self._submit_pk_grouped(unique_sets, pk_plan)
                        if pk_res is None:
                            return lambda: False
                        return lambda: (
                            self._resolve(grouped_res, t)
                            and self._resolve(pk_res, t)
                        )
                    flat = self._submit_flat(unique_sets)
                    return lambda: self._resolve(grouped_res, t) and flat()
        return self._submit_flat(sets)

    def _resolve(self, result, t_submit: float | None = None) -> bool:
        """Block on one device verdict, timing the wait (`device_wait`
        stage) and feeding the busy-fraction sampler with the full
        submit→resolve span (the device computes through the async gap,
        so resolver block time alone undercounts occupancy)."""
        t0 = time.monotonic()
        verdict = bool(result)
        now = time.monotonic()
        self.observer.observe_stage("device_wait", now - t0)
        self.observer.device_busy_sample(
            now - (t_submit if t_submit is not None else t0)
        )
        # flaky-verdict injection (testing.faults): True -> False only,
        # modeling corrupted device computation
        return _faults.flaky_verdict(verdict)

    def _mesh_shardable(self, rows: int) -> bool:
        return (
            self._mesh is not None
            and self._mesh.enabled
            and rows % self._mesh.size == 0
        )

    def _submit_grouped_mesh(self, sets, plan):
        """Sharded grouped dispatch across the serving mesh. With device
        decompression on (the default), signatures stay WIRE BYTES all
        the way onto the mesh — the `*_raw` sharded twins decode each
        chip's row slice on device, so the host marshal is a pure byte
        scatter (zero-copy ingest, same contract as the single-device
        raw path). LODESTAR_TPU_DEVICE_DECOMPRESS=0 keeps the pooled
        C-tier limb marshal. Falls back to the matching single-device
        kernel if the mesh shrank between the eligibility check and the
        dispatch."""
        from .mesh import NOT_SHARDED

        if self._device_decompress:
            with self.observer.stage("marshal"):
                marshalled = self._marshal_grouped(sets, plan, raw=True)
            if marshalled is None:
                return None
            g, sig_raw = marshalled
            with self.observer.stage("rand"):
                a_bits, b_bits = _rand_pairs(g.valid.shape, self._custom_rng)
            with self.observer.stage("dispatch"):
                result = self._mesh.dispatch_grouped_raw(
                    g, sig_raw, a_bits, b_bits
                )
                if result is NOT_SHARDED:
                    result = self.kernels.verify_grouped_raw(
                        g, sig_raw, a_bits, b_bits
                    )
            return result
        with self.observer.stage("marshal"):
            g = self._marshal_grouped(sets, plan)
        if g is None:
            return None
        with self.observer.stage("rand"):
            a_bits, b_bits = _rand_pairs(g.valid.shape, self._custom_rng)
        with self.observer.stage("dispatch"):
            result = self._mesh.dispatch_grouped(g, a_bits, b_bits)
            if result is NOT_SHARDED:
                result = self.kernels.verify_grouped(g, a_bits, b_bits)
        return result

    def _submit_grouped(self, sets, plan):
        """Dispatch one grouped-kernel batch; None marks an invalid set
        (caller reports False)."""
        self.observer.planner(
            "root_grouped", len(sets), group_sizes=[len(r) for r in plan[2]]
        )
        if self._mesh_shardable(plan[0]):
            return self._submit_grouped_mesh(sets, plan)
        if self._device_decompress:
            with self.observer.stage("marshal"):
                marshalled = self._marshal_grouped(sets, plan, raw=True)
            if marshalled is None:
                return None
            g, sig_raw = marshalled
            with self.observer.stage("rand"):
                a_bits, b_bits = _rand_pairs(g.valid.shape, self._custom_rng)
            with self.observer.stage("dispatch"):
                return self.kernels.verify_grouped_raw(
                    g, sig_raw, a_bits, b_bits
                )
        with self.observer.stage("marshal"):
            g = self._marshal_grouped(sets, plan)
        if g is None:
            return None
        with self.observer.stage("rand"):
            a_bits, b_bits = _rand_pairs(g.valid.shape, self._custom_rng)
        with self.observer.stage("dispatch"):
            return self.kernels.verify_grouped(g, a_bits, b_bits)

    def _submit_flat(self, sets):
        """Per-set kernel dispatch (chunked to the largest bucket);
        resolver ANDs the chunk verdicts — all-or-nothing, same as one
        dispatch."""
        if sets:
            self.observer.planner("per_set", len(sets))
        cap = self.kernels.buckets[-1]
        use_raw = self._device_decompress and self._native_eligible(sets)
        results = []
        t_submit = time.monotonic()
        for lo in range(0, max(len(sets), 1), cap):
            chunk = sets[lo : lo + cap]
            if use_raw:
                with self.observer.stage("marshal"):
                    marshalled = self._marshal(chunk, raw=True)
                if marshalled is None:
                    return lambda: False
                arrs, sig_raw = marshalled
                with self.observer.stage("rand"):
                    r_bits = _rand_bits(arrs.pk_x.shape[0], self._rng)
                with self.observer.stage("dispatch"):
                    results.append(
                        self.kernels.verify_batch_raw(arrs, sig_raw, r_bits)
                    )
                continue
            with self.observer.stage("marshal"):
                arrs = self._marshal(chunk)
            if arrs is None:
                return lambda: False
            with self.observer.stage("rand"):
                r_bits = _rand_bits(arrs.pk_x.shape[0], self._rng)
            with self.observer.stage("dispatch"):
                results.append(self.kernels.verify_batch(arrs, r_bits))
        return lambda: all(self._resolve(r, t_submit) for r in results)

    def verify_signature_sets_individual(self, sets) -> list[bool]:
        """Per-set verdicts via BISECTION (round-6 tentpole): one
        randomized product-tree dispatch decides the all-valid common
        case with a single final exponentiation; on failure the
        materialized internal nodes are binary-searched so k invalid
        sets cost O(k·log N) batched probe final exps instead of N
        (`individual_verify_kernel`'s price). Leaf probes are exact, so
        verdicts match the old kernel (and the CPU oracle) bit-for-bit;
        internal short-circuits carry the same 2^-64 soundness as batch
        verification itself."""
        self.observer.planner("individual", len(sets))
        _faults.on_device_dispatch(len(sets))
        with self.observer.stage("marshal"):
            arrs = self._marshal(sets)
        if arrs is None:
            # mirror reference behavior: individually report malformed as False
            return [self._verify_one(s) for s in sets]
        with self.observer.stage("rand"):
            r_bits = _rand_bits(arrs.pk_x.shape[0], self._rng)
        t = time.monotonic()
        with self.observer.stage("dispatch"):
            sharded = None
            if self._mesh is not None and self._mesh.enabled:
                from .mesh import NOT_SHARDED

                sharded = self._mesh.dispatch_bisect(arrs, r_bits)
                if sharded is NOT_SHARDED:
                    sharded = None
            if sharded is not None:
                root_ok, levels = sharded
            else:
                root_ok, levels = self.kernels.verify_bisect_tree(arrs, r_bits)
        with self.observer.stage("device_wait"):
            root_ok = bool(root_ok)
        self.observer.device_busy_sample(time.monotonic() - t)
        if root_ok:
            self.observer.bisect(rounds=0, probes=0)
            return _faults.flaky_verdicts([True] * arrs.n)
        verdicts = self._bisect(arrs, levels)
        return _faults.flaky_verdicts([bool(v) for v in verdicts[: arrs.n]])

    def _bisect(self, arrs, levels) -> np.ndarray:
        """Binary-search a failed product tree for the invalid leaves.

        levels[j] holds M >> j nodes; node (j, i) covers leaves
        [i·2^j, (i+1)·2^j). BFS from the root: every round probes the
        children of the currently-failed nodes — all probes of a round
        ride ONE fixed-shape batched final exp (PROBE_LANES lanes,
        identity-padded, shared easy-part inversion), so a round is one
        dispatch until k grows past PROBE_LANES/2. A child that passes
        clears its whole subtree (2^-64 soundness per probe); failed
        level-0 nodes are the invalid sets, exactly.

        Freak outcome — a failed parent with two passing children (a
        2^-64 cancellation): fall back to the exact per-set kernel
        rather than return an inconsistent verdict vector."""
        levels_np = [np.asarray(l) for l in levels]
        m = levels_np[0].shape[0]
        verdicts = np.ones(m, bool)
        verdicts[arrs.n:] = False  # padding lanes report False
        frontier = [(len(levels_np) - 1, 0)]
        rounds = probes = 0
        global _FP12_ONE_NP
        if _FP12_ONE_NP is None:
            _FP12_ONE_NP = np.asarray(fp12.one(()))
        while frontier:
            if frontier[0][0] == 0:
                for _, i in frontier:
                    verdicts[i] = False
                break
            rounds += 1
            children = [
                (lvl - 1, 2 * i + k) for lvl, i in frontier for k in (0, 1)
            ]
            failed = []
            for lo in range(0, len(children), PROBE_LANES):
                chunk = children[lo : lo + PROBE_LANES]
                batch = np.stack([levels_np[l][i] for l, i in chunk])
                if len(chunk) < PROBE_LANES:
                    pad = np.broadcast_to(
                        _FP12_ONE_NP,
                        (PROBE_LANES - len(chunk),) + _FP12_ONE_NP.shape,
                    )
                    batch = np.concatenate([batch, pad])
                t0 = time.monotonic()
                with self.observer.stage("bisect"):
                    out = np.asarray(self.kernels.probe_nodes(batch))
                self.observer.device_busy_sample(time.monotonic() - t0)
                probes += len(chunk)
                failed.extend(
                    node for node, ok in zip(chunk, out[: len(chunk)])
                    if not ok
                )
            if not failed:
                # 2^-64 cancellation inside a subtree: exact fallback
                self.observer.bisect(rounds=rounds, probes=probes)
                out = np.asarray(self.kernels.verify_individual(arrs))
                return out
            frontier = failed
        self.observer.bisect(rounds=rounds, probes=probes)
        return verdicts

    def _verify_one(self, s) -> bool:
        try:
            arrs = self._marshal([s])
        except (bls_api.BlsError, ValueError):
            return False
        if arrs is None:
            return False
        return bool(np.asarray(self.kernels.verify_individual(arrs))[0])
