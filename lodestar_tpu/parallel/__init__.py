"""TPU parallel tier: batched + mesh-sharded BLS verification kernels.

This package replaces the reference's worker-thread pool
(`beacon-node/src/chain/bls/multithread/` — N CPU threads, 128 sets/job)
with single-dispatch XLA kernels: `verifier` is the single-device batched
path, `sharded` shards the same math over a `jax.sharding.Mesh` with ICI
collectives.
"""

from .verifier import BatchVerifier, TpuBlsVerifier  # noqa: F401
