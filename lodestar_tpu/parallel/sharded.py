"""Mesh-sharded batch verification: the ICI-collective tier.

Where the reference scales BLS batch verification by chunking jobs across
`num_cpus` worker threads (`chain/bls/multithread/index.ts:153-166`,
`poolSize.ts`), this module shards ONE batch across all chips of a
`jax.sharding.Mesh` with `shard_map`:

- every chip runs scalar-muls + Miller loops for its slice of the batch
  (pure data parallelism over the 'dp' axis — zero communication),
- the G2 aggregated-signature sum and the Fp12 pair-product are combined
  with a single `all_gather` each over ICI (small payloads: one projective
  G2 point and one Fp12 element per chip), and the tiny cross-chip tail
  reduction plus the final exponentiation run replicated.

DCN enters when the mesh spans hosts (ROADMAP item 5, fleet serving):
every kernel here also compiles over a TWO-LEVEL mesh — `axis` may be a
tuple ``(dcn_axis, ici_axis)`` naming the outer cross-host axis and the
inner within-host axis of a 2-D `Mesh`. The combines are then
HIERARCHICAL and ICI-first: per-chip partials (Fp12 pair products, G2
bit-plane sums) all_gather over ICI and reduce to ONE per-host value
before a second all_gather crosses DCN — so the slow inter-host fabric
carries one Fp12 element / 64 combined plane sums per HOST, never
per-chip traffic. Per-chip Horner tails and Miller lanes stay ICI-local
either way (pure data parallelism; the linear chip index is DCN-major,
matching the `P((dcn, ici))` row sharding).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..observability.trace import named_scope
from ..ops import fp, fp2, fp12, msm
from ..ops.g2_decompress import (
    decompress as _g2_decompress,
    planes_in_subgroup as _planes_in_subgroup,
)


def _shard_map(f, *, mesh, in_specs, out_specs):
    """`jax.shard_map` across jax versions: the top-level API (with
    `check_vma`) landed after 0.4.x, where it lives in
    `jax.experimental.shard_map` and the kwarg is `check_rep`."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
    )


def _axes(mesh_axis) -> tuple:
    """Normalize an axis spec to a tuple of axis names: ``"dp"`` →
    ``("dp",)``; a two-level ``("dcn", "ici")`` passes through with the
    OUTER (cross-host) axis first — the same order as the 2-D Mesh's
    axis_names and the `P((dcn, ici))` input sharding."""
    return (mesh_axis,) if isinstance(mesh_axis, str) else tuple(mesh_axis)


def _one_axis_size(name):
    # lax.axis_size is newer-jax; psum(1, axis) is the 0.4.x idiom (static)
    return (
        lax.axis_size(name) if hasattr(lax, "axis_size")
        else lax.psum(1, name)
    )


def _mesh_size(mesh_axis):
    """Total chip count across all (1 or 2) mesh axes."""
    n = 1
    for name in _axes(mesh_axis):
        n = n * _one_axis_size(name)
    return n


def _mesh_index(mesh_axis):
    """This chip's linear index over the (possibly two-level) mesh,
    row-major with the DCN axis slowest — matching the `P((dcn, ici))`
    row sharding, so chip k owns global row-block k. Index 0 (host 0,
    chip 0) is the root-tail owner."""
    idx = 0
    for name in _axes(mesh_axis):
        idx = idx * _one_axis_size(name) + lax.axis_index(name)
    return idx


def _gather_fp12_partials(f_loc, mesh_axis):
    """Gather the per-chip Fp12 pair-product partials for the root tail.
    Single-level: one all_gather over ICI → (ndev, …). Two-level: gather
    over ICI first and reduce to the per-host product, so DCN carries
    exactly ONE Fp12 element per host → (hosts, …)."""
    axes = _axes(mesh_axis)
    if len(axes) == 1:
        return lax.all_gather(f_loc, axes[0])
    dcn, ici = axes
    f_host = _fp12_product_tree(lax.all_gather(f_loc, ici))
    return lax.all_gather(f_host, dcn)


def _combine_plane_sums(u_part, mesh_axis):
    """Combine per-chip partial G2 bit-plane sums into the replicated
    (64,) totals. Hierarchical and ICI-first on a two-level mesh: the
    inner gather + tree_sum collapses each host to one set of 64 plane
    sums before the outer (DCN) gather — per-host-combined sums are the
    only plane traffic that crosses hosts."""
    u = u_part
    for name in reversed(_axes(mesh_axis)):
        u_all = tuple(lax.all_gather(c, name) for c in u)  # (n, 64, …)
        u_all = tuple(jnp.moveaxis(c, 0, 1) for c in u_all)  # (64, n, …)
        u = msm.tree_sum(g2, u_all)
    return u
from ..ops.pairing import (
    final_exponentiation_one,
    miller_loop_proj_pq,
    miller_loop_projective,
)
from ..ops.points import (
    G1_GEN_X,
    G1_GEN_Y,
    NEG_G1_POW2_X,
    NEG_G1_POW2_Y,
    g1,
    g2,
    g2_psi,
)
from .verifier import HALF_BITS, N_LIMBS, _fp12_product_tree, _g2_sum_tree

__all__ = [
    "mesh_divisor",
    "make_sharded_verifier",
    "ShardedBlsVerifier",
    "make_sharded_grouped_verifier",
    "ShardedGroupedVerifier",
    "make_sharded_grouped_raw_verifier",
    "ShardedGroupedRawVerifier",
    "make_sharded_pk_grouped_verifier",
    "ShardedPkGroupedVerifier",
    "make_sharded_pk_grouped_raw_verifier",
    "ShardedPkGroupedRawVerifier",
    "make_sharded_bisect_verifier",
    "ShardedBisectVerifier",
]


# host-side mesh sizing lives in the jax-free policy module; re-exported
# here because every sharded-kernel consumer needs it for shape planning
from .mesh import mesh_divisor  # noqa: E402  (after the jax imports above)


def _local_body(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, r_bits, valid):
    """Per-chip slice of the batch equation; returns (local Fp12 pair
    product, local partial G2 signature sum) — the two values that cross
    the ICI boundary."""
    n_loc = pk_x.shape[0]
    rpk = g1.scalar_mul_bits(r_bits, (pk_x, pk_y))
    rsig = g2.scalar_mul_bits(r_bits, (sig_x, sig_y))
    rsig = g2.select(valid, rsig, g2.infinity((n_loc,)))
    s_part = _g2_sum_tree(rsig)

    fs = miller_loop_projective(rpk, (msg_x, msg_y))
    fs = fp12.select(valid, fs, fp12.one((n_loc,)))
    return _fp12_product_tree(fs), s_part


def _tail_on_root(mesh_axis, tail_fn):
    """Run the sequential tail on chip 0 only and broadcast the verdict.

    The tail (G2 affine inversion, one Miller lane, the final
    exponentiation) is a latency-bound chain that cannot shard; running
    it REPLICATED makes every chip burn the same wall-clock — harmless on
    idle real chips but disastrous on a virtual CPU mesh where all
    "devices" share host cores (round-3 MESH_SCALING regressed 145 → 66
    sets/s from exactly this). Chip 0 computes, the rest contribute a
    zero to the psum — the reference's analog is the main thread owning
    aggregation while workers verify (`chain/bls/multithread/index.ts`).

    On a two-level mesh the root is linear chip 0 = (host 0, chip 0) and
    the verdict psum spans both axes (ICI then DCN) — one int32 per host
    crosses DCN."""
    is_root = _mesh_index(mesh_axis) == 0
    verdict_int = lax.cond(
        is_root,
        lambda _: tail_fn().astype(jnp.int32),
        lambda _: jnp.int32(0),
        operand=None,
    )
    return lax.psum(verdict_int, _axes(mesh_axis)) > 0


def _sharded_verify(mesh_axis, pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, r_bits, valid):
    f_loc, s_part = _local_body(
        pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, r_bits, valid
    )
    # gather per-chip partials (1 Fp12 + 1 projective G2 point each);
    # ICI-first on a two-level mesh so only per-host combines cross DCN
    f_all = _gather_fp12_partials(f_loc, mesh_axis)
    axes = _axes(mesh_axis)
    s_all = s_part
    for i, name in enumerate(reversed(axes)):
        s_all = jax.tree.map(lambda x, _n=name: lax.all_gather(x, _n), s_all)
        if i < len(axes) - 1:
            s_all = _g2_sum_tree(s_all)

    def tail():
        s = _g2_sum_tree(s_all)
        s_inf = g2.is_infinity(s)
        s_aff = g2.to_affine(s)
        # e(−g1, S) lane + cross-chip product + final exp
        f_tail = miller_loop_projective(
            (G1_GEN_X, fp.neg(G1_GEN_Y), fp.one(())),
            (s_aff[0], s_aff[1]),
        )
        f_tail = fp12.select(~s_inf, f_tail, fp12.one(()))
        f = fp12.mul(_fp12_product_tree(f_all), f_tail)
        with named_scope("bls/final_exp_batch"):
            return fp12.is_one(final_exponentiation_one(f))

    return _tail_on_root(mesh_axis, tail)


def make_sharded_verifier(mesh: Mesh, axis: str | tuple = "dp"):
    """jit-compiled sharded batch-verify over `mesh`. Batch axis 0 of every
    input must be divisible by the mesh size."""
    spec = P(axis)

    @jax.jit
    def run(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, r_bits, valid):
        fn = _shard_map(
            partial(_sharded_verify, axis),
            mesh=mesh,
            in_specs=(spec,) * 8,
            out_specs=P(),
        )
        return fn(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, r_bits, valid)

    return run


# --- grouped (shared-signing-root) tier --------------------------------------


def _grouped_local(
    mesh_axis, pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, a_bits, b_bits, valid
):
    """Per-chip slice of the GROUPED batch equation.

    The root axis R is sharded: each chip owns R/n root-rows — their
    pubkey bit-plane MSMs, Horner combines and (A_j, H_j)/(B_j, ψH_j)
    Miller lanes are pure data parallelism. The signature aggregate's
    bit-plane sums span the WHOLE batch: each chip reduces its slice to
    64 partial G2 plane sums, one `all_gather` (64 projective points per
    chip — the only cross-chip traffic besides the final Fp12 partials)
    combines them, and the 64 constant −[2^b]g1 Miller lanes are split
    64/n per chip so the pairing work shards too.

    Returns (local Fp12 pair product, combined u_planes): the combined
    plane sums are replicated post-gather, and the raw twin's subgroup
    check (`planes_in_subgroup`) needs them — the limb path discards
    them because the C tier subgroup-checks on the host."""
    r_loc, lanes = pk_x.shape[0], pk_x.shape[1]
    n_loc = r_loc * lanes
    ndev = _mesh_size(mesh_axis)

    pk = (pk_x, pk_y, fp.one((r_loc, lanes)))
    pk = g1.select(valid, pk, g1.infinity((r_loc, lanes)))
    bits = jnp.concatenate([a_bits, b_bits], axis=-1)

    t_planes = msm.masked_plane_sums(g1, pk, bits)  # (64, r_loc)
    tp = tuple(c.reshape((2, HALF_BITS) + c.shape[1:]) for c in t_planes)
    tp = tuple(jnp.moveaxis(c, 1, 0) for c in tp)
    ab = msm.horner_pow2(g1, tp)  # (2, r_loc)
    a_pt = tuple(c[0] for c in ab)
    b_pt = tuple(c[1] for c in ab)

    # local partial signature plane sums → all_gather → combine
    sig = (
        sig_x.reshape((n_loc,) + sig_x.shape[-2:]),
        sig_y.reshape((n_loc,) + sig_y.shape[-2:]),
        fp2.one((n_loc,)),
    )
    sig = g2.select(valid.reshape(n_loc), sig, g2.infinity((n_loc,)))
    u_part = msm.masked_plane_sums(g2, sig, bits.reshape(n_loc, 2 * HALF_BITS))
    u_planes = _combine_plane_sums(u_part, mesh_axis)  # (64,) over all chips
    u_a = tuple(c[:HALF_BITS] for c in u_planes)
    u_b = g2_psi(tuple(c[HALF_BITS:] for c in u_planes))

    # this chip's slice of the 64 constant lanes (linear index: DCN-major)
    per = (2 * HALF_BITS) // ndev
    start = _mesh_index(mesh_axis) * per
    uq = tuple(
        jnp.concatenate([ca, cb], 0) for ca, cb in zip(u_a, u_b)
    )  # (64,) Q lanes in plane order
    uq_loc = tuple(
        lax.dynamic_slice_in_dim(c, start, per, axis=0) for c in uq
    )
    const_x = jnp.concatenate([NEG_G1_POW2_X, NEG_G1_POW2_X], 0)
    const_y = jnp.concatenate([NEG_G1_POW2_Y, NEG_G1_POW2_Y], 0)
    cx_loc = lax.dynamic_slice_in_dim(const_x, start, per, axis=0)
    cy_loc = lax.dynamic_slice_in_dim(const_y, start, per, axis=0)

    h = (msg_x, msg_y, fp2.one((r_loc,)))
    psi_h = g2_psi(h)
    px = jnp.concatenate([a_pt[0], b_pt[0], cx_loc], 0)
    py = jnp.concatenate([a_pt[1], b_pt[1], cy_loc], 0)
    pz = jnp.concatenate([a_pt[2], b_pt[2], fp.one((per,))], 0)
    qx = jnp.concatenate([h[0], psi_h[0], uq_loc[0]], 0)
    qy = jnp.concatenate([h[1], psi_h[1], uq_loc[1]], 0)
    qz = jnp.concatenate([h[2], psi_h[2], uq_loc[2]], 0)

    lane_ok = ~g1.is_infinity((px, py, pz)) & ~g2.is_infinity((qx, qy, qz))
    fs = miller_loop_proj_pq((px, py, pz), (qx, qy, qz))
    fs = fp12.select(lane_ok, fs, fp12.one((2 * r_loc + per,)))
    return _fp12_product_tree(fs), u_planes


def _sharded_grouped_verify(mesh_axis, *args):
    f_loc, _ = _grouped_local(mesh_axis, *args)
    f_all = _gather_fp12_partials(f_loc, mesh_axis)  # (ndev|hosts, 2,3,2,32)

    def tail():
        with named_scope("bls/final_exp_batch"):
            return fp12.is_one(final_exponentiation_one(_fp12_product_tree(f_all)))

    return _tail_on_root(mesh_axis, tail)


def _sharded_grouped_raw_verify(
    mesh_axis, pk_x, pk_y, msg_x, msg_y, sig_raw, a_bits, b_bits, valid
):
    """Raw twin of `_sharded_grouped_verify` (zero-copy wire→mesh ingest):
    each chip decompresses its own (r_loc, lanes, 96) slice of the raw
    signature bytes on device, so the host never touches signature limbs
    and the decode work itself shards with the batch. Semantics mirror
    `grouped_verify_kernel_raw` exactly: lanes that fail to decode are
    masked out of the pairing, any failed VALID lane forces the whole
    verdict False (psum-combined across chips), and the combined
    signature plane sums get the ψ-endomorphism subgroup check — the C
    tier never saw these bytes, so the device must do its own gating."""
    with named_scope("bls/g2_decompress"):
        sig_x, sig_y, dec_ok = _g2_decompress(sig_raw)
    fail_loc = jnp.any(valid & ~dec_ok)
    f_loc, u_planes = _grouped_local(
        mesh_axis, pk_x, pk_y, msg_x, msg_y, sig_x, sig_y,
        a_bits, b_bits, valid & dec_ok,
    )
    f_all = _gather_fp12_partials(f_loc, mesh_axis)
    decode_fail = (
        lax.psum(fail_loc.astype(jnp.int32), _axes(mesh_axis)) > 0
    )

    def tail():
        with named_scope("bls/final_exp_batch"):
            ok = fp12.is_one(
                final_exponentiation_one(_fp12_product_tree(f_all))
            )
        # u_planes is replicated post-gather; running the subgroup check
        # inside the root tail keeps it off the other chips' wall-clock
        return ok & _planes_in_subgroup(u_planes)

    return _tail_on_root(mesh_axis, tail) & ~decode_fail


def make_sharded_grouped_verifier(mesh: Mesh, axis: str | tuple = "dp"):
    """jit-compiled sharded grouped batch-verify over `mesh`. The root
    axis (axis 0 of pk/msg/sig/bits/valid) must be divisible by the mesh
    size, and the mesh size must divide 64 (the constant-lane count)."""
    ndev = mesh.devices.size
    if (2 * HALF_BITS) % ndev != 0:
        # a non-dividing mesh would silently drop constant Miller lanes
        # and reject every valid batch — refuse loudly instead
        raise ValueError(
            f"mesh size {ndev} must divide {2 * HALF_BITS} (constant lanes)"
        )
    spec = P(axis)

    @jax.jit
    def run(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, a_bits, b_bits, valid):
        fn = _shard_map(
            partial(_sharded_grouped_verify, axis),
            mesh=mesh,
            in_specs=(spec,) * 9,
            out_specs=P(),
        )
        return fn(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, a_bits, b_bits, valid)

    return run


def make_sharded_grouped_local_probe(mesh: Mesh, axis: str | tuple = "dp"):
    """INSTRUMENTATION ONLY (tools/mesh_scaling.py): the sharded grouped
    kernel cut after the per-chip local body — MSMs, Horner, the u-plane
    all_gather and per-chip Miller lanes — with the root tail (cross-chip
    Fp12 product + final exp) replaced by a psum checksum. Timing this
    against the full kernel splits a scaling anomaly into "data-parallel
    body" vs "sequential tail" without a profiler on the virtual mesh."""
    ndev = mesh.devices.size
    if (2 * HALF_BITS) % ndev != 0:
        raise ValueError(
            f"mesh size {ndev} must divide {2 * HALF_BITS} (constant lanes)"
        )
    spec = P(axis)

    @jax.jit
    def run(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, a_bits, b_bits, valid):
        def probe(*args):
            f_loc, _ = _grouped_local(axis, *args)
            return lax.psum(jnp.sum(f_loc), axis)

        fn = _shard_map(
            probe, mesh=mesh, in_specs=(spec,) * 9, out_specs=P()
        )
        return fn(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, a_bits, b_bits, valid)

    return run


def make_sharded_grouped_raw_verifier(mesh: Mesh, axis: str | tuple = "dp"):
    """jit-compiled sharded grouped RAW batch-verify over `mesh`:
    signatures enter as (R, L, 96) wire bytes, root-sharded like every
    other input, and decompress on their owning chip. Same divisibility
    contract as `make_sharded_grouped_verifier`."""
    ndev = mesh.devices.size
    if (2 * HALF_BITS) % ndev != 0:
        raise ValueError(
            f"mesh size {ndev} must divide {2 * HALF_BITS} (constant lanes)"
        )
    spec = P(axis)

    @jax.jit
    def run(pk_x, pk_y, msg_x, msg_y, sig_raw, a_bits, b_bits, valid):
        fn = _shard_map(
            partial(_sharded_grouped_raw_verify, axis),
            mesh=mesh,
            in_specs=(spec,) * 8,
            out_specs=P(),
        )
        return fn(pk_x, pk_y, msg_x, msg_y, sig_raw, a_bits, b_bits, valid)

    return run


class ShardedGroupedVerifier:
    """Host wrapper for the sharded grouped kernel: places (R, L) grouped
    arrays root-sharded onto the mesh."""

    def __init__(self, mesh: Mesh, axis: str | tuple = "dp"):
        self.mesh = mesh
        self.axis = axis
        self.ndev = mesh.devices.size
        self._run = make_sharded_grouped_verifier(mesh, axis)
        self._sharding = NamedSharding(mesh, P(axis))

    def submit(self, g, a_bits, b_bits):
        """Async dispatch: returns the on-device scalar verdict (the
        production pipeline resolves it later, off the dispatch thread)."""
        put = lambda x: jax.device_put(x, self._sharding)
        return self._run(
            put(g.pk_x), put(g.pk_y), put(g.msg_x), put(g.msg_y),
            put(g.sig_x), put(g.sig_y), put(a_bits), put(b_bits),
            put(g.valid),
        )

    def verify_grouped(self, g, a_bits, b_bits) -> bool:
        return bool(self.submit(g, a_bits, b_bits))


class ShardedGroupedRawVerifier:
    """Host wrapper for the sharded grouped RAW kernel: the signature
    tensor is the (R, L, 96) wire-byte scatter straight out of
    `_marshal_grouped(raw=True)` — no host decompression, no limb
    conversion; `device_put` with the row sharding is the only host
    touch before the mesh decodes."""

    def __init__(self, mesh: Mesh, axis: str | tuple = "dp"):
        self.mesh = mesh
        self.axis = axis
        self.ndev = mesh.devices.size
        self._run = make_sharded_grouped_raw_verifier(mesh, axis)
        self._sharding = NamedSharding(mesh, P(axis))

    def submit(self, g, sig_raw, a_bits, b_bits):
        """Async dispatch: returns the on-device scalar verdict."""
        put = lambda x: jax.device_put(x, self._sharding)
        return self._run(
            put(g.pk_x), put(g.pk_y), put(g.msg_x), put(g.msg_y),
            put(sig_raw), put(a_bits), put(b_bits), put(g.valid),
        )

    def verify_grouped_raw(self, g, sig_raw, a_bits, b_bits) -> bool:
        return bool(self.submit(g, sig_raw, a_bits, b_bits))


# --- pk-grouped (shared-pubkey) tier -----------------------------------------


def _pk_grouped_local(
    mesh_axis, pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, a_bits, b_bits, valid
):
    """Per-chip slice of the PK-GROUPED batch equation (the dual kernel:
    rows share a pubkey, messages MSM-combine on the twist).

    The pubkey-row axis R is sharded: each chip owns R/n rows — their
    per-row G2 message MSMs, Horner combines and (pk_k, Σ r_i·H_i) Miller
    lanes are pure data parallelism. The signature aggregate and the 64
    constant −[2^b]g1 lanes follow the same pattern as `_grouped_local`:
    one `all_gather` of 64 partial G2 plane sums, constant lanes split
    64/n per chip."""
    r_loc, lanes = msg_x.shape[0], msg_x.shape[1]
    n_loc = r_loc * lanes
    ndev = _mesh_size(mesh_axis)

    msgs = (msg_x, msg_y, fp2.one((r_loc, lanes)))
    msgs = g2.select(valid, msgs, g2.infinity((r_loc, lanes)))
    bits = jnp.concatenate([a_bits, b_bits], axis=-1)

    m_planes = msm.masked_plane_sums(g2, msgs, bits)  # (64, r_loc)
    tp = tuple(c.reshape((2, HALF_BITS) + c.shape[1:]) for c in m_planes)
    tp = tuple(jnp.moveaxis(c, 1, 0) for c in tp)
    ab = msm.horner_pow2(g2, tp)  # (2, r_loc)
    a_pt = tuple(c[0] for c in ab)
    b_pt = tuple(c[1] for c in ab)
    q_row = g2.add(a_pt, g2_psi(b_pt))  # Σ r_i·H_i per local row

    sig = (
        sig_x.reshape((n_loc,) + sig_x.shape[-2:]),
        sig_y.reshape((n_loc,) + sig_y.shape[-2:]),
        fp2.one((n_loc,)),
    )
    sig = g2.select(valid.reshape(n_loc), sig, g2.infinity((n_loc,)))
    u_part = msm.masked_plane_sums(g2, sig, bits.reshape(n_loc, 2 * HALF_BITS))
    u_planes = _combine_plane_sums(u_part, mesh_axis)
    u_a = tuple(c[:HALF_BITS] for c in u_planes)
    u_b = g2_psi(tuple(c[HALF_BITS:] for c in u_planes))

    per = (2 * HALF_BITS) // ndev
    start = _mesh_index(mesh_axis) * per
    uq = tuple(jnp.concatenate([ca, cb], 0) for ca, cb in zip(u_a, u_b))
    uq_loc = tuple(
        lax.dynamic_slice_in_dim(c, start, per, axis=0) for c in uq
    )
    const_x = jnp.concatenate([NEG_G1_POW2_X, NEG_G1_POW2_X], 0)
    const_y = jnp.concatenate([NEG_G1_POW2_Y, NEG_G1_POW2_Y], 0)
    cx_loc = lax.dynamic_slice_in_dim(const_x, start, per, axis=0)
    cy_loc = lax.dynamic_slice_in_dim(const_y, start, per, axis=0)

    px = jnp.concatenate([pk_x, cx_loc], 0)
    py = jnp.concatenate([pk_y, cy_loc], 0)
    pz = jnp.concatenate([fp.one((r_loc,)), fp.one((per,))], 0)
    qx = jnp.concatenate([q_row[0], uq_loc[0]], 0)
    qy = jnp.concatenate([q_row[1], uq_loc[1]], 0)
    qz = jnp.concatenate([q_row[2], uq_loc[2]], 0)

    lane_ok = ~g1.is_infinity((px, py, pz)) & ~g2.is_infinity((qx, qy, qz))
    fs = miller_loop_proj_pq((px, py, pz), (qx, qy, qz))
    fs = fp12.select(lane_ok, fs, fp12.one((r_loc + per,)))
    return _fp12_product_tree(fs), u_planes


def _sharded_pk_grouped_verify(mesh_axis, *args):
    f_loc, _ = _pk_grouped_local(mesh_axis, *args)
    f_all = _gather_fp12_partials(f_loc, mesh_axis)

    def tail():
        with named_scope("bls/final_exp_batch"):
            return fp12.is_one(final_exponentiation_one(_fp12_product_tree(f_all)))

    return _tail_on_root(mesh_axis, tail)


def _sharded_pk_grouped_raw_verify(
    mesh_axis, pk_x, pk_y, msg_x, msg_y, sig_raw, a_bits, b_bits, valid
):
    """Raw twin of `_sharded_pk_grouped_verify`; same decode/subgroup
    gating as `_sharded_grouped_raw_verify` (see there)."""
    with named_scope("bls/g2_decompress"):
        sig_x, sig_y, dec_ok = _g2_decompress(sig_raw)
    fail_loc = jnp.any(valid & ~dec_ok)
    f_loc, u_planes = _pk_grouped_local(
        mesh_axis, pk_x, pk_y, msg_x, msg_y, sig_x, sig_y,
        a_bits, b_bits, valid & dec_ok,
    )
    f_all = _gather_fp12_partials(f_loc, mesh_axis)
    decode_fail = (
        lax.psum(fail_loc.astype(jnp.int32), _axes(mesh_axis)) > 0
    )

    def tail():
        with named_scope("bls/final_exp_batch"):
            ok = fp12.is_one(
                final_exponentiation_one(_fp12_product_tree(f_all))
            )
        return ok & _planes_in_subgroup(u_planes)

    return _tail_on_root(mesh_axis, tail) & ~decode_fail


def make_sharded_pk_grouped_verifier(mesh: Mesh, axis: str | tuple = "dp"):
    """jit-compiled sharded pk-grouped batch-verify over `mesh`. The
    pubkey-row axis must be divisible by the mesh size, and the mesh size
    must divide 64 (the constant-lane count)."""
    ndev = mesh.devices.size
    if (2 * HALF_BITS) % ndev != 0:
        raise ValueError(
            f"mesh size {ndev} must divide {2 * HALF_BITS} (constant lanes)"
        )
    spec = P(axis)

    @jax.jit
    def run(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, a_bits, b_bits, valid):
        fn = _shard_map(
            partial(_sharded_pk_grouped_verify, axis),
            mesh=mesh,
            in_specs=(spec,) * 9,
            out_specs=P(),
        )
        return fn(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, a_bits, b_bits, valid)

    return run


def make_sharded_pk_grouped_raw_verifier(mesh: Mesh, axis: str | tuple = "dp"):
    """jit-compiled sharded pk-grouped RAW batch-verify over `mesh`:
    signatures enter as (R, L, 96) wire bytes and decompress on their
    owning chip. Same divisibility contract as the limb maker."""
    ndev = mesh.devices.size
    if (2 * HALF_BITS) % ndev != 0:
        raise ValueError(
            f"mesh size {ndev} must divide {2 * HALF_BITS} (constant lanes)"
        )
    spec = P(axis)

    @jax.jit
    def run(pk_x, pk_y, msg_x, msg_y, sig_raw, a_bits, b_bits, valid):
        fn = _shard_map(
            partial(_sharded_pk_grouped_raw_verify, axis),
            mesh=mesh,
            in_specs=(spec,) * 8,
            out_specs=P(),
        )
        return fn(pk_x, pk_y, msg_x, msg_y, sig_raw, a_bits, b_bits, valid)

    return run


class ShardedPkGroupedVerifier:
    """Host wrapper for the sharded pk-grouped kernel: places (R,) pubkey
    rows + (R, L) message/signature arrays row-sharded onto the mesh."""

    def __init__(self, mesh: Mesh, axis: str | tuple = "dp"):
        self.mesh = mesh
        self.axis = axis
        self.ndev = mesh.devices.size
        self._run = make_sharded_pk_grouped_verifier(mesh, axis)
        self._sharding = NamedSharding(mesh, P(axis))

    def submit(self, g, a_bits, b_bits):
        put = lambda x: jax.device_put(x, self._sharding)
        return self._run(
            put(g.pk_x), put(g.pk_y), put(g.msg_x), put(g.msg_y),
            put(g.sig_x), put(g.sig_y), put(a_bits), put(b_bits),
            put(g.valid),
        )

    def verify_pk_grouped(self, g, a_bits, b_bits) -> bool:
        return bool(self.submit(g, a_bits, b_bits))


class ShardedPkGroupedRawVerifier:
    """Host wrapper for the sharded pk-grouped RAW kernel (wire-byte
    signatures; see `ShardedGroupedRawVerifier`)."""

    def __init__(self, mesh: Mesh, axis: str | tuple = "dp"):
        self.mesh = mesh
        self.axis = axis
        self.ndev = mesh.devices.size
        self._run = make_sharded_pk_grouped_raw_verifier(mesh, axis)
        self._sharding = NamedSharding(mesh, P(axis))

    def submit(self, g, sig_raw, a_bits, b_bits):
        put = lambda x: jax.device_put(x, self._sharding)
        return self._run(
            put(g.pk_x), put(g.pk_y), put(g.msg_x), put(g.msg_y),
            put(sig_raw), put(a_bits), put(b_bits), put(g.valid),
        )

    def verify_pk_grouped_raw(self, g, sig_raw, a_bits, b_bits) -> bool:
        return bool(self.submit(g, sig_raw, a_bits, b_bits))


# --- bisection-verdict tier ---------------------------------------------------


def _bisect_local(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, r_bits, valid):
    """Per-chip leaf terms of the bisection tree: each chip runs the
    scalar ladders and both Miller lanes for its slice of the batch —
    f_i = ML(r_i·pk_i, H_i)·ML(−g1, r_i·sig_i), identity for padding."""
    n_loc = pk_x.shape[0]
    rpk = g1.scalar_mul_bits(r_bits, (pk_x, pk_y))
    rsig = g2.scalar_mul_bits(r_bits, (sig_x, sig_y))
    neg_gy = fp.neg(G1_GEN_Y)
    px = jnp.concatenate(
        [rpk[0], jnp.broadcast_to(G1_GEN_X, (n_loc, N_LIMBS))], 0
    )
    py = jnp.concatenate(
        [rpk[1], jnp.broadcast_to(neg_gy, (n_loc, N_LIMBS))], 0
    )
    pz = jnp.concatenate([rpk[2], fp.one((n_loc,))], 0)
    qx = jnp.concatenate([msg_x, rsig[0]], 0)
    qy = jnp.concatenate([msg_y, rsig[1]], 0)
    qz = jnp.concatenate([fp2.one((n_loc,)), rsig[2]], 0)
    fs = miller_loop_proj_pq((px, py, pz), (qx, qy, qz))
    f = fp12.mul(fs[:n_loc], fs[n_loc:])
    return fp12.select(valid, f, fp12.one((n_loc,)))


def _sharded_bisect_verify(mesh_axis, *args):
    f_loc = _bisect_local(*args)
    # one Fp12 element per leaf per chip; the gathers reconstruct the
    # host's set order (linear chip k owns rows [k·n/ndev, (k+1)·n/ndev)
    # — ICI gathered first, then DCN, matching the DCN-major row
    # sharding; bisect is the audit path, so full leaves crossing DCN on
    # a two-level mesh is acceptable, unlike the hot grouped kernels)
    leaves = f_loc
    for name in reversed(_axes(mesh_axis)):
        leaves = lax.all_gather(leaves, name)
        leaves = leaves.reshape((-1,) + leaves.shape[2:])
    n = leaves.shape[0]

    # the product tree + root final exp are the latency-bound tail; run
    # them on chip 0 only and psum-broadcast every internal level so the
    # host bisection sees the same replicated `levels` the single-device
    # kernel returns (round-4 virtual-mesh lesson: replicated tails burn
    # every "chip"'s shared host core)
    def tree(_):
        levels = []
        g_lvl = leaves
        while g_lvl.shape[0] > 1:
            g_lvl = fp12.mul(g_lvl[0::2], g_lvl[1::2])
            levels.append(g_lvl)
        with named_scope("bls/final_exp_batch"):
            root_ok = fp12.is_one(
                final_exponentiation_one(levels[-1][0])
            ).astype(jnp.int32)
        return root_ok, tuple(levels)

    def idle(_):
        shapes = []
        m = n
        while m > 1:
            m //= 2
            shapes.append(m)
        return jnp.int32(0), tuple(
            jnp.zeros((m,) + leaves.shape[1:], leaves.dtype) for m in shapes
        )

    is_root = _mesh_index(mesh_axis) == 0
    root_int, upper = lax.cond(is_root, tree, idle, operand=None)
    root_int = lax.psum(root_int, _axes(mesh_axis))
    upper = tuple(lax.psum(u, _axes(mesh_axis)) for u in upper)
    return root_int > 0, (leaves,) + upper


def make_sharded_bisect_verifier(mesh: Mesh, axis: str | tuple = "dp"):
    """jit-compiled sharded bisection-tree kernel over `mesh`. The batch
    size must be a power of two (the single-device kernel pads internally;
    here the HOST must pad before sharding so slices stay uniform) and
    divisible by the mesh size. Returns (root_ok, levels) with the same
    level layout as `bisect_tree_kernel`."""
    spec = P(axis)

    @jax.jit
    def run(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, r_bits, valid):
        n = pk_x.shape[0]
        if n & (n - 1):
            raise ValueError(f"sharded bisect needs a power-of-two batch, got {n}")
        out_specs = (P(), tuple(P() for _ in range(n.bit_length())))
        fn = _shard_map(
            partial(_sharded_bisect_verify, axis),
            mesh=mesh,
            in_specs=(spec,) * 8,
            out_specs=out_specs,
        )
        return fn(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, r_bits, valid)

    return run


class ShardedBisectVerifier:
    """Host wrapper for the sharded bisection-verdict kernel: places
    padded per-set arrays lane-sharded onto the mesh. Batch size must be
    a power of two divisible by the mesh size."""

    def __init__(self, mesh: Mesh, axis: str | tuple = "dp"):
        self.mesh = mesh
        self.axis = axis
        self.ndev = mesh.devices.size
        self._run = make_sharded_bisect_verifier(mesh, axis)
        self._sharding = NamedSharding(mesh, P(axis))

    def submit(self, arrs, r_bits):
        put = lambda x: jax.device_put(x, self._sharding)
        root_ok, levels = self._run(
            put(arrs.pk_x), put(arrs.pk_y),
            put(arrs.msg_x), put(arrs.msg_y),
            put(arrs.sig_x), put(arrs.sig_y),
            put(r_bits), put(arrs.valid),
        )
        return root_ok, list(levels)


class ShardedBlsVerifier:
    """Host wrapper: places padded batches onto the mesh and runs the
    sharded kernel. Lane count = bucket per chip × mesh size."""

    def __init__(self, mesh: Mesh, axis: str | tuple = "dp", lanes_per_chip: int = 16):
        self.mesh = mesh
        self.axis = axis
        self.ndev = mesh.devices.size
        self.lanes = lanes_per_chip * self.ndev
        self._run = make_sharded_verifier(mesh, axis)
        self._sharding = NamedSharding(mesh, P(axis))

    def verify_arrays(self, arrs, r_bits):
        put = lambda x: jax.device_put(x, self._sharding)
        return bool(
            self._run(
                put(arrs.pk_x), put(arrs.pk_y),
                put(arrs.msg_x), put(arrs.msg_y),
                put(arrs.sig_x), put(arrs.sig_y),
                put(r_bits), put(arrs.valid),
            )
        )
