"""Mesh-sharded batch verification: the ICI-collective tier.

Where the reference scales BLS batch verification by chunking jobs across
`num_cpus` worker threads (`chain/bls/multithread/index.ts:153-166`,
`poolSize.ts`), this module shards ONE batch across all chips of a
`jax.sharding.Mesh` with `shard_map`:

- every chip runs scalar-muls + Miller loops for its slice of the batch
  (pure data parallelism over the 'dp' axis — zero communication),
- the G2 aggregated-signature sum and the Fp12 pair-product are combined
  with a single `all_gather` each over ICI (small payloads: one projective
  G2 point and one Fp12 element per chip), and the tiny cross-chip tail
  reduction plus the final exponentiation run replicated.

DCN enters only if the mesh itself spans hosts — the same code compiles
for a multi-host mesh because shard_map + all_gather are topology-agnostic
(SURVEY.md §2.5 TPU-native plan).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import fp, fp12
from ..ops.pairing import final_exponentiation, miller_loop_projective
from ..ops.points import G1_GEN_X, G1_GEN_Y, g1, g2
from .verifier import _fp12_product_tree, _g2_sum_tree

__all__ = ["make_sharded_verifier", "ShardedBlsVerifier"]


def _local_body(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, r_bits, valid):
    """Per-chip slice of the batch equation; returns (local Fp12 pair
    product, local partial G2 signature sum) — the two values that cross
    the ICI boundary."""
    n_loc = pk_x.shape[0]
    rpk = g1.scalar_mul_bits(r_bits, (pk_x, pk_y))
    rsig = g2.scalar_mul_bits(r_bits, (sig_x, sig_y))
    rsig = g2.select(valid, rsig, g2.infinity((n_loc,)))
    s_part = _g2_sum_tree(rsig)

    fs = miller_loop_projective(rpk, (msg_x, msg_y))
    fs = fp12.select(valid, fs, fp12.one((n_loc,)))
    return _fp12_product_tree(fs), s_part


def _sharded_verify(mesh_axis, pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, r_bits, valid):
    f_loc, s_part = _local_body(
        pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, r_bits, valid
    )
    # ICI: gather per-chip partials (1 Fp12 + 1 projective G2 point each)
    f_all = lax.all_gather(f_loc, mesh_axis)          # (ndev, 2,3,2,32)
    s_all = jax.tree.map(lambda x: lax.all_gather(x, mesh_axis), s_part)

    s = _g2_sum_tree(s_all)
    s_inf = g2.is_infinity(s)
    s_aff = g2.to_affine(s)

    # replicated tail: e(−g1, S) lane + cross-chip product + final exp
    f_tail = miller_loop_projective(
        (G1_GEN_X, fp.neg(G1_GEN_Y), fp.one(())),
        (s_aff[0], s_aff[1]),
    )
    f_tail = fp12.select(~s_inf, f_tail, fp12.one(()))
    f = fp12.mul(_fp12_product_tree(f_all), f_tail)
    return fp12.is_one(final_exponentiation(f))


def make_sharded_verifier(mesh: Mesh, axis: str = "dp"):
    """jit-compiled sharded batch-verify over `mesh`. Batch axis 0 of every
    input must be divisible by the mesh size."""
    spec = P(axis)

    @jax.jit
    def run(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, r_bits, valid):
        fn = jax.shard_map(
            partial(_sharded_verify, axis),
            mesh=mesh,
            in_specs=(spec,) * 8,
            out_specs=P(),
            check_vma=False,
        )
        return fn(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, r_bits, valid)

    return run


class ShardedBlsVerifier:
    """Host wrapper: places padded batches onto the mesh and runs the
    sharded kernel. Lane count = bucket per chip × mesh size."""

    def __init__(self, mesh: Mesh, axis: str = "dp", lanes_per_chip: int = 16):
        self.mesh = mesh
        self.axis = axis
        self.ndev = mesh.devices.size
        self.lanes = lanes_per_chip * self.ndev
        self._run = make_sharded_verifier(mesh, axis)
        self._sharding = NamedSharding(mesh, P(axis))

    def verify_arrays(self, arrs, r_bits):
        put = lambda x: jax.device_put(x, self._sharding)
        return bool(
            self._run(
                put(arrs.pk_x), put(arrs.pk_y),
                put(arrs.msg_x), put(arrs.msg_y),
                put(arrs.sig_x), put(arrs.sig_y),
                put(r_bits), put(arrs.valid),
            )
        )
