"""Epoch-scoped device-resident pubkey table (ISSUE 18).

The reference beacon node never decompresses a pubkey on the hot path:
its `EpochContext.index2pubkey` holds every active validator's
deserialized point for the whole epoch (PAPER.md §L2), because committees
are fixed per epoch — the steady-state attestation workload reads the
same pubkeys thousands of times between transitions. This module is the
device-tier analog, shaped like a resident weight table in a serving
stack:

- One `_EpochEntry` per (epoch, validator-index set): a packed
  (rows, 2·N_LIMBS) int32 limb array (x‖y per row, the `_pk_cache` row
  format) living BOTH as a host numpy mirror (serves the host marshal
  path with a memcpy instead of a C-tier sqrt) and, when `jax.device_put`
  succeeds, as a device array gathered through the compile-ledger-wrapped
  `epoch_table` kernel.
- LRU rotation over LODESTAR_TPU_EPOCH_TABLE_EPOCHS entries (default 2 —
  current + next, the reference's EpochContext pair): populating epoch
  N+1 evicts epoch N−1.
- Device OOM (or any device_put failure) downgrades the entry to
  host-only — lookups keep working off the numpy mirror, and the
  verifier's bounded FIFO `_pk_cache` remains the fallback for keys the
  table never saw (exited validators, deposits mid-epoch).

`TpuBlsVerifier._pk_rows` consults the table FIRST, then `_pk_cache`,
then pays the C-tier decompression; `node.py` populates at epoch
transition on a daemon thread; `tools/warmup.py` has a rung; hit/miss/
occupancy/eviction land in the `lodestar_bls_epoch_table_*` families and
`/debug/epoch_table`.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

N_LIMBS = 32
ROW_WIDTH = 2 * N_LIMBS  # packed x‖y limbs, the _pk_cache row format


class _EpochEntry:
    """One epoch's packed pubkey rows + key→row index."""

    __slots__ = ("epoch", "rows_np", "rows_dev", "index", "device_resident")

    def __init__(self, epoch: int, rows_np: np.ndarray, index: dict):
        self.epoch = int(epoch)
        self.rows_np = rows_np
        self.rows_dev = None
        self.index = index
        self.device_resident = False


def _gather_kernel(table, idx):
    """Device gather of packed pubkey rows — the epoch-table compile
    unit (`epoch_table` in the ledger and the warmup ladder)."""
    return table[idx]


class EpochPubkeyTable:
    """Device-resident decompressed G1 limbs keyed by epoch, LRU over a
    bounded number of epochs, host-mirror lookups for the marshal path.

    Thread-safe: gossip executors look rows up while the node's epoch-
    transition thread populates the next entry."""

    def __init__(self, epochs: int | None = None, max_rows: int | None = None,
                 observer=None):
        from ..observability.stages import default_pipeline
        from ..utils.env import env_int

        self.epochs = (
            env_int("LODESTAR_TPU_EPOCH_TABLE_EPOCHS")
            if epochs is None else int(epochs)
        )
        self.max_rows = (
            env_int("LODESTAR_TPU_EPOCH_TABLE_MAX_ROWS")
            if max_rows is None else int(max_rows)
        )
        self.observer = observer if observer is not None else default_pipeline()
        self._lock = threading.Lock()
        self._entries: OrderedDict[int, _EpochEntry] = OrderedDict()  # guarded-by: _lock
        self._evictions = 0  # guarded-by: _lock
        self._device_failures = 0  # guarded-by: _lock
        # jit + ledger-wrap lazily: constructing a table must not touch
        # the device (tests build them on import-time paths)
        self._gather = None
        self._gather_lock = threading.Lock()

    # -- population (epoch transition / warmup) -----------------------------

    def populate(self, epoch: int, items) -> int:
        """Install one epoch's entry from `items` — an iterable of
        (pubkey_bytes, packed_row) pairs, packed_row a (2·N_LIMBS,) int32
        array (the `_pk_cache` row format). Returns rows installed.

        Re-populating an existing epoch replaces it (validator set grew
        mid-epoch); rows beyond `max_rows` are dropped and counted as
        evictions. The device upload is best-effort: an OOM (or any
        device_put failure) leaves a host-only entry and ticks the
        failure counter — lookups degrade to the numpy mirror, never
        raise."""
        index: dict[bytes, int] = {}
        rows: list[np.ndarray] = []
        truncated = 0
        for key, row in items:
            if len(index) >= self.max_rows:
                truncated += 1
                continue
            if key in index:
                continue
            index[key] = len(rows)
            rows.append(row)
        rows_np = (
            np.stack(rows).astype(np.int32)
            if rows else np.zeros((0, ROW_WIDTH), np.int32)
        )
        entry = _EpochEntry(epoch, rows_np, index)
        entry.device_resident = self._try_device_put(entry)
        with self._lock:
            self._entries.pop(int(epoch), None)
            self._entries[int(epoch)] = entry
            if truncated:
                self._evictions += truncated
                self.observer.epoch_table_eviction(truncated)
            while len(self._entries) > max(1, self.epochs):
                old_epoch, old = self._entries.popitem(last=False)
                self._evictions += old.rows_np.shape[0]
                self.observer.epoch_table_eviction(old.rows_np.shape[0])
            self._refresh_occupancy_locked()
        return rows_np.shape[0]

    def _try_device_put(self, entry: _EpochEntry) -> bool:
        if entry.rows_np.shape[0] == 0:
            return False
        try:
            import jax

            entry.rows_dev = jax.device_put(entry.rows_np)
            return True
        except Exception:
            with self._lock:
                self._device_failures += 1
            entry.rows_dev = None
            return False

    # -- lookup (hot path) ---------------------------------------------------

    def lookup_rows(self, keys) -> list:
        """Packed (2·N_LIMBS,) rows (host mirror) for each pubkey-bytes
        key, None per miss. One counter tick per batch, not per key."""
        hits: list = [None] * len(keys)
        n_hit = 0
        with self._lock:
            entries = list(self._entries.values())
        for i, k in enumerate(keys):
            for e in reversed(entries):  # newest epoch first
                row = e.index.get(k)
                if row is not None:
                    hits[i] = e.rows_np[row]
                    n_hit += 1
                    break
        self.observer.epoch_table_event(True, n=n_hit)
        self.observer.epoch_table_event(False, n=len(keys) - n_hit)
        return hits

    def gather_device(self, epoch: int, idx) -> "object | None":
        """Device gather of rows `idx` from one epoch's device-resident
        array through the ledger-wrapped kernel; None when the entry is
        absent or host-only (callers fall back to the host mirror)."""
        with self._lock:
            entry = self._entries.get(int(epoch))
        if entry is None or not entry.device_resident:
            return None
        if self._gather is None:
            with self._gather_lock:
                if self._gather is None:
                    import jax

                    from ..observability.compile_ledger import ledger

                    self._gather = ledger().wrap(
                        jax.jit(_gather_kernel), "epoch_table"
                    )
        return self._gather(entry.rows_dev, np.asarray(idx, np.int32))

    # -- observability -------------------------------------------------------

    def _refresh_occupancy_locked(self) -> None:
        rows = sum(e.rows_np.shape[0] for e in self._entries.values())
        self.observer.epoch_table_occupancy(rows)

    def snapshot(self) -> dict:
        """State for `/debug/epoch_table` and the bench document."""
        with self._lock:
            entries = [
                {
                    "epoch": e.epoch,
                    "rows": int(e.rows_np.shape[0]),
                    "device_resident": bool(e.device_resident),
                }
                for e in self._entries.values()
            ]
            return {
                "epochs_retained": self.epochs,
                "max_rows": self.max_rows,
                "entries": entries,
                "total_rows": sum(en["rows"] for en in entries),
                "evictions": self._evictions,
                "device_put_failures": self._device_failures,
            }
