"""Test/chaos-drill utilities that ship in the production tree.

`faults` is the env- and endpoint-driven fault-injection seam at the
device verifier boundary — importable from production code (the hooks
are no-ops unless armed), so live chaos drills exercise exactly the
code paths the supervisor tests do.
"""
