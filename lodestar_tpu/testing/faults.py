"""Fault injection at the device BLS verifier boundary.

The supervisor's whole failure policy (`chain/supervisor.py`: deadlines,
retry, CPU fallback, circuit breaker) is only trustworthy if every
branch can be driven on demand — in unit tests AND against a live node
(chaos drill, docs/robustness.md runbook). This module is that seam:
`TpuBlsVerifier` calls the two hooks below on every device dispatch, and
they are no-ops (one attribute load + `is None` test) unless a fault
plan is armed via:

- the environment: ``LODESTAR_TPU_FAULTS="exception,latency:0.05"``
  (read at import, so a whole test process or drill node starts faulty);
- the metrics server: ``POST /debug/faults?set=deadline:30`` /
  ``?clear=1`` (live toggling mid-drill, no restart);
  ``?clear=1&reset_counters=1`` also zeroes the injection counters
  (drill teardown — otherwise they persist so a degraded run stays
  self-labelled).

Modes (comma-separated, each with an optional ``:param``):

    exception[:rate]   raise InjectedFault on a dispatch (rate = probability,
                       default 1.0) — the transient-XLA-error shape
                       (OOM, preemption, backend reset)
    latency[:seconds]  sleep before dispatching (default 0.05 s) — a slow
                       but live device; exercises deadline headroom
    deadline[:seconds] sleep long (default 30 s) — a wedged dispatch
                       (cold compile, hung transfer); the supervisor's
                       watchdog must abandon it
    chip[:index]       raise InjectedChipFault(index) on the next MESH
                       dispatch, then disarm (ONE-SHOT) — a sick chip;
                       the supervisor must evict it from the serving mesh
                       and keep serving on the survivors (the eviction is
                       visible in the lodestar_bls_mesh_* families)
    host[:rank]        raise InjectedHostFault(rank) on the next FLEET
                       (multi-host) dispatch, then disarm (ONE-SHOT) — a
                       sick host; the supervisor must evict it, the
                       FleetRouter rebalances its subnets, and serving
                       continues on the surviving hosts
                       (lodestar_bls_fleet_* families)
    flaky[:rate]       corrupt verdicts: True -> False with probability
                       `rate` (default 1.0). One-directional by design:
                       random hardware corruption yields a pairing
                       product that is NOT the identity, i.e. a spurious
                       False — it cannot forge the unique identity
                       element, so False -> True is not a physical
                       failure mode. The supervisor's negative-verdict
                       audit must rescue these on the CPU oracle.

Injections are counted per mode (`snapshot()`), and the counts ride the
bench document's `supervisor` section so a benchmark run that executed
with faults armed is self-labelling (tools/bench_compare.py skips it).
"""

from __future__ import annotations

import random
import threading
import time

from ..utils.env import env_str


class InjectedFault(RuntimeError):
    """Synthetic transient device failure (stands in for an XLA error)."""


class InjectedChipFault(InjectedFault):
    """Synthetic SINGLE-CHIP failure on a mesh dispatch: carries the sick
    chip's index so the supervisor's eviction policy can attribute it.
    Subclasses InjectedFault — handlers that only know the device-level
    failure shape still catch it (and fall back to the CPU oracle)."""

    def __init__(self, chip: int):
        super().__init__(f"injected chip fault (chip {chip})")
        self.chip = chip


class InjectedHostFault(InjectedFault):
    """Synthetic WHOLE-HOST failure on a two-level fleet dispatch:
    carries the sick host's rank so the supervisor's host-eviction
    policy can attribute it (the chip-fault shape one level up).
    Subclasses InjectedFault for the same reason InjectedChipFault
    does: tierless handlers still catch it."""

    def __init__(self, host: int):
        super().__init__(f"injected host fault (host {host})")
        self.host = host


_MODE_DEFAULTS = {
    "exception": 1.0,   # probability
    "latency": 0.05,    # seconds
    "deadline": 30.0,   # seconds
    "flaky": 1.0,       # probability
    "chip": 0.0,        # chip index (mesh dispatch; ONE-SHOT)
    "host": 0.0,        # host rank (fleet dispatch; ONE-SHOT)
}

_lock = threading.Lock()
_plan: dict[str, float] | None = None
_injected: dict[str, int] = {}
_rand = random.random
_sleep = time.sleep


def _parse(spec: str) -> dict[str, float]:
    plan: dict[str, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, param = part.partition(":")
        name = name.strip()
        if name not in _MODE_DEFAULTS:
            raise ValueError(
                f"unknown fault mode {name!r} (known: {sorted(_MODE_DEFAULTS)})"
            )
        if not param:
            value = _MODE_DEFAULTS[name]
        else:
            try:
                value = float(param)
            except ValueError:
                raise ValueError(
                    f"fault mode {name!r}: parameter {param!r} is not a "
                    f"number (expected e.g. '{name}:{_MODE_DEFAULTS[name]}')"
                ) from None
            if value < 0:
                raise ValueError(
                    f"fault mode {name!r}: parameter must be >= 0, "
                    f"got {param!r}"
                )
            if name in ("chip", "host") and not value.is_integer():
                raise ValueError(
                    f"fault mode {name!r}: parameter must be an integer "
                    f"{name} index, got {param!r}"
                )
        plan[name] = value
    return plan


def configure(spec: str | None) -> dict:
    """Arm the plan from a spec string (None/empty disarms); returns
    `snapshot()`. Raises ValueError on an unknown mode name."""
    global _plan
    plan = _parse(spec) if spec else None
    with _lock:
        _plan = plan or None
    return snapshot()


def clear(reset_counters: bool = False) -> None:
    """Disarm the plan. Injection counters persist by default — a bench
    round that ran ANY injection stays self-labelled as degraded even if
    the plan was cleared mid-run; tests pass `reset_counters=True` for
    isolation."""
    global _plan
    with _lock:
        _plan = None
        if reset_counters:
            _injected.clear()


def active() -> bool:
    return _plan is not None


def snapshot() -> dict:
    with _lock:
        return {
            "active": _plan is not None,
            "modes": dict(_plan) if _plan else {},
            "injected": dict(_injected),
        }


def _count(mode: str) -> None:
    with _lock:
        _injected[mode] = _injected.get(mode, 0) + 1


def on_device_dispatch(n_sets: int) -> None:
    """Called by `TpuBlsVerifier` before every device dispatch. May
    sleep (latency/deadline) and/or raise InjectedFault (exception)."""
    plan = _plan
    if plan is None:
        return
    if "latency" in plan:
        _count("latency")
        _sleep(plan["latency"])
    if "deadline" in plan:
        _count("deadline")
        _sleep(plan["deadline"])
    rate = plan.get("exception")
    if rate is not None and _rand() < rate:
        _count("exception")
        raise InjectedFault(
            f"injected device fault (batch of {n_sets} sets)"
        )


def on_mesh_dispatch(mesh_size: int) -> None:
    """Called by the mesh dispatcher before every SHARDED dispatch. The
    `chip[:index]` mode raises InjectedChipFault(chip) exactly ONCE and
    then disarms itself — a sick chip is a persistent condition handled
    by eviction, so after the supervisor evicts, subsequent dispatches on
    the surviving mesh must succeed (the mid-run-eviction drill of
    docs/robustness.md: serving continues on the remaining chips)."""
    plan = _plan
    if plan is None or "chip" not in plan:
        return
    with _lock:
        if _plan is None or "chip" not in _plan:
            return
        chip = int(_plan.pop("chip"))
        _injected["chip"] = _injected.get("chip", 0) + 1
    raise InjectedChipFault(chip)


def on_fleet_dispatch(hosts) -> None:
    """Called by the mesh dispatcher before every TWO-LEVEL (multi-host)
    dispatch. The `host[:rank]` mode raises InjectedHostFault(rank)
    exactly ONCE and then disarms itself — same one-shot contract as
    `chip`: a sick host is a persistent condition handled by eviction,
    so after the supervisor evicts it, dispatches on the surviving
    fleet must succeed (the host-eviction drill)."""
    plan = _plan
    if plan is None or "host" not in plan:
        return
    with _lock:
        if _plan is None or "host" not in _plan:
            return
        host = int(_plan.pop("host"))
        _injected["host"] = _injected.get("host", 0) + 1
    raise InjectedHostFault(host)


def flaky_verdict(verdict: bool) -> bool:
    """Corrupt one batch-level verdict (True -> False w.p. rate)."""
    plan = _plan
    if plan is None or "flaky" not in plan or not verdict:
        return verdict
    if _rand() < plan["flaky"]:
        _count("flaky")
        return False
    return verdict


def flaky_verdicts(verdicts: list[bool]) -> list[bool]:
    """Corrupt per-set verdicts independently (True -> False w.p. rate)."""
    plan = _plan
    if plan is None or "flaky" not in plan:
        return verdicts
    return [flaky_verdict(v) for v in verdicts]


# arm from the environment at import: a drill node (or a fault-injected
# test subprocess) starts with the plan already live
_env_spec = env_str("LODESTAR_TPU_FAULTS")
if _env_spec:
    configure(_env_spec)
