"""Generic directory-driven spec test runner.

Reference: `spec-test-util/src/single.ts` `describeDirectorySpecTest`:
walk `<suite>/<case>/` directories, load each file by extension
(`.yaml` → parsed object, `.ssz_snappy` → decompressed bytes), hand the
case's inputs to a test function, compare against expected outputs,
honour `meta.yaml` flags (e.g. bls_setting) and expected-failure cases
(no `post` file ⇒ the transition must raise).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable

import yaml

from .. import native


@dataclass
class SpecCase:
    name: str
    directory: str
    files: dict[str, Any] = field(default_factory=dict)  # stem → content
    meta: dict = field(default_factory=dict)

    def ssz(self, stem: str) -> bytes | None:
        value = self.files.get(stem)
        return value if isinstance(value, (bytes, bytearray)) else None

    def has(self, stem: str) -> bool:
        return stem in self.files


@dataclass
class SpecTestResult:
    total: int = 0
    passed: int = 0
    failures: list[tuple[str, str]] = field(default_factory=list)

    def ok(self) -> bool:
        return self.total > 0 and not self.failures


def load_case(case_dir: str) -> SpecCase:
    case = SpecCase(name=os.path.basename(case_dir), directory=case_dir)
    for fname in sorted(os.listdir(case_dir)):
        path = os.path.join(case_dir, fname)
        if not os.path.isfile(path):
            continue
        stem, ext = fname.rsplit(".", 1)[0], fname.split(".", 1)[1]
        with open(path, "rb") as f:
            raw = f.read()
        if ext == "ssz_snappy":
            case.files[stem] = native.snappy_uncompress(raw)
        elif ext == "ssz":
            case.files[stem] = raw
        elif ext in ("yaml", "yml"):
            parsed = yaml.safe_load(raw)
            if stem == "meta":
                case.meta = parsed or {}
            else:
                case.files[stem] = parsed
    return case


def iter_cases(suite_dir: str):
    for name in sorted(os.listdir(suite_dir)):
        case_dir = os.path.join(suite_dir, name)
        if os.path.isdir(case_dir):
            yield load_case(case_dir)


def run_directory_spec_test(
    suite_dir: str,
    test_fn: Callable[[SpecCase], None],
    should_skip: Callable[[SpecCase], bool] | None = None,
) -> SpecTestResult:
    """Run `test_fn` on every case under `suite_dir`.

    `test_fn` raises AssertionError (or any exception) to fail the case;
    expected-invalid semantics live inside the per-runner functions
    (reference: each preset runner decides what a missing `post` means)."""
    result = SpecTestResult()
    for case in iter_cases(suite_dir):
        if should_skip is not None and should_skip(case):
            continue
        result.total += 1
        try:
            test_fn(case)
            result.passed += 1
        except Exception as e:  # noqa: BLE001 — collect, don't abort the suite
            result.failures.append((case.name, f"{type(e).__name__}: {e}"))
    return result
