"""Spec-test harness (equivalent of `packages/spec-test-util`).

Reference: `spec-test-util/src/single.ts` (`describeDirectorySpecTest` —
a generic directory-driven test runner over the official
`ethereum/consensus-spec-tests` fixture layout) and `downloadTests`
(`src/downloadTests.ts:35`).

This environment has no network egress, so instead of a downloader the
harness ships a *generator* (`fixtures.py`) that writes suites in the
official directory layout (`<config>/<fork>/<runner>/<handler>/<suite>/
<case>/{pre,post,...}.ssz_snappy + meta.yaml`) from chain states built
by this implementation — the runner (`runner.py`) consumes that layout
exactly as it would consume the official tarballs, so dropping in real
vectors requires zero code changes.
"""

from .runner import SpecCase, SpecTestResult, run_directory_spec_test  # noqa: F401
from .presets import (  # noqa: F401
    run_epoch_processing_suite,
    run_operations_suite,
    run_sanity_blocks_suite,
    run_sanity_slots_suite,
    run_shuffling_suite,
)
