"""Spec-test fixture generator — the no-egress stand-in for downloadTests.

Reference: `spec-test-util/src/downloadTests.ts:35` fetches the official
`ethereum/consensus-spec-tests` tarballs. This environment has no
network, so this module *writes* suites in the identical directory
layout from states/blocks built by this implementation. The runner
consumes either source unchanged; official vectors are a drop-in.

Self-generated vectors cannot prove conformance against the canonical
spec by themselves — they prove serialization/layout plumbing, the
expected-invalid machinery, and regression-pin the transition: any
future change that alters a state root breaks the pinned `post` files.
"""

from __future__ import annotations

import os

import yaml

from .. import native
from ..bls import api as bls
from ..config.beacon_config import compute_signing_root
from ..params import DOMAIN_BEACON_PROPOSER, DOMAIN_RANDAO
from ..state_transition import interop_genesis_state, process_slots, state_transition
from ..state_transition.block import _epoch_signing_root
from ..state_transition.cache import CachedBeaconState


def _write(case_dir: str, name: str, data) -> None:
    os.makedirs(case_dir, exist_ok=True)
    path = os.path.join(case_dir, name)
    if name.endswith(".ssz_snappy"):
        with open(path, "wb") as f:
            f.write(native.snappy_compress(data))
    else:
        with open(path, "w") as f:
            yaml.safe_dump(data, f)


def _sign_block(config, types, block):
    domain = config.get_domain(DOMAIN_BEACON_PROPOSER, block.slot)
    sk = bls.interop_secret_key(int(block.proposer_index))
    sig = sk.sign(compute_signing_root(block.hash_tree_root(), domain))
    return types.SignedBeaconBlock(message=block, signature=sig.to_bytes())


def _produce_block(config, types, cached: CachedBeaconState, slot: int):
    """Minimal valid block on top of `cached` (advances a copy)."""
    trial = cached.copy()
    if slot > trial.state.slot:
        process_slots(trial, types, slot)
    proposer = trial.epoch_ctx.get_beacon_proposer(slot)
    epoch = slot // config.preset.SLOTS_PER_EPOCH
    reveal = bls.interop_secret_key(proposer).sign(
        _epoch_signing_root(epoch, config.get_domain(DOMAIN_RANDAO, slot))
    ).to_bytes()
    # after process_slots the cached header's state_root is filled in by
    # process_slot, so it hashes to the true parent block root
    parent_root = trial.state.latest_block_header.hash_tree_root()
    block = types.BeaconBlock(
        slot=slot,
        proposer_index=proposer,
        parent_root=parent_root,
        state_root=b"\x00" * 32,
        body=types.BeaconBlockBody(
            randao_reveal=reveal,
            eth1_data=trial.state.eth1_data.copy(),
            graffiti=b"\x00" * 32,
        ),
    )
    post = cached.copy()
    state_transition(
        post, types, types.SignedBeaconBlock(message=block),
        verify_state_root=False, verify_signatures=False,
    )
    block.state_root = post.state.hash_tree_root()
    return _sign_block(config, types, block), post


def generate_suite_tree(root: str, config, types, n_validators: int = 16) -> dict:
    """Write a mini consensus-spec-tests tree; returns suite paths.

    Layout: <root>/minimal/phase0/<runner>/<handler>/pyspec_tests/<case>/
    — exactly the official nesting the reference walks."""
    base = os.path.join(root, "minimal", "phase0")
    genesis = interop_genesis_state(config, types, n_validators, genesis_time=1_600_000_000)
    # signing domains need the genesis validators root — promote the fork
    # config into a full BeaconConfig once genesis exists
    from ..config.beacon_config import BeaconConfig

    if not hasattr(config, "get_domain"):
        config = BeaconConfig(
            config.chain, bytes(genesis.genesis_validators_root), config.preset
        )
    state_t = types.BeaconState
    paths = {}

    # --- sanity/blocks: one valid 2-block case, one invalid (bad state root)
    suite = os.path.join(base, "sanity", "blocks", "pyspec_tests")
    cached = CachedBeaconState(config, genesis.copy())
    b1, post1 = _produce_block(config, types, cached, 1)
    b2, post2 = _produce_block(config, types, post1, 2)
    case = os.path.join(suite, "blocks_ok")
    _write(case, "pre.ssz_snappy", state_t.serialize(genesis))
    _write(case, "blocks_0.ssz_snappy", b1.serialize())
    _write(case, "blocks_1.ssz_snappy", b2.serialize())
    post2.sync_flat()
    _write(case, "post.ssz_snappy", state_t.serialize(post2.state))
    _write(case, "meta.yaml", {"blocks_count": 2})

    bad = types.SignedBeaconBlock.deserialize(b1.serialize())
    bad.message.state_root = b"\xff" * 32
    case = os.path.join(suite, "invalid_state_root")
    _write(case, "pre.ssz_snappy", state_t.serialize(genesis))
    _write(case, "blocks_0.ssz_snappy", bad.serialize())
    _write(case, "meta.yaml", {"blocks_count": 1})
    paths["sanity/blocks"] = suite

    # --- sanity/slots
    suite = os.path.join(base, "sanity", "slots", "pyspec_tests")
    case = os.path.join(suite, "slots_1")
    adv = CachedBeaconState(config, genesis.copy())
    process_slots(adv, types, 1)
    adv.sync_flat()
    _write(case, "pre.ssz_snappy", state_t.serialize(genesis))
    _write(case, "slots.yaml", 1)
    _write(case, "post.ssz_snappy", state_t.serialize(adv.state))
    case = os.path.join(suite, "over_epoch_boundary")
    spe = config.preset.SLOTS_PER_EPOCH
    adv2 = CachedBeaconState(config, genesis.copy())
    process_slots(adv2, types, spe + 1)
    adv2.sync_flat()
    _write(case, "pre.ssz_snappy", state_t.serialize(genesis))
    _write(case, "slots.yaml", spe + 1)
    _write(case, "post.ssz_snappy", state_t.serialize(adv2.state))
    paths["sanity/slots"] = suite

    # --- operations/voluntary_exit: one invalid case (validator too young)
    suite = os.path.join(base, "operations", "voluntary_exit", "pyspec_tests")
    case = os.path.join(suite, "invalid_young_validator")
    exit_msg = types.SignedVoluntaryExit(
        message=types.VoluntaryExit(epoch=0, validator_index=0),
        signature=b"\x00" * 96,
    )
    _write(case, "pre.ssz_snappy", state_t.serialize(genesis))
    _write(case, "voluntary_exit.ssz_snappy", exit_msg.serialize())
    paths["operations/voluntary_exit"] = suite

    # --- epoch_processing/justification_and_finalization (pure boundary run)
    suite = os.path.join(
        base, "epoch_processing", "justification_and_finalization", "pyspec_tests"
    )
    case = os.path.join(suite, "genesis_noop")
    jf = CachedBeaconState(config, genesis.copy())
    from ..state_transition.epoch import process_justification_and_finalization

    process_justification_and_finalization(jf, types)
    jf.sync_flat()
    _write(case, "pre.ssz_snappy", state_t.serialize(genesis))
    _write(case, "post.ssz_snappy", state_t.serialize(jf.state))
    paths["epoch_processing/justification_and_finalization"] = suite

    # --- shuffling
    import numpy as np

    from ..state_transition import util as st_util

    suite = os.path.join(base, "shuffling", "core", "shuffle")
    seed = bytes(range(32))
    for count in (1, 5, 33):
        case = os.path.join(suite, f"shuffle_{count}")
        mapping = st_util.shuffle_list(
            np.arange(count, dtype=np.uint64), seed,
            config.preset.SHUFFLE_ROUND_COUNT,
        )
        _write(
            case, "mapping.yaml",
            {
                "seed": "0x" + seed.hex(),
                "count": count,
                "mapping": [int(x) for x in mapping],
            },
        )
    paths["shuffling"] = suite

    return paths
