"""Per-category spec-test runners over the directory layout.

Reference: `beacon-node/test/spec/presets/` — `operations.ts`,
`sanity.ts`, `epoch_processing.ts`, `shuffling.ts`: each maps a fixture
case's inputs onto one state-transition entry point and compares the
resulting state root (or expects a raise when `post` is absent).
"""

from __future__ import annotations

from ..state_transition import util as st_util
from ..state_transition.cache import CachedBeaconState
from .runner import SpecCase, SpecTestResult, run_directory_spec_test


def _run_case(case: SpecCase, config, state_type, mutate) -> None:
    pre = CachedBeaconState(config, state_type.deserialize(case.ssz("pre")))
    if case.has("post"):
        mutate(pre)
        pre.sync_flat()
        got = state_type.serialize(pre.state)
        assert got == case.ssz("post"), (
            f"post state mismatch (root {pre.state.hash_tree_root().hex()[:16]})"
        )
    else:
        try:
            mutate(pre)
        except Exception:
            return  # invalid input correctly rejected
        raise AssertionError("expected the transition to reject this case")


def run_operations_suite(
    suite_dir: str, config, types, operation: str, verify_signatures: bool = True
) -> SpecTestResult:
    """`operations/<operation>` — one op applied to `pre` (operations.ts)."""
    from ..state_transition import block as block_ops

    op_map = {
        "attestation": ("attestation", lambda c, op: block_ops.process_attestation(
            c, types, op, verify_signatures)),
        "attester_slashing": ("attester_slashing", lambda c, op:
            block_ops.process_attester_slashing(c, op, verify_signatures)),
        "proposer_slashing": ("proposer_slashing", lambda c, op:
            block_ops.process_proposer_slashing(c, op, verify_signatures)),
        "deposit": ("deposit", lambda c, op: block_ops.process_deposit(c, types, op)),
        "voluntary_exit": ("voluntary_exit", lambda c, op:
            block_ops.process_voluntary_exit(c, op, verify_signatures)),
        "block_header": ("block", lambda c, op:
            block_ops.process_block_header(c, types, op)),
    }
    input_stem, apply = op_map[operation]
    type_map = {
        "attestation": types.Attestation,
        "attester_slashing": types.AttesterSlashing,
        "proposer_slashing": types.ProposerSlashing,
        "deposit": types.Deposit,
        "voluntary_exit": types.SignedVoluntaryExit,
        "block": types.BeaconBlock,
    }
    op_type = type_map[input_stem]

    def test_fn(case: SpecCase) -> None:
        op = op_type.deserialize(case.ssz(input_stem))
        _run_case(case, config, types.BeaconState, lambda pre: apply(pre, op))

    return run_directory_spec_test(suite_dir, test_fn)


def run_sanity_blocks_suite(
    suite_dir: str, config, types, verify_signatures: bool = True
) -> SpecTestResult:
    """`sanity/blocks` — full state_transition over N signed blocks."""
    from ..state_transition import state_transition

    def test_fn(case: SpecCase) -> None:
        n_blocks = int(case.meta.get("blocks_count", 0))
        blocks = [
            types.SignedBeaconBlock.deserialize(case.ssz(f"blocks_{i}"))
            for i in range(n_blocks)
        ]

        def mutate(pre: CachedBeaconState) -> None:
            for signed in blocks:
                state_transition(
                    pre, types, signed,
                    verify_state_root=True,
                    verify_signatures=verify_signatures,
                )

        _run_case(case, config, types.BeaconState, mutate)

    return run_directory_spec_test(suite_dir, test_fn)


def run_sanity_slots_suite(suite_dir: str, config, types) -> SpecTestResult:
    """`sanity/slots` — process_slots by `slots.yaml` (sanity.ts)."""
    from ..state_transition import process_slots

    def test_fn(case: SpecCase) -> None:
        n_slots = int(case.files.get("slots", 0))

        def mutate(pre: CachedBeaconState) -> None:
            process_slots(pre, types, pre.state.slot + n_slots)

        _run_case(case, config, types.BeaconState, mutate)

    return run_directory_spec_test(suite_dir, test_fn)


def run_epoch_processing_suite(
    suite_dir: str, config, types, sub_transition: str
) -> SpecTestResult:
    """`epoch_processing/<sub>` — one epoch sub-transition applied at the
    epoch boundary (epoch_processing.ts)."""
    from ..state_transition import epoch as epoch_ops

    fn_map = {
        "justification_and_finalization":
            lambda c: epoch_ops.process_justification_and_finalization(c, types),
        "rewards_and_penalties": lambda c: epoch_ops.process_rewards_and_penalties(c),
        "registry_updates": lambda c: epoch_ops.process_registry_updates(c),
        "slashings": lambda c: epoch_ops.process_slashings(c),
        "effective_balance_updates":
            lambda c: epoch_ops.process_effective_balance_updates(c),
    }
    apply = fn_map[sub_transition]

    def test_fn(case: SpecCase) -> None:
        _run_case(case, config, types.BeaconState, apply)

    return run_directory_spec_test(suite_dir, test_fn)


def run_shuffling_suite(suite_dir: str, config) -> SpecTestResult:
    """`shuffling/core/shuffle` — mapping.yaml: {seed, count, mapping}
    against the swap-or-not shuffle (shuffling.ts)."""
    import numpy as np

    def test_fn(case: SpecCase) -> None:
        mapping = case.files["mapping"]
        seed = bytes.fromhex(str(mapping["seed"]).removeprefix("0x"))
        count = int(mapping["count"])
        expected = [int(x) for x in mapping["mapping"]]
        shuffled = st_util.shuffle_list(
            np.arange(count, dtype=np.uint64), seed,
            config.preset.SHUFFLE_ROUND_COUNT,
        )
        assert list(int(x) for x in shuffled) == expected, "shuffle mismatch"

    return run_directory_spec_test(suite_dir, test_fn)
