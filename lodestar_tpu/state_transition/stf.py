"""state_transition / process_slots — the pure transition driver.

Reference: `state-transition/src/stateTransition.ts:30,91` — same
decomposition: per-slot root caching, epoch processing at boundaries,
fork upgrades at their activation epochs (`slot/upgradeStateTo*`), block
processing, optional post-state root verification.
"""

from __future__ import annotations

from ..params import ForkName
from .block import BlockProcessingError, process_block
from .epoch import process_epoch


def fork_types(cached):
    """The SSZ namespace matching the state's current fork (the state may
    upgrade mid-process_slots, so types are resolved per use, not once)."""
    from ..types import get_types

    return get_types(cached.preset).by_fork[cached.fork]


_METRICS = None


def set_metrics(m) -> None:
    """Install the process-wide metric sink for STF timings (epoch
    transitions + incremental state hashing — reference lodestar.ts
    stfn.* epochTransition/hashTreeRoot timers)."""
    global _METRICS
    _METRICS = m


def _process_epoch_for_fork(cached, types) -> None:
    if cached.is_altair:
        from .altair import process_epoch_altair

        process_epoch_altair(cached, types)
    else:
        process_epoch(cached, types)


def _upgrade_at_epoch_boundary(cached) -> None:
    """Apply the scheduled fork upgrade when the state has just entered a
    fork's activation epoch (reference: stateTransition.ts processSlots
    upgrade hooks)."""
    from ..types import get_types

    cfg, preset = cached.config, cached.preset
    epoch = cached.current_epoch
    all_types = get_types(preset)
    if cached.fork == ForkName.phase0 and epoch == cfg.ALTAIR_FORK_EPOCH:
        from .altair import upgrade_state_to_altair

        cached.sync_flat()
        cached.reload_state(
            upgrade_state_to_altair(cfg, preset, cached.state, all_types.altair)
        )
    if cached.fork == ForkName.altair and epoch == cfg.BELLATRIX_FORK_EPOCH:
        from .bellatrix import upgrade_state_to_bellatrix

        cached.sync_flat()
        cached.reload_state(
            upgrade_state_to_bellatrix(cfg, preset, cached.state, all_types.bellatrix)
        )
    if cached.fork == ForkName.bellatrix and epoch == cfg.CAPELLA_FORK_EPOCH:
        from .capella import upgrade_state_to_capella

        cached.sync_flat()
        cached.reload_state(
            upgrade_state_to_capella(cfg, preset, cached.state, all_types.capella)
        )


def process_slot(cached, types) -> None:
    import time as _t

    state, p = cached.state, cached.preset
    _t0 = _t.monotonic()
    prev_state_root = cached.hash_tree_root()  # incremental (hasher.py)
    if _METRICS is not None:
        _METRICS.state_hash_seconds.observe(_t.monotonic() - _t0)
        vh = getattr(cached, "_hasher", None)
        vh = getattr(vh, "_validators", None)
        if vh is not None:
            _METRICS.state_hash_dirty_validators.observe(vh.last_dirty)
    state.state_roots[state.slot % p.SLOTS_PER_HISTORICAL_ROOT] = prev_state_root
    if state.latest_block_header.state_root == b"\x00" * 32:
        state.latest_block_header.state_root = prev_state_root
    state.block_roots[state.slot % p.SLOTS_PER_HISTORICAL_ROOT] = (
        state.latest_block_header.hash_tree_root()
    )


def process_slots(cached, types, slot: int) -> None:
    state, p = cached.state, cached.preset
    if slot <= state.slot:
        raise BlockProcessingError(
            f"process_slots target {slot} <= current {state.slot}"
        )
    while state.slot < slot:
        process_slot(cached, fork_types(cached))
        if (state.slot + 1) % p.SLOTS_PER_EPOCH == 0:
            import time as _t

            _t0 = _t.monotonic()
            _process_epoch_for_fork(cached, fork_types(cached))
            if _METRICS is not None:
                _METRICS.epoch_transition_seconds.observe(_t.monotonic() - _t0)
            cached.sync_flat()
            state.slot += 1
            cached.epoch_ctx.rotate_epoch(state, cached.flat)
            _upgrade_at_epoch_boundary(cached)
            state = cached.state  # upgrades swap the container
        else:
            state.slot += 1


def state_transition(
    cached,
    types,
    signed_block,
    verify_state_root: bool = True,
    verify_signatures: bool = True,
    execution_engine=None,
):
    """Apply a signed block. The block-signature (proposer) check itself is
    part of the caller's signature-set batch (reference keeps it out of
    stateTransition too — `verifySignatures` option)."""
    block = signed_block.message
    if block.slot > cached.state.slot:
        process_slots(cached, types, block.slot)
    process_block(
        cached, fork_types(cached), block, verify_signatures, execution_engine
    )
    cached.sync_flat()
    if verify_state_root:
        got = cached.hash_tree_root()
        if got != bytes(block.state_root):
            raise BlockProcessingError(
                f"state root mismatch: {got.hex()} != {bytes(block.state_root).hex()}"
            )
    return cached
