"""state_transition / process_slots — the pure transition driver.

Reference: `state-transition/src/stateTransition.ts:30,91` — same
decomposition: per-slot root caching, epoch processing at boundaries,
block processing, optional post-state root verification.
"""

from __future__ import annotations

from . import util
from .block import BlockProcessingError, process_block
from .epoch import process_epoch


def _process_epoch_for_fork(cached, types) -> None:
    if cached.is_altair:
        from .altair import process_epoch_altair

        process_epoch_altair(cached, types)
    else:
        process_epoch(cached, types)


def process_slot(cached, types) -> None:
    state, p = cached.state, cached.preset
    prev_state_root = state.hash_tree_root()
    state.state_roots[state.slot % p.SLOTS_PER_HISTORICAL_ROOT] = prev_state_root
    if state.latest_block_header.state_root == b"\x00" * 32:
        state.latest_block_header.state_root = prev_state_root
    state.block_roots[state.slot % p.SLOTS_PER_HISTORICAL_ROOT] = (
        state.latest_block_header.hash_tree_root()
    )


def process_slots(cached, types, slot: int) -> None:
    state, p = cached.state, cached.preset
    if slot <= state.slot:
        raise BlockProcessingError(
            f"process_slots target {slot} <= current {state.slot}"
        )
    while state.slot < slot:
        process_slot(cached, types)
        if (state.slot + 1) % p.SLOTS_PER_EPOCH == 0:
            _process_epoch_for_fork(cached, types)
            cached.sync_flat()
            state.slot += 1
            cached.epoch_ctx.rotate_epoch(state, cached.flat)
        else:
            state.slot += 1


def state_transition(
    cached,
    types,
    signed_block,
    verify_state_root: bool = True,
    verify_signatures: bool = True,
):
    """Apply a signed block. The block-signature (proposer) check itself is
    part of the caller's signature-set batch (reference keeps it out of
    stateTransition too — `verifySignatures` option)."""
    block = signed_block.message
    if block.slot > cached.state.slot:
        process_slots(cached, types, block.slot)
    process_block(cached, types, block, verify_signatures)
    cached.sync_flat()
    if verify_state_root:
        got = cached.state.hash_tree_root()
        if got != bytes(block.state_root):
            raise BlockProcessingError(
                f"state root mismatch: {got.hex()} != {bytes(block.state_root).hex()}"
            )
    return cached
