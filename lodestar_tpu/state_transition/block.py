"""phase0 block processing (spec process_block and operations).

Reference surface: `state-transition/src/block/` (processBlockHeader,
processRandao, processEth1Data, processOperations, processAttestation*,
processDeposit, processProposerSlashing, processAttesterSlashing,
processVoluntaryExit) — re-derived from the consensus spec, with committee
lookups served by the `EpochContext` and balances mutated on the flat
arrays.

Signature verification is SEPARATE from state mutation: `verify_signatures`
controls inline verification via the CPU oracle; the production path
extracts all sets with `signature_sets.get_block_signature_sets` and hands
them to the (TPU) batch verifier — the reference's
`verifyBlocksSignatures`/`getBlockSignatureSets` split
(`chain/blocks/verifyBlocksSignatures.ts:28`).
"""

from __future__ import annotations

from ..bls import api as bls
from ..config.beacon_config import compute_signing_root
from ..params import (
    DEPOSIT_CONTRACT_TREE_DEPTH,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_DEPOSIT,
    DOMAIN_RANDAO,
    DOMAIN_VOLUNTARY_EXIT,
    FAR_FUTURE_EPOCH,
)
from ..ssz.hashing import sha256
from . import util


class BlockProcessingError(ValueError):
    pass


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise BlockProcessingError(msg)


# --- balance mutators (flat arrays are the compute representation) ----------

def increase_balance(cached, index: int, delta: int) -> None:
    cached.flat.balances[index] = int(cached.flat.balances[index]) + int(delta)


def decrease_balance(cached, index: int, delta: int) -> None:
    b = int(cached.flat.balances[index])
    cached.flat.balances[index] = max(0, b - int(delta))


# --- validator mutators -----------------------------------------------------

def initiate_validator_exit(cached, index: int) -> None:
    """Spec initiate_validator_exit with churn-limited exit queue."""
    flat, config, p = cached.flat, cached.config, cached.preset
    if int(flat.exit_epoch[index]) != FAR_FUTURE_EPOCH:
        return
    import numpy as np

    exiting = flat.exit_epoch[flat.exit_epoch != np.uint64(FAR_FUTURE_EPOCH)]
    activation_exit = util.compute_activation_exit_epoch(
        cached.current_epoch, p.MAX_SEED_LOOKAHEAD
    )
    exit_queue_epoch = max(
        int(exiting.max()) if len(exiting) else 0, activation_exit
    )
    churn = get_validator_churn_limit(cached)
    if int((flat.exit_epoch == np.uint64(exit_queue_epoch)).sum()) >= churn:
        exit_queue_epoch += 1
    flat.exit_epoch[index] = exit_queue_epoch
    flat.withdrawable_epoch[index] = (
        exit_queue_epoch + config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
    )


def get_validator_churn_limit(cached) -> int:
    active = len(cached.epoch_ctx.current.active_indices)
    return max(
        cached.config.MIN_PER_EPOCH_CHURN_LIMIT,
        active // cached.config.CHURN_LIMIT_QUOTIENT,
    )


def min_slashing_penalty_quotient(cached) -> int:
    """Per-fork slashing penalty quotient (spec slash_validator variants)."""
    p = cached.preset
    if cached.is_execution:
        return p.MIN_SLASHING_PENALTY_QUOTIENT_BELLATRIX
    if cached.is_altair:
        return p.MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR
    return p.MIN_SLASHING_PENALTY_QUOTIENT


def slash_validator(cached, slashed_index: int, whistleblower_index: int | None = None):
    """Spec slash_validator (fork-aware penalty quotient + proposer cut)."""
    flat, p = cached.flat, cached.preset
    epoch = cached.current_epoch
    initiate_validator_exit(cached, slashed_index)
    flat.slashed[slashed_index] = True
    flat.withdrawable_epoch[slashed_index] = max(
        int(flat.withdrawable_epoch[slashed_index]),
        epoch + p.EPOCHS_PER_SLASHINGS_VECTOR,
    )
    eff = int(flat.effective_balance[slashed_index])
    state = cached.state
    idx = epoch % p.EPOCHS_PER_SLASHINGS_VECTOR
    state.slashings[idx] = state.slashings[idx] + eff
    decrease_balance(cached, slashed_index, eff // min_slashing_penalty_quotient(cached))

    proposer_index = cached.epoch_ctx.get_beacon_proposer(state.slot)
    whistleblower_reward = eff // p.WHISTLEBLOWER_REWARD_QUOTIENT
    if cached.is_altair:
        from ..params import PROPOSER_WEIGHT, WEIGHT_DENOMINATOR

        proposer_reward = whistleblower_reward * PROPOSER_WEIGHT // WEIGHT_DENOMINATOR
    else:
        proposer_reward = whistleblower_reward // p.PROPOSER_REWARD_QUOTIENT
    increase_balance(cached, proposer_index, proposer_reward)
    increase_balance(
        cached,
        whistleblower_index if whistleblower_index is not None else proposer_index,
        whistleblower_reward - proposer_reward,
    )


# --- block header / randao / eth1 ------------------------------------------

def process_block_header(cached, types, block) -> None:
    state = cached.state
    _require(block.slot == state.slot, "header slot mismatch")
    _require(
        block.slot > state.latest_block_header.slot, "header slot not newer"
    )
    proposer = cached.epoch_ctx.get_beacon_proposer(block.slot)
    _require(block.proposer_index == proposer, "wrong proposer index")
    _require(
        block.parent_root == state.latest_block_header.hash_tree_root(),
        "parent root mismatch",
    )
    _require(not bool(cached.flat.slashed[proposer]), "proposer slashed")
    state.latest_block_header = types.BeaconBlockHeader(
        slot=block.slot,
        proposer_index=block.proposer_index,
        parent_root=block.parent_root,
        state_root=b"\x00" * 32,
        body_root=block.body.hash_tree_root(),
    )


def process_randao(cached, body, verify_signatures: bool = True) -> None:
    state, p = cached.state, cached.preset
    epoch = cached.current_epoch
    if verify_signatures:
        proposer = cached.epoch_ctx.get_beacon_proposer(state.slot)
        domain = cached.config.get_domain(DOMAIN_RANDAO, state.slot)
        root = _epoch_signing_root(epoch, domain)
        pk = bls.PublicKey.from_bytes(bytes(cached.flat.pubkeys[proposer]))
        sig = bls.Signature.from_bytes(bytes(body.randao_reveal))
        _require(bls.verify(pk, root, sig), "invalid randao reveal")
    mix = util.get_randao_mix(state, epoch, p.EPOCHS_PER_HISTORICAL_VECTOR)
    new_mix = bytes(a ^ b for a, b in zip(mix, sha256(bytes(body.randao_reveal))))
    state.randao_mixes[epoch % p.EPOCHS_PER_HISTORICAL_VECTOR] = new_mix


def _epoch_signing_root(epoch: int, domain: bytes) -> bytes:
    from ..ssz import uint64

    return compute_signing_root(uint64.hash_tree_root(epoch), domain)


def process_eth1_data(cached, types, body) -> None:
    state, p = cached.state, cached.preset
    state.eth1_data_votes.append(body.eth1_data.copy())
    period_slots = p.EPOCHS_PER_ETH1_VOTING_PERIOD * p.SLOTS_PER_EPOCH
    votes = sum(1 for v in state.eth1_data_votes if v == body.eth1_data)
    if votes * 2 > period_slots:
        state.eth1_data = body.eth1_data.copy()


# --- operations -------------------------------------------------------------

def is_slashable_validator(flat, index: int, epoch: int) -> bool:
    return (
        not bool(flat.slashed[index])
        and int(flat.activation_epoch[index]) <= epoch
        and epoch < int(flat.withdrawable_epoch[index])
    )


def is_slashable_attestation_data(d1, d2) -> bool:
    # double vote or surround vote
    return (
        d1 != d2 and d1.target.epoch == d2.target.epoch
    ) or (
        d1.source.epoch < d2.source.epoch and d2.target.epoch < d1.target.epoch
    )


def is_valid_indexed_attestation(
    cached, indexed, verify_signature: bool = True
) -> bool:
    indices = list(indexed.attesting_indices)
    if not indices or indices != sorted(set(indices)):
        return False
    if any(i >= len(cached.flat) for i in indices):
        return False
    if not verify_signature:
        return True
    domain = cached.config.get_domain(
        DOMAIN_BEACON_ATTESTER,
        util.compute_start_slot_at_epoch(
            indexed.data.target.epoch, cached.preset.SLOTS_PER_EPOCH
        ),
        indexed.data.target.epoch,
    )
    root = compute_signing_root(indexed.data.hash_tree_root(), domain)
    pks = [
        bls.PublicKey.from_bytes(bytes(cached.flat.pubkeys[i])) for i in indices
    ]
    sig = bls.Signature.from_bytes(bytes(indexed.signature), validate=False)
    return bls.fast_aggregate_verify(pks, root, sig)


def get_attesting_indices(cached, data, aggregation_bits) -> list[int]:
    committee = cached.epoch_ctx.get_beacon_committee(data.slot, data.index)
    _require(
        len(aggregation_bits) == len(committee), "aggregation bits length mismatch"
    )
    return sorted(int(committee[i]) for i, bit in enumerate(aggregation_bits) if bit)


def process_proposer_slashing(cached, op, verify_signatures: bool = True) -> None:
    h1, h2 = op.signed_header_1.message, op.signed_header_2.message
    _require(h1.slot == h2.slot, "slashing headers different slots")
    _require(h1.proposer_index == h2.proposer_index, "different proposers")
    _require(h1 != h2, "headers identical")
    idx = h1.proposer_index
    _require(idx < len(cached.flat), "unknown proposer")
    _require(
        is_slashable_validator(cached.flat, idx, cached.current_epoch),
        "proposer not slashable",
    )
    if verify_signatures:
        for signed in (op.signed_header_1, op.signed_header_2):
            domain = cached.config.get_domain(
                DOMAIN_BEACON_PROPOSER, signed.message.slot
            )
            root = compute_signing_root(signed.message.hash_tree_root(), domain)
            pk = bls.PublicKey.from_bytes(bytes(cached.flat.pubkeys[idx]))
            _require(
                bls.verify(pk, root, bls.Signature.from_bytes(bytes(signed.signature))),
                "bad proposer slashing signature",
            )
    slash_validator(cached, idx)


def process_attester_slashing(cached, op, verify_signatures: bool = True) -> None:
    a1, a2 = op.attestation_1, op.attestation_2
    _require(
        is_slashable_attestation_data(a1.data, a2.data), "not slashable pair"
    )
    _require(
        is_valid_indexed_attestation(cached, a1, verify_signatures),
        "attestation_1 invalid",
    )
    _require(
        is_valid_indexed_attestation(cached, a2, verify_signatures),
        "attestation_2 invalid",
    )
    slashed_any = False
    common = set(a1.attesting_indices) & set(a2.attesting_indices)
    for idx in sorted(common):
        if is_slashable_validator(cached.flat, idx, cached.current_epoch):
            slash_validator(cached, idx)
            slashed_any = True
    _require(slashed_any, "no validator slashed")


def process_attestation(cached, types, attestation, verify_signatures: bool = True):
    state, p = cached.state, cached.preset
    data = attestation.data
    _require(
        data.target.epoch in (cached.previous_epoch, cached.current_epoch),
        "target epoch out of range",
    )
    _require(
        data.target.epoch
        == util.compute_epoch_at_slot(data.slot, p.SLOTS_PER_EPOCH),
        "target epoch != slot epoch",
    )
    _require(
        data.slot + p.MIN_ATTESTATION_INCLUSION_DELAY <= state.slot,
        "attestation too new",
    )
    _require(state.slot <= data.slot + p.SLOTS_PER_EPOCH, "attestation too old")
    _require(
        data.index < cached.epoch_ctx.get_committee_count_per_slot(data.target.epoch),
        "committee index out of range",
    )
    committee = cached.epoch_ctx.get_beacon_committee(data.slot, data.index)
    _require(
        len(attestation.aggregation_bits) == len(committee),
        "bits/committee length mismatch",
    )
    pending = types.PendingAttestation(
        aggregation_bits=list(attestation.aggregation_bits),
        data=data.copy(),
        inclusion_delay=state.slot - data.slot,
        proposer_index=cached.epoch_ctx.get_beacon_proposer(state.slot),
    )
    if data.target.epoch == cached.current_epoch:
        _require(
            data.source == state.current_justified_checkpoint,
            "wrong source (current)",
        )
        state.current_epoch_attestations.append(pending)
    else:
        _require(
            data.source == state.previous_justified_checkpoint,
            "wrong source (previous)",
        )
        state.previous_epoch_attestations.append(pending)
    if verify_signatures:
        indexed = types.IndexedAttestation(
            attesting_indices=get_attesting_indices(
                cached, data, attestation.aggregation_bits
            ),
            data=data.copy(),
            signature=bytes(attestation.signature),
        )
        _require(
            is_valid_indexed_attestation(cached, indexed, True),
            "bad attestation signature",
        )


def apply_deposit_data(config, types, state, data) -> None:
    """Add new validator or top-up (spec process_deposit tail). Standalone
    (no cache): also used at genesis. Deposit signatures are verified here
    for NEW validators only (spec: invalid-sig deposits are skipped, not
    failed)."""
    p = config.preset
    pubkey = bytes(data.pubkey)
    pubkeys = [bytes(v.pubkey) for v in state.validators]
    if pubkey not in pubkeys:
        from ..config.beacon_config import compute_domain

        domain = compute_domain(DOMAIN_DEPOSIT, config.GENESIS_FORK_VERSION, b"\x00" * 32)
        msg = types.DepositMessage(
            pubkey=pubkey,
            withdrawal_credentials=bytes(data.withdrawal_credentials),
            amount=data.amount,
        )
        root = compute_signing_root(msg.hash_tree_root(), domain)
        try:
            pk = bls.PublicKey.from_bytes(pubkey)
            sig = bls.Signature.from_bytes(bytes(data.signature))
        except (bls.BlsError, ValueError):
            return
        if not bls.verify(pk, root, sig):
            return  # skip, don't fail
        amount = data.amount
        eff = min(
            amount - amount % p.EFFECTIVE_BALANCE_INCREMENT, p.MAX_EFFECTIVE_BALANCE
        )
        state.validators.append(
            types.Validator(
                pubkey=pubkey,
                withdrawal_credentials=bytes(data.withdrawal_credentials),
                effective_balance=eff,
                slashed=False,
                activation_eligibility_epoch=FAR_FUTURE_EPOCH,
                activation_epoch=FAR_FUTURE_EPOCH,
                exit_epoch=FAR_FUTURE_EPOCH,
                withdrawable_epoch=FAR_FUTURE_EPOCH,
            )
        )
        state.balances.append(amount)
    else:
        idx = pubkeys.index(pubkey)
        state.balances[idx] = state.balances[idx] + data.amount


def process_deposit(cached, types, deposit) -> None:
    state = cached.state
    _require(
        util.is_valid_merkle_branch(
            deposit.data.hash_tree_root(),
            list(deposit.proof),
            DEPOSIT_CONTRACT_TREE_DEPTH + 1,
            state.eth1_deposit_index,
            state.eth1_data.deposit_root,
        ),
        "invalid deposit proof",
    )
    state.eth1_deposit_index += 1
    n_before = len(state.validators)
    apply_deposit_data(cached.config, types, state, deposit.data)
    if len(state.validators) > n_before:
        v = state.validators[-1]
        cached.flat.append(v, state.balances[-1])
        cached.epoch_ctx.sync_pubkeys(cached.flat)
    else:
        # top-up: refresh the flat balance column for that validator
        pubkey = bytes(deposit.data.pubkey)
        idx = cached.epoch_ctx.pubkey_to_index[pubkey]
        cached.flat.balances[idx] = state.balances[idx]


def process_voluntary_exit(cached, signed_exit, verify_signatures: bool = True):
    exit_msg = signed_exit.message
    flat = cached.flat
    idx = exit_msg.validator_index
    _require(idx < len(flat), "unknown validator")
    _require(
        bool(
            util.active_mask(
                flat.activation_epoch[idx : idx + 1],
                flat.exit_epoch[idx : idx + 1],
                cached.current_epoch,
            )[0]
        ),
        "validator not active",
    )
    _require(
        int(flat.exit_epoch[idx]) == FAR_FUTURE_EPOCH, "exit already initiated"
    )
    _require(cached.current_epoch >= exit_msg.epoch, "exit epoch in future")
    _require(
        cached.current_epoch
        >= int(flat.activation_epoch[idx]) + cached.config.SHARD_COMMITTEE_PERIOD,
        "validator too young to exit",
    )
    if verify_signatures:
        domain = cached.config.get_domain(
            DOMAIN_VOLUNTARY_EXIT,
            util.compute_start_slot_at_epoch(
                exit_msg.epoch, cached.preset.SLOTS_PER_EPOCH
            ),
            exit_msg.epoch,
        )
        root = compute_signing_root(exit_msg.hash_tree_root(), domain)
        pk = bls.PublicKey.from_bytes(bytes(flat.pubkeys[idx]))
        _require(
            bls.verify(
                pk, root, bls.Signature.from_bytes(bytes(signed_exit.signature))
            ),
            "bad exit signature",
        )
    initiate_validator_exit(cached, idx)


def process_operations(cached, types, body, verify_signatures: bool = True) -> None:
    state, p = cached.state, cached.preset
    expected_deposits = min(
        p.MAX_DEPOSITS,
        state.eth1_data.deposit_count - state.eth1_deposit_index,
    )
    _require(
        len(body.deposits) == expected_deposits, "wrong number of deposits"
    )
    for op in body.proposer_slashings:
        process_proposer_slashing(cached, op, verify_signatures)
    for op in body.attester_slashings:
        process_attester_slashing(cached, op, verify_signatures)
    if cached.is_altair:
        from .altair import process_attestation_altair

        for op in body.attestations:
            process_attestation_altair(cached, types, op, verify_signatures)
    else:
        for op in body.attestations:
            process_attestation(cached, types, op, verify_signatures)
    for op in body.deposits:
        process_deposit(cached, types, op)
    for op in body.voluntary_exits:
        process_voluntary_exit(cached, op, verify_signatures)
    if cached.is_capella:
        from .capella import process_bls_to_execution_change

        for op in body.bls_to_execution_changes:
            process_bls_to_execution_change(cached, op, verify_signatures)


def process_block(
    cached, types, block, verify_signatures: bool = True, execution_engine=None
) -> None:
    process_block_header(cached, types, block)
    if cached.is_execution:
        from .bellatrix import is_execution_enabled, process_execution_payload

        if is_execution_enabled(cached.state, block.body):
            if cached.is_capella:
                from .capella import process_withdrawals

                process_withdrawals(cached, types, block.body.execution_payload)
            process_execution_payload(cached, types, block.body, execution_engine)
    process_randao(cached, block.body, verify_signatures)
    process_eth1_data(cached, types, block.body)
    process_operations(cached, types, block.body, verify_signatures)
    if cached.is_altair and hasattr(block.body, "sync_aggregate"):
        from .altair import process_sync_aggregate

        process_sync_aggregate(cached, block.body.sync_aggregate, verify_signatures)
