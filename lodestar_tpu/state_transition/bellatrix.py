"""bellatrix (merge) state transition: execution payloads.

Reference surface: `state-transition/src/block/processExecutionPayload.ts`,
`util/execution.ts` (isMergeTransitionComplete / isExecutionEnabled),
`slot/upgradeStateToBellatrix.ts` — re-derived from the bellatrix consensus
spec. Payload *execution* validity (engine_newPayload) is deliberately NOT
part of the pure transition — the chain pipeline verifies it in parallel
(reference: `chain/blocks/verifyBlocksExecutionPayloads.ts`); here we do the
consensus-side checks and header update only, with an optional engine hook
for spec-test parity.
"""

from __future__ import annotations

from . import util
from .block import _require


def is_merge_transition_complete(state) -> bool:
    """True once the state carries a non-default execution payload header
    (spec is_merge_transition_complete)."""
    header = state.latest_execution_payload_header
    return header.hash_tree_root() != type(header)().hash_tree_root()


def has_execution_payload(body) -> bool:
    """True when the body carries a non-default execution payload."""
    payload = body.execution_payload
    return payload.hash_tree_root() != type(payload)().hash_tree_root()


def is_merge_transition_block(state, body) -> bool:
    return not is_merge_transition_complete(state) and has_execution_payload(body)


def is_execution_enabled(state, body) -> bool:
    return is_merge_transition_block(state, body) or is_merge_transition_complete(
        state
    )


def get_randao_mix(state, epoch: int, preset) -> bytes:
    return bytes(state.randao_mixes[epoch % preset.EPOCHS_PER_HISTORICAL_VECTOR])


def process_execution_payload(cached, types, body, execution_engine=None) -> None:
    """Spec process_execution_payload: parent-hash continuity, randao,
    timestamp, (optional) engine notification, header update. Capella states
    additionally carry the withdrawals root in the header."""
    state, p = cached.state, cached.preset
    payload = body.execution_payload
    if is_merge_transition_complete(state):
        _require(
            bytes(payload.parent_hash)
            == bytes(state.latest_execution_payload_header.block_hash),
            "payload parent hash mismatch",
        )
    _require(
        bytes(payload.prev_randao)
        == get_randao_mix(state, cached.current_epoch, p),
        "payload prev_randao mismatch",
    )
    _require(
        payload.timestamp == compute_timestamp_at_slot(cached.config, state),
        "payload timestamp mismatch",
    )
    if execution_engine is not None:
        status = execution_engine.notify_new_payload(payload)
        # engines return ExecutePayloadStatus (a non-empty str enum — always
        # truthy) or a plain bool; only an explicit VALID/True passes
        _require(
            status is True or getattr(status, "value", status) == "VALID",
            f"execution engine rejected payload: {status}",
        )

    header_fields = dict(
        parent_hash=bytes(payload.parent_hash),
        fee_recipient=bytes(payload.fee_recipient),
        state_root=bytes(payload.state_root),
        receipts_root=bytes(payload.receipts_root),
        logs_bloom=bytes(payload.logs_bloom),
        prev_randao=bytes(payload.prev_randao),
        block_number=payload.block_number,
        gas_limit=payload.gas_limit,
        gas_used=payload.gas_used,
        timestamp=payload.timestamp,
        extra_data=bytes(payload.extra_data),
        base_fee_per_gas=payload.base_fee_per_gas,
        block_hash=bytes(payload.block_hash),
        transactions_root=_field_root(payload, "transactions"),
    )
    if cached.is_capella:
        header_fields["withdrawals_root"] = _field_root(payload, "withdrawals")
    state.latest_execution_payload_header = types.ExecutionPayloadHeader(
        **header_fields
    )


def _field_root(container, field: str) -> bytes:
    """hash_tree_root of one list/vector-typed container field (values are
    plain lists; the field's SSZ type carries the merkleization)."""
    for name, typ in container.fields:
        if name == field:
            return typ.hash_tree_root(getattr(container, field))
    raise KeyError(field)


def compute_timestamp_at_slot(config, state) -> int:
    slots_since_genesis = state.slot - 0
    return state.genesis_time + slots_since_genesis * config.SECONDS_PER_SLOT


# --- fork upgrade ------------------------------------------------------------

def upgrade_state_to_bellatrix(config, preset, pre, bellatrix_types):
    """Spec upgrade_to_bellatrix (reference: slot/upgradeStateToBellatrix):
    carry altair fields, default (pre-merge) execution payload header, bump
    fork version."""
    pre = pre.copy()
    post = bellatrix_types.BeaconState()
    for name, _ in post.fields:
        if name in ("latest_execution_payload_header", "fork"):
            continue
        setattr(post, name, getattr(pre, name))
    post.latest_execution_payload_header = bellatrix_types.ExecutionPayloadHeader()
    post.fork = type(pre.fork)(
        previous_version=bytes(pre.fork.current_version),
        current_version=config.BELLATRIX_FORK_VERSION,
        epoch=util.compute_epoch_at_slot(pre.slot, preset.SLOTS_PER_EPOCH),
    )
    return post
