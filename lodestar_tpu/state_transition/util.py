"""Spec helper functions: epoch math, seeds, shuffling, committees.

Equivalent surface to the reference's `state-transition/src/util/`
(epoch.ts, seed.ts, shuffle.ts, aggregator.ts…), with the shuffle
implemented as a whole-permutation vectorized pass (numpy) rather than a
per-index loop: one round touches every position at once — the same
swap-or-not network the spec defines, evaluated SIMD-style.
"""

from __future__ import annotations

import numpy as np

from ..ssz.hashing import sha256

UINT64_MAX = 2**64 - 1


def integer_squareroot(n: int) -> int:
    """Largest x with x² <= n (Newton iteration on exact ints — the spec's
    integer_squareroot; floats would break determinism)."""
    x = n
    y = (x + 1) // 2
    while y < x:
        x = y
        y = (x + n // x) // 2
    return x


# --- epoch / slot math ------------------------------------------------------

def compute_epoch_at_slot(slot: int, slots_per_epoch: int) -> int:
    return slot // slots_per_epoch

def compute_start_slot_at_epoch(epoch: int, slots_per_epoch: int) -> int:
    return epoch * slots_per_epoch

def compute_activation_exit_epoch(epoch: int, max_seed_lookahead: int) -> int:
    return epoch + 1 + max_seed_lookahead


# --- validator predicates (scalar + vectorized forms) -----------------------

def is_active_validator(v, epoch: int) -> bool:
    return v.activation_epoch <= epoch < v.exit_epoch


def active_mask(activation_epoch: np.ndarray, exit_epoch: np.ndarray, epoch: int):
    return (activation_epoch <= epoch) & (epoch < exit_epoch)


# --- randao / seeds ---------------------------------------------------------

def get_randao_mix(state, epoch: int, epochs_per_historical_vector: int) -> bytes:
    return state.randao_mixes[epoch % epochs_per_historical_vector]


def get_seed(state, epoch: int, domain_type: bytes, preset) -> bytes:
    """hash(domain_type + epoch + mix at epoch − MIN_SEED_LOOKAHEAD − 1)."""
    mix = get_randao_mix(
        state,
        epoch + preset.EPOCHS_PER_HISTORICAL_VECTOR - preset.MIN_SEED_LOOKAHEAD - 1,
        preset.EPOCHS_PER_HISTORICAL_VECTOR,
    )
    return sha256(domain_type + epoch.to_bytes(8, "little") + mix)


# --- swap-or-not shuffle ----------------------------------------------------

def compute_shuffled_index(index: int, count: int, seed: bytes, rounds: int) -> int:
    """Single-index forward shuffle (spec compute_shuffled_index): used for
    proposer sampling where only a few indices are needed."""
    assert index < count
    for r in range(rounds):
        pivot = int.from_bytes(sha256(seed + bytes([r]))[:8], "little") % count
        flip = (pivot + count - index) % count
        position = max(index, flip)
        source = sha256(seed + bytes([r]) + (position // 256).to_bytes(4, "little"))
        byte = source[(position % 256) // 8]
        if (byte >> (position % 8)) & 1:
            index = flip
    return index


def shuffle_list(indices: np.ndarray, seed: bytes, rounds: int) -> np.ndarray:
    """Whole-list shuffle L with L[i] = indices[π(i)] where π is the spec's
    `compute_shuffled_index` (vectorized; one pass per round over a boolean
    flip field derived from the round hashes).

    Each round's swap network σ_r is an involution and bit(i)=bit(flip(i)),
    so composing array-gathers in REVERSE round order yields
    indices ∘ σ_{R-1} ∘ … ∘ σ_0 = indices ∘ π — the same permutation as the
    per-index forward walk. (The reference keeps an optimized list form too:
    `state-transition/src/util/shuffle.ts`.)
    """
    n = len(indices)
    if n == 0:
        return indices.copy()
    out = indices.copy()
    pos = np.arange(n, dtype=np.int64)
    for r in range(rounds - 1, -1, -1):
        out = _shuffle_round(out, pos, seed, r, n)
    return out


def unshuffle_list(indices: np.ndarray, seed: bytes, rounds: int) -> np.ndarray:
    """Inverse of `shuffle_list` (rounds walked forward)."""
    n = len(indices)
    if n == 0:
        return indices.copy()
    out = indices.copy()
    pos = np.arange(n, dtype=np.int64)
    for r in range(rounds):
        out = _shuffle_round(out, pos, seed, r, n)
    return out


def _shuffle_round(out: np.ndarray, pos: np.ndarray, seed: bytes, r: int, n: int):
    pivot = int.from_bytes(sha256(seed + bytes([r]))[:8], "little") % n
    flip = (pivot + n - pos) % n
    position = np.maximum(pos, flip)
    # bit source: one 32-byte hash covers 256 positions
    n_blocks = int(position.max()) // 256 + 1
    prefix = seed + bytes([r])
    blocks = np.frombuffer(
        b"".join(
            sha256(prefix + blk.to_bytes(4, "little")) for blk in range(n_blocks)
        ),
        dtype=np.uint8,
    )
    byte_vals = blocks[(position // 8)]
    bits = (byte_vals >> (position % 8).astype(np.uint8)) & 1
    swap = bits.astype(bool)
    result = out.copy()
    result[swap] = out[flip[swap]]
    return result


# --- committees -------------------------------------------------------------

def get_committee_count_per_slot(active_count: int, preset) -> int:
    return max(
        1,
        min(
            preset.MAX_COMMITTEES_PER_SLOT,
            active_count // preset.SLOTS_PER_EPOCH // preset.TARGET_COMMITTEE_SIZE,
        ),
    )


def compute_committee_slice(
    shuffled: np.ndarray, slot_in_epoch: int, committee_index: int,
    committees_per_slot: int, slots_per_epoch: int,
) -> np.ndarray:
    """Committee = contiguous slice of the epoch's shuffled active set."""
    n = len(shuffled)
    committees = committees_per_slot * slots_per_epoch
    i = slot_in_epoch * committees_per_slot + committee_index
    start = n * i // committees
    end = n * (i + 1) // committees
    return shuffled[start:end]


def compute_proposer_index(
    effective_balances: np.ndarray, active_indices: np.ndarray, seed: bytes,
    preset,
) -> int:
    """Effective-balance-weighted sampling over the shuffled candidate
    stream (spec compute_proposer_index)."""
    total = len(active_indices)
    assert total > 0
    max_byte = 255
    i = 0
    while True:
        shuffled_i = compute_shuffled_index(
            i % total, total, seed, preset.SHUFFLE_ROUND_COUNT
        )
        candidate = int(active_indices[shuffled_i])
        rand = sha256(seed + (i // 32).to_bytes(8, "little"))[i % 32]
        eff = int(effective_balances[candidate])
        if eff * max_byte >= preset.MAX_EFFECTIVE_BALANCE * rand:
            return candidate
        i += 1


# --- merkle -----------------------------------------------------------------

def is_valid_merkle_branch(
    leaf: bytes, branch: list[bytes], depth: int, index: int, root: bytes
) -> bool:
    value = leaf
    for i in range(depth):
        if (index >> i) & 1:
            value = sha256(branch[i] + value)
        else:
            value = sha256(value + branch[i])
    return value == root
