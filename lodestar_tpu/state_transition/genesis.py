"""Genesis state construction + interop genesis.

Spec `initialize_beacon_state_from_eth1` plus the deterministic interop
path the reference uses for dev/sim networks
(`state-transition/src/util/interop.ts`-equivalent roles; genesis builder
reference: `beacon-node/src/chain/genesis/genesis.ts`).
"""

from __future__ import annotations

from ..bls.api import interop_secret_key
from ..config.beacon_config import compute_domain, compute_signing_root
from ..params import (
    DEPOSIT_CONTRACT_TREE_DEPTH,
    DOMAIN_DEPOSIT,
    GENESIS_EPOCH,
)
from ..ssz.hashing import sha256
from .block import apply_deposit_data


class DepositTree:
    """Incremental depth-32 merkle tree (the deposit-contract algorithm):
    append leaves, produce proofs against the current root. Proofs include
    the trailing length mix-in (depth+1 branch) per the spec layout."""

    def __init__(self, depth: int = DEPOSIT_CONTRACT_TREE_DEPTH):
        self.depth = depth
        self.zero_hashes = [b"\x00" * 32]
        for _ in range(depth):
            self.zero_hashes.append(
                sha256(self.zero_hashes[-1] + self.zero_hashes[-1])
            )
        self.leaves: list[bytes] = []

    def append(self, leaf: bytes) -> None:
        self.leaves.append(leaf)

    def root(self) -> bytes:
        """Root including the uint256-length mix-in (deposit contract
        `get_deposit_root`)."""
        node = self._subtree_root()
        return sha256(node + len(self.leaves).to_bytes(32, "little"))

    def _subtree_root(self) -> bytes:
        nodes = list(self.leaves)
        for h in range(self.depth):
            if len(nodes) % 2 == 1:
                nodes.append(self.zero_hashes[h])
            nodes = [sha256(nodes[i] + nodes[i + 1]) for i in range(0, len(nodes), 2)]
        return nodes[0] if nodes else self.zero_hashes[self.depth]

    def proof(self, index: int) -> list[bytes]:
        """Branch for leaf `index` against `root()` — depth+1 elements, the
        last being the length mix-in."""
        branch: list[bytes] = []
        nodes = list(self.leaves)
        idx = index
        for h in range(self.depth):
            if len(nodes) % 2 == 1:
                nodes.append(self.zero_hashes[h])
            sibling = idx ^ 1
            branch.append(nodes[sibling] if sibling < len(nodes) else self.zero_hashes[h])
            nodes = [sha256(nodes[i] + nodes[i + 1]) for i in range(0, len(nodes), 2)]
            idx //= 2
        branch.append(len(self.leaves).to_bytes(32, "little"))
        return branch


def initialize_beacon_state_from_eth1(
    config, types, eth1_block_hash: bytes, eth1_timestamp: int, deposits
):
    """Spec initialize_beacon_state_from_eth1 (phase0 types namespace)."""
    p = config.preset
    state = types.BeaconState()
    state.genesis_time = eth1_timestamp + config.GENESIS_DELAY
    state.fork = types.Fork(
        previous_version=config.GENESIS_FORK_VERSION,
        current_version=config.GENESIS_FORK_VERSION,
        epoch=GENESIS_EPOCH,
    )
    state.eth1_data = types.Eth1Data(
        deposit_root=b"\x00" * 32,
        deposit_count=len(deposits),
        block_hash=eth1_block_hash,
    )
    body_root = types.BeaconBlockBody().hash_tree_root()
    state.latest_block_header = types.BeaconBlockHeader(body_root=body_root)
    state.randao_mixes = [eth1_block_hash] * p.EPOCHS_PER_HISTORICAL_VECTOR

    # process deposits against an incrementally-updated deposit root
    tree = DepositTree()
    leaves = [d.data for d in deposits]
    for i, deposit in enumerate(deposits):
        tree.append(leaves[i].hash_tree_root())
        state.eth1_data.deposit_root = tree.root()
        # genesis deposits: proof verified against the incremental root
        from .util import is_valid_merkle_branch

        assert is_valid_merkle_branch(
            leaves[i].hash_tree_root(),
            list(deposit.proof),
            DEPOSIT_CONTRACT_TREE_DEPTH + 1,
            i,
            state.eth1_data.deposit_root,
        ), f"invalid genesis deposit proof at {i}"
        state.eth1_deposit_index += 1
        apply_deposit_data(config, types, state, deposit.data)

    # activate validators with full effective balance
    for v in state.validators:
        if v.effective_balance == p.MAX_EFFECTIVE_BALANCE:
            v.activation_eligibility_epoch = GENESIS_EPOCH
            v.activation_epoch = GENESIS_EPOCH
    validators_type = dict(type(state).fields)["validators"]
    state.genesis_validators_root = validators_type.hash_tree_root(state.validators)
    return state


def is_valid_genesis_state(config, state) -> bool:
    if state.genesis_time < config.MIN_GENESIS_TIME:
        return False
    active = sum(
        1 for v in state.validators if v.activation_epoch == GENESIS_EPOCH
    )
    return active >= config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT


def make_interop_deposits(config, types, n: int, amount: int | None = None):
    """Deterministic interop deposits: keys via `interop_secret_key(i)`,
    BLS withdrawal credentials, signed DepositMessages, merkle proofs from
    the incremental tree."""
    p = config.preset
    amount = amount if amount is not None else p.MAX_EFFECTIVE_BALANCE
    domain = compute_domain(DOMAIN_DEPOSIT, config.GENESIS_FORK_VERSION, b"\x00" * 32)
    datas = []
    for i in range(n):
        sk = interop_secret_key(i)
        pk = sk.to_public_key().to_bytes()
        wc = b"\x00" + sha256(pk)[1:]
        msg = types.DepositMessage(
            pubkey=pk, withdrawal_credentials=wc, amount=amount
        )
        sig = sk.sign(compute_signing_root(msg.hash_tree_root(), domain))
        datas.append(
            types.DepositData(
                pubkey=pk,
                withdrawal_credentials=wc,
                amount=amount,
                signature=sig.to_bytes(),
            )
        )
    # proofs are against the FINAL root only for the last deposit; genesis
    # processing verifies each against the root-so-far, so build proofs
    # incrementally.
    deposits = []
    tree = DepositTree()
    for i, data in enumerate(datas):
        tree.append(data.hash_tree_root())
    for i, data in enumerate(datas):
        # proof for leaf i against the tree containing leaves 0..i
        partial = DepositTree()
        for d in datas[: i + 1]:
            partial.append(d.hash_tree_root())
        deposits.append(types.Deposit(proof=partial.proof(i), data=data))
    return deposits


def interop_genesis_state(config, types, n_validators: int, genesis_time: int = 0):
    """Dev/sim genesis on interop keys (reference: `dev` command path,
    `cli/src/cmds/dev` + interop state)."""
    deposits = make_interop_deposits(config, types, n_validators)
    state = initialize_beacon_state_from_eth1(
        config, types, b"\x42" * 32, max(0, genesis_time - config.GENESIS_DELAY), deposits
    )
    if genesis_time:
        state.genesis_time = genesis_time
    return state
