"""phase0 epoch processing, vectorized.

Reference surface: `state-transition/src/epoch/` (processJustificationAnd-
Finalization, getAttestationDeltas, processRegistryUpdates, processSlashings,
processEffectiveBalanceUpdates, the *Reset steps) driven by the
`EpochProcess` flat cache (`cache/epochProcess.ts:43`).

Design: one `EpochSummary` pass digests the pending attestations into
boolean participation masks (source/target/head per epoch) + per-validator
earliest-inclusion data; every subsequent step is numpy array math over
those masks — no per-validator Python loops except where the spec forces
sequential semantics (activation queue ordering, exit churn).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..params import (
    BASE_REWARDS_PER_EPOCH,
    FAR_FUTURE_EPOCH,
    GENESIS_EPOCH,
    JUSTIFICATION_BITS_LENGTH,
)
from . import util
from .block import get_validator_churn_limit

U64 = np.uint64


@dataclass
class EpochSummary:
    """Digest of one epoch's pending attestations (prev or current)."""

    source: np.ndarray          # (n,) bool — unslashed & attested (source implied)
    target: np.ndarray          # (n,) bool
    head: np.ndarray            # (n,) bool
    inclusion_delay: np.ndarray  # (n,) uint64 — earliest inclusion (0 = none)
    inclusion_proposer: np.ndarray  # (n,) int64 — proposer of that inclusion


def _get_block_root_at_slot(state, slot: int, preset) -> bytes:
    assert slot < state.slot <= slot + preset.SLOTS_PER_HISTORICAL_ROOT
    return state.block_roots[slot % preset.SLOTS_PER_HISTORICAL_ROOT]


def _get_block_root(state, epoch: int, preset) -> bytes:
    return _get_block_root_at_slot(
        state, util.compute_start_slot_at_epoch(epoch, preset.SLOTS_PER_EPOCH), preset
    )


def summarize_attestations(cached, attestations, epoch: int) -> EpochSummary:
    """Fold PendingAttestations into per-validator masks. Matching rules:
    source is implied by inclusion (process_attestation already checked the
    justified checkpoint), target = epoch boundary root, head = root at
    attestation slot."""
    n = len(cached.flat)
    state, p = cached.state, cached.preset
    source = np.zeros(n, bool)
    target = np.zeros(n, bool)
    head = np.zeros(n, bool)
    delay = np.full(n, np.iinfo(np.uint64).max, U64)
    prop = np.full(n, -1, np.int64)

    target_root = _get_block_root(state, epoch, p)
    for att in attestations:
        committee = cached.epoch_ctx.get_beacon_committee(
            att.data.slot, att.data.index
        )
        bits = np.asarray(att.aggregation_bits, bool)
        members = np.asarray(committee)[bits[: len(committee)]]
        source[members] = True
        is_target = bytes(att.data.target.root) == target_root
        if is_target:
            target[members] = True
            if bytes(att.data.beacon_block_root) == _get_block_root_at_slot(
                state, att.data.slot, p
            ):
                head[members] = True
        better = att.inclusion_delay < delay[members]
        upd = members[better]
        delay[upd] = att.inclusion_delay
        prop[upd] = att.proposer_index

    unslashed = ~cached.flat.slashed
    return EpochSummary(
        source=source & unslashed,
        target=target & unslashed,
        head=head & unslashed,
        inclusion_delay=delay,
        inclusion_proposer=prop,
    )


# --- justification & finalization ------------------------------------------

def process_justification_and_finalization(cached, types) -> None:
    state, p, flat = cached.state, cached.preset, cached.flat
    current_epoch = cached.current_epoch
    if current_epoch <= GENESIS_EPOCH + 1:
        return
    previous_epoch = cached.previous_epoch
    inc = p.EFFECTIVE_BALANCE_INCREMENT
    total = flat.total_active_balance(current_epoch, inc)

    prev_summary = summarize_attestations(
        cached, state.previous_epoch_attestations, previous_epoch
    )
    curr_summary = summarize_attestations(
        cached, state.current_epoch_attestations, current_epoch
    )
    prev_target_bal = max(inc, int(flat.effective_balance[prev_summary.target].sum()))
    curr_target_bal = max(inc, int(flat.effective_balance[curr_summary.target].sum()))

    old_previous_justified = state.previous_justified_checkpoint.copy()
    old_current_justified = state.current_justified_checkpoint.copy()

    # shift justification bits
    bits = list(state.justification_bits)
    bits = [False] + bits[: JUSTIFICATION_BITS_LENGTH - 1]
    state.previous_justified_checkpoint = state.current_justified_checkpoint.copy()

    if prev_target_bal * 3 >= total * 2:
        state.current_justified_checkpoint = types.Checkpoint(
            epoch=previous_epoch, root=_get_block_root(state, previous_epoch, p)
        )
        bits[1] = True
    if curr_target_bal * 3 >= total * 2:
        state.current_justified_checkpoint = types.Checkpoint(
            epoch=current_epoch, root=_get_block_root(state, current_epoch, p)
        )
        bits[0] = True
    state.justification_bits = bits

    # finalization rules
    if all(bits[1:4]) and old_previous_justified.epoch + 3 == current_epoch:
        state.finalized_checkpoint = old_previous_justified
    if all(bits[1:3]) and old_previous_justified.epoch + 2 == current_epoch:
        state.finalized_checkpoint = old_previous_justified
    if all(bits[0:3]) and old_current_justified.epoch + 2 == current_epoch:
        state.finalized_checkpoint = old_current_justified
    if all(bits[0:2]) and old_current_justified.epoch + 1 == current_epoch:
        state.finalized_checkpoint = old_current_justified


# --- rewards & penalties ----------------------------------------------------

def _finality_delay(cached) -> int:
    return cached.previous_epoch - cached.state.finalized_checkpoint.epoch


def _is_in_inactivity_leak(cached) -> bool:
    return _finality_delay(cached) > cached.preset.MIN_EPOCHS_TO_INACTIVITY_PENALTY


def get_attestation_deltas(cached) -> tuple[np.ndarray, np.ndarray]:
    """(rewards, penalties) as int64 arrays — vectorized over validators."""
    state, p, flat = cached.state, cached.preset, cached.flat
    n = len(flat)
    previous_epoch = cached.previous_epoch
    inc = p.EFFECTIVE_BALANCE_INCREMENT
    total = flat.total_active_balance(cached.current_epoch, inc)
    sqrt_total = util.integer_squareroot(total)

    eff = flat.effective_balance.astype(np.int64)
    base_reward = (
        eff * p.BASE_REWARD_FACTOR // sqrt_total // BASE_REWARDS_PER_EPOCH
    )
    proposer_reward = base_reward // p.PROPOSER_REWARD_QUOTIENT

    active_prev = util.active_mask(
        flat.activation_epoch, flat.exit_epoch, previous_epoch
    )
    eligible = active_prev | (
        flat.slashed & (U64(previous_epoch + 1) < flat.withdrawable_epoch)
    )

    s = summarize_attestations(
        cached, state.previous_epoch_attestations, previous_epoch
    )
    rewards = np.zeros(n, np.int64)
    penalties = np.zeros(n, np.int64)
    in_leak = _is_in_inactivity_leak(cached)

    for mask in (s.source, s.target, s.head):
        attesting_bal = max(inc, int(flat.effective_balance[mask].sum()))
        att = eligible & mask
        non = eligible & ~mask
        if in_leak:
            rewards[att] += base_reward[att]
        else:
            rewards[att] += (
                base_reward[att] * (attesting_bal // inc) // (total // inc)
            )
        penalties[non] += base_reward[non]

    # inclusion delay: attester + proposer micro-rewards
    src = s.source & (s.inclusion_proposer >= 0)
    idxs = np.nonzero(src)[0]
    for i in idxs:
        rewards[s.inclusion_proposer[i]] += proposer_reward[i]
        max_attester = base_reward[i] - proposer_reward[i]
        rewards[i] += max_attester // int(s.inclusion_delay[i])

    # inactivity leak
    if in_leak:
        pen = BASE_REWARDS_PER_EPOCH * base_reward - proposer_reward
        penalties[eligible] += pen[eligible]
        not_target = eligible & ~s.target
        penalties[not_target] += (
            eff[not_target] * _finality_delay(cached) // p.INACTIVITY_PENALTY_QUOTIENT
        )

    return rewards, penalties


def process_rewards_and_penalties(cached) -> None:
    if cached.current_epoch == GENESIS_EPOCH:
        return
    rewards, penalties = get_attestation_deltas(cached)
    flat = cached.flat
    bal = flat.balances.astype(np.int64)
    bal = bal + rewards
    bal = np.maximum(0, bal - penalties)
    flat.balances = bal.astype(U64)


# --- registry updates -------------------------------------------------------

def process_registry_updates(cached) -> None:
    from .block import initiate_validator_exit

    state, p, flat, config = cached.state, cached.preset, cached.flat, cached.config
    current_epoch = cached.current_epoch

    # eligibility for the activation queue
    eligible_queue = (
        (flat.activation_eligibility_epoch == U64(FAR_FUTURE_EPOCH))
        & (flat.effective_balance == U64(p.MAX_EFFECTIVE_BALANCE))
    )
    flat.activation_eligibility_epoch[eligible_queue] = current_epoch + 1

    # ejections (sequential: each exit consumes churn)
    active_now = util.active_mask(flat.activation_epoch, flat.exit_epoch, current_epoch)
    ejectable = np.nonzero(
        active_now & (flat.effective_balance <= U64(config.EJECTION_BALANCE))
    )[0]
    for idx in ejectable:
        initiate_validator_exit(cached, int(idx))

    # dequeue activations up to churn, ordered by (eligibility_epoch, index)
    finalized = state.finalized_checkpoint.epoch
    can_activate = (
        (flat.activation_eligibility_epoch <= U64(finalized))
        & (flat.activation_epoch == U64(FAR_FUTURE_EPOCH))
    )
    queue = sorted(
        np.nonzero(can_activate)[0],
        key=lambda i: (int(flat.activation_eligibility_epoch[i]), int(i)),
    )
    churn = get_validator_churn_limit(cached)
    activation_epoch = util.compute_activation_exit_epoch(
        current_epoch, p.MAX_SEED_LOOKAHEAD
    )
    for idx in queue[:churn]:
        flat.activation_epoch[idx] = activation_epoch


# --- slashings --------------------------------------------------------------

def process_slashings(cached) -> None:
    state, p, flat = cached.state, cached.preset, cached.flat
    epoch = cached.current_epoch
    inc = p.EFFECTIVE_BALANCE_INCREMENT
    total = flat.total_active_balance(epoch, inc)
    total_slashings = sum(int(x) for x in state.slashings)
    adjusted = min(total_slashings * p.PROPORTIONAL_SLASHING_MULTIPLIER, total)

    target_epoch = epoch + p.EPOCHS_PER_SLASHINGS_VECTOR // 2
    hit = flat.slashed & (flat.withdrawable_epoch == U64(target_epoch))
    idxs = np.nonzero(hit)[0]
    for i in idxs:
        eff = int(flat.effective_balance[i])
        penalty = eff // inc * adjusted // total * inc
        flat.balances[i] = max(0, int(flat.balances[i]) - penalty)


# --- the reset / bookkeeping tail ------------------------------------------

def process_eth1_data_reset(cached) -> None:
    p = cached.preset
    next_epoch = cached.current_epoch + 1
    if next_epoch % p.EPOCHS_PER_ETH1_VOTING_PERIOD == 0:
        cached.state.eth1_data_votes = []


def process_effective_balance_updates(cached) -> None:
    p, flat = cached.preset, cached.flat
    inc = p.EFFECTIVE_BALANCE_INCREMENT
    hysteresis_inc = inc // p.HYSTERESIS_QUOTIENT
    down = hysteresis_inc * p.HYSTERESIS_DOWNWARD_MULTIPLIER
    up = hysteresis_inc * p.HYSTERESIS_UPWARD_MULTIPLIER
    bal = flat.balances.astype(np.int64)
    eff = flat.effective_balance.astype(np.int64)
    update = (bal + down < eff) | (eff + up < bal)
    new_eff = np.minimum(bal - bal % inc, p.MAX_EFFECTIVE_BALANCE)
    flat.effective_balance = np.where(update, new_eff, eff).astype(U64)


def process_slashings_reset(cached) -> None:
    p = cached.preset
    next_epoch = cached.current_epoch + 1
    cached.state.slashings[next_epoch % p.EPOCHS_PER_SLASHINGS_VECTOR] = 0


def process_randao_mixes_reset(cached) -> None:
    p, state = cached.preset, cached.state
    current_epoch = cached.current_epoch
    next_epoch = current_epoch + 1
    state.randao_mixes[next_epoch % p.EPOCHS_PER_HISTORICAL_VECTOR] = (
        util.get_randao_mix(state, current_epoch, p.EPOCHS_PER_HISTORICAL_VECTOR)
    )


def process_historical_roots_update(cached, types) -> None:
    p, state = cached.preset, cached.state
    next_epoch = cached.current_epoch + 1
    if next_epoch % (p.SLOTS_PER_HISTORICAL_ROOT // p.SLOTS_PER_EPOCH) == 0:
        batch = types.HistoricalBatch(
            block_roots=list(state.block_roots),
            state_roots=list(state.state_roots),
        )
        state.historical_roots.append(batch.hash_tree_root())


def process_participation_record_updates(cached) -> None:
    state = cached.state
    state.previous_epoch_attestations = state.current_epoch_attestations
    state.current_epoch_attestations = []


# --- orchestration ----------------------------------------------------------

def process_epoch(cached, types) -> None:
    """Spec order (phase0). Mutates flat arrays; `sync_to_state` is called
    by the slot driver before any hash_tree_root."""
    process_justification_and_finalization(cached, types)
    process_rewards_and_penalties(cached)
    process_registry_updates(cached)
    process_slashings(cached)
    process_eth1_data_reset(cached)
    process_effective_balance_updates(cached)
    process_slashings_reset(cached)
    process_randao_mixes_reset(cached)
    process_historical_roots_update(cached, types)
    process_participation_record_updates(cached)
