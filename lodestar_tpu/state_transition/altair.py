"""Altair state transition: participation flags, sync committees,
inactivity scores.

Reference surface: `state-transition/src/block/processAttestationsAltair`,
`processSyncCommittee`, `epoch/` altair branches, `slot/upgradeStateToAltair`
— re-derived from the altair consensus spec. Participation flags and
inactivity scores live in flat numpy uint8/uint64 arrays on the cache
(`CachedBeaconState.participation`), synced into the SSZ state before any
hash, in the same style as `FlatValidators`.
"""

from __future__ import annotations

import numpy as np

from ..bls import api as bls
from ..config.beacon_config import compute_signing_root
from ..params import (
    DOMAIN_SYNC_COMMITTEE,
    GENESIS_EPOCH,
    JUSTIFICATION_BITS_LENGTH,
    PARTICIPATION_FLAG_WEIGHTS,
    PROPOSER_WEIGHT,
    SYNC_REWARD_WEIGHT,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_SOURCE_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
    WEIGHT_DENOMINATOR,
)
from . import util
from .block import (
    _require,
    decrease_balance,
    get_attesting_indices,
    increase_balance,
)
from .epoch import _get_block_root, _get_block_root_at_slot

U64 = np.uint64


# --- participation flag helpers ---------------------------------------------

def has_flag(flags: np.ndarray | int, index: int):
    return (flags >> index) & 1 != 0 if isinstance(flags, int) else (
        (flags >> np.uint8(index)) & np.uint8(1)
    ).astype(bool)


def add_flag(flags, index: int):
    return flags | (1 << index)


# --- attestation participation (spec get_attestation_participation_flags) ---

def get_attestation_participation_flag_indices(
    cached, data, inclusion_delay: int
) -> list[int]:
    state, p = cached.state, cached.preset
    if data.target.epoch == cached.current_epoch:
        justified = state.current_justified_checkpoint
    else:
        justified = state.previous_justified_checkpoint
    is_matching_source = data.source == justified
    _require(is_matching_source, "wrong source checkpoint")
    is_matching_target = bytes(data.target.root) == _get_block_root(
        state, data.target.epoch, p
    )
    is_matching_head = is_matching_target and bytes(
        data.beacon_block_root
    ) == _get_block_root_at_slot(state, data.slot, p)

    flags = []
    if is_matching_source and inclusion_delay <= util.integer_squareroot(
        p.SLOTS_PER_EPOCH
    ):
        flags.append(TIMELY_SOURCE_FLAG_INDEX)
    if is_matching_target and inclusion_delay <= p.SLOTS_PER_EPOCH:
        flags.append(TIMELY_TARGET_FLAG_INDEX)
    if is_matching_head and inclusion_delay == p.MIN_ATTESTATION_INCLUSION_DELAY:
        flags.append(TIMELY_HEAD_FLAG_INDEX)
    return flags


def get_base_reward_per_increment(cached) -> int:
    p = cached.preset
    inc = p.EFFECTIVE_BALANCE_INCREMENT
    total = cached.flat.total_active_balance(cached.current_epoch, inc)
    return inc * p.BASE_REWARD_FACTOR // util.integer_squareroot(total)


def process_attestation_altair(cached, types, attestation, verify_signatures: bool = True) -> None:
    """Altair processAttestation: validity checks as phase0, then set
    participation flags + pay the proposer (no PendingAttestation lists)."""
    state, p, flat = cached.state, cached.preset, cached.flat
    data = attestation.data
    _require(
        data.target.epoch in (cached.previous_epoch, cached.current_epoch),
        "target epoch out of range",
    )
    _require(
        data.target.epoch == util.compute_epoch_at_slot(data.slot, p.SLOTS_PER_EPOCH),
        "target epoch != slot epoch",
    )
    _require(
        data.slot + p.MIN_ATTESTATION_INCLUSION_DELAY <= state.slot,
        "attestation too new",
    )
    _require(state.slot <= data.slot + p.SLOTS_PER_EPOCH, "attestation too old")
    _require(
        data.index < cached.epoch_ctx.get_committee_count_per_slot(data.target.epoch),
        "committee index out of range",
    )
    inclusion_delay = state.slot - data.slot
    flag_indices = get_attestation_participation_flag_indices(
        cached, data, inclusion_delay
    )
    indices = get_attesting_indices(cached, data, attestation.aggregation_bits)
    if verify_signatures:
        from .block import is_valid_indexed_attestation

        indexed = types.IndexedAttestation(
            attesting_indices=indices,
            data=data.copy(),
            signature=bytes(attestation.signature),
        )
        _require(
            is_valid_indexed_attestation(cached, indexed, True),
            "bad attestation signature",
        )

    epoch_participation = (
        cached.current_participation
        if data.target.epoch == cached.current_epoch
        else cached.previous_participation
    )
    base_per_inc = get_base_reward_per_increment(cached)
    inc = p.EFFECTIVE_BALANCE_INCREMENT
    proposer_reward_numerator = 0
    for idx in indices:
        base_reward = int(flat.effective_balance[idx]) // inc * base_per_inc
        for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
            if flag_index in flag_indices and not (
                int(epoch_participation[idx]) >> flag_index
            ) & 1:
                epoch_participation[idx] = add_flag(
                    int(epoch_participation[idx]), flag_index
                )
                proposer_reward_numerator += base_reward * weight
    proposer_reward = proposer_reward_numerator // (
        (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT) * WEIGHT_DENOMINATOR // PROPOSER_WEIGHT
    )
    increase_balance(
        cached, cached.epoch_ctx.get_beacon_proposer(state.slot), proposer_reward
    )


# --- sync aggregate ----------------------------------------------------------

def process_sync_aggregate(cached, aggregate, verify_signatures: bool = True):
    """Spec process_sync_aggregate: verify the committee signature over the
    previous slot's block root, pay participants, charge absentees."""
    state, p, flat = cached.state, cached.preset, cached.flat
    committee_pubkeys = [bytes(pk) for pk in state.current_sync_committee.pubkeys]
    bits = list(aggregate.sync_committee_bits)
    participant_pubkeys = [pk for pk, b in zip(committee_pubkeys, bits) if b]

    # structural rule, enforced regardless of signature verification (the
    # batched extractor emits no set for empty participation): zero bits
    # must carry the infinity signature
    if not participant_pubkeys:
        _require(
            bytes(aggregate.sync_committee_signature) == b"\xc0" + b"\x00" * 95,
            "non-infinity signature with no participants",
        )
    elif verify_signatures:
        previous_slot = max(state.slot, 1) - 1
        domain = cached.config.get_domain(
            DOMAIN_SYNC_COMMITTEE,
            previous_slot,
            util.compute_epoch_at_slot(previous_slot, p.SLOTS_PER_EPOCH),
        )
        root = compute_signing_root(
            _get_block_root_at_slot(state, previous_slot, p), domain
        )
        pks = [bls.PublicKey.from_bytes(pk, validate=False) for pk in participant_pubkeys]
        sig = bls.Signature.from_bytes(
            bytes(aggregate.sync_committee_signature), validate=False
        )
        _require(
            bls.fast_aggregate_verify(pks, root, sig), "bad sync aggregate sig"
        )

    # rewards (spec formulae)
    inc = p.EFFECTIVE_BALANCE_INCREMENT
    total_active_increments = (
        cached.flat.total_active_balance(cached.current_epoch, inc) // inc
    )
    base_per_inc = get_base_reward_per_increment(cached)
    total_base_rewards = base_per_inc * total_active_increments
    max_participant_rewards = (
        total_base_rewards * SYNC_REWARD_WEIGHT // WEIGHT_DENOMINATOR // p.SLOTS_PER_EPOCH
    )
    participant_reward = max_participant_rewards // p.SYNC_COMMITTEE_SIZE
    proposer_reward = (
        participant_reward * PROPOSER_WEIGHT // (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
    )
    proposer_index = cached.epoch_ctx.get_beacon_proposer(state.slot)
    pk_to_idx = cached.epoch_ctx.pubkey_to_index
    for pk, participated in zip(committee_pubkeys, bits):
        idx = pk_to_idx[pk]
        if participated:
            increase_balance(cached, idx, participant_reward)
            increase_balance(cached, proposer_index, proposer_reward)
        else:
            decrease_balance(cached, idx, participant_reward)


# --- sync committee computation ---------------------------------------------

def get_next_sync_committee(cached, types):
    """Spec get_next_sync_committee: effective-balance-weighted sampling of
    SYNC_COMMITTEE_SIZE members from the next epoch's active set."""
    from ..params import DOMAIN_SYNC_COMMITTEE as _D  # seed domain constant
    from ..ssz.hashing import sha256

    state, p, flat = cached.state, cached.preset, cached.flat
    epoch = cached.current_epoch + 1
    active = flat.active_indices(epoch)
    seed = util.get_seed(state, epoch, _D, p)
    total = len(active)
    indices = []
    i = 0
    while len(indices) < p.SYNC_COMMITTEE_SIZE:
        shuffled_i = util.compute_shuffled_index(
            i % total, total, seed, p.SHUFFLE_ROUND_COUNT
        )
        candidate = int(active[shuffled_i])
        rand = sha256(seed + (i // 32).to_bytes(8, "little"))[i % 32]
        if int(flat.effective_balance[candidate]) * 255 >= p.MAX_EFFECTIVE_BALANCE * rand:
            indices.append(candidate)
        i += 1
    pubkeys = [bytes(flat.pubkeys[idx]) for idx in indices]
    agg = bls.aggregate_pubkeys(
        [bls.PublicKey.from_bytes(pk, validate=False) for pk in pubkeys]
    )
    return types.SyncCommittee(pubkeys=pubkeys, aggregate_pubkey=agg.to_bytes())


# --- epoch processing (altair variants) -------------------------------------

def process_inactivity_updates(cached) -> None:
    state, p, flat, config = cached.state, cached.preset, cached.flat, cached.config
    if cached.current_epoch == GENESIS_EPOCH:
        return
    prev = cached.previous_epoch
    scores = cached.inactivity_scores
    active_prev = util.active_mask(flat.activation_epoch, flat.exit_epoch, prev)
    eligible = active_prev | (
        flat.slashed & (U64(prev + 1) < flat.withdrawable_epoch)
    )
    target = has_flag(cached.previous_participation, TIMELY_TARGET_FLAG_INDEX) & (
        ~flat.slashed
    )
    # increase by bias for non-participants, else decrement by 1
    scores[eligible & target] -= np.minimum(
        U64(1), scores[eligible & target]
    )
    scores[eligible & ~target] += U64(config.INACTIVITY_SCORE_BIAS)
    # recovery when not in leak
    leak = (prev - state.finalized_checkpoint.epoch) > p.MIN_EPOCHS_TO_INACTIVITY_PENALTY
    if not leak:
        dec = np.minimum(U64(config.INACTIVITY_SCORE_RECOVERY_RATE), scores)
        scores[eligible] -= dec[eligible]


def process_justification_and_finalization_altair(cached, types) -> None:

    state, p, flat = cached.state, cached.preset, cached.flat
    current_epoch = cached.current_epoch
    if current_epoch <= GENESIS_EPOCH + 1:
        return
    inc = p.EFFECTIVE_BALANCE_INCREMENT
    total = flat.total_active_balance(current_epoch, inc)

    def target_balance(participation, epoch):
        active = util.active_mask(flat.activation_epoch, flat.exit_epoch, epoch)
        mask = active & ~flat.slashed & has_flag(
            participation, TIMELY_TARGET_FLAG_INDEX
        )
        return max(inc, int(flat.effective_balance[mask].sum()))

    prev_target = target_balance(cached.previous_participation, cached.previous_epoch)
    curr_target = target_balance(cached.current_participation, current_epoch)
    _weigh_justification_and_finalization(
        cached, types, total, prev_target, curr_target
    )


def _weigh_justification_and_finalization(
    cached, types, total, prev_target_bal, curr_target_bal
) -> None:
    state, p = cached.state, cached.preset
    current_epoch = cached.current_epoch
    previous_epoch = cached.previous_epoch
    old_previous_justified = state.previous_justified_checkpoint.copy()
    old_current_justified = state.current_justified_checkpoint.copy()
    checkpoint_cls = type(state.current_justified_checkpoint)
    bits = list(state.justification_bits)
    bits = [False] + bits[: JUSTIFICATION_BITS_LENGTH - 1]
    state.previous_justified_checkpoint = state.current_justified_checkpoint.copy()
    if prev_target_bal * 3 >= total * 2:
        state.current_justified_checkpoint = checkpoint_cls(
            epoch=previous_epoch, root=_get_block_root(state, previous_epoch, p)
        )
        bits[1] = True
    if curr_target_bal * 3 >= total * 2:
        state.current_justified_checkpoint = checkpoint_cls(
            epoch=current_epoch, root=_get_block_root(state, current_epoch, p)
        )
        bits[0] = True
    state.justification_bits = bits
    if all(bits[1:4]) and old_previous_justified.epoch + 3 == current_epoch:
        state.finalized_checkpoint = old_previous_justified
    if all(bits[1:3]) and old_previous_justified.epoch + 2 == current_epoch:
        state.finalized_checkpoint = old_previous_justified
    if all(bits[0:3]) and old_current_justified.epoch + 2 == current_epoch:
        state.finalized_checkpoint = old_current_justified
    if all(bits[0:2]) and old_current_justified.epoch + 1 == current_epoch:
        state.finalized_checkpoint = old_current_justified


def process_rewards_and_penalties_altair(cached) -> None:
    state, p, flat, config = cached.state, cached.preset, cached.flat, cached.config
    if cached.current_epoch == GENESIS_EPOCH:
        return
    prev = cached.previous_epoch
    inc = p.EFFECTIVE_BALANCE_INCREMENT
    total = flat.total_active_balance(cached.current_epoch, inc)
    base_per_inc = get_base_reward_per_increment(cached)
    eff = flat.effective_balance.astype(np.int64)
    base_reward = eff // inc * base_per_inc

    active_prev = util.active_mask(flat.activation_epoch, flat.exit_epoch, prev)
    eligible = active_prev | (
        flat.slashed & (U64(prev + 1) < flat.withdrawable_epoch)
    )
    leak = (prev - state.finalized_checkpoint.epoch) > p.MIN_EPOCHS_TO_INACTIVITY_PENALTY

    rewards = np.zeros(len(flat), np.int64)
    penalties = np.zeros(len(flat), np.int64)
    for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
        unslashed = (
            has_flag(cached.previous_participation, flag_index) & ~flat.slashed
        )
        unslashed_bal = max(inc, int(flat.effective_balance[unslashed].sum()))
        att = eligible & unslashed
        non = eligible & ~unslashed
        if not leak:
            reward_numerator = (
                base_reward[att] * weight * (unslashed_bal // inc)
            )
            rewards[att] += reward_numerator // (
                (total // inc) * WEIGHT_DENOMINATOR
            )
        if flag_index != TIMELY_HEAD_FLAG_INDEX:
            penalties[non] += base_reward[non] * weight // WEIGHT_DENOMINATOR

    # inactivity penalties (altair: score-scaled)
    target_flag = has_flag(cached.previous_participation, TIMELY_TARGET_FLAG_INDEX) & (
        ~flat.slashed
    )
    not_target = eligible & ~target_flag
    scores = cached.inactivity_scores.astype(np.int64)
    inactivity_quotient = (
        p.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX
        if cached.is_execution
        else p.INACTIVITY_PENALTY_QUOTIENT_ALTAIR
    )
    penalties[not_target] += (
        eff[not_target] * scores[not_target]
        // (config.INACTIVITY_SCORE_BIAS * inactivity_quotient)
    )

    bal = flat.balances.astype(np.int64) + rewards
    flat.balances = np.maximum(0, bal - penalties).astype(U64)


def process_participation_flag_updates(cached) -> None:
    cached.previous_participation = cached.current_participation
    cached.current_participation = np.zeros(len(cached.flat), np.uint8)


def process_sync_committee_updates(cached, types) -> None:
    p = cached.preset
    next_epoch = cached.current_epoch + 1
    if next_epoch % p.EPOCHS_PER_SYNC_COMMITTEE_PERIOD == 0:
        state = cached.state
        state.current_sync_committee = state.next_sync_committee
        state.next_sync_committee = get_next_sync_committee(cached, types)


def process_slashings_altair(cached) -> None:
    state, p, flat = cached.state, cached.preset, cached.flat
    epoch = cached.current_epoch
    inc = p.EFFECTIVE_BALANCE_INCREMENT
    total = flat.total_active_balance(epoch, inc)
    total_slashings = sum(int(x) for x in state.slashings)
    multiplier = (
        p.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX
        if cached.is_execution
        else p.PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR
    )
    adjusted = min(total_slashings * multiplier, total)
    target_epoch = epoch + p.EPOCHS_PER_SLASHINGS_VECTOR // 2
    hit = flat.slashed & (flat.withdrawable_epoch == U64(target_epoch))
    for i in np.nonzero(hit)[0]:
        eff = int(flat.effective_balance[i])
        penalty = eff // inc * adjusted // total * inc
        flat.balances[i] = max(0, int(flat.balances[i]) - penalty)


def process_epoch_altair(cached, types) -> None:
    from .epoch import (
        process_effective_balance_updates,
        process_eth1_data_reset,
        process_historical_roots_update,
        process_randao_mixes_reset,
        process_registry_updates,
        process_slashings_reset,
    )

    process_justification_and_finalization_altair(cached, types)
    process_inactivity_updates(cached)
    process_rewards_and_penalties_altair(cached)
    process_registry_updates(cached)
    process_slashings_altair(cached)
    process_eth1_data_reset(cached)
    process_effective_balance_updates(cached)
    process_slashings_reset(cached)
    process_randao_mixes_reset(cached)
    if cached.is_capella:
        from .capella import process_historical_summaries_update

        process_historical_summaries_update(cached, types)
    else:
        process_historical_roots_update(cached, types)
    process_participation_flag_updates(cached)
    process_sync_committee_updates(cached, types)


# --- fork upgrade ------------------------------------------------------------

def upgrade_state_to_altair(config, preset, pre, altair_types):
    """Spec upgrade_to_altair (reference: slot/upgradeStateToAltair):
    carry fields over, empty participation, zero inactivity scores, set
    the fork version, and compute both sync committees (identical at the
    fork — both are get_next_sync_committee of the post state)."""
    from .cache import CachedBeaconState

    n = len(pre.validators)
    pre = pre.copy()
    post = altair_types.BeaconState()
    for name, _ in post.fields:
        if name in (
            "previous_epoch_participation",
            "current_epoch_participation",
            "inactivity_scores",
            "current_sync_committee",
            "next_sync_committee",
            "fork",
        ):
            continue
        setattr(post, name, getattr(pre, name))
    post.previous_epoch_participation = [0] * n
    post.current_epoch_participation = [0] * n
    post.inactivity_scores = [0] * n
    post.fork = type(pre.fork)(
        previous_version=bytes(pre.fork.current_version),
        current_version=config.ALTAIR_FORK_VERSION,
        epoch=util.compute_epoch_at_slot(pre.slot, preset.SLOTS_PER_EPOCH),
    )
    cached = CachedBeaconState(config, post, preset)
    committee = get_next_sync_committee(cached, altair_types)
    post.current_sync_committee = committee
    post.next_sync_committee = committee.copy()
    return post
