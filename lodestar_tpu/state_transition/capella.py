"""capella state transition: withdrawals + BLS→execution credential changes.

Reference surface: the capella consensus spec (the reference @ v1.1.1
predates capella's release but ships its early container work in
`types/src/capella`); structured after `state-transition/src/block/` and
`slot/upgradeState*` patterns: withdrawals sweep the flat balance arrays,
credential changes mutate the validator columns, historical summaries
replace historical-roots accumulation.
"""

from __future__ import annotations

import numpy as np

from ..bls import api as bls
from ..config.beacon_config import compute_domain, compute_signing_root
from ..params import (
    BLS_WITHDRAWAL_PREFIX,
    DOMAIN_BLS_TO_EXECUTION_CHANGE,
    ETH1_ADDRESS_WITHDRAWAL_PREFIX,
)
from ..ssz.hashing import sha256
from . import util
from .block import _require, decrease_balance

U64 = np.uint64


# --- withdrawal predicates (spec capella helpers) ----------------------------

def has_eth1_withdrawal_credential(withdrawal_credentials: bytes) -> bool:
    return withdrawal_credentials[:1] == ETH1_ADDRESS_WITHDRAWAL_PREFIX


def is_fully_withdrawable_validator(
    withdrawal_credentials: bytes, withdrawable_epoch: int, balance: int, epoch: int
) -> bool:
    return (
        has_eth1_withdrawal_credential(withdrawal_credentials)
        and withdrawable_epoch <= epoch
        and balance > 0
    )


def is_partially_withdrawable_validator(
    withdrawal_credentials: bytes, effective_balance: int, balance: int, preset
) -> bool:
    return (
        has_eth1_withdrawal_credential(withdrawal_credentials)
        and effective_balance == preset.MAX_EFFECTIVE_BALANCE
        and balance > preset.MAX_EFFECTIVE_BALANCE
    )


# --- withdrawals -------------------------------------------------------------

def get_expected_withdrawals(cached, types) -> list:
    """Spec get_expected_withdrawals: bounded sweep from
    next_withdrawal_validator_index over the registry."""
    state, p, flat = cached.state, cached.preset, cached.flat
    epoch = cached.current_epoch
    withdrawal_index = state.next_withdrawal_index
    validator_index = state.next_withdrawal_validator_index
    n = len(flat)
    withdrawals = []
    bound = min(n, p.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)
    creds = [bytes(v.withdrawal_credentials) for v in state.validators]
    for _ in range(bound):
        balance = int(flat.balances[validator_index])
        wc = creds[validator_index]
        if is_fully_withdrawable_validator(
            wc, int(flat.withdrawable_epoch[validator_index]), balance, epoch
        ):
            withdrawals.append(
                types.Withdrawal(
                    index=withdrawal_index,
                    validator_index=validator_index,
                    address=wc[12:],
                    amount=balance,
                )
            )
            withdrawal_index += 1
        elif is_partially_withdrawable_validator(
            wc, int(flat.effective_balance[validator_index]), balance, p
        ):
            withdrawals.append(
                types.Withdrawal(
                    index=withdrawal_index,
                    validator_index=validator_index,
                    address=wc[12:],
                    amount=balance - p.MAX_EFFECTIVE_BALANCE,
                )
            )
            withdrawal_index += 1
        if len(withdrawals) == p.MAX_WITHDRAWALS_PER_PAYLOAD:
            break
        validator_index = (validator_index + 1) % n
    return withdrawals


def process_withdrawals(cached, types, payload) -> None:
    """Spec process_withdrawals: payload withdrawals must equal the expected
    sweep; debit balances and advance the sweep cursors."""
    state, p, flat = cached.state, cached.preset, cached.flat
    expected = get_expected_withdrawals(cached, types)
    got = list(payload.withdrawals)
    _require(len(got) == len(expected), "wrong number of withdrawals")
    for g, e in zip(got, expected):
        _require(
            g.index == e.index
            and g.validator_index == e.validator_index
            and bytes(g.address) == bytes(e.address)
            and g.amount == e.amount,
            "withdrawal mismatch",
        )
    for w in expected:
        decrease_balance(cached, w.validator_index, w.amount)
    if expected:
        state.next_withdrawal_index = expected[-1].index + 1
    n = len(flat)
    if len(expected) == p.MAX_WITHDRAWALS_PER_PAYLOAD:
        # full payload: next sweep starts after the last withdrawn validator
        state.next_withdrawal_validator_index = (
            expected[-1].validator_index + 1
        ) % n
    else:
        # bounded sweep exhausted: advance cursor by the sweep bound
        state.next_withdrawal_validator_index = (
            state.next_withdrawal_validator_index
            + min(n, p.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)
        ) % n


# --- BLS → execution credential change ---------------------------------------

def bls_to_execution_change_signing_root(config, state, message) -> bytes:
    """Signed under the GENESIS fork version regardless of current fork
    (spec process_bls_to_execution_change) so changes sign once, forever."""
    domain = compute_domain(
        DOMAIN_BLS_TO_EXECUTION_CHANGE,
        config.GENESIS_FORK_VERSION,
        bytes(state.genesis_validators_root),
    )
    return compute_signing_root(message.hash_tree_root(), domain)


def process_bls_to_execution_change(cached, signed_change, verify_signatures=True):
    state = cached.state
    change = signed_change.message
    idx = change.validator_index
    _require(idx < len(state.validators), "unknown validator")
    validator = state.validators[idx]
    wc = bytes(validator.withdrawal_credentials)
    _require(wc[:1] == BLS_WITHDRAWAL_PREFIX, "not a BLS credential")
    _require(
        wc[1:] == sha256(bytes(change.from_bls_pubkey))[1:],
        "credential does not match from_bls_pubkey",
    )
    if verify_signatures:
        root = bls_to_execution_change_signing_root(cached.config, state, change)
        pk = bls.PublicKey.from_bytes(bytes(change.from_bls_pubkey))
        sig = bls.Signature.from_bytes(bytes(signed_change.signature))
        _require(bls.verify(pk, root, sig), "bad bls_to_execution_change signature")
    new_wc = (
        ETH1_ADDRESS_WITHDRAWAL_PREFIX
        + b"\x00" * 11
        + bytes(change.to_execution_address)
    )
    validator.withdrawal_credentials = new_wc
    # keep the flat column in lockstep (it is the hashing source of truth
    # and sync_to_state writes it back over the SSZ objects)
    import numpy as np

    cached.flat.withdrawal_credentials[idx] = np.frombuffer(new_wc, np.uint8)


# --- epoch: historical summaries ---------------------------------------------

def process_historical_summaries_update(cached, types) -> None:
    """Capella replaces HistoricalBatch accumulation with light
    HistoricalSummary roots (block/state roots only)."""
    p, state = cached.preset, cached.state
    next_epoch = cached.current_epoch + 1
    if next_epoch % (p.SLOTS_PER_HISTORICAL_ROOT // p.SLOTS_PER_EPOCH) == 0:
        from .bellatrix import _field_root

        state.historical_summaries.append(
            types.HistoricalSummary(
                block_summary_root=_field_root(state, "block_roots"),
                state_summary_root=_field_root(state, "state_roots"),
            )
        )


# --- fork upgrade ------------------------------------------------------------

def upgrade_state_to_capella(config, preset, pre, capella_types):
    """Spec upgrade_to_capella: carry bellatrix fields, extend the payload
    header with an empty withdrawals root, zero the withdrawal cursors."""
    pre = pre.copy()
    post = capella_types.BeaconState()
    skip = {
        "latest_execution_payload_header",
        "fork",
        "next_withdrawal_index",
        "next_withdrawal_validator_index",
        "historical_summaries",
    }
    for name, _ in post.fields:
        if name in skip:
            continue
        setattr(post, name, getattr(pre, name))
    old = pre.latest_execution_payload_header
    post.latest_execution_payload_header = capella_types.ExecutionPayloadHeader(
        parent_hash=bytes(old.parent_hash),
        fee_recipient=bytes(old.fee_recipient),
        state_root=bytes(old.state_root),
        receipts_root=bytes(old.receipts_root),
        logs_bloom=bytes(old.logs_bloom),
        prev_randao=bytes(old.prev_randao),
        block_number=old.block_number,
        gas_limit=old.gas_limit,
        gas_used=old.gas_used,
        timestamp=old.timestamp,
        extra_data=bytes(old.extra_data),
        base_fee_per_gas=old.base_fee_per_gas,
        block_hash=bytes(old.block_hash),
        transactions_root=bytes(old.transactions_root),
        withdrawals_root=b"\x00" * 32,
    )
    post.next_withdrawal_index = 0
    post.next_withdrawal_validator_index = 0
    post.historical_summaries = []
    post.fork = type(pre.fork)(
        previous_version=bytes(pre.fork.current_version),
        current_version=config.CAPELLA_FORK_VERSION,
        epoch=util.compute_epoch_at_slot(pre.slot, preset.SLOTS_PER_EPOCH),
    )
    return post
