"""Consensus state transition — the pure core (SURVEY.md §2 `state-transition`).

Architecture: where the reference reaches eth2fastspec-level speed with
persistent-tree views + epoch caches (`packages/state-transition/src/cache/`),
this package keeps consensus data in SSZ containers (bit-exact roots) and
mirrors the hot per-validator columns into flat numpy arrays
(`FlatValidators`) so epoch processing is vectorized array math — the same
flat-cache idea, realized as struct-of-arrays instead of object graphs, and
ready to lift onto device (int arrays are jit/vmap friendly).

All consensus arithmetic is host ints / numpy uint64 — never floats
(determinism requirement, SURVEY.md §7 hard part 8).
"""

from .cache import EpochContext, FlatValidators, CachedBeaconState  # noqa: F401
from .stf import state_transition, process_slots  # noqa: F401
from .genesis import (  # noqa: F401
    initialize_beacon_state_from_eth1,
    interop_genesis_state,
)
