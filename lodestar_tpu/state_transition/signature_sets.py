"""Extract every signature set from a signed block.

Reference: `state-transition/src/signatureSets/index.ts:24`
(getBlockSignatureSets) — the producer side of the batch-verification
pipeline: ~100 sets per mainnet block, fed to the (TPU) batch verifier in
one dispatch instead of per-op inline verification.

Each set carries a PRE-AGGREGATED pubkey (reference aggregates on the main
thread — `chain/bls/utils.ts:5`): aggregation is cheap G1 addition; the
pairing work stays on device.
"""

from __future__ import annotations

from ..bls import api as bls
from ..config.beacon_config import compute_signing_root
from ..params import (
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_RANDAO,
    DOMAIN_VOLUNTARY_EXIT,
)
from . import util
from .block import get_attesting_indices


def _pk(cached, index: int) -> bls.PublicKey:
    return bls.PublicKey.from_bytes(bytes(cached.flat.pubkeys[index]), validate=False)


def block_proposer_signature_set(cached, signed_block) -> bls.SignatureSet:
    block = signed_block.message
    domain = cached.config.get_domain(DOMAIN_BEACON_PROPOSER, block.slot)
    return bls.SignatureSet(
        pubkey=_pk(cached, block.proposer_index),
        message=compute_signing_root(block.hash_tree_root(), domain),
        signature=bytes(signed_block.signature),
    )


def randao_signature_set(cached, block) -> bls.SignatureSet:
    from .block import _epoch_signing_root

    epoch = util.compute_epoch_at_slot(block.slot, cached.preset.SLOTS_PER_EPOCH)
    domain = cached.config.get_domain(DOMAIN_RANDAO, block.slot)
    return bls.SignatureSet(
        pubkey=_pk(cached, block.proposer_index),
        message=_epoch_signing_root(epoch, domain),
        signature=bytes(block.body.randao_reveal),
    )


def indexed_attestation_signature_set(cached, indexed) -> bls.SignatureSet:
    domain = cached.config.get_domain(
        DOMAIN_BEACON_ATTESTER,
        util.compute_start_slot_at_epoch(
            indexed.data.target.epoch, cached.preset.SLOTS_PER_EPOCH
        ),
        indexed.data.target.epoch,
    )
    agg = bls.aggregate_pubkeys(
        [_pk(cached, i) for i in indexed.attesting_indices]
    )
    return bls.SignatureSet(
        pubkey=agg,
        message=compute_signing_root(indexed.data.hash_tree_root(), domain),
        signature=bytes(indexed.signature),
    )


def attestation_signature_set(cached, types, attestation) -> bls.SignatureSet:
    indexed = types.IndexedAttestation(
        attesting_indices=get_attesting_indices(
            cached, attestation.data, attestation.aggregation_bits
        ),
        data=attestation.data.copy(),
        signature=bytes(attestation.signature),
    )
    return indexed_attestation_signature_set(cached, indexed)


def proposer_slashing_signature_sets(cached, op) -> list[bls.SignatureSet]:
    sets = []
    for signed in (op.signed_header_1, op.signed_header_2):
        domain = cached.config.get_domain(
            DOMAIN_BEACON_PROPOSER, signed.message.slot
        )
        sets.append(
            bls.SignatureSet(
                pubkey=_pk(cached, signed.message.proposer_index),
                message=compute_signing_root(signed.message.hash_tree_root(), domain),
                signature=bytes(signed.signature),
            )
        )
    return sets


def attester_slashing_signature_sets(cached, op) -> list[bls.SignatureSet]:
    return [
        indexed_attestation_signature_set(cached, indexed)
        for indexed in (op.attestation_1, op.attestation_2)
    ]


def voluntary_exit_signature_set(cached, signed_exit) -> bls.SignatureSet:
    msg = signed_exit.message
    domain = cached.config.get_domain(
        DOMAIN_VOLUNTARY_EXIT,
        util.compute_start_slot_at_epoch(msg.epoch, cached.preset.SLOTS_PER_EPOCH),
        msg.epoch,
    )
    return bls.SignatureSet(
        pubkey=_pk(cached, msg.validator_index),
        message=compute_signing_root(msg.hash_tree_root(), domain),
        signature=bytes(signed_exit.signature),
    )


def sync_aggregate_signature_set(cached, block) -> bls.SignatureSet | None:
    """Sync-committee aggregate over the previous slot's block root
    (reference: syncCommittee signature set in signatureSets/). None when
    no bits are set — the mandatory infinity-signature rule for empty
    participation is structural and enforced inline by
    process_sync_aggregate regardless of signature verification."""
    from ..params import DOMAIN_SYNC_COMMITTEE

    state, p = cached.state, cached.preset
    aggregate = block.body.sync_aggregate
    bits = list(aggregate.sync_committee_bits)
    participants = [
        bytes(pk)
        for pk, b in zip(state.current_sync_committee.pubkeys, bits)
        if b
    ]
    if not participants:
        return None
    previous_slot = max(block.slot, 1) - 1
    domain = cached.config.get_domain(
        DOMAIN_SYNC_COMMITTEE,
        previous_slot,
        util.compute_epoch_at_slot(previous_slot, p.SLOTS_PER_EPOCH),
    )
    root = bytes(
        state.block_roots[previous_slot % p.SLOTS_PER_HISTORICAL_ROOT]
    )
    agg = bls.aggregate_pubkeys(
        [bls.PublicKey.from_bytes(pk, validate=False) for pk in participants]
    )
    return bls.SignatureSet(
        pubkey=agg,
        message=compute_signing_root(root, domain),
        signature=bytes(aggregate.sync_committee_signature),
    )


def get_block_signature_sets(
    cached, types, signed_block, include_proposer: bool = True
) -> list[bls.SignatureSet]:
    """All sets for one block (reference getBlockSignatureSets). Deposits
    are excluded: their proofs/signatures verify inline with their own
    rules (invalid deposit sigs are skipped, not failed)."""
    block = signed_block.message
    body = block.body
    sets: list[bls.SignatureSet] = []
    if include_proposer:
        sets.append(block_proposer_signature_set(cached, signed_block))
    sets.append(randao_signature_set(cached, block))
    for op in body.proposer_slashings:
        sets.extend(proposer_slashing_signature_sets(cached, op))
    for op in body.attester_slashings:
        sets.extend(attester_slashing_signature_sets(cached, op))
    for att in body.attestations:
        sets.append(attestation_signature_set(cached, types, att))
    for op in body.voluntary_exits:
        sets.append(voluntary_exit_signature_set(cached, op))
    if cached.is_altair and hasattr(body, "sync_aggregate"):
        sync_set = sync_aggregate_signature_set(cached, block)
        if sync_set is not None:
            sets.append(sync_set)
    if cached.is_capella:
        for op in body.bls_to_execution_changes:
            sets.append(bls_to_execution_change_signature_set(cached, op))
    return sets


def bls_to_execution_change_signature_set(cached, signed_change) -> bls.SignatureSet:
    from .capella import bls_to_execution_change_signing_root

    return bls.SignatureSet(
        pubkey=bls.PublicKey.from_bytes(
            bytes(signed_change.message.from_bls_pubkey), validate=False
        ),
        message=bls_to_execution_change_signing_root(
            cached.config, cached.state, signed_change.message
        ),
        signature=bytes(signed_change.signature),
    )


# --- sync-committee gossip signature sets (validation/signatureSets/) -------

def sync_committee_message_signature_set(cached, msg) -> bls.SignatureSet:
    """DOMAIN_SYNC_COMMITTEE over the message's beacon_block_root, signed
    by the referenced validator (reference
    validation/signatureSets/syncCommittee.ts:10)."""
    from ..params import DOMAIN_SYNC_COMMITTEE

    p = cached.preset
    domain = cached.config.get_domain(
        DOMAIN_SYNC_COMMITTEE,
        msg.slot,
        util.compute_epoch_at_slot(msg.slot, p.SLOTS_PER_EPOCH),
    )
    return bls.SignatureSet(
        pubkey=_pk(cached, msg.validator_index),
        message=compute_signing_root(bytes(msg.beacon_block_root), domain),
        signature=bytes(msg.signature),
    )


def sync_selection_proof_signature_set(cached, types, contribution_and_proof):
    """DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF over SyncAggregatorSelectionData
    (reference signatureSets/syncCommitteeSelectionProof.ts)."""
    from ..params import DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF

    p = cached.preset
    c = contribution_and_proof.contribution
    domain = cached.config.get_domain(
        DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
        c.slot,
        util.compute_epoch_at_slot(c.slot, p.SLOTS_PER_EPOCH),
    )
    selection_data = types.SyncAggregatorSelectionData(
        slot=c.slot, subcommittee_index=c.subcommittee_index
    )
    return bls.SignatureSet(
        pubkey=_pk(cached, contribution_and_proof.aggregator_index),
        message=compute_signing_root(selection_data.hash_tree_root(), domain),
        signature=bytes(contribution_and_proof.selection_proof),
    )


def contribution_and_proof_signature_set(cached, signed) -> bls.SignatureSet:
    """DOMAIN_CONTRIBUTION_AND_PROOF over the ContributionAndProof container
    (reference signatureSets/contributionAndProof.ts:10)."""
    from ..params import DOMAIN_CONTRIBUTION_AND_PROOF

    p = cached.preset
    slot = signed.message.contribution.slot
    domain = cached.config.get_domain(
        DOMAIN_CONTRIBUTION_AND_PROOF,
        slot,
        util.compute_epoch_at_slot(slot, p.SLOTS_PER_EPOCH),
    )
    return bls.SignatureSet(
        pubkey=_pk(cached, signed.message.aggregator_index),
        message=compute_signing_root(signed.message.hash_tree_root(), domain),
        signature=bytes(signed.signature),
    )


def sync_contribution_signature_set(
    cached, contribution, participant_pubkeys: list[bytes]
) -> bls.SignatureSet:
    """DOMAIN_SYNC_COMMITTEE over the contribution's beacon_block_root with
    the aggregate of the participant pubkeys (reference
    signatureSets/syncCommitteeContribution.ts:6)."""
    from ..params import DOMAIN_SYNC_COMMITTEE

    p = cached.preset
    domain = cached.config.get_domain(
        DOMAIN_SYNC_COMMITTEE,
        contribution.slot,
        util.compute_epoch_at_slot(contribution.slot, p.SLOTS_PER_EPOCH),
    )
    agg = bls.aggregate_pubkeys(
        [bls.PublicKey.from_bytes(pk, validate=False) for pk in participant_pubkeys]
    )
    return bls.SignatureSet(
        pubkey=agg,
        message=compute_signing_root(
            bytes(contribution.beacon_block_root), domain
        ),
        signature=bytes(contribution.signature),
    )
