"""Weak subjectivity: how stale may a checkpoint be before it cannot be
trusted (reference: `state-transition/src/util/weakSubjectivity.ts` —
isWithinWeakSubjectivityPeriod used by checkpoint sync,
`cli/src/cmds/beacon/initBeaconState.ts`).

Computes the spec's ws-period approximation from validator count and
average balance (safety decay D = 10%).
"""

from __future__ import annotations

SAFETY_DECAY = 10  # percent


def compute_weak_subjectivity_period(cached) -> int:
    """Spec compute_weak_subjectivity_period (phase0 ws-calc): epochs a
    checkpoint stays serviceable."""
    config, p, flat = cached.config, cached.preset, cached.flat
    ws_period = config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
    n = len(flat.active_indices(cached.current_epoch))
    if n == 0:
        return ws_period
    total = cached.flat.total_active_balance(
        cached.current_epoch, p.EFFECTIVE_BALANCE_INCREMENT
    )
    t = total // n // 10**9  # average balance in ETH
    T = p.MAX_EFFECTIVE_BALANCE // 10**9
    delta = _churn_limit(cached)
    Delta = p.MAX_DEPOSITS * p.SLOTS_PER_EPOCH
    D = SAFETY_DECAY

    if T * (200 + 3 * D) < t * (200 + 12 * D):
        epochs_for_validator_set_churn = (
            n * (t * (200 + 12 * D) - T * (200 + 3 * D)) // (600 * delta * (2 * t + T))
        )
        epochs_for_balance_top_ups = n * (200 + 3 * D) // (600 * Delta)
        ws_period += max(epochs_for_validator_set_churn, epochs_for_balance_top_ups)
    else:
        ws_period += 3 * n * D * t // (200 * Delta * (T - t))
    return ws_period


def _churn_limit(cached) -> int:
    from ..state_transition.block import get_validator_churn_limit

    return get_validator_churn_limit(cached)


def is_within_weak_subjectivity_period(cached, ws_checkpoint_epoch: int) -> bool:
    """Is the anchor checkpoint still safe to sync from at the current
    clock epoch? (reference: checkpoint-sync gate)"""
    ws_period = compute_weak_subjectivity_period(cached)
    return cached.current_epoch <= ws_checkpoint_epoch + ws_period
