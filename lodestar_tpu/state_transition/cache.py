"""Epoch-scoped caches + flat validator arrays.

The reference's speed comes from `EpochContext` / `EpochProcess`
(`state-transition/src/cache/epochContext.ts:80`, `epochProcess.ts:43`):
shufflings, proposers and flat effective-balance arrays computed once per
epoch. Here the same role is played by numpy struct-of-arrays — every
per-validator column is one contiguous uint64 array, so epoch processing
and committee math are SIMD passes rather than object-graph walks (and
can be lifted to device arrays wholesale).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..params import (
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    GENESIS_EPOCH,
)
from . import util

U64 = np.uint64


class FlatValidators:
    """Struct-of-arrays mirror of state.validators (+ balances).

    Columns are plain numpy arrays; `sync_to_state` writes mutated columns
    back into the SSZ containers before any hash_tree_root. Mutations during
    block/epoch processing go through BOTH (SSZ object is source of truth
    for roots; arrays are the compute representation)."""

    __slots__ = (
        "pubkeys", "effective_balance", "slashed",
        "activation_eligibility_epoch", "activation_epoch",
        "exit_epoch", "withdrawable_epoch", "balances",
        "withdrawal_credentials", "_sync_snap",
    )

    _SYNC_COLS = (
        "effective_balance", "slashed", "activation_eligibility_epoch",
        "activation_epoch", "exit_epoch", "withdrawable_epoch",
    )

    def __init__(self, state):
        vs = state.validators
        n = len(vs)
        self.pubkeys = [v.pubkey for v in vs]
        self.effective_balance = np.array([v.effective_balance for v in vs], U64)
        self.slashed = np.array([v.slashed for v in vs], bool)
        self.activation_eligibility_epoch = np.array(
            [v.activation_eligibility_epoch for v in vs], U64
        )
        self.activation_epoch = np.array([v.activation_epoch for v in vs], U64)
        self.exit_epoch = np.array([v.exit_epoch for v in vs], U64)
        self.withdrawable_epoch = np.array([v.withdrawable_epoch for v in vs], U64)
        self.balances = np.array(state.balances, U64)
        self.withdrawal_credentials = (
            np.frombuffer(
                b"".join(bytes(v.withdrawal_credentials) for v in vs), np.uint8
            ).reshape(n, 32).copy()
            if n
            else np.zeros((0, 32), np.uint8)
        )
        self._sync_snap = None

    def __len__(self):
        return len(self.effective_balance)

    def append(self, validator, balance: int):
        self.pubkeys.append(validator.pubkey)
        self.effective_balance = np.append(
            self.effective_balance, U64(validator.effective_balance)
        )
        self.slashed = np.append(self.slashed, bool(validator.slashed))
        self.activation_eligibility_epoch = np.append(
            self.activation_eligibility_epoch, U64(validator.activation_eligibility_epoch)
        )
        self.activation_epoch = np.append(
            self.activation_epoch, U64(validator.activation_epoch)
        )
        self.exit_epoch = np.append(self.exit_epoch, U64(validator.exit_epoch))
        self.withdrawable_epoch = np.append(
            self.withdrawable_epoch, U64(validator.withdrawable_epoch)
        )
        self.balances = np.append(self.balances, U64(balance))
        self.withdrawal_credentials = np.concatenate(
            [
                self.withdrawal_credentials,
                np.frombuffer(
                    bytes(validator.withdrawal_credentials), np.uint8
                ).reshape(1, 32),
            ]
        )

    def active_indices(self, epoch: int) -> np.ndarray:
        mask = util.active_mask(self.activation_epoch, self.exit_epoch, epoch)
        return np.nonzero(mask)[0].astype(np.int64)

    def total_active_balance(self, epoch: int, increment: int) -> int:
        mask = util.active_mask(self.activation_epoch, self.exit_epoch, epoch)
        total = int(self.effective_balance[mask].sum())
        return max(increment, total)

    def sync_to_state(self, state) -> None:
        """Write mutated columns back into the SSZ containers.

        Dirty-row write-back: columns are diffed against the last-synced
        snapshot (vectorized), so a per-slot sync where nothing changed is
        O(compare) instead of an O(n) Python object walk — the per-slot
        state-root path (`CachedBeaconState.hash_tree_root`) calls this
        every slot and the incremental hasher already made the hashing
        itself O(dirty·log n) (round-3 review finding)."""
        vs = state.validators
        n = len(self.effective_balance)
        snap = getattr(self, "_sync_snap", None)
        if (
            snap is None
            or len(snap["effective_balance"]) != n
            or len(vs) != n
            or len(state.balances) != n
        ):
            dirty = np.arange(n)
            bal_dirty = np.arange(n)
        else:
            changed = np.zeros(n, bool)
            for name in self._SYNC_COLS:
                changed |= snap[name] != getattr(self, name)
            from ..ssz.tree_cache import rows_ne

            changed |= rows_ne(snap["wc"], self.withdrawal_credentials)
            dirty = np.nonzero(changed)[0]
            bal_dirty = np.nonzero(snap["balances"] != self.balances)[0]
        if len(dirty):
            wc_bytes = self.withdrawal_credentials.tobytes()
            for i in dirty:
                i = int(i)
                v = vs[i]
                v.effective_balance = int(self.effective_balance[i])
                v.slashed = bool(self.slashed[i])
                v.activation_eligibility_epoch = int(
                    self.activation_eligibility_epoch[i]
                )
                v.activation_epoch = int(self.activation_epoch[i])
                v.exit_epoch = int(self.exit_epoch[i])
                v.withdrawable_epoch = int(self.withdrawable_epoch[i])
                v.withdrawal_credentials = wc_bytes[32 * i : 32 * i + 32]
        if len(bal_dirty) == n:
            state.balances = [int(b) for b in self.balances]
        else:
            for i in bal_dirty:
                state.balances[int(i)] = int(self.balances[i])
        # snapshot maintenance is O(dirty) when shapes are stable
        if snap is not None and len(snap["effective_balance"]) == n:
            for name in self._SYNC_COLS:
                snap[name][dirty] = getattr(self, name)[dirty]
            snap["wc"][dirty] = self.withdrawal_credentials[dirty]
            snap["balances"][bal_dirty] = self.balances[bal_dirty]
        else:
            self._sync_snap = {
                name: getattr(self, name).copy() for name in self._SYNC_COLS
            }
            self._sync_snap["wc"] = self.withdrawal_credentials.copy()
            self._sync_snap["balances"] = self.balances.copy()


@dataclass
class EpochShuffling:
    """Active-set shuffling for one epoch (reference: IEpochShuffling in
    epochContext — activeIndices + committees derived by slicing)."""

    epoch: int
    active_indices: np.ndarray  # (n_active,) validator indices
    shuffled: np.ndarray        # permuted active_indices
    committees_per_slot: int


class EpochContext:
    """Per-epoch derived data: shufflings for prev/current/next, proposer
    schedule for the current epoch, pubkey→index map
    (reference: `cache/epochContext.ts`, `pubkeyCache.ts`)."""

    def __init__(self, config, preset):
        self.config = config
        self.preset = preset
        self.pubkey_to_index: dict[bytes, int] = {}
        self.previous: EpochShuffling | None = None
        self.current: EpochShuffling | None = None
        self.next: EpochShuffling | None = None
        self.proposers: list[int] = []
        self.current_epoch = -1

    # -- construction --------------------------------------------------------

    def load_state(self, state, flat: FlatValidators):
        epoch = util.compute_epoch_at_slot(state.slot, self.preset.SLOTS_PER_EPOCH)
        self.sync_pubkeys(flat)
        self.current = self._build_shuffling(state, flat, epoch)
        prev_epoch = max(GENESIS_EPOCH, epoch - 1)
        self.previous = (
            self.current if prev_epoch == epoch
            else self._build_shuffling(state, flat, prev_epoch)
        )
        self.next = self._build_shuffling(state, flat, epoch + 1)
        self.current_epoch = epoch
        self._compute_proposers(state, flat, epoch)

    def sync_pubkeys(self, flat: FlatValidators):
        for i in range(len(self.pubkey_to_index), len(flat.pubkeys)):
            self.pubkey_to_index[bytes(flat.pubkeys[i])] = i

    def _build_shuffling(self, state, flat: FlatValidators, epoch: int):
        from . import stf as _stf

        if _stf._METRICS is not None:
            _stf._METRICS.shuffling_cache_misses_total.inc()
        active = flat.active_indices(epoch)
        seed = util.get_seed(state, epoch, DOMAIN_BEACON_ATTESTER, self.preset)
        shuffled = util.shuffle_list(active, seed, self.preset.SHUFFLE_ROUND_COUNT)
        cps = util.get_committee_count_per_slot(len(active), self.preset)
        return EpochShuffling(epoch, active, shuffled, cps)

    def _compute_proposers(self, state, flat: FlatValidators, epoch: int):
        seed_base = util.get_seed(state, epoch, DOMAIN_BEACON_PROPOSER, self.preset)
        from ..ssz.hashing import sha256

        start = util.compute_start_slot_at_epoch(epoch, self.preset.SLOTS_PER_EPOCH)
        self.proposers = [
            util.compute_proposer_index(
                flat.effective_balance,
                self.current.active_indices,
                sha256(seed_base + slot.to_bytes(8, "little")),
                self.preset,
            )
            for slot in range(start, start + self.preset.SLOTS_PER_EPOCH)
        ]

    # -- epoch rotation -------------------------------------------------------

    def rotate_epoch(self, state, flat: FlatValidators):
        """After `process_epoch`: prev←current, current←next, next rebuilt
        (reference: `epochContext.afterProcessEpoch` :454)."""
        epoch = self.current_epoch + 1
        self.previous = self.current
        self.current = self.next
        # current shuffling's committees_per_slot may change if the active
        # set changed during registry updates — rebuild honestly.
        self.next = self._build_shuffling(state, flat, epoch + 1)
        self.current_epoch = epoch
        self._compute_proposers(state, flat, epoch)

    # -- queries --------------------------------------------------------------

    def _shuffling_at(self, epoch: int) -> EpochShuffling:
        for sh in (self.previous, self.current, self.next):
            if sh is not None and sh.epoch == epoch:
                from . import stf as _stf

                if _stf._METRICS is not None:
                    _stf._METRICS.shuffling_cache_hits_total.inc()
                return sh
        raise ValueError(f"no shuffling cached for epoch {epoch}")

    def get_committee_count_per_slot(self, epoch: int) -> int:
        return self._shuffling_at(epoch).committees_per_slot

    def get_beacon_committee(self, slot: int, index: int) -> np.ndarray:
        epoch = util.compute_epoch_at_slot(slot, self.preset.SLOTS_PER_EPOCH)
        sh = self._shuffling_at(epoch)
        return util.compute_committee_slice(
            sh.shuffled,
            slot % self.preset.SLOTS_PER_EPOCH,
            index,
            sh.committees_per_slot,
            self.preset.SLOTS_PER_EPOCH,
        )

    def get_beacon_proposer(self, slot: int) -> int:
        epoch = util.compute_epoch_at_slot(slot, self.preset.SLOTS_PER_EPOCH)
        if epoch != self.current_epoch:
            raise ValueError("proposer requested outside current epoch")
        return self.proposers[slot % self.preset.SLOTS_PER_EPOCH]


class CachedBeaconState:
    """SSZ state + flat arrays + epoch context, travelling together
    (reference: `CachedBeaconState*`, `cache/stateCache.ts:112`)."""

    def __init__(self, config, state, preset=None):
        self.config = config
        self.preset = preset if preset is not None else config.preset
        self.state = state
        self.flat = FlatValidators(state)
        # Fork detection by state shape (each fork adds fields); drives the
        # per-fork branches in block/epoch processing (reference: ForkSeq
        # comparisons throughout state-transition/src).
        from ..params import ForkName, ForkSeq

        if hasattr(state, "next_withdrawal_index"):
            self.fork = ForkName.capella
        elif hasattr(state, "latest_execution_payload_header"):
            self.fork = ForkName.bellatrix
        elif hasattr(state, "previous_epoch_participation"):
            self.fork = ForkName.altair
        else:
            self.fork = ForkName.phase0
        self.fork_seq = ForkSeq[self.fork]
        # altair+: participation flags + inactivity scores mirror into flat
        # arrays (same pattern as FlatValidators)
        self.is_altair = self.fork_seq >= ForkSeq.altair
        self.is_execution = self.fork_seq >= ForkSeq.bellatrix
        self.is_capella = self.fork_seq >= ForkSeq.capella
        if self.is_altair:
            self.previous_participation = np.array(
                state.previous_epoch_participation, np.uint8
            )
            self.current_participation = np.array(
                state.current_epoch_participation, np.uint8
            )
            self.inactivity_scores = np.array(state.inactivity_scores, U64)
        self.epoch_ctx = EpochContext(config, self.preset)
        self.epoch_ctx.load_state(state, self.flat)
        self._hasher = None

    def hash_tree_root(self) -> bytes:
        """State root via the incremental columnar hasher (bit-identical to
        `state.hash_tree_root()`; re-hashes only dirty paths — the
        reference's ViewDU commit+hashTreeRoot analog,
        `stateTransition.ts:69-74`). Syncs flat columns first."""
        self.sync_flat()
        if self._hasher is None or self._hasher.state_class is not type(self.state):
            from .hasher import StateHasher

            self._hasher = StateHasher(self.state)
        return self._hasher.root(self)

    def sync_flat(self) -> None:
        """Write every flat-array column back into the SSZ state (called
        before any hash_tree_root)."""
        self.flat.sync_to_state(self.state)
        if self.is_altair:
            n = len(self.flat)
            # new validators since load: extend participation columns
            for name in ("previous_participation", "current_participation"):
                arr = getattr(self, name)
                if len(arr) < n:
                    setattr(
                        self,
                        name,
                        np.concatenate([arr, np.zeros(n - len(arr), np.uint8)]),
                    )
            if len(self.inactivity_scores) < n:
                self.inactivity_scores = np.concatenate(
                    [self.inactivity_scores, np.zeros(n - len(self.inactivity_scores), U64)]
                )
            self.state.previous_epoch_participation = [
                int(x) for x in self.previous_participation
            ]
            self.state.current_epoch_participation = [
                int(x) for x in self.current_participation
            ]
            self.state.inactivity_scores = [int(x) for x in self.inactivity_scores]

    @property
    def slot(self) -> int:
        return self.state.slot

    @property
    def current_epoch(self) -> int:
        return util.compute_epoch_at_slot(self.state.slot, self.preset.SLOTS_PER_EPOCH)

    @property
    def previous_epoch(self) -> int:
        return max(GENESIS_EPOCH, self.current_epoch - 1)

    def copy(self) -> "CachedBeaconState":
        self.sync_flat()  # flat arrays may be dirty mid-pipeline
        return CachedBeaconState(self.config, self.state.copy(), self.preset)

    def reload_state(self, state) -> None:
        """Adopt a new underlying state in place (fork upgrades swap the
        state container type mid-process_slots; reference rebuilds the
        CachedBeaconState on upgrade — stateTransition.ts processSlots)."""
        self.__init__(self.config, state, self.preset)
