"""Incremental BeaconState hashing from flat columns.

`stateTransition` ends in commit + hashTreeRoot per block
(reference `state-transition/src/stateTransition.ts:69-74`); the reference
affords that because `@chainsafe/ssz` ViewDU states re-hash only dirty
subtrees. This module plays that role TPU-framework-style: the hot
per-validator data already lives in numpy columns
(`cache.FlatValidators`), so each big list/vector field is hashed through
a cached `ssz.tree_cache.ChunkTree` whose leaf arrays are BUILT
VECTORIZED from the columns and DIFFED against the previous call — dirty
discovery is a numpy compare, re-hashing is O(dirty · log n) batched
SHA-256, and no object-graph walk ever happens.

Output is bit-identical to the plain `BeaconState.hash_tree_root()`
(differential-tested in tests/test_hasher.py); the plain path remains the
oracle.
"""

from __future__ import annotations

import numpy as np

from ..ssz.hashing import merkleize_chunks, mix_in_length
from ..ssz.tree_cache import ChunkTree, _hash_rows, rows_ne

U64 = np.uint64


def _u64_chunks(arr: np.ndarray) -> np.ndarray:
    """(n,) uint64 → (ceil(n/4), 32) uint8 packed little-endian chunks."""
    n = len(arr)
    nchunks = (n + 3) // 4
    buf = np.zeros(nchunks * 4, U64)
    buf[:n] = arr
    return buf.astype("<u8").view(np.uint8).reshape(nchunks, 32)


def _u8_chunks(arr: np.ndarray) -> np.ndarray:
    """(n,) uint8 → (ceil(n/32), 32) packed chunks."""
    n = len(arr)
    nchunks = (n + 31) // 32
    buf = np.zeros(nchunks * 32, np.uint8)
    buf[:n] = arr
    return buf.reshape(nchunks, 32)


def _bytes32_rows(values) -> np.ndarray:
    """List of 32-byte values → (n, 32) uint8."""
    if not values:
        return np.zeros((0, 32), np.uint8)
    return np.frombuffer(b"".join(bytes(v) for v in values), np.uint8).reshape(
        -1, 32
    )


def _u64_col_chunk(arr: np.ndarray) -> np.ndarray:
    """(n,) uint64 → (n, 32) uint8: one chunk per element (LE + zero pad)."""
    out = np.zeros((len(arr), 32), np.uint8)
    out[:, :8] = arr.astype("<u8").view(np.uint8).reshape(-1, 8)
    return out


class _ValidatorsHasher:
    """Cached per-validator roots + the registry list tree.

    Leaf chunks per validator (SSZ Validator container, 8 fields):
      0 pubkey root = H(pk[0:32] ‖ pk[32:48]·0¹⁶)   (append-only)
      1 withdrawal_credentials
      2 effective_balance  3 slashed  4 activation_eligibility_epoch
      5 activation_epoch   6 exit_epoch  7 withdrawable_epoch
    Dirty rows are found by comparing the numeric/wc columns against
    snapshots (vectorized); only dirty rows re-hash their 8-chunk subtree
    (3 batched SHA-256 levels)."""

    _NUM_COLS = (
        "effective_balance",
        "slashed",
        "activation_eligibility_epoch",
        "activation_epoch",
        "exit_epoch",
        "withdrawable_epoch",
    )

    def __init__(self, limit: int):
        self.tree = ChunkTree(limit)
        self.pk_roots = np.zeros((0, 32), np.uint8)
        self.roots = np.zeros((0, 32), np.uint8)
        self.snap: dict[str, np.ndarray] | None = None
        self.last_dirty = 0  # rows re-hashed by the latest root() call

    def _pubkey_roots_for(self, pubkeys, start: int) -> np.ndarray:
        raw = np.frombuffer(
            b"".join(bytes(pk) for pk in pubkeys[start:]), np.uint8
        ).reshape(-1, 48)
        pairs = np.zeros((len(raw), 64), np.uint8)
        pairs[:, :48] = raw
        return _hash_rows(pairs)

    def root(self, flat) -> bytes:
        n = len(flat)
        # no astype copies: the flat columns are already uint64/bool — the
        # per-call cost must stay at one compare pass, not O(n) memcpys
        cols = {
            name: np.asarray(getattr(flat, name), U64)[:n]
            for name in self._NUM_COLS
        }
        wc = flat.withdrawal_credentials[:n]
        # append-only pubkey roots
        if len(self.pk_roots) < n:
            new = self._pubkey_roots_for(flat.pubkeys, len(self.pk_roots))
            self.pk_roots = (
                np.concatenate([self.pk_roots, new]) if len(self.pk_roots) else new
            )
        # dirty rows: column diff vs snapshot (+ everything appended)
        if self.snap is None:
            dirty = np.arange(n)
        else:
            prev_n = len(self.snap["effective_balance"])
            keep = min(prev_n, n)
            changed = np.zeros(keep, bool)
            for name in self._NUM_COLS:
                changed |= self.snap[name][:keep] != cols[name][:keep]
            changed |= rows_ne(self.snap["wc"][:keep], wc[:keep])
            dirty = np.nonzero(changed)[0]
            if n > prev_n:
                dirty = np.concatenate([dirty, np.arange(prev_n, n)])
        if len(dirty) > 0:
            d = len(dirty)
            chunks = np.zeros((d, 8, 32), np.uint8)
            chunks[:, 0] = self.pk_roots[dirty]
            chunks[:, 1] = wc[dirty]
            chunks[:, 2] = _u64_col_chunk(cols["effective_balance"][dirty])
            chunks[:, 3, 0] = cols["slashed"][dirty].astype(np.uint8)
            chunks[:, 4] = _u64_col_chunk(
                cols["activation_eligibility_epoch"][dirty]
            )
            chunks[:, 5] = _u64_col_chunk(cols["activation_epoch"][dirty])
            chunks[:, 6] = _u64_col_chunk(cols["exit_epoch"][dirty])
            chunks[:, 7] = _u64_col_chunk(cols["withdrawable_epoch"][dirty])
            lvl = chunks.reshape(d * 4, 64)
            lvl = _hash_rows(lvl).reshape(d * 2, 64)  # 8 → 4
            lvl = _hash_rows(lvl).reshape(d, 64)      # 4 → 2
            new_roots = _hash_rows(lvl)               # 2 → 1
            if len(self.roots) < n:
                grown = np.zeros((n, 32), np.uint8)
                grown[: len(self.roots)] = self.roots
                self.roots = grown
            self.roots[dirty] = new_roots
        # snapshot maintenance is O(dirty), not O(n): untouched rows are
        # already equal to the snapshot by construction of `dirty`
        if self.snap is None or len(self.snap["effective_balance"]) != n:
            self.snap = {name: cols[name].copy() for name in self._NUM_COLS}
            self.snap["wc"] = wc.copy()
        elif len(dirty) > 0:
            for name in self._NUM_COLS:
                self.snap[name][dirty] = cols[name][dirty]
            self.snap["wc"][dirty] = wc[dirty]
        self.last_dirty = int(len(dirty))
        self.tree.update(self.roots[:n])
        return mix_in_length(self.tree.root(), n)


class StateHasher:
    """hash_tree_root of a CachedBeaconState from its flat columns, with
    cached trees for every O(n_validators)/O(history) field."""

    def __init__(self, state):
        self.state_class = type(state)
        self._trees: dict[str, ChunkTree] = {}
        self._validators: _ValidatorsHasher | None = None
        self._memo: dict[str, tuple[object, bytes]] = {}

    def _tree(self, name: str, limit_chunks: int) -> ChunkTree:
        t = self._trees.get(name)
        if t is None:
            t = self._trees[name] = ChunkTree(limit_chunks)
        return t

    def _tree_root(self, name, leaves, limit_chunks, length=None) -> bytes:
        t = self._tree(name, limit_chunks)
        t.update(leaves)
        r = t.root()
        return r if length is None else mix_in_length(r, length)

    def root(self, cached) -> bytes:
        state = cached.state
        flat = cached.flat
        chunks = []
        for name, typ in state.fields:
            if name == "validators":
                if self._validators is None:
                    self._validators = _ValidatorsHasher(typ.limit)
                r = self._validators.root(flat)
            elif name == "balances":
                arr = np.asarray(flat.balances, U64)
                r = self._tree_root(
                    name, _u64_chunks(arr), (typ.limit + 3) // 4, len(arr)
                )
            elif name == "inactivity_scores":
                arr = np.asarray(cached.inactivity_scores, U64)
                r = self._tree_root(
                    name, _u64_chunks(arr), (typ.limit + 3) // 4, len(arr)
                )
            elif name in (
                "previous_epoch_participation",
                "current_epoch_participation",
            ):
                arr = np.asarray(
                    cached.previous_participation
                    if name.startswith("previous")
                    else cached.current_participation,
                    np.uint8,
                )
                r = self._tree_root(
                    name, _u8_chunks(arr), (typ.limit + 31) // 32, len(arr)
                )
            elif name in ("block_roots", "state_roots", "randao_mixes"):
                rows = _bytes32_rows(getattr(state, name))
                r = self._tree_root(name, rows, typ.length)
            elif name == "slashings":
                arr = np.asarray(getattr(state, name), U64)
                r = self._tree_root(name, _u64_chunks(arr), (typ.length + 3) // 4)
            elif name == "historical_roots":
                vals = getattr(state, name)
                r = self._tree_root(
                    name, _bytes32_rows(vals), typ.limit, len(vals)
                )
            elif name in ("current_sync_committee", "next_sync_committee"):
                # replaced (never mutated in place) at period boundaries —
                # memo by identity, keeping a strong ref against id reuse
                val = getattr(state, name)
                hit = self._memo.get(name)
                if hit is not None and hit[0] is val:
                    r = hit[1]
                else:
                    r = typ.hash_tree_root(val)
                    self._memo[name] = (val, r)
            else:
                r = typ.hash_tree_root(getattr(state, name))
            chunks.append(r)
        return merkleize_chunks(b"".join(chunks))
