"""Unrealized justification/finalization: what the FFG checkpoints WOULD
become if the current epoch ended right now.

Fork choice needs this to "pull up" tips from prior epochs (reference:
`computeUnrealizedCheckpoints` imported at `forkChoice.ts:22`, used at
`forkChoice.ts:423`; spec `compute_pulled_up_tip`). Unlike
`process_justification_and_finalization` this never mutates the state —
the result is a pair of plain `(epoch, root)` tuples.
"""

from __future__ import annotations

from ..params.constants import GENESIS_EPOCH, JUSTIFICATION_BITS_LENGTH, TIMELY_TARGET_FLAG_INDEX
from . import util
from .epoch import _get_block_root, summarize_attestations


def _has_flag(participation, index):
    from .altair import has_flag

    return has_flag(participation, index)


def compute_unrealized_checkpoints(cached, types):
    """-> ((justified_epoch, justified_root), (finalized_epoch, finalized_root)).

    Runs the justification weighing (phase0 pending attestations or
    altair+ participation flags, chosen by state shape) against local
    variables only.
    """
    state, p, flat = cached.state, cached.preset, cached.flat
    current_epoch = cached.current_epoch
    cj = state.current_justified_checkpoint
    fin = state.finalized_checkpoint
    if current_epoch <= GENESIS_EPOCH + 1:
        return (
            (int(cj.epoch), bytes(cj.root)),
            (int(fin.epoch), bytes(fin.root)),
        )
    previous_epoch = cached.previous_epoch
    inc = p.EFFECTIVE_BALANCE_INCREMENT
    total = flat.total_active_balance(current_epoch, inc)

    if hasattr(state, "previous_epoch_attestations"):
        prev_summary = summarize_attestations(
            cached, state.previous_epoch_attestations, previous_epoch
        )
        prev_target_bal = max(
            inc, int(flat.effective_balance[prev_summary.target].sum())
        )
        if state.slot <= current_epoch * p.SLOTS_PER_EPOCH:
            # state sits exactly AT the epoch start: no current-epoch
            # attestation can be included yet (min inclusion delay), and
            # the epoch's start-slot block root is not in history —
            # summarizing would assert (the spec dodges this because its
            # 2/3 condition is vacuously false)
            curr_target_bal = inc
        else:
            curr_summary = summarize_attestations(
                cached, state.current_epoch_attestations, current_epoch
            )
            curr_target_bal = max(
                inc, int(flat.effective_balance[curr_summary.target].sum())
            )
    else:

        def target_balance(participation, epoch):
            active = util.active_mask(
                flat.activation_epoch, flat.exit_epoch, epoch
            )
            mask = (
                active
                & ~flat.slashed
                & _has_flag(participation, TIMELY_TARGET_FLAG_INDEX)
            )
            return max(inc, int(flat.effective_balance[mask].sum()))

        prev_target_bal = target_balance(
            cached.previous_participation, previous_epoch
        )
        curr_target_bal = target_balance(
            cached.current_participation, current_epoch
        )

    # pure weigh: same rules as _weigh_justification_and_finalization but
    # into locals
    old_prev_j = (
        int(state.previous_justified_checkpoint.epoch),
        bytes(state.previous_justified_checkpoint.root),
    )
    old_curr_j = (int(cj.epoch), bytes(cj.root))
    justified = old_curr_j
    finalized = (int(fin.epoch), bytes(fin.root))
    bits = [False] + list(state.justification_bits)[: JUSTIFICATION_BITS_LENGTH - 1]
    if prev_target_bal * 3 >= total * 2:
        justified = (previous_epoch, bytes(_get_block_root(state, previous_epoch, p)))
        bits[1] = True
    if (
        curr_target_bal * 3 >= total * 2
        and state.slot > current_epoch * p.SLOTS_PER_EPOCH
    ):
        justified = (current_epoch, bytes(_get_block_root(state, current_epoch, p)))
        bits[0] = True
    if all(bits[1:4]) and old_prev_j[0] + 3 == current_epoch:
        finalized = old_prev_j
    if all(bits[1:3]) and old_prev_j[0] + 2 == current_epoch:
        finalized = old_prev_j
    if all(bits[0:3]) and old_curr_j[0] + 2 == current_epoch:
        finalized = old_curr_j
    if all(bits[0:2]) and old_curr_j[0] + 1 == current_epoch:
        finalized = old_curr_j
    return justified, finalized
