"""Eth1 deposit tracking + eth1-data voting (SURVEY.md §2.2 `eth1/`).

Reference: `eth1/` — deposit-contract follower (`provider/eth1Provider.ts`
JSON-RPC), `eth1DepositsCache` / `eth1DataCache`, eth1-data vote picking
(`utils/eth1Vote.ts`-equivalent majority rule), deposit-root tracking.
The provider here is an interface; the dev tier uses `Eth1ProviderMock`
(the reference dev path injects deposits the same way).
"""

from .deposit_tracker import Eth1DepositTracker, Eth1ProviderMock  # noqa: F401
