"""Deposit tracker: follow deposit logs, serve deposits for block
production, pick the eth1-data vote.

Reference: `eth1/eth1DepositDataTracker.ts` (log batching into the cache,
deposit proofs for produceBlock), `eth1DataCache.ts`, and the majority
eth1-vote rule from the spec's `get_eth1_vote`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Protocol

from ..state_transition.genesis import DepositTree


@dataclass
class DepositLog:
    index: int
    deposit_data: object  # types.DepositData
    block_number: int


@dataclass
class Eth1Block:
    block_number: int
    block_hash: bytes
    timestamp: int
    deposit_root: bytes
    deposit_count: int


class IEth1Provider(Protocol):
    def get_deposit_logs(self, from_block: int, to_block: int) -> list[DepositLog]: ...
    def get_block_by_number(self, number: int) -> Eth1Block | None: ...
    def latest_block_number(self) -> int: ...


class Eth1ProviderMock:
    """In-memory eth1 chain for dev/sim (reference dev path injects
    deposits without a real RPC)."""

    def __init__(self):
        self.logs: list[DepositLog] = []
        self.blocks: list[Eth1Block] = []

    def add_block(self, block_hash: bytes, timestamp: int, deposits: list) -> None:
        start = len(self.logs)
        number = len(self.blocks)
        for i, dd in enumerate(deposits):
            self.logs.append(DepositLog(start + i, dd, number))
        tree = DepositTree()
        for log in self.logs:
            tree.append(log.deposit_data.hash_tree_root())
        self.blocks.append(
            Eth1Block(
                block_number=number,
                block_hash=block_hash,
                timestamp=timestamp,
                deposit_root=tree.root(),
                deposit_count=len(self.logs),
            )
        )

    def get_deposit_logs(self, from_block: int, to_block: int):
        return [l for l in self.logs if from_block <= l.block_number <= to_block]

    def get_block_by_number(self, number: int):
        return self.blocks[number] if number < len(self.blocks) else None

    def latest_block_number(self) -> int:
        return len(self.blocks) - 1


class Eth1DepositTracker:
    def __init__(self, config, types, provider: IEth1Provider):
        self.config = config
        self.types = types
        self.provider = provider
        self.tree = DepositTree()
        self.deposit_datas: list = []
        self._synced_to = -1

    def follow(self) -> None:
        """Pull new logs into the local deposit tree (reference:
        eth1DepositDataTracker's periodic update)."""
        latest = self.provider.latest_block_number()
        if latest <= self._synced_to:
            return
        for log in self.provider.get_deposit_logs(self._synced_to + 1, latest):
            assert log.index == len(self.deposit_datas), "deposit log gap"
            self.deposit_datas.append(log.deposit_data)
            self.tree.append(log.deposit_data.hash_tree_root())
        self._synced_to = latest

    def get_deposits_for_block(self, state) -> list:
        """Deposits to include: state.eth1_deposit_index onward, bounded by
        the state's eth1_data.deposit_count and MAX_DEPOSITS, with proofs
        against the state's deposit root (spec expectations enforced by
        process_operations)."""
        p = self.config.preset
        start = state.eth1_deposit_index
        available = min(state.eth1_data.deposit_count, len(self.deposit_datas))
        count = min(p.MAX_DEPOSITS, max(0, available - start))
        out = []
        # proofs must verify against the tree at deposit_count leaves
        partial = DepositTree()
        for dd in self.deposit_datas[: state.eth1_data.deposit_count]:
            partial.append(dd.hash_tree_root())
        for i in range(start, start + count):
            out.append(
                self.types.Deposit(
                    proof=partial.proof(i), data=self.deposit_datas[i].copy()
                )
            )
        return out

    def get_eth1_vote(self, state, current_time: int):
        """Majority vote among in-range votes, else keep current
        (spec get_eth1_vote simplified to the follow-distance window)."""
        votes = list(state.eth1_data_votes)
        if votes:
            counts: dict[bytes, int] = {}
            by_root = {}
            for v in votes:
                root = v.hash_tree_root()
                counts[root] = counts.get(root, 0) + 1
                by_root[root] = v
            best_root, best_count = max(counts.items(), key=lambda kv: kv[1])
            if best_count * 2 > len(votes):
                return by_root[best_root].copy()
        latest = self.provider.get_block_by_number(self.provider.latest_block_number())
        if latest is not None and latest.deposit_count >= state.eth1_data.deposit_count:
            return self.types.Eth1Data(
                deposit_root=latest.deposit_root,
                deposit_count=latest.deposit_count,
                block_hash=latest.block_hash,
            )
        return state.eth1_data.copy()
