"""Live eth1 JSON-RPC provider: the deposit-contract follower.

Reference: `eth1/provider/eth1Provider.ts` — batched `eth_getLogs` over
bounded block ranges with range-halving on truncated responses and
retries, `eth_getBlockByNumber`, head tracking behind
ETH1_FOLLOW_DISTANCE. Deposit logs are decoded from the deposit
contract's `DepositEvent(bytes,bytes,bytes,bytes,bytes)` ABI encoding
(reference `eth1/utils/depositContract.ts:parseDepositLog`).

Round-1 shipped only `Eth1ProviderMock` (VERDICT missing #5); this is the
real follower on the same `IEth1Provider` seam, reusing the plain
`http.client` JSON-RPC idiom of `execution/engine.ExecutionEngineHttp`.
"""

from __future__ import annotations

import json
import time

from .deposit_tracker import DepositLog, Eth1Block

# keccak256("DepositEvent(bytes,bytes,bytes,bytes,bytes)") — the single
# topic the deposit contract emits (depositContract.ts:13)
DEPOSIT_EVENT_TOPIC = (
    "0x649bbc62d0e31342afea4e5cd82d4049e7e1ee912fc0889aa790803be39038c5"
)
# deposit contract view selectors (IDepositContract)
_SEL_GET_DEPOSIT_ROOT = "0xc5f2892f"   # get_deposit_root()
_SEL_GET_DEPOSIT_COUNT = "0x621fd130"  # get_deposit_count()


def _q(n: int) -> str:
    """int → JSON-RPC QUANTITY."""
    return hex(n)


def _num(q: str) -> int:
    return int(q, 16)


def _abi_bytes_fields(data: bytes, n_fields: int) -> list[bytes]:
    """Decode n dynamic `bytes` fields from ABI-encoded log data
    (head: n offsets; tail: 32B length + padded payload each)."""
    out = []
    for i in range(n_fields):
        off = int.from_bytes(data[i * 32 : i * 32 + 32], "big")
        length = int.from_bytes(data[off : off + 32], "big")
        out.append(data[off + 32 : off + 32 + length])
    return out


def parse_deposit_log(types, log: dict) -> DepositLog:
    """One eth_getLogs entry → DepositLog (depositContract.ts semantics:
    amount and index are little-endian byte arrays)."""
    data = bytes.fromhex(log["data"].removeprefix("0x"))
    pubkey, wc, amount, signature, index = _abi_bytes_fields(data, 5)
    dd = types.DepositData(
        pubkey=pubkey,
        withdrawal_credentials=wc,
        amount=int.from_bytes(amount, "little"),
        signature=signature,
    )
    return DepositLog(
        index=int.from_bytes(index, "little"),
        deposit_data=dd,
        block_number=_num(log["blockNumber"]),
    )


class Eth1ProviderHttp:
    """IEth1Provider over plain JSON-RPC (no external deps).

    `latest_block_number` already applies ETH1_FOLLOW_DISTANCE so the
    tracker only ever sees the stable window (the reference applies the
    distance in the data tracker; keeping it here keeps the mock and the
    live provider interchangeable behind the same seam).
    """

    def __init__(
        self,
        config,
        types,
        host: str,
        port: int,
        *,
        deploy_block: int = 0,
        logs_batch_size: int = 1000,
        retries: int = 3,
        retry_delay: float = 0.5,
        timeout: float = 12.0,
        follow_distance: int | None = None,
        metrics=None,
    ):
        self.metrics = metrics
        self.config = config
        self.types = types
        self.host = host
        self.port = port
        self.deploy_block = deploy_block
        self.logs_batch_size = logs_batch_size
        self.retries = retries
        self.retry_delay = retry_delay
        self.timeout = timeout
        self.follow_distance = (
            follow_distance
            if follow_distance is not None
            else config.ETH1_FOLLOW_DISTANCE
        )
        self.contract = "0x" + config.DEPOSIT_CONTRACT_ADDRESS.hex()
        self._id = 0

    # -- transport ----------------------------------------------------------

    def _call_once(self, method: str, params: list):
        import http.client

        self._id += 1
        body = json.dumps(
            {"jsonrpc": "2.0", "id": self._id, "method": method, "params": params}
        ).encode()
        conn = http.client.HTTPConnection(self.host, self.port, timeout=self.timeout)
        try:
            conn.request(
                "POST", "/", body=body,
                headers={"Content-Type": "application/json"},
            )
            resp = json.loads(conn.getresponse().read())
        finally:
            conn.close()
        if "error" in resp:
            raise RuntimeError(f"{method}: {resp['error']}")
        return resp["result"]

    def _call(self, method: str, params: list):
        """JSON-RPC call through the shared retry helper (`utils/retry`):
        jittered exponential backoff replaces the round-1 ad-hoc loop
        whose synchronized sleeps stampeded a recovering endpoint."""
        from ..utils.retry import RetryPolicy, retry_call

        def _once():
            t0 = time.monotonic()
            out = self._call_once(method, params)
            if self.metrics is not None:
                self.metrics.eth1_request_seconds.observe(
                    time.monotonic() - t0, method=method
                )
            return out

        def _on_error(exc, attempt, will_retry):
            if self.metrics is not None:
                self.metrics.eth1_request_errors_total.inc()

        policy = RetryPolicy(
            max_attempts=self.retries,
            base_delay_s=self.retry_delay,
            retryable=lambda e: isinstance(e, (OSError, RuntimeError, ValueError)),
        )
        try:
            return retry_call(_once, policy=policy, on_error=_on_error)
        except (OSError, RuntimeError, ValueError) as e:
            raise RuntimeError(f"eth1 rpc {method} failed after retries: {e}")

    # -- IEth1Provider -------------------------------------------------------

    def latest_block_number(self) -> int:
        head = _num(self._call("eth_blockNumber", []))
        if self.metrics is not None:
            self.metrics.eth1_follow_distance.set(self.follow_distance)
            self.metrics.eth1_synced_block.set(max(self.deploy_block, head - self.follow_distance))
        return max(self.deploy_block, head - self.follow_distance)

    def get_deposit_logs(self, from_block: int, to_block: int) -> list[DepositLog]:
        """Chunked eth_getLogs; a failing/truncated chunk is halved and
        retried (eth1Provider.ts getDepositEvents + truncation fallback)."""
        out: list[DepositLog] = []
        frm = max(from_block, self.deploy_block)
        chunk = self.logs_batch_size
        while frm <= to_block:
            to = min(frm + chunk - 1, to_block)
            try:
                logs = self._call(
                    "eth_getLogs",
                    [
                        {
                            "fromBlock": _q(frm),
                            "toBlock": _q(to),
                            "address": self.contract,
                            "topics": [DEPOSIT_EVENT_TOPIC],
                        }
                    ],
                )
            except RuntimeError:
                if chunk == 1:
                    raise
                chunk = max(1, chunk // 2)  # halve and retry the range
                continue
            if self.metrics is not None:
                self.metrics.eth1_logs_batch_size.observe(len(logs))
                self.metrics.eth1_deposits_total.inc(len(logs))
            out.extend(parse_deposit_log(self.types, lg) for lg in logs)
            frm = to + 1
        out.sort(key=lambda l: l.index)
        return out

    def get_block_by_number(self, number: int) -> Eth1Block | None:
        raw = self._call("eth_getBlockByNumber", [_q(number), False])
        if raw is None:
            return None
        root = self._call(
            "eth_call",
            [{"to": self.contract, "data": _SEL_GET_DEPOSIT_ROOT}, _q(number)],
        )
        count_raw = self._call(
            "eth_call",
            [{"to": self.contract, "data": _SEL_GET_DEPOSIT_COUNT}, _q(number)],
        )
        # get_deposit_count returns ABI-encoded dynamic bytes8 (LE count)
        count_bytes = bytes.fromhex(count_raw.removeprefix("0x"))
        if len(count_bytes) > 8:
            count_bytes = _abi_bytes_fields(count_bytes, 1)[0]
        count = int.from_bytes(count_bytes[:8], "little")
        return Eth1Block(
            block_number=_num(raw["number"]),
            block_hash=bytes.fromhex(raw["hash"].removeprefix("0x")),
            timestamp=_num(raw["timestamp"]),
            deposit_root=bytes.fromhex(root.removeprefix("0x"))[:32],
            deposit_count=count,
        )
