"""Terminal PoW block tracker for the merge transition.

Reference: `eth1/eth1MergeBlockTracker.ts` — while bellatrix is scheduled
but the chain has not merged, poll the eth1 endpoint for the first block
whose total difficulty crosses TERMINAL_TOTAL_DIFFICULTY with a parent
still below it; that block's hash becomes the first execution payload's
parent (`prepareExecutionPayload`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Protocol

from ..utils.logger import get_logger

log = get_logger("eth1-merge")


@dataclass
class PowBlock:
    block_hash: bytes
    parent_hash: bytes
    total_difficulty: int
    number: int = 0


class IPowProvider(Protocol):
    def get_pow_block(self, block_hash: bytes) -> PowBlock | None: ...
    def latest_pow_block(self) -> PowBlock | None: ...


class PowProviderMock:
    """In-memory PoW chain for tests (role of the mocked eth1 provider)."""

    def __init__(self):
        self.blocks: dict[bytes, PowBlock] = {}
        self.head: bytes | None = None

    def add_block(self, block_hash: bytes, parent_hash: bytes, total_difficulty: int):
        number = 0
        parent = self.blocks.get(parent_hash)
        if parent is not None:
            number = parent.number + 1
        self.blocks[block_hash] = PowBlock(
            block_hash, parent_hash, total_difficulty, number
        )
        self.head = block_hash

    def get_pow_block(self, block_hash: bytes) -> PowBlock | None:
        return self.blocks.get(block_hash)

    def latest_pow_block(self) -> PowBlock | None:
        return self.blocks.get(self.head) if self.head else None


class Eth1MergeBlockTracker:
    """Finds and caches the terminal PoW block (status: PRE_MERGE →
    SEARCHING → FOUND, reference StatusCode)."""

    def __init__(self, config, provider: IPowProvider):
        self.ttd = config.TERMINAL_TOTAL_DIFFICULTY
        self.terminal_block_hash = config.TERMINAL_BLOCK_HASH
        self.provider = provider
        self.terminal_block: PowBlock | None = None

    def is_valid_terminal_pow_block(self, block: PowBlock) -> bool:
        """Spec is_valid_terminal_pow_block: block crossed TTD, parent did
        not (genesis parent counts as below)."""
        if block.total_difficulty < self.ttd:
            return False
        parent = self.provider.get_pow_block(block.parent_hash)
        return parent is None or parent.total_difficulty < self.ttd

    def get_terminal_pow_block(self) -> PowBlock | None:
        """Poll step: walk back from the head to the first TTD-crossing
        block. Cached once found (the terminal block never changes)."""
        if self.terminal_block is not None:
            return self.terminal_block
        # explicit override (TERMINAL_BLOCK_HASH configured non-zero)
        if self.terminal_block_hash != b"\x00" * 32:
            block = self.provider.get_pow_block(self.terminal_block_hash)
            if block is not None:
                self.terminal_block = block
            return self.terminal_block
        block = self.provider.latest_pow_block()
        while block is not None and block.total_difficulty >= self.ttd:
            parent = self.provider.get_pow_block(block.parent_hash)
            if parent is None or parent.total_difficulty < self.ttd:
                log.info(
                    "terminal PoW block found: %s (TD %d)",
                    block.block_hash.hex()[:12],
                    block.total_difficulty,
                )
                self.terminal_block = block
                return block
            block = parent
        return None
