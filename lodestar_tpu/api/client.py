"""REST client generated from the route table.

Reference: `api/src/.../client/httpClient.ts` (cross-fetch based typed
client). Methods are generated per route: positional args fill path
params, `query=`/`body=` keywords pass through.
"""

from __future__ import annotations

import http.client
import json
import re
from urllib.parse import urlencode

from .routes import API_ROUTES


class ApiClientError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"{status}: {message}")
        self.status = status


class BeaconApiClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 5052, timeout: float = 30.0):
        self.host = host
        self.port = port
        self.timeout = timeout
        for route in API_ROUTES:
            setattr(self, route.operation_id, self._make_method(route))

    def _make_method(self, route):
        path_params = re.findall(r"\{(\w+)\}", route.path)

        def call(*args, query: dict | None = None, body=None):
            if len(args) != len(path_params):
                raise TypeError(
                    f"{route.operation_id} takes {len(path_params)} path args"
                    f" ({path_params}), got {len(args)}"
                )
            path = route.path
            for name, value in zip(path_params, args):
                path = path.replace("{" + name + "}", str(value))
            if query:
                path += "?" + urlencode(query)
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout
            )
            try:
                payload = json.dumps(body).encode() if body is not None else None
                headers = {"Content-Type": "application/json"} if payload else {}
                conn.request(route.method, path, body=payload, headers=headers)
                resp = conn.getresponse()
                raw = resp.read()
                obj = json.loads(raw) if raw else {}
                if resp.status >= 400:
                    raise ApiClientError(resp.status, obj.get("message", ""))
                return obj.get("data")
            finally:
                conn.close()

        call.__name__ = route.operation_id
        return call


def stream_events(host: str, port: int, topics=None, timeout: float = 30.0):
    """SSE client generator (reference `eventSource.ts`): yields
    (event_name, payload_dict) until the server closes or `timeout`
    passes without a frame."""
    import http.client as _http
    import json as _json

    path = "/eth/v1/events"
    if topics:
        path += "?topics=" + ",".join(topics)
    conn = _http.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path, headers={"Accept": "text/event-stream"})
        resp = conn.getresponse()
        if resp.status != 200:
            raise RuntimeError(f"event stream refused: {resp.status}")
        event_name = None
        while True:
            line = resp.fp.readline()
            if not line:
                return
            line = line.decode().rstrip("\n")
            if line.startswith("event: "):
                event_name = line[len("event: "):]
            elif line.startswith("data: ") and event_name is not None:
                yield event_name, _json.loads(line[len("data: "):])
                event_name = None
    finally:
        conn.close()
