"""Server-side implementation of the Beacon API against a BeaconChain.

Reference: `beacon-node/src/api/impl/` — the same separation: route
handlers take parsed params and return JSON-able dicts; SSZ containers
cross the boundary via to_obj/from_obj (the reference's json types).
"""

from __future__ import annotations



class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


class BeaconApiImpl:
    VERSION = "lodestar-tpu/0.1.0"

    def __init__(self, config, types, chain, validator_service=None):
        self.config = config
        self.types = types
        self.chain = chain
        self.validator_service = validator_service

    # -- state resolution ----------------------------------------------------

    def _resolve_state(self, state_id: str):
        chain = self.chain
        if state_id == "head":
            return chain.head_state
        if state_id == "finalized":
            _, root = chain.finalized_checkpoint
            st = chain.state_cache.get_by_block_root(root)
            if st is None:
                raise ApiError(404, "finalized state not in hot cache")
            return st
        if state_id == "genesis":
            raise ApiError(501, "genesis state queries not retained")
        if state_id.startswith("0x"):
            st = chain.state_cache.get(bytes.fromhex(state_id[2:]))
            if st is None:
                raise ApiError(404, "state not found")
            return st
        raise ApiError(400, f"unsupported state_id {state_id}")

    def _resolve_block(self, block_id: str):
        chain = self.chain
        if block_id == "head":
            root = chain.head_root
        elif block_id == "finalized":
            _, root = chain.finalized_checkpoint
        elif block_id.startswith("0x"):
            root = bytes.fromhex(block_id[2:])
        else:
            raise ApiError(400, f"unsupported block_id {block_id}")
        signed = chain.blocks.get(root) or chain.finalized_blocks.get(root)
        if signed is None:
            signed = self.chain.db.get_archived_block_by_root(root)
        if signed is None:
            raise ApiError(404, "block not found")
        return root, signed

    # -- beacon --------------------------------------------------------------

    def getGenesis(self, params, query, body):
        state = self.chain.head_state.state
        return {
            "genesis_time": str(state.genesis_time),
            "genesis_validators_root": "0x" + bytes(state.genesis_validators_root).hex(),
            "genesis_fork_version": "0x" + self.config.GENESIS_FORK_VERSION.hex(),
        }

    def getStateRoot(self, params, query, body):
        st = self._resolve_state(params["state_id"])
        return {"root": "0x" + st.state.hash_tree_root().hex()}

    def getStateFinalityCheckpoints(self, params, query, body):
        st = self._resolve_state(params["state_id"]).state
        cp = lambda c: {"epoch": str(c.epoch), "root": "0x" + bytes(c.root).hex()}
        return {
            "previous_justified": cp(st.previous_justified_checkpoint),
            "current_justified": cp(st.current_justified_checkpoint),
            "finalized": cp(st.finalized_checkpoint),
        }

    def _validator_entry(self, st, idx: int):
        v = st.state.validators[idx]
        return {
            "index": str(idx),
            "balance": str(st.state.balances[idx]),
            "status": _validator_status(v, st.current_epoch),
            "validator": v.to_obj(),
        }

    def getStateValidators(self, params, query, body):
        st = self._resolve_state(params["state_id"])
        return [self._validator_entry(st, i) for i in range(len(st.state.validators))]

    def getStateValidator(self, params, query, body):
        st = self._resolve_state(params["state_id"])
        vid = params["validator_id"]
        if vid.startswith("0x"):
            idx = st.epoch_ctx.pubkey_to_index.get(bytes.fromhex(vid[2:]))
            if idx is None:
                raise ApiError(404, "unknown pubkey")
        else:
            idx = int(vid)
            if idx >= len(st.state.validators):
                raise ApiError(404, "index out of range")
        return self._validator_entry(st, idx)

    def getBlockHeader(self, params, query, body):
        root, signed = self._resolve_block(params["block_id"])
        msg = signed.message
        return {
            "root": "0x" + root.hex(),
            "canonical": True,
            "header": {
                "message": {
                    "slot": str(msg.slot),
                    "proposer_index": str(msg.proposer_index),
                    "parent_root": "0x" + bytes(msg.parent_root).hex(),
                    "state_root": "0x" + bytes(msg.state_root).hex(),
                    "body_root": "0x" + msg.body.hash_tree_root().hex(),
                },
                "signature": "0x" + bytes(signed.signature).hex(),
            },
        }

    def getBlockV2(self, params, query, body):
        _, signed = self._resolve_block(params["block_id"])
        version = self.config.get_fork_name_at_slot(signed.message.slot)
        return {"version": version, "data": signed.to_obj()}

    def getBlockRoot(self, params, query, body):
        root, _ = self._resolve_block(params["block_id"])
        return {"root": "0x" + root.hex()}

    def publishBlock(self, params, query, body):
        # decode with the fork's container for the block's slot (the wire
        # shape changes across forks)
        from ..types import get_types

        slot = int(body["message"]["slot"])
        fork = self.config.get_fork_name_at_slot(slot)
        types = get_types(self.config.preset).by_fork.get(fork, self.types)
        signed = types.SignedBeaconBlock.from_obj(body)
        self.chain.process_block(signed)
        return None

    def submitPoolAttestations(self, params, query, body):
        errors = []
        for i, obj in enumerate(body):
            try:
                att = self.types.Attestation.from_obj(obj)
                self.chain.on_aggregated_attestation(
                    att, att.data.hash_tree_root()
                )
            except Exception as e:  # collect per-item failures like the spec
                errors.append({"index": i, "message": str(e)})
        if errors:
            raise ApiError(400, f"{len(errors)} attestations failed")
        return None

    def submitPoolVoluntaryExit(self, params, query, body):
        """Validated like the gossip path (round-1 advisor finding: an
        unvalidated REST submission could poison the pool and invalidate
        the next produced block; reference runs the same validation in
        the pool API)."""
        from ..chain.validation import GossipAction, validate_gossip_voluntary_exit

        exit_ = self.types.SignedVoluntaryExit.from_obj(body)
        result = validate_gossip_voluntary_exit(self.chain, self.types, exit_)
        if result.action is GossipAction.REJECT:
            raise ApiError(400, f"invalid voluntary exit: {result.reason}")
        if result.action is GossipAction.ACCEPT:
            self.chain.op_pool.add_voluntary_exit(exit_)
        return None

    def submitPoolProposerSlashings(self, params, query, body):
        from ..chain.validation import (
            GossipAction,
            validate_gossip_proposer_slashing,
        )

        slashing = self.types.ProposerSlashing.from_obj(body)
        result = validate_gossip_proposer_slashing(self.chain, self.types, slashing)
        if result.action is GossipAction.REJECT:
            raise ApiError(400, f"invalid proposer slashing: {result.reason}")
        if result.action is GossipAction.ACCEPT:
            self.chain.op_pool.add_proposer_slashing(slashing)
        return None

    def submitPoolAttesterSlashings(self, params, query, body):
        from ..chain.validation import (
            GossipAction,
            validate_gossip_attester_slashing,
        )

        slashing = self.types.AttesterSlashing.from_obj(body)
        result = validate_gossip_attester_slashing(self.chain, self.types, slashing)
        if result.action is GossipAction.REJECT:
            raise ApiError(400, f"invalid attester slashing: {result.reason}")
        if result.action is GossipAction.ACCEPT:
            self.chain.op_pool.add_attester_slashing(slashing)
        return None

    def prepareBeaconProposer(self, params, query, body):
        """Fee-recipient registrations ahead of proposals (validator.ts
        prepareBeaconProposer → beaconProposerCache)."""
        epoch = self.chain.clock.current_epoch
        for entry in body or []:
            try:
                fee_recipient = bytes.fromhex(
                    entry["fee_recipient"].removeprefix("0x")
                )
                index = int(entry["validator_index"])
            except (KeyError, ValueError, AttributeError, TypeError) as e:
                raise ApiError(400, f"malformed preparation: {e}")
            if len(fee_recipient) != 20:
                raise ApiError(400, "fee_recipient must be 20 bytes")
            self.chain.beacon_proposer_cache.add(epoch, index, fee_recipient)
        return None

    def getPoolProposerSlashings(self, params, query, body):
        return [s.to_obj() for s in list(self.chain.op_pool.proposer_slashings.values())]

    def getPoolAttesterSlashings(self, params, query, body):
        return [s.to_obj() for s in list(self.chain.op_pool.attester_slashings)]

    # -- node ----------------------------------------------------------------

    def getNodeVersion(self, params, query, body):
        return {"version": self.VERSION}

    def getNodeIdentity(self, params, query, body):
        """Peer id + shareable ENR of the attached network (routes/node.ts
        getNetworkIdentity)."""
        network = getattr(self, "network", None)
        if network is None:
            return {"peer_id": "", "enr": "", "p2p_addresses": []}
        enr_text = ""
        if network.discovery is not None:
            from ..network.discovery import enr_to_text

            enr_text = enr_to_text(network.discovery.local_enr)
        addr = network.transport.listen_addr
        return {
            "peer_id": network.peer_id,
            "enr": enr_text,
            "p2p_addresses": [f"{addr[0]}:{addr[1]}"] if addr else [],
        }

    def getNodePeers(self, params, query, body):
        network = getattr(self, "network", None)
        if network is None:
            return []
        out = []
        for pid, info in network.peer_manager.peers.items():
            out.append(
                {
                    "peer_id": pid,
                    "state": "connected" if pid in network.transport.connections else "disconnected",
                    "direction": info.direction,
                }
            )
        return out

    def getSyncingStatus(self, params, query, body):
        head_slot = self.chain.head_state.state.slot
        clock_slot = self.chain.clock.current_slot
        return {
            "head_slot": str(head_slot),
            "sync_distance": str(max(0, clock_slot - head_slot)),
            "is_syncing": clock_slot > head_slot + 1,
            "is_optimistic": False,
        }

    def getHealth(self, params, query, body):
        return None  # 200

    # -- config --------------------------------------------------------------

    def getSpec(self, params, query, body):
        p = self.config.preset
        return {
            "SECONDS_PER_SLOT": str(self.config.SECONDS_PER_SLOT),
            "SLOTS_PER_EPOCH": str(p.SLOTS_PER_EPOCH),
            "MAX_EFFECTIVE_BALANCE": str(p.MAX_EFFECTIVE_BALANCE),
            "PRESET_BASE": p.PRESET_BASE,
            "DEPOSIT_CONTRACT_ADDRESS": "0x" + "00" * 20,
        }

    def getDepositContract(self, params, query, body):
        return {"chain_id": "1", "address": "0x" + "00" * 20}

    # -- validator -----------------------------------------------------------

    def getAttesterDuties(self, params, query, body):
        """Committee assignments for the requested validator indices,
        computed from the head epoch context (reference
        getAttesterDuties → getCommitteeAssignments)."""
        epoch = int(params["epoch"])
        wanted = {int(i) for i in body} if body else None
        st = self.chain.head_state
        ctx = st.epoch_ctx
        spe = self.config.preset.SLOTS_PER_EPOCH
        out = []
        try:
            count = ctx.get_committee_count_per_slot(epoch)
        except ValueError:
            raise ApiError(400, f"epoch {epoch} outside cached shuffling range")
        for slot in range(epoch * spe, (epoch + 1) * spe):
            for cidx in range(count):
                committee = ctx.get_beacon_committee(slot, cidx)
                for pos, vidx in enumerate(committee):
                    vidx = int(vidx)
                    if wanted is not None and vidx not in wanted:
                        continue
                    out.append(
                        {
                            "pubkey": "0x" + bytes(st.flat.pubkeys[vidx]).hex(),
                            "validator_index": str(vidx),
                            "committee_index": str(cidx),
                            "committee_length": str(len(committee)),
                            "committees_at_slot": str(count),
                            "validator_committee_index": str(pos),
                            "slot": str(slot),
                        }
                    )
        return out

    def getProposerDuties(self, params, query, body):
        st = self.chain.head_state
        epoch = int(params["epoch"])
        spe = self.config.preset.SLOTS_PER_EPOCH
        if epoch != st.epoch_ctx.current_epoch:
            # duties may be requested before the head crosses the epoch
            # boundary: advance a copy (reference: regen + proposer cache;
            # the prepared next-slot state usually makes this cheap)
            prepared = self.chain.prepare_next_slot.get_prepared(
                epoch * spe, self.chain.head_root
            )
            if prepared is not None:
                st = prepared
            elif epoch == st.epoch_ctx.current_epoch + 1:
                from ..state_transition import process_slots

                st = st.copy()
                process_slots(st, self.types, epoch * spe)
            else:
                raise ApiError(400, f"epoch {epoch} not derivable from head")
        ctx = st.epoch_ctx
        out = []
        for i, proposer in enumerate(ctx.proposers):
            pk = st.flat.pubkeys[proposer]
            out.append(
                {
                    "pubkey": "0x" + bytes(pk).hex(),
                    "validator_index": str(proposer),
                    "slot": str(epoch * spe + i),
                }
            )
        return out

    def produceBlockV2(self, params, query, body):
        slot = int(params["slot"])
        reveal = bytes.fromhex(query.get("randao_reveal", "")[2:])
        block = self.chain.produce_block(slot, randao_reveal=reveal)
        version = self.config.get_fork_name_at_slot(slot)
        return {"version": version, "data": block.to_obj()}

    def produceAttestationData(self, params, query, body):
        slot = int(query["slot"])
        index = int(query["committee_index"])
        st = self.chain.head_state
        epoch = slot // self.config.preset.SLOTS_PER_EPOCH
        start = epoch * self.config.preset.SLOTS_PER_EPOCH
        head_root = self.chain.head_root
        if start == st.state.slot:
            target_root = head_root
        else:
            target_root = bytes(
                st.state.block_roots[
                    start % self.config.preset.SLOTS_PER_HISTORICAL_ROOT
                ]
            )
        data = self.types.AttestationData(
            slot=slot,
            index=index,
            beacon_block_root=head_root,
            source=st.state.current_justified_checkpoint.copy(),
            target=self.types.Checkpoint(epoch=epoch, root=target_root),
        )
        return data.to_obj()

    def getAggregatedAttestation(self, params, query, body):
        slot = int(query["slot"])
        data_root = bytes.fromhex(query["attestation_data_root"][2:])
        got = self.chain.attestation_pool.get_aggregate(slot, data_root)
        if got is None:
            raise ApiError(404, "no aggregate for data root")
        data, bits, sig = got
        att = self.types.Attestation(
            aggregation_bits=bits, data=data.copy(), signature=sig.to_bytes()
        )
        return att.to_obj()

    def publishAggregateAndProofs(self, params, query, body):
        for obj in body:
            signed = self.types.SignedAggregateAndProof.from_obj(obj)
            agg = signed.message.aggregate
            self.chain.on_aggregated_attestation(agg, agg.data.hash_tree_root())
        return None

    def getLiveness(self, params, query, body):
        """Per-epoch liveness from the seen-caches (reference: lodestar's
        /eth/v1/validator/liveness used by doppelganger protection)."""
        epoch = int(params["epoch"])
        out = []
        for idx in body or []:
            idx = int(idx)
            live = self.chain.seen_attesters.is_known(epoch, idx)
            if not live:
                spe = self.config.preset.SLOTS_PER_EPOCH
                live = any(
                    self.chain.seen_block_proposers.is_known(slot, idx)
                    for slot in range(epoch * spe, (epoch + 1) * spe)
                )
            out.append({"index": str(idx), "is_live": live})
        return out

    # -- light client (reference routes/lightclient.ts over the chain's
    # LightClientServer) ------------------------------------------------------

    def getLightClientBootstrap(self, params, query, body):
        root = bytes.fromhex(params["block_root"].removeprefix("0x"))
        boot = self.chain.light_client_server.get_bootstrap(root)
        if boot is None:
            raise ApiError(404, "no bootstrap for block root")
        return boot.to_obj()

    def getLightClientUpdatesByRange(self, params, query, body):
        start = int(query.get("start_period", 0))
        count = min(int(query.get("count", 1)), 128)
        return [u.to_obj() for u in self.chain.light_client_server.get_updates(start, count)]

    def getLightClientFinalityUpdate(self, params, query, body):
        update = getattr(self.chain.light_client_server, "latest_finality_update", None)
        if update is None:
            raise ApiError(404, "no finality update available")
        return update.to_obj()

    def getLightClientOptimisticUpdate(self, params, query, body):
        update = getattr(self.chain.light_client_server, "latest_optimistic_update", None)
        if update is None:
            raise ApiError(404, "no optimistic update available")
        return update.to_obj()

    # -- debug ---------------------------------------------------------------

    def getStateV2(self, params, query, body):
        """Full SSZ state, hex-wrapped in JSON (reference serves
        application/octet-stream; same bytes either way). Checkpoint sync
        downloads its anchor through this route."""
        # serialize a private copy: sync_flat() writes flat columns back into
        # the state, and the live head may be mid-transition on another thread
        st = self._resolve_state(params["state_id"]).copy()
        st.sync_flat()
        return {
            "version": st.fork,
            "ssz": "0x" + type(st.state).ssz_type.serialize(st.state).hex(),
        }

    def getDebugChainHeadsV2(self, params, query, body):
        out = []
        for node in self.chain.fork_choice.proto.nodes:
            if node.best_child is None:
                out.append(
                    {
                        "slot": str(node.slot),
                        "root": "0x" + node.root.hex(),
                        "execution_optimistic": node.execution_status == "syncing",
                    }
                )
        return out


def _validator_status(v, epoch: int) -> str:
    """Condensed validator status (spec status taxonomy)."""
    from ..params import FAR_FUTURE_EPOCH

    if v.activation_epoch > epoch:
        return (
            "pending_queued"
            if v.activation_eligibility_epoch != FAR_FUTURE_EPOCH
            else "pending_initialized"
        )
    if epoch < v.exit_epoch:
        return "active_slashed" if v.slashed else "active_ongoing"
    if epoch < v.withdrawable_epoch:
        return "exited_slashed" if v.slashed else "exited_unslashed"
    return "withdrawal_possible"
