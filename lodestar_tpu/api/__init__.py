"""Eth Beacon API: typed routes shared by client and server (layer L3).

Reference: `packages/api` — route definitions (`api/src/beacon/routes/*`)
consumed by both the REST client (validator) and the fastify server glue
(beacon node). Here: `routes` declares the typed surface, `server` exposes
it over stdlib http.server, `client` speaks it over http.client — the same
route table drives both sides (single source of truth, like the reference).
"""

from .routes import API_ROUTES, Route  # noqa: F401
from .server import BeaconApiServer  # noqa: F401
from .client import BeaconApiClient  # noqa: F401
