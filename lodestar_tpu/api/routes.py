"""Typed Beacon API route table.

Reference: `api/src/beacon/routes/{beacon,node,validator,config,debug}.ts`
— each route = (method, path template, handler name). The server binds
handler names to an implementation object (`api/impl` equivalent:
`lodestar_tpu.api.impl.BeaconApiImpl`); the client generates request
methods from the same table.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Route:
    operation_id: str
    method: str  # GET | POST
    path: str    # /eth/v1/... with {param} templates


API_ROUTES: list[Route] = [
    # beacon (routes/beacon/*)
    Route("getGenesis", "GET", "/eth/v1/beacon/genesis"),
    Route("getStateRoot", "GET", "/eth/v1/beacon/states/{state_id}/root"),
    Route("getStateFinalityCheckpoints", "GET", "/eth/v1/beacon/states/{state_id}/finality_checkpoints"),
    Route("getStateValidators", "GET", "/eth/v1/beacon/states/{state_id}/validators"),
    Route("getStateValidator", "GET", "/eth/v1/beacon/states/{state_id}/validators/{validator_id}"),
    Route("getBlockHeader", "GET", "/eth/v1/beacon/headers/{block_id}"),
    Route("getBlockV2", "GET", "/eth/v2/beacon/blocks/{block_id}"),
    Route("getBlockRoot", "GET", "/eth/v1/beacon/blocks/{block_id}/root"),
    Route("publishBlock", "POST", "/eth/v1/beacon/blocks"),
    Route("submitPoolAttestations", "POST", "/eth/v1/beacon/pool/attestations"),
    Route("submitPoolVoluntaryExit", "POST", "/eth/v1/beacon/pool/voluntary_exits"),
    Route("submitPoolProposerSlashings", "POST", "/eth/v1/beacon/pool/proposer_slashings"),
    Route("submitPoolAttesterSlashings", "POST", "/eth/v1/beacon/pool/attester_slashings"),
    Route("getPoolProposerSlashings", "GET", "/eth/v1/beacon/pool/proposer_slashings"),
    Route("getPoolAttesterSlashings", "GET", "/eth/v1/beacon/pool/attester_slashings"),
    # node (routes/node.ts)
    Route("getNodeVersion", "GET", "/eth/v1/node/version"),
    Route("getNodeIdentity", "GET", "/eth/v1/node/identity"),
    Route("getNodePeers", "GET", "/eth/v1/node/peers"),
    Route("getSyncingStatus", "GET", "/eth/v1/node/syncing"),
    Route("getHealth", "GET", "/eth/v1/node/health"),
    # config (routes/config.ts)
    Route("getSpec", "GET", "/eth/v1/config/spec"),
    Route("getDepositContract", "GET", "/eth/v1/config/deposit_contract"),
    # validator (routes/validator.ts)
    Route("getAttesterDuties", "POST", "/eth/v1/validator/duties/attester/{epoch}"),
    Route("getProposerDuties", "GET", "/eth/v1/validator/duties/proposer/{epoch}"),
    Route("produceBlockV2", "GET", "/eth/v2/validator/blocks/{slot}"),
    Route("produceAttestationData", "GET", "/eth/v1/validator/attestation_data"),
    Route("getAggregatedAttestation", "GET", "/eth/v1/validator/aggregate_attestation"),
    Route("publishAggregateAndProofs", "POST", "/eth/v1/validator/aggregate_and_proofs"),
    Route("getLiveness", "POST", "/eth/v1/validator/liveness/{epoch}"),
    Route("prepareBeaconProposer", "POST", "/eth/v1/validator/prepare_beacon_proposer"),
    # debug (routes/debug.ts)
    Route("getDebugChainHeadsV2", "GET", "/eth/v2/debug/beacon/heads"),
    Route("getStateV2", "GET", "/eth/v2/debug/beacon/states/{state_id}"),
    # light client (routes/lightclient.ts)
    Route("getLightClientBootstrap", "GET", "/eth/v1/beacon/light_client/bootstrap/{block_root}"),
    Route("getLightClientUpdatesByRange", "GET", "/eth/v1/beacon/light_client/updates"),
    Route("getLightClientFinalityUpdate", "GET", "/eth/v1/beacon/light_client/finality_update"),
    Route("getLightClientOptimisticUpdate", "GET", "/eth/v1/beacon/light_client/optimistic_update"),
]

ROUTES_BY_ID = {r.operation_id: r for r in API_ROUTES}


def match_route(method: str, path: str):
    """Match a concrete request path against the table → (route, params)."""
    parts = path.rstrip("/").split("/")
    for route in API_ROUTES:
        if route.method != method:
            continue
        tparts = route.path.split("/")
        if len(tparts) != len(parts):
            continue
        params = {}
        ok = True
        for t, p in zip(tparts, parts):
            if t.startswith("{") and t.endswith("}"):
                params[t[1:-1]] = p
            elif t != p:
                ok = False
                break
        if ok:
            return route, params
    return None, {}
