"""Keymanager API (EIP-3030-style key management surface).

Reference: `api/src/keymanager/` routes + `validator` keymanager server —
list/import/delete local keystores, list/import/delete remote keys, and
slashing-protection interchange export on delete. Served on the VALIDATOR
process, guarded by a bearer token in the reference (token optional here).
"""

from __future__ import annotations


from ..validator.keystore import KeystoreError, decrypt_keystore
from .impl import ApiError
from .routes import Route

KEYMANAGER_ROUTES: list[Route] = [
    Route("listKeys", "GET", "/eth/v1/keystores"),
    Route("importKeystores", "POST", "/eth/v1/keystores"),
    Route("deleteKeys", "DELETE", "/eth/v1/keystores"),
    Route("listRemoteKeys", "GET", "/eth/v1/remotekeys"),
    Route("importRemoteKeys", "POST", "/eth/v1/remotekeys"),
    Route("deleteRemoteKeys", "DELETE", "/eth/v1/remotekeys"),
]


def match_keymanager_route(method: str, path: str):
    parts = path.rstrip("/").split("/")
    for route in KEYMANAGER_ROUTES:
        if route.method != method:
            continue
        if route.path.split("/") == parts:
            return route, {}
    return None, {}


class KeymanagerApiImpl:
    """Binds the keymanager routes to a ValidatorStore (+ optional
    external-signer clients for remote keys)."""

    def __init__(self, store, signer_factory=None):
        self.store = store
        # url → client factory for remote key import
        self.signer_factory = signer_factory

    # -- local keystores ------------------------------------------------------

    def listKeys(self, params, query, body):
        return [
            {"validating_pubkey": "0x" + pk.hex(), "derivation_path": "", "readonly": False}
            for pk in self.store.pubkeys
            if pk in self.store._keys
        ]

    def importKeystores(self, params, query, body):
        import json as _json

        from ..bls import api as bls

        keystores = body.get("keystores", [])
        passwords = body.get("passwords", [])
        if len(passwords) not in (1, len(keystores)):
            raise ApiError(400, "passwords must match keystores")
        # EIP-3076 interchange travels with the keys so migrated validators
        # keep their anti-slashing history (keymanager spec importKeystores)
        interchange = body.get("slashing_protection")
        if interchange:
            obj = _json.loads(interchange) if isinstance(interchange, str) else interchange
            slashing = getattr(self.store, "protection", None)
            if slashing is not None:
                slashing.import_interchange(obj)
        statuses = []
        for i, raw in enumerate(keystores):
            ks = _json.loads(raw) if isinstance(raw, str) else raw
            password = passwords[i] if i < len(passwords) else passwords[0]
            try:
                secret = decrypt_keystore(ks, password)
                sk = bls.SecretKey.from_bytes(secret)
                pk = sk.to_public_key().to_bytes()
                if self.store.has_pubkey(pk):
                    statuses.append({"status": "duplicate", "message": ""})
                else:
                    self.store.add_secret_key(sk)
                    statuses.append({"status": "imported", "message": ""})
            except (KeystoreError, ValueError) as e:
                statuses.append({"status": "error", "message": str(e)})
        return statuses

    def deleteKeys(self, params, query, body):
        statuses = []
        deleted = []
        for pk_hex in body.get("pubkeys", []):
            pk = bytes.fromhex(pk_hex.removeprefix("0x"))
            if self.store.remove_key(pk):
                statuses.append({"status": "deleted", "message": ""})
                deleted.append(pk)
            else:
                statuses.append({"status": "not_found", "message": ""})
        # EIP-3076 interchange for the deleted keys (reference exports the
        # slashing history so the keys can move safely)
        gvr = getattr(self.store.config, "genesis_validators_root", b"\x00" * 32)
        interchange = self.store.protection.export_interchange(gvr, deleted)
        return {"statuses": statuses, "slashing_protection": interchange}

    # -- remote keys ----------------------------------------------------------

    def listRemoteKeys(self, params, query, body):
        return [
            {"pubkey": "0x" + pk.hex(), "url": "", "readonly": False}
            for pk in self.store.pubkeys
            if pk in self.store._remote
        ]

    def importRemoteKeys(self, params, query, body):
        if self.signer_factory is None:
            raise ApiError(501, "no external signer factory configured")
        statuses = []
        for entry in body.get("remote_keys", []):
            pk = bytes.fromhex(entry["pubkey"].removeprefix("0x"))
            try:
                self.store.add_remote_key(pk, self.signer_factory(entry.get("url", "")))
                statuses.append({"status": "imported", "message": ""})
            except Exception as e:
                statuses.append({"status": "error", "message": str(e)})
        return statuses

    def deleteRemoteKeys(self, params, query, body):
        statuses = []
        for pk_hex in body.get("pubkeys", []):
            pk = bytes.fromhex(pk_hex.removeprefix("0x"))
            statuses.append(
                {"status": "deleted" if self.store.remove_key(pk) else "not_found",
                 "message": ""}
            )
        return statuses


def create_keymanager_server(store, host: str = "127.0.0.1", port: int = 0,
                             signer_factory=None, bearer_token: str | None = None,
                             token_file: str | None = None):
    """Keymanager REST server. The reference REQUIRES bearer auth here
    (`api/rest/index.ts` keymanager registration): if no token is given,
    one is generated; `token_file` persists it (reference writes
    `api-token.txt` under the datadir) so operators can find it."""
    from .server import BeaconApiServer

    if bearer_token is None:
        import secrets as _secrets

        bearer_token = "api-token-0x" + _secrets.token_hex(16)
        from ..utils.logger import get_logger

        if token_file is not None:
            get_logger("keymanager").info(
                "generated keymanager bearer token; written to %s", token_file
            )
        else:
            # never log the secret itself (it would persist a live
            # credential in log history) and never write files the
            # caller didn't ask for — point the operator at the handle
            get_logger("keymanager").warning(
                "generated keymanager bearer token but no token_file was "
                "given: pass token_file to persist it (the CLI wires "
                "<datadir>.api-token.txt); available as server.bearer_token"
            )
    if token_file is not None:
        import os

        # owner-only from creation — no world-readable window
        fd = os.open(token_file, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            f.write(bearer_token + "\n")
    impl = KeymanagerApiImpl(store, signer_factory)
    server = BeaconApiServer(
        impl, host=host, port=port, matcher=match_keymanager_route,
        bearer_token=bearer_token,
    )
    server.bearer_token = bearer_token
    server.token_file = token_file
    return server
