"""REST server over the route table (stdlib http.server).

Reference: `api/src/utils/server/genericJsonServer.ts` + fastify
registration in `beacon-node/src/api/rest/` — here a ThreadingHTTPServer
binds `routes.API_ROUTES` to a `BeaconApiImpl` by operation id.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl, urlparse

from .impl import ApiError
from .routes import match_route


class BeaconApiServer:
    def __init__(self, impl, host: str = "127.0.0.1", port: int = 0, matcher=None):
        """`matcher(method, path) -> (route, params)`: defaults to the
        beacon route table; the keymanager server passes its own."""
        self.impl = impl
        impl_ref = impl
        match = matcher if matcher is not None else match_route

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def _handle(self, method: str):
                parsed = urlparse(self.path)
                route, params = match(method, parsed.path)
                if route is None:
                    return self._send(404, {"message": "route not found"})
                query = dict(parse_qsl(parsed.query))
                body = None
                length = int(self.headers.get("Content-Length") or 0)
                if length:
                    try:
                        body = json.loads(self.rfile.read(length))
                    except json.JSONDecodeError:
                        return self._send(400, {"message": "invalid JSON body"})
                handler = getattr(impl_ref, route.operation_id, None)
                if handler is None:
                    return self._send(501, {"message": "not implemented"})
                try:
                    result = handler(params, query, body)
                except ApiError as e:
                    return self._send(e.status, {"message": e.message})
                except Exception as e:
                    return self._send(500, {"message": f"internal error: {e}"})
                if result is None:
                    return self._send(200, {})
                return self._send(200, {"data": result})

            def _send(self, status: int, obj):
                payload = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                self._handle("GET")

            def do_POST(self):
                self._handle("POST")

            def do_DELETE(self):
                self._handle("DELETE")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
