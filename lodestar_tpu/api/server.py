"""REST server over the route table (stdlib http.server).

Reference: `api/src/utils/server/genericJsonServer.ts` + fastify
registration in `beacon-node/src/api/rest/` — here a ThreadingHTTPServer
binds `routes.API_ROUTES` to a `BeaconApiImpl` by operation id.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl, urlparse

from .impl import ApiError
from .routes import match_route


class BeaconApiServer:
    def __init__(
        self, impl, host: str = "127.0.0.1", port: int = 0, matcher=None,
        metrics=None, bearer_token: str | None = None,
        cors_origin: str | None = None,
    ):
        """`matcher(method, path) -> (route, params)`: defaults to the
        beacon route table; the keymanager server passes its own.

        `bearer_token`: when set, every request must carry
        `Authorization: Bearer <token>` or is refused with 401 — the
        reference's fastify bearer-auth plugin (`api/rest/index.ts:52-58`,
        keymanager server requires it; beacon server opt-in).
        `cors_origin`: when set, responses carry CORS headers for that
        origin (`*` allowed) and OPTIONS preflights are answered —
        the reference's fastify-cors registration (`api/rest/index.ts:47-50`).
        """
        self.impl = impl
        impl_ref = impl
        match = matcher if matcher is not None else match_route
        metrics_ref = metrics
        token_ref = bearer_token
        cors_ref = cors_origin

        def _observe(path: str, status: int, seconds: float) -> None:
            if metrics_ref is None:
                return
            # bounded cardinality: the namespace segment, not the full path
            parts = path.split("/")
            ns = parts[2] if len(parts) > 2 else "root"
            metrics_ref.api_requests_total.inc(
                namespace=ns, status=f"{status // 100}xx"
            )
            metrics_ref.api_request_seconds.observe(seconds, namespace=ns)

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def _authorized(self) -> bool:
                if token_ref is None:
                    return True
                import hmac

                header = self.headers.get("Authorization", "")
                # constant-time compare (the reference's fastify
                # bearer-auth does the same) — no timing oracle on the
                # token; bytes (not str) because compare_digest raises on
                # non-ASCII str and headers arrive latin-1-decoded
                return hmac.compare_digest(
                    header.encode("latin-1", "replace"),
                    f"Bearer {token_ref}".encode(),
                )

            def _handle(self, method: str):
                if not self._authorized():
                    return self._send(
                        401, {"message": "missing or invalid bearer token"}
                    )
                parsed = urlparse(self.path)
                if method == "GET" and parsed.path == "/eth/v1/events":
                    return self._handle_events(parsed)
                route, params = match(method, parsed.path)
                if route is None:
                    return self._send(404, {"message": "route not found"})
                query = dict(parse_qsl(parsed.query))
                body = None
                length = int(self.headers.get("Content-Length") or 0)
                if length:
                    try:
                        body = json.loads(self.rfile.read(length))
                    except json.JSONDecodeError:
                        return self._send(400, {"message": "invalid JSON body"})
                handler = getattr(impl_ref, route.operation_id, None)
                if handler is None:
                    return self._send(501, {"message": "not implemented"})
                try:
                    result = handler(params, query, body)
                except ApiError as e:
                    return self._send(e.status, {"message": e.message})
                except Exception as e:
                    return self._send(500, {"message": f"internal error: {e}"})
                if result is None:
                    return self._send(200, {})
                return self._send(200, {"data": result})

            def _handle_events(self, parsed):
                """SSE event stream (reference `beacon/server/events.ts:25`):
                `event: <topic>\\ndata: <json>\\n\\n` frames until the client
                disconnects. Topics filtered by the ?topics= query."""
                import queue as _queue

                chain = getattr(impl_ref, "chain", None)
                emitter = getattr(chain, "emitter", None)
                if emitter is None:
                    return self._send(501, {"message": "no event source"})
                from ..chain.emitter import ChainEvent

                # both array forms: topics=a&topics=b and topics=a,b
                wanted = {
                    t
                    for key, value in parse_qsl(parsed.query)
                    if key == "topics"
                    for t in value.split(",")
                    if t
                } or {e.value for e in ChainEvent}
                q: _queue.Queue = _queue.Queue(maxsize=256)

                def on_event(event, payload):
                    if event.value in wanted:
                        try:
                            q.put_nowait((event.value, payload))
                        except _queue.Full:
                            pass  # slow consumer: drop rather than block import

                for e in ChainEvent:
                    emitter.on(e, on_event)
                if metrics_ref is not None:
                    metrics_ref.api_sse_subscribers.inc(1)
                try:
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self._cors_headers()
                    self.end_headers()
                    while True:
                        try:
                            name, payload = q.get(timeout=1.0)
                        except _queue.Empty:
                            self.wfile.write(b": keep-alive\n\n")
                            self.wfile.flush()
                            continue
                        frame = f"event: {name}\ndata: {json.dumps(payload)}\n\n"
                        self.wfile.write(frame.encode())
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass  # client went away
                finally:
                    if metrics_ref is not None:
                        metrics_ref.api_sse_subscribers.inc(-1)
                    for e in ChainEvent:
                        emitter.off(e, on_event)

            def _cors_headers(self):
                if cors_ref is not None:
                    self.send_header("Access-Control-Allow-Origin", cors_ref)
                    if cors_ref != "*":
                        self.send_header("Vary", "Origin")

            def _send(self, status: int, obj):
                import time as _t

                _observe(
                    urlparse(self.path).path, status,
                    _t.monotonic() - getattr(self, "_t0", _t.monotonic()),
                )
                payload = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self._cors_headers()
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                import time as _t

                self._t0 = _t.monotonic()
                self._handle("GET")

            def do_POST(self):
                import time as _t

                self._t0 = _t.monotonic()
                self._handle("POST")

            def do_DELETE(self):
                import time as _t

                self._t0 = _t.monotonic()
                self._handle("DELETE")

            def do_OPTIONS(self):
                # CORS preflight: no auth (browsers send it tokenless)
                self.send_response(204)
                self._cors_headers()
                self.send_header(
                    "Access-Control-Allow-Methods", "GET, POST, DELETE, OPTIONS"
                )
                self.send_header(
                    "Access-Control-Allow-Headers", "Content-Type, Authorization"
                )
                self.send_header("Access-Control-Max-Age", "86400")
                self.end_headers()

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
