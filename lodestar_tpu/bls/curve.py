"""BLS12-381 curve groups G1 (over Fq) and G2 (over Fq2).

Jacobian-coordinate arithmetic, scalar multiplication, subgroup membership,
and the ZCash point-serialization format (compressed/uncompressed with
C/I/S flag bits) used by Eth consensus. Oracle tier — clarity over speed.

E1: y² = x³ + 4        over Fq
E2: y² = x³ + 4(1+u)   over Fq2   (M-twist with ξ = 1+u)
"""

from __future__ import annotations

from typing import Generic, TypeVar

from .fields import P, R, X_PARAM, Fq, Fq2, XI

F = TypeVar("F", Fq, Fq2)

B1 = Fq(4)
B2 = Fq2.from_ints(4, 4)

# Standard generators (public BLS12-381 parameters)
G1_X = Fq(
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
)
G1_Y = Fq(
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1
)
G2_X = Fq2(
    Fq(0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8),
    Fq(0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E),
)
G2_Y = Fq2(
    Fq(0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801),
    Fq(0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE),
)


class Point(Generic[F]):
    """Jacobian point (X, Y, Z); Z=0 is the point at infinity."""

    __slots__ = ("x", "y", "z", "b")

    def __init__(self, x: F, y: F, z: F, b: F):
        self.x, self.y, self.z, self.b = x, y, z, b

    # -- constructors --
    @classmethod
    def from_affine(cls, x: F, y: F, b: F) -> "Point[F]":
        one = type(x).one()
        return cls(x, y, one, b)

    @classmethod
    def infinity(cls, field, b) -> "Point":
        return cls(field.one(), field.one(), field.zero(), b)

    def is_infinity(self) -> bool:
        return self.z.is_zero()

    def to_affine(self) -> tuple[F, F] | None:
        if self.is_infinity():
            return None
        zinv = self.z.inverse()
        zinv2 = zinv * zinv
        return (self.x * zinv2, self.y * zinv2 * zinv)

    def is_on_curve(self) -> bool:
        if self.is_infinity():
            return True
        aff = self.to_affine()
        assert aff is not None
        x, y = aff
        return y * y == x * x * x + self.b

    # -- group law (jacobian, a = 0) --
    def double(self) -> "Point[F]":
        if self.is_infinity():
            return self
        X1, Y1, Z1 = self.x, self.y, self.z
        A = X1 * X1
        B = Y1 * Y1
        C = B * B
        t = X1 + B
        D = t * t - A - C
        D = D + D
        E = A + A + A
        Fv = E * E
        X3 = Fv - (D + D)
        eight_c = C + C
        eight_c = eight_c + eight_c
        eight_c = eight_c + eight_c
        Y3 = E * (D - X3) - eight_c
        Z3 = (Y1 + Y1) * Z1
        return type(self)(X3, Y3, Z3, self.b)

    def __add__(self, other: "Point[F]") -> "Point[F]":
        if self.is_infinity():
            return other
        if other.is_infinity():
            return self
        X1, Y1, Z1 = self.x, self.y, self.z
        X2, Y2, Z2 = other.x, other.y, other.z
        Z1Z1 = Z1 * Z1
        Z2Z2 = Z2 * Z2
        U1 = X1 * Z2Z2
        U2 = X2 * Z1Z1
        S1 = Y1 * Z2 * Z2Z2
        S2 = Y2 * Z1 * Z1Z1
        if U1 == U2:
            if S1 == S2:
                return self.double()
            return type(self).infinity(type(X1), self.b)
        H = U2 - U1
        t = H + H
        I = t * t
        J = H * I
        r = S2 - S1
        r = r + r
        V = U1 * I
        X3 = r * r - J - (V + V)
        S1J = S1 * J
        Y3 = r * (V - X3) - (S1J + S1J)
        Z3 = ((Z1 + Z2) * (Z1 + Z2) - Z1Z1 - Z2Z2) * H
        return type(self)(X3, Y3, Z3, self.b)

    def __neg__(self) -> "Point[F]":
        return type(self)(self.x, -self.y, self.z, self.b)

    def __sub__(self, other: "Point[F]") -> "Point[F]":
        return self + (-other)

    def __mul__(self, scalar: int) -> "Point[F]":
        """Scalar multiplication (double-and-add; not constant-time — the
        oracle only handles public data except in tests)."""
        k = int(scalar)
        if k < 0:
            return (-self) * (-k)
        result = type(self).infinity(type(self.x), self.b)
        addend = self
        while k:
            if k & 1:
                result = result + addend
            addend = addend.double()
            k >>= 1
        return result

    __rmul__ = __mul__

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Point):
            return NotImplemented
        if type(self.x) is not type(other.x):  # G1 vs G2: never equal
            return False
        # (X1/Z1², Y1/Z1³) == (X2/Z2², Y2/Z2³) cross-multiplied
        if self.is_infinity() or other.is_infinity():
            return self.is_infinity() and other.is_infinity()
        Z1Z1 = self.z * self.z
        Z2Z2 = other.z * other.z
        return (
            self.x * Z2Z2 == other.x * Z1Z1
            and self.y * Z2Z2 * other.z == other.y * Z1Z1 * self.z
        )

    def __repr__(self) -> str:
        aff = self.to_affine()
        return f"{type(self).__name__}({aff!r})"


class PointG1(Point[Fq]):
    __slots__ = ()

    def __init__(self, x: Fq, y: Fq, z: Fq, b: Fq | None = None):
        super().__init__(x, y, z, b if b is not None else B1)

    @staticmethod
    def generator() -> "PointG1":
        return PointG1(G1_X, G1_Y, Fq.one())

    @staticmethod
    def zero() -> "PointG1":
        return PointG1(Fq.one(), Fq.one(), Fq.zero())

    def is_in_subgroup(self) -> bool:
        return (self * R).is_infinity()


class PointG2(Point[Fq2]):
    __slots__ = ()

    def __init__(self, x: Fq2, y: Fq2, z: Fq2, b: Fq2 | None = None):
        super().__init__(x, y, z, b if b is not None else B2)

    @staticmethod
    def generator() -> "PointG2":
        return PointG2(G2_X, G2_Y, Fq2.one())

    @staticmethod
    def zero() -> "PointG2":
        return PointG2(Fq2.one(), Fq2.one(), Fq2.zero())

    def is_in_subgroup(self) -> bool:
        return (self * R).is_infinity()

    def psi(self) -> "PointG2":
        """Untwist-Frobenius-twist endomorphism ψ (used for fast cofactor
        clearing, Budroni–Pintore)."""
        aff = self.to_affine()
        if aff is None:
            return self
        x, y = aff
        return PointG2(x.conjugate() * _PSI_CX, y.conjugate() * _PSI_CY, Fq2.one())


# ψ coefficients: untwist (x/w², y/w³), frobenius, retwist (·w², ·w³):
# ψ(x, y) = (conj(x)·w^(2p)/w² , conj(y)·w^(3p)/w³) with w^(p−1) expressible
# via ξ: w^(p−1) = ξ^((p−1)/6). So cx = ξ^((p−1)/3)⁻¹... computed directly:
# cx = 1/ξ^((p−1)/3), cy = 1/ξ^((p−1)/2).
_PSI_CX = XI.pow((P - 1) // 3).inverse()
_PSI_CY = XI.pow((P - 1) // 2).inverse()


_HALF_P = (P - 1) // 2


def _fq_lex_larger(y: Fq) -> bool:
    return y.n > _HALF_P


def _fq2_lex_larger(y: Fq2) -> bool:
    """ZCash convention: compare (c1, c0) lexicographically."""
    if y.c1.n != 0:
        return y.c1.n > _HALF_P
    return y.c0.n > _HALF_P


# --- ZCash serialization (the Eth consensus wire format) ---

_C_FLAG = 0x80  # compressed
_I_FLAG = 0x40  # infinity
_S_FLAG = 0x20  # sign (lexicographically larger y)


def g1_to_bytes(point: PointG1, compressed: bool = True) -> bytes:
    if not compressed:
        raise NotImplementedError("only compressed G1 serialization")
    if point.is_infinity():
        return bytes([_C_FLAG | _I_FLAG]) + b"\x00" * 47
    aff = point.to_affine()
    assert aff is not None
    x, y = aff
    data = bytearray(x.n.to_bytes(48, "big"))
    data[0] |= _C_FLAG
    if _fq_lex_larger(y):
        data[0] |= _S_FLAG
    return bytes(data)


def g1_from_bytes(data: bytes) -> PointG1:
    if len(data) != 48:
        raise ValueError(f"G1 compressed point must be 48 bytes, got {len(data)}")
    flags = data[0]
    if not flags & _C_FLAG:
        raise ValueError("G1: uncompressed deserialization not supported")
    if flags & _I_FLAG:
        if flags & _S_FLAG or any(data[1:]) or data[0] != (_C_FLAG | _I_FLAG):
            raise ValueError("G1: malformed infinity encoding")
        return PointG1.zero()
    xn = int.from_bytes(bytes([flags & 0x1F]) + data[1:], "big")
    if xn >= P:
        raise ValueError("G1: x not in field")
    x = Fq(xn)
    y2 = x * x * x + B1
    y = y2.sqrt()
    if y is None:
        raise ValueError("G1: x not on curve")
    if _fq_lex_larger(y) != bool(flags & _S_FLAG):
        y = -y
    return PointG1(x, y, Fq.one())


def g2_to_bytes(point: PointG2, compressed: bool = True) -> bytes:
    if not compressed:
        raise NotImplementedError("only compressed G2 serialization")
    if point.is_infinity():
        return bytes([_C_FLAG | _I_FLAG]) + b"\x00" * 95
    aff = point.to_affine()
    assert aff is not None
    x, y = aff
    data = bytearray(x.c1.n.to_bytes(48, "big") + x.c0.n.to_bytes(48, "big"))
    data[0] |= _C_FLAG
    if _fq2_lex_larger(y):
        data[0] |= _S_FLAG
    return bytes(data)


def g2_from_bytes(data: bytes) -> PointG2:
    if len(data) != 96:
        raise ValueError(f"G2 compressed point must be 96 bytes, got {len(data)}")
    flags = data[0]
    if not flags & _C_FLAG:
        raise ValueError("G2: uncompressed deserialization not supported")
    if flags & _I_FLAG:
        if flags & _S_FLAG or any(data[1:]) or data[0] != (_C_FLAG | _I_FLAG):
            raise ValueError("G2: malformed infinity encoding")
        return PointG2.zero()
    x1 = int.from_bytes(bytes([flags & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:], "big")
    if x0 >= P or x1 >= P:
        raise ValueError("G2: x not in field")
    x = Fq2.from_ints(x0, x1)
    y2 = x * x * x + B2
    y = y2.sqrt()
    if y is None:
        raise ValueError("G2: x not on curve")
    if _fq2_lex_larger(y) != bool(flags & _S_FLAG):
        y = -y
    return PointG2(x, y, Fq2.one())


def clear_cofactor_g2(point: PointG2) -> PointG2:
    """Map an E2(Fq2) point into the order-r subgroup G2.

    Budroni–Pintore endomorphism method (as referenced by RFC 9380 for the
    BLS12381G2 suites): h_eff·P = [x²−x−1]P + [x−1]ψ(P) + ψ²([2]P)
    with x the (negative) BLS parameter.
    """
    x = X_PARAM
    t1 = point * (x * x - x - 1)
    t2 = point.psi() * (x - 1)
    t3 = point.double().psi().psi()
    return t1 + t2 + t3
