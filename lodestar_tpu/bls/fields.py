"""BLS12-381 field towers over Python big ints (the CPU oracle tier).

Equivalent role of the supranational `blst` C library behind
`@chainsafe/blst` in the reference (SURVEY.md §2.3): this module is the
*correctness oracle* — written for clarity and auditability, not speed. The
TPU tier (lodestar_tpu/ops) is differentially tested against it.

Tower (standard for BLS12-381):
    Fq2  = Fq[u]  / (u² + 1)
    Fq6  = Fq2[v] / (v³ − ξ),  ξ = 1 + u
    Fq12 = Fq6[w] / (w² − v)         (so w⁶ = ξ)

All constants below are the standard public BLS12-381 parameters; nothing is
copied from the reference repo (which contains no field arithmetic — it calls
blst via FFI).
"""

from __future__ import annotations

# Base field modulus
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
# Subgroup order
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
# BLS parameter x (negative): p and r are polynomials in x.
X_PARAM = -0xD201000000010000


class Fq:
    """Prime field element (immutable)."""

    __slots__ = ("n",)

    def __init__(self, n: int):
        self.n = n % P

    def __add__(self, other: "Fq") -> "Fq":
        return Fq(self.n + other.n)

    def __sub__(self, other: "Fq") -> "Fq":
        return Fq(self.n - other.n)

    def __mul__(self, other: "Fq") -> "Fq":
        return Fq(self.n * other.n)

    def __neg__(self) -> "Fq":
        return Fq(-self.n)

    def square(self) -> "Fq":
        return Fq(self.n * self.n)

    def inverse(self) -> "Fq":
        if self.n == 0:
            raise ZeroDivisionError("Fq inverse of 0")
        return Fq(pow(self.n, P - 2, P))

    def pow(self, e: int) -> "Fq":
        return Fq(pow(self.n, e, P))

    def is_zero(self) -> bool:
        return self.n == 0

    def is_square(self) -> bool:
        return self.n == 0 or pow(self.n, (P - 1) // 2, P) == 1

    def sqrt(self) -> "Fq | None":
        """Square root for p ≡ 3 (mod 4); None if not a QR."""
        if self.n == 0:
            return Fq(0)
        cand = pow(self.n, (P + 1) // 4, P)
        if cand * cand % P == self.n:
            return Fq(cand)
        return None

    def sgn0(self) -> int:
        return self.n & 1

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Fq) and self.n == other.n

    def __hash__(self) -> int:
        return hash(("Fq", self.n))

    def __repr__(self) -> str:
        return f"Fq(0x{self.n:x})"

    @staticmethod
    def zero() -> "Fq":
        return Fq(0)

    @staticmethod
    def one() -> "Fq":
        return Fq(1)


class Fq2:
    """Fq[u]/(u²+1): c0 + c1·u."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: Fq, c1: Fq):
        self.c0 = c0
        self.c1 = c1

    @staticmethod
    def from_ints(a: int, b: int) -> "Fq2":
        return Fq2(Fq(a), Fq(b))

    def __add__(self, o: "Fq2") -> "Fq2":
        return Fq2(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fq2") -> "Fq2":
        return Fq2(self.c0 - o.c0, self.c1 - o.c1)

    def __mul__(self, o: "Fq2") -> "Fq2":
        # (a0 + a1 u)(b0 + b1 u) = a0b0 − a1b1 + (a0b1 + a1b0) u
        t0 = self.c0 * o.c0
        t1 = self.c1 * o.c1
        t2 = (self.c0 + self.c1) * (o.c0 + o.c1)
        return Fq2(t0 - t1, t2 - t0 - t1)

    def mul_scalar(self, k: Fq) -> "Fq2":
        return Fq2(self.c0 * k, self.c1 * k)

    def __neg__(self) -> "Fq2":
        return Fq2(-self.c0, -self.c1)

    def square(self) -> "Fq2":
        # (a + bu)² = (a+b)(a−b) + 2ab·u
        a, b = self.c0, self.c1
        return Fq2((a + b) * (a - b), Fq(2 * a.n * b.n))

    def conjugate(self) -> "Fq2":
        return Fq2(self.c0, -self.c1)

    def norm(self) -> Fq:
        return self.c0.square() + self.c1.square()

    def inverse(self) -> "Fq2":
        inv_norm = self.norm().inverse()
        return Fq2(self.c0 * inv_norm, -(self.c1 * inv_norm))

    def pow(self, e: int) -> "Fq2":
        if e < 0:
            return self.inverse().pow(-e)
        result = Fq2.one()
        base = self
        while e > 0:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero()

    def is_square(self) -> bool:
        # a is a square in Fq2 iff norm(a) is a square in Fq
        return self.norm().is_square()

    def sqrt(self) -> "Fq2 | None":
        """Square root in Fq2 (q = p² ≡ 9 mod 16): candidate a^((q+7)/16)
        corrected by a root of unity from {1, i, ω, iω} with ω² = i."""
        if self.is_zero():
            return Fq2.zero()
        cand = self.pow((P * P + 7) // 16)
        for root in _SQRT_CORRECTIONS:
            s = cand * root
            if s * s == self:
                return s
        return None

    def sgn0(self) -> int:
        # RFC 9380 sgn0 for m=2
        sign_0 = self.c0.n & 1
        zero_0 = self.c0.n == 0
        return sign_0 | (int(zero_0) & (self.c1.n & 1))

    def frobenius(self) -> "Fq2":
        # x^p = conjugate (u^p = -u since p ≡ 3 mod 4)
        return self.conjugate()

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Fq2) and self.c0 == other.c0 and self.c1 == other.c1

    def __hash__(self) -> int:
        return hash(("Fq2", self.c0.n, self.c1.n))

    def __repr__(self) -> str:
        return f"Fq2(0x{self.c0.n:x}, 0x{self.c1.n:x})"

    @staticmethod
    def zero() -> "Fq2":
        return Fq2(Fq(0), Fq(0))

    @staticmethod
    def one() -> "Fq2":
        return Fq2(Fq(1), Fq(0))


# ξ = 1 + u: the Fq6/Fq12 non-residue
XI = Fq2.from_ints(1, 1)

# sqrt corrections: {1, i, ω, iω} with i = sqrt(-1) = u, ω = sqrt(i)
_I = Fq2.from_ints(0, 1)


def _compute_sqrt_i() -> Fq2:
    # (a + bu)² = u  =>  a² − b² = 0, 2ab = 1. With b = a: 2a² = 1;
    # with b = −a: −2a² = 1. Exactly one of ±1/2 is a QR mod p.
    half = Fq(pow(2, P - 2, P))
    a = half.sqrt()
    if a is not None:
        return Fq2(a, a)
    a = (-half).sqrt()
    assert a is not None
    return Fq2(a, -a)


_OMEGA = _compute_sqrt_i()
_SQRT_CORRECTIONS = [Fq2.one(), _I, _OMEGA, _I * _OMEGA]


class Fq6:
    """Fq2[v]/(v³ − ξ): c0 + c1·v + c2·v²."""

    __slots__ = ("c0", "c1", "c2")

    def __init__(self, c0: Fq2, c1: Fq2, c2: Fq2):
        self.c0, self.c1, self.c2 = c0, c1, c2

    def __add__(self, o: "Fq6") -> "Fq6":
        return Fq6(self.c0 + o.c0, self.c1 + o.c1, self.c2 + o.c2)

    def __sub__(self, o: "Fq6") -> "Fq6":
        return Fq6(self.c0 - o.c0, self.c1 - o.c1, self.c2 - o.c2)

    def __neg__(self) -> "Fq6":
        return Fq6(-self.c0, -self.c1, -self.c2)

    def __mul__(self, o: "Fq6") -> "Fq6":
        a0, a1, a2 = self.c0, self.c1, self.c2
        b0, b1, b2 = o.c0, o.c1, o.c2
        t0 = a0 * b0
        t1 = a1 * b1
        t2 = a2 * b2
        # c0 = t0 + ξ((a1+a2)(b1+b2) − t1 − t2)
        c0 = t0 + XI * ((a1 + a2) * (b1 + b2) - t1 - t2)
        # c1 = (a0+a1)(b0+b1) − t0 − t1 + ξ t2
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1 + XI * t2
        # c2 = (a0+a2)(b0+b2) − t0 − t2 + t1
        c2 = (a0 + a2) * (b0 + b2) - t0 - t2 + t1
        return Fq6(c0, c1, c2)

    def mul_by_fq2(self, k: Fq2) -> "Fq6":
        return Fq6(self.c0 * k, self.c1 * k, self.c2 * k)

    def mul_by_v(self) -> "Fq6":
        # v·(c0 + c1 v + c2 v²) = ξ c2 + c0 v + c1 v²
        return Fq6(XI * self.c2, self.c0, self.c1)

    def square(self) -> "Fq6":
        return self * self

    def inverse(self) -> "Fq6":
        a, b, c = self.c0, self.c1, self.c2
        # Standard tower inversion
        t0 = a.square() - XI * (b * c)
        t1 = XI * c.square() - (a * b)
        t2 = b.square() - (a * c)
        denom = a * t0 + XI * (c * t1 + b * t2)
        inv = denom.inverse()
        return Fq6(t0 * inv, t1 * inv, t2 * inv)

    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero() and self.c2.is_zero()

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, Fq6)
            and self.c0 == other.c0
            and self.c1 == other.c1
            and self.c2 == other.c2
        )

    def __repr__(self) -> str:
        return f"Fq6({self.c0!r}, {self.c1!r}, {self.c2!r})"

    @staticmethod
    def zero() -> "Fq6":
        return Fq6(Fq2.zero(), Fq2.zero(), Fq2.zero())

    @staticmethod
    def one() -> "Fq6":
        return Fq6(Fq2.one(), Fq2.zero(), Fq2.zero())


class Fq12:
    """Fq6[w]/(w² − v): c0 + c1·w."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: Fq6, c1: Fq6):
        self.c0, self.c1 = c0, c1

    def __add__(self, o: "Fq12") -> "Fq12":
        return Fq12(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fq12") -> "Fq12":
        return Fq12(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self) -> "Fq12":
        return Fq12(-self.c0, -self.c1)

    def __mul__(self, o: "Fq12") -> "Fq12":
        a0, a1 = self.c0, self.c1
        b0, b1 = o.c0, o.c1
        t0 = a0 * b0
        t1 = a1 * b1
        # w² = v
        c0 = t0 + t1.mul_by_v()
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1
        return Fq12(c0, c1)

    def square(self) -> "Fq12":
        return self * self

    def conjugate(self) -> "Fq12":
        """x^(p⁶): negates the w-component (the Fq12/Fq6 conjugation)."""
        return Fq12(self.c0, -self.c1)

    def inverse(self) -> "Fq12":
        # (c0 + c1 w)⁻¹ = (c0 − c1 w)/(c0² − v c1²)
        denom = self.c0.square() - self.c1.square().mul_by_v()
        inv = denom.inverse()
        return Fq12(self.c0 * inv, -(self.c1 * inv))

    def pow(self, e: int) -> "Fq12":
        if e < 0:
            return self.inverse().pow(-e)
        result = Fq12.one()
        base = self
        while e > 0:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero()

    def is_one(self) -> bool:
        return self == Fq12.one()

    # --- flattened view for Frobenius: Fq12 = Fq2[w]/(w⁶ − ξ) ---
    def to_w_coeffs(self) -> list[Fq2]:
        """Coefficients [d0..d5] with self = Σ d_i w^i (d_i ∈ Fq2).

        Tower→flat: c0 = a0 + a1 v + a2 v² = a0 + a1 w² + a2 w⁴;
        c1 w = b0 w + b1 w³ + b2 w⁵.
        """
        a, b = self.c0, self.c1
        return [a.c0, b.c0, a.c1, b.c1, a.c2, b.c2]

    @staticmethod
    def from_w_coeffs(d: list[Fq2]) -> "Fq12":
        return Fq12(Fq6(d[0], d[2], d[4]), Fq6(d[1], d[3], d[5]))

    def frobenius(self, power: int = 1) -> "Fq12":
        """x^(p^power) via the flattened representation:
        φ^k(Σ d_i w^i) = Σ conj^k(d_i) · γ_i^(k) · w^i,
        γ_i^(k) = ξ^(i(p^k − 1)/6)."""
        if power not in (1, 2, 3):
            raise ValueError(f"frobenius power {power} not precomputed")
        coeffs = self.to_w_coeffs()
        gammas = _FROB_GAMMA[power]
        out = []
        for i, d in enumerate(coeffs):
            if power % 2 == 1:
                d = d.conjugate()
            out.append(d * gammas[i])
        return Fq12.from_w_coeffs(out)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Fq12) and self.c0 == other.c0 and self.c1 == other.c1

    def __repr__(self) -> str:
        return f"Fq12({self.c0!r}, {self.c1!r})"

    @staticmethod
    def zero() -> "Fq12":
        return Fq12(Fq6.zero(), Fq6.zero())

    @staticmethod
    def one() -> "Fq12":
        return Fq12(Fq6.one(), Fq6.zero())


def _compute_frob_gammas() -> dict[int, list[Fq2]]:
    """γ_i^(k) = ξ^(i(p^k−1)/6) for k in 1..3 (all we need), i in 0..5."""
    out: dict[int, list[Fq2]] = {}
    for k in (1, 2, 3):
        exp = (P**k - 1) // 6
        base = XI.pow(exp)
        gammas = [Fq2.one()]
        for _ in range(5):
            gammas.append(gammas[-1] * base)
        out[k] = gammas
    return out


_FROB_GAMMA = _compute_frob_gammas()
