"""Optimal ate pairing on BLS12-381 (oracle tier).

e: G1 × G2 → μ_r ⊂ Fq12. Miller loop over the (absolute) BLS parameter with
a final conjugation for its sign, then final exponentiation. Two final-exp
paths are provided: a naive big-int pow (obviously correct; used to validate)
and the fast easy-part + Hayashida–Hayasaka–Teruya hard-part used in
production libraries. The TPU kernels (lodestar_tpu/ops) mirror the fast path
and are differentially tested against this module.

The Miller loop here works entirely in E(Fq12) with generic affine line
evaluations via the untwist map — slow but transparently matching the
textbook definition.
"""

from __future__ import annotations

from .curve import PointG1, PointG2
from .fields import P, R, X_PARAM, Fq, Fq2, Fq6, Fq12

# |x| for the Miller loop
X_ABS = abs(X_PARAM)
X_BITS = bin(X_ABS)[2:]

# w⁻² and w⁻³ as Fq12 elements for the untwist map
_W = Fq12(Fq6.zero(), Fq6.one())  # w
_W2_INV = (_W * _W).inverse()
_W3_INV = (_W * _W * _W).inverse()


def _embed_fq(x) -> Fq12:
    return Fq12(Fq6(Fq2(x, type(x)(0)), Fq2.zero(), Fq2.zero()), Fq6.zero())


def _embed_fq2(x: Fq2) -> Fq12:
    return Fq12(Fq6(x, Fq2.zero(), Fq2.zero()), Fq6.zero())


def untwist(q: PointG2) -> tuple[Fq12, Fq12]:
    """E'(Fq2) → E(Fq12): (x, y) → (x/w², y/w³)."""
    aff = q.to_affine()
    assert aff is not None, "untwist of infinity"
    x, y = aff
    return (_embed_fq2(x) * _W2_INV, _embed_fq2(y) * _W3_INV)


def miller_loop(p: PointG1, q: PointG2) -> Fq12:
    """Miller loop f_{|x|,Q}(P), conjugated for the negative parameter.

    Returns 1 for degenerate inputs (either point at infinity), matching the
    convention e(O, Q) = e(P, O) = 1.
    """
    if p.is_infinity() or q.is_infinity():
        return Fq12.one()

    paff = p.to_affine()
    assert paff is not None
    xp = _embed_fq(paff[0])
    yp = _embed_fq(paff[1])

    xq, yq = untwist(q)
    xt, yt = xq, yq
    f = Fq12.one()
    three = _embed_fq(Fq(3))

    for bit in X_BITS[1:]:
        # doubling step: tangent line at T evaluated at P
        slope = (xt * xt) * three * (yt + yt).inverse()
        line = yp - yt - slope * (xp - xt)
        f = f * f * line
        x_new = slope * slope - xt - xt
        y_new = slope * (xt - x_new) - yt
        xt, yt = x_new, y_new
        if bit == "1":
            # addition step: chord through T and Q evaluated at P.
            # T = kQ with 1 < k < |x| < r and Q of prime order r, so T
            # can never equal ±Q here.
            if xt == xq:
                raise ArithmeticError("Miller loop degenerate addition (T == ±Q)")
            slope = (yq - yt) * (xq - xt).inverse()
            line = yp - yt - slope * (xp - xt)
            f = f * line
            x_new = slope * slope - xt - xq
            y_new = slope * (xt - x_new) - yt
            xt, yt = x_new, y_new

    # Negative BLS parameter: conjugate (f^(p⁶) ≡ f⁻¹ modulo the final
    # exponentiation), the standard convention in production pairing code.
    return f.conjugate()


FINAL_EXP_POWER = (P**12 - 1) // R


def final_exponentiation_naive(f: Fq12) -> Fq12:
    """f^((p¹²−1)/r) by direct square-and-multiply. Slow, obviously correct."""
    return f.pow(FINAL_EXP_POWER)


def _pow_x_abs(f: Fq12) -> Fq12:
    """f^|x| (x = BLS parameter, 64-bit)."""
    return f.pow(X_ABS)


def final_exponentiation(f: Fq12) -> Fq12:
    """Fast final exponentiation.

    Note: the HHT hard-part decomposition (x−1)²(x+p)(x²+p²−1) + 3 equals
    3·(p⁴−p²+1)/r, so this computes pairing(...)³ — a fixed power coprime to
    r, preserving all verification equations (same convention as production
    pairing libraries). Differential tests vs the naive path account for the
    cube.

    Easy part: f ← f^(p⁶−1)(p²+1). Hard part computed as
      b = (f^((x−1)²))^x · frob(f^((x−1)²))
      result = b^(x²) · frob²(b) · b⁻¹ · f³
    using conj for inverses (valid in the cyclotomic subgroup after the easy
    part) and conj∘pow for the negative x.
    """
    # easy part
    f = f.conjugate() * f.inverse()  # f^(p^6 - 1)
    f = f.frobenius(2) * f  # ^(p^2 + 1); now f is in the cyclotomic subgroup

    def pow_x(g: Fq12) -> Fq12:
        # g^x with x negative: g^|x| then invert (conjugate — cyclotomic)
        return _pow_x_abs(g).conjugate()

    def pow_x_minus_1(g: Fq12) -> Fq12:
        # g^(x-1) = g^x · g^-1
        return pow_x(g) * g.conjugate()

    a = pow_x_minus_1(pow_x_minus_1(f))  # f^((x-1)^2)
    b = pow_x(a) * a.frobenius(1)  # a^(x+p)
    # b^(x² + p² − 1)
    c = pow_x(pow_x(b)) * b.frobenius(2) * b.conjugate()
    return c * f * f * f  # · f^3


def pairing(p: PointG1, q: PointG2, fast: bool = True) -> Fq12:
    f = miller_loop(p, q)
    return final_exponentiation(f) if fast else final_exponentiation_naive(f)


def multi_pairing(pairs: list[tuple[PointG1, PointG2]]) -> Fq12:
    """Π e(P_i, Q_i): product of Miller loops, one shared final exponentiation
    — the batch-verification primitive (reference analog: blst
    verifyMultipleSignatures aggregation, chain/bls/maybeBatch.ts)."""
    acc = Fq12.one()
    for p, q in pairs:
        acc = acc * miller_loop(p, q)
    return final_exponentiation(acc)
