"""BLS12-381 (CPU oracle tier) — equivalent of @chainsafe/bls + blst.

The TPU tier lives in lodestar_tpu/ops (kernels) + lodestar_tpu/parallel
(sharded batch verification) and is differentially tested against this
package.
"""

from .api import (  # noqa: F401
    BlsError,
    PublicKey,
    SecretKey,
    Signature,
    SignatureSet,
    aggregate_pubkeys,
    aggregate_signatures,
    aggregate_verify,
    fast_aggregate_verify,
    interop_secret_key,
    verify,
    verify_signature_sets,
)
from .curve import PointG1, PointG2  # noqa: F401
from .fields import P as FIELD_MODULUS  # noqa: F401
from .fields import R as CURVE_ORDER  # noqa: F401
from .hash_to_curve import DST_G2, hash_to_g2  # noqa: F401
