"""Hash-to-curve for G2: RFC 9380 suite BLS12381G2_XMD:SHA-256_SSWU_RO_.

Pipeline: expand_message_xmd(SHA-256) → hash_to_field(Fq2, count=2) →
simplified SWU onto the 3-isogenous curve E2' → 3-isogeny to E2 →
clear_cofactor (Budroni–Pintore endomorphism method) — exactly the RFC
construction for the Eth BLS signature ciphersuite
(BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_).

The degree-3 isogeny E2' → E2 is *derived at import time* via Vélu's
formulas (kernel found by factoring the 3-division polynomial of E2' over
Fq2) rather than hard-coding the RFC Appendix E.3 constants; the derivation
asserts that the codomain lands exactly on E2 (y² = x³ + 4(1+u)). Velu's
formulas give the normalized isogeny, which is the one the RFC specifies.
"""

from __future__ import annotations

import hashlib

from .curve import B2, PointG2, clear_cofactor_g2
from .fields import P, Fq, Fq2

# --- RFC 9380 §8.8.2 curve parameters for E2': y² = x³ + A'x + B' ---
A_PRIME = Fq2.from_ints(0, 240)
B_PRIME = Fq2.from_ints(1012, 1012)
Z_SSWU = Fq2.from_ints(P - 2, P - 1)  # Z = -(2 + u)

DST_G2 = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

_SHA256_BLOCK_SIZE = 64
_SHA256_OUT_SIZE = 32
_L = 64  # bytes per field element draw (ceil((381 + 128)/8))


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    """RFC 9380 §5.3.1 expand_message_xmd with SHA-256."""
    if len(dst) > 255:
        dst = hashlib.sha256(b"H2C-OVERSIZE-DST-" + dst).digest()
    ell = (len_in_bytes + _SHA256_OUT_SIZE - 1) // _SHA256_OUT_SIZE
    if ell > 255:
        raise ValueError("expand_message_xmd: output too long")
    dst_prime = dst + bytes([len(dst)])
    z_pad = b"\x00" * _SHA256_BLOCK_SIZE
    l_i_b_str = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b_str + b"\x00" + dst_prime).digest()
    b = [hashlib.sha256(b0 + b"\x01" + dst_prime).digest()]
    for i in range(2, ell + 1):
        tmp = bytes(x ^ y for x, y in zip(b0, b[-1]))
        b.append(hashlib.sha256(tmp + bytes([i]) + dst_prime).digest())
    return b"".join(b)[:len_in_bytes]


def hash_to_field_fq2(msg: bytes, count: int, dst: bytes = DST_G2) -> list[Fq2]:
    """RFC 9380 §5.2 hash_to_field with m=2 (Fq2), L=64."""
    len_in_bytes = count * 2 * _L
    uniform = expand_message_xmd(msg, dst, len_in_bytes)
    out = []
    for i in range(count):
        coords = []
        for j in range(2):
            offset = _L * (j + i * 2)
            coords.append(Fq(int.from_bytes(uniform[offset : offset + _L], "big")))
        out.append(Fq2(coords[0], coords[1]))
    return out


# ---------------------------------------------------------------------------
# Degree-3 isogeny E2' → E2, derived via Vélu's formulas at import time.
# ---------------------------------------------------------------------------


def _poly_mulmod(a: list[Fq2], b: list[Fq2], mod: list[Fq2]) -> list[Fq2]:
    """(a*b) mod `mod` — dense poly arithmetic over Fq2, low-degree only."""
    res = [Fq2.zero()] * (len(a) + len(b) - 1)
    for i, ai in enumerate(a):
        if ai.is_zero():
            continue
        for j, bj in enumerate(b):
            res[i + j] = res[i + j] + ai * bj
    return _poly_mod(res, mod)


def _poly_mod(a: list[Fq2], mod: list[Fq2]) -> list[Fq2]:
    a = list(a)
    dm = len(mod) - 1
    lead_inv = mod[-1].inverse()
    while len(a) - 1 >= dm:
        coef = a[-1] * lead_inv
        shift = len(a) - 1 - dm
        for i in range(len(mod)):
            a[shift + i] = a[shift + i] - coef * mod[i]
        while len(a) > 1 and a[-1].is_zero():
            a.pop()
        if len(a) == 1 and a[0].is_zero():
            break
    return a


def _poly_gcd(a: list[Fq2], b: list[Fq2]) -> list[Fq2]:
    while len(b) > 1 or not b[0].is_zero():
        a, b = b, _poly_mod(a, b)
        if len(b) == 1 and b[0].is_zero():
            break
    # normalize monic
    inv = a[-1].inverse()
    return [c * inv for c in a]


def _poly_powmod(base: list[Fq2], e: int, mod: list[Fq2]) -> list[Fq2]:
    result = [Fq2.one()]
    b = _poly_mod(base, mod)
    while e > 0:
        if e & 1:
            result = _poly_mulmod(result, b, mod)
        b = _poly_mulmod(b, b, mod)
        e >>= 1
    return result


def _find_quartic_roots(poly: list[Fq2]) -> list[Fq2]:
    """Roots in Fq2 of a quartic (equal-degree splitting, deterministic
    sweep of shift elements)."""
    q = P * P
    # g = gcd(x^q - x, poly): product of linear factors over Fq2
    xq = _poly_powmod([Fq2.zero(), Fq2.one()], q, poly)
    xq_minus_x = list(xq)
    while len(xq_minus_x) < 2:
        xq_minus_x.append(Fq2.zero())
    xq_minus_x[1] = xq_minus_x[1] - Fq2.one()
    g = _poly_gcd(poly, xq_minus_x)

    roots: list[Fq2] = []

    def split(h: list[Fq2]) -> None:
        deg = len(h) - 1
        if deg == 0:
            return
        if deg == 1:
            # monic x + c -> root -c
            roots.append(-h[0])
            return
        # try shifts deterministically: s(x) = (x + delta)^((q-1)/2) - 1
        for delta_ints in ((0, 0), (1, 0), (0, 1), (1, 1), (2, 0), (0, 2), (2, 1), (3, 5)):
            delta = Fq2.from_ints(*delta_ints)
            s = _poly_powmod([delta, Fq2.one()], (q - 1) // 2, h)
            s = list(s)
            s[0] = s[0] - Fq2.one()
            while len(s) > 1 and s[-1].is_zero():
                s.pop()
            if len(s) == 1 and s[0].is_zero():
                continue
            f1 = _poly_gcd(h, s)
            if 0 < len(f1) - 1 < deg:
                f2 = _poly_divide_exact(h, f1)
                split(f1)
                split(f2)
                return
        raise ArithmeticError("quartic splitting failed")

    split(g)
    return roots


def _poly_divide_exact(a: list[Fq2], b: list[Fq2]) -> list[Fq2]:
    """a / b for exact division, both monic-ish."""
    a = list(a)
    out = [Fq2.zero()] * (len(a) - len(b) + 1)
    binv = b[-1].inverse()
    while len(a) >= len(b):
        coef = a[-1] * binv
        shift = len(a) - len(b)
        out[shift] = coef
        for i in range(len(b)):
            a[shift + i] = a[shift + i] - coef * b[i]
        while len(a) > 1 and a[-1].is_zero():
            a.pop()
        if len(a) == 1 and a[0].is_zero():
            break
    return out


def _derive_isogeny() -> tuple[Fq2, Fq2, Fq2, Fq, Fq]:
    """Find the kernel x-coordinate x0 of the 3-isogeny E2' → E2 and the
    Vélu parameters (x0, t, u) plus the isomorphism scale:

        X(x)  = x + t/(x−x0) + u/(x−x0)²,   t = 2(3x0² + A'), u = 4y0²
        Y(x,y)= y·X'(x),  X'(x) = 1 − t/(x−x0)² − 2u/(x−x0)³

    Vélu's codomain is y² = x³ + (A'−5t)x + (B'−7(u+t·x0)). For BLS12-381 it
    comes out as y² = x³ + λ⁶·4(1+u) with λ = 3, so the map onto E2 itself is
    the composition with (x, y) → (x/λ², y/λ³). The sign of λ (equivalently,
    post-composition with negation) is fixed to match RFC 9380's map — pinned
    empirically against the reference's interop deposit signature vector
    (beacon-node/test/e2e/interop/genesisState.test.ts).
    """
    # ψ₃(x) = 3x⁴ + 6A'x² + 12B'x − A'²
    three = Fq2.from_ints(3, 0)
    six = Fq2.from_ints(6, 0)
    twelve = Fq2.from_ints(12, 0)
    poly = [
        -(A_PRIME * A_PRIME),
        twelve * B_PRIME,
        six * A_PRIME,
        Fq2.zero(),
        three,
    ]
    # normalize monic for root finding
    inv = poly[-1].inverse()
    poly_monic = [c * inv for c in poly]
    candidates = []
    for x0 in _find_quartic_roots(poly_monic):
        y0_sq = x0 * x0 * x0 + A_PRIME * x0 + B_PRIME
        t = (x0 * x0).mul_scalar(Fq(6)) + A_PRIME + A_PRIME
        u = y0_sq.mul_scalar(Fq(4))
        a_new = A_PRIME - t.mul_scalar(Fq(5))
        b_new = B_PRIME - (u + t * x0).mul_scalar(Fq(7))
        if not a_new.is_zero():
            continue
        # b_new must be λ⁶ · B2 for some λ ∈ Fq; check small integer λ.
        for lam_int in (1, 2, 3, 4, 5, 6, 7, 8, 9):
            lam = Fq(lam_int)
            if B2.mul_scalar(lam.pow(6)) == b_new:
                candidates.append((x0, t, u, lam))
                break
    if not candidates:
        raise ArithmeticError("no 3-isogeny E2' -> E2 found")
    candidates.sort(key=lambda c: (c[0].c1.n, c[0].c0.n))
    x0, t, u, lam = candidates[0]
    # RFC 9380's isogeny corresponds to λ = −3 (not +3): with +3 the final
    # hash point comes out negated. Pinned empirically by reproducing the
    # reference's interop deposit signature byte-for-byte (validator 0,
    # sig 0xa95af8ff..., beacon-node/test/e2e/interop/genesisState.test.ts).
    lam = -lam
    inv_l2 = lam.pow(2).inverse()
    inv_l3 = lam.pow(3).inverse()
    return x0, t, u, inv_l2, inv_l3


_ISO_X0, _ISO_T, _ISO_U, _ISO_INV_L2, _ISO_INV_L3 = _derive_isogeny()


def iso_map_to_e2(x: Fq2, y: Fq2) -> tuple[Fq2, Fq2]:
    """Apply the derived 3-isogeny E2' → E2 (affine): Vélu map composed with
    the scaling isomorphism (x, y) → (x/λ², y/λ³)."""
    d = x - _ISO_X0
    d_inv = d.inverse()
    d_inv2 = d_inv * d_inv
    d_inv3 = d_inv2 * d_inv
    xx = x + _ISO_T * d_inv + _ISO_U * d_inv2
    dx = Fq2.one() - _ISO_T * d_inv2 - (_ISO_U + _ISO_U) * d_inv3
    return xx.mul_scalar(_ISO_INV_L2), (y * dx).mul_scalar(_ISO_INV_L3)


def simplified_swu(u: Fq2) -> tuple[Fq2, Fq2]:
    """RFC 9380 §6.6.2 simplified SWU onto E2' (A'B' ≠ 0)."""
    A, B, Z = A_PRIME, B_PRIME, Z_SSWU
    u2 = u * u
    zu2 = Z * u2
    tv = zu2 * zu2 + zu2  # Z²u⁴ + Zu²
    if tv.is_zero():
        x1 = B * (Z * A).inverse()  # x1 = B / (Z·A)
    else:
        x1 = (-B) * A.inverse() * (Fq2.one() + tv.inverse())
    gx1 = x1 * x1 * x1 + A * x1 + B
    y1 = gx1.sqrt()
    if y1 is not None:
        x, y = x1, y1
    else:
        x2 = zu2 * x1
        gx2 = x2 * x2 * x2 + A * x2 + B
        y2 = gx2.sqrt()
        assert y2 is not None, "SSWU: neither gx1 nor gx2 is square"
        x, y = x2, y2
    if y.sgn0() != u.sgn0():
        y = -y
    return x, y


def map_to_curve_g2(u: Fq2) -> PointG2:
    x, y = simplified_swu(u)
    xx, yy = iso_map_to_e2(x, y)
    return PointG2(xx, yy, Fq2.one())


def hash_to_g2(msg: bytes, dst: bytes = DST_G2) -> PointG2:
    """Full hash_to_curve (random-oracle variant): two field draws, two maps,
    point addition, cofactor clearing."""
    u0, u1 = hash_to_field_fq2(msg, 2, dst)
    q0 = map_to_curve_g2(u0)
    q1 = map_to_curve_g2(u1)
    return clear_cofactor_g2(q0 + q1)
