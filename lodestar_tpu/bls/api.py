"""BLS signature API (ciphersuite BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_).

Equivalent of the `@chainsafe/bls` surface the reference consumes
(SecretKey/PublicKey/Signature classes + verify/aggregate helpers, used by
chain/bls/maybeBatch.ts and the worker pool) plus the batch verification
primitive `verify_signature_sets` mirroring blst's verifyMultipleSignatures:
random linear combination with one shared final exponentiation.

Pubkeys live in G1 (48B compressed), signatures in G2 (96B compressed) —
the Eth "minimal-pubkey-size" instantiation. This is the CPU oracle tier; the
TPU tier (lodestar_tpu/ops + parallel) implements the same batch equation as
vmapped XLA kernels and is differentially tested against this module.

Cross-validated byte-for-byte against the reference's interop deposit
signature (beacon-node/test/e2e/interop/genesisState.test.ts).
"""

from __future__ import annotations

import hashlib
import secrets
from dataclasses import dataclass

from .curve import (
    PointG1,
    PointG2,
    g1_from_bytes,
    g1_to_bytes,
    g2_from_bytes,
    g2_to_bytes,
)
from .fields import Fq
from .fields import R as CURVE_ORDER
from .hash_to_curve import DST_G2, hash_to_g2
from .pairing import multi_pairing

__all__ = [
    "SecretKey",
    "PublicKey",
    "Signature",
    "SignatureSet",
    "aggregate_pubkeys",
    "aggregate_signatures",
    "verify",
    "aggregate_verify",
    "fast_aggregate_verify",
    "verify_signature_sets",
    "interop_secret_key",
]


class BlsError(ValueError):
    pass


class SecretKey:
    __slots__ = ("value",)

    def __init__(self, value: int):
        if not 0 < value < CURVE_ORDER:
            raise BlsError("secret key out of range")
        self.value = value

    @classmethod
    def from_bytes(cls, data: bytes) -> "SecretKey":
        if len(data) != 32:
            raise BlsError("secret key must be 32 bytes")
        return cls(int.from_bytes(data, "big"))

    @classmethod
    def from_keygen(cls, ikm: bytes | None = None) -> "SecretKey":
        """HKDF-based KeyGen per the BLS signature spec (simplified salt loop)."""
        ikm = ikm if ikm is not None else secrets.token_bytes(32)
        salt = b"BLS-SIG-KEYGEN-SALT-"
        while True:
            prk = _hkdf_extract(hashlib.sha256(salt).digest(), ikm + b"\x00")
            okm = _hkdf_expand(prk, (48).to_bytes(2, "big"), 48)
            sk = int.from_bytes(okm, "big") % CURVE_ORDER
            if sk != 0:
                return cls(sk)
            salt = hashlib.sha256(salt).digest()

    def to_bytes(self) -> bytes:
        return self.value.to_bytes(32, "big")

    def to_public_key(self) -> "PublicKey":
        return PublicKey(PointG1.generator() * self.value)

    def sign(self, message: bytes, dst: bytes = DST_G2) -> "Signature":
        from .. import native as _native

        if _native.HAVE_NATIVE_BLS:
            # C tier: hash-to-curve + G2 scalar mul (~6x the oracle);
            # byte-identical output, differential-tested
            rc, sig96 = _native.bls_sign(
                self.value.to_bytes(32, "big"), message, dst
            )
            if rc == 0:
                return Signature.from_bytes(sig96, validate=False)
        return Signature(hash_to_g2(message, dst) * self.value)


def _hkdf_extract(salt: bytes, ikm: bytes) -> bytes:
    import hmac

    return hmac.new(salt, ikm, hashlib.sha256).digest()


def _hkdf_expand(prk: bytes, info: bytes, length: int) -> bytes:
    import hmac

    okm = b""
    t = b""
    i = 1
    while len(okm) < length:
        t = hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        okm += t
        i += 1
    return okm[:length]


class PublicKey:
    __slots__ = ("point", "_compressed")

    def __init__(self, point: PointG1, compressed: bytes | None = None):
        self.point = point
        self._compressed = compressed

    @classmethod
    def from_bytes(cls, data: bytes, validate: bool = True) -> "PublicKey":
        point = g1_from_bytes(data)
        if validate:
            # KeyValidate: not infinity + subgroup membership
            if point.is_infinity():
                raise BlsError("pubkey is point at infinity")
            if not point.is_in_subgroup():
                raise BlsError("pubkey not in G1 subgroup")
        return cls(point, compressed=bytes(data))

    def to_bytes(self) -> bytes:
        # cache: the compressed form is the native marshalling tier's input,
        # so the hot path must not pay a Python affine inversion per use
        if self._compressed is None:
            self._compressed = g1_to_bytes(self.point)
        return self._compressed

    def __eq__(self, other: object) -> bool:
        return isinstance(other, PublicKey) and self.point == other.point


class Signature:
    __slots__ = ("point",)

    def __init__(self, point: PointG2):
        self.point = point

    @classmethod
    def from_bytes(cls, data: bytes, validate: bool = True) -> "Signature":
        point = g2_from_bytes(data)
        if validate and not point.is_infinity() and not point.is_in_subgroup():
            raise BlsError("signature not in G2 subgroup")
        return cls(point)

    def to_bytes(self) -> bytes:
        return g2_to_bytes(self.point)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Signature) and self.point == other.point


def aggregate_pubkeys(pubkeys: list[PublicKey]) -> PublicKey:
    """G1 sum (reference: getAggregatedPubkey on the main thread,
    chain/bls/utils.ts:5 — jacobian aggregation).

    Hot path (every attestation/sync aggregate sums up to 512 pubkeys):
    the native C tier sums compressed keys in one GIL-released call
    (`native/src/bls12.c lodestar_bls_g1_aggregate`); subgroup checks are
    skipped there because PublicKey construction KeyValidates. Falls back
    to big-int addition when the extension is unavailable."""
    if not pubkeys:
        raise BlsError("cannot aggregate empty pubkey list")
    if len(pubkeys) > 1:
        from .. import native as _native

        if _native.HAVE_NATIVE_BLS:
            try:
                pk_b = b"".join(pk.to_bytes() for pk in pubkeys)
            except (BlsError, ValueError):
                pk_b = None
            if pk_b is not None:
                rc, limbs = _native.bls_g1_aggregate(pk_b, check_each=False)
                if rc == 1:
                    return PublicKey(PointG1.zero())
                if rc == 0:
                    from ..ops.limbs import fp_from_mont_host

                    return PublicKey(
                        PointG1(
                            Fq(fp_from_mont_host(limbs[0])),
                            Fq(fp_from_mont_host(limbs[1])),
                            Fq(1),
                        )
                    )
                # rc < 0: malformed bytes — report through the slow path
    acc = PointG1.zero()
    for pk in pubkeys:
        acc = acc + pk.point
    return PublicKey(acc)


def aggregate_signatures(signatures: list[Signature]) -> Signature:
    if not signatures:
        raise BlsError("cannot aggregate empty signature list")
    acc = PointG2.zero()
    for sig in signatures:
        acc = acc + sig.point
    return Signature(acc)


_NEG_G1 = -PointG1.generator()


def _pairing_check(pairs: list[tuple[PointG1, PointG2]]) -> bool:
    return multi_pairing(pairs).is_one()


def verify(
    pubkey: PublicKey, message: bytes, signature: Signature, dst: bytes = DST_G2
) -> bool:
    """CoreVerify: e(pk, H(m)) == e(g1, sig), i.e.
    e(pk, H(m)) · e(−g1, sig) == 1. Infinity pubkey/signature → False
    (eth2 semantics).

    Fast path: the native C pairing (~10 ms vs ~2 s for the big-int
    oracle) — every one-off verification (gossip objects, deposits,
    voluntary exits) rides it; the oracle stays as the fallback and the
    differential reference."""
    if pubkey.point.is_infinity() or signature.point.is_infinity():
        return False
    from .. import native as _native

    if _native.HAVE_NATIVE_BLS:
        try:
            out = _native.bls_verify_sets(
                pubkey.to_bytes(), [message], g2_to_bytes(signature.point), dst
            )
            return bool(out[0])
        except (ValueError, OSError):
            pass  # malformed re-serialization — fall through to the oracle
    h = hash_to_g2(message, dst)
    return _pairing_check([(pubkey.point, h), (_NEG_G1, signature.point)])


def aggregate_verify(
    pubkeys: list[PublicKey],
    messages: list[bytes],
    signature: Signature,
    dst: bytes = DST_G2,
) -> bool:
    if not pubkeys or len(pubkeys) != len(messages):
        return False
    if any(pk.point.is_infinity() for pk in pubkeys) or signature.point.is_infinity():
        return False
    pairs: list[tuple[PointG1, PointG2]] = [
        (pk.point, hash_to_g2(msg, dst)) for pk, msg in zip(pubkeys, messages)
    ]
    pairs.append((_NEG_G1, signature.point))
    return _pairing_check(pairs)


def fast_aggregate_verify(
    pubkeys: list[PublicKey], message: bytes, signature: Signature, dst: bytes = DST_G2
) -> bool:
    """All pubkeys sign the same message (sync-committee aggregate path,
    512 pubkeys: baseline config #4)."""
    if not pubkeys:
        return False
    return verify(aggregate_pubkeys(pubkeys), message, signature, dst)


@dataclass
class SignatureSet:
    """One verification work item: pubkey is pre-aggregated by the caller
    (reference ISignatureSet, chain/bls/interface.ts:20; aggregation happens
    main-thread per bls/utils.ts)."""

    pubkey: PublicKey
    message: bytes  # 32-byte signing root
    signature: bytes  # 96-byte compressed G2


def verify_signature_sets(sets: list[SignatureSet]) -> bool:
    """Batch verification with random linear combination (blst
    verifyMultipleSignatures equivalent; reference calls it for ≥2 sets —
    maybeBatch.ts:16-27):

        Π e(r_i·pk_i, H(m_i)) · e(−g1, Σ r_i·sig_i) == 1

    with independent random 64-bit nonzero r_i. Putting r_i on the pubkey
    (G1) side keeps the extra scalar mul in the cheaper group.
    """
    if not sets:
        return False
    try:
        pairs: list[tuple[PointG1, PointG2]] = []
        sig_acc = PointG2.zero()
        for s in sets:
            if s.pubkey.point.is_infinity():
                return False
            sig = Signature.from_bytes(s.signature).point
            if sig.is_infinity():
                return False
            r = 0
            while r == 0:
                r = secrets.randbits(64)
            pairs.append((s.pubkey.point * r, hash_to_g2(s.message)))
            sig_acc = sig_acc + sig * r
        pairs.append((_NEG_G1, sig_acc))
        return _pairing_check(pairs)
    except (BlsError, ValueError):
        return False


def interop_secret_key(index: int) -> SecretKey:
    """Deterministic interop secret key i (reference:
    state-transition/src/util/interop.ts interopSecretKey):
    sk = int_le(sha256(uint256_le(i))) mod r."""
    h = hashlib.sha256(index.to_bytes(32, "little")).digest()
    return SecretKey(int.from_bytes(h, "little") % CURVE_ORDER)
