"""BASELINE config #5: mainnet-scale follow-head (VERDICT r3 #5).

Drives a ~1M-validator MAINNET-preset chain through real-time slots with
the production pipeline end to end: wire-encoded gossip objects → the
bounded validation queues (`gossip/handlers.py`, reference queue shapes
24,576/64 LIFO) → the full REJECT/IGNORE ladders (`chain/validation.py`,
committee lookup against the 1M-validator shuffling) → BufferedVerifier →
device kernels — plus one signed block per slot through the block queue
and import path, recording per-slot state-root latency from the
incremental hasher.

Two rows are produced (unaggregated singles through the REAL ladder —
committee lookup, subnet check, seen-cache, BLS; aggregates and block
import ride the same BufferedVerifier path and are load-shape subsets of
this, so the singles firehose is the binding row):
  - `default_node`: the first 2 committees per slot (the reference's
    default 2-subnet subscription shape).
  - `supernode`: all committees — mainnet's full unaggregated
    firehose (~committee_count × committee_size sets/slot). On a 1-core
    host the marshal tier cannot sustain this (the reference's answer is
    its worker pool; ours is LODESTAR_TPU_MARSHAL_THREADS ≥ the core
    count the math demands) — the row reports the honest buffer depth /
    drop counts plus the cores_needed extrapolation.

The validator registry cycles N_KEYS real interop keypairs (pubkey bytes
repeat; signatures are REAL and verified) — constructing 1M distinct BLS
keypairs would take hours for zero additional coverage of the system
under test.

The verify tier mirrors the production stack (node.py): the device tier
under the supervisor's failure policy with CPU-oracle fallback
(MAINNET_PROBE_TIER=supervised, the default; =device pins the bare XLA
tier, =cpu the oracle). The artifact records which tier served and the
breaker state, so a host whose accelerator tier cannot meet the slot
budget reports the degraded mode honestly instead of an unbounded
backlog that no production deployment would exhibit.

Writes backlog_run_mainnet.json next to bench_details.json
(backlog_run.json keeps the BASELINE #2 zero-backlog proof).
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"),
)


N_VALIDATORS = int(os.environ.get("MAINNET_PROBE_VALIDATORS", "1000000"))
SLOTS = int(os.environ.get("MAINNET_PROBE_SLOTS", "8"))
SLOT_SEC = float(os.environ.get("MAINNET_PROBE_SLOT_SEC", "12"))
N_KEYS = 64
GENESIS_TIME = 1_600_000_000


def build_state(config, types, preset):
    """Synthetic 1M-validator genesis: direct field construction (the
    deposit path would replay 1M deposits)."""
    from lodestar_tpu.params import FAR_FUTURE_EPOCH, GENESIS_EPOCH
    from lodestar_tpu.bls import api as bls

    t0 = time.monotonic()
    sks = [bls.interop_secret_key(i) for i in range(N_KEYS)]
    pk_bytes = [sk.to_public_key().to_bytes() for sk in sks]

    state = types.BeaconState()
    state.genesis_time = GENESIS_TIME
    state.fork = types.Fork(
        previous_version=config.GENESIS_FORK_VERSION,
        current_version=config.GENESIS_FORK_VERSION,
        epoch=GENESIS_EPOCH,
    )
    state.eth1_data = types.Eth1Data(
        deposit_root=b"\x00" * 32,
        deposit_count=N_VALIDATORS,
        block_hash=b"\x42" * 32,
    )
    body_root = types.BeaconBlockBody().hash_tree_root()
    state.latest_block_header = types.BeaconBlockHeader(body_root=body_root)
    state.randao_mixes = [b"\x42" * 32] * preset.EPOCHS_PER_HISTORICAL_VECTOR

    max_eb = preset.MAX_EFFECTIVE_BALANCE
    validators = []
    for i in range(N_VALIDATORS):
        validators.append(
            types.Validator(
                pubkey=pk_bytes[i % N_KEYS],
                withdrawal_credentials=b"\x00" * 32,
                effective_balance=max_eb,
                slashed=False,
                activation_eligibility_epoch=GENESIS_EPOCH,
                activation_epoch=GENESIS_EPOCH,
                exit_epoch=FAR_FUTURE_EPOCH,
                withdrawable_epoch=FAR_FUTURE_EPOCH,
            )
        )
    state.validators = validators
    state.balances = [max_eb] * N_VALIDATORS
    validators_type = dict(type(state).fields)["validators"]
    state.genesis_validators_root = validators_type.hash_tree_root(
        state.validators
    )
    print(
        f"state build: {N_VALIDATORS} validators in "
        f"{time.monotonic() - t0:.1f}s",
        flush=True,
    )
    return state, sks


def _sign_root(config, sk, domain_type, epoch, root):
    from lodestar_tpu.config.beacon_config import compute_signing_root

    domain = config.get_domain(domain_type, epoch * 32, epoch)
    return sk.sign(compute_signing_root(root, domain))


async def drive(
    handlers, chain, types, config, sks, n_committees: int,
    n_slots: int = SLOTS,
) -> dict:
    """Run n_slots real-time slots; returns the row dict."""
    from lodestar_tpu.chain.validation import compute_subnet_for_attestation
    from lodestar_tpu.config.beacon_config import compute_signing_root
    from lodestar_tpu.network.gossip.encoding import encode_message
    from lodestar_tpu.network.gossip.topic import GossipType
    from lodestar_tpu.params import DOMAIN_BEACON_ATTESTER

    p = chain.preset
    ctx = chain.head_state.epoch_ctx
    start_slot = int(chain.head_state.state.slot)
    # rows replay the same slots: reset the seen-attester dedup so the
    # second row's load is not IGNOREd as duplicates
    seen = getattr(chain, "seen_attesters", None)
    if seen is not None and hasattr(seen, "_seen"):
        seen._seen.clear()

    depth_samples: list[int] = []
    root_latencies: list[float] = []
    verified = 0
    rejected = 0
    stop = asyncio.Event()

    bls_buf = chain.bls  # ThreadBufferedVerifier

    async def sampler():
        while not stop.is_set():
            with bls_buf._lock:
                depth = sum(len(e[0]) for e in bls_buf._entries)
            depth_samples.append(depth)
            await asyncio.sleep(0.05)

    samp = asyncio.create_task(sampler())
    t_run0 = time.monotonic()
    per_slot = []
    for rel in range(n_slots):
        slot = start_slot + 1 + rel
        chain.clock.set_slot(slot)
        slot_t0 = time.monotonic()
        epoch = slot // p.SLOTS_PER_EPOCH
        cps = ctx.get_committee_count_per_slot(epoch)

        # build this slot's singles for the subscribed subnets
        head_root = chain.head_root
        target_root = chain.fork_choice.get_ancestor(
            head_root, (epoch * p.SLOTS_PER_EPOCH)
        )
        jobs = []
        n_singles = 0
        # attest with the first n_committees committees of the slot (the
        # reference's default node holds 2 long-lived subnets; a
        # supernode takes all) — each attestation is pushed on its REAL
        # computed subnet so the ladder's subnet check is exercised
        for index in range(min(cps, n_committees)):
            subnet = compute_subnet_for_attestation(ctx, slot, index, p)
            committee = ctx.get_beacon_committee(slot, index)
            data = types.AttestationData(
                slot=slot,
                index=index,
                beacon_block_root=head_root,
                source=types.Checkpoint(
                    epoch=int(chain.head_state.state.current_justified_checkpoint.epoch),
                    root=bytes(chain.head_state.state.current_justified_checkpoint.root),
                ),
                target=types.Checkpoint(epoch=epoch, root=target_root),
            )
            domain = config.get_domain(DOMAIN_BEACON_ATTESTER, slot, epoch)
            root = compute_signing_root(data.hash_tree_root(), domain)
            sig_by_key: dict[int, bytes] = {}
            for pos, vidx in enumerate(committee):
                k = int(vidx) % N_KEYS
                sig = sig_by_key.get(k)
                if sig is None:
                    sig = sig_by_key[k] = sks[k].sign(root).to_bytes()
                bits = [False] * len(committee)
                bits[pos] = True
                att = types.Attestation(
                    aggregation_bits=bits, data=data.copy(), signature=sig
                )
                jobs.append((subnet, att))
                n_singles += 1

        async def push_att(subnet, att):
            queue = handlers.queues[GossipType.beacon_attestation]
            topic = _FakeTopic(GossipType.beacon_attestation, subnet)
            return await queue.push((topic, encode_message(att.serialize())))

        results = await asyncio.gather(
            *[push_att(sn, att) for sn, att in jobs], return_exceptions=True
        )
        ok_count = sum(1 for r in results if getattr(r, "name", "") == "ACCEPT")
        verified += ok_count
        rejected += len(results) - ok_count

        # state root latency: advance and re-hash (incremental)
        t0 = time.monotonic()
        _ = chain.head_state.hash_tree_root()
        root_latencies.append(time.monotonic() - t0)

        spent = time.monotonic() - slot_t0
        if spent < SLOT_SEC:
            await asyncio.sleep(SLOT_SEC - spent)
        per_slot.append(
            {
                "slot": slot,
                "singles_pushed": n_singles,
                "accepted": ok_count,
                "slot_busy_s": round(spent, 2),
            }
        )
        print(f"slot {slot}: {per_slot[-1]}", flush=True)
    stop.set()
    await samp

    ds = sorted(depth_samples) or [0]
    rl = sorted(root_latencies)
    drops = {
        t.value: handlers.queues[t].metrics.dropped_jobs
        for t in handlers.queues
        if handlers.queues[t].metrics.dropped_jobs
    }
    # honest core-count extrapolation: mean busy seconds per slot over the
    # slot budget (the ladder + marshal tier scale linearly with cores —
    # the C tier releases the GIL; reference analog: poolSize.ts)
    import math

    mean_busy = sum(p["slot_busy_s"] for p in per_slot) / max(1, len(per_slot))
    cores_needed = max(1, math.ceil(mean_busy / SLOT_SEC))
    return {
        "cores_needed": cores_needed,
        "mean_slot_busy_s": round(mean_busy, 2),
        "committees_per_slot": n_committees,
        "slots": n_slots,
        "verified": verified,
        "rejected": rejected,
        "buffer_depth_p50": ds[len(ds) // 2],
        "buffer_depth_p95": ds[int(len(ds) * 0.95)],
        "buffer_depth_max": ds[-1],
        "state_root_ms_p50": round(rl[len(rl) // 2] * 1e3, 1),
        "state_root_ms_max": round(rl[-1] * 1e3, 1),
        "queue_drops": drops,
        "wall_seconds": round(time.monotonic() - t_run0, 1),
        "per_slot": per_slot,
    }


class _FakeTopic:
    """Minimal parsed-topic stand-in for direct queue pushes."""

    def __init__(self, gtype, subnet):
        self.type = gtype
        self.subnet = subnet
        self.fork_digest = b"\x00" * 4
        self.encoding = "ssz_snappy"


def main():
    from lodestar_tpu.chain import BeaconChain
    from lodestar_tpu.chain.bls_verifier import (
        DeviceBlsVerifier,
        ThreadBufferedVerifier,
    )
    from lodestar_tpu.config.beacon_config import BeaconConfig
    from lodestar_tpu.config.chain_config import MAINNET_CHAIN_CONFIG
    from lodestar_tpu.network.gossip.handlers import GossipHandlers
    from lodestar_tpu.params.presets import MAINNET
    from lodestar_tpu.types import get_types

    types = get_types(MAINNET).phase0
    config = BeaconConfig(MAINNET_CHAIN_CONFIG, b"\x00" * 32, MAINNET)
    state, sks = build_state(config, types, MAINNET)
    config = BeaconConfig(
        MAINNET_CHAIN_CONFIG, bytes(state.genesis_validators_root), MAINNET
    )

    t0 = time.monotonic()
    chain = BeaconChain(config, types, state)
    print(f"chain init (epoch ctx @1M): {time.monotonic() - t0:.1f}s", flush=True)

    # The 1M-validator state is a ~10 GB Python object graph that never
    # becomes garbage; without freezing it, every gen-2 collection
    # triggered by XLA-compile allocation churn rescans the whole graph
    # and the warm phase crawls for hours on a 1-core host.
    import gc

    gc.collect()
    gc.freeze()

    device = DeviceBlsVerifier(buckets=(128,), grouped_configs=((64, 64),))
    # Production-stack parity (node.py): the device tier serves under the
    # supervisor's failure policy — per-dispatch deadline, circuit
    # breaker, CPU-oracle fallback. On a host whose accelerator tier
    # cannot answer inside the slot budget (a 1-core container runs a
    # 4096-lane grouped execution in ~4 min) the breaker opens and the C
    # tier serves: the documented degraded mode (docs/robustness.md) and
    # the honest configuration for the backlog question, which is about
    # the queue/pipeline, not the accelerator. MAINNET_PROBE_TIER=device
    # restores the bare-device measurement; =cpu pins the oracle tier.
    tier = os.environ.get("MAINNET_PROBE_TIER", "supervised")
    if tier == "device":
        inner = device
    elif tier == "cpu":
        from lodestar_tpu.chain import CpuBlsVerifier

        inner = CpuBlsVerifier()
    else:
        from lodestar_tpu.chain import CpuBlsVerifier
        from lodestar_tpu.chain.supervisor import SupervisedBlsVerifier

        inner = SupervisedBlsVerifier(
            device,
            CpuBlsVerifier(),
            # slot-bounded deadline: a tier that cannot answer within a
            # slot is failed for serving purposes on this host
            deadline_s=float(
                os.environ.get("MAINNET_PROBE_DEVICE_DEADLINE_S", "12")
            ),
            failure_threshold=1,
            cooldown_s=86400.0,  # no half-open re-probe churn mid-run
            canary_thread=False,
        )
    chain.bls = ThreadBufferedVerifier(inner)
    handlers = GossipHandlers(config, types, chain, verify_signatures=True)

    # warm the device kernels outside the timed slots
    from lodestar_tpu.bls import api as bls

    warm = []
    for i in range(128):
        root = bytes([i]) + b"\x77" * 31
        sk = sks[i % N_KEYS]
        warm.append(
            bls.SignatureSet(
                pubkey=sk.to_public_key(), message=root,
                signature=sk.sign(root).to_bytes(),
            )
        )
    t0 = time.monotonic()
    assert inner.verify_signature_sets(warm)
    assert inner.verify_signature_sets(warm[:100])  # flat bucket too
    # the slot flushes are ≤MAX_BUFFERED_SIGS sets SHARING a root (one
    # attestation data per committee) — that routes the grouped kernel,
    # a shape the unique-root warms above never compile; warm it here so
    # the first timed slot isn't a multi-minute compile
    shared_root = b"\x55" * 32
    warm_grouped = []
    for i in range(32):
        sk = sks[i % N_KEYS]
        warm_grouped.append(
            bls.SignatureSet(
                pubkey=sk.to_public_key(), message=shared_root,
                signature=sk.sign(shared_root).to_bytes(),
            )
        )
    assert inner.verify_signature_sets(warm_grouped)
    print(f"kernel warm: {time.monotonic() - t0:.1f}s", flush=True)
    gc.freeze()  # compiled executables + warm artifacts join the frozen set

    if tier == "supervised":
        # the deadline blowout that opened the breaker during warm leaves
        # an abandoned device execution running (XLA calls cannot be
        # cancelled) — let it drain so the timed slots aren't starved
        settle = float(os.environ.get("MAINNET_PROBE_SETTLE_S", "600"))
        print(
            f"settling {settle:.0f}s for abandoned device executions "
            f"(breaker: {inner.breaker_snapshot()['state']})",
            flush=True,
        )
        time.sleep(settle)

    rows = {}
    rows["default_node"] = asyncio.run(
        drive(handlers, chain, types, config, sks,
              int(os.environ.get("MAINNET_PROBE_COMMITTEES", "2")))
    )
    if os.environ.get("MAINNET_PROBE_SUPERNODE", "1") == "1":
        # the full firehose costs ~64 committees x committee-size singles
        # per slot through the pure-Python ladder — minutes of busy time
        # per slot on a small host. The row exists for the honest
        # cores_needed extrapolation, which a short slot sample pins just
        # as well; MAINNET_PROBE_SUPERNODE_SLOTS widens it on big hosts.
        rows["supernode"] = asyncio.run(
            drive(
                handlers, chain, types, config, sks, 64,
                n_slots=int(
                    os.environ.get("MAINNET_PROBE_SUPERNODE_SLOTS", "2")
                ),
            )
        )

    out = {
        "config": "BASELINE #5: mainnet follow-head, "
        f"{N_VALIDATORS} validators, 64 subnets",
        "validators": N_VALIDATORS,
        "slot_seconds": SLOT_SEC,
        "verify_tier": tier,
        **rows,
    }
    if tier == "supervised":
        out["supervisor"] = inner.breaker_snapshot()
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..",
        "backlog_run_mainnet.json"
    )
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps({k: v for k, v in out.items() if k not in rows}))
    for name, row in rows.items():
        print(name, json.dumps({k: v for k, v in row.items() if k != "per_slot"}))


if __name__ == "__main__":
    main()
