"""Component-level profile of batch_verify_kernel at a given batch size.

Times each stage as its own jitted kernel (device-resident inputs):
  scalar muls (G1, G2) · G2 sum tree · Miller loop · Fp12 product tree ·
  final exponentiation. The sum of parts exceeds the fused kernel's time
  (XLA overlaps stages), but the RATIOS say where the next optimization
  dollar goes. Usage: python tools/kernel_profile.py [BATCH]
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"),
)
import jax.numpy as jnp

from __graft_entry__ import _example_arrays
from lodestar_tpu.ops import fp, fp12
from lodestar_tpu.ops.pairing import final_exponentiation, miller_loop_projective
from lodestar_tpu.ops.points import G1_GEN_X, G1_GEN_Y, g1, g2
from lodestar_tpu.parallel import verifier as V

B = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, r_bits, valid = [
    jax.device_put(a) for a in _example_arrays(B)
]
jax.block_until_ready([pk_x, r_bits])


def bench(name, fn, *args, reps=3):
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:28s} {dt*1000:9.1f} ms   (compile+1 {compile_s:.1f}s)", flush=True)
    return out


f_g1 = jax.jit(lambda b, x, y: g1.scalar_mul_bits(b, (x, y)))
f_g2 = jax.jit(lambda b, x, y: g2.scalar_mul_bits(b, (x, y)))
rpk = bench("g1 scalar mul (r_i*pk_i)", f_g1, r_bits, pk_x, pk_y)
rsig = bench("g2 scalar mul (r_i*sig_i)", f_g2, r_bits, sig_x, sig_y)

f_tree = jax.jit(lambda x, y, z: V._g2_sum_tree((x, y, z)))
s_pt = bench("g2 sum tree", f_tree, *rsig)

f_aff = jax.jit(lambda x, y, z: g2.to_affine((x, y, z)))
s_aff = bench("g2 to_affine (1 fp2 inv)", f_aff, *s_pt)


def miller_all(rx, ry, rz, mx, my, sx, sy):
    xs = jnp.concatenate([rx, G1_GEN_X[None]], 0)
    ys = jnp.concatenate([ry, fp.neg(G1_GEN_Y)[None]], 0)
    zs = jnp.concatenate([rz, fp.one((1,))], 0)
    qx = jnp.concatenate([mx, sx[None]], 0)
    qy = jnp.concatenate([my, sy[None]], 0)
    return miller_loop_projective((xs, ys, zs), (qx, qy))


f_miller = jax.jit(miller_all)
fs = bench(
    "miller loops (B+1)", f_miller, rpk[0], rpk[1], rpk[2],
    msg_x, msg_y, s_aff[0], s_aff[1],
)

f_prod = jax.jit(fp12.product_tree)
prod = bench("fp12 product tree", f_prod, fs)

f_fe = jax.jit(lambda f: fp12.is_one(final_exponentiation(f[None]))[0])
bench("final exponentiation (x1)", f_fe, prod)
