"""Profile the BLS batch-verify kernel piecewise on the real chip.

Times each stage of batch_verify_kernel at the bench shape so the next
optimisation target is measured, not guessed. Run:  python tools/profile_kernel.py
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"),
)

BATCH = int(os.environ.get("PROFILE_BATCH", "4096"))
REPS = int(os.environ.get("PROFILE_REPS", "3"))


def timeit(name, fn, *args):
    fn_j = jax.jit(fn)
    r = fn_j(*args)
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    for _ in range(REPS):
        r = fn_j(*args)
    jax.block_until_ready(r)
    dt = (time.perf_counter() - t0) / REPS
    print(f"{name:40s} {dt*1e3:10.2f} ms")
    return dt


def main():
    from lodestar_tpu.ops import fp, fp2, fp12
    from lodestar_tpu.ops.pairing import (
        final_exponentiation,
        miller_loop_projective,
    )
    from lodestar_tpu.ops.points import g1, g2
    from lodestar_tpu.parallel.verifier import N_LIMBS
    from __graft_entry__ import _example_arrays

    print(f"batch={BATCH} reps={REPS} device={jax.devices()[0]}")
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.integers(0, 1 << 12, (BATCH, N_LIMBS), dtype=np.int32))
    b = jnp.asarray(rng.integers(0, 1 << 12, (BATCH, N_LIMBS), dtype=np.int32))
    a2 = jnp.stack([a, b], axis=-2)
    b2 = jnp.stack([b, a], axis=-2)

    def chain_mul(a, b):
        # 16 chained muls: amortizes dispatch, defeats CSE via data dep
        x = a
        for _ in range(16):
            x = fp.mul(x, b)
        return x

    dt = timeit("fp.mul x16 chained", chain_mul, a, b)
    print(f"  -> per fp.mul: {dt/16*1e3:.3f} ms")

    def chain_mul2(a, b):
        x = a
        for _ in range(16):
            x = fp2.mul(x, b)
        return x

    dt = timeit("fp2.mul x16 chained", chain_mul2, a2, b2)
    print(f"  -> per fp2.mul: {dt/16*1e3:.3f} ms")

    args = [jax.device_put(x) for x in _example_arrays(BATCH)]
    jax.block_until_ready(args)
    (pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, r_bits, valid) = args

    timeit("g1.scalar_mul_bits (64-bit)", lambda r, x, y: g1.scalar_mul_bits(r, (x, y)), r_bits, pk_x, pk_y)
    timeit("g2.scalar_mul_bits (64-bit)", lambda r, x, y: g2.scalar_mul_bits(r, (x, y)), r_bits, sig_x, sig_y)

    def ml(px, py, qx, qy):
        return miller_loop_projective((px, py, fp.one((BATCH,))), (qx, qy))

    dt_ml = timeit("miller_loop (batch lanes)", ml, pk_x, pk_y, msg_x, msg_y)

    f = ml(pk_x, pk_y, msg_x, msg_y)
    f = jax.block_until_ready(jax.jit(lambda x: x)(f))
    timeit("fp12.product_tree", fp12.product_tree, f)
    timeit("final_exponentiation (1 lane)", final_exponentiation, f[:1])

    def sq_chain(f):
        x = f
        for _ in range(4):
            x = fp12.square(x)
        return x

    dt = timeit("fp12.square x4 chained", sq_chain, f)
    print(f"  -> per fp12.square: {dt/4*1e3:.3f} ms")


if __name__ == "__main__":
    main()
