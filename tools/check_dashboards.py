"""Lint dashboards against the live metric registry + reference parity.

Every metric name referenced by a panel expression in `dashboards/*.json`
must exist in the default node registry (create_beacon_metrics +
ValidatorMonitor + GcMetrics) — a dashboard panel over a metric nothing
emits is the bug this repo shipped for five rounds (ISSUE 1). The reverse
direction — registry families no dashboard plots — is REPORTED but not a
failure: breadth families land before their dashboards do.

ISSUE 2 adds the PARITY check: the reference ships 16 Grafana
dashboards; `REQUIRED_DASHBOARDS` lists the 16 lodestar-tpu equivalents
and any file missing from the lint directory fails the run.

Exit code 0 = all 16 dashboards present and every panel name resolves;
1 otherwise. Run directly or via the tier-1 test
(tests/test_observability.py::test_check_dashboards_lint_passes).
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys

# PromQL functions/keywords that appear inside panel expressions
PROMQL_WORDS = {
    "rate", "irate", "sum", "avg", "min", "max", "count", "by", "on",
    "histogram_quantile", "increase", "delta", "label_replace", "time",
    "without", "group_left", "group_right", "clamp_max", "clamp_min",
}

# panels specific subsystem dashboards must plot (ISSUE 3: the round-6
# bisection-verdict and decompress-fallback families must be visible on
# the bls-verifier dashboard, not just registered) — {file: metric
# families at least one panel must reference}
REQUIRED_PANEL_METRICS = {
    "lodestar_tpu_bls_verifier.json": (
        "lodestar_bls_verifier_bisect_batches_total",
        "lodestar_bls_verifier_bisect_rounds_total",
        "lodestar_bls_verifier_bisect_probes_total",
        "lodestar_bls_verifier_decompress_fallback_total",
        # round-7 failure-policy families (ISSUE 4): the supervisor's
        # breaker/fallback/deadline state must be VISIBLE, not just
        # registered — a silent CPU-fallback node looks healthy on every
        # other panel
        "lodestar_bls_supervisor_breaker_state",
        "lodestar_bls_supervisor_fallbacks_total",
        "lodestar_bls_supervisor_deadline_exceeded_total",
        "lodestar_bls_supervisor_retries_total",
        "lodestar_bls_supervisor_both_tiers_failed_total",
        "lodestar_bls_verifier_waiter_timeouts_total",
        # round-7 mesh-serving families (tentpole): a node serving on a
        # shrunken mesh is healthy-but-slower — the eviction state must
        # be on the dashboard, not only in /debug/mesh
        "lodestar_bls_mesh_size",
        "lodestar_bls_mesh_evicted_devices",
        "lodestar_bls_mesh_evictions_total",
        "lodestar_bls_mesh_readmissions_total",
        "lodestar_bls_mesh_chip_dispatch_total",
        # lane-dispatcher families (ISSUE 15): flood load-shedding and
        # continuous-batching health — a node silently shedding
        # attestations (or worse, coalescing nothing) must be visible
        "lodestar_bls_lane_depth",
        "lodestar_bls_lane_shed_total",
        "lodestar_bls_lane_coalesced_sets",
        "lodestar_bls_lane_overlap_fraction",
        # compile-ledger families (ISSUE 11): every XLA compile is a
        # measured event — the compile tax that killed two driver rounds
        # must be on the dashboard, not only in /debug/compiles
        "lodestar_tpu_compile_events_total",
        "lodestar_tpu_compile_seconds_total",
        "lodestar_tpu_compile_cumulative_seconds",
        "lodestar_tpu_compile_cache_entries",
        "lodestar_tpu_compile_cache_pruned_bytes_total",
        # AOT executable store (ISSUE 19): a store silently degrading
        # every restart to JIT (corrupt artifacts, fingerprint drift
        # after an upgrade) must be a dashboard signal, not a log line
        "lodestar_tpu_aot_events_total",
        # epoch-resident crypto families (ISSUE 18): the device pubkey
        # table's hit rate / occupancy / rotation and the dispatcher's
        # H(msg) dedup — a table that silently stopped serving (0% hits
        # after an OOM downgrade or a wedged population thread) must be
        # visible, not only in /debug/epoch_table
        "lodestar_bls_epoch_table_hits_total",
        "lodestar_bls_epoch_table_misses_total",
        "lodestar_bls_epoch_table_occupancy",
        "lodestar_bls_epoch_table_evictions_total",
        "lodestar_bls_h2c_dedup_total",
    ),
    # cold-start / runtime-identity families (ISSUE 11): the
    # serving-ready SLO and build info belong on the fleet summary
    "lodestar_tpu_summary.json": (
        "lodestar_tpu_build_info",
        "lodestar_tpu_serving_ready_seconds",
        "lodestar_tpu_startup_phase_seconds",
        # SLO engine families (ISSUE 16): every lodestar_slo_* family
        # must be on the fleet summary — burn state nobody can see is
        # not an alerting layer
        "lodestar_slo_burning",
        "lodestar_slo_budget_remaining_fraction",
        "lodestar_slo_burn_rate",
        "lodestar_slo_evaluations_total",
        # device-time & memory ledger families (ISSUE 16): where
        # device-seconds and HBM bytes go, per lane x kernel x chip
        "lodestar_tpu_device_dispatch_seconds_total",
        "lodestar_tpu_device_overlap_seconds_total",
        "lodestar_tpu_device_idle_wall_seconds",
        "lodestar_tpu_device_memory_bytes",
        "lodestar_tpu_device_memory_watermark_bytes",
    ),
    # fleet-serving families (ISSUE 20): the two-level (ICI x DCN) mesh
    # is a cross-host concern, so its census belongs on the multinode
    # comparison view — a fleet silently serving on fewer hosts (or a
    # router rebalancing in a loop) must be visible per instance
    "lodestar_tpu_multinode.json": (
        "lodestar_bls_fleet_hosts",
        "lodestar_bls_fleet_evicted_hosts",
        "lodestar_bls_fleet_host_dispatch_total",
        "lodestar_bls_fleet_dcn_collective_seconds_total",
        "lodestar_bls_fleet_host_evictions_total",
        "lodestar_bls_fleet_rebalances_total",
        "lodestar_bls_fleet_subnets_moved_total",
    ),
}

SLO_RULES_FILE = "slo_rules.json"
SLO_RULES_MIN_OBJECTIVES = 6

# 16/16 parity with the reference dashboard set (ISSUE 2): one file per
# reference dashboard, mapped to this repo's subsystem names
REQUIRED_DASHBOARDS = (
    "lodestar_tpu_block_processor.json",
    "lodestar_tpu_bls_verifier.json",
    "lodestar_tpu_discv5.json",
    "lodestar_tpu_execution_engine.json",
    "lodestar_tpu_gossipsub.json",
    "lodestar_tpu_libp2p.json",
    "lodestar_tpu_multinode.json",
    "lodestar_tpu_network.json",
    "lodestar_tpu_rest_api.json",
    "lodestar_tpu_state_cache_regen.json",
    "lodestar_tpu_storage.json",
    "lodestar_tpu_summary.json",
    "lodestar_tpu_sync.json",
    "lodestar_tpu_validator_client.json",
    "lodestar_tpu_validator_monitor.json",
    "lodestar_tpu_vm_host.json",
)

REPO_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")


def _strip_label_syntax(expr: str) -> str:
    """Remove label selectors `{...}` and grouping label lists
    (`by (a, b)`, `without (...)`, `on (...)`, `group_left(...)`) so
    label names are not mistaken for metric families."""
    expr = re.sub(r"\{[^}]*\}", " ", expr)
    expr = re.sub(
        r"\b(by|without|on|ignoring|group_left|group_right)\s*\([^)]*\)",
        " ",
        expr,
    )
    return expr


def registry_names() -> set[str]:
    """Every series name the default full-node registry can expose."""
    sys.path.insert(0, REPO_ROOT)
    from lodestar_tpu.metrics.beacon import create_beacon_metrics
    from lodestar_tpu.metrics.gc_stats import GcMetrics
    from lodestar_tpu.metrics.validator_monitor import ValidatorMonitor

    m = create_beacon_metrics()
    ValidatorMonitor(m.registry)
    GcMetrics(m.registry)
    known: set[str] = set()
    families: set[str] = set()
    for metric in m.registry._metrics:
        families.add(metric.name)
        known.add(metric.name)
        if metric.kind == "histogram":
            known |= {metric.name + s for s in ("_bucket", "_sum", "_count")}
        elif metric.kind == "summary":
            known |= {metric.name + s for s in ("_sum", "_count")}
    return known, families


def dashboard_refs(dash_dir: str):
    """Yield (file, panel_title, metric_name) for every name-shaped token
    in every panel expression."""
    for path in sorted(glob.glob(os.path.join(dash_dir, "*.json"))):
        doc = json.load(open(path))
        for panel in doc.get("panels", []):
            for target in panel.get("targets", []):
                expr = _strip_label_syntax(target["expr"])
                for name in re.findall(r"[a-z][a-z0-9_]{3,}", expr):
                    if name in PROMQL_WORDS:
                        continue
                    yield os.path.basename(path), panel.get("title", "?"), name


def lint_slo_rules(dash_dir: str, families: set[str]) -> list[str]:
    """Lint `dashboards/slo_rules.json` (ISSUE 16): the file must parse,
    satisfy the engine's schema, commit at least SLO_RULES_MIN_OBJECTIVES
    objectives, and every objective's source metric must exist in the
    registry — a typo'd source silently never burns."""
    sys.path.insert(0, REPO_ROOT)
    from lodestar_tpu.observability.slo import validate_rules

    path = os.path.join(dash_dir, SLO_RULES_FILE)
    if not os.path.exists(path):
        return [f"{SLO_RULES_FILE} absent from {dash_dir} (the SLO engine "
                "has no committed objectives)"]
    try:
        doc = json.load(open(path))
    except (OSError, ValueError) as e:
        return [f"{SLO_RULES_FILE} unreadable: {e}"]
    try:
        validate_rules(doc)
    except ValueError as e:
        return [f"{SLO_RULES_FILE} schema: {e}"]
    problems = []
    objectives = doc["objectives"]
    if len(objectives) < SLO_RULES_MIN_OBJECTIVES:
        problems.append(
            f"{SLO_RULES_FILE} commits only {len(objectives)} objectives "
            f"(>= {SLO_RULES_MIN_OBJECTIVES} required)"
        )
    for obj in objectives:
        if obj["source"] not in families:
            problems.append(
                f"objective {obj['name']!r} reads source metric "
                f"{obj['source']!r} which no registry family declares"
            )
    return problems


def main(argv=None) -> int:
    dash_dir = os.path.join(REPO_ROOT, "dashboards")
    if argv and len(argv) > 1:
        dash_dir = argv[1]
    known, families = registry_names()

    absent = [
        name
        for name in REQUIRED_DASHBOARDS
        if not os.path.exists(os.path.join(dash_dir, name))
    ]
    for name in absent:
        print(f"ABSENT {name}  (reference parity requires 16 dashboards)")

    missing = []
    referenced_families: set[str] = set()
    per_file_refs: dict[str, set[str]] = {}
    for fname, title, name in dashboard_refs(dash_dir):
        if name in known:
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix) and name[: -len(suffix)] in families:
                    name = name[: -len(suffix)]
                    break
            referenced_families.add(name)
            per_file_refs.setdefault(fname, set()).add(name)
        else:
            missing.append((fname, title, name))

    for fname, title, name in missing:
        print(f"MISSING {name}  ({fname} :: {title})")

    unplotted_required = []
    for fname, metric_names in REQUIRED_PANEL_METRICS.items():
        refs = per_file_refs.get(fname, set())
        for name in metric_names:
            if name not in refs:
                unplotted_required.append((fname, name))
    for fname, name in unplotted_required:
        print(f"NO-PANEL {name}  (required on {fname})")
    slo_problems = lint_slo_rules(dash_dir, families)
    for problem in slo_problems:
        print(f"SLO-RULES {problem}")
    unexported = sorted(families - referenced_families)
    if unexported:
        print(
            f"note: {len(unexported)} registry families not plotted by any "
            "dashboard (informational):"
        )
        for name in unexported:
            print(f"  unplotted {name}")
    if missing or absent or unplotted_required or slo_problems:
        if missing:
            print(
                f"FAIL: {len(missing)} dashboard references missing from "
                "the registry"
            )
        if absent:
            print(
                f"FAIL: {len(absent)}/{len(REQUIRED_DASHBOARDS)} required "
                "dashboards absent"
            )
        if unplotted_required:
            print(
                f"FAIL: {len(unplotted_required)} required panel metric(s) "
                "not plotted by their dashboard"
            )
        if slo_problems:
            print(
                f"FAIL: {len(slo_problems)} SLO rules problem(s) in "
                f"{SLO_RULES_FILE}"
            )
        return 1
    print(
        f"OK: {len(REQUIRED_DASHBOARDS)}/16 dashboards present, every "
        f"dashboard metric resolves "
        f"({len(referenced_families)}/{len(families)} families plotted), "
        "slo_rules.json clean"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
