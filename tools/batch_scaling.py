"""Measure full-kernel throughput vs batch size on the real chip."""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"),
)


def main():
    from __graft_entry__ import _example_arrays
    from lodestar_tpu.parallel.verifier import batch_verify_kernel

    fn = jax.jit(batch_verify_kernel)
    for batch in (4096, 8192, 16384, 32768):
        args = [jax.device_put(a) for a in _example_arrays(batch, unique=32)]
        jax.block_until_ready(args)
        t0 = time.perf_counter()
        ok = bool(fn(*args))
        t_compile_and_run = time.perf_counter() - t0
        assert ok, f"batch {batch} failed verification"
        t0 = time.perf_counter()
        r = fn(*args)
        r.block_until_ready()
        dt = time.perf_counter() - t0
        print(
            f"batch={batch:6d}  first={t_compile_and_run:8.1f}s  "
            f"steady={dt:7.3f}s  {batch/dt:9.1f} sets/s",
            flush=True,
        )


if __name__ == "__main__":
    main()
