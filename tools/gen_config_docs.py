"""Generate docs/configuration.md from the typed env-var registry.

`lodestar_tpu/utils/env.py` is the single source of truth for every
``LODESTAR_TPU_*`` knob (name / type / default / one-line doc); this tool
renders it as a markdown table so operators never read source to learn a
knob exists. The table is DRIFT-CHECKED in tier-1
(tests/test_lint.py::test_config_docs_not_stale): adding or changing a
registry entry without regenerating fails the default suite.

    python tools/gen_config_docs.py            # rewrite docs/configuration.md
    python tools/gen_config_docs.py --check    # exit 1 if the doc is stale
"""

from __future__ import annotations

import argparse
import os
import sys

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)
DOC_PATH = os.path.join(REPO_ROOT, "docs", "configuration.md")

HEADER = """\
# Configuration

<!-- GENERATED FILE — do not edit by hand.
     Source: lodestar_tpu/utils/env.py (the typed env-var registry).
     Regenerate with: python tools/gen_config_docs.py
     Drift-checked in tier-1: tests/test_lint.py::test_config_docs_not_stale -->

Every environment knob the node, bench harness and tools read. All reads
go through `lodestar_tpu/utils/env.py` (enforced by the graftlint
`env-registry` rule — see docs/architecture.md, "Enforced invariants");
booleans treat `0 / off / false / no` and the empty string as false,
numeric knobs fall back to their default on unparseable values.
"""


def _fmt_default(var) -> str:
    if var.default is None:
        return "_(unset)_"
    if var.type == "bool":
        return "on" if var.default else "off"
    if isinstance(var.default, float) and var.default == int(var.default):
        return str(int(var.default))
    return f"`{var.default}`" if isinstance(var.default, str) else str(var.default)


def render() -> str:
    sys.path.insert(0, REPO_ROOT)
    from lodestar_tpu.utils.env import REGISTRY

    lines = [HEADER]
    lines.append("| Name | Type | Default | Description |")
    lines.append("| --- | --- | --- | --- |")
    for name in sorted(REGISTRY):
        var = REGISTRY[name]
        lines.append(
            f"| `{var.name}` | {var.type} | {_fmt_default(var)} | {var.doc} |"
        )
    lines.append("")
    lines.append(f"{len(REGISTRY)} variables registered.")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--check", action="store_true",
                    help="exit 1 if docs/configuration.md is stale instead "
                         "of rewriting it")
    ap.add_argument("--out", default=DOC_PATH)
    args = ap.parse_args(argv)

    content = render()
    if args.check:
        try:
            current = open(args.out).read()
        except OSError:
            current = ""
        if current != content:
            print(
                f"STALE: {args.out} does not match the env registry — "
                "regenerate with `python tools/gen_config_docs.py`"
            )
            return 1
        print(f"OK: {args.out} matches the env registry")
        return 0
    with open(args.out, "w") as f:
        f.write(content)
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
