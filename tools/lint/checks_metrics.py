"""metric-discipline: code and registry agree on metric families+labels.

Declarations are ``registry.counter/gauge/histogram/summary/gauge_func(
"lodestar_…", help, label_names)`` calls. The rule enforces, across the
whole linted tree (cross-file state, emitted in ``finalize``):

* a family declared twice with different label sets is a finding (the
  exporter would emit conflicting series);
* every *other* full-string ``lodestar_*`` literal in code (dashboards
  checks, alert text, tests of the export path) must resolve to a
  declared family — ``_bucket`` / ``_sum`` / ``_count`` suffixes resolve
  to their histogram/summary base;
* a call on a bound metric attribute (``m.batches.inc(…)``) must pass
  exactly the declared label names as keywords — a missing or extra
  label raises at runtime, on the error path where nobody is looking;
* a declared family whose bound attribute is never touched again and
  which no dashboard plots is dead weight: it exports a flat zero
  forever (``gauge_func`` is exempt — the callback IS the use).

Cross-checks only run when the linted paths contained declarations, so
path-scoped runs over a leaf directory don't misreport unknown families.
"""

from __future__ import annotations

import ast
import os
import re

from .core import Checker, Context

_DECL_KINDS = ("counter", "gauge", "histogram", "summary", "gauge_func")
_USE_METHODS = ("inc", "observe", "set", "time")
# methods whose name is too generic to infer "this receiver is a metric"
# unless label kwargs are present
_GENERIC_METHODS = ("set", "time")
_FAMILY_RE = re.compile(r"lodestar_[a-z][a-z0-9_]*")
_EXPORT_SUFFIXES = ("_bucket", "_sum", "_count")
_STAR = "**"


def _state(ctx: Context) -> dict:
    return ctx.state.setdefault(
        "metric-discipline",
        {"declared": {}, "usages": [], "attr_uses": [], "attr_mentions": {}},
    )


def _literal_labels(node: ast.AST | None):
    """Tuple of label names for a literal tuple/list of strings, () for
    None/missing, None when the expression isn't statically known."""
    if node is None:
        return ()
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    if isinstance(node, ast.Constant) and node.value in ((), None):
        return ()
    return None


class MetricDisciplineChecker(Checker):
    name = "metric-discipline"
    description = (
        "lodestar_* names in code must exist in the registry (and vice "
        "versa) with consistent label sets"
    )

    def visit_Call(self, node: ast.Call, ctx: Context) -> None:
        func = node.func
        if not isinstance(func, ast.Attribute):
            return
        state = _state(ctx)
        if func.attr in _DECL_KINDS:
            self._record_declaration(node, func, state, ctx)
        elif func.attr in _USE_METHODS and isinstance(func.value, ast.Attribute):
            attr = func.value.attr
            if any(kw.arg is None for kw in node.keywords):
                labels = _STAR  # **labels — not statically checkable
            else:
                labels = tuple(sorted(kw.arg for kw in node.keywords))
            state["attr_uses"].append(
                (attr, func.attr, labels, ctx.module, node.lineno,
                 node.col_offset)
            )

    def _record_declaration(self, node, func, state, ctx: Context) -> None:
        if not node.args:
            return
        arg0 = node.args[0]
        if not (isinstance(arg0, ast.Constant) and isinstance(arg0.value, str)):
            return
        family = arg0.value
        if not _FAMILY_RE.fullmatch(family):
            return
        kind = func.attr
        if kind == "gauge_func":
            labels = ()
        else:
            label_arg = None
            if len(node.args) > 2:
                label_arg = node.args[2]
            for kw in node.keywords:
                if kw.arg == "label_names":
                    label_arg = kw.value
            labels = _literal_labels(label_arg)
        bound_attr = None
        parent = ctx.parent()
        if isinstance(parent, ast.Assign) and parent.value is node:
            for target in parent.targets:
                if isinstance(target, ast.Attribute):
                    bound_attr = target.attr
        prior = state["declared"].get(family)
        if prior is not None:
            if (
                labels is not None
                and prior["labels"] is not None
                and tuple(sorted(labels)) != tuple(sorted(prior["labels"]))
            ):
                ctx.report(
                    self.name, node,
                    f"metric family {family!r} redeclared with labels "
                    f"{sorted(labels)} but first declared at "
                    f"{prior['where']} with {sorted(prior['labels'])}",
                )
            if bound_attr:
                prior["attrs"].add(bound_attr)
            return
        state["declared"][family] = {
            "labels": labels,
            "kind": kind,
            "attrs": {bound_attr} if bound_attr else set(),
            "where": f"{ctx.module.rel_path}:{node.lineno}"
            if ctx.module else "?",
            "module": ctx.module,
            "line": node.lineno,
        }

    def visit_Constant(self, node: ast.Constant, ctx: Context) -> None:
        if not isinstance(node.value, str):
            return
        if not _FAMILY_RE.fullmatch(node.value):
            return
        if node.value.startswith("lodestar_tpu"):
            return  # the package name, dashboards file names, etc.
        parent = ctx.parent()
        if isinstance(parent, ast.Expr):
            return  # docstring
        if (
            isinstance(parent, ast.Call)
            and isinstance(parent.func, ast.Attribute)
            and parent.func.attr in _DECL_KINDS
            and parent.args
            and parent.args[0] is node
        ):
            return  # the declaration itself
        _state(ctx)["usages"].append(
            (node.value, ctx.module, node.lineno, node.col_offset)
        )

    def visit_Attribute(self, node: ast.Attribute, ctx: Context) -> None:
        mentions = _state(ctx)["attr_mentions"]
        mentions[node.attr] = mentions.get(node.attr, 0) + 1

    # --- cross-file resolution -------------------------------------------

    def finalize(self, ctx: Context) -> None:
        state = _state(ctx)
        declared = state["declared"]
        if not declared:
            return  # path-scoped run without the registry modules

        for literal, module, line, col in state["usages"]:
            if literal in declared:
                continue
            base = None
            for suffix in _EXPORT_SUFFIXES:
                if literal.endswith(suffix):
                    base = literal[: -len(suffix)]
                    break
            if base is not None and base in declared:
                continue
            ctx.report(
                self.name, line,
                f"{literal!r} does not match any declared metric family "
                "(registry declarations are the source of truth; fix the "
                "name or declare the metric)",
                module=module, col=col,
            )

        # attr -> unique declared label set (skip ambiguous attr names)
        attr_labels: dict[str, tuple] = {}
        for family, info in declared.items():
            if info["labels"] is None:
                continue
            for attr in info["attrs"]:
                key = tuple(sorted(info["labels"]))
                if attr in attr_labels and attr_labels[attr] != key:
                    attr_labels[attr] = None  # ambiguous across families
                else:
                    attr_labels.setdefault(attr, key)
        for attr, method, labels, module, line, col in state["attr_uses"]:
            expected = attr_labels.get(attr)
            if expected is None or labels == _STAR:
                continue
            if method in _GENERIC_METHODS and not labels:
                # bare .set(v)/.time(): receiver names are too generic to
                # be sure this is a metric, so only keyword mismatches
                # (clear evidence of intent) are findings
                continue
            if labels != expected:
                ctx.report(
                    self.name, line,
                    f".{method}() on metric attribute `{attr}` passes "
                    f"labels {list(labels)} but the declaration expects "
                    f"{list(expected)}",
                    module=module, col=col,
                )

        dashboards_text = self._dashboards_text()
        for family, info in declared.items():
            if info["kind"] == "gauge_func":
                continue
            literal_used = any(
                u[0] == family
                or any(u[0] == family + s for s in _EXPORT_SUFFIXES)
                for u in state["usages"]
            )
            attr_used = any(
                state["attr_mentions"].get(a, 0) > 1 for a in info["attrs"]
            )
            if literal_used or attr_used:
                continue
            if family in dashboards_text:
                continue
            ctx.report(
                self.name, info["line"],
                f"metric family {family!r} is declared but its handle is "
                "never used and no dashboard plots it — it will export a "
                "flat zero forever; wire it up or remove it",
                module=info["module"],
            )

    @staticmethod
    def _dashboards_text() -> str:
        from .core import REPO_ROOT

        chunks = []
        dash_dir = os.path.join(REPO_ROOT, "dashboards")
        try:
            names = sorted(os.listdir(dash_dir))
        except OSError:
            return ""
        for name in names:
            if name.endswith(".json"):
                try:
                    with open(os.path.join(dash_dir, name),
                              encoding="utf-8") as f:
                        chunks.append(f.read())
                except OSError:
                    continue
        return "\n".join(chunks)
