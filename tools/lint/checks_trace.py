"""trace-safety: no host round-trips or Python branching inside kernels.

A "kernel" is any function that runs under a JAX trace: decorated with
``jax.jit`` (directly or via ``functools.partial(jax.jit, …)``), passed
to ``jit`` / ``shard_map`` / ``_shard_map`` / ``vmap`` / ``pmap`` /
``lax.scan``-family wrappers, or (transitively) any same-module function
called from a kernel body. Inside a kernel:

* ``.item()`` / ``.tolist()`` / ``.block_until_ready()`` /
  ``jax.device_get`` force a device sync — under ``jit`` they fail at
  trace time or silently break; under interpret-mode they "work" and
  then explode on the TPU path (the exact class of bug the dryrun
  multichip check exists to catch early).
* ``np.asarray`` / ``np.array`` / ``np.frombuffer`` on a traced value
  pulls it to host — constants must use ``jnp.asarray`` (legal: it
  stages a device constant).
* ``float(x)`` / ``bool(x)`` on a traced value raise
  ``ConcretizationTypeError`` at trace time (shape/ndim/dtype/len
  arguments are static and exempt).
* ``if``/``while`` whose test calls ``jnp.*`` / ``lax.*`` branches on a
  traced value — use ``jnp.where`` / ``lax.cond``.

Separately, call sites of functions jitted with ``static_argnums`` /
``static_argnames`` must pass hashable values in static positions —
a list/set/dict/ndarray there recompiles every call or raises.
"""

from __future__ import annotations

import ast

from .core import Checker, Context, dotted_name

_JIT_LEAVES = ("jit", "pjit")
_WRAPPER_LEAVES = (
    "jit", "pjit", "shard_map", "_shard_map", "vmap", "pmap",
    "scan", "fori_loop", "while_loop", "cond", "switch", "checkpoint",
    "remat", "custom_jvp", "custom_vjp", "grad", "value_and_grad",
    # Pallas kernel bodies (pl.pallas_call(kernel, …)) run under a trace
    # too — and worse, host syncs "work" in interpret mode and only
    # explode when Mosaic lowers them, so they must be caught statically.
    "pallas_call",
)
_NP_ROOTS = ("np", "numpy", "onp")
_HOST_PULL_ATTRS = ("item", "tolist", "block_until_ready")
_STATIC_SHAPE_HINTS = ("shape", "ndim", "dtype", "size", "len")


def _leaf(name: str | None) -> str:
    return (name or "").rsplit(".", 1)[-1]


def _parse_static_kwargs(keywords) -> tuple[set[int], set[str]]:
    nums: set[int] = set()
    names: set[str] = set()
    for kw in keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                nums.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                nums |= {
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, int)
                }
        elif kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                names.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                names |= {
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                }
    return nums, names


def _jit_decoration(dec: ast.AST):
    """(is_jit, static_argnums, static_argnames) for a decorator node."""
    if _leaf(dotted_name(dec)) in _JIT_LEAVES:
        return True, set(), set()
    if isinstance(dec, ast.Call):
        leaf = _leaf(dotted_name(dec.func))
        if leaf in _JIT_LEAVES:
            return (True, *_parse_static_kwargs(dec.keywords))
        if leaf == "partial" and dec.args:
            if _leaf(dotted_name(dec.args[0])) in _JIT_LEAVES:
                return (True, *_parse_static_kwargs(dec.keywords))
    return False, set(), set()


class TraceSafetyChecker(Checker):
    name = "trace-safety"
    description = (
        "no host syncs (.item/np.asarray/device_get/float()) or Python "
        "branching on traced values inside jitted/shard_map'd kernels; "
        "static_argnums call sites must pass hashable values"
    )

    def end_module(self, module, ctx: Context) -> None:
        tree = module.tree
        defs: dict[str, ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[node.name] = node

        kernels: set[str] = set()
        # static-call contracts: callable name -> (argnum set, argname set)
        static_sigs: dict[str, tuple[set[int], set[str]]] = {}

        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    is_jit, nums, names = _jit_decoration(dec)
                    if is_jit:
                        kernels.add(node.name)
                        if nums or names:
                            static_sigs[node.name] = (nums, names)
            elif isinstance(node, ast.Call):
                leaf = _leaf(dotted_name(node.func))
                if leaf in _WRAPPER_LEAVES:
                    for arg in node.args:
                        if isinstance(arg, ast.Name) and arg.id in defs:
                            kernels.add(arg.id)

        # `g = jax.jit(f, static_argnums=…)` binds the contract to `g`
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                call = node.value
                if _leaf(dotted_name(call.func)) in _JIT_LEAVES:
                    nums, names = _parse_static_kwargs(call.keywords)
                    if nums or names:
                        for target in node.targets:
                            if isinstance(target, ast.Name):
                                static_sigs[target.id] = (nums, names)

        # transitive closure: same-module functions called from kernels
        # run under the same trace
        changed = True
        while changed:
            changed = False
            for kname in list(kernels):
                fn = defs.get(kname)
                if fn is None:
                    continue
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call):
                        callee = node.func
                        if (
                            isinstance(callee, ast.Name)
                            and callee.id in defs
                            and callee.id not in kernels
                        ):
                            kernels.add(callee.id)
                            changed = True

        for kname in kernels:
            fn = defs.get(kname)
            if fn is not None:
                self._check_kernel_body(fn, module, ctx)

        if static_sigs:
            self._check_static_call_sites(tree, static_sigs, module, ctx)

    # --- host-sync and branching checks inside a kernel body ------------

    def _check_kernel_body(self, fn, module, ctx: Context) -> None:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                self._check_call(node, fn, module, ctx)
            elif isinstance(node, (ast.If, ast.While)):
                self._check_branch(node, fn, module, ctx)

    def _check_call(self, node: ast.Call, fn, module, ctx: Context) -> None:
        name = dotted_name(node.func) or ""
        leaf = _leaf(name)
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _HOST_PULL_ATTRS
        ):
            ctx.report(
                self.name, node,
                f"`.{node.func.attr}()` inside kernel `{fn.name}` forces a host "
                "sync on a traced value; compute on-device "
                "(jnp.where/lax ops) and sync outside the kernel",
                module=module,
            )
            return
        root = name.split(".", 1)[0]
        if root in _NP_ROOTS and leaf in ("asarray", "array", "frombuffer"):
            ctx.report(
                self.name, node,
                f"`{name}` inside kernel `{fn.name}` pulls the operand to "
                "host; use `jnp.asarray` for constants, jnp ops for "
                "traced values",
                module=module,
            )
            return
        if leaf == "device_get" and root in ("jax", "device_get"):
            ctx.report(
                self.name, node,
                f"`jax.device_get` inside kernel `{fn.name}` forces a "
                "device->host transfer under trace",
                module=module,
            )
            return
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in ("float", "bool")
            and len(node.args) == 1
            and not node.keywords
        ):
            arg = node.args[0]
            if isinstance(arg, ast.Constant):
                return
            if self._is_static_shape_expr(arg):
                return
            ctx.report(
                self.name, node,
                f"`{node.func.id}(…)` on a traced value inside kernel "
                f"`{fn.name}` raises ConcretizationTypeError at trace "
                "time; use jnp casts (`.astype`) or keep the value traced",
                module=module,
            )

    @staticmethod
    def _is_static_shape_expr(expr: ast.AST) -> bool:
        """shape/ndim/dtype/len() expressions are static under trace."""
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute) and node.attr in _STATIC_SHAPE_HINTS:
                return True
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "len"
            ):
                return True
        return False

    def _check_branch(self, node, fn, module, ctx: Context) -> None:
        for sub in ast.walk(node.test):
            if isinstance(sub, ast.Call):
                root = (dotted_name(sub.func) or "").split(".", 1)[0]
                if root in ("jnp", "lax"):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    ctx.report(
                        self.name, node,
                        f"Python `{kind}` on a `{root}.*` value inside "
                        f"kernel `{fn.name}` branches on a traced value; "
                        "use jnp.where or lax.cond",
                        module=module,
                    )
                    return

    # --- static_argnums call-site hashability ----------------------------

    @staticmethod
    def _unhashable(arg: ast.AST) -> str | None:
        if isinstance(arg, ast.List):
            return "list"
        if isinstance(arg, ast.Set):
            return "set"
        if isinstance(arg, ast.Dict):
            return "dict"
        if isinstance(arg, ast.Call):
            name = dotted_name(arg.func) or ""
            if _leaf(name) in ("array", "asarray", "zeros", "ones") and \
                    name.split(".", 1)[0] in _NP_ROOTS + ("jnp",):
                return "ndarray"
        return None

    def _check_static_call_sites(self, tree, static_sigs, module,
                                 ctx: Context) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fname = None
            if isinstance(node.func, ast.Name):
                fname = node.func.id
            elif isinstance(node.func, ast.Attribute):
                fname = node.func.attr
            if fname not in static_sigs:
                continue
            nums, names = static_sigs[fname]
            for i, arg in enumerate(node.args):
                if i in nums:
                    kind = self._unhashable(arg)
                    if kind:
                        ctx.report(
                            self.name, arg,
                            f"unhashable {kind} passed in static position "
                            f"{i} of jitted `{fname}` — static args are "
                            "cache keys; pass a tuple/scalar",
                        )
            for kw in node.keywords:
                if kw.arg in names:
                    kind = self._unhashable(kw.value)
                    if kind:
                        ctx.report(
                            self.name, kw.value,
                            f"unhashable {kind} passed for static arg "
                            f"`{kw.arg}` of jitted `{fname}` — static "
                            "args are cache keys; pass a tuple/scalar",
                        )
