"""graftlint core: one AST walk per file, pluggable checkers, suppressions.

The framework parses each file once, walks the tree once (maintaining the
ancestor stack), and fans every node out to the checkers that registered
a handler for its type (``visit_Call``, ``visit_If``, …). Checkers that
need whole-module structure (class layouts, jit closures) get
``begin_module`` / ``end_module`` with the parsed tree; checkers that
need cross-file state (the metric registry lives in one module, the
increments in many) accumulate into ``ctx.state`` and emit from
``finalize``.

Suppressions: a ``# graftlint: disable=<rule>[,<rule>…]`` comment on the
line a finding anchors to silences it (``disable=all`` silences every
rule on that line); ``# graftlint: disable-file=<rule>`` anywhere in the
file silences the rule file-wide. Suppressions are parsed from real
comment tokens, not substring matches, so string literals cannot
accidentally disable a rule.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass

_SUPPRESS_RE = re.compile(
    r"graftlint:\s*(disable|disable-file)\s*=\s*([a-z0-9_,\s-]+)"
)


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str

    def human(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def as_dict(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "rule": self.rule,
            "message": self.message,
        }


class Module:
    """One parsed source file plus its suppression tables."""

    def __init__(self, path: str, rel_path: str, source: str):
        self.path = path
        self.rel_path = rel_path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # {lineno: set of rule names (or "all")} and file-wide rule names
        self.line_suppressions: dict[int, set[str]] = {}
        self.file_suppressions: set[str] = set()
        self._parse_suppressions()

    def _parse_suppressions(self) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
                if m.group(1) == "disable-file":
                    self.file_suppressions |= rules
                else:
                    self.line_suppressions.setdefault(
                        tok.start[0], set()
                    ).update(rules)
        except tokenize.TokenError:
            pass  # graftlint: disable=exception-hygiene — unparseable tail; the AST parse above already vouched for the file

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_suppressions or "all" in self.file_suppressions:
            return True
        rules = self.line_suppressions.get(line)
        return bool(rules) and (rule in rules or "all" in rules)

    def line_comment(self, line: int) -> str:
        """The text of `line` (1-based), '' when out of range — checkers
        use this for structured annotations like `# guarded-by: _lock`."""
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""


class Context:
    """Shared walk state: the current module, the ancestor stack, the
    findings sink, and a cross-file scratch dict keyed by checker."""

    def __init__(self):
        self.module: Module | None = None
        self.stack: list[ast.AST] = []
        self.findings: list[Finding] = []
        self.state: dict[str, object] = {}

    def parent(self, up: int = 1) -> ast.AST | None:
        return self.stack[-up] if len(self.stack) >= up else None

    def report(self, rule: str, node: ast.AST | int, message: str,
               module: Module | None = None, col: int | None = None) -> None:
        mod = module or self.module
        if isinstance(node, int):
            line, column = node, col or 0
        else:
            line = getattr(node, "lineno", 0)
            column = getattr(node, "col_offset", 0) if col is None else col
        if mod is not None and mod.suppressed(rule, line):
            return
        self.findings.append(
            Finding(mod.rel_path if mod else "?", line, column, rule, message)
        )


class Checker:
    """Base class. Subclasses set `name`/`description`, implement any of
    `visit_<NodeType>`, `begin_module`, `end_module`, `finalize`."""

    name = "abstract"
    description = ""

    def begin_module(self, module: Module, ctx: Context) -> None:
        pass

    def end_module(self, module: Module, ctx: Context) -> None:
        pass

    def finalize(self, ctx: Context) -> None:
        pass

    def handlers(self) -> dict[type, callable]:
        table: dict[type, callable] = {}
        for attr in dir(self):
            if attr.startswith("visit_"):
                node_type = getattr(ast, attr[len("visit_"):], None)
                if node_type is not None:
                    table[node_type] = getattr(self, attr)
        return table


def dotted_name(node: ast.AST) -> str | None:
    """`a.b.c` for Name/Attribute chains, None for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> str | None:
    return dotted_name(node.func)


class _Walker:
    """Single-pass dispatcher: every node visited exactly once, handlers
    looked up by concrete node type."""

    def __init__(self, checkers: list[Checker], ctx: Context):
        self.ctx = ctx
        self.dispatch: dict[type, list[callable]] = {}
        for checker in checkers:
            for node_type, handler in checker.handlers().items():
                self.dispatch.setdefault(node_type, []).append(handler)

    def walk(self, tree: ast.AST) -> None:
        self._visit(tree)

    def _visit(self, node: ast.AST) -> None:
        for handler in self.dispatch.get(type(node), ()):
            handler(node, self.ctx)
        self.ctx.stack.append(node)
        for child in ast.iter_child_nodes(node):
            self._visit(child)
        self.ctx.stack.pop()


DEFAULT_PATHS = ("lodestar_tpu", "tools", "bench.py", "__graft_entry__.py")

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)


def iter_py_files(paths) -> list[str]:
    out: list[str] = []
    for path in paths:
        if os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = sorted(
                    d for d in dirnames if d not in ("__pycache__", ".git")
                )
                out.extend(
                    os.path.join(dirpath, f)
                    for f in sorted(filenames)
                    if f.endswith(".py")
                )
        elif path.endswith(".py") and os.path.exists(path):
            out.append(path)
    return out


def run(paths=None, checkers=None, root: str | None = None) -> list[Finding]:
    """Lint `paths` (files or directories) with `checkers` (default: all
    registered rules); returns findings sorted by location."""
    from . import all_checkers

    root = root or os.getcwd()
    if paths is None:
        paths = [p for p in DEFAULT_PATHS if os.path.exists(os.path.join(root, p))]
    active = checkers if checkers is not None else all_checkers()
    ctx = Context()
    modules: list[Module] = []
    for file_path in iter_py_files(
        [p if os.path.isabs(p) else os.path.join(root, p) for p in paths]
    ):
        rel = os.path.relpath(file_path, root).replace(os.sep, "/")
        try:
            with open(file_path, encoding="utf-8") as f:
                source = f.read()
            module = Module(file_path, rel, source)
        except (OSError, SyntaxError, ValueError) as e:
            ctx.findings.append(
                Finding(rel, getattr(e, "lineno", 0) or 0, 0, "parse-error",
                        f"could not parse: {e}")
            )
            continue
        modules.append(module)
        ctx.module = module
        walker = _Walker(active, ctx)
        for checker in active:
            checker.begin_module(module, ctx)
        walker.walk(module.tree)
        for checker in active:
            checker.end_module(module, ctx)
    ctx.module = None
    for checker in active:
        checker.finalize(ctx)
    ctx.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return ctx.findings


def render(findings: list[Finding], as_json: bool = False) -> str:
    if as_json:
        return json.dumps(
            {"findings": [f.as_dict() for f in findings],
             "count": len(findings)},
            indent=2,
        )
    if not findings:
        return "graftlint: no findings"
    lines = [f.human() for f in findings]
    lines.append(f"graftlint: {len(findings)} finding(s)")
    return "\n".join(lines)
