"""lock-discipline: guarded attributes stay under their lock; no
blocking calls while a lock is held.

Attributes annotated ``# guarded-by: _lock`` on their initialising
assignment (``self._entries = {} # guarded-by: _lock``) may only be
written inside ``with self._lock:`` in every other method — the
annotation turns the class's implicit locking convention into a checked
contract. ``__init__`` is exempt (no concurrent access before the
constructor returns).

Independently, a ``with <lock>:`` block must not park the thread:
``time.sleep``, zero-argument ``.join()``, and ``.wait()`` with no (or
``None``) timeout are findings — a blocked lock-holder stalls every
other thread at the acquire site (exactly the pipeline-wide stall the
dispatch-deadline work in chain/supervisor.py exists to prevent).
Condition-variable receivers (``cond`` / ``cv`` / ``condition``) are
exempt from the ``.wait()`` rule: Condition.wait releases the lock.
"""

from __future__ import annotations

import ast
import re

from .core import Checker, Context, dotted_name

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
_COND_HINTS = ("cond", "cv", "condition")


def _self_attr(node: ast.AST) -> str | None:
    """'x' for `self.x`, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _lock_names_in_with(node: ast.With) -> list[str]:
    """Receiver names of with-items that look like plain locks
    (`self._lock`, `lock`, `self._pk_lock`, …) — not condition vars."""
    names = []
    for item in node.items:
        expr = item.context_expr
        # unwrap `with self._lock:` vs `with self._lock.acquire_timeout(..)`
        name = dotted_name(expr) or (
            dotted_name(expr.func) if isinstance(expr, ast.Call) else None
        )
        if not name:
            continue
        leaf = name.rsplit(".", 1)[-1].lower()
        if "lock" in leaf and not any(h in leaf for h in _COND_HINTS):
            names.append(name.rsplit(".", 1)[-1])
    return names


class LockDisciplineChecker(Checker):
    name = "lock-discipline"
    description = (
        "`# guarded-by: <lock>` attributes only written under that lock; "
        "no time.sleep / untimed .wait() / .join() while a lock is held"
    )

    # --- guarded-by contract (whole-class analysis in end_module) -------

    def end_module(self, module, ctx: Context) -> None:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                self._check_class(node, module, ctx)

    def _check_class(self, cls: ast.ClassDef, module, ctx: Context) -> None:
        guarded: dict[str, str] = {}  # attr -> lock attr name
        for node in ast.walk(cls):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                m = _GUARDED_RE.search(module.line_comment(node.lineno))
                if not m:
                    continue
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for target in targets:
                    attr = _self_attr(target)
                    if attr:
                        guarded[attr] = m.group(1)
        if not guarded:
            return
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if item.name == "__init__":
                continue  # no concurrent access before the ctor returns
            if item.name.endswith("_locked"):
                continue  # repo convention: the caller holds the lock
            self._check_method(item, guarded, module, ctx)

    def _check_method(self, func, guarded: dict[str, str], module,
                      ctx: Context) -> None:
        self._walk_writes(func.body, guarded, held=set(), module=module,
                          ctx=ctx)

    def _walk_writes(self, stmts, guarded, held: set[str], module,
                     ctx: Context) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.With):
                inner = held | {
                    n for n in _lock_names_in_with(stmt) if n in guarded.values()
                }
                self._walk_writes(stmt.body, guarded, inner, module, ctx)
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested def runs later, not under the current lock;
                # treat its body as lock-free
                self._walk_writes(stmt.body, guarded, set(), module, ctx)
                continue
            if isinstance(stmt, (ast.If,)):
                self._walk_writes(stmt.body, guarded, held, module, ctx)
                self._walk_writes(stmt.orelse, guarded, held, module, ctx)
                continue
            if isinstance(stmt, (ast.For, ast.While)):
                self._walk_writes(stmt.body, guarded, held, module, ctx)
                self._walk_writes(stmt.orelse, guarded, held, module, ctx)
                continue
            if isinstance(stmt, ast.Try):
                self._walk_writes(stmt.body, guarded, held, module, ctx)
                for handler in stmt.handlers:
                    self._walk_writes(handler.body, guarded, held, module, ctx)
                self._walk_writes(stmt.orelse, guarded, held, module, ctx)
                self._walk_writes(stmt.finalbody, guarded, held, module, ctx)
                continue
            for node in ast.walk(stmt):
                if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    targets = (
                        node.targets if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    flat = []
                    for target in targets:
                        if isinstance(target, (ast.Tuple, ast.List)):
                            flat.extend(target.elts)
                        else:
                            flat.append(target)
                    for target in flat:
                        attr = _self_attr(target)
                        if attr in guarded and guarded[attr] not in held:
                            ctx.report(
                                self.name, node,
                                f"`self.{attr}` is annotated `# guarded-by: "
                                f"{guarded[attr]}` but is written without "
                                f"holding `self.{guarded[attr]}`",
                                module=module,
                            )

    # --- blocking-while-locked (shared single walk) ---------------------

    def visit_With(self, node: ast.With, ctx: Context) -> None:
        if not _lock_names_in_with(node):
            return
        self._scan_blocking(node.body, ctx)

    def _scan_blocking(self, stmts, ctx: Context) -> None:
        for stmt in stmts:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(node, ast.With) and _lock_names_in_with(node):
                    continue  # nested with reported by its own visit
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func) or ""
                leaf = name.rsplit(".", 1)[-1]
                if name in ("time.sleep", "sleep") and node.args:
                    ctx.report(
                        self.name, node,
                        "time.sleep while holding a lock stalls every "
                        "thread blocked on the acquire",
                    )
                elif leaf == "join" and not node.args and not node.keywords:
                    receiver = (
                        dotted_name(node.func.value) or ""
                        if isinstance(node.func, ast.Attribute) else ""
                    )
                    # str.join takes an iterable arg; a 0-arg join is a
                    # thread/process join — unbounded while locked
                    ctx.report(
                        self.name, node,
                        f"unbounded {receiver or 'thread'}.join() while "
                        "holding a lock; join outside the lock or use a "
                        "timeout",
                    )
                elif leaf == "wait":
                    receiver = (
                        (dotted_name(node.func.value) or "").lower()
                        if isinstance(node.func, ast.Attribute) else ""
                    )
                    if any(h in receiver for h in _COND_HINTS):
                        continue  # Condition.wait releases the lock
                    timeout = None
                    if node.args:
                        timeout = node.args[0]
                    for kw in node.keywords:
                        if kw.arg in ("timeout", "timeout_s"):
                            timeout = kw.value
                    unbounded = timeout is None or (
                        isinstance(timeout, ast.Constant)
                        and timeout.value is None
                    )
                    if unbounded:
                        ctx.report(
                            self.name, node,
                            "untimed .wait() while holding a lock can "
                            "block forever; pass a timeout or wait "
                            "outside the lock",
                        )
