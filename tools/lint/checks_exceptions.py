"""exception-hygiene: no bare `except:`, no silently-swallowed Exception.

A bare ``except:`` catches SystemExit/KeyboardInterrupt and turns Ctrl-C
into a hang; ``except Exception: pass`` hides real faults (the
fault-injection harness exists precisely because swallowed device errors
looked like liveness bugs). Handlers that *do something* — log, count a
metric, return a fallback, re-raise — are fine; only handlers whose body
is pure no-op (``pass`` / ``...`` / ``continue`` / ``break`` / a bare
constant) are findings.
"""

from __future__ import annotations

import ast

from .core import Checker, Context

_BROAD = ("Exception", "BaseException")


def _is_broad(type_node: ast.AST) -> bool:
    if isinstance(type_node, ast.Name):
        return type_node.id in _BROAD
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(e) for e in type_node.elts)
    return False


def _is_silent(body: list[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / ellipsis
        return False
    return True


class ExceptionHygieneChecker(Checker):
    name = "exception-hygiene"
    description = (
        "no bare `except:`; broad `except Exception` handlers must act "
        "(log, count, return, re-raise) rather than silently pass"
    )

    def visit_ExceptHandler(self, node: ast.ExceptHandler, ctx: Context) -> None:
        if node.type is None:
            ctx.report(
                self.name, node,
                "bare `except:` also catches SystemExit/KeyboardInterrupt; "
                "catch Exception (or something narrower) instead",
            )
            return
        if _is_broad(node.type) and _is_silent(node.body):
            ctx.report(
                self.name, node,
                "broad exception handler silently swallows the error; log "
                "it, count it, or narrow the exception type "
                "(`# graftlint: disable=exception-hygiene` with a reason "
                "if intentional)",
            )
