"""CLI: ``python -m tools.lint [paths…] [--json] [--rules r1,r2]``.

Exits 1 when there are findings (tier-1 wires this through
tests/test_lint.py), 2 on usage errors.
"""

from __future__ import annotations

import argparse
import sys

from . import ALL_CHECKER_CLASSES, render, rule_names, run


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="graftlint: AST-based invariant checker "
                    "(trace-safety, lock-discipline, env-registry, "
                    "exception-hygiene, metric-discipline)",
    )
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: "
                         "lodestar_tpu tools bench.py __graft_entry__.py)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of rules to run")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the available rules and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for cls in ALL_CHECKER_CLASSES:
            print(f"{cls.name}: {cls.description}")
        return 0

    checkers = None
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = wanted - set(rule_names())
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))} "
                  f"(available: {', '.join(rule_names())})", file=sys.stderr)
            return 2
        checkers = [cls() for cls in ALL_CHECKER_CLASSES if cls.name in wanted]

    findings = run(paths=args.paths or None, checkers=checkers)
    print(render(findings, as_json=args.json))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
