"""env-registry: every LODESTAR_TPU_* read goes through utils/env.py.

The typed registry (lodestar_tpu/utils/env.py) is the single source of
truth for knob names, types, defaults and docs — docs/configuration.md
is generated from it. A raw ``os.getenv("LODESTAR_TPU_…")`` bypasses the
type contract and the generated docs, so it is a finding anywhere except
inside the registry module itself. Environment *writes* stay legal (the
probes and test harnesses set knobs for child processes).

The rule also checks the other direction: a literal name passed to the
typed accessors (``env_str`` / ``env_int`` / ``env_float`` / ``env_bool``
/ ``raw`` / ``is_set``) must exist in the registry — a typo'd knob name
otherwise silently reads the default forever.
"""

from __future__ import annotations

import ast

from .core import Checker, Context, call_name, dotted_name

_PREFIX = "LODESTAR_TPU_"
_ACCESSORS = ("env_str", "env_int", "env_float", "env_bool", "raw", "is_set")
# the registry module itself (and its tests) may touch os.environ
_EXEMPT_SUFFIXES = ("utils/env.py",)


def _registry_names() -> set[str] | None:
    """The registered knob names, or None when the package can't be
    imported from here (path-scoped run outside the repo)."""
    try:
        from lodestar_tpu.utils.env import REGISTRY

        return set(REGISTRY)
    except Exception:  # graftlint: disable=exception-hygiene — degrade to prefix-only checking rather than crash the linter
        return None


class EnvRegistryChecker(Checker):
    name = "env-registry"
    description = (
        "LODESTAR_TPU_* reads must go through lodestar_tpu/utils/env.py; "
        "names passed to the typed accessors must be registered"
    )

    def __init__(self):
        self._registry = _registry_names()

    def _exempt(self, ctx: Context) -> bool:
        mod = ctx.module
        return mod is not None and mod.rel_path.endswith(_EXEMPT_SUFFIXES)

    @staticmethod
    def _lodestar_literal(node: ast.AST) -> str | None:
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and node.value.startswith(_PREFIX)
        ):
            return node.value
        return None

    def visit_Call(self, node: ast.Call, ctx: Context) -> None:
        if self._exempt(ctx):
            return
        name = call_name(node) or ""
        short = name.rsplit(".", 1)[-1]
        arg0 = self._lodestar_literal(node.args[0]) if node.args else None

        # raw reads: os.getenv(...) / os.environ.get(...) / getenv(...)
        # (environ writes — assignment, pop, setdefault — stay legal: the
        # probes and harnesses configure knobs for child processes)
        is_raw_read = short == "getenv" or (
            short == "get" and "environ" in name
        )
        if is_raw_read and arg0 is not None:
            ctx.report(
                self.name, node,
                f"raw environment read of {arg0!r}; use the typed accessor "
                "from lodestar_tpu/utils/env.py so the knob stays in the "
                "registry and docs/configuration.md",
            )
            return

        # typed-accessor reads: the literal must be a registered knob
        if short in _ACCESSORS and arg0 is not None and self._registry is not None:
            if arg0 not in self._registry:
                ctx.report(
                    self.name, node,
                    f"{arg0!r} is not registered in lodestar_tpu/utils/"
                    "env.py — register it (with type, default and doc) "
                    "and regenerate docs/configuration.md",
                )

    def visit_Subscript(self, node: ast.Subscript, ctx: Context) -> None:
        if self._exempt(ctx):
            return
        # os.environ["LODESTAR_TPU_X"] in Load/Del context (writes allowed)
        if isinstance(node.ctx, ast.Store):
            return
        base = dotted_name(node.value) or ""
        if "environ" not in base:
            return
        lit = self._lodestar_literal(node.slice)
        if lit is not None:
            ctx.report(
                self.name, node,
                f"raw os.environ[{lit!r}] read; use the typed accessor "
                "from lodestar_tpu/utils/env.py",
            )
