"""graftlint — AST-based invariant checker for this repo.

Five rules, one AST walk per file (see core.py for the framework and the
suppression syntax):

* ``trace-safety``      — no host syncs / Python branching in kernels
* ``lock-discipline``   — guarded-by contracts, no blocking under locks
* ``env-registry``      — LODESTAR_TPU_* reads go through utils/env.py
* ``exception-hygiene`` — no bare/silent broad exception handlers
* ``metric-discipline`` — code and metric registry agree on families

Run it: ``python -m tools.lint [paths…] [--json] [--rules r1,r2]``.
Enforced in tier-1 by tests/test_lint.py (zero findings over
lodestar_tpu/, tools/, bench.py, __graft_entry__.py — and every rule
must fire on its planted-violation fixture).
"""

from __future__ import annotations

from .checks_env import EnvRegistryChecker
from .checks_exceptions import ExceptionHygieneChecker
from .checks_locks import LockDisciplineChecker
from .checks_metrics import MetricDisciplineChecker
from .checks_trace import TraceSafetyChecker
from .core import DEFAULT_PATHS, Checker, Context, Finding, Module, render, run

ALL_CHECKER_CLASSES = (
    TraceSafetyChecker,
    LockDisciplineChecker,
    EnvRegistryChecker,
    ExceptionHygieneChecker,
    MetricDisciplineChecker,
)


def all_checkers() -> list[Checker]:
    """Fresh checker instances (checkers hold per-run state)."""
    return [cls() for cls in ALL_CHECKER_CLASSES]


def rule_names() -> list[str]:
    return [cls.name for cls in ALL_CHECKER_CLASSES]


__all__ = [
    "ALL_CHECKER_CLASSES",
    "Checker",
    "Context",
    "DEFAULT_PATHS",
    "Finding",
    "Module",
    "all_checkers",
    "render",
    "rule_names",
    "run",
]
