"""Time batch_verify_kernel compile + steady state for one batch size.
Usage: python tools/kernel_probe.py {default|scan|mxu} BATCH [REPS]"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

mode = sys.argv[1]
batch = int(sys.argv[2])
reps = int(sys.argv[3]) if len(sys.argv) > 3 else 3
if mode == "scan":
    os.environ["LODESTAR_TPU_LEGACY_FP"] = "1"
elif mode == "mxu":
    os.environ["LODESTAR_TPU_MXU_MUL"] = "1"
elif mode == "mxu2":
    os.environ["LODESTAR_TPU_PALLAS_MXU"] = "1"

import jax

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"),
)

from __graft_entry__ import _example_arrays  # noqa: E402
from lodestar_tpu.parallel.verifier import batch_verify_kernel  # noqa: E402

args = [jax.device_put(a) for a in _example_arrays(batch)]
jax.block_until_ready(args)
fn = jax.jit(batch_verify_kernel)

t0 = time.perf_counter()
ok = bool(fn(*args))
print(
    f"{mode} b={batch}: compile+first = {time.perf_counter()-t0:.1f}s ok={ok}",
    flush=True,
)
assert ok
t0 = time.perf_counter()
for _ in range(reps):
    r = fn(*args)
r.block_until_ready()
dt = (time.perf_counter() - t0) / reps
print(f"{mode} b={batch}: steady = {dt:.3f}s  {batch/dt:.1f} sets/s", flush=True)
