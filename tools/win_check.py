"""Cross-check windowed vs bits ladders ON DEVICE (and time honestly)."""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"),
)
import jax.numpy as jnp
import numpy as np

from lodestar_tpu.bls import curve as oc
from lodestar_tpu.ops.io_host import g2_affine_to_limbs
from lodestar_tpu.ops.points import g2
from lodestar_tpu.ops import fp

B = 512
rng = np.random.default_rng(0)
bits_np = rng.integers(0, 2, (B, 64), dtype=np.int32)
bits = jnp.asarray(bits_np)
g2x, g2y, _ = g2_affine_to_limbs(oc.PointG2.generator())
q = (jnp.broadcast_to(g2x, (B, 2, 32)), jnp.broadcast_to(g2y, (B, 2, 32)))

f_bits = jax.jit(g2.scalar_mul_bits)
f_win = jax.jit(g2.scalar_mul_windowed)
r1 = f_bits(bits, q)
r2 = f_win(bits, q)
jax.block_until_ready((r1, r2))

# compare affine forms (projective reps differ)
a1 = g2.to_affine(r1)
a2 = g2.to_affine(r2)
eq = jnp.all(fp.eq(a1[0], a2[0]) & fp.eq(a1[1], a2[1]))
print("windowed == bits on device:", bool(jnp.all(eq)))

for name, f in (("bits", f_bits), ("windowed", f_win)):
    # fresh input each rep to defeat any caching
    t0 = time.perf_counter()
    outs = []
    for i in range(3):
        b = jnp.asarray(np.roll(bits_np, i, axis=0))
        outs.append(f(b, q))
    jax.block_until_ready(outs)
    print(f"g2 {name} B={B}: {(time.perf_counter()-t0)/3*1000:.1f} ms/rep", flush=True)
