"""Zero-backlog proof: BASELINE config #2 on the device verifier.

Synthetic gossip load — 256 attestations/slot across 64 committees (each
committee shares one signing root: the real gossip shape), delivered in
three bursts per 12 s slot like live attestation traffic (slot start,
slot/3 attestation deadline, slot*2/3 aggregates) — driven through the
production `BufferedVerifier` → `DeviceBlsVerifier` path for >= 10 slots
on the real chip.

Records per-slot buffer depth samples (lodestar_bls_verifier_buffer_sigs),
buffer-wait / sets-per-job histograms, and verdicts, and writes
backlog_run.json next to bench_details.json (VERDICT r2 next-step #5;
reference: lodestar_bls_thread_pool dashboard + gossip queue budget
"keep job wait < 3 s", network/gossip/handlers/index.ts).
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"),
)

SLOTS = 10
SLOT_SEC = float(os.environ.get("BACKLOG_SLOT_SEC", "12"))
ATT_PER_SLOT = 256
COMMITTEES = 64


def build_slot_sets(slot: int, sks, pks):
    """256 attestation signature sets over 64 shared committee roots."""
    from lodestar_tpu.bls import api as bls

    sets = []
    sigs = {}
    for i in range(ATT_PER_SLOT):
        committee = i % COMMITTEES
        k = i % len(sks)
        root = bytes([slot % 256, committee]) + b"\x5a" * 30
        sig = sigs.get((k, root))
        if sig is None:
            sig = sigs[(k, root)] = sks[k].sign(root).to_bytes()
        sets.append(
            bls.SignatureSet(pubkey=pks[k], message=root, signature=sig)
        )
    return sets


async def run() -> dict:
    from lodestar_tpu.bls import api as bls
    from lodestar_tpu.chain.bls_verifier import BufferedVerifier, DeviceBlsVerifier
    from lodestar_tpu.metrics.beacon import create_beacon_metrics

    n_keys = 64
    sks = [bls.interop_secret_key(i) for i in range(n_keys)]
    pks = [sk.to_public_key() for sk in sks]

    prom = create_beacon_metrics()
    # one flat bucket + one grouped config: every merged batch pads to 128
    # lanes, so warm-up needs exactly two tunnel compiles (the tunnel has
    # been flaky under long compile bursts today)
    device = DeviceBlsVerifier(buckets=(128,), grouped_configs=((64, 64),))
    verifier = BufferedVerifier(device, prom=prom)

    # warm every bucket the merged batches can land in, outside the timed
    # window (a cold first dispatch would otherwise look like minutes of
    # backlog — compiles are one-time and cached)
    warm = build_slot_sets(255, sks, pks)
    t0 = time.monotonic()
    ok = verifier.verifier.verify_signature_sets(warm[:128])
    assert ok, "warm-up grouped-128 failed"
    print(f"warm grouped-128: {time.monotonic() - t0:.1f}s", flush=True)
    # the 128-set warm above routes GROUPED (64 shared roots); also warm
    # the FLAT 128 bucket with an all-unique batch
    from lodestar_tpu.bls import api as _bls

    uniq = []
    for i in range(128):
        root = bytes([i, 0xEE]) + b"\x11" * 30
        sk = sks[i % len(sks)]
        uniq.append(
            _bls.SignatureSet(
                pubkey=pks[i % len(pks)], message=root,
                signature=sk.sign(root).to_bytes(),
            )
        )
    t0 = time.monotonic()
    ok = verifier.verifier.verify_signature_sets(uniq)
    assert ok, "warm-up flat-128 failed"
    print(f"warm flat-128: {time.monotonic() - t0:.1f}s", flush=True)

    depth_samples: list[int] = []
    slot_rows = []
    all_ok = True

    async def sample_depth(stop):
        while not stop.is_set():
            buffered = sum(len(s) for s, _, _ in verifier._buffer)
            depth_samples.append(buffered)
            await asyncio.sleep(0.05)

    t_run0 = time.monotonic()
    stop = asyncio.Event()
    sampler = asyncio.create_task(sample_depth(stop))
    for slot in range(SLOTS):
        slot_t0 = time.monotonic()
        sets = build_slot_sets(slot, sks, pks)
        verdicts = []
        # three bursts per slot: singles at t0, the attestation-deadline
        # wave at slot/3, aggregates at 2/3 (handlers verify PER OBJECT —
        # one set each, batchable — exactly the gossip validation shape)
        bursts = [
            sets[: ATT_PER_SLOT // 2],
            sets[ATT_PER_SLOT // 2 : 3 * ATT_PER_SLOT // 4],
            sets[3 * ATT_PER_SLOT // 4 :],
        ]
        for b_i, burst in enumerate(bursts):
            target = slot_t0 + b_i * SLOT_SEC / 3
            delay = target - time.monotonic()
            if delay > 0:
                await asyncio.sleep(delay)
            tasks = [
                asyncio.create_task(verifier.verify([s], batchable=True))
                for s in burst
            ]
            verdicts.extend(await asyncio.gather(*tasks))
        all_ok = all_ok and all(verdicts)
        spent = time.monotonic() - slot_t0
        if spent < SLOT_SEC:
            await asyncio.sleep(SLOT_SEC - spent)
        window = depth_samples[-int(SLOT_SEC / 0.05) :]
        window_sorted = sorted(window)
        slot_rows.append(
            {
                "slot": slot,
                "verified": len(verdicts),
                "all_valid": all(verdicts),
                "depth_p50": window_sorted[len(window_sorted) // 2],
                "depth_p95": window_sorted[int(len(window_sorted) * 0.95)],
                "depth_max": max(window),
            }
        )
        print(f"slot {slot}: {slot_rows[-1]}", flush=True)
    stop.set()
    await sampler

    ds = sorted(depth_samples)
    return {
        "config": "BASELINE #2: 256 attestations/slot x 64 committees",
        "slots": SLOTS,
        "slot_seconds": SLOT_SEC,
        "sets_verified": verifier.metrics["sigs_verified"],
        "device_dispatches": verifier.metrics["batches"],
        "batch_fallbacks": verifier.metrics["batch_fallbacks"],
        "all_verdicts_valid": all_ok,
        "buffer_depth_p50": ds[len(ds) // 2],
        "buffer_depth_p95": ds[int(len(ds) * 0.95)],
        "buffer_depth_max": ds[-1],
        "wall_seconds": round(time.monotonic() - t_run0, 1),
        "per_slot": slot_rows,
    }


def main():
    out = asyncio.run(run())
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "backlog_run.json"
    )
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps({k: v for k, v in out.items() if k != "per_slot"}))


if __name__ == "__main__":
    main()
